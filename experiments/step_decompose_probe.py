"""Decompose the production wavefront STEP at north-star scale (round-4
VERDICT item 1 / item 8 groundwork): time every piece of one anti-diagonal
step — query build, anchor packing, the packed scan kernel (and the round-4
fusion candidates), champion select, fp32 re-score, coherence block,
scatter — each as a loop-carried on-chip fori_loop, so the sum can be
compared against the real per-step cost and against the HBM roofline.

The shipping kernel case is `packed2k_best` (the round-4 K-wide form);
the superseded round-3 candidates it was measured against are recorded in
the in-file history note (their builds no longer exist in production).

    python experiments/step_decompose_probe.py [--size 1024] [--iters 600]
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

import jax
import jax.numpy as jnp

from examples.make_assets import make_structured
from image_analogies_tpu.backends.base import LevelJob
from image_analogies_tpu.backends.tpu import (
    TpuMatcher,
    _batched_coherence,
    make_anchor_fn,
)
from image_analogies_tpu.tune import resolve as tune
from image_analogies_tpu.config import AnalogyParams
from image_analogies_tpu.ops import color
from image_analogies_tpu.ops.features import spec_for_level
from image_analogies_tpu.ops.pallas_match import (
    bf16_split3,
    packed2k_best,
)

_F32 = jnp.float32


def bench_loop(body, carry_init, args_tuple, iters, reps=3):
    """Time `body` inside one on-device fori_loop (one dispatch per rep —
    the PJRT tunnel costs ~100 ms per dispatch, so per-call costs must be
    amortized over >= ~100 in-loop iterations).  `body(i, carry, *args)`
    returns the new carry; arrays ride as jit ARGUMENTS (closure constants
    blow the remote-compile payload limit)."""

    def run(carry0, *arrs):
        def f(i, c):
            return body(i, c, *arrs)

        return jax.lax.fori_loop(0, iters, f, carry0)

    jrun = jax.jit(run)
    jax.block_until_ready(jrun(carry_init, *args_tuple))  # compile
    ts = []
    for _ in range(reps):
        t0 = time.perf_counter()
        jax.block_until_ready(jrun(carry_init, *args_tuple))
        ts.append(time.perf_counter() - t0)
    return min(ts) / iters


def main() -> int:
    pa = argparse.ArgumentParser()
    pa.add_argument("--size", type=int, default=1024)
    pa.add_argument("--iters", type=int, default=600)
    pa.add_argument("--cases", default="all")
    args = pa.parse_args()

    print(f"# backend={jax.default_backend()} "
          f"dev={jax.devices()[0].device_kind}", file=sys.stderr)

    a, ap, b = make_structured(args.size)
    params = AnalogyParams(levels=1, backend="tpu", strategy="wavefront",
                           match_mode="exact_hi2_2p")
    spec = spec_for_level(params, 0, 1, 1)
    a_src, a_filt, b_src = (color.luminance(a), color.luminance(ap),
                            color.luminance(b))
    a_src, a_filt = color.remap_pair(a_src, a_filt, b_src)
    job = LevelJob(level=0, spec=spec,
                   kappa_mult=params.kappa_factor(0) ** 2,
                   a_src=a_src, a_filt=a_filt, b_src=b_src)
    db = TpuMatcher(params).build_features(job)
    km = jnp.float32(job.kappa_mult)

    hb, wb = db.hb, db.wb
    ha, wa = db.ha, db.wa
    na = ha * wa
    nb = hb * wb
    nf = int(db.off.shape[0])
    c = spec.fine_size // 2 + 1
    m = min(hb, (wb + c - 1) // c)  # plateau diagonal width
    m = (m + 7) // 8 * 8
    f = int(db.static_q.shape[1])
    npad, kp = db.db_pad.shape
    tile = tune.scan_tile(npad, kp)
    ntiles = npad // tile
    live = int(db.live_idx.shape[0])

    rng = np.random.default_rng(0)
    # a mid-scan state snapshot: random but realistic shapes/values
    pix = jnp.asarray(
        np.sort(rng.choice(nb, size=m, replace=False)).astype(np.int32))
    bp0 = jnp.asarray(rng.random(nb, dtype=np.float32))
    s0 = jnp.asarray(rng.integers(0, na, nb).astype(np.int32))
    q0 = jnp.asarray(rng.random((m, f), dtype=np.float32) * 0.3)
    p0 = jnp.asarray(rng.integers(0, na, m).astype(np.int32))
    tv0 = jnp.asarray(rng.random((m, ntiles), dtype=np.float32))
    ti0 = jnp.asarray(
        rng.integers(0, npad, (m, ntiles)).astype(np.int32))

    off_i = db.off[:, 0][None, :]
    off_j = db.off[:, 1][None, :]

    dep = lambda x: (x.reshape(-1)[0].astype(_F32) * 1e-30)

    nc = (nf - 1) // 2  # the causal prefix production gathers (round 4)

    def qbuild(i, carry, static_q, bp, sqrtw):
        """The PRODUCTION query build (round-4 form): causal-prefix
        window gather + static_q gather + splice."""
        q, acc = carry
        pixc = pix + (acc % 2)  # loop-carried dependency
        qi = pixc // wb
        qj = pixc - qi * wb
        wi = qi[:, None] + off_i[:, :nc]
        wj = qj[:, None] + off_j[:, :nc]
        idx = (jnp.clip(wi, 0, hb - 1) * wb + jnp.clip(wj, 0, wb - 1))
        written = (idx < pixc[:, None]).astype(_F32)
        dyn = bp[idx] * written * sqrtw[None, :nc]
        dyn_full = jnp.zeros((m, nf), _F32).at[:, :nc].set(dyn)
        queries = jax.lax.dynamic_update_slice(
            static_q[pixc], dyn_full, (0, db.fine_start))
        return queries, acc + queries.reshape(-1)[0].astype(jnp.int32) % 1

    def pack(i, carry, feat_mean, live_idx):
        q, acc = carry
        qc = q - feat_mean[None, :f]
        g1, g2, _ = bf16_split3(qc[:, live_idx])
        q1 = g1.astype(jnp.bfloat16)
        q2 = g2.astype(jnp.bfloat16)
        out = q1[0, 0].astype(_F32) + q2[0, 0].astype(_F32)
        return q.at[0, 0].add(out * 1e-30), acc

    def mk_kernel_case(fn):
        def body(i, carry, w1, w2, dbnh, feat_mean, live_idx):
            q, acc = carry
            qc = q - feat_mean[None, :f]
            g1, g2, _ = bf16_split3(qc[:, live_idx])
            out = fn(g1.astype(jnp.bfloat16), g2.astype(jnp.bfloat16),
                     w1, w2, dbnh)
            return q.at[0, 0].add(dep(out)), acc
        return body

    def champ_select(i, carry, tv, ti):
        q, acc = carry
        vals = tv + q[0, 0] * 1e-30
        k = jnp.argmax(vals, axis=1)
        p = jnp.minimum(
            jnp.take_along_axis(ti, k[:, None], axis=1)[:, 0], na - 1)
        return q.at[0, 0].add(dep(p)), acc

    def rescore(i, carry, dbf):
        q, acc = carry
        p = (p0 + acc) % na
        d = jnp.sum((dbf[p] - q) ** 2, axis=1)
        return q.at[0, 0].add(dep(d)), acc

    def coherence(i, carry, dbf, s):
        """The PRODUCTION coherence block (round-4 form): causal-prefix
        candidates, live/dead-split scoring when the build carries it."""
        q, acc = carry
        pixc = pix
        qi = pixc // wb
        qj = pixc - qi * wb
        wi = qi[:, None] + off_i[:, :nc]
        wj = qj[:, None] + off_j[:, :nc]
        inb = (wi >= 0) & (wi < hb) & (wj >= 0) & (wj < wb)
        idx = (jnp.clip(wi, 0, hb - 1) * wb + jnp.clip(wj, 0, wb - 1))
        qq = q + acc.astype(_F32) * 1e-30
        q_live = (qq[:, db.live_idx]
                  if db.db_live is not None and db.live_idx is not None
                  else None)
        p_coh, d_coh, has = _batched_coherence(
            db, s, qq, idx, inb, nc, lambda i_: dbf[i_], q_live=q_live)
        return q.at[0, 0].add(dep(d_coh)), acc

    def scatter(i, carry, afilt):
        bp, acc = carry
        p = (p0 + acc) % na
        bp = bp.at[pix].set(afilt[p], mode="drop")
        return bp, acc + 1

    def anchor_full(i, carry, *arrs):
        q, acc = carry
        p, d = anchor_fn(q + acc.astype(_F32) * 0.0)
        return q.at[0, 0].add(dep(d)), acc

    def noop(i, carry):
        """Pure loop baseline: the ~100 ms tunnel dispatch divided by
        `iters` shows up as a per-step floor in EVERY case — subtract
        this case's number from the others."""
        q, acc = carry
        return q.at[0, 0].add(q[0, 1] * 1e-30), acc

    anchor_fn = make_anchor_fn(db)

    # round 4: the exact_hi2_2p build already folds norms into W1's lanes
    # (backends/tpu._packed_weight_arrays), so db_pad IS w1n.  The
    # two-stream subtract-based cases reuse the same array for timing
    # (identical shapes/op counts; their scores are not validated here).
    w1n = db.db_pad  # the 2p build IS the K-wide norm-laned array

    cases = {
        "qbuild": (qbuild, (q0, jnp.int32(0)),
                   (db.static_q, bp0, db.fine_sqrtw)),
        "pack": (pack, (q0, jnp.int32(0)), (db.feat_mean, db.live_idx)),
        # NOTE (round-4 history): the two-array kernel variants
        # (packed2/packed2_best/packed1w*/packed2wn) were measured here
        # against the round-3 build before the K-wide layout shipped —
        # shipping scan 1429 us/step, champion-in-kernel 1242, 1-stream
        # 1141-1176 (REJECTED on parity), all noop-subtracted at plateau
        # M=344/Na=1M.  db_pad is now the K-wide array, so those cases
        # are no longer constructible from a production build.
        # the SHIPPING round-4 exact_hi2_2p kernel: K-wide single array,
        # champion in kernel, norms in W lanes, one MXU dot per tile
        "packed2k_best": (mk_kernel_case(
            lambda q1, q2, w1, w2, dn: packed2k_best(
                q1, q2, w1, tile_n=4096)[0]),
            (q0, jnp.int32(0)),
            (w1n, db.db_pad2, db.dbnh_pad, db.feat_mean, db.live_idx)),
        "noop": (lambda i, c: noop(i, c), (q0, jnp.int32(0)), ()),
        "champ_select": (champ_select, (q0, jnp.int32(0)), (tv0, ti0)),
        "rescore": (rescore, (q0, jnp.int32(0)), (db.db,)),
        "coherence": (coherence, (q0, jnp.int32(0)), (db.db, s0)),
        "scatter": (scatter, (bp0, jnp.int32(0)), (db.a_filt_flat,)),
        "anchor_full": (anchor_full, (q0, jnp.int32(0)), ()),
    }
    rec = {"size": args.size, "m": m, "na": na, "npad": npad, "kp": kp,
           "tile": tile, "ntiles": ntiles, "live": live,
           "iters": args.iters}
    # rooflines (v5e-class numbers: ~820 GB/s HBM, ~394 TF/s bf16)
    bytes_2stream = 2 * npad * kp * 2
    rec["scan_bytes_2stream_mb"] = round(bytes_2stream / 1e6, 1)
    rec["roofline_2stream_us"] = round(bytes_2stream / 820e9 * 1e6, 1)
    rec["roofline_1stream_us"] = round(bytes_2stream / 2 / 820e9 * 1e6, 1)

    names = (list(cases) if args.cases == "all" else args.cases.split(","))
    for name in names:
        body, carry, arrs = cases[name]
        # ONE iters value for every case: the ~100 ms tunnel dispatch
        # appears as dispatch/iters in each number, so equal iters makes
        # the `noop` baseline directly subtractable
        iters = args.iters
        for attempt in range(3):  # the remote-compile service drops pipes
            try:
                us = bench_loop(body, carry, arrs, iters) * 1e6
                break
            except Exception as e:  # noqa: BLE001
                print(f"# {name}: retry {attempt + 1} ({type(e).__name__})",
                      file=sys.stderr, flush=True)
                time.sleep(5.0)
        else:
            continue
        rec[name + "_us"] = round(us, 1)
        print(f"# {name}: {us:.1f} us/step", file=sys.stderr, flush=True)
    print(json.dumps(rec))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
