"""Timing probe on the REAL chip: warm wall-clock per strategy/size.

    python experiments/tpu_time.py --size 256 --levels 3 --strategies batched,wavefront
"""

from __future__ import annotations

import argparse
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from experiments.parity_probe import make_structured
from image_analogies_tpu.config import AnalogyParams
from image_analogies_tpu.models.analogy import create_image_analogy


def main() -> int:
    ap_ = argparse.ArgumentParser()
    ap_.add_argument("--size", type=int, default=256)
    ap_.add_argument("--levels", type=int, default=3)
    ap_.add_argument("--kappa", type=float, default=5.0)
    ap_.add_argument("--strategies", default="batched,wavefront")
    args = ap_.parse_args()

    a, ap, b = make_structured(args.size)
    for strat in args.strategies.split(","):
        p = AnalogyParams(levels=args.levels, kappa=args.kappa,
                          backend="tpu", strategy=strat)
        t0 = time.perf_counter()
        create_image_analogy(a, ap, b, p)
        cold = time.perf_counter() - t0
        t0 = time.perf_counter()
        res = create_image_analogy(a, ap, b, p)
        warm = time.perf_counter() - t0
        lvl = " ".join(f"{s['ms']:.0f}ms" for s in res.stats)
        print(f"{strat:>10} size={args.size} cold={cold:.1f}s warm={warm:.2f}s"
              f"  levels: {lvl}", flush=True)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
