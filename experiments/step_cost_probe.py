"""Decompose the wavefront step's kernel cost on-chip (round-3 perf work).

Times the argmin kernel variants in isolation at north-star scale
(M=344 queries x Na=1M rows x F=128) with a loop-carried data dependency
(so XLA can't CSE the repeats), plus a tiny-DB variant to expose the
per-call fixed cost.  Answers: is the kernel MXU-bound (HIGHEST's 3 passes
dominate -> precision schemes pay) or VPU/overhead-bound (the (M, tile_n)
score reductions dominate -> cut reduction work, not MXU passes)?

    python experiments/step_cost_probe.py
"""

from __future__ import annotations

import functools
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

import jax
import jax.numpy as jnp

from image_analogies_tpu.ops.pallas_match import (
    pallas_argmin2_l2_prepadded,
    pallas_argmin_l2_prepadded,
)

HI = jax.lax.Precision.HIGHEST
DEF = jax.lax.Precision.DEFAULT


def bench(fn, reps=3):
    fn()  # compile
    ts = []
    for _ in range(reps):
        t0 = time.perf_counter()
        jax.block_until_ready(fn())
        ts.append(time.perf_counter() - t0)
    return min(ts)


def main() -> int:
    m, f = 344, 128
    rng = np.random.default_rng(0)
    q0 = jnp.asarray(rng.standard_normal((m, f)).astype(np.float32) * 0.05)

    import argparse

    pa = argparse.ArgumentParser()
    pa.add_argument("--cases", default="top1_f32_HIGHEST,top1_f32_DEFAULT,"
                    "top2_bf16,top2_f32_HIGHEST")
    pa.add_argument("--sizes", default="1048576")
    pa.add_argument("--iters", type=int, default=30)
    args = pa.parse_args()

    for n, iters in ((int(s), args.iters) for s in args.sizes.split(",")):
        db32 = jnp.asarray(
            rng.standard_normal((n, f)).astype(np.float32) * 0.05)
        dbn = jnp.full((1, n), jnp.inf, jnp.float32).at[0, :].set(
            jnp.sum(db32 * db32, axis=1))
        db16 = db32.astype(jnp.bfloat16)

        def loop(body, iters=iters):
            def f(i, carry):
                q, acc = carry
                out = body(q)
                # data dependency: nudge one query element by ~0 so the next
                # iteration depends on this one's output
                q = q.at[0, 0].add(out[0].astype(jnp.float32) * 1e-30)
                return q, acc + out[0]

            return jax.jit(lambda: jax.lax.fori_loop(
                0, iters, f, (q0, jnp.int32(0)))[1])

        cases = {
            "top1_f32_HIGHEST": lambda q: pallas_argmin_l2_prepadded(
                q, db32, dbn, tile_n=8192, precision=HI)[0],
            "top1_f32_DEFAULT": lambda q: pallas_argmin_l2_prepadded(
                q, db32, dbn, tile_n=8192, precision=DEF)[0],
            "top2_bf16": lambda q: pallas_argmin2_l2_prepadded(
                q.astype(jnp.bfloat16), db16, dbn, tile_n=8192)[0],
            "top2_bf16_qsplit": lambda q: pallas_argmin2_l2_prepadded(
                q, db16, dbn, tile_n=8192, q_split=True)[0],
            "top2_f32_HIGHEST": lambda q: pallas_argmin2_l2_prepadded(
                q, db32, dbn, tile_n=8192, precision=HI)[0],
        }
        rec = {"n_rows": n, "iters": iters}
        # roofline reference points first (so partial runs still inform)
        mxu_us = 2 * m * f * n / 394e12 * 1e6  # one bf16 pass
        hbm_us = n * f * 4 / 820e9 * 1e6  # fp32 stream at ~820 GB/s
        rec["roofline_1pass_mxu_us"] = round(mxu_us, 1)
        rec["roofline_f32_hbm_us"] = round(hbm_us, 1)
        for name in args.cases.split(","):
            per_call_us = bench(loop(cases[name])) / iters * 1e6
            rec[name + "_us"] = round(per_call_us, 1)
            print(f"# {name}: {per_call_us:.1f} us/call", file=sys.stderr,
                  flush=True)
        print(json.dumps(rec))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
