"""Decompose the wavefront step's kernel cost on-chip (round-3 perf work).

Times the argmin kernel variants in isolation at north-star scale
(M=344 queries x Na=1M rows x F=128) with a loop-carried data dependency
(so XLA can't CSE the repeats), plus a tiny-DB variant to expose the
per-call fixed cost.  Answers: is the kernel MXU-bound (HIGHEST's 3 passes
dominate -> precision schemes pay) or VPU/overhead-bound (the (M, tile_n)
score reductions dominate -> cut reduction work, not MXU passes)?

    python experiments/step_cost_probe.py
"""

from __future__ import annotations

import functools
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

import jax
import jax.numpy as jnp

from image_analogies_tpu.ops.pallas_match import (
    bf16_split3,
    pallas_argmin2_l2_prepadded,
    pallas_argmin_l2_prepadded,
    pallas_packed_champions,
    pallas_pertile_champions,
)


def _packed3(q, db16, dn, tile):
    """Shape-faithful exact_hi2 scan: 3-way split queries, db16 stands in
    for both packed weight arrays."""
    import jax.numpy as jnp

    g1, g2, gr = bf16_split3(q)
    qa = jnp.concatenate([g1.astype(jnp.bfloat16),
                          g2.astype(jnp.bfloat16)], axis=0)
    qc = gr.astype(jnp.bfloat16)
    return pallas_packed_champions(qa, qc, db16, db16, dn, tile_n=tile,
                                   fold_a=True)[1][0]

HI = jax.lax.Precision.HIGHEST
DEF = jax.lax.Precision.DEFAULT


def bench(fn, reps=3):
    fn()  # compile
    ts = []
    for _ in range(reps):
        t0 = time.perf_counter()
        jax.block_until_ready(fn())
        ts.append(time.perf_counter() - t0)
    return min(ts)


def main() -> int:
    m, f = 344, 128
    rng = np.random.default_rng(0)
    q0 = jnp.asarray(rng.standard_normal((m, f)).astype(np.float32) * 0.05)

    import argparse

    pa = argparse.ArgumentParser()
    pa.add_argument("--cases", default="top1_f32_HIGHEST,top1_f32_DEFAULT,"
                    "top2_bf16,top2_f32_HIGHEST")
    pa.add_argument("--sizes", default="1048576")
    pa.add_argument("--iters", type=int, default=30)
    args = pa.parse_args()

    for n, iters in ((int(s), args.iters) for s in args.sizes.split(",")):
        db32 = jnp.asarray(
            rng.standard_normal((n, f)).astype(np.float32) * 0.05)
        dbn = jnp.full((1, n), jnp.inf, jnp.float32).at[0, :].set(
            jnp.sum(db32 * db32, axis=1))
        db16 = db32.astype(jnp.bfloat16)

        def loop(name, iters=iters):
            body = cases[name]
            # the DB arrays must be jit ARGUMENTS, not closure constants:
            # constants are embedded in the compile payload, and a 512 MB
            # DB blows the axon remote-compile request limit (HTTP 413)
            def f(i, carry):
                q, acc, db, dbnorm = carry
                out = body(q, db, dbnorm)
                # data dependency: nudge one query element by ~0 so the next
                # iteration depends on this one's output
                q = q.at[0, 0].add(out[0].astype(jnp.float32) * 1e-30)
                return q, acc + out[0], db, dbnorm

            db = db16 if ("bf16" in name or "packed3" in name) else db32
            run = jax.jit(lambda d, dn: jax.lax.fori_loop(
                0, iters, f, (q0, jnp.int32(0), d, dn))[1])
            return lambda: run(db, dbn)

        cases = {
            "top1_f32_HIGHEST": lambda q, db, dn: pallas_argmin_l2_prepadded(
                q, db, dn, tile_n=8192, precision=HI)[0],
            "top1_f32_DEFAULT": lambda q, db, dn: pallas_argmin_l2_prepadded(
                q, db, dn, tile_n=8192, precision=DEF)[0],
            "top2_bf16": lambda q, db, dn: pallas_argmin2_l2_prepadded(
                q.astype(jnp.bfloat16), db, dn, tile_n=8192)[0],
            "top2_bf16_qsplit": lambda q, db, dn: pallas_argmin2_l2_prepadded(
                q, db, dn, tile_n=8192, q_split=True)[0],
            "top2_f32_HIGHEST": lambda q, db, dn: pallas_argmin2_l2_prepadded(
                q, db, dn, tile_n=8192, precision=HI)[0],
            # per-tile champion kernel (dn passed = HALF norms here; the
            # probe times, it does not validate values)
            "pertile_hi": lambda q, db, dn: pallas_pertile_champions(
                q, db, dn, tile_n=4096, precision=HI)[1][0],
            "pertile_bf16": lambda q, db, dn: pallas_pertile_champions(
                q.astype(jnp.bfloat16), db, dn, tile_n=4096)[1][0],
            "pertile_bf16_qsplit": lambda q, db, dn:
                pallas_pertile_champions(q, db, dn, tile_n=4096,
                                         q_split=True)[1][0],
            # 3-pass packed exact scan (exact_hi2); db/dn shapes reused as
            # stand-ins for W1/W2 — the probe times, it does not validate
            "packed3_t2048": lambda q, db, dn: _packed3(q, db, dn, 2048),
            "packed3_t4096": lambda q, db, dn: _packed3(q, db, dn, 4096),
            "packed3_t8192": lambda q, db, dn: _packed3(q, db, dn, 8192),
        }
        rec = {"n_rows": n, "iters": iters}
        # roofline reference points first (so partial runs still inform)
        mxu_us = 2 * m * f * n / 394e12 * 1e6  # one bf16 pass
        hbm_us = n * f * 4 / 820e9 * 1e6  # fp32 stream at ~820 GB/s
        rec["roofline_1pass_mxu_us"] = round(mxu_us, 1)
        rec["roofline_f32_hbm_us"] = round(hbm_us, 1)
        for name in args.cases.split(","):
            per_call_us = bench(loop(name)) / iters * 1e6
            rec[name + "_us"] = round(per_call_us, 1)
            print(f"# {name}: {per_call_us:.1f} us/call", file=sys.stderr,
                  flush=True)
        print(json.dumps(rec))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
