"""Parity probe: SSIM(strategy, oracle) on structured inputs.

Measures, for each TPU strategy, how closely it tracks the CPU/cKDTree
oracle on perlin-like natural-statistics inputs (VERDICT.md round-1 item 1:
the bench's white-noise inputs made the task ambiguous everywhere and the
parity number meaningless).  Run on the forced-CPU JAX platform so it probes
semantics, not chip perf:

    python experiments/parity_probe.py [--size 128] [--levels 3]
"""

from __future__ import annotations

import argparse
import os
import sys
import time

os.environ.setdefault("JAX_PLATFORMS", "cpu")

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax

try:
    jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])
except RuntimeError:
    pass

import numpy as np

from examples.make_assets import _oil_filter, make_structured
from image_analogies_tpu.config import AnalogyParams
from image_analogies_tpu.models.analogy import create_image_analogy
from image_analogies_tpu.utils.ssim import ssim


def main() -> int:
    ap_ = argparse.ArgumentParser()
    ap_.add_argument("--size", type=int, default=128)
    ap_.add_argument("--levels", type=int, default=3)
    ap_.add_argument("--kappa", type=float, default=5.0)
    ap_.add_argument("--strategies", default="rowwise,batched")
    ap_.add_argument("--seeds", default="7")
    args = ap_.parse_args()

    for seed in [int(s) for s in args.seeds.split(",")]:
        a, ap, b = make_structured(args.size, seed)
        ideal = _oil_filter(b)

        base = dict(levels=args.levels, kappa=args.kappa)
        t0 = time.perf_counter()
        oracle = create_image_analogy(
            a, ap, b, AnalogyParams(backend="cpu", **base))
        t_oracle = time.perf_counter() - t0
        print(f"seed={seed} oracle: {t_oracle:.1f}s "
              f"ssim_vs_ideal={ssim(oracle.bp_y, ideal):.3f} "
              f"coh={[round(s['coherence_ratio'], 2) for s in oracle.stats]}")

        for strat in args.strategies.split(","):
            t0 = time.perf_counter()
            res = create_image_analogy(
                a, ap, b,
                AnalogyParams(backend="tpu", strategy=strat, **base))
            dt = time.perf_counter() - t0
            print(f"seed={seed} {strat:>10}: {dt:.1f}s "
                  f"ssim_vs_oracle={ssim(res.bp_y, oracle.bp_y):.3f} "
                  f"ssim_vs_ideal={ssim(res.bp_y, ideal):.3f} "
                  f"coh={[round(s['coherence_ratio'], 2) for s in res.stats]}")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
