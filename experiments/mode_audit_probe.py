"""On-chip match-mode parity audit: run one config on the REAL TPU with a
forced wavefront match_mode, score it against a live CPU/cKDTree oracle
run, and emit SSIM / value_match / the full tie-audit — the adjudication
step every new scan variant must pass before `auto` may steer to it
(round-3 memory: bf16-resolution scans LOOK fine on SSIM and still walk
away from the oracle; only the audit separates tie-drift from real drift).

    python experiments/mode_audit_probe.py --mode exact_hi2_2p --size 256
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# adjudication must be able to measure the gated non-parity modes too
os.environ["IA_EXPERIMENTAL"] = "1"

import numpy as np

from examples.make_assets import make_structured
from image_analogies_tpu.config import AnalogyParams
from image_analogies_tpu.models.analogy import create_image_analogy
from image_analogies_tpu.utils.parity import audit_source_map_mismatches
from image_analogies_tpu.utils.ssim import ssim


def main() -> int:
    pa = argparse.ArgumentParser()
    pa.add_argument("--mode", default="exact_hi2_2p")
    pa.add_argument("--size", type=int, default=256)
    pa.add_argument("--levels", type=int, default=3)
    pa.add_argument("--kappa", type=float, default=5.0)
    pa.add_argument("--seed", type=int, default=7)
    pa.add_argument("--reps", type=int, default=3)
    args = pa.parse_args()

    import jax

    a, ap, b = make_structured(args.size, args.seed)
    p = AnalogyParams(levels=args.levels, kappa=args.kappa, backend="tpu",
                      strategy="wavefront", match_mode=args.mode)
    res = create_image_analogy(a, ap, b, p, keep_levels=True)  # warm
    ts = []
    for _ in range(args.reps):
        t0 = time.perf_counter()
        res = create_image_analogy(a, ap, b, p, keep_levels=True)
        ts.append(time.perf_counter() - t0)

    t0 = time.perf_counter()
    orc = create_image_analogy(a, ap, b, p.replace(backend="cpu"),
                               keep_levels=True)
    cpu_s = time.perf_counter() - t0

    audit = audit_source_map_mismatches(a, ap, b, p, res.levels, orc.levels)
    print(json.dumps({
        "mode": args.mode, "size": args.size, "levels": args.levels,
        "seed": args.seed,
        "backend": jax.default_backend(),
        "tpu_s": round(min(ts), 3),
        "tpu_s_median": round(float(np.median(ts)), 3),
        "cpu_s": round(cpu_s, 1),
        "ssim_vs_oracle": round(ssim(res.bp_y, orc.bp_y), 4),
        "value_match": round(float((res.bp_y == orc.bp_y).mean()), 4),
        "source_map_mismatch": round(float(
            (res.source_map != orc.source_map).mean()), 6),
        "mismatch_explained_by_ties": audit["mismatch_explained_by_ties"],
        "unexplained": audit["unexplained"],
        "first_divergence_is_tie": audit["first_divergence_is_tie"],
        "classes": {k: audit[k] for k in
                    ("mismatches", "ctx_diverged", "tie_exact", "tie_fp",
                     "kappa_boundary")},
    }))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
