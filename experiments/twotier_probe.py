"""Feasibility probe for a rigorous-bound two-tier wavefront scan
(round-4 VERDICT item 1, "alternatively/additionally" clause).

The shipping `packed2k_best` scan streams the FULL K-wide weight array
(512 MB at north-star level 0) every wavefront step.  A two-tier scheme
would:

  pass 1 (cheap): stream only the d1 + norm lanes (~half the bytes, one
          K=128 MXU pass, per-TILE max only — no argmax), giving each
          query's per-tile cheap maxima  c[m, t].
  pass 2 (exact): re-run the exact 2p kernel over ONLY the tiles that
          could contain the champion.  Exclusion is by Cauchy-Schwarz:
          with  e(m, r) = exact(m, r) - cheap(m, r)
                        = q1.d2 + q2.d1 + q1.d3   (+ fp slop),
          |e(m, r)| <= E[m] = ||q1[m]|| (max_r||d2[r]|| + max_r||d3[r]||)
                             + ||q2[m]|| max_r||d1[r]||,
          a row r can win or TIE the champion only if its tile satisfies
          c[m, t] >= max_t c[m, t] - 2 E[m]  (rows outside are STRICTLY
          worse — see the derivation in the two-tier design note in
          ops/pallas_match.py if this ships).  Pass-2 scores are computed
          by the same kernel on the same tile blocks, so the final
          (val, idx) champion is BIT-IDENTICAL to the full scan's.

Whether this wins depends on ONE empirical number this probe measures on
the real north-star data: the size of the UNION over the diagonal's M
queries of the candidate tile sets (the pass-2 kernel streams the union).
If the union is a small fraction of the ~256 tiles, pass 2 is cheap and
the scan's HBM/MXU/VPU cost roughly halves; if neighboring queries'
champions scatter across tiles, the union saturates and the scheme loses.

Queries are reconstructed EXACTLY as the wavefront step builds them, from
the cached oracle's level planes (each pixel is written once, so the
final plane restricted to `written` positions IS the mid-scan state).

MEASURED VERDICT (round 5, north-star level 0, seeds 7): **dead end, both
variants.**  (a) cheap = q1.d1 + norm: the Cauchy-Schwarz band 2E is
14-27% of the score magnitude — every tile survives (union_frac = 1.0 at
every tile size).  (b) cheap = the full packed1w set (residual ONLY
q1.d3, E ~ 1e-5): the per-query candidate set is STILL ~half of all
tiles and the union saturates (union_frac 0.93-1.0 at tile 512, ~1.0 at
4096; tile-refined per-tile bounds shave < 4%).  The score
distribution's top is radically flat — posterized/flat regions put
thousands of rows within ~1e-5 of the champion (the same tie structure
the audit classifies), so NO rigorous bound can prune tiles: the band
that guarantees bit-equality necessarily contains half the DB.  The
512 MB/step two-stream-equivalent the round-4 BASELINE derived is
confirmed as the parity floor; further scan speedups must come from
outside the scan (fusing the XLA tail, host/tunnel share).

Usage:  python experiments/twotier_probe.py [--size 1024] [--seed 7]
"""

from __future__ import annotations

import argparse
import json
import os
import sys

import numpy as np

_HERE = os.path.dirname(os.path.abspath(__file__))
sys.path.insert(0, os.path.dirname(_HERE))

import jax
import jax.numpy as jnp

from image_analogies_tpu.backends.base import LevelJob
from image_analogies_tpu.backends.tpu import TpuMatcher
from image_analogies_tpu.config import AnalogyParams
from image_analogies_tpu.models.analogy import _prep_planes
from image_analogies_tpu.ops.features import spec_for_level
from image_analogies_tpu.ops.pyramid import build_pyramid_np

_F32 = jnp.float32


def build_level0_db(size: int, seed: int, levels: int, kappa: float):
    """Level-0 TpuLevelDB for the north-star config, with the coarser B'
    plane taken from the cached oracle (bp_l1) so the DB and queries are
    the ones the real benchmark run sees."""
    from examples.make_assets import make_structured

    a, ap, b = make_structured(size, seed)
    oz = np.load(os.path.join(os.path.dirname(_HERE), "bench_cache",
                              f"oracle_1024_seed{seed}.npz"))
    params = AnalogyParams(levels=levels, kappa=kappa, backend="tpu",
                           strategy="wavefront")
    a_src, b_src, a_filt, _, _ = _prep_planes(a, ap, b, params)
    a_src_pyr = build_pyramid_np(a_src, levels)
    a_filt_pyr = build_pyramid_np(a_filt, levels)
    b_src_pyr = build_pyramid_np(b_src, levels)
    spec = spec_for_level(params, 0, levels, 1)
    job = LevelJob(
        level=0, spec=spec, kappa_mult=params.kappa_factor(0) ** 2,
        a_src=a_src_pyr[0], a_filt=a_filt_pyr[0], b_src=b_src_pyr[0],
        a_src_coarse=a_src_pyr[1], a_filt_coarse=a_filt_pyr[1],
        b_src_coarse=b_src_pyr[1],
        b_filt_coarse=np.asarray(oz["bp_l1"], np.float32),
    )
    db = TpuMatcher(params).build_features(job)
    return db, oz


def queries_at_step(db, bps, seg, t):
    """EXACT mirror of wavefront_scan_core's per-step query build."""
    nf = int(db.off.shape[0])
    nc = (nf - 1) // 2
    off_i = db.off[:, 0][None, :]
    off_j = db.off[:, 1][None, :]
    hb, wb = db.hb, db.wb
    pix = seg[t]
    lane_ok = pix >= 0
    pixc = jnp.maximum(pix, 0)
    qi = pixc // wb
    qj = pixc - qi * wb
    wi = qi[:, None] + off_i[:, :nc]
    wj = qj[:, None] + off_j[:, :nc]
    idx = (jnp.clip(wi, 0, hb - 1) * wb + jnp.clip(wj, 0, wb - 1))
    written = (idx < pixc[:, None]).astype(_F32)
    g = bps[idx]
    dyn = g[..., 0] * written * db.fine_sqrtw[None, :nc]
    m = int(dyn.shape[0])
    dyn_full = jnp.zeros((m, nf), _F32).at[:, :nc].set(dyn)
    queries = jax.lax.dynamic_update_slice(
        db.static_q[pixc], dyn_full, (0, db.fine_start))
    return queries, lane_ok


def main() -> int:
    ap_ = argparse.ArgumentParser()
    ap_.add_argument("--size", type=int, default=1024)
    ap_.add_argument("--seed", type=int, default=7)
    ap_.add_argument("--levels", type=int, default=5)
    ap_.add_argument("--kappa", type=float, default=5.0)
    ap_.add_argument("--steps", type=int, default=8,
                     help="number of sampled wavefront steps")
    args = ap_.parse_args()

    db, oz = build_level0_db(args.size, args.seed, args.levels, args.kappa)
    assert db.match_mode in ("auto", "exact_hi2_2p") or True
    wk = db.db_pad  # (Npad, Kp) K-wide packed array
    live = db.live_idx
    lw = int(live.shape[0])
    o2 = 2 * lw + 3
    npad = int(wk.shape[0])
    print(f"level-0 DB: Na={db.ha * db.wa} Npad={npad} L={lw} "
          f"Kp={int(wk.shape[1])} mode={db.match_mode}", flush=True)

    # final level-0 planes -> the packed (Nb, 2) carry
    bp0 = jnp.asarray(np.asarray(oz["bp_l0"], np.float32).reshape(-1))
    s0 = jnp.asarray(np.asarray(oz["s_l0"], np.int32).reshape(-1))
    bps = jnp.stack([bp0, s0.astype(_F32)], axis=-1)

    # weight-lane views (all bf16 -> f32 for the probe math)
    d1 = wk[:, :lw].astype(_F32)
    d2 = wk[:, lw:2 * lw].astype(_F32)
    d3 = wk[:, o2 + lw:o2 + 2 * lw].astype(_F32)
    nsum = jnp.sum(wk[:, 2 * lw:o2].astype(_F32), axis=1)  # ~ -dbnh
    nd1 = float(jnp.max(jnp.linalg.norm(d1, axis=1)))
    nd2 = float(jnp.max(jnp.linalg.norm(d2, axis=1)))
    nd3 = float(jnp.max(jnp.linalg.norm(d3, axis=1)))
    print(f"max row norms: ||d1||={nd1:.4f} ||d2||={nd2:.2e} "
          f"||d3||={nd3:.2e}", flush=True)

    from image_analogies_tpu.ops.pallas_match import bf16_split3

    # big arrays are jit ARGUMENTS, not closure constants — captured
    # constants ride inside the remote-compile request and 413 it.
    # Everything reduces ON DEVICE: fetching an (M, Npad) f32 plane over
    # this ~20 MB/s tunnel would cost ~70 s per step.
     
    base_tile = 512

    # per-512-tile max of ||d3[r]|| — the residual term's tile-refined bound
    nd3_tile512 = jnp.max(
        jnp.linalg.norm(d3, axis=1).reshape(npad // base_tile, base_tile),
        axis=1)

    @jax.jit
    def tile_stats(queries, d1, d2, d3, nsum):
        qc = queries - db.feat_mean[None, :queries.shape[1]]
        g1, g2, _ = bf16_split3(qc[:, live])
        q1 = g1.astype(jnp.bfloat16).astype(_F32)
        q2 = g2.astype(jnp.bfloat16).astype(_F32)
        # "1w" cheap pass: the full packed1w product set (q1.d1 + q1.d2 +
        # q2.d1 + norm) — one 128-lane weight stream [d1|d2|norms], HALF
        # the K-wide array's bytes; residual vs exact 2p is ONLY q1.d3
        cheap = (q1 @ d1.T + q1 @ d2.T + q2 @ d1.T + nsum[None, :])
        exact = cheap + q1 @ d3.T
        m = cheap.shape[0]
        cm = cheap.reshape(m, npad // base_tile, base_tile).max(axis=2)
        champ = jnp.argmax(exact, axis=1)
        nq1 = jnp.linalg.norm(q1, axis=1)
        e_bound = nq1 * nd3
        # fp slop: the kernel's fp32 accumulation vs this probe's — both
        # ~2^-22 relative of the partial magnitudes; inflate generously
        e_bound = e_bound * 1.02 + 2.0 ** -18 * (nq1 * nd1 + 1.0)
        # tile-refined residual bound (per query x per 512-tile)
        e_tile = (nq1[:, None] * nd3_tile512[None, :] * 1.02
                  + 2.0 ** -18 * (nq1[:, None] * nd1 + 1.0))
        return cm, champ, e_bound, e_tile

    # sample steps across the schedule, weighted toward the plateau
    segs = db.diag
    flat = [(si, t) for si, seg in enumerate(segs)
            for t in range(int(seg.shape[0]))]
    n_total = len(flat)
    picks = [flat[int(f * (n_total - 1))]
             for f in np.linspace(0.1, 0.95, args.steps)]

    results = []
    for si, t in picks:
        seg = segs[si]
        queries, lane_ok = queries_at_step(db, jnp.asarray(bps), seg, t)
        cm512, champ, e_b, e_t = tile_stats(queries, d1, d2, d3, nsum)
        cm512 = np.asarray(cm512)    # (M, Npad/512) per-512-tile maxima
        champ = np.asarray(champ)
        e_b = np.asarray(e_b)
        e_t = np.asarray(e_t)        # (M, Npad/512) tile-refined bound
        ok = np.asarray(lane_ok)
        m = int(ok.sum())
        rec = {"seg": si, "t": t, "M": m,
               "E_med": float(np.median(e_b[ok])),
               "band_rel": float(np.median(
                   2 * e_b[ok] / np.maximum(np.abs(
                       cm512[ok].max(axis=1)), 1e-9)))}
        for tile in (512, 1024, 2048, 4096):
            nt = npad // tile
            pool = lambda x: x.reshape(x.shape[0], nt, tile // base_tile
                                       ).max(axis=2)
            cm = pool(cm512)
            et = pool(e_t)
            # global-bound selection: c[t] >= cmax - 2E
            cand = cm >= (cm.max(axis=1) - 2 * e_b)[:, None]
            # tile-refined: candidate tile needs cm[t] + E[t] >= max_s
            # (cm[s] - E[s]);  champion's own -E side uses per-tile too
            lo = (cm - et).max(axis=1)
            cand_r = (cm + et) >= lo[:, None]
            per_q = cand[ok].sum(axis=1)
            union = int(np.any(cand[ok], axis=0).sum())
            union_r = int(np.any(cand_r[ok], axis=0).sum())
            # sanity: the exact champion's tile must be in each query's set
            champ_tile = champ[ok] // tile
            in_set = bool(np.all(cand[ok][np.arange(m), champ_tile]))
            in_set_r = bool(np.all(cand_r[ok][np.arange(m), champ_tile]))
            rec[f"tile{tile}"] = {
                "ntiles": nt, "perq_med": float(np.median(per_q)),
                "perq_max": int(per_q.max()), "union": union,
                "union_frac": round(union / nt, 4),
                "union_refined": union_r,
                "union_refined_frac": round(union_r / nt, 4),
                "champ_in_set": in_set and in_set_r}
        results.append(rec)
        print(json.dumps(rec), flush=True)

    # aggregate
    for tile in (512, 1024, 2048, 4096):
        fr = [r[f"tile{tile}"]["union_frac"] for r in results]
        print(f"tile={tile}: union_frac med={np.median(fr):.4f} "
              f"max={max(fr):.4f}", flush=True)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
