"""On-chip A/B of the wavefront anchor modes (round-3 VERDICT item 1).

Runs the wavefront strategy end-to-end in both match modes —
"exact_hi" (round-2 baseline: HIGHEST-precision scan kernel) and
"two_pass" (bf16 top-2 scan + exact fp32 re-score) — and reports wall-clock
plus parity (value_match / SSIM / source-map mismatch) against the live CPU
oracle at sizes where the oracle is affordable, and two_pass-vs-exact_hi
agreement at every size.

    python experiments/two_pass_probe.py [--sizes 256,512] [--reps 3]

Timing variance over the PJRT tunnel is +-40% run-to-run: report min of
--reps (the schedulable floor) AND the list.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

from examples.make_assets import make_structured
from image_analogies_tpu.config import AnalogyParams
from image_analogies_tpu.models.analogy import create_image_analogy
from image_analogies_tpu.utils.ssim import ssim


def timed(p, a, ap, b, reps):
    res = create_image_analogy(a, ap, b, p)  # compile warm-up
    ts = []
    for _ in range(reps):
        t0 = time.perf_counter()
        res = create_image_analogy(a, ap, b, p)
        ts.append(round(time.perf_counter() - t0, 3))
    return res, ts


def parity(x, y):
    return {
        "value_match": round(float((x.bp_y == y.bp_y).mean()), 5),
        "ssim": round(ssim(x.bp_y, y.bp_y), 5),
        "map_mismatch": round(
            float((x.source_map != y.source_map).mean()), 5),
        "mae": round(float(np.abs(x.bp_y - y.bp_y).mean()), 7),
    }


def main() -> int:
    ap_args = argparse.ArgumentParser()
    ap_args.add_argument("--sizes", default="256,512")
    ap_args.add_argument("--reps", type=int, default=3)
    ap_args.add_argument("--oracle-max", type=int, default=256,
                         help="run the live CPU oracle up to this size")
    ap_args.add_argument("--modes",
                         default="auto,exact_hi2_2p,exact_hi")
    args = ap_args.parse_args()

    import jax

    print(f"# backend={jax.default_backend()} "
          f"dev={jax.devices()[0].device_kind}", file=sys.stderr)

    # a user-supplied --modes list may name the gated experimental probes;
    # override even an inherited falsey value (the gate guards users, not
    # measurement)
    os.environ["IA_EXPERIMENTAL"] = "1"
    modes = args.modes.split(",")
    for size in [int(s) for s in args.sizes.split(",")]:
        levels = 5 if size >= 1024 else 3
        a, ap, b = make_structured(size)
        base = AnalogyParams(levels=levels, kappa=5.0, backend="tpu",
                             strategy="wavefront")
        runs = {}
        for mode in modes:
            runs[mode] = timed(base.replace(match_mode=mode), a, ap, b,
                               args.reps)
            print(f"# {size} {mode}: {runs[mode][1]}", file=sys.stderr,
                  flush=True)
        rec = {"size": size, "levels": levels}
        for mode, (_, ts) in runs.items():
            rec[f"{mode}_s"] = ts
            rec[f"{mode}_min"] = min(ts)
        if "exact_hi" in runs:
            for mode in modes:
                if mode != "exact_hi":
                    rec[f"speedup_{mode}_vs_hi"] = round(
                        min(runs["exact_hi"][1]) / min(runs[mode][1]), 2)
                    rec[f"{mode}_vs_hi"] = parity(runs[mode][0],
                                                  runs["exact_hi"][0])
        if size <= args.oracle_max:
            t0 = time.perf_counter()
            r_cpu = create_image_analogy(a, ap, b,
                                         base.replace(backend="cpu"))
            rec["oracle_s"] = round(time.perf_counter() - t0, 1)
            for mode in modes:
                rec[f"{mode}_vs_oracle"] = parity(runs[mode][0], r_cpu)
        print(json.dumps(rec))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
