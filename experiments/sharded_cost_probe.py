"""Measure the sharded wavefront step's overhead on the REAL chip
(round-3 VERDICT item 3: replace 'argmin work divides' with a defended
multi-chip projection).

On this one-chip box the collectives themselves are degenerate, but the
mesh program's STRUCTURE is real: the same shard_map with the min+argmin
all-gather, two psum row-gathers per step, shard padding, and the
HIGHEST-precision shard scan.  Comparing per-level wall-clock of

  (a) the normal single-chip path (auto: the packed 2-pass parity scan
      on the big level — the same kernel the mesh step runs per shard), and
  (b) the REAL mesh path on a 1-chip ('data' x 'db') mesh
      (build_sharded_db + multichip_level_step, exactly what db_shards>1
      dispatches),

gives the per-step dispatch/structure overhead of the sharded program.
The ICI bandwidth/latency terms are then analytic (payload sizes are
static), and BASELINE.md carries the resulting 4-chip projection with
every assumption stated.

    python experiments/sharded_cost_probe.py [--size 512] [--reps 3]
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

import jax
import jax.numpy as jnp

from examples.make_assets import make_structured
from image_analogies_tpu.backends.base import LevelJob
from image_analogies_tpu.backends.tpu import (
    _prepare_query_arrays,
    build_sharded_db,
    make_level_template,
)
from image_analogies_tpu.tune import resolve as tune
from image_analogies_tpu.config import AnalogyParams
from image_analogies_tpu.models.analogy import _prep_planes, create_image_analogy
from image_analogies_tpu.ops.features import spec_for_level
from image_analogies_tpu.ops.pyramid import build_pyramid_np
from image_analogies_tpu.parallel.mesh import make_mesh
from image_analogies_tpu.parallel.step import multichip_level_step


def main() -> int:
    pa = argparse.ArgumentParser()
    pa.add_argument("--size", type=int, default=512)
    pa.add_argument("--reps", type=int, default=3)
    args = pa.parse_args()

    size = args.size
    levels = 3
    a, ap, b = make_structured(size)
    # auto resolves the big level to exact_hi2_2p — the SAME packed scan
    # the real-TPU mesh step now runs per shard, so solo-vs-mesh compares
    # identical kernels and the delta isolates the mesh structure
    params = AnalogyParams(levels=levels, kappa=5.0, backend="tpu",
                           strategy="wavefront")

    # (a) normal single-chip path at the mesh step's scan precision —
    # timed at the runner level (block_until_ready, no host fetch), warm,
    # exactly like the mesh side below, so the delta isolates the mesh
    # program's structure
    res = create_image_analogy(a, ap, b, params, keep_levels=True)

    # (b) the REAL mesh program on a 1-chip mesh, finest level only,
    # driven exactly like backends.tpu.synthesize_level's sharded branch
    a_src, b_src, a_filt, _, _ = _prep_planes(a, ap, b, params)
    pa_, pf_, pb_ = (build_pyramid_np(x, levels)
                     for x in (a_src, a_filt, b_src))
    lv = 0
    spec = spec_for_level(params, lv, levels, 1)
    job = LevelJob(
        level=lv, spec=spec, kappa_mult=params.kappa_factor(lv) ** 2,
        a_src=pa_[lv], a_filt=pf_[lv], b_src=pb_[lv],
        a_src_coarse=pa_[lv + 1], a_filt_coarse=pf_[lv + 1],
        b_src_coarse=pb_[lv + 1],
        b_filt_coarse=np.asarray(res.levels[lv + 1][0], np.float32),
        a_temporal=None, b_temporal=None)

    from image_analogies_tpu.backends.tpu import TpuMatcher, _run_wavefront

    matcher = TpuMatcher(params)
    db = matcher.build_features(job)
    km = jnp.float32(job.kappa_mult)

    def run_solo():
        bp, s, n = _run_wavefront(db, km)
        jax.block_until_ready((bp, s))

    run_solo()  # warm
    solo = []
    for _ in range(args.reps):
        t0 = time.perf_counter()
        run_solo()
        solo.append(time.perf_counter() - t0)
    lvl0_ms = min(solo) * 1e3

    mesh = make_mesh(db_shards=1)
    to_j = lambda x: None if x is None else jnp.asarray(x, jnp.float32)
    template = make_level_template(params, job, "wavefront")
    dbp, dbnp, afp, wk, shift, dbl = build_sharded_db(
        spec, to_j(job.a_src), to_j(job.a_filt), to_j(job.a_src_coarse),
        to_j(job.a_filt_coarse), None, template.rowsafe, mesh, True,
        tune.tile_rows(spec.total), packed=True)
    import dataclasses

    template = dataclasses.replace(template, feat_mean=shift)
    static_q = _prepare_query_arrays(
        spec, to_j(job.b_src), to_j(job.b_src_coarse),
        to_j(job.b_filt_coarse), None)

    def run_mesh():
        bp, s, n = multichip_level_step(
            mesh, static_q[None], dbp, dbnp, afp, template,
            job.kappa_mult, force_xla=False, wk_shard=wk, dbl_shard=dbl)
        jax.block_until_ready((bp, s))

    run_mesh()  # warm/compile
    mesh_s = []
    for _ in range(args.reps):
        t0 = time.perf_counter()
        run_mesh()
        mesh_s.append(time.perf_counter() - t0)

    hb, wb = job.b_shape
    c = spec.fine_size // 2 + 1
    steps = c * (hb - 1) + wb
    m_plateau = min(hb, (wb + c - 1) // c)
    f = spec.total
    nf = spec.fine_n
    rec = {
        "size": size,
        "solo_level0_s": [round(x, 3) for x in solo],
        "solo_level0_ms": round(lvl0_ms, 1),
        "mesh1_level0_s": [round(x, 3) for x in mesh_s],
        "steps_level0": steps,
        "solo_per_step_us": round(lvl0_ms * 1e3 / steps, 1),
        "mesh1_per_step_us": round(min(mesh_s) * 1e6 / steps, 1),
        "mesh_overhead_per_step_us": round(
            (min(mesh_s) - lvl0_ms / 1e3) * 1e6 / steps, 1),
        # analytic per-step ICI payloads for the 4-chip model (BASELINE.md)
        "allgather_pairs_bytes": 4 * m_plateau * 8,
        "psum_coh_bytes": m_plateau * nf * f * 4,
        "psum_afilt_bytes": m_plateau * nf * 4,
    }
    print(json.dumps(rec), flush=True)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
