"""Screen candidate-rescue depths for the fast wavefront anchor
(round-3 VERDICT item 1).

The scan_rescue anchor is: per-tile top-K champions under the centered bf16
scan metric -> top-T tiles by champion score -> exact fp32 re-score of the
T*K candidates -> (distance, index)-lexicographic min.  Its failure mode is
a true argmin whose scan score ranks BELOW K other rows in its own tile
(near-ties cluster within a tile: adjacent A pixels are near-duplicate
patches and tiles are contiguous row ranges), or whose tile's champion
ranks below T other tiles.  The round-3 audit showed K=1, T=8 mispicks from
the coarsest level up (first_divergence_is_tie=false, 48 clean unexplained)
— this probe measures the per-decision mispick rate for a (K, T) grid on
REAL evolved queries (reconstructed exactly from an exact_hi run's final
level planes; causality makes the final plane equal the decision-time
values).

    python experiments/rescue_probe.py [--size 256] [--level 0] [--sample N]
"""

from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

import jax
import jax.numpy as jnp

from examples.make_assets import make_structured
from image_analogies_tpu.tune import resolve as tune
from image_analogies_tpu.config import AnalogyParams
from image_analogies_tpu.models.analogy import _prep_planes, create_image_analogy
from image_analogies_tpu.ops.features import (
    build_features_np,
    fine_gather_maps,
    spec_for_level,
)
from image_analogies_tpu.ops.pyramid import build_pyramid_np

HI = jax.lax.Precision.HIGHEST


def main() -> int:
    pa = argparse.ArgumentParser()
    pa.add_argument("--size", type=int, default=256)
    pa.add_argument("--sample", type=int, default=16384)
    pa.add_argument("--level", type=int, default=0)
    pa.add_argument("--ks", default="1,2,4")
    pa.add_argument("--ts", default="4,8,16")
    args = pa.parse_args()

    size = args.size
    levels = 5 if size >= 1024 else 3
    a, ap, b = make_structured(size)
    params = AnalogyParams(levels=levels, kappa=5.0, backend="tpu",
                           strategy="wavefront", match_mode="exact_hi")
    res = create_image_analogy(a, ap, b, params, keep_levels=True)

    a_src, b_src, a_filt, _, _ = _prep_planes(a, ap, b, params)
    a_src_pyr = build_pyramid_np(a_src, levels)
    a_filt_pyr = build_pyramid_np(a_filt, levels)
    b_src_pyr = build_pyramid_np(b_src, levels)
    lv = args.level
    spec = spec_for_level(params, lv, levels, 1)
    coarse = lv + 1 < levels
    db = build_features_np(
        spec, a_src_pyr[lv], a_filt_pyr[lv],
        a_src_pyr[lv + 1] if coarse else None,
        a_filt_pyr[lv + 1] if coarse else None)
    static_q = build_features_np(
        spec, b_src_pyr[lv], None,
        b_src_pyr[lv + 1] if coarse else None,
        np.asarray(res.levels[lv + 1][0], np.float32) if coarse else None)
    hb, wb = np.asarray(res.levels[lv][0]).shape
    flat_idx, valid, written = fine_gather_maps(hb, wb, spec.fine_size)
    fsl = spec.fine_filt_slice
    sqrtw = spec.sqrt_weights()[fsl]
    bp_final = np.asarray(res.levels[lv][0], np.float32).reshape(-1)

    rng = np.random.default_rng(0)
    nb = hb * wb
    sel = np.sort(rng.choice(nb, min(args.sample, nb), replace=False))
    q = static_q[sel].copy()
    q[:, fsl] = bp_final[flat_idx[sel]] * written[sel] * sqrtw[None, :]

    na, f = db.shape
    a_filt_flat = a_filt_pyr[lv].reshape(-1).astype(np.float32)

    # production pad/tile geometry (backends/tpu.py build_features): the
    # build pad tile caps at tune.tile_rows(spec.total) and the scan tile
    # is chosen from the PADDED feature width, exactly like the backend
    fp = max((f + 127) // 128 * 128, 128)
    pad_tile = min(tune.tile_rows(spec.total),
                   max((na + 255) // 256 * 256, 256))
    npad = (na + pad_tile - 1) // pad_tile * pad_tile
    tile = tune.scan_tile(npad, fp)
    ntiles = npad // tile

    dbj = jnp.asarray(db)
    dbn = jnp.sum(dbj * dbj, axis=1)
    mean = jnp.mean(dbj, axis=0)
    dbc = dbj - mean[None, :]
    dbc16p = jnp.zeros((npad, f), jnp.bfloat16).at[:na].set(
        dbc.astype(jnp.bfloat16))
    dbnhp = jnp.full((npad,), jnp.inf, jnp.float32).at[:na].set(
        0.5 * jnp.sum(dbc * dbc, axis=1))
    qj = jnp.asarray(q)
    kmax = max(int(k) for k in args.ks.split(","))

    @jax.jit
    def chunk_stats(qc):
        # exact reference: HIGHEST-score argmin (= the exact_hi kernel pick)
        s_hi = dbn[None, :] - 2.0 * jnp.dot(
            qc, dbj.T, preferred_element_type=jnp.float32, precision=HI)
        ref = jnp.argmin(s_hi, axis=1).astype(jnp.int32)
        d_ref = jnp.sum((dbj[ref] - qc) ** 2, axis=-1)
        # scan sim (two_pass metric): centered bf16, hi/lo query split
        qcc = qc - mean[None, :]
        qh = qcc.astype(jnp.bfloat16)
        ql = (qcc - qh.astype(jnp.float32)).astype(jnp.bfloat16)
        dots = (jnp.dot(qh, dbc16p.T, preferred_element_type=jnp.float32)
                + jnp.dot(ql, dbc16p.T, preferred_element_type=jnp.float32))
        s2 = dots - dbnhp[None, :]  # bigger = closer; -inf on padding
        s2t = s2.reshape(s2.shape[0], ntiles, tile)
        tv, ta = jax.lax.top_k(s2t, kmax)  # per-tile top-kmax
        gidx = ta + (jnp.arange(ntiles) * tile)[None, :, None]
        return ref, d_ref, tv, gidx

    refs, drefs, tvs, gidxs = [], [], [], []
    C = 1024
    for c0 in range(0, qj.shape[0], C):
        r, dr, tv, gi = chunk_stats(qj[c0:c0 + C])
        refs.append(np.asarray(r)); drefs.append(np.asarray(dr))
        tvs.append(np.asarray(tv)); gidxs.append(np.asarray(gi))
    ref = np.concatenate(refs); d_ref = np.concatenate(drefs)
    tv = np.concatenate(tvs); gidx = np.concatenate(gidxs)
    m = ref.shape[0]

    print(json.dumps({"size": size, "level": lv, "na": int(na),
                      "tile": tile, "ntiles": ntiles,
                      "nb_sampled": int(m)}), flush=True)
    for k in [int(x) for x in args.ks.split(",")]:
        for t in [int(x) for x in args.ts.split(",")]:
            t_eff = min(t, ntiles)
            # top-t tiles by champion score
            torder = np.argsort(-tv[:, :, 0], axis=1, kind="stable")[:, :t_eff]
            cand = np.take_along_axis(
                gidx[:, :, :k], torder[:, :, None], axis=1).reshape(m, -1)
            cand = np.minimum(cand, na - 1)
            d = ((db[cand] - q[:, None, :]) ** 2).sum(-1)
            order = np.lexsort((cand, d), axis=-1)[:, 0]
            pick = np.take_along_axis(cand, order[:, None], 1)[:, 0]
            pick_d = np.take_along_axis(d, order[:, None], 1)[:, 0]
            mis = pick != ref
            rec = {
                "scheme": f"K{k}_T{t_eff}",
                "mispick": round(float(mis.mean()), 6),
                "value_mispick": round(float(
                    (a_filt_flat[pick] != a_filt_flat[ref]).mean()), 6),
                "dist_mispick": round(float((pick_d > d_ref).mean()), 6),
                "gap_max": float((pick_d - d_ref).max()),
            }
            print(json.dumps(rec), flush=True)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
