"""Isolate the two-pass scan kernel's per-query accuracy from the wavefront
cascade: on a REAL level DB (256^2 fine level), compare the top-2 +
fp32-re-score pick against the exact fp32 argmin for a batch of real
queries, and report mispick rate + the fp32 score gap distribution of the
mispicks.  Distinguishes "precision scheme insufficient" (small gaps,
moderate rate) from "kernel bug" (large gaps / huge rate).

    python experiments/kernel_accuracy_probe.py
"""

from __future__ import annotations

import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# this probe exists to measure the gated non-parity modes; override even
# an inherited falsey value — the gate guards users, not measurement
os.environ["IA_EXPERIMENTAL"] = "1"

import numpy as np

import jax
import jax.numpy as jnp

from examples.make_assets import make_structured
from image_analogies_tpu.backends.base import LevelJob
from image_analogies_tpu.backends.tpu import TpuMatcher
from image_analogies_tpu.tune import resolve as tune
from image_analogies_tpu.config import AnalogyParams
from image_analogies_tpu.models.analogy import _prep_planes
from image_analogies_tpu.ops.features import spec_for_level
from image_analogies_tpu.ops.pallas_match import (
    _lex_lt,
    prepadded_argmin2_queries,
)
from image_analogies_tpu.ops.pyramid import build_pyramid_np


def main() -> int:
    size = 256
    a, ap, b = make_structured(size)
    params = AnalogyParams(levels=3, kappa=5.0, backend="tpu",
                           strategy="wavefront", match_mode="two_pass")
    a_src, b_src, a_filt, _, _ = _prep_planes(a, ap, b, params)
    pyr_as = build_pyramid_np(a_src, 3)
    pyr_af = build_pyramid_np(a_filt, 3)
    pyr_bs = build_pyramid_np(b_src, 3)
    level = 0
    spec = spec_for_level(params, level, 3, 1)
    job = LevelJob(
        level=level, spec=spec, kappa_mult=params.kappa_factor(level) ** 2,
        a_src=pyr_as[level], a_filt=pyr_af[level], b_src=pyr_bs[level],
        a_src_coarse=pyr_as[level + 1], a_filt_coarse=pyr_af[level + 1],
        b_src_coarse=pyr_bs[level + 1],
        b_filt_coarse=np.zeros_like(pyr_bs[level + 1]),
        a_temporal=None, b_temporal=None)
    m = TpuMatcher(params)
    db = m.build_features(job)
    print(f"# db_pad dtype={db.db_pad.dtype} shape={db.db_pad.shape} "
          f"feat_mean? {db.feat_mean is not None}", file=sys.stderr)

    # realistic queries: static_q rows with the causal block zero —
    # exactly what the first diagonal scores; then add DB rows themselves
    # as queries (distance-0 case: exact self-match expected)
    rng = np.random.default_rng(0)
    qs = np.asarray(db.static_q)[rng.choice(db.static_q.shape[0], 2048,
                                            replace=False)]
    qd = np.asarray(db.db)[rng.choice(db.db.shape[0], 1024, replace=False)]
    dbf = jnp.asarray(db.db)
    dbn = jnp.asarray(db.db_sqnorm)

    for name, q in [("static_q", qs), ("db_rows", qd)]:
        qj = jnp.asarray(q)
        # exact reference on-chip: fp32 scores at HIGHEST via plain XLA
        scores = dbn[None, :] - 2.0 * jnp.dot(
            qj, dbf.T, preferred_element_type=jnp.float32,
            precision=jax.lax.Precision.HIGHEST)
        ref = jnp.argmin(scores, axis=1)
        ref_d = jnp.sum((dbf[ref] - qj) ** 2, axis=1)

        for q_split in (False, True):
            # chunk like the wavefront does (M <= ~344 per diagonal):
            # a single big M explodes the kernel's (M, tile_n) VMEM scores
            outs = []
            for c0 in range(0, qj.shape[0], 256):
                qc = qj[c0:c0 + 256] - db.feat_mean[None, :qj.shape[1]]
                outs.append(prepadded_argmin2_queries(
                    qc, db.db_pad, db.dbn_pad,
                    tile_n=tune.tile_rows(qj.shape[1]) // 2, q_split=q_split))
            i1 = jnp.concatenate([o[0] for o in outs])
            i2 = jnp.concatenate([o[1] for o in outs])
            ok2 = jnp.concatenate([o[2] for o in outs])
            d1 = jnp.sum((dbf[i1] - qj) ** 2, axis=1)
            d2 = jnp.where(ok2, jnp.sum((dbf[i2] - qj) ** 2, axis=1),
                           jnp.inf)
            use2 = _lex_lt(d2, i2, d1, i1)
            pick = jnp.where(use2, i2, i1)
            pick_d = jnp.where(use2, d2, d1)
            mis = np.asarray(pick != ref)
            gap = np.asarray(pick_d - ref_d)
            vals = np.asarray(db.a_filt_flat)
            val_mis = np.asarray(vals[np.asarray(pick)]
                                 != vals[np.asarray(ref)])
            rec = {
                "queries": name, "q_split": q_split,
                "mispick": round(float(mis.mean()), 5),
                "value_mispick": round(float(val_mis.mean()), 5),
                "gap_p50": float(np.median(gap[mis])) if mis.any() else 0.0,
                "gap_max": float(gap.max()),
                "rank2_rescues": int(np.asarray(use2).sum()),
            }
            print(json.dumps(rec))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
