"""Tile-size sweep for the packed2k_best scan kernel (round 5).

The shipping kernel runs 256 grid steps of 4096 rows at north-star level 0
(measured 0.845-1.03 ms vs a 625 us HBM floor).  Per-grid-step fixed cost
(champion fold, bookkeeping, DMA issue) is a candidate for part of the
gap: larger tiles halve the step count at the price of a bigger VMEM
footprint — the (M, tile) fp32 score block is the limiter, so tiles past
4096 need `vmem_limit` raised above the platform's scoped default.

    python experiments/kernel_tile_probe.py [--iters 300]
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

import jax
import jax.numpy as jnp

from image_analogies_tpu.ops.pallas_match import packed2k_best

_F32 = jnp.float32


def main() -> int:
    pa = argparse.ArgumentParser()
    pa.add_argument("--iters", type=int, default=300)
    pa.add_argument("--m", type=int, default=344)
    pa.add_argument("--npad", type=int, default=1048576)
    pa.add_argument("--kp", type=int, default=256)
    pa.add_argument("--l", type=int, default=55)
    args = pa.parse_args()

    rng = np.random.default_rng(0)
    wk = jnp.asarray(
        rng.standard_normal((args.npad, args.kp)).astype(np.float32)
        .astype(jnp.bfloat16))
    q1 = jnp.asarray(rng.standard_normal((args.m, args.l))
                     .astype(np.float32).astype(jnp.bfloat16))
    q2 = jnp.asarray((rng.standard_normal((args.m, args.l)) * 2 ** -8)
                     .astype(np.float32).astype(jnp.bfloat16))

    def bench(tile, vmem):
        @jax.jit
        def run(q1, q2, wk):
            def body(i, carry):
                q, acc = carry
                # feed a changing bf16 bit-pattern so iterations can't CSE
                qq = q + (acc % 2).astype(jnp.bfloat16)
                idx, val = packed2k_best(qq, q2, wk, tile_n=tile,
                                         vmem_limit=vmem)
                return q, acc + idx[0] % 2
            return jax.lax.fori_loop(0, args.iters, body,
                                     (q1, jnp.int32(0)))[1]

        out = run(q1, q2, wk)
        jax.block_until_ready(out)
        ts = []
        for _ in range(3):
            t0 = time.perf_counter()
            jax.block_until_ready(run(q1, q2, wk))
            ts.append(time.perf_counter() - t0)
        return min(ts) / args.iters * 1e6

    rec = {"m": args.m, "npad": args.npad, "iters": args.iters}
    for tile, vmem in ((4096, 0), (8192, 96 * 2 ** 20),
                      (16384, 110 * 2 ** 20)):
        try:
            us = bench(tile, vmem)
        except Exception as e:  # noqa: BLE001 — OOM/compile fails are data
            print(f"# tile={tile}: {type(e).__name__}", file=sys.stderr,
                  flush=True)
            rec[f"tile{tile}_us"] = None
            continue
        rec[f"tile{tile}_us"] = round(us, 1)
        print(f"# tile={tile} vmem={vmem >> 20}MB: {us:.1f} us/call",
              file=sys.stderr, flush=True)
    print(json.dumps(rec))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
