"""Tile-size sweep for the packed2k_best scan kernel (round 5).

The shipping kernel runs 256 grid steps of 4096 rows at north-star level 0
(measured 0.845-1.03 ms vs a 625 us HBM floor).  Per-grid-step fixed cost
(champion fold, bookkeeping, DMA issue) is a candidate for part of the
gap: larger tiles halve the step count at the price of a bigger VMEM
footprint — the (M, tile) fp32 score block is the limiter, so tiles past
4096 need `vmem_limit` raised above the platform's scoped default.

    python experiments/kernel_tile_probe.py [--iters 300]
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

import jax
import jax.numpy as jnp

from image_analogies_tpu.ops.pallas_match import packed2k_best

_F32 = jnp.float32


def main() -> int:
    pa = argparse.ArgumentParser()
    pa.add_argument("--iters", type=int, default=300)
    pa.add_argument("--m", type=int, default=344)
    pa.add_argument("--npad", type=int, default=1048576)
    pa.add_argument("--kp", type=int, default=256)
    pa.add_argument("--l", type=int, default=55)
    args = pa.parse_args()

    rng = np.random.default_rng(0)
    wk = jnp.asarray(
        rng.standard_normal((args.npad, args.kp)).astype(np.float32)
        .astype(jnp.bfloat16))

    # Harness cloned from step_decompose_probe's kernel case (the one
    # fori形 that measures real kernel time on this box): an f32 query
    # carried through the loop with centering + bf16 splits INSIDE the
    # body, nudged by dep(out)*1e-30 each iteration.  Plain async
    # dispatch was tried and rejected — per-call tunnel overhead ~2 ms
    # swamps the 0.85 ms kernel.
    from image_analogies_tpu.ops.pallas_match import bf16_split3

    q0v = jnp.asarray(rng.random((args.m, 128), dtype=np.float32) * 0.3)
    feat_mean = jnp.asarray(rng.random(128, dtype=np.float32) * 0.1)
    live_idx = jnp.asarray(
        np.sort(rng.choice(128, args.l, replace=False)).astype(np.int32))
    dep = lambda x: (x.reshape(-1)[0].astype(_F32) * 1e-30)

    def bench(tile, vmem):
        @jax.jit
        def run(q0v, wk, feat_mean, live_idx):
            def body(i, carry):
                q, acc = carry
                qc = q - feat_mean[None, :]
                g1, g2, _ = bf16_split3(qc[:, live_idx])
                idx, val = packed2k_best(
                    g1.astype(jnp.bfloat16), g2.astype(jnp.bfloat16), wk,
                    tile_n=tile, vmem_limit=vmem)
                return q.at[0, 0].add(dep(idx)), acc
            return jax.lax.fori_loop(0, args.iters, body,
                                      (q0v, jnp.int32(0)))[0]

        out = run(q0v, wk, feat_mean, live_idx)
        jax.block_until_ready(out)
        ts = []
        for _ in range(3):
            t0 = time.perf_counter()
            jax.block_until_ready(run(q0v, wk, feat_mean, live_idx))
            ts.append(time.perf_counter() - t0)
        return min(ts) / args.iters * 1e6

    rec = {"m": args.m, "npad": args.npad, "iters": args.iters}
    for tile, vmem in ((4096, 0), (8192, 96 * 2 ** 20),
                      (16384, 110 * 2 ** 20)):
        try:
            us = bench(tile, vmem)
        except Exception as e:  # noqa: BLE001 — OOM/compile fails are data
            print(f"# tile={tile}: {type(e).__name__}", file=sys.stderr,
                  flush=True)
            rec[f"tile{tile}_us"] = None
            continue
        rec[f"tile{tile}_us"] = round(us, 1)
        print(f"# tile={tile} vmem={vmem >> 20}MB: {us:.1f} us/call",
              file=sys.stderr, flush=True)
    print(json.dumps(rec))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
