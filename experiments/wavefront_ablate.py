"""Ablation probe: where does the wavefront finest-level time go on-chip?

Times the REAL wavefront scan against variants with pieces stubbed out:
  full        - THE production scan (wavefront_scan_core itself, so this
                baseline cannot drift from backends/tpu.py)
  no_coh      - skip the batched coherence block (kappa=0-ish path cost)
  no_kernel   - replace the Pallas argmin with a constant index (keeps
                gathers/scatters; isolates the kernel's share)
  kernel_only - argmin + scatter only (no coherence, no rescore)

    python experiments/wavefront_ablate.py --size 512
"""

from __future__ import annotations

import argparse
import functools
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np

from examples.make_assets import make_structured
from image_analogies_tpu.backends.base import LevelJob
from image_analogies_tpu.backends.tpu import (
    TpuLevelDB,
    TpuMatcher,
    _batched_coherence,
    make_approx_fn,
    wavefront_scan_core,
)
from image_analogies_tpu.config import AnalogyParams
from image_analogies_tpu.ops import color
from image_analogies_tpu.ops.features import spec_for_level

_F32 = jnp.float32


@functools.partial(jax.jit, static_argnames=("variant",))
def _run_variant(db: TpuLevelDB, kappa_mult, variant: str):
    approx_fn = make_approx_fn(db)
    if variant == "full":  # the REAL production scan
        return wavefront_scan_core(db, kappa_mult, approx_fn)
    nb = db.hb * db.wb
    nf = int(db.off.shape[0])
    # db.diag is a tuple of width-bucketed segments; the stubbed variants
    # only need relative timings, so run them on the concatenated schedule
    # padded to the widest segment
    m_max = max(int(seg.shape[1]) for seg in db.diag)
    diag = jnp.concatenate([
        jnp.pad(seg, ((0, 0), (0, m_max - seg.shape[1])),
                constant_values=-1) for seg in db.diag])
    t_total = int(diag.shape[0])

    def step(t, state):
        bp, s, n = state
        pix = diag[t]
        lane_ok = pix >= 0
        pixc = jnp.maximum(pix, 0)
        idx = db.flat_idx[pixc]
        dyn = bp[idx] * db.written[pixc] * db.fine_sqrtw[None, :]
        queries = jax.lax.dynamic_update_slice(
            db.static_q[pixc], dyn, (0, db.fine_start))
        if variant == "no_kernel":
            p_app = jnp.zeros((pix.shape[0],), jnp.int32)
        else:
            p_app, _ = approx_fn(queries)
        if variant == "no_kernel":
            d_app = jnp.sum((db.db[p_app] - queries) ** 2, axis=1)
            p_coh, d_coh, has_coh = _batched_coherence(
                db, s, queries, idx, db.valid[pixc] > 0, nf,
                lambda i: db.db[i])
            use_coh = has_coh & (d_coh <= d_app * kappa_mult)
            p = jnp.where(use_coh, p_coh, p_app).astype(jnp.int32)
        elif variant == "no_coh":
            d_app = jnp.sum((db.db[p_app] - queries) ** 2, axis=1)
            p = p_app.astype(jnp.int32)
            use_coh = lane_ok & (d_app < 0)
        else:  # kernel_only
            p = p_app.astype(jnp.int32)
            use_coh = lane_ok & (p < 0)
        wpix = jnp.where(lane_ok, pix, nb)
        bp = bp.at[wpix].set(db.a_filt_flat[p], mode="drop")
        s = s.at[wpix].set(p, mode="drop")
        return bp, s, n + (use_coh & lane_ok).sum(dtype=jnp.int32)

    bp0 = jnp.zeros((nb,), _F32)
    s0 = jnp.zeros((nb,), jnp.int32)
    return jax.lax.fori_loop(0, t_total, step, (bp0, s0, jnp.int32(0)))


def main() -> int:
    ap_ = argparse.ArgumentParser()
    ap_.add_argument("--size", type=int, default=512)
    ap_.add_argument("--reps", type=int, default=3)
    args = ap_.parse_args()

    a, ap, b = make_structured(args.size)
    params = AnalogyParams(levels=1, backend="tpu", strategy="wavefront")
    spec = spec_for_level(params, 0, 1, 1)
    a_src, a_filt, b_src = (color.luminance(a), color.luminance(ap),
                            color.luminance(b))
    a_src, a_filt = color.remap_pair(a_src, a_filt, b_src)
    job = LevelJob(level=0, spec=spec, kappa_mult=params.kappa_factor(0) ** 2,
                   a_src=a_src, a_filt=a_filt, b_src=b_src)
    db = TpuMatcher(params).build_features(job)
    km = jnp.float32(job.kappa_mult)

    for variant in ("full", "no_coh", "kernel_only", "no_kernel"):
        np.asarray(_run_variant(db, km, variant)[0])  # compile + drain
        ts = []
        for _ in range(args.reps):
            t0 = time.perf_counter()
            np.asarray(_run_variant(db, km, variant)[0])  # host copy blocks
            ts.append(time.perf_counter() - t0)
        print(f"{variant:>12}: {min(ts):.2f}s (min of {args.reps})",
              flush=True)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
