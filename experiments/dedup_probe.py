"""Measure per-level duplicate-row rates of the exemplar feature DB
(round-3 VERDICT item 1 groundwork).

The 1024^2 bench shows 37.8% source-map "mismatch" explained almost
entirely by exact ties among IDENTICAL DB rows (bench.py docstring).
Identical rows are pure waste for the full-DB scan kernel: every duplicate
row costs MXU flops + HBM stream every wavefront step yet can never beat
its lowest-index twin under the (val, idx)-lexicographic tie rule.  This
probe counts them: if the duplicate mass is large, an exact per-level dedup
(stable lowest-index representative) shrinks the kernel's Na proportionally
at ZERO parity cost.

    python experiments/dedup_probe.py [--sizes 256,1024] [--seed 7]
"""

from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

from examples.make_assets import make_structured
from image_analogies_tpu.config import AnalogyParams
from image_analogies_tpu.models.analogy import _prep_planes
from image_analogies_tpu.ops.features import build_features_np, spec_for_level
from image_analogies_tpu.ops.pyramid import build_pyramid_np, num_feasible_levels


def main() -> int:
    ap_args = argparse.ArgumentParser()
    ap_args.add_argument("--sizes", default="256,1024")
    ap_args.add_argument("--seed", type=int, default=7)
    args = ap_args.parse_args()

    for size in [int(s) for s in args.sizes.split(",")]:
        levels_req = 5 if size >= 1024 else 3
        a, ap, b = make_structured(size, args.seed)
        params = AnalogyParams(levels=levels_req, kappa=5.0)
        a_src, b_src, a_filt, _, _ = _prep_planes(a, ap, b, params)
        levels = num_feasible_levels(a_src.shape[:2], params.levels,
                                     params.patch_size)
        a_src_pyr = build_pyramid_np(a_src, levels)
        a_filt_pyr = build_pyramid_np(a_filt, levels)
        rec = {"size": size, "seed": args.seed, "levels": levels,
               "per_level": []}
        for level in range(levels - 1, -1, -1):
            spec = spec_for_level(params, level, levels, 1)
            db = build_features_np(
                spec, a_src_pyr[level], a_filt_pyr[level],
                a_src_pyr[level + 1] if level + 1 < levels else None,
                a_filt_pyr[level + 1] if level + 1 < levels else None)
            rows = np.ascontiguousarray(db).view(
                np.dtype((np.void, db.dtype.itemsize * db.shape[1]))
            ).ravel()
            n = rows.size
            n_unique = np.unique(rows).size
            rec["per_level"].append({
                "level": level, "rows": int(n), "unique": int(n_unique),
                "dup_frac": round(1.0 - n_unique / n, 4),
            })
        # weight by per-level kernel work ~ Na * Nb ~ Na^2 (A and B same size
        # here), so the finest level dominates the achievable saving
        work = sum(r["rows"] ** 2 for r in rec["per_level"])
        saved = sum(r["rows"] * (r["rows"] - r["unique"])
                    for r in rec["per_level"])
        rec["work_weighted_dup_frac"] = round(saved / work, 4)
        print(json.dumps(rec), flush=True)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
