"""Gauss-Seidel iterated strategies probe.

Hypothesis (from parity_probe results): the SSIM gap vs the oracle is driven
by the approximate-match ANCHORS being picked from stale queries (same-row
left neighbors zeroed) — in-row sequential coherence alone (rowwise) only
reaches ~0.6.  The oracle's output is a fixed point of re-resolving each row
with queries rebuilt from the current row estimate; iterate that:

  pass 0: anchors from rowsafe queries -> resolve row
  pass k: rebuild FULL queries (same-row left values from current estimate),
          redo full-DB argmin anchors, re-resolve row

"rowwise_gs": the re-resolve is the exact sequential coherence/kappa pass.
"""

from __future__ import annotations

import argparse
import functools
import os
import sys
import time

os.environ.setdefault("JAX_PLATFORMS", "cpu")
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax

try:
    jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])
except RuntimeError:
    pass

import jax.numpy as jnp
import numpy as np

from experiments.parity_probe import make_structured
from examples.make_assets import _oil_filter
from image_analogies_tpu.backends.tpu import (
    TpuLevelDB,
    TpuMatcher,
    _exact_qvec,
    _pixel_coherence,
    _row_queries,
)
from image_analogies_tpu.config import AnalogyParams
from image_analogies_tpu.models.analogy import create_image_analogy
from image_analogies_tpu.ops.pallas_match import argmin_l2
from image_analogies_tpu.utils.ssim import ssim

_F32 = jnp.float32


@functools.partial(jax.jit, static_argnames=("passes",))
def _run_rowwise_gs(db: TpuLevelDB, kappa_mult, passes: int = 2):
    wb, hb = db.wb, db.hb
    ones = jnp.ones_like(db.rowsafe)

    def seq_pass(r, bp, s, p_apps):
        def pixel_body(j, carry):
            bp, s, n_coh = carry
            q = r * wb + j
            qvec = _exact_qvec(db, q, bp)
            p_app = p_apps[j]
            d_app = jnp.sum((db.db[p_app] - qvec) ** 2)
            p_coh, d_coh, has_coh = _pixel_coherence(db, qvec, q, s)
            use_coh = has_coh & (d_coh <= d_app * kappa_mult)
            p = jnp.where(use_coh, p_coh, p_app).astype(jnp.int32)
            bp = bp.at[q].set(db.a_filt_flat[p])
            s = s.at[q].set(p)
            return bp, s, n_coh + use_coh.astype(jnp.int32)

        return jax.lax.fori_loop(0, wb, pixel_body, (bp, s, jnp.int32(0)))

    def row_body(r, state):
        bp, s, n_coh_tot = state
        q0 = _row_queries(db, r, bp, db.rowsafe)
        p_apps, _ = argmin_l2(q0, db.db, db.db_sqnorm)
        bp, s, n_coh = seq_pass(r, bp, s, p_apps)
        for _ in range(passes):
            qk = _row_queries(db, r, bp, ones)
            p_apps, _ = argmin_l2(qk, db.db, db.db_sqnorm)
            bp, s, n_coh = seq_pass(r, bp, s, p_apps)
        return bp, s, n_coh_tot + n_coh

    bp0 = jnp.zeros((hb * wb,), _F32)
    s0 = jnp.zeros((hb * wb,), jnp.int32)
    return jax.lax.fori_loop(0, hb, row_body, (bp0, s0, jnp.int32(0)))


class GsMatcher(TpuMatcher):
    """Routes synthesize_level through the GS runner (probe only)."""

    def __init__(self, params, passes, runner="rowwise"):
        super().__init__(params)
        self.passes = passes
        self.runner = runner

    def synthesize_level(self, db, job):
        t0 = time.perf_counter()
        fn = (_run_rowwise_gs if self.runner == "rowwise"
              else _run_batched_gs)
        bp, s, n_coh = fn(db, jnp.float32(job.kappa_mult), passes=self.passes)
        bp = np.asarray(bp, np.float32)
        s = np.asarray(s, np.int32)
        hb, wb = job.b_shape
        stats = {"level": job.level, "pixels": hb * wb,
                 "coherence_ratio": float(n_coh) / max(hb * wb, 1),
                 "ms": (time.perf_counter() - t0) * 1e3,
                 "backend": "tpu", "strategy": f"rowwise_gs{self.passes}"}
        return bp.reshape(hb, wb), s.reshape(hb, wb), stats


def main() -> int:
    ap_ = argparse.ArgumentParser()
    ap_.add_argument("--size", type=int, default=128)
    ap_.add_argument("--levels", type=int, default=3)
    ap_.add_argument("--kappa", type=float, default=5.0)
    ap_.add_argument("--seed", type=int, default=7)
    ap_.add_argument("--passes", default="1,2")
    ap_.add_argument("--runner", default="rowwise")
    args = ap_.parse_args()

    a, ap, b = make_structured(args.size, args.seed)
    ideal = _oil_filter(b)
    base = dict(levels=args.levels, kappa=args.kappa)

    oracle = create_image_analogy(a, ap, b, AnalogyParams(backend="cpu", **base))
    print(f"oracle ssim_vs_ideal={ssim(oracle.bp_y, ideal):.3f}")

    for passes in [int(x) for x in args.passes.split(",")]:
        p = AnalogyParams(backend="tpu", strategy="rowwise", **base)
        t0 = time.perf_counter()
        res = create_image_analogy(a, ap, b, p,
                                   backend=GsMatcher(p, passes, args.runner))
        dt = time.perf_counter() - t0
        print(f"{args.runner}_gs passes={passes}: {dt:.1f}s "
              f"ssim_vs_oracle={ssim(res.bp_y, oracle.bp_y):.3f} "
              f"ssim_vs_ideal={ssim(res.bp_y, ideal):.3f}")
    return 0




@functools.partial(jax.jit, static_argnames=("passes",))
def _run_batched_gs(db: TpuLevelDB, kappa_mult, passes: int = 2):
    """Fully-batched GS: pass 0 = rows-above resolve; passes k>0 rebuild FULL
    queries from the current row estimate and re-resolve with the full causal
    candidate window (same-row candidates from current s) — no sequential
    inner loop at all."""
    wb, hb = db.wb, db.hb
    nf = int(db.off.shape[0])
    nrs = db.n_rowsafe
    ones = jnp.ones_like(db.rowsafe)

    def resolve(r, bp, s, queries, p_app, d_app, n_cand):
        """Batched coherence + kappa for row r using the first n_cand causal
        offsets (nrs for pass 0, all nf for GS passes), full-DB metric."""
        q0 = r * wb
        idx_c = jax.lax.dynamic_slice(db.flat_idx, (q0, 0), (wb, nf))[:, :n_cand]
        ok = jax.lax.dynamic_slice(db.valid, (q0, 0), (wb, nf))[:, :n_cand] > 0
        s_r = s[idx_c]
        ci = s_r // db.wa - db.off[:n_cand, 0][None, :]
        cj = s_r % db.wa - db.off[:n_cand, 1][None, :]
        ok = ok & (ci >= 0) & (ci < db.ha) & (cj >= 0) & (cj < db.wa)
        cand = (jnp.clip(ci, 0, db.ha - 1) * db.wa
                + jnp.clip(cj, 0, db.wa - 1))
        cf = db.db[cand]
        dc = jnp.sum((cf - queries[:, None, :]) ** 2, axis=-1)
        dc = jnp.where(ok, dc, jnp.inf)
        k = jnp.argmin(dc, axis=1)
        d_coh = jnp.take_along_axis(dc, k[:, None], axis=1)[:, 0]
        p_coh = jnp.take_along_axis(cand, k[:, None], axis=1)[:, 0]
        use_coh = ok.any(axis=1) & (d_coh <= d_app * kappa_mult)
        p = jnp.where(use_coh, p_coh, p_app).astype(jnp.int32)
        return p, use_coh

    def row_body(r, state):
        bp, s, n_coh = state
        q0 = r * wb
        queries = _row_queries(db, r, bp, db.rowsafe)
        p_app, d_app = argmin_l2(queries, db.db, db.db_sqnorm)
        p, use_coh = resolve(r, bp, s, queries, p_app, d_app, nrs)
        bp = jax.lax.dynamic_update_slice(bp, db.a_filt_flat[p], (q0,))
        s = jax.lax.dynamic_update_slice(s, p, (q0,))
        for _ in range(passes):
            queries = _row_queries(db, r, bp, ones)
            p_app, d_app = argmin_l2(queries, db.db, db.db_sqnorm)
            p, use_coh = resolve(r, bp, s, queries, p_app, d_app, nf)
            bp = jax.lax.dynamic_update_slice(bp, db.a_filt_flat[p], (q0,))
            s = jax.lax.dynamic_update_slice(s, p, (q0,))
        return bp, s, n_coh + use_coh.sum(dtype=jnp.int32)

    bp0 = jnp.zeros((hb * wb,), _F32)
    s0 = jnp.zeros((hb * wb,), jnp.int32)
    return jax.lax.fori_loop(0, hb, row_body, (bp0, s0, jnp.int32(0)))

if __name__ == "__main__":
    raise SystemExit(main())
