"""Measure the CPU/cKDTree oracle ONCE on the north-star config (1024^2 B',
5-level pyramid, kappa=5) and cache {wall-clock, per-level stats, output
plane} for bench.py — the oracle run takes ~an hour, far too slow to repeat
every bench invocation (BASELINE.md's 'CPU-oracle wall-clock' TBD row).

    JAX_PLATFORMS=cpu python experiments/oracle_1024.py

Writes bench_cache/oracle_1024_seed7.npz + bench_cache/oracle_1024.json.
"""

from __future__ import annotations

import json
import os
import sys
import time

os.environ.setdefault("JAX_PLATFORMS", "cpu")
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

from examples.make_assets import make_structured
from image_analogies_tpu.config import AnalogyParams


def main() -> int:
    from image_analogies_tpu.models.analogy import create_image_analogy

    size, levels, kappa, seed = 1024, 5, 5.0, 7
    a, ap, b = make_structured(size, seed)
    p = AnalogyParams(levels=levels, kappa=kappa, backend="cpu")
    t0 = time.perf_counter()
    res = create_image_analogy(a, ap, b, p)
    wall_s = time.perf_counter() - t0

    out = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "bench_cache")
    os.makedirs(out, exist_ok=True)
    np.savez_compressed(os.path.join(out, f"oracle_1024_seed{seed}.npz"),
                        bp_y=res.bp_y.astype(np.float32),
                        source_map=res.source_map.astype(np.int32))
    from bench import input_digest

    with open(os.path.join(out, "oracle_1024.json"), "w") as f:
        json.dump({
            "config": {"size": size, "levels": levels, "kappa": kappa,
                       "seed": seed, "inputs": "make_assets.make_structured"},
            "input_digest": input_digest(a, ap, b),
            "wall_s": round(wall_s, 1),
            "levels_ms": [round(s["ms"], 1) for s in res.stats],
            "host": "this box (judge's CPU)",
        }, f, indent=1)
    print(f"oracle 1024^2 done: {wall_s:.1f}s")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
