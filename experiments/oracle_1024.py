"""Measure the CPU/cKDTree oracle ONCE per seed on the north-star config
(1024^2 B', 5-level pyramid, kappa=5) and cache {wall-clock, per-level stats,
output plane} for bench.py — the oracle run takes ~half an hour, far too slow
to repeat every bench invocation (BASELINE.md's 'CPU-oracle wall-clock' row).

    python experiments/oracle_1024.py [--seed N]

Writes bench_cache/oracle_1024_seed{N}.npz + oracle_1024_seed{N}.json (and
the historic oracle_1024.json name for the primary seed 7).  bench.py scores
the TPU run against EVERY cached seed it finds, so a second seed turns the
north-star-scale parity claim from n=1 into n>=2 (round-2 VERDICT weak 2).
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

os.environ.setdefault("JAX_PLATFORMS", "cpu")
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax

# the box's sitecustomize force-registers the TPU plugin over JAX_PLATFORMS;
# this oracle is CPU-only and must never grab the chip out from under a bench
jax.config.update("jax_platforms", "cpu")

import numpy as np

from examples.make_assets import make_structured
from image_analogies_tpu.config import AnalogyParams


def main() -> int:
    from image_analogies_tpu.models.analogy import create_image_analogy

    ap_args = argparse.ArgumentParser()
    ap_args.add_argument("--seed", type=int, default=7)
    seed = ap_args.parse_args().seed
    size, levels, kappa = 1024, 5, 5.0
    a, ap, b = make_structured(size, seed)
    p = AnalogyParams(levels=levels, kappa=kappa, backend="cpu")
    t0 = time.perf_counter()
    res = create_image_analogy(a, ap, b, p, keep_levels=True)
    wall_s = time.perf_counter() - t0

    out = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "bench_cache")
    os.makedirs(out, exist_ok=True)
    # every level's planes: the finest pair feeds parity scoring, the full
    # pyramid feeds the tie-audit (utils/parity.py re-scores mismatched
    # picks against each run's exact per-level decision context)
    planes = {"bp_y": res.bp_y.astype(np.float32),
              "source_map": res.source_map.astype(np.int32)}
    for lv, (bp, s) in enumerate(res.levels):
        planes[f"bp_l{lv}"] = bp.astype(np.float32)
        planes[f"s_l{lv}"] = s.astype(np.int32)
    np.savez_compressed(os.path.join(out, f"oracle_1024_seed{seed}.npz"),
                        **planes)
    from bench import input_digest

    digest = input_digest(a, ap, b)
    # wall_s records the BEST observed oracle wall-clock for this exact
    # input across generations: a re-generation on a loaded box (e.g. while
    # test suites hog the CPU) must not inflate the baseline, which would
    # flatter our reported speedup.  wall_s_this_run / levels_ms always
    # describe THIS generation (the one whose planes are cached).
    prev_wall = None
    prev_path = os.path.join(out, f"oracle_1024_seed{seed}.json")
    if os.path.exists(prev_path):
        with open(prev_path) as f:
            prev = json.load(f)
        if prev.get("input_digest") == digest:
            prev_wall = prev.get("wall_s")
    meta = {
        "config": {"size": size, "levels": levels, "kappa": kappa,
                   "seed": seed, "inputs": "make_assets.make_structured"},
        "input_digest": digest,
        "wall_s": round(min(wall_s, prev_wall) if prev_wall else wall_s, 1),
        "wall_s_this_run": round(wall_s, 1),
        "levels_ms": [round(s["ms"], 1) for s in res.stats],
        "host": "this box (judge's CPU)",
        # self-consistency note (round-4 ADVICE item 2): wall_s (the
        # speedup denominator bench.py uses) and levels_ms can come from
        # DIFFERENT runs when a regeneration is slower than a prior run
        "provenance": ("wall_s is the MIN over all generations of this "
                       "exact input (digest-matched); wall_s_this_run and "
                       "levels_ms describe the generation whose planes are "
                       "cached in the .npz"),
    }
    names = [f"oracle_1024_seed{seed}.json"]
    if seed == 7:  # historic name bench.py's primary leg reads
        names.append("oracle_1024.json")
    for name in names:
        with open(os.path.join(out, name), "w") as f:
            json.dump(meta, f, indent=1)
    print(f"oracle 1024^2 done: {wall_s:.1f}s")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
