"""Split the wavefront step's coherence block (trace: ~135 us/step at
north-star plateau — the ONE dominant XLA fusion left after round 4) into
its parts, each timed as a loop-carried on-chip fori_loop at high iteration
count (the ~90 ms tunnel dispatch is ~30 us/step at iters=3000 and is
subtracted via the noop case):

  gather12   the (M, nc=12) row gather from db_live (L+1 cols)
  gather6    same with HALF the rows (is cost really per-row?)
  score      live-split scoring given pre-gathered rows (no gather)
  argmin     the masked argmin + take_along_axis tail
  full       the production _batched_coherence block
  bpsgather  the query build's (M, nc) gather from the (Nb, 2) carry
  rescore    the anchor re-score gather+sum (M rows)
  scatter    the (M,) row scatter into the carry

    python experiments/coherence_parts_probe.py [--iters 3000]
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

import jax
import jax.numpy as jnp

from examples.make_assets import make_structured
from image_analogies_tpu.backends.base import LevelJob
from image_analogies_tpu.backends.tpu import TpuMatcher, _batched_coherence
from image_analogies_tpu.config import AnalogyParams
from image_analogies_tpu.ops import color
from image_analogies_tpu.ops.features import spec_for_level

_F32 = jnp.float32


def bench(run, args_tuple, reps=3):
    run_c = jax.jit(run)
    out = run_c(*args_tuple)
    jax.block_until_ready(out)
    ts = []
    for _ in range(reps):
        t0 = time.perf_counter()
        jax.block_until_ready(run_c(*args_tuple))
        ts.append(time.perf_counter() - t0)
    return min(ts)


def main() -> int:
    pa = argparse.ArgumentParser()
    pa.add_argument("--size", type=int, default=1024)
    pa.add_argument("--iters", type=int, default=3000)
    args = pa.parse_args()

    a, ap, b = make_structured(args.size)
    params = AnalogyParams(levels=1, backend="tpu", strategy="wavefront",
                           match_mode="exact_hi2_2p")
    spec = spec_for_level(params, 0, 1, 1)
    a_src, a_filt, b_src = (color.luminance(a), color.luminance(ap),
                            color.luminance(b))
    a_src, a_filt = color.remap_pair(a_src, a_filt, b_src)
    job = LevelJob(level=0, spec=spec,
                   kappa_mult=params.kappa_factor(0) ** 2,
                   a_src=a_src, a_filt=a_filt, b_src=b_src)
    db = TpuMatcher(params).build_features(job)

    hb, wb, ha, wa = db.hb, db.wb, db.ha, db.wa
    na, nb = ha * wa, hb * wb
    nf = int(db.off.shape[0])
    nc = (nf - 1) // 2
    c = spec.fine_size // 2 + 1
    m = (min(hb, (wb + c - 1) // c) + 7) // 8 * 8
    lw = int(db.live_idx.shape[0])

    rng = np.random.default_rng(0)
    pix = jnp.asarray(
        np.sort(rng.choice(nb, size=m, replace=False)).astype(np.int32))
    bps0 = jnp.asarray(rng.random((nb, 2), dtype=np.float32))
    qlive0 = jnp.asarray(rng.random((m, lw), dtype=np.float32))
    cand0 = jnp.asarray(rng.integers(0, na, (m, nc)).astype(np.int32))
    rows0 = jnp.asarray(rng.random((m, nc, lw + 1), dtype=np.float32))
    p0 = jnp.asarray(rng.integers(0, na, m).astype(np.int32))
    off_i = db.off[:, 0][None, :nc]
    off_j = db.off[:, 1][None, :nc]
    iters = args.iters

    def loop(body):
        def run(*arrs):
            def f(i, carry):
                return body(i, carry, *arrs)
            return jax.lax.fori_loop(0, iters, f, jnp.int32(0))
        return run

    # consume EVERY element (sum) — a [0]-element dep lets XLA slice the
    # whole case down to a 1-row gather (measured: 0.01 us/step "gathers")
    dep = lambda x: jnp.sum(x.astype(_F32)).astype(jnp.int32) % 2

    def noop(i, acc):
        return acc + (i % 2)

    def gather_n(n):
        def body(i, acc, dbl, cand):
            cf = dbl[(cand[:, :n] + acc) % na]
            return acc + dep(cf)
        return body

    def score(i, acc, rows, qlive):
        cf = rows + acc.astype(_F32) * 1e-30
        dc = (jnp.sum((cf[..., :-1] - qlive[:, None, :]) ** 2, axis=-1)
              + cf[..., -1])
        return acc + dep(dc)

    def argmin_tail(i, acc, dc0, cand):
        dc = dc0 + acc.astype(_F32) * 1e-30
        k = jnp.argmin(dc, axis=1)
        d = jnp.take_along_axis(dc, k[:, None], axis=1)[:, 0]
        p = jnp.take_along_axis(cand, k[:, None], axis=1)[:, 0]
        return acc + dep(d) + (p[0] % 2)

    def full(i, acc, dbl, s_r, qlive, queries):
        sr = (s_r + acc) % na
        ci = sr // wa - off_i
        cj = sr % wa - off_j
        ok = (ci >= 0) & (ci < ha) & (cj >= 0) & (cj < wa)
        idx = jnp.zeros((m, nc), jnp.int32)  # placeholder base validity
        p_coh, d_coh, has = _batched_coherence(
            db, None, queries, idx, ok, nc, lambda i_: db.db[i_],
            q_live=qlive, s_r=sr)
        return acc + dep(d_coh)

    def bps_gather(i, acc, bps):
        pixc = (pix + acc) % nb
        qi = pixc // wb
        qj = pixc - qi * wb
        wi = qi[:, None] + off_i
        wj = qj[:, None] + off_j
        idx = (jnp.clip(wi, 0, hb - 1) * wb + jnp.clip(wj, 0, wb - 1))
        g = bps[idx]
        return acc + dep(g)

    def rescore(i, acc, dbl, qlive):
        p = (p0 + acc) % na
        g = dbl[p]
        d = jnp.sum((g[:, :-1] - qlive) ** 2, axis=1) + g[:, -1]
        return acc + dep(d)

    def scatter(i, acc, bps, vals):
        wpix = (pix + acc) % nb
        out = bps.at[wpix].set(vals, mode="drop")
        return acc + dep(out)

    dc0 = jnp.asarray(rng.random((m, nc), dtype=np.float32))
    vals0 = jnp.asarray(rng.random((m, 2), dtype=np.float32))
    queries0 = jnp.asarray(
        rng.random((m, int(db.static_q.shape[1])), dtype=np.float32))

    def scatter_sorted(i, acc, bps, vals):
        # sorted ascending + per-lane OOB sentinels (all distinct) — the
        # production schedule's pix rows ARE ascending with -1 pads at the
        # end, so this formulation is realizable in the real step
        wpix = pix + acc * 0 + jnp.arange(m, dtype=jnp.int32) * 0
        wpix = jnp.where(wpix >= 0, wpix,
                         nb + jnp.arange(m, dtype=jnp.int32))
        out = bps.at[wpix].set(vals, mode="drop", unique_indices=True,
                               indices_are_sorted=True)
        return acc + dep(out)

    def dus_scatter(i, acc, bps_diag, vals):
        # diagonal-layout scatter: the step's M results land CONTIGUOUS
        off = (acc.astype(jnp.int32) % 32) * m
        out = jax.lax.dynamic_update_slice(bps_diag, vals, (off, 0))
        return acc + dep(out)

    def staticq_gather(i, acc, static_q):
        pixc = (pix + acc) % nb
        g = static_q[pixc]
        return acc + dep(g)

    def staticq_slice(i, acc, static_q_diag):
        off = (acc.astype(jnp.int32) % 32) * m
        g = jax.lax.dynamic_slice(static_q_diag, (off, 0),
                                  (m, static_q_diag.shape[1]))
        return acc + dep(g)

    def gather_clustered(i, acc, dbl, s_r):
        # production-shaped candidate gather: 12 rows per query CLUSTERED
        # around a base row (sr +- window shifts), like real coherence
        sr = (s_r[:, :1] + acc) % na
        cand = jnp.clip(sr + jnp.arange(nc)[None, :] * (wa // 256), 0,
                        na - 1)
        cf = dbl[cand]
        return acc + dep(cf)

    cases = {
        "noop": (noop, ()),
        "gather12": (gather_n(nc), (db.db_live, cand0)),
        "gather6": (gather_n(6), (db.db_live, cand0)),
        "gather3": (gather_n(3), (db.db_live, cand0)),
        "gather_clustered": (gather_clustered, (db.db_live, cand0)),
        "score": (score, (rows0, qlive0)),
        "argmin": (argmin_tail, (dc0, cand0)),
        "full": (full, (db.db_live, cand0, qlive0, queries0)),
        "bpsgather": (bps_gather, (bps0,)),
        "rescore": (rescore, (db.db_live, qlive0)),
        "scatter": (scatter, (bps0, vals0)),
        "scatter_sorted": (scatter_sorted, (bps0, vals0)),
        "dus_scatter": (dus_scatter, (jnp.zeros((nb + 64 * m, 2), _F32),
                                      vals0)),
        "staticq_gather": (staticq_gather, (db.static_q,)),
        "staticq_slice": (staticq_slice,
                          (jnp.zeros((nb + 64 * m,
                                      int(db.static_q.shape[1])), _F32),)),
    }
    rec = {"m": m, "na": na, "nc": nc, "iters": iters}
    base = None
    for name, (body, arrs) in cases.items():
        us = bench(loop(body), arrs) / iters * 1e6
        if name == "noop":
            base = us
        rec[name + "_us"] = round(us, 2)
        extra = f"  (-noop: {us - base:.1f})" if base is not None else ""
        print(f"# {name}: {us:.2f} us/step{extra}", file=sys.stderr,
              flush=True)
    print(json.dumps(rec))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
