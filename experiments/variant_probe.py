"""Probe strategy variants for parity (throwaway experiment harness).

V1 "fulldb": replace the rowsafe-masked DB with the FULL db in the level DB,
so approx + coherence score against the oracle's metric (full A/A' rows vs
zero-masked queries) instead of the symmetric masked metric.
"""

from __future__ import annotations

import argparse
import dataclasses
import os
import sys
import time

os.environ.setdefault("JAX_PLATFORMS", "cpu")
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax

try:
    jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])
except RuntimeError:
    pass

import numpy as np

from experiments.parity_probe import make_structured
from examples.make_assets import _oil_filter
from image_analogies_tpu.backends.tpu import TpuMatcher
from image_analogies_tpu.config import AnalogyParams
from image_analogies_tpu.models.analogy import create_image_analogy
from image_analogies_tpu.utils.ssim import ssim


class FullDbMatcher(TpuMatcher):
    def build_features(self, job):
        db = super().build_features(job)
        return dataclasses.replace(
            db, db_rowsafe=db.db, db_rowsafe_sqnorm=db.db_sqnorm)


def main() -> int:
    ap_ = argparse.ArgumentParser()
    ap_.add_argument("--size", type=int, default=128)
    ap_.add_argument("--levels", type=int, default=3)
    ap_.add_argument("--kappa", type=float, default=5.0)
    ap_.add_argument("--seed", type=int, default=7)
    args = ap_.parse_args()

    a, ap, b = make_structured(args.size, args.seed)
    ideal = _oil_filter(b)
    base = dict(levels=args.levels, kappa=args.kappa)

    oracle = create_image_analogy(a, ap, b, AnalogyParams(backend="cpu", **base))
    print(f"oracle ssim_vs_ideal={ssim(oracle.bp_y, ideal):.3f}")

    for strat in ("rowwise", "batched"):
        p = AnalogyParams(backend="tpu", strategy=strat, **base)
        t0 = time.perf_counter()
        res = create_image_analogy(a, ap, b, p, backend=FullDbMatcher(p))
        dt = time.perf_counter() - t0
        print(f"fulldb-{strat:>8}: {dt:.1f}s "
              f"ssim_vs_oracle={ssim(res.bp_y, oracle.bp_y):.3f} "
              f"ssim_vs_ideal={ssim(res.bp_y, ideal):.3f}")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
