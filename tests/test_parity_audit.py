"""Tie-audit (utils/parity.py): the source-map mismatch story must be a
checked theorem, not a narrative (round-2 VERDICT item 4 / round-3 item 2).

Runs the TPU wavefront (XLA-exact on the CPU test platform) against the
CPU/cKDTree oracle on posterized inputs (dense exact ties), audits every
mismatched pick, and asserts NOTHING is unexplained.  A negative control
corrupts one pick and checks the audit actually flags it.
"""

import numpy as np
import pytest

from examples.make_assets import make_structured
from image_analogies_tpu.config import AnalogyParams
from image_analogies_tpu.models.analogy import create_image_analogy
from image_analogies_tpu.utils.parity import audit_source_map_mismatches


@pytest.fixture(scope="module")
def runs():
    # 128^2 seed 5 measured: 3.04% pick mismatch, 99.77% value match — a
    # real tie population for the audit to chew on
    a, ap, b = make_structured(128, seed=5)
    p = AnalogyParams(levels=2, kappa=5.0, backend="tpu",
                      strategy="wavefront")
    x = create_image_analogy(a, ap, b, p, keep_levels=True)
    y = create_image_analogy(a, ap, b, p.replace(backend="cpu"),
                             keep_levels=True)
    return a, ap, b, p, x, y


def test_all_mismatches_explained(runs):
    a, ap, b, p, x, y = runs
    audit = audit_source_map_mismatches(a, ap, b, p, x.levels, y.levels)
    # posterized inputs at 96^2 must produce SOME tie-driven mismatches for
    # the audit to be meaningful; if this ever goes to zero the fixture
    # inputs need more posterization, not a weaker assert
    assert audit["mismatches"] > 0
    assert audit["unexplained"] == 0, audit
    assert audit["mismatch_explained_by_ties"] == 1.0
    assert audit["first_divergence_is_tie"] is True
    # every clean-context mismatch is an exact or fp32-resolution tie
    assert audit["clean_ctx_tie_fraction"] == 1.0


def test_outputs_value_match_despite_tie_mismatches(runs):
    # the companion claim: tie mismatches land on value-equal rows
    _, _, _, _, x, y = runs
    match = float((x.bp_y == y.bp_y).mean())
    assert match >= 0.995, match


def test_audit_flags_real_disparity(runs):
    """Negative control: corrupt one coarsest-level pick with a strictly
    worse row — the audit must report it unexplained (and the first
    divergence is then NOT a tie)."""
    a, ap, b, p, x, y = runs
    lx = [(bp.copy(), s.copy()) for bp, s in x.levels]
    coarsest = len(lx) - 1
    bp_c, s_c = lx[coarsest]
    sy_c = y.levels[coarsest][1]
    # corrupt the first pixel where the runs AGREE (a clean mismatch site)
    q = int(np.nonzero(s_c.reshape(-1) == sy_c.reshape(-1))[0][0])
    s_flat = s_c.reshape(-1)
    s_flat[q] = (s_flat[q] + 7919) % (s_c.size)  # arbitrary distant row
    audit = audit_source_map_mismatches(a, ap, b, p, lx, y.levels)
    assert audit["unexplained"] >= 1
    assert audit["mismatch_explained_by_ties"] < 1.0


def test_audit_level_count_guard(runs):
    a, ap, b, p, x, y = runs
    with pytest.raises(ValueError, match="level count"):
        audit_source_map_mismatches(a, ap, b, p, x.levels[:1], y.levels)


def test_committed_bench_record_backs_auto_default():
    """The auto match-mode default steers 1024^2 levels onto the packed
    2-pass scan; the parity claim behind that default must be verifiable
    AT HEAD (round-3 ADVICE item 2): the newest committed BENCH_r*.json
    must carry a north-star tie-audit with explained ~1.0."""
    import glob
    import json
    import os
    import re
    import subprocess

    here = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    # enumerate AND read the committed bench records via git (round-4
    # ADVICE item 3): both the file list and the CONTENT come from HEAD,
    # so an untracked/stale/locally-edited/deleted working-tree bench
    # file can neither be validated nor crash the test.  Fall back to the
    # working-directory glob only when git can't serve HEAD (exported
    # tarball; note ls-files alone would also return empty when such an
    # export lands inside some enclosing work tree)
    reads = []
    git_ok = False
    try:
        tracked = subprocess.run(
            ["git", "ls-tree", "-r", "--name-only", "HEAD"], cwd=here,
            capture_output=True, text=True, timeout=30, check=True,
        ).stdout.split()
        for p in sorted(tracked):
            if re.fullmatch(r"BENCH_r\d+\.json", p):
                raw = subprocess.run(
                    ["git", "show", f"HEAD:{p}"], cwd=here,
                    capture_output=True, text=True, timeout=30, check=True,
                ).stdout
                reads.append((os.path.join(here, p), raw))
        git_ok = True
    except (OSError, subprocess.SubprocessError):
        reads = []
    if not git_ok:
        # fall back to the working tree only when git itself FAILED
        # (exported tarball, no git binary) — a git that succeeded with
        # zero matches is an authoritative "HEAD has no bench records"
        # and must not be second-guessed by untracked working-tree files
        for path in sorted(glob.glob(os.path.join(here, "BENCH_r*.json"))):
            with open(path) as f:
                reads.append((path, f.read()))
    records = []
    for path, raw in reads:
        data = json.loads(raw)
        # the driver wraps bench.py's JSON line under "parsed"; when that
        # is null (output overflowed), the record survives only in the
        # raw "tail" text — scan the seed-7 span for the audit fields
        parsed = data.get("parsed") or {}
        rec = (parsed.get("configs") or {}).get("north_star_1024_seed7")
        if rec is None:
            span = raw.split('north_star_1024_seed7', 1)
            if len(span) == 2:
                span = span[1].split('north_star_1024_seed', 1)[0]
                rec = {
                    k: float(m.group(1)) for k in
                    ("mismatch_explained_by_ties", "ssim_vs_oracle")
                    if (m := re.search(
                        rf'\\?"{k}\\?": ([0-9.]+)', span))
                }
        records.append((path, rec))
    assert records, "no committed BENCH_r*.json file found"
    # the NEWEST bench file must itself carry the audit — NO fallback to
    # an older round's evidence, whatever the failure mode (missing run,
    # truncated tail, audit-less record): stale evidence at HEAD is
    # exactly the regression this test exists to catch (round-3 ADVICE)
    path, rec = records[-1]
    assert rec and "mismatch_explained_by_ties" in rec, (
        f"{path}: newest bench file carries no north-star tie-audit")
    assert rec["mismatch_explained_by_ties"] >= 0.9999, (path, rec)
    assert rec["ssim_vs_oracle"] >= 0.99, (path, rec)
