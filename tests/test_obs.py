"""Observability layer (obs/): registry thread-safety, span nesting +
run_id propagation, `ia report` golden output on solo and sharded fixture
logs, and the disabled path's zero-record / zero-allocation guarantee."""

import json
import os
import threading
import tracemalloc

import pytest

from image_analogies_tpu.config import AnalogyParams
from image_analogies_tpu.models.analogy import create_image_analogy
from image_analogies_tpu.obs import metrics as obs_metrics
from image_analogies_tpu.obs import report as obs_report
from image_analogies_tpu.obs import trace as obs_trace

from tests.conftest import make_pair


# ---------------------------------------------------------------- registry

def test_registry_counters_under_threads():
    reg = obs_metrics.MetricsRegistry()

    def work():
        for _ in range(1000):
            reg.inc("hits")
            reg.inc("bytes", 64)
            reg.observe("ms", 2.5)

    threads = [threading.Thread(target=work) for _ in range(8)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    snap = reg.snapshot()
    assert snap["counters"]["hits"] == 8000
    assert snap["counters"]["bytes"] == 8000 * 64
    h = snap["histograms"]["ms"]
    assert h["count"] == 8000
    assert h["min"] == h["max"] == 2.5
    assert h["sum"] == pytest.approx(8000 * 2.5)


def test_module_helpers_inert_without_run():
    assert obs_metrics.registry() is None
    obs_metrics.inc("nope")
    obs_metrics.observe("nope", 1.0)
    assert obs_metrics.registry() is None
    assert obs_metrics.snapshot() == {"counters": {}, "gauges": {},
                                      "histograms": {}}


def test_module_helpers_route_to_active_run():
    p = AnalogyParams(metrics=True)
    with obs_trace.run_scope(p) as ctx:
        obs_metrics.inc("x", 2)
        obs_metrics.inc("x", 3)
        assert obs_metrics.registry() is ctx.registry
        assert ctx.registry.counter("x") == 5
    assert obs_metrics.registry() is None


# ------------------------------------------------------------------ spans

def test_span_nesting_and_run_id_on_every_record(tmp_path):
    log = str(tmp_path / "run.jsonl")
    p = AnalogyParams(metrics=True, log_path=log)
    with obs_trace.run_scope(p) as ctx:
        rid = ctx.run_id
        with obs_trace.span("phase", phase="phase1"):
            with obs_trace.span("level", level=2):
                pass
            with obs_trace.span("level", level=1):
                pass
    recs = [json.loads(l) for l in open(log)]
    # manifest + 3 spans + run_end
    assert [r.get("event") for r in recs] == [
        "run_manifest", "span", "span", "span", "run_end"]
    assert all(r["run_id"] == rid for r in recs)
    assert [r["seq"] for r in recs] == list(range(5))
    inner = [r for r in recs if r.get("name") == "level"]
    assert [r["level"] for r in inner] == [2, 1]
    assert all(r["depth"] == 1 and r["parent"] == "phase" for r in inner)
    outer = next(r for r in recs if r.get("name") == "phase")
    assert outer["depth"] == 0 and "parent" not in outer
    assert outer["wall_ms"] >= max(r["wall_ms"] for r in inner)


def test_run_scope_reentrant_single_run_id(tmp_path):
    log = str(tmp_path / "run.jsonl")
    p = AnalogyParams(metrics=True, log_path=log)
    with obs_trace.run_scope(p) as outer:
        with obs_trace.run_scope(p) as inner:  # video frame joins the clip
            assert inner is outer
            assert obs_trace.current_run_id() == outer.run_id
    recs = [json.loads(l) for l in open(log)]
    assert sum(r.get("event") == "run_manifest" for r in recs) == 1
    assert sum(r.get("event") == "run_end" for r in recs) == 1


def test_run_join_warns_on_cross_thread_entry(tmp_path):
    """_CURRENT is a module global: a second THREAD entering run_scope
    joins the first thread's run — the join must emit one run_join
    warning record carrying both thread ids."""
    log = str(tmp_path / "run.jsonl")
    p = AnalogyParams(metrics=True, log_path=log)
    seen = {}

    def worker():
        with obs_trace.run_scope(p) as ctx:
            seen["ctx"] = ctx
            seen["tid"] = threading.get_ident()

    with obs_trace.run_scope(p) as outer:
        t = threading.Thread(target=worker)
        t.start()
        t.join()
        # re-entry from the SAME thread must not warn
        with obs_trace.run_scope(p):
            pass
    assert seen["ctx"] is outer  # joined, not a second run
    recs = [json.loads(line) for line in open(log)]
    joins = [r for r in recs if r.get("event") == "run_join"]
    assert len(joins) == 1
    assert joins[0]["severity"] == "warning"
    assert joins[0]["owner_thread"] == outer.owner_thread
    assert joins[0]["joined_thread"] == seen["tid"]
    assert joins[0]["joined_thread"] != joins[0]["owner_thread"]
    assert joins[0]["run_id"] == outer.run_id


def test_emit_caches_append_handle_during_run(tmp_path, monkeypatch):
    """Inside a run ONE append handle serves every record of a path
    (flushed+closed with the run); outside a run the historic
    open-per-record behavior is preserved."""
    from image_analogies_tpu.utils import logging as ialog

    log = str(tmp_path / "run.jsonl")
    opens = []
    real_open = open

    def counting_open(path, *a, **kw):
        opens.append(path)
        return real_open(path, *a, **kw)

    monkeypatch.setattr(ialog, "open", counting_open, raising=False)
    p = AnalogyParams(metrics=True, log_path=log)
    with obs_trace.run_scope(p):
        for i in range(5):
            ialog.emit({"i": i}, log)
    assert opens.count(log) == 1  # manifest+5+run_end on one handle
    n_in_run = len(open(log).readlines())
    assert n_in_run == 7  # flushed at run end

    opens.clear()
    ialog.emit({"after": 1}, log)
    ialog.emit({"after": 2}, log)
    assert opens.count(log) == 2  # per-record open again outside a run
    assert len(open(log).readlines()) == n_in_run + 2


def test_engine_log_records_all_stamped(tmp_path):
    log = str(tmp_path / "run.jsonl")
    a, ap, b = make_pair(20, 22, seed=3)
    params = AnalogyParams(levels=2, backend="cpu", metrics=True,
                           log_path=log)
    create_image_analogy(a, ap, b, params)
    recs = [json.loads(l) for l in open(log)]
    assert recs[0]["event"] == "run_manifest"
    assert recs[-1]["event"] == "run_end"
    rids = {r.get("run_id") for r in recs}
    assert len(rids) == 1 and None not in rids
    assert [r["seq"] for r in recs] == list(range(len(recs)))
    # one stat + one span per level
    assert sum(1 for r in recs if r.get("name") == "level") == 2
    assert sum(1 for r in recs
               if "level" in r and "event" not in r) == 2
    # kappa totals landed in the registry snapshot
    counters = recs[-1]["metrics"]["counters"]
    assert counters["kappa.total_px"] > 0


# -------------------------------------------------------------- ia report

def _write_solo_fixture(path):
    recs = [
        {"event": "run_manifest", "config_hash": "abc123def456",
         "backend": "tpu", "strategy": "wavefront", "mesh": [1, 1],
         "levels": 2, "metrics": True, "git_rev": "deadbee",
         "run_id": "solo1", "seq": 0, "ts": 1.0},
        {"level": 1, "db_rows": 100, "pixels": 144, "ms": 10.0,
         "total_ms": 12.0, "coherence_ratio": 0.5, "backend": "tpu",
         "strategy": "wavefront", "run_id": "solo1", "seq": 1, "ts": 1.1},
        {"event": "span", "name": "level", "level": 1, "wall_ms": 12.5,
         "depth": 0, "run_id": "solo1", "seq": 2, "ts": 1.2},
        {"level": 0, "db_rows": 400, "pixels": 576, "ms": 40.0,
         "total_ms": 45.0, "coherence_ratio": 0.75, "backend": "tpu",
         "strategy": "wavefront", "run_id": "solo1", "seq": 3, "ts": 1.3},
        {"event": "span", "name": "level", "level": 0, "wall_ms": 46.0,
         "depth": 0, "run_id": "solo1", "seq": 4, "ts": 1.4},
        {"event": "span", "name": "fetch", "wall_ms": 3.0, "depth": 0,
         "run_id": "solo1", "seq": 5, "ts": 1.5},
        {"event": "run_end", "metrics": {"counters": {
            "devcache.hits": 3, "devcache.misses": 1,
            "devcache.upload_bytes": 4096, "fetch.bytes": 2048,
            "kappa.coherence_px": 504.0, "kappa.total_px": 720},
            "gauges": {}, "histograms": {}},
         "run_id": "solo1", "seq": 6, "ts": 1.6},
    ]
    with open(path, "w") as f:
        for r in recs:
            f.write(json.dumps(r, sort_keys=True) + "\n")


SOLO_GOLDEN = """\
run solo1 — 7 records
  manifest:
    config_hash   abc123def456
    backend       tpu
    strategy      wavefront
    mesh          [1, 1]
    levels        2
    git_rev       deadbee
    metrics       True
  per-level timing (ms):
    phase    lvl frames       wall     device       host     pixels   coh%
    -          1      1       12.5       10.0        2.5        144   50.0
    -          0      1       46.0       40.0        6.0        576   75.0
    total                     58.5       50.0        8.5
  counters:
    devcache      3 hits / 1 misses (hit rate 75.0%), uploaded 4.0 KiB
    retries       0
    kappa picks   70.0% coherence / 30.0% approx
    fetched       2.0 KiB
  spans:
    fetch                n=1    total       3.0 ms"""


def test_report_golden_solo(tmp_path):
    log = str(tmp_path / "solo.jsonl")
    _write_solo_fixture(log)
    assert obs_report.report(log) == SOLO_GOLDEN


def _write_mesh_fixture(path):
    recs = [
        {"event": "run_manifest", "config_hash": "fedcba987654",
         "backend": "tpu", "strategy": "wavefront", "mesh": [2, 2],
         "levels": 2, "metrics": True, "run_id": "mesh1", "seq": 0,
         "ts": 2.0},
    ]
    seq = 1
    for lv in (1, 0):
        for fr in (0, 1):
            # the sharded phase's streamed per-frame record: NO timing
            # fields, coherence deferred to the phase-end summary
            recs.append({"level": lv, "frame": fr, "phase": "phase1",
                         "db_rows": 100, "pixels": 256, "backend": "tpu",
                         "strategy": "wavefront",
                         "mesh": {"data": 2, "db": 2}, "run_id": "mesh1",
                         "seq": seq, "ts": 2.0 + seq})
            seq += 1
        recs.append({"event": "span", "name": "level", "level": lv,
                     "phase": "phase1", "wall_ms": 20.0 + lv, "depth": 1,
                     "parent": "phase", "run_id": "mesh1", "seq": seq,
                     "ts": 2.0 + seq})
        seq += 1
    recs.append({"event": "coherence_ratios", "phase": "phase1",
                 "ratios": {"l1_f0": 0.5, "l1_f1": 0.5, "l0_f0": 0.75,
                            "l0_f1": 0.25},
                 "run_id": "mesh1", "seq": seq, "ts": 2.0 + seq})
    seq += 1
    recs.append({"event": "span", "name": "fetch", "phase": "phase1",
                 "wall_ms": 5.0, "depth": 1, "parent": "phase",
                 "run_id": "mesh1", "seq": seq, "ts": 2.0 + seq})
    seq += 1
    recs.append({"event": "span", "name": "phase", "phase": "phase1",
                 "wall_ms": 60.0, "depth": 0, "run_id": "mesh1",
                 "seq": seq, "ts": 2.0 + seq})
    seq += 1
    recs.append({"event": "run_end", "metrics": {"counters": {
        "devcache.hits": 10, "devcache.misses": 4,
        "devcache.upload_bytes": 1 << 20, "mesh.level_steps": 2,
        "mesh.psum_gather_bytes": 3 << 20, "fetch.bytes": 8192,
        "kappa.coherence_px": 512.0, "kappa.total_px": 1024},
        "gauges": {}, "histograms": {}}, "run_id": "mesh1", "seq": seq,
        "ts": 2.0 + seq})
    with open(path, "w") as f:
        for r in recs:
            f.write(json.dumps(r, sort_keys=True) + "\n")


MESH_GOLDEN = """\
run mesh1 — 11 records
  manifest:
    config_hash   fedcba987654
    backend       tpu
    strategy      wavefront
    mesh          [2, 2]
    levels        2
    metrics       True
  per-level timing (ms):
    phase    lvl frames       wall     device       host     pixels   coh%
    phase1     1      2       21.0        0.0       21.0        512   50.0
    phase1     0      2       20.0        0.0       20.0        512   50.0
    total                     41.0        0.0       41.0
  counters:
    devcache      10 hits / 4 misses (hit rate 71.4%), uploaded 1.0 MiB
    retries       0
    kappa picks   50.0% coherence / 50.0% approx
    mesh steps    2, psum-gather ~3.0 MiB
    fetched       8.0 KiB
  spans:
    phase                n=1    total      60.0 ms
    fetch                n=1    total       5.0 ms"""


def test_report_golden_sharded(tmp_path):
    log = str(tmp_path / "mesh.jsonl")
    _write_mesh_fixture(log)
    assert obs_report.report(log) == MESH_GOLDEN


def test_report_cli_subcommand(tmp_path, capsys):
    from image_analogies_tpu.cli import main

    log = str(tmp_path / "solo.jsonl")
    _write_solo_fixture(log)
    assert main(["report", log]) == 0
    out = capsys.readouterr().out
    assert "run solo1" in out
    assert "per-level timing" in out
    assert main(["report", str(tmp_path / "missing.jsonl")]) == 2


def test_report_json_cli(tmp_path, capsys):
    from image_analogies_tpu.cli import main

    log = str(tmp_path / "solo.jsonl")
    _write_solo_fixture(log)
    assert main(["report", log, "--json"]) == 0
    out = json.loads(capsys.readouterr().out)
    assert out["path"] == log
    (run,) = out["runs"]
    assert run["run_id"] == "solo1"
    assert run["manifest"]["backend"] == "tpu"
    assert [r["level"] for r in run["levels"]] == [1, 0]
    assert run["counters"]["devcache.hits"] == 3
    # no compile events / counters in the fixture -> sections are null,
    # present as keys so CI diffs see the schema either way
    assert run["compile"] is None and run["hbm"] is None


def test_report_json_compile_and_hbm_sections(tmp_path):
    log = str(tmp_path / "dev.jsonl")
    recs = [
        {"event": "run_manifest", "backend": "tpu", "run_id": "d1",
         "seq": 0, "ts": 1.0},
        {"event": "compile", "name": "tpu.run_wavefront", "ms": 120.0,
         "flops": 2e9, "bytes": 1e8, "ok": True, "level": 0,
         "run_id": "d1", "seq": 1, "ts": 1.2},
        {"level": 0, "db_rows": 10, "pixels": 4, "ms": 10.0,
         "run_id": "d1", "seq": 2, "ts": 1.3},
        {"event": "hbm", "peaks": {"d0": 1 << 30}, "level": 0,
         "run_id": "d1", "seq": 3, "ts": 1.4},
        {"event": "run_end", "metrics": {
            "counters": {"compile.count": 1, "compile.cache_hits": 2,
                         "compile.ms": 120.0, "xla.flops": 6e9,
                         "xla.bytes": 3e8},
            "gauges": {"hbm.peak_bytes.d0": float(1 << 30)},
            "histograms": {}}, "run_id": "d1", "seq": 4, "ts": 1.5},
    ]
    with open(log, "w") as f:
        for r in recs:
            f.write(json.dumps(r, sort_keys=True) + "\n")
    an = obs_report.analyze(obs_report.load_records(log))
    assert an["compile"]["count"] == 1
    assert an["compile"]["cache_hits"] == 2
    assert an["compile"]["flops"] == 6e9
    assert an["compile"]["level_flops"] == {0: 2e9}
    assert an["hbm"] == {"d0": float(1 << 30)}
    text = obs_report.render(an, "d1")
    assert "compile:" in text
    assert "1 compiled / 2 cache hits, total 120.0 ms" in text
    # 2e9 flops over 10 ms device -> 0.2 TFLOP/s
    assert "L0 achieved   ~0.2 TFLOP/s" in text
    assert "hbm peak:" in text and "1.0 GiB" in text
    # the device counters must NOT leak into the generic counter dump
    assert "xla.flops" not in text


def test_report_tolerates_truncated_tail(tmp_path):
    log = str(tmp_path / "cut.jsonl")
    _write_solo_fixture(log)
    with open(log, "a") as f:
        f.write('{"event": "span", "name": "lev')  # preempted mid-write
    assert obs_report.report(log) == SOLO_GOLDEN


# ---------------------------------------------------------- disabled path

def test_disabled_path_no_records_no_allocations(tmp_path):
    a, ap, b = make_pair(20, 22, seed=3)
    params = AnalogyParams(levels=2, backend="cpu")  # metrics off, no log

    emitted = []
    from image_analogies_tpu.utils import logging as ialog
    orig_stamper = ialog._STAMPER

    def spy(record):
        emitted.append(dict(record))
        if orig_stamper is not None:
            orig_stamper(record)

    ialog.set_record_stamper(spy)
    try:
        create_image_analogy(a, ap, b, params)  # warm caches
        assert obs_trace.current_run_id() is None
        # the stamper sees emit() calls even with no log file — with
        # observability off, zero obs records (spans/manifest/run_end)
        # may pass through it
        assert not any(r.get("event") in ("span", "run_manifest",
                                          "run_end") for r in emitted)
        assert not any("run_id" in r for r in emitted)
    finally:
        ialog.set_record_stamper(orig_stamper)

    # the disabled span is the no-op SINGLETON: nothing retained
    sp = obs_trace.span("level", level=0)
    assert sp is obs_trace.span("fetch")
    assert sp is obs_trace._NOOP

    # no net allocations attributable to the obs layer across a full run
    tracemalloc.start()
    try:
        create_image_analogy(a, ap, b, params)
        snap = tracemalloc.take_snapshot()
    finally:
        tracemalloc.stop()
    obs_allocs = [t for t in snap.traces
                  if any("image_analogies_tpu/obs/" in fr.filename
                         for fr in t.traceback)]
    assert obs_allocs == []
    assert obs_metrics.registry() is None


# ------------------------------------------------- shared VMEM tile cap

def test_packed_tile_cap_shrinks_with_wide_b():
    from image_analogies_tpu.tune import resolve as tune
    from image_analogies_tpu.tune.geometry import DEFAULT_PACKED_TILE_CAP

    # north-star geometry (1024^2, 5x5 patches): plateau M ~ 344 keeps
    # the full round-5 tile raise
    assert tune.packed_tile_cap(1024, 1024, 25) == DEFAULT_PACKED_TILE_CAP
    # a ~4096-wide B plateaus at M ~ 1365: the cap must shrink below the
    # fixed 16384 rows or the (M, tile) f32 block blows the VMEM budget
    wide = tune.packed_tile_cap(4096, 4096, 25)
    assert wide < DEFAULT_PACKED_TILE_CAP
    assert wide >= 256 and (wide & (wide - 1)) == 0  # power of two


# --------------------------------------------- scoped observability (PR 11)

def test_scope_isolation_under_concurrency():
    """Two workers writing the SAME counter name through the ambient
    one-liner API land in their OWN registries only; the federated merge
    sums them; writes chain to a shared parent scope."""
    from image_analogies_tpu.obs import fleet as obs_fleet

    parent = obs_metrics.ObsScope(scope_id="fleet")
    s0 = obs_metrics.ObsScope(scope_id="w0.g0", parent=parent)
    s1 = obs_metrics.ObsScope(scope_id="w1.g0", parent=parent)
    barrier = threading.Barrier(2)

    def work(scope, n):
        with obs_metrics.scope_active(scope):
            barrier.wait()
            for _ in range(n):
                obs_metrics.inc("serve.admitted")
                obs_metrics.observe("serve.latency_ms", float(n))
            obs_metrics.set_gauge("hbm.peak_bytes.d0", n)

    threads = [threading.Thread(target=work, args=(s0, 100)),
               threading.Thread(target=work, args=(s1, 300))]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    # isolation: each scope saw only its own writes
    assert s0.registry.counter("serve.admitted") == 100
    assert s1.registry.counter("serve.admitted") == 300
    # chaining: the parent saw the union (reads never chain; writes do)
    assert parent.registry.counter("serve.admitted") == 400
    # the test thread itself never had a scope active
    assert obs_metrics.current_scope() is None
    assert obs_metrics.registry() is None
    # federation: merged view sums counters/histograms, maxes peak gauges
    merged = obs_fleet.merge_snapshots({"w0": s0.registry.snapshot(),
                                        "w1": s1.registry.snapshot()})
    assert merged["counters"]["serve.admitted"] == 400
    assert merged["histograms"]["serve.latency_ms"]["count"] == 400
    assert merged["gauges"]["hbm.peak_bytes.d0"] == 300  # max, not 400


def test_scope_active_nests_and_restores_per_thread():
    a = obs_metrics.ObsScope(scope_id="a")
    b = obs_metrics.ObsScope(scope_id="b")
    with obs_metrics.scope_active(a):
        assert obs_metrics.current_scope() is a
        with obs_metrics.scope_active(b):
            assert obs_metrics.current_scope() is b
            obs_metrics.inc("x")
        assert obs_metrics.current_scope() is a
        obs_metrics.inc("x")
    assert obs_metrics.current_scope() is None
    assert a.registry.counter("x") == 1
    assert b.registry.counter("x") == 1
    # scope_active(None) is a transparent no-op
    with obs_metrics.scope_active(None):
        assert obs_metrics.current_scope() is None


def test_disabled_path_zero_alloc_holds_per_scope():
    """The zero-alloc contract of the disabled path survives scope
    churn: after scopes push/pop, helpers allocate nothing."""
    s = obs_metrics.ObsScope(scope_id="churn")
    with obs_metrics.scope_active(s):
        obs_metrics.inc("warm")
    # pre-warm PAST CPython 3.10's lazy opcode-cache threshold (~1k
    # executions per code object): the one-time co_opcache malloc is
    # attributed to the executing line in obs/metrics.py and would
    # read as a fake steady-state allocation
    for _ in range(3000):
        obs_metrics.inc("nope")
        obs_metrics.registry()
    tracemalloc.start()
    try:
        for _ in range(1000):
            obs_metrics.inc("nope")
            obs_metrics.registry()
        snap = tracemalloc.take_snapshot()
    finally:
        tracemalloc.stop()
    obs_allocs = [t for t in snap.traces
                  if any("image_analogies_tpu/obs/" in fr.filename
                         for fr in t.traceback)]
    assert obs_allocs == []


# --------------------------------------------------- flight recorder (PR 11)

def test_flight_recorder_ring_eviction_and_snapshot():
    from image_analogies_tpu.obs import recorder as obs_recorder

    r = obs_recorder.FlightRecorder(capacity=4)
    for i in range(10):
        r.record({"ts": float(i), "event": f"e{i}"})
    assert len(r) == 4
    records, dropped = r.snapshot()
    assert dropped == 6
    assert [rec["event"] for rec in records] == ["e6", "e7", "e8", "e9"]
    # snapshot copies: mutating a copy must not touch the ring
    records[0]["event"] = "mutated"
    assert r.snapshot()[0][0]["event"] == "e6"


def test_blackbox_dump_seal_roundtrip_and_corruption(tmp_path):
    from image_analogies_tpu.obs import recorder as obs_recorder

    r = obs_recorder.FlightRecorder(capacity=8)
    for i in range(3):
        r.record({"ts": 100.0 + i, "event": f"e{i}", "k": i})
    path = obs_recorder.dump(r, str(tmp_path), "watchdog_timeout",
                             scope_id="w0.g2", extra={"timeout_s": 5.0})
    assert obs_recorder.list_dumps(str(tmp_path)) == [path]
    doc = obs_recorder.load_dump(path)
    assert doc["reason"] == "watchdog_timeout"
    assert doc["scope"] == "w0.g2"
    assert doc["extra"] == {"timeout_s": 5.0}
    assert [rec["event"] for rec in doc["records"]] == ["e0", "e1", "e2"]
    text = obs_recorder.render_dump(doc)
    assert "reason=watchdog_timeout" in text and "scope=w0.g2" in text
    assert "+0.000s e2" in text  # timestamps relative to the last record
    assert "-2.000s e0" in text
    # a flipped byte must fail the seal, not render a wrong flight log
    blob = open(path).read().replace('"e1"', '"eX"')
    with open(path, "w") as f:
        f.write(blob)
    with pytest.raises(ValueError, match="seal"):
        obs_recorder.load_dump(path)


def test_dump_current_scope_resolution(tmp_path):
    """dump_current is a no-op without a scope or dump_dir, writes a
    sealed dump when both exist, and bumps the blackbox counters."""
    from image_analogies_tpu.obs import recorder as obs_recorder

    assert obs_recorder.dump_current("process_death") is None
    scope = obs_metrics.ObsScope(scope_id="w3.g0")
    p = AnalogyParams(metrics=True)
    with obs_trace.run_scope(p), obs_metrics.scope_active(scope):
        # records stamped while the worker scope is ambient land in ITS
        # flight ring (the _stamp -> recorder feed)
        obs_trace.emit_record({"event": "before_death", "k": 1})
        # no dump_dir assigned yet -> still a no-op
        assert obs_recorder.dump_current("process_death") is None
        scope.dump_dir = str(tmp_path)
        path = obs_recorder.dump_current("process_death",
                                        extra={"batch_size": 2})
    assert path is not None and os.path.exists(path)
    doc = obs_recorder.load_dump(path)
    assert doc["extra"] == {"batch_size": 2}
    assert any(r.get("event") == "before_death" for r in doc["records"])
    assert scope.registry.counter("obs.blackbox.dumps") == 1
    assert scope.registry.counter(
        "obs.blackbox.dumps.process_death") == 1
