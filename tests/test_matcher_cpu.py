"""CPU oracle matcher: kappa truth table, coherence candidates at borders,
approximate match vs brute force (SURVEY.md §4.2)."""

import numpy as np
import pytest

from image_analogies_tpu.backends.base import LevelJob
from image_analogies_tpu.backends.cpu import CpuMatcher
from image_analogies_tpu.config import AnalogyParams
from image_analogies_tpu.models.analogy import create_image_analogy
from image_analogies_tpu.ops.features import spec_for_level
from tests.conftest import make_pair


def _job(a, ap, b, params, level=0, levels=1):
    spec = spec_for_level(params, level, levels, 1)
    return LevelJob(level=level, spec=spec,
                    kappa_mult=params.kappa_factor(level) ** 2,
                    a_src=a, a_filt=ap, b_src=b)


def test_kappa_factor_truth_table():
    p = AnalogyParams(levels=3, kappa=4.0)
    # finest level: 1 + 2^0 * k ; coarser: halved exponent weight
    assert p.kappa_factor(0) == 5.0
    assert p.kappa_factor(1) == 3.0
    assert p.kappa_factor(2) == 2.0
    assert AnalogyParams(kappa=0.0).kappa_factor(0) == 1.0


def test_kappa_decision_rule(rng):
    """Coherence candidate wins iff d_coh <= d_app * mult."""
    a, ap, b = make_pair(12, 12)
    for kappa, expect_more_coherence in [(0.0, False), (25.0, True)]:
        p = AnalogyParams(levels=1, kappa=kappa, backend="cpu")
        res = create_image_analogy(a, ap, b, p)
        ratio = res.stats[0]["coherence_ratio"]
        if expect_more_coherence:
            assert ratio > 0.5, ratio
        else:
            # kappa=0: coherence only when it's at least as close as approx
            assert ratio <= 0.5, ratio


def test_first_pixel_has_no_coherence_candidate(rng):
    a, ap, b = make_pair(10, 10)
    p = AnalogyParams(levels=1, backend="cpu")
    m = CpuMatcher(p)
    job = _job(a, ap, b, p)
    db = m.build_features(job)
    n = b.size
    bp = np.zeros(n, np.float32)
    s = np.zeros(n, np.int32)
    qv = m.query_vector(db, job, 0, bp)
    p_coh, d_coh = m.best_coherence_match(db, job, 0, qv, s)
    assert p_coh == -1 and d_coh == np.inf


def test_coherence_candidates_follow_source_map(rng):
    """If s is a pure translation, the coherence candidate continues it."""
    a, ap, b = make_pair(10, 10)
    p = AnalogyParams(levels=1, backend="cpu", gaussian_weights=False)
    m = CpuMatcher(p)
    job = _job(a, ap, b, p)
    db = m.build_features(job)
    wa = 10
    # source map: s(r) = r (identity translation)
    s = np.arange(100, dtype=np.int32)
    bp = db.a_filt_flat.copy()
    q = 5 * wa + 5
    qv = m.query_vector(db, job, q, bp)
    p_coh, _ = m.best_coherence_match(db, job, q, qv, s)
    # all candidates s(r) - offset = r - offset = q, so candidate must be q
    assert p_coh == q


def test_coherence_border_candidates_rejected():
    """Candidates falling outside A are dropped (SURVEY.md §4.2 borders)."""
    a, ap, b = make_pair(8, 8)
    p = AnalogyParams(levels=1, backend="cpu")
    m = CpuMatcher(p)
    job = _job(a, ap, b, p)
    db = m.build_features(job)
    # s maps everything to pixel 0 -> candidates 0 - offset are out of bounds
    # for offsets with positive dj or di
    s = np.zeros(64, np.int32)
    bp = np.zeros(64, np.float32)
    q = 4 * 8 + 4
    qv = m.query_vector(db, job, q, bp)
    p_coh, d = m.best_coherence_match(db, job, q, qv, s)
    # offsets (-1,-1),(0,-1) etc. give s - off inside; only those survive
    assert p_coh >= 0
    ha, wa = 8, 8
    ci, cj = p_coh // wa, p_coh % wa
    assert 0 <= ci < ha and 0 <= cj < wa


def test_approximate_match_tree_vs_brute(rng):
    a, ap, b = make_pair(10, 11, seed=3)
    p_ann = AnalogyParams(levels=1, backend="cpu", use_ann=True)
    p_bf = AnalogyParams(levels=1, backend="cpu", use_ann=False)
    m_ann, m_bf = CpuMatcher(p_ann), CpuMatcher(p_bf)
    job = _job(a, ap, b, p_ann)
    db_ann = m_ann.build_features(job)
    db_bf = m_bf.build_features(job)
    for q in [0, 17, 53, 109]:
        qv = m_ann.query_vector(db_ann, job, q, np.zeros(110, np.float32))
        ia, da = m_ann.best_approximate_match(db_ann, qv)
        ib, dbd = m_bf.best_approximate_match(db_bf, qv)
        assert abs(da - dbd) < 1e-4
        # indices may differ only on exact ties
        if ia != ib:
            assert abs(da - dbd) < 1e-6


def test_best_match_writes_source_pixels(rng):
    """B' values must come verbatim from A' (the copy step, Hertzmann §3)."""
    a, ap, b = make_pair(12, 12)
    res = create_image_analogy(a, ap, b, AnalogyParams(levels=2, backend="cpu"))
    vals = set(np.round(np.asarray(ap), 6).reshape(-1).tolist())
    # every synthesized luminance value exists in (remapped) A'... use
    # source_map instead: bp_y[q] == a_filt[s(q)] by construction at finest.
    s = res.source_map.reshape(-1)
    assert s.min() >= 0 and s.max() < a.size
