"""serve/journal.py — write-ahead request journal + durability plane.

Tier-1 invariants locked here:

- the journal replays: admit/transition histories fold back into
  per-key states, in original admit order;
- damage never poisons replay: a torn tail or a flipped byte costs the
  damaged suffix only — the valid prefix survives, the damaged file is
  quarantined as ``.corrupt`` (same contract as checkpoint quarantine,
  same assertions as tests/test_aux.py's);
- exactly-once: a finished key dedupes with the recorded response; a
  corrupt response spill degrades the key to not-done (deterministic
  re-run, same bytes) instead of serving garbage;
- poison containment: a key that exhausted its crash budget is
  persisted poisoned and future submissions shed with
  ``Rejected("poison")`` before the breaker can see them;
- disabled (the default) costs nothing: the request path never touches
  the journal module;
- serve/journal.py never imports jax (grep lock — durability is pure
  host-side control flow).
"""

import json
import os
import re

import numpy as np
import pytest

from image_analogies_tpu.chaos import drills, inject
from image_analogies_tpu.chaos.plan import ChaosPlan, SiteRule
from image_analogies_tpu.serve import journal as sj
from image_analogies_tpu.serve.types import Rejected, Response


@pytest.fixture(autouse=True)
def _disarm_fault_injector():
    yield
    inject.disarm()


def _planes(seed=0, size=(6, 6)):
    rng = np.random.RandomState(seed)
    h, w = size
    return (rng.rand(h, w).astype(np.float32),
            rng.rand(h, w).astype(np.float32),
            rng.rand(h, w).astype(np.float32))


def _resp(rid, bp, bp_y=None):
    return Response(request_id=rid, bp=bp,
                    bp_y=bp_y if bp_y is not None else bp,
                    stats={"levels": 1}, batch_size=1, queue_ms=0.0,
                    dispatch_ms=0.0, total_ms=0.0)


def _journal(tmp_path, name="j"):
    return sj.RequestJournal(str(tmp_path / name), fsync=False)


def _admit(jr, idem, rid=1, seed=0):
    a, ap, b = _planes(seed)
    jr.record_admit(idem, rid, a, ap, b, drills.image_params(levels=1),
                    None, "key")
    return a, ap, b


# ------------------------------------------------------- core replay


def test_idem_key_is_deterministic_and_content_sensitive():
    _, _, b = _planes(0)
    assert sj.idem_key("k", b) == sj.idem_key("k", b.copy())
    assert sj.idem_key("k", b) != sj.idem_key("other", b)
    b2 = b.copy()
    b2[0, 0] += 1.0
    assert sj.idem_key("k", b) != sj.idem_key("k", b2)


def test_roundtrip_replay_folds_states_in_admit_order(tmp_path):
    jr = _journal(tmp_path)
    jr.open()
    a, ap, b = _admit(jr, "aa", rid=1, seed=1)
    jr.record_dispatched("aa")
    jr.record_done("aa", _resp(1, b))
    _admit(jr, "bb", rid=2, seed=2)
    jr.record_dispatched("bb")
    _admit(jr, "cc", rid=3, seed=3)
    jr.record_poisoned("cc")
    jr.close()

    # a FRESH journal object (a restarted process) replays the history
    jr2 = _journal(tmp_path)
    rep = jr2.replay()
    assert rep.order == ["aa", "bb", "cc"]
    assert rep.quarantined == 0
    assert rep.entries["aa"].done is not None
    assert rep.entries["bb"].dispatched == 1
    assert not rep.entries["bb"].complete
    assert rep.entries["cc"].poisoned
    assert [e.idem for e in rep.incomplete] == ["bb"]
    # done-dedupe: lazily loads the recorded response, bit-identical
    got = jr2.lookup_done("aa")
    assert got is not None and got.request_id == 1
    assert np.array_equal(got.bp, b)
    assert jr2.is_poisoned("cc")
    # the incomplete entry's payload replays bit-identically too
    payload = jr2.load_payload("bb")
    assert payload is not None
    assert np.array_equal(payload[2], _planes(2)[2])


def test_replay_is_deterministic(tmp_path):
    jr = _journal(tmp_path)
    jr.open()
    for i, idem in enumerate(("x1", "x2", "x3")):
        _admit(jr, idem, rid=i + 1, seed=i)
    jr.record_dispatched("x2")
    jr.close()
    r1 = _journal(tmp_path).replay()
    r2 = _journal(tmp_path).replay()
    assert r1.order == r2.order
    assert {k: (e.dispatched, e.complete) for k, e in r1.entries.items()} \
        == {k: (e.dispatched, e.complete) for k, e in r2.entries.items()}


def test_duplicate_done_lines_fold_once(tmp_path):
    """A done retry that raced a death leaves two done lines; replay must
    count the request once, not answer twice."""
    jr = _journal(tmp_path)
    jr.open()
    _, _, b = _admit(jr, "dd", rid=1, seed=4)
    jr.record_done("dd", _resp(1, b))
    jr.record_done("dd", _resp(1, b))  # duplicate append
    jr.close()
    jr2 = _journal(tmp_path)
    rep = jr2.replay()
    assert len(rep.entries) == 1
    assert rep.entries["dd"].done is not None
    assert rep.incomplete == []
    assert jr2.inspect()["states"] == {"done": 1}


# ---------------------------------------------- damage + quarantine
# (same .corrupt contract — and the same assertion shapes — as the
# checkpoint quarantine tests in tests/test_aux.py)


def _segments(jr):
    return jr._segments()


def test_torn_tail_keeps_valid_prefix_and_quarantines(tmp_path):
    jr = _journal(tmp_path)
    jr.open()
    _admit(jr, "p1", rid=1, seed=1)
    _admit(jr, "p2", rid=2, seed=2)
    jr.close()
    (seg,) = _segments(jr)
    with open(seg) as f:
        whole = f.read()
    # tear mid-way through the LAST line (a death mid-append)
    torn_at = len(whole) - 10
    with open(seg, "w") as f:
        f.write(whole[:torn_at])

    jr2 = _journal(tmp_path)
    rep = jr2.replay()
    assert rep.quarantined == 1
    assert os.path.exists(seg + ".corrupt")       # evidence kept
    assert rep.order == ["p1"]                     # valid prefix survived
    # the rewritten segment replays cleanly on the NEXT restart too
    rep2 = _journal(tmp_path).replay()
    assert rep2.quarantined == 0
    assert rep2.order == ["p1"]


def test_flipped_byte_fails_seal_and_quarantines(tmp_path):
    jr = _journal(tmp_path)
    jr.open()
    _admit(jr, "q1", rid=1, seed=1)
    _admit(jr, "q2", rid=2, seed=2)
    jr.close()
    (seg,) = _segments(jr)
    with open(seg) as f:
        lines = f.readlines()
    # flip one byte INSIDE the second line's record payload (keep it
    # valid JSON: damage the idem value, so only the seal can catch it)
    lines[1] = lines[1].replace('"idem":"q2"', '"idem":"qX"')
    with open(seg, "w") as f:
        f.writelines(lines)

    rep = _journal(tmp_path).replay()
    assert rep.quarantined == 1
    assert os.path.exists(seg + ".corrupt")
    assert rep.order == ["q1"]


def test_corrupt_response_spill_degrades_to_not_done(tmp_path):
    """Exactly-once under spill rot: the key stops answering from the
    journal (quarantine), so a resubmission re-runs deterministically
    instead of serving damaged bytes."""
    jr = _journal(tmp_path)
    jr.open()
    _, _, b = _admit(jr, "rr", rid=1, seed=5)
    jr.record_done("rr", _resp(1, b))
    jr.close()
    rpath = jr.response_path("rr")
    with open(rpath, "r+b") as f:
        f.seek(os.path.getsize(rpath) // 2)
        f.write(b"\xff" * 32)

    jr2 = _journal(tmp_path)
    jr2.replay()
    assert jr2.lookup_done("rr") is None
    assert os.path.exists(rpath + ".corrupt")
    assert not os.path.exists(rpath)


def test_corrupt_payload_spill_is_unrecoverable_not_fatal(tmp_path):
    jr = _journal(tmp_path)
    jr.open()
    _admit(jr, "uu", rid=1, seed=6)
    jr.close()
    ppath = jr.payload_path("uu")
    with open(ppath, "r+b") as f:
        f.seek(os.path.getsize(ppath) // 2)
        f.write(b"\x00" * 32)
    jr2 = _journal(tmp_path)
    jr2.replay()
    assert jr2.load_payload("uu") is None
    assert os.path.exists(ppath + ".corrupt")


def test_compact_rewrites_final_states_only(tmp_path):
    jr = _journal(tmp_path)
    jr.open()
    _, _, b = _admit(jr, "c1", rid=1, seed=1)
    jr.record_dispatched("c1")
    jr.record_done("c1", _resp(1, b))
    _admit(jr, "c2", rid=2, seed=2)
    jr.record_dispatched("c2")
    jr.close()

    out = _journal(tmp_path).compact()
    assert out["after"]["segments"] == 1
    assert out["dropped_lines"] > 0
    jr3 = _journal(tmp_path)
    rep = jr3.replay()
    assert rep.entries["c1"].done is not None
    assert rep.entries["c2"].dispatched == 1      # attempt count survives
    assert [e.idem for e in rep.incomplete] == ["c2"]
    assert jr3.lookup_done("c1") is not None      # resp spill kept
    assert not os.path.exists(jr3.payload_path("c1"))  # finished input gone
    assert os.path.exists(jr3.payload_path("c2"))      # pending input kept


# -------------------------------------------- boundary hardening


def test_valid_idem_charset():
    """Keys name spill files: only [A-Za-z0-9_-]{1,64} passes, and the
    derived sha1 keys pass by construction."""
    assert sj.valid_idem("kill-restart-0")
    assert sj.valid_idem("A_b-9")
    assert sj.valid_idem(sj.idem_key("k", np.zeros((2, 2), np.float32)))
    for bad in ("", "../../../x", "a/b", "a\\b", ".", "..", "a.b",
                "a b", "a\x00b", "a" * 65, 7, None):
        assert not sj.valid_idem(bad)


def test_unsafe_idem_never_becomes_a_path(tmp_path):
    """Path builders are the backstop behind boundary validation: a
    traversal-shaped key must fail loudly, never join into a path."""
    jr = _journal(tmp_path)
    for bad in ("../../../x", "a/b", "..", "a" * 65):
        with pytest.raises(ValueError):
            jr.payload_path(bad)
        with pytest.raises(ValueError):
            jr.response_path(bad)


def test_replay_skips_handcrafted_unsafe_idem_lines(tmp_path):
    """A sealed-but-unsafe idem in a (handcrafted) journal line is
    skipped by replay — recovery must never turn it into a file path
    (load_payload on it would read/quarantine an arbitrary target)."""
    jr = _journal(tmp_path)
    rec = {"op": "admitted", "idem": "../../../etc/target", "rid": 1,
           "key": "k", "deadline_s": None}
    line = json.dumps({"seal": sj._seal(rec), **rec},
                      sort_keys=True, separators=(",", ":"))
    with open(os.path.join(jr.path, "segment-000001.jsonl"), "w") as f:
        f.write(line + "\n")
    rep = jr.replay()
    assert rep.entries == {} and rep.order == []
    assert rep.incomplete == []  # nothing for recover() to re-enqueue


def test_concurrent_admit_spills_stay_valid(tmp_path):
    """A client retry racing the original submission (both past the
    exists check) must not corrupt the payload spill: each writer uses
    its own temp file, so the surviving spill always load/checksums."""
    import threading

    jr = _journal(tmp_path)
    jr.open()
    a, ap, b = _planes(3)
    params = drills.image_params(levels=1)
    for round_ in range(8):
        idem = f"race-{round_}"
        barrier = threading.Barrier(2)

        def spill(rid, idem=idem):
            barrier.wait()
            jr.record_admit(idem, rid, a, ap, b, params, None, "key")

        threads = [threading.Thread(target=spill, args=(rid,))
                   for rid in (1, 2)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert jr.load_payload(idem) is not None  # checksum holds
    jr.close()
    names = os.listdir(os.path.join(jr.path, "payloads"))
    assert not any(n.endswith(".corrupt") for n in names)


def test_compact_refuses_while_journal_active(tmp_path, monkeypatch):
    """compact() deleting segments under a live appender would send its
    fsync'd appends to an unlinked file — refused via journal.lock."""
    jr = _journal(tmp_path)
    jr.open()
    _admit(jr, "live-1", rid=1, seed=1)
    with pytest.raises(RuntimeError, match="active"):
        jr.compact()  # same object: in-process appender
    other = _journal(tmp_path)
    with pytest.raises(RuntimeError, match="active"):
        other.compact()  # lock file names a live pid (ours)
    jr.close()
    out = _journal(tmp_path).compact()  # lock released: allowed
    assert out["after"]["segments"] == 1

    # a crashed incarnation's stale lock (dead owner) must not block
    jr2 = _journal(tmp_path)
    with open(os.path.join(jr2.path, "journal.lock"), "w") as f:
        f.write("123456789")
    def dead(pid, sig):
        raise ProcessLookupError

    monkeypatch.setattr(sj.os, "kill", dead)
    assert jr2.active_pid() is None
    jr2.compact()  # proceeds, stale lock swept
    assert not os.path.exists(os.path.join(jr2.path, "journal.lock"))


# ------------------------------------------------- server integration


def test_poisoned_key_sheds_before_breaker(tmp_path):
    """A persisted poison verdict sheds resubmission instantly with
    Rejected("poison") — counted, and never able to trip the breaker."""
    from image_analogies_tpu.obs import metrics as obs_metrics
    from image_analogies_tpu.obs import trace as obs_trace
    from image_analogies_tpu.serve.server import Server

    jdir = str(tmp_path / "j")
    pre = sj.RequestJournal(jdir, fsync=False)
    pre.open()
    _admit(pre, "bad-key", rid=1, seed=7)
    pre.record_poisoned("bad-key")
    pre.close()

    cfg = drills.serve_config(workers=1, journal_dir=jdir)
    a, ap, b = _planes(7)
    with obs_trace.run_scope(cfg.params.replace(metrics=True)):
        with Server(cfg) as srv:
            for _ in range(3):
                with pytest.raises(Rejected) as exc:
                    srv.submit(a, ap, b, idempotency_key="bad-key")
                assert exc.value.reason == "poison"
            assert srv._pool.breaker.state == "closed"
            counters = obs_metrics.snapshot()["counters"]
    assert counters.get("serve.poisoned") == 3


def test_unsafe_idempotency_key_rejected_at_submit(tmp_path):
    """A traversal-shaped client key is refused at the submit boundary
    before it can reach a journal line or a spill path."""
    from image_analogies_tpu.obs import trace as obs_trace
    from image_analogies_tpu.serve.server import Server

    cfg = drills.serve_config(workers=1, journal_dir=str(tmp_path / "j"))
    a, ap, b = _planes(5, size=(12, 12))
    with obs_trace.run_scope(cfg.params):
        with Server(cfg) as srv:
            for bad in ("../../../x", "a/b", "a" * 65, ""):
                with pytest.raises(Rejected) as exc:
                    srv.submit(a, ap, b, idempotency_key=bad)
                assert exc.value.reason == "bad_idempotency_key"
            # a well-formed key still flows
            ok = srv.submit(a, ap, b,
                            idempotency_key="good-key_1").result(timeout=60)
    assert ok.status == "ok"
    assert not os.path.exists(tmp_path / "x")  # nothing escaped the dir


def test_crash_exhaustion_persists_poison_across_restart(tmp_path):
    """The in-process crash-containment verdict survives the process:
    the key that took workers down is shed by the NEXT server too."""
    from image_analogies_tpu.obs import trace as obs_trace
    from image_analogies_tpu.serve.server import Server

    jdir = str(tmp_path / "j")
    cfg = drills.serve_config(workers=1, crash_requeues=0,
                              journal_dir=jdir)
    plan = ChaosPlan(seed=0, sites=(
        ("serve.dispatch", SiteRule(kind="crash", p=1.0)),))
    a, ap, b = _planes(8)
    with obs_trace.run_scope(cfg.params):
        with inject.plan_scope(plan):
            with Server(cfg) as srv:
                fut = srv.submit(a, ap, b, idempotency_key="crasher")
                with pytest.raises(Rejected) as exc:
                    fut.result(timeout=30)
                assert exc.value.reason == "worker_crash"
        # restart on the same journal, chaos disarmed: the key is
        # remembered as poison, not retried
        with Server(cfg) as srv2:
            assert srv2.recovery_stats["replayed"] == 0
            with pytest.raises(Rejected) as exc:
                srv2.submit(a, ap, b, idempotency_key="crasher")
            assert exc.value.reason == "poison"


def test_duplicate_submission_dedupes_with_recorded_response(tmp_path):
    from image_analogies_tpu.obs import trace as obs_trace
    from image_analogies_tpu.serve.server import Server

    cfg = drills.serve_config(workers=1, journal_dir=str(tmp_path / "j"))
    a, ap, b = _planes(9, size=(12, 12))
    with obs_trace.run_scope(cfg.params):
        with Server(cfg) as srv:
            first = srv.submit(a, ap, b).result(timeout=60)
            again = srv.submit(a, ap, b).result(timeout=60)
    assert again.request_id == first.request_id
    assert np.array_equal(again.bp, first.bp)


def test_disabled_journal_path_never_touches_module(tmp_path, monkeypatch):
    """Zero-cost disabled: with journal_dir unset, the request path must
    not instantiate a journal or derive an idem key."""
    from image_analogies_tpu.obs import trace as obs_trace
    from image_analogies_tpu.serve.server import Server

    def poisoned(*a, **k):
        raise AssertionError("journal touched on the disabled path")

    monkeypatch.setattr(sj.RequestJournal, "__init__", poisoned)
    monkeypatch.setattr(sj, "idem_key", poisoned)

    cfg = drills.serve_config(workers=1)  # no journal_dir
    a, ap, b = _planes(10, size=(12, 12))
    with obs_trace.run_scope(cfg.params):
        with Server(cfg) as srv:
            resp = srv.submit(a, ap, b).result(timeout=60)
    assert resp.status == "ok"


def test_loadgen_selftest_journal_smoke(tmp_path):
    """`ia serve --selftest --journal DIR`'s engine: the journaled smoke
    must complete, stay bit-identical, and answer every resubmission
    from the journal."""
    from image_analogies_tpu.serve import loadgen

    cfg = drills.serve_config(workers=1, journal_dir=str(tmp_path / "j"))
    summary = loadgen.selftest(cfg, 3, seed=0, shapes=((12, 12),))
    assert summary["errors"] == 0
    assert summary["bit_identical"] is True
    jn = summary["journal"]
    assert jn is not None
    assert jn["resubmit_deduped"] == summary["completed"] == 3
    assert jn["admitted"] == 3 and jn["done"] == 3


# ------------------------------------------------------- telemetry


def test_journal_surfaces_in_report_and_trace(tmp_path):
    """A journaled run's log carries the durability section in
    `ia report` and replay/dedupe instants on the serve trace track."""
    from image_analogies_tpu.obs import export as obs_export
    from image_analogies_tpu.obs import report as obs_report
    from image_analogies_tpu.obs import trace as obs_trace
    from image_analogies_tpu.serve.server import Server

    jdir = str(tmp_path / "j")
    log = str(tmp_path / "run.jsonl")
    cfg = drills.serve_config(workers=1, journal_dir=jdir)

    # incarnation 1: admit one request but kill before the worker can
    # finish it — guaranteed replay work for incarnation 2
    slow = drills.serve_config(workers=1, batch_window_ms=5000.0,
                               max_batch=2, journal_dir=jdir)
    a, ap, b = _planes(11, size=(12, 12))
    params = cfg.params.replace(metrics=True, log_path=log)
    with obs_trace.run_scope(params):
        srv = Server(slow)
        srv.start()
        jr = srv._journal
        jr.record_admit("ghost", 99, a, ap, b, slow.params, None, "key")
        srv.kill()
        with Server(cfg) as srv2:
            assert srv2.recovery_stats["replayed"] == 1
            assert srv2.wait_recovered(timeout=60) == {"ghost": "ok"}
            dup = srv2.submit(a, ap, b,
                              idempotency_key="ghost").result(timeout=60)
            assert np.array_equal(dup.bp, srv2.recovery["ghost"]
                                  .result().bp)

    an = obs_report.analyze(obs_report.load_records(log))
    assert an["journal"] is not None
    assert an["journal"]["replayed"] == 1
    assert an["journal"]["deduped"] == 1
    assert an["journal"]["recoveries"][-1]["replayed"] == 1
    assert "durability:" in obs_report.report(log)

    out = str(tmp_path / "trace.json")
    obs_export.export_trace(log, out)
    with open(out) as f:
        trace = json.load(f)
    serve_instants = [e["name"] for e in trace["traceEvents"]
                      if e.get("tid") == obs_export.SERVE_TID
                      and e["ph"] == "i"]
    assert any(n.startswith("replay requeued") for n in serve_instants)
    assert any(n.startswith("recovery replayed=1") for n in serve_instants)
    assert any(n.startswith("dedupe") for n in serve_instants)


# ------------------------------------------------------------- CLI


def test_cli_journal_inspect_and_compact(tmp_path, capsys):
    from image_analogies_tpu.cli import main

    jdir = str(tmp_path / "j")
    jr = sj.RequestJournal(jdir, fsync=False)
    jr.open()
    _, _, b = _admit(jr, "k1", rid=1, seed=1)
    jr.record_dispatched("k1")
    jr.record_done("k1", _resp(1, b))
    _admit(jr, "k2", rid=2, seed=2)
    assert main(["journal", "compact", jdir]) == 2  # refused: active
    assert "active" in capsys.readouterr().err
    jr.close()

    assert main(["journal", "inspect", jdir]) == 0
    out = capsys.readouterr().out
    assert "2 requests" in out and "done" in out and "k2" in out

    assert main(["journal", "compact", jdir, "--json"]) == 0
    out = json.loads(capsys.readouterr().out)
    assert out["after"]["lines"] == 2  # admit k2 + done k1 = final states

    assert main(["journal", "inspect", "/nonexistent/journal"]) == 2


# ------------------------------------------------------- grep locks


def test_journal_module_is_jax_free():
    """Durability is host-side control flow: serve/journal.py must import
    cleanly (and run) with no jax anywhere — same lock as chaos/."""
    src_path = sj.__file__
    with open(src_path) as f:
        src = f.read()
    assert not re.findall(r"^(import jax|from jax)", src, re.MULTILINE)
    assert not re.findall(r"\bjax\.jit\s*\(|\bpjit\s*\(|\bjax\.pmap\s*\(",
                          src)
