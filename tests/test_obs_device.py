"""Device-side observability (obs/device.py + obs/export.py): compile
shim accounting (compile counts, cache hits, XLA cost), static-arg AOT
dispatch and its fallback, the disabled path's zero-record /
zero-allocation guarantee, HBM sampling on statless backends, and the
Chrome-trace export schema on both synthetic and real engine logs."""

import json
import tracemalloc

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from image_analogies_tpu.config import AnalogyParams
from image_analogies_tpu.models.analogy import create_image_analogy
from image_analogies_tpu.obs import device as obs_device
from image_analogies_tpu.obs import export as obs_export
from image_analogies_tpu.obs import metrics as obs_metrics
from image_analogies_tpu.obs import trace as obs_trace

from tests.conftest import make_pair


# ------------------------------------------------------------- JitShim

def test_shim_disabled_passthrough_zero_alloc():
    shim = obs_device.instrument(
        jax.jit(lambda x, y: jnp.dot(x, y)), "test.dot")
    x = jnp.ones((8, 8), jnp.float32)
    ref = np.asarray(shim(x, x))  # warm the jit cache

    emitted = []
    from image_analogies_tpu.utils import logging as ialog
    orig = ialog._STAMPER
    ialog.set_record_stamper(lambda rec: emitted.append(dict(rec)))
    try:
        tracemalloc.start()
        try:
            for _ in range(50):
                # results are NOT retained: the only allocations below
                # the passthrough frame are the (freed) output arrays
                shim(x, x)
            snap = tracemalloc.take_snapshot()
        finally:
            tracemalloc.stop()
    finally:
        ialog.set_record_stamper(orig)
    assert np.array_equal(np.asarray(shim(x, x)), ref)
    assert emitted == []  # no compile records with metrics off
    obs_allocs = [t for t in snap.traces
                  if any("image_analogies_tpu/obs/" in fr.filename
                         for fr in t.traceback)]
    assert obs_allocs == []


def test_shim_compile_then_cache_hits(tmp_path):
    log = str(tmp_path / "run.jsonl")
    shim = obs_device.instrument(
        jax.jit(lambda x, y: jnp.dot(x, y)), "test.dot")
    x = jnp.ones((8, 8), jnp.float32)
    p = AnalogyParams(metrics=True, log_path=log)
    with obs_trace.run_scope(p) as ctx:
        with obs_trace.span("level", level=3):
            r1 = shim(x, x)
        r2 = shim(x, x)  # same program key -> cache hit
        y = jnp.ones((16, 16), jnp.float32)
        shim(y, y)  # new shapes -> second compile
        reg = ctx.registry
        assert reg.counter("compile.count") == 2
        assert reg.counter("compile.cache_hits") == 1
        assert reg.counter("compile.ms") > 0
        assert reg.counter("xla.flops") > 0  # 3 dot executions
        assert reg.counter("xla.bytes") > 0
    assert np.array_equal(np.asarray(r1), np.asarray(r2))
    recs = [json.loads(line) for line in open(log)]
    comps = [r for r in recs if r.get("event") == "compile"]
    assert len(comps) == 2
    assert all(c["name"] == "test.dot" and c["ok"] for c in comps)
    assert all(c["flops"] > 0 and c["bytes"] > 0 for c in comps)
    assert comps[0]["level"] == 3  # span attr attribution
    assert "level" not in comps[1]


def test_shim_static_args_aot_call():
    import functools

    @functools.partial(jax.jit, static_argnames=("k", "mode"))
    def scale(x, k, mode="mul"):
        return x * k if mode == "mul" else x + k

    shim = obs_device.instrument(scale, "test.scale", static_argnums=(1, 2))
    x = jnp.arange(4, dtype=jnp.float32)
    with obs_trace.run_scope(AnalogyParams(metrics=True)) as ctx:
        a = shim(x, 3, "mul")  # compile
        b = shim(x, 3, "mul")  # AOT call with statics stripped
        c = shim(x, 2, "add")  # different statics -> new program
        assert ctx.registry.counter("compile.count") == 2
        assert ctx.registry.counter("compile.cache_hits") == 1
    assert np.array_equal(np.asarray(a), np.asarray(b))
    assert np.array_equal(np.asarray(c), np.arange(4) + 2)


def test_shim_wrong_statics_falls_back():
    """A broken static_argnums spec must never change results: the AOT
    call raises, the shim retires the executable and dispatches the raw
    jitted fn instead."""
    shim = obs_device.instrument(
        jax.jit(lambda x, y: x + y), "test.bad", static_argnums=(1,))
    x = jnp.ones((4,), jnp.float32)
    with obs_trace.run_scope(AnalogyParams(metrics=True)) as ctx:
        a = shim(x, x)  # compile (lower sees both args; AOT expects both)
        b = shim(x, x)  # AOT call drops arg 1 -> TypeError -> fallback
        assert ctx.registry.counter("compile.count") == 1
        assert ctx.registry.counter("compile.cache_hits") == 1
    assert np.array_equal(np.asarray(a), np.full(4, 2.0))
    assert np.array_equal(np.asarray(b), np.full(4, 2.0))


def test_shim_delegates_jit_attrs():
    fn = jax.jit(lambda x: x + 1)
    shim = obs_device.instrument(fn, "test.attr")
    # attribute access falls through to the wrapped jit fn
    assert shim._cache_size() == fn._cache_size()
    lowered = shim.lower(jnp.ones((2,), jnp.float32))
    assert hasattr(lowered, "compile")
    # jax.jit keeps a weakref to its callable: the shim must be
    # re-wrappable (the graft entry jits the instrumented runner)
    rejit = jax.jit(lambda x: shim(x) * 2)
    assert np.array_equal(np.asarray(rejit(jnp.ones((2,), jnp.float32))),
                          np.full(2, 4.0))


def test_record_hbm_tolerates_statless_backend():
    # XLA:CPU returns None from memory_stats(): no gauges, no records,
    # no exception — and a plain no-op with metrics off
    obs_device.record_hbm(level=0)
    with obs_trace.run_scope(AnalogyParams(metrics=True)) as ctx:
        jax.devices()  # ensure the backend exists for the peek
        obs_device.record_hbm(level=0)
        gauges = ctx.registry.snapshot()["gauges"]
    assert not any(k.startswith("hbm.") for k in gauges)


# ------------------------------------------------------- chrome export

def _write_synthetic(path):
    recs = [
        {"event": "run_manifest", "backend": "tpu", "run_id": "r1",
         "seq": 0, "ts": 100.0},
        {"event": "compile", "name": "tpu.run_wavefront", "ms": 50.0,
         "flops": 1e6, "bytes": 2e6, "ok": True, "level": 1,
         "run_id": "r1", "seq": 1, "ts": 100.06},
        # spans are written at EXIT: outer [100.0, 100.5], inner
        # [100.2, 100.4] — the inner record appears FIRST in the file
        {"event": "span", "name": "level", "level": 1, "wall_ms": 200.0,
         "depth": 1, "parent": "phase", "run_id": "r1", "seq": 2,
         "ts": 100.4},
        {"level": 1, "db_rows": 64, "pixels": 100, "ms": 120.0,
         "run_id": "r1", "seq": 3, "ts": 100.39},
        {"event": "span", "name": "phase", "wall_ms": 500.0, "depth": 0,
         "run_id": "r1", "seq": 4, "ts": 100.5},
        {"event": "run_end", "metrics": {"counters": {}, "gauges": {},
                                         "histograms": {}},
         "run_id": "r1", "seq": 5, "ts": 100.5},
    ]
    with open(path, "w") as f:
        for r in recs:
            f.write(json.dumps(r, sort_keys=True) + "\n")


def _assert_schema(events):
    assert events, "empty trace"
    for e in events:
        assert e["ph"] in ("X", "i", "M")
        assert isinstance(e["ts"], (int, float))
        assert "pid" in e and "tid" in e
        assert "dur" in e or e["ph"] == "i"


def test_trace_export_golden(tmp_path):
    log = str(tmp_path / "synth.jsonl")
    _write_synthetic(log)
    trace = obs_export.to_chrome_trace(obs_export.load_records(log))
    events = trace["traceEvents"]
    _assert_schema(events)

    spans = {e["name"]: e for e in events
             if e["ph"] == "X" and e["tid"] == obs_export.HOST_TID}
    outer, inner = spans["phase"], spans["level"]
    # nesting consistent with span depth: the depth-1 interval sits
    # inside the depth-0 interval despite appearing first in the file
    assert outer["ts"] <= inner["ts"]
    assert inner["ts"] + inner["dur"] <= outer["ts"] + outer["dur"]
    assert outer["args"]["depth"] == 0 and inner["args"]["depth"] == 1
    assert inner["dur"] == pytest.approx(200.0 * 1e3)  # µs

    dev = [e for e in events if e["tid"] == obs_export.DEVICE_TID
           and e["ph"] == "X"]
    assert len(dev) == 1 and dev[0]["name"] == "L1 device"
    assert dev[0]["dur"] == pytest.approx(120.0 * 1e3)

    comp = [e for e in events if e["tid"] == obs_export.COMPILE_TID
            and e["ph"] == "X"]
    assert len(comp) == 1
    assert comp[0]["args"]["flops"] == 1e6

    insts = [e for e in events if e["ph"] == "i"]
    assert {e["name"] for e in insts} == {"run_manifest", "run_end"}
    # one pid for the single run, shared by every non-metadata event
    assert len({e["pid"] for e in events if e["ph"] != "M"}) == 1


# --------------------------------------------- acceptance: engine log

@pytest.fixture(scope="module")
def engine_log(tmp_path_factory):
    """Two same-shape engine runs inside one metrics scope on the
    jax-backed matcher (XLA:CPU compiles the same programs)."""
    log = str(tmp_path_factory.mktemp("obsdev") / "run.jsonl")
    a, ap, b = make_pair(20, 22, seed=3)
    params = AnalogyParams(levels=2, backend="tpu", metrics=True,
                           log_path=log)
    with obs_trace.run_scope(params):
        create_image_analogy(a, ap, b, params)
        create_image_analogy(a, ap, b, params)
    return log


def test_engine_report_compile_section(engine_log):
    from image_analogies_tpu.obs import report as obs_report

    recs = obs_report.load_records(engine_log)
    an = obs_report.analyze(recs)
    assert an["compile"] is not None
    assert an["compile"]["count"] >= 1
    # second run of equal shapes dispatches the cached executables
    assert an["compile"]["cache_hits"] > 0
    assert an["compile"]["total_ms"] > 0
    text = obs_report.render(an, "x")
    assert "compile:" in text
    assert "cache hits" in text


def test_engine_trace_cli(engine_log, tmp_path):
    from image_analogies_tpu.cli import main

    out = str(tmp_path / "trace.json")
    assert main(["trace", engine_log, "-o", out]) == 0
    trace = json.load(open(out))
    _assert_schema(trace["traceEvents"])
    names = {e["name"] for e in trace["traceEvents"]}
    assert any(n.startswith("compile ") for n in names)
    assert main(["trace", str(tmp_path / "missing.jsonl"),
                 "-o", out]) == 2
