"""Durable telemetry archive, tail-quantile sketches, and ceiling
watchdogs (ISSUE 17): the witness layer that survives the process.

Tier-1 invariants locked here:

- a sealed archive round-trips bit-identically: the replayed /timeline
  and /tenants documents equal the last appended docs, verbatim;
- a flipped byte costs exactly the records at and after it in that
  segment — the valid prefix survives, the file is quarantined
  ``.corrupt`` in place, and stats() counts it;
- compaction bounds the raw tier while preserving replay: the latest
  documents and total per-kind counts survive total raw-tier folding;
- the DDSketch-style quantile sketch honours its stated relative-error
  bound at 1e5 samples (bench re-runs at 1e6), merge is associative and
  merge-closed — the worker -> fleet federation path reports the same
  tail as the whole stream;
- the DISARMED archive plane allocates nothing (tracemalloc, same
  contract as the ledger/timeline planes);
- the timeline prunes per-series baselines of dead worker generations
  (fake clock: idle > 2 retentions -> dictionaries reclaimed,
  ``series_pruned`` counts) and a respawned generation starts fresh;
- the ceilings watchdog selftest catches a seeded synthetic leak within
  its tick budget, never alarms on flat noise, and an alarm lands as a
  sealed fleet DecisionLog record (`ia why` visibility);
- `ia archive inspect` summarizes a sealed store from the CLI and
  `ia top --from-archive --once` renders the archived cockpit offline;
- the live server exposes ``/archive/stats`` (disarmed shape mirrors
  the other planes) and ``/healthz`` carries process vitals;
- `ia bench --check` gates archive_overhead_pct in absolute points
  (legacy archives record-only) and passes sketch_p999_rel_err through.
"""

import gc
import json
import os
import threading
import tracemalloc
import urllib.request

import pytest

from image_analogies_tpu.chaos import drills, inject
from image_analogies_tpu.obs import archive as obs_archive
from image_analogies_tpu.obs import ceilings as obs_ceilings
from image_analogies_tpu.obs import quantiles as obs_quantiles
from image_analogies_tpu.obs import timeline as obs_timeline
from image_analogies_tpu.serve import journal as serve_journal
from image_analogies_tpu.serve.server import Server
from tests.conftest import make_pair


@pytest.fixture(autouse=True)
def _clean_planes():
    yield
    inject.disarm()
    for _ in range(8):
        if obs_archive.current() is None:
            break
        obs_archive.disarm()
    for _ in range(8):
        if obs_ceilings.current() is None:
            break
        obs_ceilings.disarm()
    for _ in range(8):
        if obs_timeline.current() is None:
            break
        obs_timeline.disarm()


def _tl_doc(n):
    """A synthetic /timeline-shaped doc; the archive treats docs as
    opaque, so the round-trip contract is plain equality."""
    return {"armed": True, "window_s": 1.0, "series": {
        "w0:serve.completed": {"kind": "counter",
                               "points": [[float(n), float(n + 1)]]}},
        "anomalies": [], "seq": n}


# ------------------------------------------------ sealed round trip


def test_archive_round_trip_bit_identity(tmp_path):
    root = str(tmp_path / "ar")
    ar = obs_archive.TelemetryArchive(root, sample_interval_s=0.0)
    docs = [_tl_doc(i) for i in range(5)]
    for d in docs:
        assert ar.append("timeline", d) is True
    ar.append("tenants", {"armed": True, "tenants": [], "recorded": 3})
    ar.append("decision", {"site": "router", "verdict": "spill"})

    # a SECOND reader over the same root sees only what is durable
    rd = obs_archive.TelemetryArchive(root)
    rep = rd.replay()
    assert rep["timeline"] == docs[-1]
    assert rep["tenants"]["recorded"] == 3
    assert rep["kinds"] == {"timeline": 5, "tenants": 1, "decision": 1}
    assert rep["decisions"] == [{"site": "router", "verdict": "spill"}]
    assert rd.history("timeline") == docs
    st = rd.stats()
    assert st["segments"] >= 1 and st["bytes"] > 0
    assert st["quarantined"] == 0


def test_flipped_byte_quarantines_and_keeps_valid_prefix(tmp_path):
    """Torn-write honesty: per-record segments make the blast radius
    exactly one record; the damaged file is renamed ``.corrupt``."""
    root = str(tmp_path / "ar")
    # max_segment_bytes=1: every append rotates -> one record/segment
    ar = obs_archive.TelemetryArchive(root, max_segment_bytes=1)
    docs = [_tl_doc(i) for i in range(5)]
    for d in docs:
        ar.append("timeline", d)
    segs = sorted(n for n in os.listdir(root) if n.endswith(".jsonl"))
    assert len(segs) == 5
    victim = os.path.join(root, segs[2])
    raw = bytearray(open(victim, "rb").read())
    raw[len(raw) // 2] ^= 0x01  # flip one payload bit
    with open(victim, "wb") as f:
        f.write(bytes(raw))

    rd = obs_archive.TelemetryArchive(root)
    hist = rd.history("timeline")
    assert hist == [docs[0], docs[1], docs[3], docs[4]]
    names = os.listdir(root)
    assert sum(1 for n in names if n.endswith(".corrupt")) == 1
    assert segs[2] not in names  # quarantined in place, not re-read
    assert rd.stats()["quarantined"] == 1
    # the survivors replay verbatim — corruption is surgical
    assert rd.replay()["timeline"] == docs[-1]


def test_compaction_bounds_disk_and_preserves_replay(tmp_path):
    root = str(tmp_path / "ar")
    ar = obs_archive.TelemetryArchive(
        root, max_segment_bytes=400, max_total_bytes=1600,
        sample_interval_s=0.0)
    n = 120
    for i in range(n):
        assert ar.append("timeline", _tl_doc(i)) is True
    st = ar.stats()
    assert st["compactions"] >= 1
    assert st["summary_segments"] >= 1
    # the RAW tier stays bounded near the cap (one open segment of
    # slack); the summary tier grows one sealed line per fold
    raw = sum(os.path.getsize(os.path.join(root, f))
              for f in os.listdir(root) if f.startswith("archive-"))
    assert raw <= ar.max_total_bytes + ar.max_segment_bytes
    rep = obs_archive.TelemetryArchive(root).replay()
    assert rep["timeline"] == _tl_doc(n - 1)      # latest doc survives
    assert rep["kinds"]["timeline"] == n          # counts fold, not drop


# ------------------------------------------------ quantile sketches


def test_sketch_selftest_and_merge_associativity():
    """The stated relative-error bound holds at 1e5 samples, whole
    stream AND after a worker->fleet merge; merge is associative and
    merge-closed (summary round trip)."""
    st = obs_quantiles.selftest(n=100_000)
    assert st["ok"], st
    assert st["p999_rel_err"] <= st["bound"]

    import random
    rng = random.Random(3)
    streams = [[rng.lognormvariate(3.0, 0.7) for _ in range(2000)]
               for _ in range(3)]
    sks = []
    for vals in streams:
        sk = obs_quantiles.QuantileSketch()
        for v in vals:
            sk.observe(v)
        sks.append(sk)
    whole = obs_quantiles.QuantileSketch()
    for vals in streams:
        for v in vals:
            whole.observe(v)
    a, b, c = (sk.summary() for sk in sks)
    left = obs_quantiles.merge_summaries(
        [obs_quantiles.merge_summaries([a, b]), c])
    right = obs_quantiles.merge_summaries(
        [a, obs_quantiles.merge_summaries([b, c])])
    assert left == right == whole.summary()
    merged = obs_quantiles.QuantileSketch.from_summary(left)
    exact = obs_quantiles.exact_quantile(
        [v for vals in streams for v in vals], 0.999)
    assert abs(merged.quantile(0.999) - exact) / exact <= merged.alpha


def test_sketch_values_never_poison():
    sk = obs_quantiles.QuantileSketch()
    sk.observe(float("nan"))
    sk.observe(0.0)
    sk.observe(-1.0)
    sk.observe(5.0)
    assert sk.count == 3 and sk.zeros == 2
    assert sk.quantile(0.999) > 0.0


# ------------------------------------------------ disarmed plane cost


def test_disarmed_archive_plane_allocates_nothing():
    """Acceptance: disarmed, the producer path is one module-bool read —
    no steady-state allocations attributable to obs/ (same tracemalloc
    lock as the timeline/ledger planes)."""
    assert obs_archive.current() is None
    doc = {"series": {"serve.qps": 1.0}}
    gc.collect()
    gc.disable()
    tracemalloc.start()
    try:
        for _ in range(2000):
            obs_archive.record("timeline", doc)
            obs_archive.sample()
        taken = tracemalloc.take_snapshot()
    finally:
        tracemalloc.stop()
        gc.enable()
    obs_allocs = [t for t in taken.traces
                  if any("image_analogies_tpu/obs/" in fr.filename
                         for fr in t.traceback)]
    assert len(obs_allocs) <= 8
    assert sum(t.size for t in obs_allocs) <= 1024


# ------------------------------------------------ timeline pruning


def test_timeline_prunes_dead_worker_series_baselines():
    """A SIGKILLed worker's series stop arriving; idle > 2 retentions,
    its per-series baselines are reclaimed and counted.  A respawned
    generation re-enters fresh (whole value = first delta)."""
    now = [0.0]
    tl = obs_timeline.Timeline(tiers=((1.0, 4),), clock=lambda: now[0])
    snap_w0 = {"counters": {"serve.requests": 10.0}, "gauges": {},
               "histograms": {}}
    tl.sample_snapshot(snap_w0, worker="w0", now=0.0)
    retention = 4.0  # tier-0 window_s * maxlen
    # w1 keeps reporting long past w0's horizon (2 * retention idle)
    for t in range(1, 14):
        tl.sample_snapshot(
            {"counters": {"serve.requests": 10.0 + t}, "gauges": {},
             "histograms": {}}, worker="w1", now=float(t))
    assert tl.series_pruned >= 1
    assert not any(k.startswith("w0:") for k in tl._cum)
    assert any(k.startswith("w1:") for k in tl._cum)
    # respawn: the fresh generation's counter enters as its own delta
    tl.sample_snapshot(snap_w0, worker="w0", now=14.0)
    assert tl._cum["w0:serve.requests"] == 10.0
    assert tl.series_pruned >= 1 and retention == 4.0


# ------------------------------------------------ ceiling watchdogs


def test_ceilings_selftest_catches_seeded_leak():
    st = obs_ceilings.selftest()
    assert st["ok"], st
    assert st["first_alarm_tick"] <= st["budget_ticks"]
    assert st["flat_alarms"] == 0


def test_ceiling_alarm_lands_in_fleet_decision_log(tmp_path):
    """The funnel end-to-end: a synthetic RSS leak trips the trend
    watchdog and the alarm is durable in decisions.jsonl — the same
    sealed trail `ia why` merges."""
    dl = serve_journal.DecisionLog(
        str(tmp_path / serve_journal.DecisionLog.NAME))
    now = [0.0]
    mon = obs_ceilings.CeilingMonitor(
        clock=lambda: now[0], cooldown_s=0.0, decision_log=dl)
    alarms = []
    for i in range(24):
        now[0] = float(i)
        alarms += mon.sample(
            extra={"proc.rss_bytes": float((512 << 20) + (4 << 20) * i)},
            now=float(i))
    assert alarms and alarms[0]["series"] == "proc.rss_bytes"
    recs = [r for r in dl.read() if r["site"] == "ceilings"]
    assert recs and recs[0]["verdict"] == "alarm"
    assert recs[0]["cause"] == "proc.rss_bytes_trend"
    assert recs[0].get("idem") is None  # fleet-scope, no request chain
    rpt = mon.report()["proc.rss_bytes"]
    assert rpt["alarms"] >= 1 and rpt["slope_per_s"] > 0


def test_proc_vitals_graceful_without_proc(monkeypatch):
    """On a /proc-less host (macOS, hardened sandboxes) the vitals
    reader must still return the FULL key set via its fallbacks —
    resource.getrusage for RSS, threading.active_count for threads,
    None for what has no fallback — and never raise."""
    real_open = open

    def _no_proc_open(path, *a, **kw):
        if str(path).startswith("/proc"):
            raise OSError("no /proc here")
        return real_open(path, *a, **kw)

    real_listdir = os.listdir

    def _no_proc_listdir(path="."):
        if str(path).startswith("/proc"):
            raise OSError("no /proc here")
        return real_listdir(path)

    # shadow the builtins in the module's own namespace: only the
    # ceilings reader sees the /proc-less world
    monkeypatch.setattr(obs_ceilings, "open", _no_proc_open,
                        raising=False)
    monkeypatch.setattr(obs_ceilings.os, "listdir", _no_proc_listdir)
    v = obs_ceilings.read_proc_vitals()
    assert set(v) == {"pid", "rss_bytes", "open_fds", "threads"}
    assert v["pid"] == os.getpid()
    assert v["open_fds"] is None  # no fallback exists; None, not a crash
    assert v["rss_bytes"] is not None and v["rss_bytes"] > 0
    assert v["threads"] is not None and v["threads"] >= 1


def test_frozen_fallback_vitals_never_alarm(monkeypatch):
    """The off-/proc RSS fallback is ru_maxrss — a PEAK, frozen between
    ticks.  A monitor fed that constant for a whole window must stay
    silent (slope 0), not alarm or crash the fleet health loop."""
    frozen = {"pid": 4242, "rss_bytes": 512 << 20, "open_fds": None,
              "threads": 8}
    monkeypatch.setattr(obs_ceilings, "read_proc_vitals",
                        lambda: dict(frozen))
    now = [0.0]
    mon = obs_ceilings.CeilingMonitor(clock=lambda: now[0],
                                      cooldown_s=0.0)
    alarms = []
    for i in range(24):
        now[0] = float(i)
        alarms += mon.sample(now=float(i))
    assert alarms == []
    rpt = mon.report()["proc.rss_bytes"]
    assert rpt["alarms"] == 0 and rpt["slope_per_s"] == 0.0


# ------------------------------------------------ CLI offline readers


def _seed_archive(root, n=3):
    ar = obs_archive.TelemetryArchive(root, sample_interval_s=0.0)
    for i in range(n):
        ar.append("timeline", _tl_doc(i))
    ar.append("anomaly", {"series": "w0:serve.latency_ms",
                          "kind": "zscore"})
    return ar


def test_cli_archive_inspect_and_replay(tmp_path, capsys):
    from image_analogies_tpu.cli import main

    root = str(tmp_path / "ar")
    _seed_archive(root)
    rc = main(["archive", "inspect", root])
    out = capsys.readouterr().out
    assert rc == 0
    assert "segment(s)" in out and "timeline=3" in out

    rc = main(["archive", "inspect", root, "--json"])
    doc = json.loads(capsys.readouterr().out)
    assert rc == 0
    assert doc["kinds"] == {"timeline": 3, "anomaly": 1}
    assert doc["quarantined"] == 0

    rc = main(["archive", "replay", root])
    out = capsys.readouterr().out
    assert rc == 0
    assert "ia top" in out  # archived cockpit frame

    missing = main(["archive", "inspect", str(tmp_path / "nope")])
    assert missing == 2


def test_cli_archive_diff(tmp_path, capsys):
    from image_analogies_tpu.cli import main

    ra, rb = str(tmp_path / "a"), str(tmp_path / "b")
    _seed_archive(ra, n=2)
    _seed_archive(rb, n=4)
    rc = main(["archive", "diff", ra, rb, "--json"])
    doc = json.loads(capsys.readouterr().out)
    assert rc == 0
    assert isinstance(doc, dict) and doc


def test_cli_top_from_archive_once(tmp_path, capsys):
    from image_analogies_tpu.cli import main

    root = str(tmp_path / "ar")
    _seed_archive(root)
    rc = main(["top", "--from-archive", root, "--once"])
    out = capsys.readouterr().out
    assert rc == 0
    assert "ia top" in out and "WORKER" in out

    empty = str(tmp_path / "empty")
    os.makedirs(empty)
    rc = main(["top", "--from-archive", empty, "--once"])
    captured = capsys.readouterr()
    assert rc == 2 and captured.err


# ------------------------------------------------ live endpoints


def test_http_archive_stats_and_healthz_vitals(tmp_path):
    """Satellites: /archive/stats mirrors the plane (armed shape with a
    live root, disarmed shape otherwise) and /healthz carries process
    vitals for the fleet health loop."""
    from image_analogies_tpu.serve.http import serve_http

    a, ap, b = make_pair(10, 10, seed=42)
    with Server(drills.serve_config(workers=1)) as srv:
        assert srv.request(a, ap, b, timeout=120).status == "ok"
        httpd = serve_http(srv, 0)
        t = threading.Thread(target=httpd.serve_forever, daemon=True)
        t.start()
        try:
            base = f"http://127.0.0.1:{httpd.server_address[1]}"
            with urllib.request.urlopen(base + "/archive/stats",
                                        timeout=5) as resp:
                disarmed = json.loads(resp.read().decode())
            obs_archive.arm(root=str(tmp_path / "ar"))
            try:
                obs_archive.current().append("timeline", _tl_doc(0))
                with urllib.request.urlopen(base + "/archive/stats",
                                            timeout=5) as resp:
                    armed = json.loads(resp.read().decode())
            finally:
                obs_archive.disarm()
            with urllib.request.urlopen(base + "/healthz",
                                        timeout=5) as resp:
                health = json.loads(resp.read().decode())
        finally:
            httpd.shutdown()
    assert disarmed == {"armed": False, "segments": 0, "bytes": 0}
    assert armed["armed"] is True and armed["bytes"] > 0
    assert armed["appended"] == 1
    vitals = health["vitals"]
    assert vitals["rss_bytes"] and vitals["rss_bytes"] > 0
    assert vitals["threads"] and vitals["threads"] >= 1


# ------------------------------------------------ bench rider


def test_bench_check_gates_archive_overhead():
    """archive_overhead_pct rides the bench trajectory with the same
    absolute-points gate as the timeline/ledger riders; legacy archives
    record-only; sketch_p999_rel_err passes through ungated."""
    import importlib.util

    spec = importlib.util.spec_from_file_location(
        "ia_bench_archive_test", os.path.join(
            os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
            "bench.py"))
    bench = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(bench)

    doc = {"parsed": {"value": 7.5, "metric": "1024x1024 north star",
                      "archive_overhead_pct": 1.5,
                      "sketch_p999_rel_err": 0.004}}
    head = bench.extract_headline(doc)
    assert head["archive_overhead_pct"] == 1.5
    assert head["sketch_p999_rel_err"] == 0.004

    trajectory = {"points": [
        {"value": 7.0, "metric_key": "1024x1024", "round": 1,
         "file": "BENCH_r01.json", "archive_overhead_pct": 1.0},
        {"value": 7.2, "metric_key": "1024x1024", "round": 2,
         "file": "BENCH_r02.json", "archive_overhead_pct": 2.0},
    ], "problems": []}
    ok = bench.check_regression(trajectory, fresh_value=7.1,
                                fresh_archive=2.5, threshold_pct=20.0)
    assert ok["ok"] and ok["archive_overhead_pct"] == 2.5
    assert ok["archive_overhead_floor"] == 1.0
    assert ok["archive_overhead_delta_pts"] == 1.5
    bad = bench.check_regression(trajectory, fresh_value=7.1,
                                 fresh_archive=30.0, threshold_pct=20.0)
    assert not bad["ok"]
    assert any("archive_overhead_pct" in p for p in bad["problems"])
    # self-check reads the latest point's own overhead
    latest = bench.check_regression(trajectory, threshold_pct=20.0)
    assert latest["archive_overhead_pct"] == 2.0
    assert latest["archive_overhead_floor"] == 1.0
    # legacy archive (no archive points): record-only, never a gate
    legacy = {"points": [
        {"value": 7.0, "metric_key": "1024x1024", "round": 1,
         "file": "BENCH_r01.json"}], "problems": []}
    rec = bench.check_regression(legacy, fresh_value=7.1,
                                 fresh_archive=99.0, threshold_pct=20.0)
    assert rec["ok"] and rec["archive_overhead_pct"] == 99.0
    assert rec["archive_overhead_floor"] is None
