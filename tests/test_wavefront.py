"""Wavefront (anti-diagonal parity) strategy tests — VERDICT.md round-1 item 1.

The wavefront strategy must reproduce the CPU/cKDTree oracle's output: the
raster scan is re-scheduled onto anti-diagonals skewed by patch_radius+1 so
every causal dependency lands on an earlier diagonal, and each diagonal
resolves in one batch with the oracle's exact per-pixel rule (backends/tpu.py
wavefront_scan_core) — output identical up to fp tie-breaks.
"""

import numpy as np
import pytest

from tests.conftest import make_pair
from image_analogies_tpu.config import AnalogyParams
from image_analogies_tpu.models.analogy import create_image_analogy
from image_analogies_tpu.utils.ssim import ssim


def _structured(h, seed=7):
    from examples.make_assets import _oil_filter, _perlin_ish

    rng = np.random.default_rng(seed)
    a = _perlin_ish(h, h, rng)
    return a, _oil_filter(a), _perlin_ish(h, h, rng)


@pytest.mark.parametrize("levels,kappa", [(1, 2.0), (2, 5.0)])
def test_wavefront_matches_oracle_small(levels, kappa):
    a, ap, b = make_pair(26, 24, seed=3)
    base = dict(levels=levels, kappa=kappa)
    oracle = create_image_analogy(a, ap, b, AnalogyParams(backend="cpu", **base))
    wf = create_image_analogy(
        a, ap, b, AnalogyParams(backend="tpu", strategy="wavefront", **base))
    # identical picks except (rare) fp-tie divergences
    mismatch = (wf.source_map != oracle.source_map).mean()
    assert mismatch < 0.02, f"source maps diverge on {mismatch:.1%} of pixels"
    np.testing.assert_allclose(wf.bp_y, oracle.bp_y, atol=1e-5)


def test_wavefront_structured_parity_64():
    a, ap, b = _structured(64)
    base = dict(levels=3, kappa=5.0)
    oracle = create_image_analogy(a, ap, b, AnalogyParams(backend="cpu", **base))
    wf = create_image_analogy(
        a, ap, b, AnalogyParams(backend="tpu", strategy="wavefront", **base))
    s = ssim(wf.bp_y, oracle.bp_y)
    assert s >= 0.98, f"SSIM vs oracle {s:.3f} < 0.98"


def test_wavefront_7x7_patches():
    a, ap, b = make_pair(24, 24, seed=5)
    base = dict(levels=2, kappa=0.5, patch_size=7)
    oracle = create_image_analogy(a, ap, b, AnalogyParams(backend="cpu", **base))
    wf = create_image_analogy(
        a, ap, b, AnalogyParams(backend="tpu", strategy="wavefront", **base))
    assert ssim(wf.bp_y, oracle.bp_y) >= 0.95


def test_wavefront_kappa_zero_pure_approx():
    # kappa=0 -> coherence never beats approx unless strictly closer; the
    # parity argument still holds (anchors converge to oracle anchors).
    a, ap, b = make_pair(22, 22, seed=9)
    base = dict(levels=1, kappa=0.0)
    oracle = create_image_analogy(a, ap, b, AnalogyParams(backend="cpu", **base))
    wf = create_image_analogy(
        a, ap, b, AnalogyParams(backend="tpu", strategy="wavefront", **base))
    np.testing.assert_allclose(wf.bp_y, oracle.bp_y, atol=1e-5)


def test_wavefront_sharded_matches_unsharded():
    a, ap, b = make_pair(24, 24, seed=11)
    base = dict(levels=2, kappa=2.0, strategy="wavefront", backend="tpu")
    solo = create_image_analogy(a, ap, b, AnalogyParams(**base))
    sharded = create_image_analogy(
        a, ap, b, AnalogyParams(db_shards=4, **base))
    np.testing.assert_array_equal(solo.source_map, sharded.source_map)
    np.testing.assert_allclose(solo.bp_y, sharded.bp_y, atol=1e-6)


def test_data_shards_single_image_gate():
    """data_shards > 1 on a single image exists only for the wavefront
    (query-parallel); other strategies must fail closed with an error
    naming the video entry point."""
    a, ap, b = make_pair(16, 16, seed=2)
    with pytest.raises(ValueError, match="video_analogy"):
        create_image_analogy(a, ap, b, AnalogyParams(
            levels=1, backend="tpu", strategy="batched", data_shards=2))


def test_wavefront_query_parallel_matches_unsharded():
    """Round-5 (SURVEY §5.7): ONE image over BOTH mesh axes — the patch
    DB over 'db' AND each anti-diagonal's queries over 'data'.  Query
    slicing is semantically a no-op (per-query work never reads across
    queries), so the 2x4 mesh must reproduce the solo scan BIT-exactly,
    including the all_gather lane reassembly on every segment width."""
    a, ap, b = make_pair(24, 24, seed=11)
    base = dict(levels=2, kappa=2.0, strategy="wavefront", backend="tpu")
    solo = create_image_analogy(a, ap, b, AnalogyParams(**base))
    both = create_image_analogy(
        a, ap, b, AnalogyParams(db_shards=4, data_shards=2, **base))
    np.testing.assert_array_equal(solo.source_map, both.source_map)
    np.testing.assert_allclose(solo.bp_y, both.bp_y, atol=1e-6)
    # queries over 'data' ONLY (db unsharded) must also hold
    qonly = create_image_analogy(
        a, ap, b, AnalogyParams(data_shards=2, **base))
    np.testing.assert_array_equal(solo.source_map, qonly.source_map)


def test_wavefront_a_b_different_sizes():
    # exemplar and target need not share shapes; parity must survive the
    # asymmetric DB/query geometry (A 28x26 vs B 20x24)
    rng = np.random.default_rng(13)
    a = rng.uniform(0, 1, (28, 26)).astype(np.float32)
    ap = (np.round(a * 5) / 5).astype(np.float32)
    b = rng.uniform(0, 1, (20, 24)).astype(np.float32)
    base = dict(levels=2, kappa=3.0)
    oracle = create_image_analogy(a, ap, b, AnalogyParams(backend="cpu", **base))
    wf = create_image_analogy(
        a, ap, b, AnalogyParams(backend="tpu", strategy="wavefront", **base))
    assert wf.bp_y.shape == (20, 24)
    mismatch = (wf.source_map != oracle.source_map).mean()
    assert mismatch < 0.02, f"{mismatch:.2%}"


@pytest.mark.slow
def test_wavefront_sharded_matches_unsharded_128():
    """Round-3 VERDICT item 7: sharded wavefront at REALISTIC size.

    At 24^2 the diagonal schedule has a handful of narrow segments and the
    shard padding geometry is trivial; 128^2 exercises width-bucketed
    segments (plateau M ~ 43) against db_shards=4 shard padding on the
    8-device virtual mesh — the interaction the small tests can't see."""
    rng = np.random.default_rng(31)
    a = rng.uniform(0, 1, (128, 128)).astype(np.float32)
    ap = (np.round(a * 6) / 6).astype(np.float32)
    b = rng.uniform(0, 1, (128, 128)).astype(np.float32)
    base = dict(levels=2, kappa=3.0, strategy="wavefront", backend="tpu")
    solo = create_image_analogy(a, ap, b, AnalogyParams(**base))
    sharded = create_image_analogy(a, ap, b,
                                   AnalogyParams(db_shards=4, **base))
    np.testing.assert_array_equal(solo.source_map, sharded.source_map)
    np.testing.assert_allclose(solo.bp_y, sharded.bp_y, atol=1e-6)


def test_live_dead_split_scoring_matches_full_rows():
    """The round-4 live/dead-split scoring (TpuLevelDB.db_live):
    d = sum_live (cf - q)^2 + dead_sqnorm[row] must equal the full-row
    distance to fp tolerance (queries are identically zero on dead dims),
    and the end-to-end wavefront scan with the split injected must match
    the full-row scan's output."""
    import dataclasses

    import jax.numpy as jnp

    from image_analogies_tpu.backends.base import LevelJob
    from image_analogies_tpu.backends.tpu import (
        TpuMatcher,
        _run_wavefront,
    )
    from image_analogies_tpu.config import AnalogyParams
    from image_analogies_tpu.ops.features import spec_for_level
    from tests.conftest import make_pair

    a, ap, b = make_pair(14, 14, seed=9)
    p = AnalogyParams(levels=1, backend="tpu", strategy="wavefront")
    spec = spec_for_level(p, 0, 1, 1)
    job = LevelJob(level=0, spec=spec, kappa_mult=p.kappa_factor(0) ** 2,
                   a_src=a, a_filt=ap, b_src=b)
    db = TpuMatcher(p).build_features(job)
    assert db.db_live is None  # CPU build keeps full-row scoring

    live = np.nonzero(spec.query_live_mask())[0]
    dead = np.setdiff1d(np.arange(spec.total), live)
    dbf = np.asarray(db.db)
    # the split identity itself, against real query rows (dead dims zero)
    q = np.asarray(db.static_q)[:5]
    assert np.abs(q[:, dead]).max() == 0.0
    d_full = ((dbf[None, :, :] - q[:, None, :]) ** 2).sum(-1)
    d_split = (((dbf[:, live][None] - q[:, None, live]) ** 2).sum(-1)
               + (dbf[:, dead] ** 2).sum(-1)[None, :])
    np.testing.assert_allclose(d_split, d_full, rtol=1e-5, atol=1e-5)

    # end-to-end: inject the split arrays; outputs must agree with the
    # full-row scan (identical up to fp summation order)
    db_live = dataclasses.replace(
        db, db_live=jnp.asarray(np.concatenate(
            [dbf[:, live], (dbf[:, dead] ** 2).sum(-1)[:, None]], axis=1)),
        live_idx=jnp.asarray(live, np.int32))
    km = jnp.float32(job.kappa_mult)
    bp_f, s_f, n_f = _run_wavefront(db, km)
    bp_l, s_l, n_l = _run_wavefront(db_live, km)
    np.testing.assert_allclose(np.asarray(bp_l), np.asarray(bp_f),
                               atol=1e-5)
    assert (np.asarray(s_l) == np.asarray(s_f)).mean() > 0.95


def test_wavefront_exemplar_cap_error_names_fallback():
    """The 2^24-row wavefront exemplar cap (f32-exact index lanes) must
    fail CLOSED at trace time with an error naming the cap, the reason,
    and the supported fallbacks; a boundary-sized static geometry (ha*wa
    == 2^24) must NOT trip it."""
    import dataclasses

    import pytest

    from image_analogies_tpu.backends.base import LevelJob
    from image_analogies_tpu.backends.tpu import (
        TpuMatcher,
        wavefront_scan_core,
        make_anchor_fn,
    )
    from image_analogies_tpu.config import AnalogyParams
    from image_analogies_tpu.ops.features import spec_for_level
    from tests.conftest import make_pair

    a, ap, b = make_pair(14, 14, seed=1)
    p = AnalogyParams(levels=1, backend="tpu", strategy="wavefront")
    spec = spec_for_level(p, 0, 1, 1)
    job = LevelJob(level=0, spec=spec, kappa_mult=p.kappa_factor(0) ** 2,
                   a_src=a, a_filt=ap, b_src=b)
    db = TpuMatcher(p).build_features(job)
    # one row past the cap: the raise happens before any array op, so a
    # statics-only override exercises the guard without a 16M-row build
    over = dataclasses.replace(db, ha=4096, wa=4097)
    with pytest.raises(ValueError, match=r"2\^24.*batched"):
        wavefront_scan_core(over, 1.0, make_anchor_fn(over))
    # exactly at the cap: no raise (the guard is strictly greater-than);
    # trace aborts later for unrelated shape reasons, which is fine —
    # only the guard's boundary semantics are under test here
    at_cap = dataclasses.replace(db, ha=4096, wa=4096)
    try:
        wavefront_scan_core(at_cap, 1.0, make_anchor_fn(at_cap))
    except ValueError as e:
        assert "2^24" not in str(e)
    except Exception:
        pass  # downstream shape errors from the statics-only override


def test_fused_anchor_rescore_matches_standalone():
    """The round-5 fused gather (`_batched_coherence(p_app=...)`): the
    anchor re-score rides the coherence candidates' row gather.  d_app
    must match the standalone live/dead-split re-score to fp-band (same
    rows and formula; reduction order may differ), and the coherence
    outputs must be untouched by the extra column."""
    import dataclasses

    import jax.numpy as jnp

    from image_analogies_tpu.backends.base import LevelJob
    from image_analogies_tpu.backends.tpu import (
        TpuMatcher,
        _batched_coherence,
    )
    from image_analogies_tpu.config import AnalogyParams
    from image_analogies_tpu.ops.features import spec_for_level
    from tests.conftest import make_pair

    a, ap, b = make_pair(16, 16, seed=3)
    p = AnalogyParams(levels=1, backend="tpu", strategy="wavefront")
    spec = spec_for_level(p, 0, 1, 1)
    job = LevelJob(level=0, spec=spec, kappa_mult=p.kappa_factor(0) ** 2,
                   a_src=a, a_filt=ap, b_src=b)
    db = TpuMatcher(p).build_features(job)
    live = np.nonzero(spec.query_live_mask())[0]
    dead = np.setdiff1d(np.arange(spec.total), live)
    dbf = np.asarray(db.db)
    db = dataclasses.replace(
        db, db_live=jnp.asarray(np.concatenate(
            [dbf[:, live], (dbf[:, dead] ** 2).sum(-1)[:, None]], axis=1)),
        live_idx=jnp.asarray(live, np.int32))

    rng = np.random.default_rng(0)
    na = db.ha * db.wa
    m, nc = 9, (int(db.off.shape[0]) - 1) // 2
    queries = jnp.asarray(np.asarray(db.static_q)[
        rng.choice(db.hb * db.wb, m, replace=False)])
    idx_c = jnp.asarray(rng.integers(0, db.hb * db.wb, (m, nc)), jnp.int32)
    s_r = jnp.asarray(rng.integers(0, na, (m, nc)), jnp.int32)
    ok = jnp.asarray(rng.random((m, nc)) < 0.8)
    p_app = jnp.asarray(rng.integers(0, na, m), jnp.int32)
    q_live = queries[:, db.live_idx]

    p0, d0, h0 = _batched_coherence(db, None, queries, idx_c, ok, nc,
                                    lambda i: db.db[i], q_live=q_live,
                                    s_r=s_r)
    p1, d1, h1, d_app = _batched_coherence(
        db, None, queries, idx_c, ok, nc, lambda i: db.db[i],
        q_live=q_live, s_r=s_r, p_app=p_app)
    np.testing.assert_array_equal(np.asarray(p0), np.asarray(p1))
    np.testing.assert_array_equal(np.asarray(d0), np.asarray(d1))
    np.testing.assert_array_equal(np.asarray(h0), np.asarray(h1))
    # the standalone re-score the fused column replaces — same rows, same
    # formula; XLA may reduce the (M, nc+1, L+1) block in a different
    # order than the (M, L+1) one, so the comparison is fp-band (~1e-6
    # relative), the class the tie-audit adjudicates on-chip
    lw = live.size
    gj = db.db_live[p_app]
    d_ref = jnp.sum((gj[:, :lw] - q_live) ** 2, axis=1) + gj[:, lw]
    np.testing.assert_allclose(np.asarray(d_app), np.asarray(d_ref),
                               rtol=1e-5, atol=1e-6)

    # round-5 A' column: widen db_live to [live | dead norm | A'] — the
    # fused call must return the picked candidates' and the anchor's A'
    # values, and leave every other output untouched
    afl = np.asarray(db.a_filt_flat)
    db_w = dataclasses.replace(
        db, db_live=jnp.concatenate(
            [db.db_live, jnp.asarray(afl)[:, None]], axis=1))
    p2, d2, h2, d_app2, af_coh, af_app = _batched_coherence(
        db_w, None, queries, idx_c, ok, nc, lambda i: db_w.db[i],
        q_live=q_live, s_r=s_r, p_app=p_app)
    np.testing.assert_array_equal(np.asarray(p2), np.asarray(p0))
    np.testing.assert_array_equal(np.asarray(d2), np.asarray(d0))
    np.testing.assert_array_equal(np.asarray(d_app2), np.asarray(d_app))
    np.testing.assert_array_equal(np.asarray(af_app), afl[np.asarray(p_app)])
    np.testing.assert_array_equal(np.asarray(af_coh), afl[np.asarray(p2)])
