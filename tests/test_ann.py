"""Two-stage ANN matcher (ISSUE 13): PCA prefilter + exact-f32 re-score.

Tier-1 invariants locked here:

- parity: a two-stage synthesis vs the exact matcher at 32^2/64^2
  (wavefront) and 32^2 (batched) leaves every source-map mismatch
  tie-explained (utils/parity.py audit) and the output planes value-
  matching — the same theorem discipline as tests/test_parity_audit.py;
- the parity gate probes ONCE per (device class, strategy), caches a
  refusal, and a refused gate leaves synthesis bit-identical to the
  exact engine (``ann.fallback_exact``, never ``ann.prefilter_used``);
- sealed artifacts (catalog/ann.py): save/load roundtrip is bit-exact,
  rebuilding from the same bytes is deterministic, damage (flipped
  byte, stored-key mismatch) quarantines as ``.corrupt`` and returns
  None instead of poisoned state;
- the slab/rank knobs resolve through tune/ (env ``IA_ANN_TOP_M`` /
  ``IA_ANN_PROJ_DIMS``, tuner ``override`` above env), and the
  adversarial ``ann_top_m=1`` floor still synthesizes valid output;
- ``ia catalog build`` seals one ``_ann/`` basis per level and the next
  prefiltered request resolves them (``ann.artifact_hits``) instead of
  paying the eigendecomposition (``ann.projection_built`` absent);
- ``ia bench --check``'s exemplar-scaling gates: the absolute
  sub-linearity gate needs no archive floor, legacy archives record
  only, and the relative floor gate fails a regressed ratio.
"""

import json
import os

import numpy as np
import pytest

import bench
from examples.make_assets import make_structured
from image_analogies_tpu import cli
from image_analogies_tpu.backends import tpu
from image_analogies_tpu.catalog import ann as catalog_ann
from image_analogies_tpu.catalog import build as catalog_build
from image_analogies_tpu.catalog import tiers
from image_analogies_tpu.config import AnalogyParams
from image_analogies_tpu.models.analogy import create_image_analogy
from image_analogies_tpu.obs import trace as obs_trace
from image_analogies_tpu.tune import geometry
from image_analogies_tpu.tune import resolve as tune
from image_analogies_tpu.utils.parity import audit_source_map_mismatches


@pytest.fixture(autouse=True)
def _clean_ann_state(monkeypatch, tmp_path):
    """Gate verdicts and memory tiers are process-global by design;
    tests must never leak a cached verdict, a configured catalog root,
    or a developer store/env into the suite."""
    for var in ("IA_ANN_TOP_M", "IA_ANN_PROJ_DIMS"):
        monkeypatch.delenv(var, raising=False)
    monkeypatch.setenv("IA_TUNE_STORE", str(tmp_path / "no_store.json"))
    tpu.reset_ann_gate()
    tiers.clear()
    tiers.configure(None)
    yield
    tpu.reset_ann_gate()
    tiers.clear()
    tiers.configure(None)


def _inputs(size=20, seed=7):
    rng = np.random.RandomState(seed)
    return (rng.rand(size, size).astype(np.float32),
            rng.rand(size, size).astype(np.float32),
            rng.rand(size, size).astype(np.float32))


def _params(**kw):
    base = dict(backend="tpu", strategy="wavefront", levels=2,
                patch_size=3, coarse_patch_size=3, metrics=True)
    base.update(kw)
    return AnalogyParams(**base)


_OK_VERDICT = {"ok": True, "mismatches": 0, "unexplained": 0,
               "first_divergence_is_tie": None}
_REFUSED_VERDICT = {"ok": False, "mismatches": 3, "unexplained": 3,
                    "first_divergence_is_tie": False}


# ------------------------------------------------------ config surface


def test_ann_prefilter_param_validation():
    with pytest.raises(ValueError, match="ann_prefilter"):
        AnalogyParams(backend="cpu", ann_prefilter=True)
    with pytest.raises(ValueError, match="ann_prefilter"):
        AnalogyParams(backend="tpu", strategy="exact", ann_prefilter=True)
    for s in ("wavefront", "batched", "auto"):
        AnalogyParams(backend="tpu", strategy=s, ann_prefilter=True)


# ------------------------------------------------------- parity audits


@pytest.mark.parametrize("strategy,size", [("wavefront", 32),
                                           ("wavefront", 64),
                                           ("batched", 32)])
def test_two_stage_parity_audit(strategy, size):
    """The support theorem behind the gate: every pick the two-stage
    matcher makes differently from the exact engine is an exact or
    fp32-resolution tie (gate bypassed — the gate's own probe is the
    production copy of this test)."""
    a, ap, b = make_structured(size, seed=5)
    p = AnalogyParams(levels=2, kappa=5.0, backend="tpu",
                      strategy=strategy, patch_size=3,
                      coarse_patch_size=3)
    exact = create_image_analogy(a, ap, b, p, keep_levels=True)
    with tpu.ann_gate_bypass():
        two = create_image_analogy(a, ap, b,
                                   p.replace(ann_prefilter=True),
                                   keep_levels=True)
    audit = audit_source_map_mismatches(a, ap, b, p, two.levels,
                                        exact.levels)
    assert audit["unexplained"] == 0, audit
    match = float((np.asarray(exact.bp_y) == np.asarray(two.bp_y)).mean())
    assert match >= 0.99, match


def test_off_means_bit_identical():
    """Acceptance: ann_prefilter (default False) leaves the engine
    byte-for-byte the exact matcher."""
    a, ap, b = _inputs()
    x = np.asarray(create_image_analogy(a, ap, b, _params()).bp)
    y = np.asarray(create_image_analogy(
        a, ap, b, _params(ann_prefilter=False)).bp)
    assert np.array_equal(x, y)


# ----------------------------------------------------- parity gate


def test_gate_refusal_caches_and_stays_exact(monkeypatch):
    """A refused verdict is probed once, cached per (device, strategy),
    and every synthesis silently keeps the exact matcher."""
    calls = []

    def fake_verdict(params, strategy):
        calls.append(strategy)
        return dict(_REFUSED_VERDICT)

    monkeypatch.setattr(tpu, "_ann_probe_verdict", fake_verdict)
    tpu.reset_ann_gate()
    a, ap, b = _inputs()
    ref = np.asarray(create_image_analogy(a, ap, b, _params()).bp)
    p = _params(ann_prefilter=True)
    with obs_trace.run_scope(p) as ctx:
        out1 = np.asarray(create_image_analogy(a, ap, b, p).bp)
        out2 = np.asarray(create_image_analogy(a, ap, b, p).bp)
    c = ctx.registry.snapshot()["counters"]
    assert calls == ["wavefront"]  # second run hits the cached refusal
    assert np.array_equal(out1, ref) and np.array_equal(out2, ref)
    assert c["ann.disabled_unexplained"] == 1
    assert c["ann.fallback_exact"] >= 4  # two levels x two runs
    assert "ann.prefilter_used" not in c


def test_gate_ok_engages_prefilter_per_level(monkeypatch):
    monkeypatch.setattr(tpu, "_ann_probe_verdict",
                        lambda p, s: dict(_OK_VERDICT))
    tpu.reset_ann_gate()
    a, ap, b = _inputs()
    p = _params(ann_prefilter=True)
    with obs_trace.run_scope(p) as ctx:
        out = np.asarray(create_image_analogy(a, ap, b, p).bp)
    c = ctx.registry.snapshot()["counters"]
    gauges = ctx.registry.snapshot()["gauges"]
    assert c["ann.gate_ok"] == 1
    assert c["ann.prefilter_used"] == 2  # one per level
    assert c["ann.projection_built"] == 2  # no catalog root: on-the-fly
    assert gauges["ann.top_m"] == tune.ann_top_m()
    assert out.shape == b.shape and np.isfinite(out).all()


# ------------------------------------------------- sealed artifacts


def test_artifact_roundtrip_and_determinism(tmp_path):
    rng = np.random.RandomState(0)
    db = rng.rand(200, 37).astype(np.float32)
    m1, p1 = catalog_ann.build_projection(db, 8)
    m2, p2 = catalog_ann.build_projection(db, 8)
    assert np.array_equal(m1, m2) and np.array_equal(p1, p2)
    assert m1.shape == (37,) and p1.shape == (37, 8)
    path = catalog_ann.save_artifact(str(tmp_path), "feedcafe", m1, p1)
    assert path == catalog_ann.artifact_path(str(tmp_path), "feedcafe")
    got = catalog_ann.load_artifact(str(tmp_path), "feedcafe")
    assert got is not None
    assert np.array_equal(got[0], m1) and np.array_equal(got[1], p1)
    # rank clamps to min(dims, F, N) — a tiny DB can't mint a wide basis
    _, p3 = catalog_ann.build_projection(db[:5], 64)
    assert p3.shape[1] == 5


def test_artifact_damage_quarantines(tmp_path):
    rng = np.random.RandomState(1)
    m, p = catalog_ann.build_projection(rng.rand(64, 16), 4)
    path = catalog_ann.save_artifact(str(tmp_path), "deadbeef", m, p)
    catalog_ann.damage_artifact(path, seed=3)
    assert catalog_ann.load_artifact(str(tmp_path), "deadbeef") is None
    assert not os.path.exists(path)
    assert os.path.exists(path + ".corrupt")
    # a second load of the quarantined key is a clean miss, not a crash
    assert catalog_ann.load_artifact(str(tmp_path), "deadbeef") is None
    # damaging an absent artifact is a no-op (chaos may fire pre-build)
    catalog_ann.damage_artifact(
        catalog_ann.artifact_path(str(tmp_path), "nope"), seed=3)


def test_artifact_key_mismatch_reads_as_damage(tmp_path):
    """Bytes filed under the wrong content key must NOT serve: the seal
    binds the stored key, so a renamed artifact quarantines."""
    rng = np.random.RandomState(2)
    m, p = catalog_ann.build_projection(rng.rand(64, 16), 4)
    src = catalog_ann.save_artifact(str(tmp_path), "aaaa1111", m, p)
    dst = catalog_ann.artifact_path(str(tmp_path), "bbbb2222")
    os.rename(src, dst)
    assert catalog_ann.load_artifact(str(tmp_path), "bbbb2222") is None
    assert os.path.exists(dst + ".corrupt")


# --------------------------------------------------- tune knob funnel


def test_ann_knob_resolution_env_and_override(monkeypatch):
    assert tune.ann_top_m() == geometry.DEFAULT_ANN_TOP_M
    assert tune.ann_proj_dims() == geometry.DEFAULT_ANN_PROJ_DIMS
    monkeypatch.setenv("IA_ANN_TOP_M", "48")
    monkeypatch.setenv("IA_ANN_PROJ_DIMS", "12")
    assert tune.ann_top_m() == 48
    assert tune.ann_proj_dims() == 12
    with tune.override(ann_top_m=7, ann_proj_dims=5):
        assert tune.ann_top_m() == 7  # tuner override beats env
        assert tune.ann_proj_dims() == 5
    assert tune.ann_top_m() == 48
    monkeypatch.setenv("IA_ANN_TOP_M", "not-a-number")
    assert tune.ann_top_m() == geometry.DEFAULT_ANN_TOP_M


def test_adversarial_top_m_one():
    """Slab floor: a single prefilter survivor per query degenerates the
    re-score to the prefilter's own champion — still a valid synthesis
    (every pick a real DB row, output drawn from A')."""
    a, ap, b = make_structured(32, seed=5)
    p = AnalogyParams(levels=2, kappa=5.0, backend="tpu",
                      strategy="wavefront", patch_size=3,
                      coarse_patch_size=3, ann_prefilter=True)
    with tune.override(ann_top_m=1), tpu.ann_gate_bypass():
        out = create_image_analogy(a, ap, b, p)
    bp = np.asarray(out.bp)
    assert bp.shape == b.shape
    assert np.isfinite(bp).all()
    assert bp.min() >= ap.min() - 1e-6 and bp.max() <= ap.max() + 1e-6


# ------------------------------------------------ catalog integration


def test_catalog_build_seals_bases_and_request_hits(tmp_path,
                                                    monkeypatch):
    a, ap, b = _inputs()
    root = str(tmp_path)
    p = _params(catalog_dir=root, ann_prefilter=True)
    res = catalog_build.build_style(a, ap, p, root_dir=root, target=b)
    sealed = [f for f in os.listdir(os.path.join(root, catalog_ann.ANN_DIR))
              if f.endswith(".npz")]
    assert len(sealed) == res["levels"] == 2
    assert all(e.get("ann_dims") for e in res["entries"])
    monkeypatch.setattr(tpu, "_ann_probe_verdict",
                        lambda pp, s: dict(_OK_VERDICT))
    tpu.reset_ann_gate()
    with obs_trace.run_scope(p) as ctx:
        create_image_analogy(a, ap, b, p)
    c = ctx.registry.snapshot()["counters"]
    assert c["ann.artifact_hits"] == 2
    assert c["ann.prefilter_used"] == 2
    assert "ann.projection_built" not in c  # sealed bases, no eigh


# --------------------------------------------- bench gates + CLI seam


def test_exemplar_scale_check_gates():
    legacy = [{"metric_key": "k", "value": 1.0, "file": "BENCH_r1.json"}]
    with_floor = legacy + [{"metric_key": "k", "value": 1.0,
                            "file": "BENCH_r2.json",
                            "exemplar_scale_ratio": 6.0}]
    # absolute sub-linearity gate fires with no archive floor at all
    out = bench.check_regression({"points": legacy}, fresh_value=1.0,
                                 fresh_key="k", fresh_scale=9.4)
    assert out["ok"] is False
    assert any("exemplar_scale_not_sublinear" in pr
               for pr in out["problems"])
    assert out["exemplar_scale_floor"] is None
    # legacy archive + sub-linear candidate: recorded only
    out = bench.check_regression({"points": legacy}, fresh_value=1.0,
                                 fresh_key="k", fresh_scale=6.3)
    assert out["ok"] is True
    assert out["exemplar_scale_ratio"] == 6.3
    assert out["exemplar_scale_floor"] is None
    # relative floor gate: 6.0 -> 7.9 is a 31.7% regression
    out = bench.check_regression({"points": with_floor}, fresh_value=1.0,
                                 fresh_key="k", fresh_scale=7.9)
    assert out["ok"] is False
    assert out["exemplar_scale_floor"] == 6.0
    assert any("exemplar_scale_ratio regressed" in pr
               for pr in out["problems"])
    # within threshold (and under 8x) passes both gates
    out = bench.check_regression({"points": with_floor}, fresh_value=1.0,
                                 fresh_key="k", fresh_scale=6.5)
    assert out["ok"] is True


def test_cli_bench_exemplar_scale_flag(monkeypatch, capsys):
    # cmd_bench imports the repo-root bench.py through its own loader;
    # stub THAT seam so the flag test never pays a real measurement
    class _Stub:
        @staticmethod
        def measure_exemplar_scaling():
            return {"exemplar_scale_ratio": 5.0, "max_scale": 16,
                    "points": []}

    monkeypatch.setattr(cli, "_load_bench_module", lambda: _Stub)
    rc = cli.main(["bench", "--exemplar-scale"])
    assert rc == 0
    doc = json.loads(capsys.readouterr().out)
    assert doc["exemplar_scale_ratio"] == 5.0
