"""Golden SSIM regression over the five BASELINE.json eval configs
(round-1 VERDICT item 5).

Each config runs end-to-end on the TPU backend (wavefront parity strategy)
from the committed miniature assets and must (a) reproduce its committed
golden PNG within SSIM tolerance — an output regression fails loudly and the
gallery diff shows what changed — and (b) track the CPU oracle's output,
locking cross-backend quality at every config, not just the oil filter.

Regenerate the gallery after an INTENTIONAL output change with:
    JAX_PLATFORMS=cpu python examples/make_golden.py
"""

import os

import numpy as np
import pytest

from image_analogies_tpu.utils.imageio import load_image
from image_analogies_tpu.utils.ssim import ssim

GOLDEN_DIR = os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "examples", "golden")

# (config name, golden output keys, SSIM floor vs committed golden,
#  SSIM floor vs the CPU oracle).  Golden floors allow 8-bit PNG
# quantization.  Oracle floors: round 2 carried loose tbn (0.90) and video
# (0.95) floors for exact-tie divergence; the round-3 lexicographic
# (distance, index) anchors resolve every tie to the lowest index on both
# backends, and ALL five configs now measure SSIM 1.0 / 100% bit-equal
# TPU-vs-oracle at these sizes — 0.99 everywhere leaves margin only for
# platform fp drift (round-3 VERDICT item 6).
CONFIGS = [
    ("tbn", ["out"], 0.98, 0.99),
    ("oil", ["out"], 0.98, 0.99),
    ("superres", ["out"], 0.98, 0.99),
    ("npr", ["out"], 0.98, 0.99),
    ("video", ["f0", "f1", "f2"], 0.98, 0.99),
]


@pytest.fixture(scope="module")
def assets():
    from examples.make_golden import make_assets_small

    return make_assets_small()


@pytest.fixture(scope="module")
def configs(assets):
    import functools

    from examples.make_golden import golden_configs

    # memoize per (config, backend): the oracle-vs-golden test reuses the
    # planes the SSIM test already computed instead of re-running the full
    # synthesis (the video config is the priciest CPU run in the suite)
    return {name: functools.lru_cache(maxsize=None)(fn)
            for name, fn in golden_configs(assets)}


@pytest.mark.golden
@pytest.mark.parametrize("name,keys,g_floor,o_floor", CONFIGS)
def test_golden_config(name, keys, g_floor, o_floor, configs):
    tpu = configs[name]("tpu")
    cpu = configs[name]("cpu")
    for key in keys:
        golden = load_image(
            os.path.join(GOLDEN_DIR, f"golden_{name}_{key}.png"))
        got = np.clip(np.asarray(tpu[key], np.float32), 0, 1)
        s_golden = ssim(got, golden)
        assert s_golden >= g_floor, (
            f"{name}/{key}: SSIM vs committed golden {s_golden:.4f} < "
            f"{g_floor} — output changed; if intentional, regenerate with "
            f"examples/make_golden.py")
        s_oracle = ssim(np.asarray(tpu[key], np.float32),
                        np.asarray(cpu[key], np.float32))
        assert s_oracle >= o_floor, (
            f"{name}/{key}: SSIM vs CPU oracle {s_oracle:.4f} < {o_floor}")


@pytest.mark.golden
def test_golden_inputs_committed(assets):
    # the gallery must contain every input the configs consume, pinned
    for name in assets:
        path = os.path.join(GOLDEN_DIR, f"in_{name}.png")
        assert os.path.exists(path), f"missing committed input {path}"
        committed = load_image(path)
        fresh = np.clip(np.asarray(assets[name], np.float32), 0, 1)
        assert committed.shape == fresh.shape
        np.testing.assert_allclose(committed, fresh, atol=1.5 / 255,
                                   err_msg=f"asset generator drifted: {name}")


@pytest.mark.golden
def test_video_golden_tracks_oracle_exactly(configs):
    """The committed video goldens ARE the CPU oracle's output (8-bit PNG
    quantization aside).  In particular the byte-identical f1/f2 golden
    pair is the algorithm's attractor — with temporal_weight=1.0 the
    phase-2 synthesis of both frames converges onto bit-equal source maps
    despite inputs differing — continuously verified here instead of a
    one-time regen note (round-3 ADVICE)."""
    cpu = configs["video"]("cpu")
    f1 = np.asarray(cpu["f1"], np.float32)
    f2 = np.asarray(cpu["f2"], np.float32)
    np.testing.assert_array_equal(f1, f2)
    for key in ("f0", "f1", "f2"):
        golden = load_image(
            os.path.join(GOLDEN_DIR, f"golden_video_{key}.png"))
        got = np.clip(np.asarray(cpu[key], np.float32), 0, 1)
        np.testing.assert_allclose(
            golden, got, atol=1.5 / 255,
            err_msg=f"video/{key}: committed golden drifted from the CPU "
                    "oracle — regenerate with examples/make_golden.py only "
                    "after confirming the oracle change is intentional")
