"""Tenant-scoped metering & decision attribution (ISSUE 16): per-style
cost ledger, fixed-memory heavy hitters, and `ia why` request forensics.

Tier-1 invariants locked here:

- the space-saving sketch is provably fixed-memory: under a 10k-style
  synthetic load it tracks at most K keys, guarantees every key with
  true frequency > N/K a slot, and every reported count is an honest
  interval ``[count - error, count]``;
- sketches and tenant documents MERGE (the PR 11 federation path):
  shared keys sum, foreign keys enter at the local floor, the union
  re-trims to K, and latency histograms fold via from_summary;
- the DISARMED ledger plane allocates nothing (tracemalloc, same
  contract as obs/timeline.py) and arm() nests across owners;
- arming mirrors tracked tenants into ``tenant:<sha1[:8]>``-labeled
  timeline series via the feeder registry;
- `ia why <idem>` replays journal + decision evidence into one ordered
  causal chain — locked on a live journaled server AND across a real
  degrade + SIGKILL handoff + spill drill on the subprocess fleet,
  cross-checked against the journals' raw history and the router's
  counters;
- `ia top --tenants --once` renders the per-style view from a live
  ``/tenants`` endpoint and exits 0 (2 when unreachable);
- `ia bench --check` gates ledger_overhead_pct in absolute points
  (legacy archives record-only);
- the loadgen's ``--zipf`` mode draws a deterministic, skewed per-style
  load whose same-style requests share exemplars (one tenant key);
- obs/ledger.py and obs/tenants.py never import jax (grep lock).
"""

import gc
import json
import os
import re
import signal
import threading
import time
import tracemalloc
import urllib.request

import numpy as np
import pytest

from image_analogies_tpu.chaos import drills, inject
from image_analogies_tpu.obs import ledger as obs_ledger
from image_analogies_tpu.obs import metrics as obs_metrics
from image_analogies_tpu.obs import tenants as obs_tenants
from image_analogies_tpu.obs import timeline as obs_timeline
from image_analogies_tpu.obs import trace as obs_trace
from image_analogies_tpu.serve import journal as serve_journal
from image_analogies_tpu.serve import loadgen
from image_analogies_tpu.serve.server import Server
from tests.conftest import make_pair


@pytest.fixture(autouse=True)
def _clean_planes():
    yield
    inject.disarm()
    while obs_ledger.armed():
        obs_ledger.disarm()


# ------------------------------------------------ space-saving sketch


def test_sketch_fixed_memory_under_10k_styles():
    """Acceptance: K slots, 10k+ distinct styles — memory stays O(K),
    every >N/K heavy hitter is tracked, and each reported count is an
    honest interval around the true frequency."""
    k = 16
    ss = obs_tenants.SpaceSaving(k)
    truth = {}
    stream = [f"hh{i % 4}" for i in range(4000)]
    stream += [f"tail{i}" for i in range(10000)]
    rng = np.random.RandomState(0)
    rng.shuffle(stream)
    for key in stream:
        truth[key] = truth.get(key, 0) + 1
        ss.offer(key)
    assert len(ss) <= k
    assert ss.offered == len(stream)
    items = ss.items()
    # the guarantee: true frequency > N/K (= 875) cannot be evicted
    tracked = {key for key, _, _ in items}
    assert {"hh0", "hh1", "hh2", "hh3"} <= tracked
    for key, count, err in items:
        assert count - err <= truth[key] <= count
    # sorted by count desc: the heavy hitters lead
    assert all(key.startswith("hh") for key, _, _ in items[:4])


def test_sketch_merge_is_honest_and_bounded():
    a, b = obs_tenants.SpaceSaving(4), obs_tenants.SpaceSaving(4)
    for _ in range(10):
        a.offer("x")
    for _ in range(3):
        a.offer("y")
    for _ in range(7):
        b.offer("x")
    for _ in range(5):
        b.offer("z")
    a.merge(b)
    assert len(a) <= 4
    assert a.offered == 25
    counts = {key: (c, e) for key, c, e in a.items()}
    # shared key: exact sum (both sides tracked it exactly)
    assert counts["x"] == (17.0, 0.0)
    assert counts["z"][0] >= 5.0  # foreign key enters >= its remote count


def test_tenant_tracker_is_bounded_and_aggregates():
    t = obs_tenants.TenantTracker(k=8)
    for i in range(10000):
        t.observe(f"style{i}", latency_ms=1.0)
    for _ in range(500):
        t.observe("viral", latency_ms=20.0, dispatch_ms=5.0,
                  degraded=True, retries=1, wire_bytes=100, lanes=2)
    doc = t.snapshot()
    assert doc["tracked"] <= 8 and len(t._stats) <= 8
    assert doc["offered"] == 10500
    top = doc["tenants"][0]
    assert top["tenant"] == "viral"
    assert top["requests"] == 500 and top["degraded"] == 500
    assert top["retries"] == 500 and top["wire_bytes"] == 50000
    assert top["cost_share"] == pytest.approx(1.0, abs=0.01)
    assert top["p95_ms"] == pytest.approx(20.0, rel=0.2)


def test_merge_docs_federates_worker_snapshots():
    t1, t2 = obs_tenants.TenantTracker(k=4), obs_tenants.TenantTracker(k=4)
    for _ in range(6):
        t1.observe("shared", latency_ms=10.0, dispatch_ms=2.0)
    for _ in range(4):
        t2.observe("shared", latency_ms=100.0, dispatch_ms=1.0)
    t2.observe("only2", latency_ms=5.0, dispatch_ms=7.0)
    merged = obs_tenants.merge_docs([t1.snapshot(), t2.snapshot()])
    assert merged["offered"] == 11 and merged["tracked"] == 2
    rows = {r["tenant"]: r for r in merged["tenants"]}
    assert rows["shared"]["requests"] == 10
    assert rows["shared"]["count"] == 10
    assert rows["shared"]["dispatch_ms"] == pytest.approx(16.0)
    # histograms fold via from_summary: p95 reflects BOTH sides' samples
    assert rows["shared"]["p95_ms"] >= 90.0
    total = sum(r["cost_share"] for r in merged["tenants"])
    assert total == pytest.approx(1.0, abs=0.01)
    # the obs/fleet re-export is the same function
    from image_analogies_tpu.obs import fleet as obs_fleet

    again = obs_fleet.merge_tenant_docs([t1.snapshot(), t2.snapshot()])
    assert again["offered"] == merged["offered"]


# ------------------------------------------------ module plane


def test_ledger_arm_record_disarm_roundtrip():
    led = obs_ledger.arm(capacity=4, tenant_k=4)
    try:
        for i in range(6):
            obs_ledger.record({"tenant": f"t{i % 2}", "status": "ok",
                               "total_ms": 10.0, "queue_ms": 1.0,
                               "dispatch_ms": 4.0, "lanes": 1,
                               "wire_bytes": 64})
        assert obs_ledger.current() is led
        assert len(led.recent()) == 4  # capacity bound holds
        doc = obs_ledger.tenants_doc()
        assert doc["armed"] is True and doc["recorded"] == 6
        rows = {r["tenant"]: r for r in doc["tenants"]}
        assert rows["t0"]["requests"] == 3 and rows["t1"]["requests"] == 3
        assert all("qps" in r for r in doc["tenants"])
        # nested arm joins the same ledger; inner disarm keeps it
        assert obs_ledger.arm() is led
        obs_ledger.disarm()
        assert obs_ledger.current() is led
    finally:
        obs_ledger.disarm()
    assert obs_ledger.current() is None
    assert obs_ledger.tenants_doc() == {
        "armed": False, "k": 0, "tracked": 0, "offered": 0,
        "recorded": 0, "tenants": []}


def test_disarmed_ledger_plane_allocates_nothing():
    """Acceptance: disarmed, the producer path is one module-bool read —
    no steady-state allocations attributable to obs/ (same tracemalloc
    lock as obs/timeline.py's)."""
    assert obs_ledger.current() is None
    vec = {"tenant": "abc", "status": "ok", "total_ms": 1.0}
    gc.collect()
    gc.disable()
    tracemalloc.start()
    try:
        for _ in range(2000):
            obs_ledger.record(vec)
            obs_ledger.sample_timeline()
        taken = tracemalloc.take_snapshot()
    finally:
        tracemalloc.stop()
        gc.enable()
    obs_allocs = [t for t in taken.traces
                  if any("image_analogies_tpu/obs/" in fr.filename
                         for fr in t.traceback)]
    assert len(obs_allocs) <= 8
    assert sum(t.size for t in obs_allocs) <= 1024


def test_armed_ledger_feeds_tenant_labeled_timeline_series():
    """Arming registers the feeder; sample_timeline mirrors tracked
    tenants into ``tenant:<sha1[:8]>``-labeled series the cockpit and
    per-worker anomaly detector already understand."""
    tl = obs_timeline.arm()
    led = obs_ledger.arm(tenant_k=4)
    try:
        assert obs_ledger.sample_timeline in obs_timeline._FEEDERS
        for _ in range(3):
            led.record({"tenant": "cafe0123deadbeef", "status": "ok",
                        "total_ms": 12.0, "queue_ms": 1.0,
                        "dispatch_ms": 5.0, "lanes": 1})
        obs_ledger.sample_timeline()
        pts = tl.range("tenant:cafe0123:serve.completed")
        assert pts and pts[-1][1] == 3.0
        hpts = tl.range("tenant:cafe0123:serve.latency_ms")
        assert hpts and hpts[-1][1]["count"] == 3
    finally:
        obs_ledger.disarm()
        obs_timeline.disarm()
    assert obs_ledger.sample_timeline not in obs_timeline._FEEDERS


def test_emit_decision_counts_and_traces():
    scope = obs_metrics.ObsScope(scope_id="dec")
    with obs_metrics.scope_active(scope):
        obs_ledger.emit_decision("worker", "degrade", "ewma_over_budget",
                                 idem="k1", levels=2)
        snap = scope.registry.snapshot()
    assert snap["counters"].get("serve.decision.degrade") == 1


# ------------------------------------------------ ia why (live server)


def test_ia_why_reconstructs_journaled_server_chain(tmp_path, capsys):
    """Acceptance: a degrade-planned request on a journaled server leaves
    admit/decision/cost/done evidence that `ia why` replays into one
    ordered chain, exit 0; a missing key exits 2."""
    from image_analogies_tpu.cli import main

    jdir = str(tmp_path / "j")
    cfg = drills.serve_config(workers=1, journal_dir=jdir)
    a, ap, b = make_pair(12, 12, seed=9)
    with obs_trace.run_scope(cfg.params):
        with Server(cfg) as srv:
            # Pessimistic observation (1000 s/unit): even blended into a
            # store-seeded prior the full-fidelity estimate dwarfs the
            # 30s deadline, so the degrade verdict fires deterministically.
            srv.cost_model.observe(1.0, 1000.0)
            resp = srv.submit(a, ap, b, deadline_s=30.0,
                              idempotency_key="why-key").result(timeout=180)
    assert resp.status == "degraded"

    doc = serve_journal.reconstruct("why-key", jdir)
    assert doc["found"]
    ops = [e["op"] for e in doc["events"]]
    assert ops[0] == "admitted" and ops[-1] == "done"
    assert "cost" in ops and "decision" in ops
    # the cost vector carries the tenant key (= batcher exemplar digest)
    assert doc["tenant"] and len(doc["tenant"]) == 12
    chain = " ".join(doc["chain"])
    assert "degrade" in chain

    rc = main(["why", "why-key", "--root", jdir])
    out = capsys.readouterr().out
    assert rc == 0
    for token in ("ia why why-key", "admitted", "degrade", "done",
                  "chain:"):
        assert token in out

    rc = main(["why", "missing-key", "--root", jdir])
    captured = capsys.readouterr()
    assert rc == 2 and "no journal" in captured.out

    rc = main(["why", "why-key", "--root", jdir, "--json"])
    jdoc = json.loads(capsys.readouterr().out)
    assert rc == 0 and jdoc["found"] and jdoc["chain"]


# ------------------------------------------------ ia why (forensics drill)


def test_ia_why_forensics_degrade_spill_sigkill(tmp_path, monkeypatch,
                                                capsys):
    """Acceptance tentpole: one request drilled through a degrade
    verdict, a REAL SIGKILL journal handoff, and a spill to the ring
    successor — `ia why` reconstructs the complete ordered chain across
    both worker journals plus the router's decision log, reconciled
    against the journals' raw history and the router's counters."""
    from image_analogies_tpu.chaos.plan import ChaosPlan, SiteRule
    from image_analogies_tpu.cli import main
    from image_analogies_tpu.serve.fleet import Fleet
    from image_analogies_tpu.serve.types import FleetConfig
    from image_analogies_tpu.tune import store as tune_store

    # A pessimistic cost prior in the tune store (inherited via the env
    # by every spawned child) makes the deadline request degrade
    # DETERMINISTICALLY inside the subprocess worker.
    store = str(tmp_path / "tune.json")
    monkeypatch.setenv("IA_TUNE_STORE", store)
    tune_store.save_entries(
        {"serve_cost|cpu|any": {"cost_rate": 1.0}}, store)
    tune_store.invalidate_cache()

    n = 3
    root = str(tmp_path / "journals")
    fcfg = FleetConfig(
        serve=drills.serve_config(workers=1, max_batch=n,
                                  batch_window_ms=2000.0),
        size=2, vnodes=16, journal_root=root, transport="subprocess",
        health_interval_s=0.1, death_checks=2,
        backoff_s=0.01, backoff_cap_s=0.05)
    load = drills.make_serve_load(n, seed=11)
    ikey = "why-fleet-{}".format
    # router.forward visits 0..n-1 are the original submits; the FIRST
    # post-handoff resubmit (visit n) eats a transient hop fault and
    # must spill to the ring successor (same geometry as the
    # fleet_death_subprocess drill).
    plan = ChaosPlan(seed=0, name="why-forensics", sites=(
        ("router.forward", SiteRule(kind="transient", schedule=(n,))),))

    with obs_trace.run_scope(fcfg.serve.params) as ctx:
        inject.arm(plan)
        try:
            with Fleet(fcfg) as fl:
                # wave 1: the probe request, deadlined so the child's
                # seeded cost model degrades it; journaled done.
                item0 = load[0]
                futures = {0: fl.submit(item0["a"], item0["ap"],
                                        item0["b"], deadline_s=120.0,
                                        idempotency_key=ikey(0))}
                probe = futures[0].result(timeout=180)
                assert probe.status == "degraded"

                def _journal(wid):
                    w = fl.health()["workers"].get(wid, {})
                    return w.get("journal") or {}

                home = next(wid for wid in fl.workers
                            if _journal(wid).get("done", 0) >= 1)
                victim_pid = fl.workers[home].pid

                # wave 2: coalescing in the home child's batch window
                for i, item in enumerate(load[1:], start=1):
                    futures[i] = fl.submit(item["a"], item["ap"],
                                           item["b"],
                                           idempotency_key=ikey(i))
                end = time.monotonic() + 60.0
                while (_journal(home).get("admitted", 0) < n
                       and time.monotonic() < end):
                    time.sleep(0.02)
                assert _journal(home).get("admitted", 0) >= n

                os.kill(victim_pid, signal.SIGKILL)
                end = time.monotonic() + 120.0
                while not fl.handoffs and time.monotonic() < end:
                    time.sleep(0.02)
                assert fl.handoffs, "no journal handoff happened"
                for fut in futures.values():
                    fut.result(timeout=180)

                # resubmit under the original keys: the probe's forward
                # is visit n -> transient -> spill to the successor,
                # which computes fresh (and degrades again: the prior
                # rides the env into every child)
                replies = {}
                for i, item in enumerate(load):
                    replies[i] = fl.submit(
                        item["a"], item["ap"], item["b"],
                        deadline_s=120.0 if i == 0 else None,
                        idempotency_key=ikey(i)).result(timeout=180)
                assert replies[0].status == "degraded"
                successor = next(w for w in fl.workers if w != home)
        finally:
            inject.disarm()
        counters = dict(ctx.registry.snapshot()["counters"])

    # --- the causal chain, merged across both journals + decision log
    doc = serve_journal.reconstruct(ikey(0), root)
    assert doc["found"]
    assert set(doc["workers"]) == {home, successor}
    assert doc["tenant"] and len(doc["tenant"]) == 12
    chain = doc["chain"]
    # ordered: home's full lifecycle, THEN the spill verdict, THEN the
    # successor's fresh lifecycle
    i_done = chain.index("done")
    i_spill = next(i for i, s in enumerate(chain) if s.startswith("spill"))
    second_admit = [i for i, s in enumerate(chain)
                    if s.startswith("admitted")][1]
    assert i_done < i_spill < second_admit
    assert chain[-1] == "done"
    assert sum(1 for s in chain if s.startswith("degrade")) == 2
    assert sum(1 for s in chain if s == "done") == 2

    # --- reconciled against journal ground truth: per-worker event
    # slices must equal each journal's raw history, op for op
    for wid in (home, successor):
        hist = serve_journal.RequestJournal(
            os.path.join(root, wid)).history(ikey(0))
        assert [e["op"] for e in doc["events"] if e["worker"] == wid] \
            == [r["op"] for r in hist]

    # --- reconciled against the router's counters
    assert counters.get("router.spills") == 1
    assert counters.get("router.deaths") == 1
    assert counters.get("router.handoffs") == 1
    assert counters.get("serve.decision.spill") == 1
    assert counters.get("serve.decision.death") == 1
    assert counters.get("serve.decision.handoff") == 1
    spills_in_chain = sum(1 for s in chain if s.startswith("spill"))
    assert spills_in_chain == counters["router.spills"]

    # fleet-scope verdicts (death, handoff) carry no idem: they feed
    # counters and `ia report`, never another request's chain
    dl = serve_journal.DecisionLog(
        os.path.join(root, serve_journal.DecisionLog.NAME))
    verdicts = {}
    for rec in dl.read():
        verdicts.setdefault(rec["verdict"], []).append(rec)
    assert "death" in verdicts and "handoff" in verdicts
    assert all(r.get("idem") is None
               for v in ("death", "handoff") for r in verdicts[v])
    assert verdicts["spill"][0]["idem"] == ikey(0)

    # --- the CLI renders it
    rc = main(["why", ikey(0), "--root", root])
    out = capsys.readouterr().out
    assert rc == 0
    for token in ("degrade", "spill", "admitted", "done", "chain:",
                  home, successor):
        assert token in out


# ------------------------------------------------ ia top --tenants


def test_ia_top_tenants_once_renders_live_view(capsys):
    """Satellite: `ia top --tenants --once` fetches a live server's
    /tenants and renders the per-style table, exit 0."""
    from image_analogies_tpu.cli import main
    from image_analogies_tpu.serve.http import serve_http

    a, ap, b = make_pair(10, 10, seed=42)
    with Server(drills.serve_config(workers=1)) as srv:
        assert srv.request(a, ap, b, timeout=120).status == "ok"
        httpd = serve_http(srv, 0)
        t = threading.Thread(target=httpd.serve_forever, daemon=True)
        t.start()
        try:
            base = f"http://127.0.0.1:{httpd.server_address[1]}"
            with urllib.request.urlopen(base + "/tenants",
                                        timeout=5) as resp:
                doc = json.loads(resp.read().decode())
            rc = main(["top", "--tenants", "--once", "--url", base])
        finally:
            httpd.shutdown()
    assert doc["armed"] is True and doc["tenants"]
    tenant = doc["tenants"][0]["tenant"]
    out = capsys.readouterr().out
    assert rc == 0
    for col in ("TENANT", "REQS", "QPS", "P95MS", "COST%", "DEGR"):
        assert col in out
    assert tenant[:12] in out


def test_ia_top_tenants_unreachable_exits_2(capsys):
    from image_analogies_tpu.cli import main

    rc = main(["top", "--tenants", "--once",
               "--url", "http://127.0.0.1:1"])
    captured = capsys.readouterr()
    assert rc == 2
    assert "cannot fetch" in captured.err


# ------------------------------------------------ bench rider


def test_bench_check_gates_ledger_overhead():
    """Satellite: ledger_overhead_pct rides the bench trajectory with
    the same absolute-points gate as the timeline rider; legacy
    archives record-only."""
    import importlib.util

    spec = importlib.util.spec_from_file_location(
        "ia_bench_ledger_test", os.path.join(
            os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
            "bench.py"))
    bench = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(bench)

    doc = {"parsed": {"value": 7.5, "metric": "1024x1024 north star",
                      "ledger_overhead_pct": 1.5}}
    assert bench.extract_headline(doc)["ledger_overhead_pct"] == 1.5

    trajectory = {"points": [
        {"value": 7.0, "metric_key": "1024x1024", "round": 1,
         "file": "BENCH_r01.json", "ledger_overhead_pct": 1.0},
        {"value": 7.2, "metric_key": "1024x1024", "round": 2,
         "file": "BENCH_r02.json", "ledger_overhead_pct": 2.0},
    ], "problems": []}
    ok = bench.check_regression(trajectory, fresh_value=7.1,
                                fresh_ledger=2.5, threshold_pct=20.0)
    assert ok["ok"] and ok["ledger_overhead_pct"] == 2.5
    assert ok["ledger_overhead_floor"] == 1.0
    assert ok["ledger_overhead_delta_pts"] == 1.5
    bad = bench.check_regression(trajectory, fresh_value=7.1,
                                 fresh_ledger=30.0, threshold_pct=20.0)
    assert not bad["ok"]
    assert any("ledger_overhead_pct" in p for p in bad["problems"])
    # archive self-check reads the latest point's own overhead
    latest = bench.check_regression(trajectory, threshold_pct=20.0)
    assert latest["ledger_overhead_pct"] == 2.0
    assert latest["ledger_overhead_floor"] == 1.0
    # legacy archive (no ledger points): record-only, never a gate
    legacy = {"points": [
        {"value": 7.0, "metric_key": "1024x1024", "round": 1,
         "file": "BENCH_r01.json"}], "problems": []}
    rec = bench.check_regression(legacy, fresh_value=7.1,
                                 fresh_ledger=99.0, threshold_pct=20.0)
    assert rec["ok"] and rec["ledger_overhead_pct"] == 99.0
    assert rec["ledger_overhead_floor"] is None


def test_cli_bench_check_ledger_rider(tmp_path, capsys):
    from image_analogies_tpu.cli import main

    with open(tmp_path / "BENCH_r01.json", "w") as f:
        json.dump({"parsed": {"value": 7.0,
                              "metric": "1024x1024 north star",
                              "ledger_overhead_pct": 1.0}}, f)
    res = tmp_path / "result.json"
    with open(res, "w") as f:
        json.dump({"value": 7.1, "metric": "1024x1024 north star",
                   "ledger_overhead_pct": 2.5}, f)
    rc = main(["bench", "--check", "--result", str(res),
               "--dir", str(tmp_path)])
    assert rc == 0
    out = json.loads(capsys.readouterr().out)
    assert out["ledger_overhead_pct"] == 2.5
    assert out["ledger_overhead_floor"] == 1.0


# ------------------------------------------------ zipf loadgen


def test_zipf_load_is_deterministic_and_skewed():
    shapes = [(12, 12)]
    l1 = loadgen.make_load(40, shapes, seed=3, zipf=1.2, styles=6)
    l2 = loadgen.make_load(40, shapes, seed=3, zipf=1.2, styles=6)
    h1, h2 = loadgen.style_hist(l1), loadgen.style_hist(l2)
    assert h1 == h2 and sum(h1.values()) == 40
    assert len(h1) <= 6
    # Zipf skew: the rank-1 style dominates
    assert h1["s0"] == max(h1.values())
    assert h1["s0"] > 40 // 6
    # same-style requests share exemplars — ONE tenant key per style
    by_style = {}
    for item in l1:
        by_style.setdefault(item["style"], []).append(item)
    for items in by_style.values():
        for item in items[1:]:
            np.testing.assert_array_equal(item["a"], items[0]["a"])
            np.testing.assert_array_equal(item["ap"], items[0]["ap"])
    # distinct styles use distinct exemplars
    s_keys = sorted(by_style)
    if len(s_keys) >= 2:
        assert not np.array_equal(by_style[s_keys[0]][0]["a"],
                                  by_style[s_keys[1]][0]["a"])
    # classic loads have no style histogram
    assert loadgen.style_hist(
        loadgen.make_load(4, shapes, seed=3)) is None


def test_zipf_selftest_summary_carries_style_hist():
    cfg = drills.serve_config(workers=1)
    with obs_trace.run_scope(cfg.params):
        summary = loadgen.selftest(cfg, 4, seed=5, zipf=1.1, styles=3)
    assert summary["errors"] == 0
    assert summary["zipf"] == 1.1
    hist = summary["style_hist"]
    assert hist and sum(hist.values()) == 4
    text = loadgen.render(summary)
    assert "zipf S=1.1" in text


# ------------------------------------------------ grep locks


def test_ledger_and_tenants_modules_are_jax_free():
    """Satellite lock: the metering plane is host-side bookkeeping on
    the request path — no module-scope jax import, no jit/pjit calls."""
    import image_analogies_tpu.obs as obs_pkg

    root = os.path.dirname(obs_pkg.__file__)
    forbidden = re.compile(r"\bjax\.jit\s*\(|\bpjit\s*\(|\bjax\.pmap\s*\(")
    toplevel_jax = re.compile(r"^(import jax|from jax)", re.MULTILINE)
    for name in ("ledger.py", "tenants.py"):
        with open(os.path.join(root, name)) as f:
            src = f.read()
        assert not forbidden.findall(src), f"obs/{name} calls jit/pjit"
        assert not toplevel_jax.findall(src), (
            f"obs/{name} imports jax at module scope")
