"""Production multi-chip video path (round-1 VERDICT items 3 + 7).

`video_analogy(..., data_shards>1)` must dispatch frames through the
('data','db') mesh step (`parallel/step.py`) and produce the SAME frames as
the serial two_phase path (with `remap_luminance=False`; the sharded path
remaps against the first frame by design — see models/video.py docstring),
without re-jitting the shard_map per call.

Runs on the 8-device virtual CPU mesh from conftest.
"""

import numpy as np
import pytest

import jax

from tests.conftest import make_pair
from image_analogies_tpu.config import AnalogyParams
from image_analogies_tpu.models.video import video_analogy


def _frames(a, n=4):
    rng = np.random.default_rng(1)
    return [np.clip(np.roll(a, t, axis=1)
                    + 0.01 * rng.standard_normal(a.shape), 0, 1)
            .astype(np.float32) for t in range(n)]


@pytest.mark.parametrize("strategy", ["batched", "wavefront"])
def test_sharded_video_matches_serial(strategy):
    a, ap, _ = make_pair(20, 20, seed=2)
    frames = _frames(a, 4)
    base = dict(levels=2, kappa=2.0, backend="tpu", strategy=strategy,
                temporal_weight=1.0, remap_luminance=False)
    serial = video_analogy(a, ap, frames, AnalogyParams(**base))
    sharded = video_analogy(
        a, ap, frames, AnalogyParams(data_shards=2, db_shards=2, **base))
    assert len(sharded.frames) == len(serial.frames)
    for t, (fs, fr) in enumerate(zip(sharded.frames_y, serial.frames_y)):
        np.testing.assert_allclose(fs, fr, atol=1e-5,
                                   err_msg=f"frame {t} diverged")
    # the sharded run went through the mesh step for every level x phase
    mesh_recs = [s for s in sharded.stats if "mesh" in s]
    assert mesh_recs and all(s["mesh"] == {"data": 2, "db": 2}
                             for s in mesh_recs)


def test_sharded_video_pads_odd_frame_count():
    # 3 frames over data_shards=2: batch pads to 4, outputs drop the pad
    a, ap, _ = make_pair(18, 18, seed=3)
    frames = _frames(a, 3)
    base = dict(levels=1, kappa=2.0, backend="tpu", strategy="batched",
                temporal_weight=1.0, remap_luminance=False)
    serial = video_analogy(a, ap, frames, AnalogyParams(**base))
    sharded = video_analogy(
        a, ap, frames, AnalogyParams(data_shards=2, db_shards=1, **base))
    assert len(sharded.frames) == 3
    for fs, fr in zip(sharded.frames_y, serial.frames_y):
        np.testing.assert_allclose(fs, fr, atol=1e-5)


def test_sharded_video_does_not_retrace():
    """Two identical-shape calls must reuse the cached shard_map'd jit
    (round-1 VERDICT weak item 2: per-call jax.jit re-tracing)."""
    from image_analogies_tpu.parallel.mesh import make_mesh
    from image_analogies_tpu.parallel.step import _cached_multichip_step

    a, ap, _ = make_pair(16, 16, seed=4)
    frames = _frames(a, 2)
    p = AnalogyParams(levels=1, kappa=2.0, backend="tpu", strategy="batched",
                      temporal_weight=1.0, remap_luminance=False,
                      data_shards=2, db_shards=2)
    video_analogy(a, ap, frames, p)
    mesh = make_mesh(db_shards=2, data_shards=2)
    # args must match the production call EXACTLY (lru_cache keys on the
    # literal argument tuple — omitted defaults are a different key)
    step = _cached_multichip_step(mesh, "batched", True,
                                  jax.lax.Precision.DEFAULT, False, False,
                                  False, False)
    before = step._cache_size()
    assert before > 0  # the run above used this cached jit
    video_analogy(a, ap, frames, p)
    assert step._cache_size() == before  # no new traces for equal shapes


def test_sharded_video_matches_serial_with_remap():
    """With remap_luminance=True BOTH paths anchor the §3.4 remap on the
    clip's first frame (round-2 ADVICE item 3), so sharded == serial holds
    with remapping ON too — toggling data_shards must never change output."""
    a, ap, _ = make_pair(18, 18, seed=5)
    frames = _frames(a, 3)
    base = dict(levels=2, kappa=2.0, backend="tpu", strategy="wavefront",
                temporal_weight=1.0, remap_luminance=True)
    serial = video_analogy(a, ap, frames, AnalogyParams(**base))
    sharded = video_analogy(
        a, ap, frames, AnalogyParams(data_shards=2, db_shards=2, **base))
    assert len(sharded.frames) == 3
    for t, (fs, fr) in enumerate(zip(sharded.frames_y, serial.frames_y)):
        np.testing.assert_allclose(fs, fr, atol=1e-5,
                                   err_msg=f"frame {t} diverged (remap on)")


def test_sequential_scheme_rejects_data_shards():
    a, ap, _ = make_pair(16, 16, seed=6)
    with pytest.raises(ValueError, match="two_phase"):
        video_analogy(a, ap, _frames(a, 2),
                      AnalogyParams(data_shards=2, temporal_weight=1.0),
                      scheme="sequential")


def test_sharded_video_checkpoint_kill_resume(tmp_path):
    """§5.4 on the mesh path (round-3 VERDICT weak item 4): kill the run
    after the coarse level (injected fault, no retries), then resume —
    the resumed run must (a) reload the completed coarser level from disk
    and (b) produce BIT-EQUAL frames to an uninterrupted run."""
    import json

    from image_analogies_tpu.utils import failure

    a, ap, _ = make_pair(20, 20, seed=4)
    frames = _frames(a, 2)
    log = str(tmp_path / "log.jsonl")
    base = AnalogyParams(
        levels=2, kappa=2.0, backend="tpu", strategy="wavefront",
        temporal_weight=1.0, remap_luminance=False, data_shards=2,
        checkpoint_dir=str(tmp_path / "ck"), log_path=log)

    ref = video_analogy(a, ap, frames, base)  # uninterrupted

    ck2 = base.replace(checkpoint_dir=str(tmp_path / "ck2"))
    # phase 1 of 2 levels: fault the SECOND wrapped level call (finest),
    # after the coarse level's checkpoint hit disk
    failure.inject_failures(0)
    try:
        failure._INJECT["n"] = 0
        import image_analogies_tpu.utils.failure as f2

        calls = {"n": 0}
        orig = f2.run_with_retry

        def dying(fn, **kw):
            calls["n"] += 1
            if calls["n"] == 2:
                raise f2.InjectedFailure("killed after coarse level")
            return orig(fn, **kw)

        f2.run_with_retry = dying
        try:
            with pytest.raises(f2.InjectedFailure):
                video_analogy(a, ap, frames, ck2)
        finally:
            f2.run_with_retry = orig
    finally:
        failure.inject_failures(0)
    # the coarse level's checkpoint must exist, the finest's must not
    import os

    assert os.path.exists(str(tmp_path / "ck2" / "phase1" / "level_01.npz"))
    assert not os.path.exists(
        str(tmp_path / "ck2" / "phase1" / "level_00.npz"))

    res = video_analogy(a, ap, frames, ck2.replace(resume_from_level=0))
    for t, (fr, fx) in enumerate(zip(res.frames_y, ref.frames_y)):
        np.testing.assert_array_equal(fr, fx,
                                      err_msg=f"frame {t} not bit-equal")
    events = [json.loads(line) for line in open(log)]
    assert any(e.get("event") == "resume_level" and e.get("phase") == "phase1"
               for e in events)


def test_sharded_video_stale_checkpoint_not_resumed(tmp_path):
    """A checkpoint from a different clip config (kappa changed) must be
    recomputed, not silently resumed (digest mismatch)."""
    a, ap, _ = make_pair(18, 18, seed=5)
    frames = _frames(a, 2)
    base = AnalogyParams(
        levels=2, kappa=2.0, backend="tpu", strategy="wavefront",
        temporal_weight=1.0, remap_luminance=False, data_shards=2,
        checkpoint_dir=str(tmp_path / "ck"))
    video_analogy(a, ap, frames, base)
    # same dir, different kappa: resume must miss and recompute cleanly
    changed = base.replace(kappa=5.0, resume_from_level=0)
    ref = video_analogy(a, ap, frames, base.replace(
        kappa=5.0, checkpoint_dir=None))
    res = video_analogy(a, ap, frames, changed)
    for fr, fx in zip(res.frames_y, ref.frames_y):
        np.testing.assert_array_equal(fr, fx)
