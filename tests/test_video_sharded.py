"""Production multi-chip video path (round-1 VERDICT items 3 + 7).

`video_analogy(..., data_shards>1)` must dispatch frames through the
('data','db') mesh step (`parallel/step.py`) and produce the SAME frames as
the serial two_phase path (with `remap_luminance=False`; the sharded path
remaps against the first frame by design — see models/video.py docstring),
without re-jitting the shard_map per call.

Runs on the 8-device virtual CPU mesh from conftest.
"""

import numpy as np
import pytest

import jax

from tests.conftest import make_pair
from image_analogies_tpu.config import AnalogyParams
from image_analogies_tpu.models.video import video_analogy


def _frames(a, n=4):
    rng = np.random.default_rng(1)
    return [np.clip(np.roll(a, t, axis=1)
                    + 0.01 * rng.standard_normal(a.shape), 0, 1)
            .astype(np.float32) for t in range(n)]


@pytest.mark.parametrize("strategy", ["batched", "wavefront"])
def test_sharded_video_matches_serial(strategy):
    a, ap, _ = make_pair(20, 20, seed=2)
    frames = _frames(a, 4)
    base = dict(levels=2, kappa=2.0, backend="tpu", strategy=strategy,
                temporal_weight=1.0, remap_luminance=False)
    serial = video_analogy(a, ap, frames, AnalogyParams(**base))
    sharded = video_analogy(
        a, ap, frames, AnalogyParams(data_shards=2, db_shards=2, **base))
    assert len(sharded.frames) == len(serial.frames)
    for t, (fs, fr) in enumerate(zip(sharded.frames_y, serial.frames_y)):
        np.testing.assert_allclose(fs, fr, atol=1e-5,
                                   err_msg=f"frame {t} diverged")
    # the sharded run went through the mesh step for every level x phase
    mesh_recs = [s for s in sharded.stats if "mesh" in s]
    assert mesh_recs and all(s["mesh"] == {"data": 2, "db": 2}
                             for s in mesh_recs)


def test_sharded_video_pads_odd_frame_count():
    # 3 frames over data_shards=2: batch pads to 4, outputs drop the pad
    a, ap, _ = make_pair(18, 18, seed=3)
    frames = _frames(a, 3)
    base = dict(levels=1, kappa=2.0, backend="tpu", strategy="batched",
                temporal_weight=1.0, remap_luminance=False)
    serial = video_analogy(a, ap, frames, AnalogyParams(**base))
    sharded = video_analogy(
        a, ap, frames, AnalogyParams(data_shards=2, db_shards=1, **base))
    assert len(sharded.frames) == 3
    for fs, fr in zip(sharded.frames_y, serial.frames_y):
        np.testing.assert_allclose(fs, fr, atol=1e-5)


def test_sharded_video_does_not_retrace():
    """Two identical-shape calls must reuse the cached shard_map'd jit
    (round-1 VERDICT weak item 2: per-call jax.jit re-tracing)."""
    from image_analogies_tpu.parallel.mesh import make_mesh
    from image_analogies_tpu.parallel.step import _cached_multichip_step

    a, ap, _ = make_pair(16, 16, seed=4)
    frames = _frames(a, 2)
    p = AnalogyParams(levels=1, kappa=2.0, backend="tpu", strategy="batched",
                      temporal_weight=1.0, remap_luminance=False,
                      data_shards=2, db_shards=2)
    video_analogy(a, ap, frames, p)
    mesh = make_mesh(db_shards=2, data_shards=2)
    step = _cached_multichip_step(mesh, "batched", True,
                                  jax.lax.Precision.DEFAULT, False, False)
    before = step._cache_size()
    assert before > 0  # the run above used this cached jit
    video_analogy(a, ap, frames, p)
    assert step._cache_size() == before  # no new traces for equal shapes


def test_sharded_video_matches_serial_with_remap():
    """With remap_luminance=True BOTH paths anchor the §3.4 remap on the
    clip's first frame (round-2 ADVICE item 3), so sharded == serial holds
    with remapping ON too — toggling data_shards must never change output."""
    a, ap, _ = make_pair(18, 18, seed=5)
    frames = _frames(a, 3)
    base = dict(levels=2, kappa=2.0, backend="tpu", strategy="wavefront",
                temporal_weight=1.0, remap_luminance=True)
    serial = video_analogy(a, ap, frames, AnalogyParams(**base))
    sharded = video_analogy(
        a, ap, frames, AnalogyParams(data_shards=2, db_shards=2, **base))
    assert len(sharded.frames) == 3
    for t, (fs, fr) in enumerate(zip(sharded.frames_y, serial.frames_y)):
        np.testing.assert_allclose(fs, fr, atol=1e-5,
                                   err_msg=f"frame {t} diverged (remap on)")


def test_sequential_scheme_rejects_data_shards():
    a, ap, _ = make_pair(16, 16, seed=6)
    with pytest.raises(ValueError, match="two_phase"):
        video_analogy(a, ap, _frames(a, 2),
                      AnalogyParams(data_shards=2, temporal_weight=1.0),
                      scheme="sequential")
