"""Temporal observability plane (ISSUE 14): windowed time-series store,
cross-hop trace propagation, and the `ia top` cockpit.

Locked here:

- Histogram.merge == union-of-samples (empty/one-sample edge cases) and
  the from_summary round-trip the timeline's window folding relies on;
- Timeline windowing under a fake clock: counter deltas (with the
  generation-reset rule), gauge last-value, per-window histogram
  percentiles, and the 1s -> 10s downsampling cascade;
- the EWMA/MAD anomaly detector: a spike past warmup raises a hint,
  bumps obs.anomaly.* through the ambient scope, and surfaces as an
  advisory without dragging the baseline;
- the DISARMED module plane allocates nothing (tracemalloc, same
  contract as the disabled metrics registry);
- X-IA-Trace header parse/format round-trip + the IAT1 wire context
  frame's strict validation;
- cross-process stitching acceptance: one POSTed X-IA-Trace id spans
  router + worker records written from two ISOLATED worker registries,
  and `ia trace` re-homes the whole chain onto a single per-trace
  track;
- /timeline over the serve front end (tier select, 400/404 contracts)
  with the obs.scrape.* self-report counters visible in /metrics;
- blackbox dumps fold the ambient request context (explicit extra
  wins);
- `ia bench --check` gates timeline_overhead_pct in absolute points
  (legacy archives record-only), and `ia top --once` renders the
  cockpit from a live server and exits 0.
"""

import dataclasses
import gc
import json
import os
import threading
import time
import tracemalloc
import urllib.error
import urllib.request

import pytest

from image_analogies_tpu.chaos import drills
from image_analogies_tpu.config import AnalogyParams
from image_analogies_tpu.obs import live as obs_live
from image_analogies_tpu.obs import metrics as obs_metrics
from image_analogies_tpu.obs import timeline as obs_timeline
from image_analogies_tpu.obs import trace as obs_trace
from image_analogies_tpu.obs.metrics import Histogram
from image_analogies_tpu.obs.timeline import Timeline
from tests.conftest import make_pair


def _params(**kw):
    kw.setdefault("levels", 2)
    kw.setdefault("backend", "cpu")
    kw.setdefault("metrics", True)
    return AnalogyParams(**kw)


def _snap(counters=None, gauges=None, histograms=None):
    return {"counters": counters or {}, "gauges": gauges or {},
            "histograms": histograms or {}}


# ------------------------------------------------ histogram merge


def test_histogram_merge_is_union_of_samples():
    """Acceptance satellite: merging two histograms is indistinguishable
    from observing the union of their samples — count/sum/min/max/
    buckets/percentiles all agree."""
    sa, sb = [0.5, 3.0, 100.0, 0.0], [7.0, 3.5]
    ha, hb, hu = Histogram(), Histogram(), Histogram()
    for v in sa:
        ha.observe(v)
        hu.observe(v)
    for v in sb:
        hb.observe(v)
        hu.observe(v)
    ha.merge(hb)
    assert ha.summary() == hu.summary()
    assert ha.percentile(50) == hu.percentile(50)
    assert ha.percentile(95) == hu.percentile(95)

    # empty other: a no-op (its inf/-inf extremes must not leak in)
    h = Histogram()
    h.observe(1.0)
    before = h.summary()
    h.merge(Histogram())
    assert h.summary() == before

    # empty self absorbs the other wholesale
    h2 = Histogram()
    h2.merge(hb)
    assert h2.summary() == hb.summary()

    # empty + empty stays empty (and keeps the legacy summary shape)
    e = Histogram()
    e.merge(Histogram())
    assert e.summary() == {"count": 0, "sum": 0.0, "min": 0.0,
                           "max": 0.0, "mean": 0.0}

    # single-sample merge
    h3, h4 = Histogram(), Histogram()
    h3.observe(7.0)
    h4.merge(h3)
    assert h4.percentile(50) == 7.0 and h4.count == 1


def test_histogram_from_summary_roundtrip():
    h = Histogram()
    for v in (0.5, 3.0, 100.0):
        h.observe(v)
    assert Histogram.from_summary(h.summary()).summary() == h.summary()
    # empty summary (no buckets key) -> empty histogram
    back = Histogram.from_summary(Histogram().summary())
    assert back.count == 0 and back.summary()["count"] == 0


# ------------------------------------------------ timeline windowing


def test_counter_delta_gauge_last_and_generation_reset():
    clk = {"t": 1000.2}
    tl = Timeline(tiers=((1.0, 120), (10.0, 90)),
                  clock=lambda: clk["t"])
    tl.sample_snapshot(_snap(counters={"serve.completed": 5.0},
                             gauges={"serve.queue_depth": 3.0}))
    clk["t"] = 1001.1
    tl.sample_snapshot(_snap(counters={"serve.completed": 9.0},
                             gauges={"serve.queue_depth": 1.0}))
    clk["t"] = 1001.6  # same window: gauge overwrites, delta accumulates
    tl.sample_snapshot(_snap(counters={"serve.completed": 10.0},
                             gauges={"serve.queue_depth": 7.0}))
    # a replacement worker restarts its registry: v < prev means the
    # whole value is this window's delta, never a negative
    clk["t"] = 1002.5
    tl.sample_snapshot(_snap(counters={"serve.completed": 2.0}))
    assert tl.range("serve.completed") == [
        (1000.0, 5.0), (1001.0, 5.0), (1002.0, 2.0)]
    assert tl.range("serve.queue_depth") == [(1000.0, 3.0), (1001.0, 7.0)]
    # worker labels namespace the same metric into distinct series
    tl.sample_snapshot(_snap(counters={"serve.completed": 4.0}),
                       worker="w1")
    assert tl.range("w1:serve.completed") == [(1002.0, 4.0)]


def test_histogram_windows_have_per_window_percentiles():
    clk = {"t": 2000.0}
    tl = Timeline(tiers=((1.0, 120),), clock=lambda: clk["t"])
    h = Histogram()
    for v in (10.0, 12.0):
        h.observe(v)
    tl.sample_snapshot(_snap(histograms={"serve.latency_ms": h.summary()}))
    # next window: cumulative summary grows by two much-slower samples;
    # the window must show ONLY the new ones
    clk["t"] = 2001.0
    for v in (100.0, 120.0):
        h.observe(v)
    tl.sample_snapshot(_snap(histograms={"serve.latency_ms": h.summary()}))
    pts = tl.range("serve.latency_ms")
    assert [p[0] for p in pts] == [2000.0, 2001.0]
    assert pts[0][1]["count"] == 2 and pts[0][1]["mean"] == 11.0
    assert pts[1][1]["count"] == 2 and pts[1][1]["mean"] == 110.0
    assert pts[1][1]["p50"] >= 64.0  # window p50, not lifetime


def test_downsampling_cascade_folds_closed_windows():
    clk = {"t": 0.5}
    tl = Timeline(tiers=((1.0, 120), (10.0, 90), (60.0, 60)),
                  clock=lambda: clk["t"])
    h = Histogram()
    total = 0.0
    for i in range(10):
        clk["t"] = i + 0.5
        total += 2.0
        h.observe(float(i + 1))
        tl.sample_snapshot(_snap(counters={"serve.completed": total},
                                 gauges={"serve.queue_depth": float(i)},
                                 histograms={"serve.latency_ms":
                                             h.summary()}))
    clk["t"] = 12.0  # every tier-0 window of [0, 10) is now closed
    pts = tl.range("serve.completed", window_s=10.0)
    assert pts == [(0.0, 20.0)]  # counter deltas ADD across the fold
    gpts = tl.range("serve.queue_depth", window_s=10.0)
    assert gpts == [(0.0, 9.0)]  # gauge: last closed window's value
    hpts = tl.range("serve.latency_ms", window_s=10.0)
    assert hpts[0][1]["count"] == 10  # histograms merge across the fold
    assert hpts[0][1]["sum"] == pytest.approx(55.0)
    # unknown tier -> KeyError (the /timeline 404 contract)
    with pytest.raises(KeyError):
        tl.range("serve.completed", window_s=7.0)
    # to_json carries tier geometry + series kinds
    doc = tl.to_json(10.0)
    assert doc["armed"] is True and doc["window_s"] == 10.0
    assert doc["series"]["serve.completed"]["kind"] == "counter"
    assert [t["window_s"] for t in doc["tiers"]] == [1.0, 10.0, 60.0]


# ------------------------------------------------ anomaly detection


def test_anomaly_detector_flags_spike_and_keeps_baseline():
    clk = {"t": 0.5}
    tl = Timeline(tiers=((1.0, 120),), clock=lambda: clk["t"],
                  warmup=4, z_threshold=4.0)
    scope = obs_metrics.ObsScope(scope_id="det")
    with obs_metrics.scope_active(scope):
        # alternating steady values give the MAD a small nonzero floor
        for i in range(10):
            clk["t"] = i + 0.5
            tl.sample_snapshot(_snap(
                gauges={"serve.queue_depth": 5.0 + 0.2 * (i % 2)}))
        clk["t"] = 10.5  # closes the last steady window
        tl.sample_snapshot(_snap(gauges={"serve.queue_depth": 50.0}))
        clk["t"] = 11.5  # closes the spike window -> detection fires
        tl.sample_snapshot(_snap(gauges={"serve.queue_depth": 5.0}))
        doc = tl.to_json()
    hints = [h for h in doc["anomalies"]
             if h["series"] == "serve.queue_depth"]
    assert len(hints) == 1
    assert hints[0]["value"] == 50.0 and hints[0]["z"] > 4.0
    assert hints[0]["baseline"] == pytest.approx(5.1, abs=0.2)
    # the outlier bumped the ambient scope's counters
    assert scope.registry.counter("obs.anomaly.total") == 1
    assert scope.registry.counter(
        "obs.anomaly.serve.queue_depth") == 1
    # advisory: fresh hint -> degrade_hint dict; stale hint -> None
    adv = tl.advisory()
    assert adv is not None and adv["degrade_hint"] is True
    clk["t"] = 1000.0
    assert tl.advisory() is None
    # non-latency/queue series never detect
    assert not any(h["series"] == "serve.completed"
                   for h in doc["anomalies"])


# ------------------------------------------------ disarmed fast path


def test_disarmed_timeline_plane_allocates_nothing():
    """Acceptance: with the plane disarmed, sample_snapshot and
    sample_ambient are one module-bool read — no steady-state
    allocations attributable to obs/ (same tracemalloc lock as the
    disabled metrics registry)."""
    assert obs_timeline.current() is None
    snap = _snap(counters={"x": 1.0})
    # a cyclic-GC pass triggered mid-loop runs earlier tests' finalizers
    # with OUR frame innermost, mis-attributing their tiny allocations
    # to obs/ — collect first, then keep the collector out of the window
    gc.collect()
    gc.disable()
    tracemalloc.start()
    try:
        for _ in range(2000):
            obs_timeline.sample_snapshot(snap)
            obs_timeline.sample_ambient()
        taken = tracemalloc.take_snapshot()
    finally:
        tracemalloc.stop()
        gc.enable()
    obs_allocs = [t for t in taken.traces
                  if any("image_analogies_tpu/obs/" in fr.filename
                         for fr in t.traceback)]
    # The interpreter may keep a couple hundred bytes of per-function
    # internal state live and attribute it to the `def` line (seen only
    # when certain earlier tests ran in-process). That noise is bounded;
    # a disarmed fast path that actually allocated and retained would
    # leave thousands of live traces after 2000 calls — allow at most a
    # handful of tiny ones.
    assert len(obs_allocs) <= 8
    assert sum(t.size for t in obs_allocs) <= 1024
    # disarmed /timeline document says so instead of erroring
    assert obs_timeline.snapshot_json() == {"armed": False, "series": {},
                                            "anomalies": []}


def test_arm_nests_and_last_disarm_clears():
    t1 = obs_timeline.arm()
    t2 = obs_timeline.arm()
    assert t1 is t2 and obs_timeline.current() is t1
    obs_timeline.disarm()
    assert obs_timeline.current() is t1  # still held by the first owner
    obs_timeline.disarm()
    assert obs_timeline.current() is None


# ------------------------------------------------ trace header + wire frame


def test_trace_header_parse_and_format_roundtrip():
    parse = obs_trace.parse_trace_header
    assert parse("cafe0123/http/r42") == {
        "trace": "cafe0123", "parent_span": "http",
        "origin_request": "r42"}
    assert parse("cafe0123/-/-") == {"trace": "cafe0123"}
    # no trace id -> no adoption, even with other fields present
    assert parse("-/http/r42") is None
    # malformed degrades to None, never an exception
    assert parse(None) is None
    assert parse("") is None
    assert parse("a/b") is None                   # wrong arity
    assert parse("bad$chars/-/-") is None         # charset violation
    assert parse("x" * 65 + "/-/-") is None       # token too long
    hdr = obs_trace.format_trace_header({"trace": "cafe0123"})
    assert hdr == "cafe0123/-/-"
    assert parse(hdr) == {"trace": "cafe0123"}
    # capture_trace reflects the ambient request context
    with obs_trace.request_context(trace="t1", parent_span="http",
                                   origin_request="r9"):
        assert obs_trace.capture_trace() == {
            "trace": "t1", "parent_span": "http", "origin_request": "r9"}
        assert obs_trace.format_trace_header() == "t1/http/r9"
    assert obs_trace.capture_trace() is None


def test_ensure_trace_mints_or_adopts():
    with obs_trace.ensure_trace("router_submit", origin_request="idem1"):
        ctx = obs_trace.context_attrs()
        assert ctx["parent_span"] == "router_submit"
        assert ctx["origin_request"] == "idem1"
        minted = ctx["trace"]
        assert obs_trace.parse_trace_header(f"{minted}/-/-") is not None
        # an inner ensure_trace ADOPTS the ambient id, never re-mints
        with obs_trace.ensure_trace("inner"):
            assert obs_trace.context_attrs()["trace"] == minted
    assert obs_trace.context_attrs() is None


def test_wire_context_frame_strict_roundtrip():
    from image_analogies_tpu.serve import wire

    ctx = {"trace": "cafe0123", "parent_span": "http",
           "origin_request": "r42"}
    frame = wire.encode_context(ctx)
    assert frame.startswith(wire.CONTEXT_MAGIC)
    assert wire.decode_context(frame) == ctx
    assert wire.decode_context(wire.encode_context({})) == {}
    with pytest.raises(wire.WireError):
        wire.decode_context(b"IAXX" + frame[4:])      # bad magic
    with pytest.raises(wire.WireError):
        wire.decode_context(frame[:-1])               # truncated
    with pytest.raises(wire.WireError):
        wire.decode_context(frame + b"x")             # trailing bytes
    with pytest.raises(wire.WireError):
        wire.encode_context({"k": 7})                 # non-str value
    with pytest.raises(wire.WireError):
        wire.encode_context({"k": "v" * (wire.MAX_CONTEXT + 1)})


# ------------------------------------------------ cockpit rendering


def test_cockpit_rows_and_render():
    doc = {"armed": True, "window_s": 1.0, "series": {
        "w0:serve.completed": {"kind": "counter",
                               "points": [[0.0, 4.0]]},
        "w0:serve.latency_ms": {"kind": "hist", "points": [
            [0.0, {"count": 4, "p50": 10.0, "p95": 20.0}]]},
        "w0:serve.queue_depth": {"kind": "gauge", "points": [[0.0, 3]]},
        "w0:serve.breaker.state.cpu": {"kind": "gauge",
                                       "points": [[0.0, 2]]},
        "w0:hbm.peak_bytes.d0": {"kind": "gauge",
                                 "points": [[0.0, float(2 << 20)]]},
        "serve.queue_depth": {"kind": "gauge", "points": [[0.0, 1]]},
    }, "anomalies": [{"series": "w0:serve.latency_ms", "value": 50.0,
                      "baseline": 10.0, "z": 9.0, "window_start": 0.0}]}
    rows = obs_timeline.cockpit_rows(doc)
    assert [r["worker"] for r in rows] == ["-", "w0"]
    w0 = rows[1]
    assert w0["qps"] == 4.0
    assert w0["p50"] == 10.0 and w0["p95"] == 20.0
    assert w0["queue"] == 3 and w0["breaker"] == "OPEN"
    assert w0["hbm"] == float(2 << 20) and w0["anomalies"] == 1
    text = obs_timeline.render_cockpit(doc)
    assert "WORKER" in text and "QPS" in text and "P95ms" in text
    assert "OPEN" in text and "2.0M" in text
    assert "! anomaly w0:serve.latency_ms" in text
    # disarmed doc renders the banner, not a crash
    off = obs_timeline.render_cockpit({"armed": False, "series": {},
                                       "anomalies": []})
    assert "[timeline disarmed]" in off and "(no series yet)" in off


# ------------------------------------------------ blackbox context fold


def test_blackbox_dump_folds_request_context(tmp_path):
    """Satellite: dump_current folds the ambient request context
    (request id, trace id, batch key) into the sealed dump; explicit
    extra keys win on collision."""
    from image_analogies_tpu.obs import recorder as obs_recorder

    scope = obs_metrics.ObsScope(scope_id="w7.g0")
    scope.dump_dir = str(tmp_path)
    with obs_trace.run_scope(_params()), obs_metrics.scope_active(scope):
        with obs_trace.request_context(request=7, trace="cafe0123",
                                       key="k1"):
            path = obs_recorder.dump_current(
                "process_death", extra={"batch_size": 2,
                                        "key": "explicit-wins"})
    doc = obs_recorder.load_dump(path)
    assert doc["extra"]["request"] == 7
    assert doc["extra"]["trace"] == "cafe0123"
    assert doc["extra"]["batch_size"] == 2
    assert doc["extra"]["key"] == "explicit-wins"


# ------------------------------------------------ serve front end


def test_serve_http_timeline_endpoint_and_scrape_counters(tmp_path):
    """/timeline serves the armed document (tier select via ?window=,
    400 on garbage, 404 on an unknown tier), and both scrape endpoints
    self-report under obs.scrape.* — visible in the NEXT /metrics
    scrape."""
    from image_analogies_tpu.serve import Server
    from image_analogies_tpu.serve.http import serve_http

    a, ap, b = make_pair(10, 10, seed=40)
    tl = obs_timeline.arm()
    try:
        with Server(drills.serve_config(workers=1)) as srv:
            assert srv.request(a, ap, b, timeout=120).status == "ok"
            srv.refresh_gauges()
            tl.sample_snapshot(obs_metrics.snapshot() or {}, worker="w0")
            httpd = serve_http(srv, 0)
            t = threading.Thread(target=httpd.serve_forever, daemon=True)
            t.start()
            try:
                base = f"http://127.0.0.1:{httpd.server_address[1]}"
                with urllib.request.urlopen(base + "/timeline") as r:
                    assert r.headers["Content-Type"] == "application/json"
                    doc = json.load(r)
                assert doc["armed"] is True
                assert "w0:serve.completed" in doc["series"]
                with urllib.request.urlopen(
                        base + "/timeline?window=10") as r:
                    assert json.load(r)["window_s"] == 10.0
                with pytest.raises(urllib.error.HTTPError) as e404:
                    urllib.request.urlopen(base + "/timeline?window=7")
                assert e404.value.code == 404
                assert json.loads(
                    e404.value.read())["error"] == "unknown_window"
                with pytest.raises(urllib.error.HTTPError) as e400:
                    urllib.request.urlopen(base + "/timeline?window=abc")
                assert e400.value.code == 400
                assert json.loads(
                    e400.value.read())["error"] == "bad_window"
                # meta-observability: every scrape bumps its own total
                # BEFORE rendering, so this scrape sees itself
                urllib.request.urlopen(base + "/metrics").read()
                text = urllib.request.urlopen(
                    base + "/metrics").read().decode()
            finally:
                httpd.shutdown()
            # durations land in the handler's finally AFTER the reply is
            # on the wire, so read them from the registry (with a short
            # grace for the last handler thread) rather than the body
            deadline = time.monotonic() + 5.0
            while time.monotonic() < deadline:
                hists = obs_metrics.snapshot()["histograms"]
                if (hists.get("obs.scrape.metrics.duration_ms",
                              {}).get("count", 0) >= 2
                        and hists.get("obs.scrape.timeline.duration_ms",
                                      {}).get("count", 0) >= 4):
                    break
                time.sleep(0.02)
    finally:
        obs_timeline.disarm()
    # 4 timeline GETs above; this is the second /metrics scrape
    assert "ia_obs_scrape_timeline_total_total 4" in text
    assert "ia_obs_scrape_metrics_total_total 2" in text
    assert hists["obs.scrape.metrics.duration_ms"]["count"] == 2
    assert hists["obs.scrape.timeline.duration_ms"]["count"] == 4


def test_live_http_server_timeline_route():
    """obs/live.py's sidecar exposition server (ia run --metrics-port)
    grows the same /timeline route + scrape self-report."""
    tl = obs_timeline.arm()
    try:
        tl.sample_snapshot(_snap(counters={"level.steps": 3.0}))
        # run_scope installs the PROCESS-default scope, so the sidecar's
        # handler threads resolve it for the obs.scrape.* counters
        with obs_trace.run_scope(_params()):
            httpd = obs_live.start_http_server(0)
            try:
                base = f"http://127.0.0.1:{httpd.server_address[1]}"
                with urllib.request.urlopen(base + "/timeline") as r:
                    doc = json.load(r)
                assert doc["armed"] is True
                assert "level.steps" in doc["series"]
                with pytest.raises(urllib.error.HTTPError) as e400:
                    urllib.request.urlopen(base + "/timeline?window=abc")
                assert e400.value.code == 400
            finally:
                obs_live.stop_http_server(httpd)
            counters = obs_metrics.snapshot()["counters"]
    finally:
        obs_timeline.disarm()
    assert counters["obs.scrape.timeline.total"] == 2
    assert counters["obs.scrape.timeline.errors"] == 1
    assert counters["obs.scrape.errors"] == 1


# ------------------------------------------------ cross-process stitching


def test_stitched_trace_across_two_isolated_registries(tmp_path):
    """Tentpole acceptance: a client-sent X-IA-Trace id survives the
    HTTP hop, the router, the IAF2 forward, and the worker thread — the
    fleet's workers write through two ISOLATED ObsScope registries, yet
    every record of the request carries one trace id, and `ia trace`
    renders the chain as a single per-trace track."""
    from image_analogies_tpu.obs import export as obs_export
    from image_analogies_tpu.obs import report as obs_report
    from image_analogies_tpu.serve.fleet import Fleet
    from image_analogies_tpu.serve.http import serve_fleet_http
    from image_analogies_tpu.serve.types import FleetConfig

    log = str(tmp_path / "fleet.jsonl")
    scfg = drills.serve_config(workers=1, max_batch=2,
                               batch_window_ms=5.0)
    scfg = dataclasses.replace(
        scfg, params=scfg.params.replace(log_path=log))
    fcfg = FleetConfig(serve=scfg, size=2, vnodes=16,
                       journal_root=str(tmp_path / "journals"),
                       health_interval_s=0.05,
                       backoff_s=0.01, backoff_cap_s=0.05)
    a, ap, b = make_pair(8, 8, seed=41)
    with Fleet(fcfg) as fl:
        regs = {id(h.scope.registry) for h in fl.workers.values()}
        assert len(regs) == 2  # the registries really are isolated
        httpd = serve_fleet_http(fl, 0)
        t = threading.Thread(target=httpd.serve_forever, daemon=True)
        t.start()
        try:
            base = f"http://127.0.0.1:{httpd.server_address[1]}"
            body = json.dumps({"a": a.tolist(), "ap": ap.tolist(),
                               "b": b.tolist()}).encode()
            req = urllib.request.Request(
                base + "/v1/analogy", data=body,
                headers={"Content-Type": "application/json",
                         "X-IA-Trace": "cafe0123/client/r42"})
            with urllib.request.urlopen(req, timeout=120) as r:
                echoed = r.headers.get("X-IA-Trace")
                resp = json.load(r)
        finally:
            httpd.shutdown()
    assert resp["status"] == "ok"
    # the id is echoed to the client in body and header alike
    assert resp["trace"] == "cafe0123"
    assert echoed.split("/")[0] == "cafe0123"

    recs = [json.loads(line) for line in open(log)]
    chain = [r for r in recs if r.get("trace") == "cafe0123"]
    events = {r.get("event") for r in chain}
    span_names = {r.get("name") for r in chain if r.get("event") == "span"}
    assert "router_route" in events         # router hop stitched
    assert "serve_request" in events        # worker completion stitched
    assert "serve_dispatch" in span_names   # worker dispatch stitched
    assert span_names & {"level", "batch_level"}  # ENGINE spans stitched

    # `ia report` groups the journey under one traces entry
    an = obs_report.analyze(recs)
    ours = [t for t in (an["traces"] or [])
            if t["trace"] == "cafe0123"]
    assert len(ours) == 1
    assert ours[0]["spans"] >= 2
    assert "router_route" in ours[0]["events"]
    assert "traces:" in obs_report.render(an)

    # `ia trace` re-homes the whole chain onto ONE per-trace track
    out = str(tmp_path / "trace.json")
    obs_export.export_trace(log, out)
    tr = json.load(open(out))
    track_names = {e["args"]["name"] for e in tr["traceEvents"]
                   if e["ph"] == "M" and e["name"] == "thread_name"}
    assert "trace cafe0123" in track_names
    tids = {e["tid"] for e in tr["traceEvents"] if e["ph"] != "M"
            and e.get("args", {}).get("trace") == "cafe0123"}
    assert len(tids) == 1 and tids.pop() >= obs_export.TRACE_TID_BASE


# ------------------------------------------------ bench rider


def test_bench_check_gates_timeline_overhead():
    """Satellite: timeline_overhead_pct rides the bench trajectory —
    extract_headline propagates it, check_regression gates it in
    absolute percentage points, and legacy archives record-only."""
    import importlib.util

    spec = importlib.util.spec_from_file_location(
        "ia_bench_timeline_test", os.path.join(
            os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
            "bench.py"))
    bench = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(bench)

    doc = {"parsed": {"value": 7.5, "metric": "1024x1024 north star",
                      "timeline_overhead_pct": 1.5}}
    assert bench.extract_headline(doc)["timeline_overhead_pct"] == 1.5

    trajectory = {"points": [
        {"value": 7.0, "metric_key": "1024x1024", "round": 1,
         "file": "BENCH_r01.json", "timeline_overhead_pct": 1.0},
        {"value": 7.2, "metric_key": "1024x1024", "round": 2,
         "file": "BENCH_r02.json", "timeline_overhead_pct": 2.0},
    ], "problems": []}
    ok = bench.check_regression(trajectory, fresh_value=7.1,
                                fresh_timeline=2.5, threshold_pct=20.0)
    assert ok["ok"] and ok["timeline_overhead_pct"] == 2.5
    assert ok["timeline_overhead_floor"] == 1.0
    assert ok["timeline_overhead_delta_pts"] == 1.5
    bad = bench.check_regression(trajectory, fresh_value=7.1,
                                 fresh_timeline=30.0, threshold_pct=20.0)
    assert not bad["ok"]
    assert any("timeline_overhead_pct" in p for p in bad["problems"])
    # archive self-check reads the latest point's own overhead
    latest = bench.check_regression(trajectory, threshold_pct=20.0)
    assert latest["timeline_overhead_pct"] == 2.0
    assert latest["timeline_overhead_floor"] == 1.0
    # legacy archive (no timeline points): record-only, never a gate
    legacy = {"points": [
        {"value": 7.0, "metric_key": "1024x1024", "round": 1,
         "file": "BENCH_r01.json"}], "problems": []}
    rec = bench.check_regression(legacy, fresh_value=7.1,
                                 fresh_timeline=99.0, threshold_pct=20.0)
    assert rec["ok"] and rec["timeline_overhead_pct"] == 99.0
    assert rec["timeline_overhead_floor"] is None


def test_cli_bench_check_timeline_rider(tmp_path, capsys):
    from image_analogies_tpu.cli import main

    with open(tmp_path / "BENCH_r01.json", "w") as f:
        json.dump({"parsed": {"value": 7.0,
                              "metric": "1024x1024 north star",
                              "timeline_overhead_pct": 1.0}}, f)
    res = tmp_path / "result.json"
    with open(res, "w") as f:
        json.dump({"value": 7.1, "metric": "1024x1024 north star",
                   "timeline_overhead_pct": 2.5}, f)
    rc = main(["bench", "--check", "--result", str(res),
               "--dir", str(tmp_path)])
    assert rc == 0
    out = json.loads(capsys.readouterr().out)
    assert out["timeline_overhead_pct"] == 2.5
    assert out["timeline_overhead_floor"] == 1.0


# ------------------------------------------------ ia top


def test_ia_top_once_renders_live_cockpit(tmp_path, capsys):
    """Acceptance: `ia top --once` fetches a live server's /timeline and
    renders the QPS/p50/p95/queue/breaker/HBM/anomaly columns, exit 0."""
    from image_analogies_tpu.cli import main
    from image_analogies_tpu.serve import Server
    from image_analogies_tpu.serve.http import serve_http

    a, ap, b = make_pair(10, 10, seed=42)
    tl = obs_timeline.arm()
    try:
        with Server(drills.serve_config(workers=1)) as srv:
            assert srv.request(a, ap, b, timeout=120).status == "ok"
            srv.refresh_gauges()
            tl.sample_snapshot(obs_metrics.snapshot() or {}, worker="w0")
            httpd = serve_http(srv, 0)
            t = threading.Thread(target=httpd.serve_forever, daemon=True)
            t.start()
            try:
                base = f"http://127.0.0.1:{httpd.server_address[1]}"
                rc = main(["top", "--once", "--url", base])
            finally:
                httpd.shutdown()
    finally:
        obs_timeline.disarm()
    out = capsys.readouterr().out
    assert rc == 0
    for col in ("WORKER", "QPS", "P50ms", "P95ms", "QUEUE", "BREAKER",
                "HBM", "ANOM"):
        assert col in out
    assert "w0" in out  # the sampled worker's row rendered


def test_ia_top_once_unreachable_exits_2(capsys):
    from image_analogies_tpu.cli import main

    rc = main(["top", "--once", "--url", "http://127.0.0.1:1"])
    captured = capsys.readouterr()
    assert rc == 2
    assert "cannot fetch" in captured.err
