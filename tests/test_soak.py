"""Seeded trace-driven soak harness (ISSUE 20): `ia soak`.

Tier-1 invariants locked here:

- TraceSpec is a replayable artifact: to_dict/from_dict round-trips,
  unknown fields and malformed mixes are rejected at load, and the
  stream digest is bit-stable across replays while sensitive to the
  seed (same spec ⇒ byte-identical request stream);
- `loadgen.arrival_schedule` delegates to the spec's arrival model —
  the historic pinned offsets survive the delegation, so drills,
  selftests, and soaks can never drift onto parallel pacing code;
- ChaosPlan.validate_sites rejects unknown injection sites and
  `ia chaos --plan` / `TraceSpec` inline plans refuse them at load;
- the scaled-down smoke soak PASSES its full invariant gate on CPU
  with chaos armed throughout (worker kills, tier evictions, a torn
  archive segment, hop latency), twice, with identical verdicts;
- the gate FAILS LOUDLY on an unrecoverable fault plan: non-zero
  verdicts, a non-zero loss count, and a culprit idempotency key that
  `journal.reconstruct` (the `ia why` engine) can replay from the
  persisted workdir;
- the invariant evaluators are pure functions of the fact document
  (synthetic facts exercise each verdict without a fleet).

Every live-fleet test runs under a hard SIGALRM budget (the
test_transport.py idiom): a wedged fleet fails ONE test loudly instead
of eating the tier-1 budget.  The full-profile soak (240 requests, the
bench headline's own spec) rides `-m slow`.
"""

import json
import signal

import numpy as np
import pytest

from image_analogies_tpu.chaos.plan import KNOWN_SITES, ChaosPlan, SiteRule
from image_analogies_tpu.soak import driver as soak_driver
from image_analogies_tpu.soak import invariants as soak_invariants
from image_analogies_tpu.soak.trace import (TraceSpec, full_spec,
                                            smoke_spec)


@pytest.fixture(autouse=True)
def _hard_timeout():
    """Per-test wall-clock ceiling: a wedged fleet or a lost handoff
    raises here instead of hanging the suite."""

    def _boom(signum, frame):  # noqa: ARG001 - signal API
        from image_analogies_tpu.serve import transport
        transport.reap_orphans()
        raise TimeoutError("soak test exceeded its 180 s budget")

    old = signal.signal(signal.SIGALRM, _boom)
    signal.alarm(180)
    yield
    signal.alarm(0)
    signal.signal(signal.SIGALRM, old)


# ------------------------------------------------------- TraceSpec codec


def test_trace_spec_roundtrip_and_rejection(tmp_path):
    spec = smoke_spec(seed=11)
    again = TraceSpec.from_dict(json.loads(json.dumps(spec.to_dict())))
    assert again == spec

    path = tmp_path / "spec.json"
    path.write_text(json.dumps(spec.to_dict()))
    assert TraceSpec.load(str(path)) == spec

    with pytest.raises(ValueError, match="unknown trace spec field"):
        TraceSpec.from_dict({"requests": 4, "warp_factor": 9})
    with pytest.raises(ValueError, match="unknown session kind"):
        TraceSpec(sessions=(("streaming", 1.0),))
    with pytest.raises(ValueError, match="unknown priority"):
        TraceSpec(priorities=(("vip", 1.0),))
    with pytest.raises(ValueError, match="flash crowd"):
        TraceSpec(flash_crowds=((0.0, 1.0, 0.5),))
    with pytest.raises(ValueError, match="diurnal_amplitude"):
        TraceSpec(diurnal_amplitude=1.5)
    with pytest.raises(ValueError, match="weight"):
        TraceSpec(sessions=(("oneshot", 0.0),))


def test_stream_digest_replayable_and_seed_sensitive():
    a, b = smoke_spec(seed=7), smoke_spec(seed=7)
    assert a.arrivals() == b.arrivals()
    assert a.stream_digest() == b.stream_digest()
    assert a.stream_digest() != smoke_spec(seed=8).stream_digest()
    # the diurnal + surge shaping actually shapes: the flash crowd
    # window compresses inter-arrival gaps relative to the base rate
    rates = [a.rate_at(t) for t in (0.0, 0.3)]
    assert rates[1] > rates[0] * 2


def test_arrival_schedule_delegates_to_trace_spec():
    from image_analogies_tpu.serve import loadgen

    sched = loadgen.arrival_schedule(50, t0=0.2, duration=1.0,
                                     mult=20.0, base_rps=30.0, seed=7)
    # pinned offsets from before the delegation: the shared arrival
    # model must reproduce the historic drill/bench pacing exactly
    assert [round(t, 6) for t in sched[:3]] == [
        0.00164, 0.054923, 0.058585]
    spec = TraceSpec(seed=7, requests=50, base_rps=30.0,
                     flash_crowds=((0.2, 1.0, 20.0),))
    assert sched == spec.arrivals()


# -------------------------------------------------- plan site validation


def test_validate_sites_rejects_unknown(tmp_path):
    good = ChaosPlan(seed=1, sites=(
        ("level.dispatch", SiteRule(kind="transient", p=0.5)),))
    assert good.validate_sites() is good

    bad = ChaosPlan(seed=1, sites=(
        ("level.dispatchh", SiteRule(kind="transient", p=0.5)),))
    with pytest.raises(ValueError, match="level.dispatchh"):
        bad.validate_sites()
    # a custom registry tightens the check the same way
    with pytest.raises(ValueError, match="level.dispatch"):
        good.validate_sites(known=("serve.dispatch",))

    # load() is the operator surface: a file plan with a typo'd site
    # refuses before any drill arms it...
    path = tmp_path / "plan.json"
    path.write_text(json.dumps(bad.to_dict()))
    with pytest.raises(ValueError, match="unknown injection site"):
        ChaosPlan.load(str(path))
    # ...and `ia chaos --plan` turns that into exit 2
    from image_analogies_tpu.cli import main
    assert main(["chaos", "--plan", str(path)]) == 2
    assert all(s in KNOWN_SITES for s in ("serve.dispatch",
                                          "devcache.tier",
                                          "archive.append"))


def test_soak_spec_inline_chaos_is_validated():
    spec = TraceSpec(requests=2, chaos={
        "seed": 1, "sites": {"no.such.site": {"kind": "transient",
                                              "p": 1.0}}})
    with pytest.raises(ValueError, match="no.such.site"):
        soak_driver.run(spec)


# ------------------------------------------------ invariant pure functions


def _facts(**kw):
    base = {"submitted": 4, "answered": 4, "rejected": {}, "errors": {},
            "journals": {}, "audit": {}, "resubmits": 1,
            "resubmit_identical": True, "kills": [], "handoffs": [],
            "sites": {}, "archive": {"quarantined": 0},
            "latencies_ms": [5.0, 6.0, 7.0, 8.0],
            "counters": {}}
    base.update(kw)
    return base


def _by_name(verdicts):
    return {v["name"]: v for v in verdicts}


def test_invariants_on_synthetic_facts():
    spec = TraceSpec(name="syn", seed=3, requests=4, audit=0)
    plan = soak_driver.default_plan(3)

    # clean shed is accounted, hard rejection + raw errors are loss
    assert soak_invariants.lost(_facts(
        answered=2, rejected={"queue_full": 2})) == 0
    assert soak_invariants.lost(_facts(
        answered=2, rejected={"poison": 1}, errors={"3": "Timeout"})) == 2

    v = _by_name(soak_invariants.evaluate(spec, plan, _facts(
        answered=3, errors={2: "TimeoutError"})))
    assert not v["zero_loss"]["ok"]
    assert v["zero_loss"]["culprit"] == "syn-3-2"

    v = _by_name(soak_invariants.evaluate(spec, plan, _facts(
        journals={"w0": {"poisoned": ["syn-3-1"], "segments": 1,
                         "compacted": {}}})))
    assert not v["no_poison"]["ok"]
    assert v["no_poison"]["culprit"] == "syn-3-1"

    v = _by_name(soak_invariants.evaluate(spec, plan, _facts(
        counters={"obs.ceiling.alarms": 1})))
    assert not v["no_ceiling_alarms"]["ok"]

    v = _by_name(soak_invariants.evaluate(spec, plan, _facts(
        journals={"w0": {"poisoned": [], "segments": 3,
                         "compacted": {}}})))
    assert not v["journal_bounded"]["ok"]

    v = _by_name(soak_invariants.evaluate(
        spec, plan, _facts(audit={0: "ok", 1: "mismatch"})))
    assert not v["bit_identity"]["ok"]
    assert v["bit_identity"]["culprit"] == "syn-3-1"

    # p99.9 over an empty run refuses to pass (None is not a bound)
    v = _by_name(soak_invariants.evaluate(
        spec, plan, _facts(latencies_ms=[], answered=0, submitted=0)))
    assert not v["p999_bound"]["ok"]


# --------------------------------------------------------- live soak gate


def _assert_green(res):
    report = soak_invariants.render(res)
    assert res["ok"], report
    return report


def test_smoke_soak_gate_passes_and_replays_identically():
    """The tier-1 soak: scaled-down spec, full methodology — chaos armed
    throughout, seeded kills, every invariant green, twice, with
    identical verdicts."""
    first = soak_driver.run(smoke_spec())
    report = _assert_green(first)
    assert "PASS" in report

    facts = first["facts"]
    # chaos was demonstrably armed the whole run: the acceptance
    # witness list all fired, and every seeded kill recovered
    assert len(facts["kills"]) >= 2
    assert len(facts["handoffs"]) >= len(facts["kills"])
    for site in soak_driver.REQUIRED_SITES:
        assert facts["sites"].get(site, {}).get("injected", 0) >= 1, \
            facts["sites"]
    assert facts["archive"]["quarantined"] >= 1
    assert first["loss"] == 0 and first["p999_ms"] is not None
    # the smoke kills one worker twice: its second replace finds a
    # multi-segment corpse and must actually compact it; every other
    # kill at least ran the decision
    autoc = facts["counters"].get("serve.journal.autocompact", 0)
    skipped = facts["counters"].get("serve.journal.autocompact_skipped",
                                    0)
    assert autoc >= 1
    assert autoc + skipped >= len(facts["kills"])
    # post-compaction, every worker journal is bounded to one segment
    assert all(doc["segments"] <= 1 for doc in facts["journals"].values())

    second = soak_driver.run(smoke_spec())
    _assert_green(second)
    assert [(v["name"], v["ok"]) for v in first["verdicts"]] \
        == [(v["name"], v["ok"]) for v in second["verdicts"]]


def test_soak_gate_fails_loudly_with_why_linkable_culprit(tmp_path):
    """An unrecoverable fault plan (every dispatch crashes, forever)
    must redden the gate — and the persisted workdir must let `ia why`
    reconstruct the culprit's causal chain."""
    from image_analogies_tpu.serve import journal as serve_journal

    spec = TraceSpec(name="hostile", seed=3, requests=6,
                     shapes=((12, 12),), base_rps=200.0,
                     sessions=(("oneshot", 1.0),), audit=2)
    plan = ChaosPlan(seed=3, sites=(
        ("serve.dispatch", SiteRule(kind="crash", p=1.0)),),
        name="hostile").validate_sites()
    workdir = tmp_path / "run"
    res = soak_driver.run(spec, workdir=str(workdir), plan=plan)

    assert not res["ok"]
    assert res["loss"] > 0
    failing = [v for v in res["verdicts"] if not v["ok"]]
    assert failing
    culprits = [v["culprit"] for v in res["verdicts"] if v.get("culprit")]
    assert culprits and all(c.startswith("hostile-3-") for c in culprits)
    # the red gate's evidence survived on disk, `ia why`-linkable
    root = res["facts"]["journal_root"]
    assert root and root.startswith(str(workdir))
    why = serve_journal.reconstruct(culprits[0], root)
    assert why["found"] and why["workers"]
    # the renderer names the culprit in the runbook form
    assert f"ia why {culprits[0]}" in soak_invariants.render(res)


def test_cli_soak_smoke(tmp_path, capsys):
    from image_analogies_tpu.cli import main

    rc = main(["soak", "--seed", "7", "--json"])
    captured = capsys.readouterr()
    assert rc == 0
    assert "ia soak: PASS" in captured.out
    doc = json.loads(captured.err)
    assert doc["ok"] and doc["workload"] == "soak"

    bad = tmp_path / "bad.json"
    bad.write_text(json.dumps({"requests": 4, "warp_factor": 9}))
    assert main(["soak", "--spec", str(bad)]) == 2
    assert main(["soak", "--spec", str(tmp_path / "missing.json")]) == 2


@pytest.mark.slow
def test_full_profile_soak_headlines():
    """The bench-profile spec end-to-end: the same run measure_soak
    records headlines from must be green at duration."""
    res = soak_driver.run(full_spec())
    _assert_green(res)
    assert res["loss"] == 0
    assert res["p999_ms"] is not None \
        and res["p999_ms"] <= full_spec().p999_bound_ms
    assert len(res["facts"]["kills"]) >= 4


# ------------------------------------------------- bench headline riders


def test_bench_check_gates_soak_headlines(tmp_path):
    import bench

    traj = {"points": [
        {"metric_key": "1024x1024", "value": 10.0, "file": "r1",
         "soak_p999_ms": 900.0, "soak_loss": 0},
    ], "problems": []}
    ok = bench.check_regression(traj, fresh_value=10.0,
                                fresh_soak_p999=950.0, fresh_soak_loss=0)
    assert ok["ok"] and ok["soak_p999_floor"] == 900.0

    red = bench.check_regression(traj, fresh_value=10.0,
                                 fresh_soak_p999=2000.0,
                                 fresh_soak_loss=0)
    assert not red["ok"]
    assert any("soak_p999_ms" in p for p in red["problems"])

    # loss gates ABSOLUTELY — any lost request fails without a floor
    lossy = bench.check_regression(traj, fresh_value=10.0,
                                   fresh_soak_p999=950.0,
                                   fresh_soak_loss=1)
    assert not lossy["ok"]
    assert any("soak_lost_requests" in p for p in lossy["problems"])

    # legacy archives carry no soak floor: record-only, never a gate
    legacy = {"points": [{"metric_key": "1024x1024", "value": 10.0,
                          "file": "r1"}], "problems": []}
    rec = bench.check_regression(legacy, fresh_value=10.0,
                                 fresh_soak_p999=950.0,
                                 fresh_soak_loss=0)
    assert rec["ok"] and rec["soak_p999_floor"] is None
    assert rec["soak_p999_ms"] == 950.0 and rec["soak_loss"] == 0
