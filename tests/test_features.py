"""Feature spec: layout, causal mask, edge clamping, weights, JAX twin
(SURVEY.md §4.2-4.3)."""

import numpy as np
import pytest

from image_analogies_tpu.config import AnalogyParams
from image_analogies_tpu.ops import features as F


def _spec(**kw):
    kw.setdefault("fine_size", 5)
    kw.setdefault("coarse_size", 3)
    kw.setdefault("has_coarse", True)
    kw.setdefault("src_channels", 1)
    return F.FeatureSpec(**kw)


def test_causal_mask_is_strict_raster_half():
    m = F.causal_mask(3).reshape(3, 3)
    expect = np.array([[1, 1, 1], [1, 0, 0], [0, 0, 0]], np.float32)
    np.testing.assert_array_equal(m, expect)


def test_window_offsets_row_major():
    off = F.window_offsets(3)
    assert off.tolist()[:4] == [[-1, -1], [-1, 0], [-1, 1], [0, -1]]
    assert off.tolist()[4] == [0, 0]


def test_gaussian_window_normalized_and_peaked():
    w = F.gaussian_window(5)
    assert abs(w.sum() - 1.0) < 1e-6
    assert w.argmax() == 12  # center of the 5x5 window


def test_feature_layout_sizes():
    spec = _spec()
    assert spec.block_sizes == [25, 25, 9, 9, 0]
    assert spec.total == 68  # SURVEY.md §3.2: F = 25+25+9+9
    single = _spec(has_coarse=False)
    assert single.total == 50
    rgb = _spec(src_channels=3)
    assert rgb.total == 75 + 25 + 27 + 9


def test_extract_patches_edge_clamp():
    img = np.arange(6, dtype=np.float32).reshape(2, 3)
    p = F.extract_patches_np(img, 3)
    # pixel (0,0): neighbors clamp to row/col 0
    win = p[0].reshape(3, 3)
    np.testing.assert_array_equal(win, [[0, 0, 1], [0, 0, 1], [3, 3, 4]])
    # center offset equals the pixel itself everywhere
    np.testing.assert_array_equal(p[:, 4], img.reshape(-1))


def test_db_fine_filt_is_causally_masked(rng):
    spec = _spec(has_coarse=False, gaussian=False)
    src = rng.uniform(0, 1, (7, 7)).astype(np.float32)
    filt = rng.uniform(0, 1, (7, 7)).astype(np.float32)
    feats = F.build_features_np(spec, src, filt, None, None)
    blk = feats[:, spec.fine_filt_slice]
    m = F.causal_mask(5)
    # masked-out columns all zero, kept columns match raw gathers * weight
    assert np.all(blk[:, m == 0] == 0)
    w = spec.sqrt_weights()[spec.fine_filt_slice]
    raw = F.extract_patches_np(filt, 5)
    np.testing.assert_allclose(blk[:, m > 0], (raw * w)[:, m > 0], atol=1e-6)


def test_query_static_has_zero_fine_filt(rng):
    spec = _spec(has_coarse=False)
    src = rng.uniform(0, 1, (6, 6)).astype(np.float32)
    feats = F.build_features_np(spec, src, None, None, None)
    assert np.all(feats[:, spec.fine_filt_slice] == 0)


def test_coarse_indexing(rng):
    spec = _spec(gaussian=False)
    src = rng.uniform(0, 1, (8, 8)).astype(np.float32)
    filt = rng.uniform(0, 1, (8, 8)).astype(np.float32)
    srcc = rng.uniform(0, 1, (4, 4)).astype(np.float32)
    filtc = rng.uniform(0, 1, (4, 4)).astype(np.float32)
    feats = F.build_features_np(spec, src, filt, srcc, filtc)
    sl = spec.slices()
    # coarse_src block of fine pixel (5,3) = 3x3 window of coarse at (2,1)
    q = 5 * 8 + 3
    w = spec.sqrt_weights()[sl[2]]
    expect = F.extract_patches_np(srcc, 3)[2 * 4 + 1] * w
    np.testing.assert_allclose(feats[q, sl[2]], expect, atol=1e-6)


def test_src_weight_zero_kills_src_blocks(rng):
    spec = _spec(src_weight=0.0)
    src = rng.uniform(0, 1, (8, 8)).astype(np.float32)
    filt = rng.uniform(0, 1, (8, 8)).astype(np.float32)
    srcc = rng.uniform(0, 1, (4, 4)).astype(np.float32)
    filtc = rng.uniform(0, 1, (4, 4)).astype(np.float32)
    feats = F.build_features_np(spec, src, filt, srcc, filtc)
    sl = spec.slices()
    assert np.all(feats[:, sl[0]] == 0) and np.all(feats[:, sl[2]] == 0)
    assert np.any(feats[:, sl[1]] != 0) and np.any(feats[:, sl[3]] != 0)


def test_temporal_block(rng):
    spec = _spec(has_coarse=False, temporal_weight=0.5, gaussian=False)
    assert spec.block_sizes[4] == 25
    src = rng.uniform(0, 1, (6, 6)).astype(np.float32)
    tp = rng.uniform(0, 1, (6, 6)).astype(np.float32)
    feats = F.build_features_np(spec, src, None, None, None, temporal_fine=tp)
    sl = spec.slices()
    w = spec.sqrt_weights()[sl[4]]
    np.testing.assert_allclose(
        feats[:, sl[4]], F.extract_patches_np(tp, 5) * w, atol=1e-6)
    # temporal weight scales the block: w = sqrt(0.5 * uniform)
    np.testing.assert_allclose(w, np.sqrt(0.5 / 25.0), atol=1e-6)


def test_jax_twin_matches_numpy(rng):
    for cs, has_coarse in [(1, True), (3, True), (1, False)]:
        spec = _spec(src_channels=cs, has_coarse=has_coarse)
        shape = (9, 10) if cs == 1 else (9, 10, cs)
        src = rng.uniform(0, 1, shape).astype(np.float32)
        filt = rng.uniform(0, 1, (9, 10)).astype(np.float32)
        cshape = (5, 5) if cs == 1 else (5, 5, cs)
        srcc = rng.uniform(0, 1, cshape).astype(np.float32) if has_coarse else None
        filtc = rng.uniform(0, 1, (5, 5)).astype(np.float32) if has_coarse else None
        ref = F.build_features_np(spec, src, filt, srcc, filtc)
        got = np.asarray(F.build_features_jax(spec, src, filt, srcc, filtc))
        np.testing.assert_allclose(got, ref, atol=1e-5)


def test_fine_gather_maps_validity():
    flat, valid, written = F.fine_gather_maps(4, 5, 3)
    # pixel (0,0): nothing synthesized before it
    assert valid[0].sum() == 0
    # pixel (0,1): only the left neighbor is causal AND in-bounds
    assert valid[1].sum() == 1
    # interior pixel: full causal half = 4 of 9
    q = 2 * 5 + 2
    assert valid[q].sum() == 4
    # clipped indices stay in range
    assert flat.min() >= 0 and flat.max() < 20
    # written: no query ever reads an index >= itself
    qcol = np.arange(20).reshape(-1, 1)
    assert np.all(flat[written > 0].reshape(-1)
                  < np.broadcast_to(qcol, flat.shape)[written > 0])
    # interior pixels: written == valid == causal half
    np.testing.assert_array_equal(written[q], valid[q])
    # border pixel (1,0): offset (0,-1) clamps to itself -> not written,
    # but offsets in row 0 clamp to written pixels -> kept
    qb = 1 * 5 + 0
    assert written[qb].sum() > 0
    assert written[qb].sum() >= valid[qb].sum()


def test_spec_for_level():
    p = AnalogyParams(levels=3, patch_size=5, coarse_patch_size=3)
    s0 = F.spec_for_level(p, 0, 3, 1)
    s2 = F.spec_for_level(p, 2, 3, 1)
    assert s0.has_coarse and not s2.has_coarse
