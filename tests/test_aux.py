"""Aux subsystems (SURVEY.md §5): checkpoint/resume, structured logging,
profiling hook, SSIM metric."""

import json
import os

import numpy as np
import pytest

from image_analogies_tpu.config import AnalogyParams
from image_analogies_tpu.models.analogy import create_image_analogy
from image_analogies_tpu.utils import checkpoint as ckpt
from image_analogies_tpu.utils.ssim import ssim
from tests.conftest import make_pair


def test_checkpoint_roundtrip(tmp_path, rng):
    bp = rng.uniform(0, 1, (8, 9)).astype(np.float32)
    s = rng.integers(0, 72, (8, 9)).astype(np.int32)
    ckpt.save_level(str(tmp_path), 2, bp, s)
    out = ckpt.load_level(str(tmp_path), 2)
    assert out is not None
    np.testing.assert_array_equal(out[0], bp)
    np.testing.assert_array_equal(out[1], s)
    assert ckpt.load_level(str(tmp_path), 3) is None


def test_resume_reuses_coarse_levels(tmp_path, rng):
    a, ap, b = make_pair(16, 16, seed=5)
    log1 = str(tmp_path / "log1.jsonl")
    log2 = str(tmp_path / "log2.jsonl")
    p = AnalogyParams(levels=2, backend="cpu",
                      checkpoint_dir=str(tmp_path / "ck"), log_path=log1)
    r1 = create_image_analogy(a, ap, b, p)
    p2 = p.replace(resume_from_level=0, log_path=log2)
    r2 = create_image_analogy(a, ap, b, p2)
    np.testing.assert_array_equal(r1.bp_y, r2.bp_y)
    recs = [json.loads(l) for l in open(log2)]
    assert any(r.get("event") == "resume_level" for r in recs)


def test_stale_checkpoint_not_resumed(tmp_path, rng):
    """A checkpoint from a different run config (ADVICE round-1: shape or
    params mismatch) must be recomputed, not silently resumed."""
    a, ap, b = make_pair(16, 16, seed=5)
    ckdir = str(tmp_path / "ck")
    p = AnalogyParams(levels=2, backend="cpu", checkpoint_dir=ckdir)
    create_image_analogy(a, ap, b, p)
    # same dir, different kappa: digest differs -> loader returns None
    p2 = p.replace(kappa=0.5, resume_from_level=0,
                   log_path=str(tmp_path / "log.jsonl"))
    r2 = create_image_analogy(a, ap, b, p2)
    recs = [json.loads(l) for l in open(str(tmp_path / "log.jsonl"))]
    assert not any(r.get("event") == "resume_level" for r in recs)
    # and the run still completes correctly
    assert r2.bp_y.shape == (16, 16)
    # a LEGACY .npz (written before the digest field existed) must still
    # load when the caller requests no digest, and be skipped when one is
    # requested
    legacy = ckpt.level_path(ckdir, 7)
    np.savez(legacy, level=7, bp=np.zeros((4, 4), np.float32),
             s=np.zeros((4, 4), np.int32))
    assert ckpt.load_level(ckdir, 7) is not None
    assert ckpt.load_level(ckdir, 7, digest="abc") is None


def test_corrupt_checkpoint_quarantined_and_recomputed(tmp_path, rng):
    """Damaged checkpoint bytes (payload OR metadata fields) must fail
    the integrity seal, be quarantined as `.corrupt`, and make the
    loader return None so the level recomputes — never resume garbage,
    never trip on the same file twice."""
    from image_analogies_tpu.chaos import faults as chaos_faults

    bp = rng.uniform(0, 1, (8, 9)).astype(np.float32)
    s = rng.integers(0, 72, (8, 9)).astype(np.int32)
    path = ckpt.save_level(str(tmp_path), 1, bp, s, digest="d1gest")
    assert chaos_faults.corrupt_file(path, seed=0) > 0
    assert ckpt.load_level(str(tmp_path), 1, digest="d1gest") is None
    assert not os.path.exists(path)
    assert os.path.exists(path + ".corrupt")  # evidence kept, not deleted
    # the quarantined path no longer collides: a fresh save + load works
    ckpt.save_level(str(tmp_path), 1, bp, s, digest="d1gest")
    out = ckpt.load_level(str(tmp_path), 1, digest="d1gest")
    np.testing.assert_array_equal(out[0], bp)


def test_truncated_checkpoint_quarantined(tmp_path, rng):
    """A partial write (file cut mid-stream) is damage, not staleness."""
    bp = rng.uniform(0, 1, (8, 9)).astype(np.float32)
    s = rng.integers(0, 72, (8, 9)).astype(np.int32)
    path = ckpt.save_level(str(tmp_path), 3, bp, s)
    blob = open(path, "rb").read()
    with open(path, "wb") as f:
        f.write(blob[:len(blob) // 2])
    assert ckpt.load_level(str(tmp_path), 3) is None
    assert os.path.exists(path + ".corrupt")


def test_stale_checkpoint_skipped_not_quarantined(tmp_path, rng):
    """Digest mismatch on an INTACT file stays a clean skip: the file
    belongs to another run config and must survive untouched."""
    bp = rng.uniform(0, 1, (8, 9)).astype(np.float32)
    s = rng.integers(0, 72, (8, 9)).astype(np.int32)
    path = ckpt.save_level(str(tmp_path), 4, bp, s, digest="old-config")
    assert ckpt.load_level(str(tmp_path), 4, digest="new-config") is None
    assert os.path.exists(path)  # still there...
    assert not os.path.exists(path + ".corrupt")  # ...and not quarantined
    out = ckpt.load_level(str(tmp_path), 4, digest="old-config")
    np.testing.assert_array_equal(out[0], bp)


def test_structured_log_records(tmp_path, rng):
    a, ap, b = make_pair(12, 12, seed=5)
    log = str(tmp_path / "log.jsonl")
    p = AnalogyParams(levels=2, backend="cpu", log_path=log)
    create_image_analogy(a, ap, b, p)
    recs = [json.loads(l) for l in open(log)]
    # a log_path run is an observed run (obs/): the per-level stat records
    # ride inside a run-scoped envelope — manifest first, run_end (metrics
    # snapshot) last, every record stamped with the one run_id
    stat = [r for r in recs if "level" in r and "event" not in r]
    assert len(stat) == 2
    for r in stat:
        for key in ("level", "db_rows", "pixels", "coherence_ratio", "ms",
                    "backend", "ts"):
            assert key in r, key
    assert recs[0].get("event") == "run_manifest"
    assert recs[-1].get("event") == "run_end"
    assert len({r.get("run_id") for r in recs}) == 1


def test_profile_dir_writes_trace(tmp_path, rng):
    a, ap, b = make_pair(12, 12, seed=5)
    prof = str(tmp_path / "prof")
    p = AnalogyParams(levels=1, backend="tpu", strategy="batched",
                      profile_dir=prof)
    create_image_analogy(a, ap, b, p)
    found = []
    for root, _, files in os.walk(prof):
        found.extend(files)
    assert found, "profiler produced no trace files"


@pytest.fixture(autouse=True)
def _disarm_fault_injector():
    """The injector is process-global: always reset it so a failing test
    cannot leak armed synthetic faults into unrelated tests."""
    yield
    from image_analogies_tpu.utils import failure

    failure.inject_failures(0)


def test_level_retry_recovers_from_transient_fault(tmp_path, rng):
    """SURVEY.md §5.3: a transient device fault mid-run retries at level
    granularity and completes, logging a level_retry record; the output
    equals an undisturbed run."""
    from image_analogies_tpu.utils import failure

    a, ap, b = make_pair(14, 14, seed=5)
    clean = create_image_analogy(a, ap, b, AnalogyParams(levels=2,
                                                         backend="cpu"))
    log = str(tmp_path / "log.jsonl")
    failure.inject_failures(1)  # first level attempt dies
    res = create_image_analogy(a, ap, b, AnalogyParams(
        levels=2, backend="cpu", level_retries=2, log_path=log))
    np.testing.assert_array_equal(res.bp_y, clean.bp_y)
    recs = [json.loads(l) for l in open(log)]
    retries = [r for r in recs if r.get("event") == "level_retry"]
    assert len(retries) == 1 and retries[0]["error"] == "InjectedFailure"


def test_level_retry_exhausted_propagates(rng):
    from image_analogies_tpu.utils import failure

    a, ap, b = make_pair(12, 12, seed=5)
    failure.inject_failures(3)  # more faults than the retry budget
    with pytest.raises(failure.InjectedFailure):
        create_image_analogy(a, ap, b, AnalogyParams(
            levels=1, backend="cpu", level_retries=1))


def test_nontransient_errors_not_retried():
    from image_analogies_tpu.utils import failure

    calls = {"n": 0}

    def bad():
        calls["n"] += 1
        raise ValueError("a bug, not a fault")

    with pytest.raises(ValueError):
        failure.run_with_retry(bad, retries=5)
    assert calls["n"] == 1  # no retry on programming errors


def test_retry_exhaustion_surfaces_original_exception():
    """A persistent transient fault exhausts the budget and the caller
    sees the ORIGINAL exception object — not a wrapper, not a generic
    retry error — so upstream handlers keep their type checks."""
    from image_analogies_tpu.utils import failure

    class XlaRuntimeError(RuntimeError):  # name-matched as transient
        pass

    raised = []

    def always_down():
        exc = XlaRuntimeError("UNAVAILABLE: device lost")
        raised.append(exc)
        raise exc

    with pytest.raises(XlaRuntimeError) as ei:
        failure.run_with_retry(always_down, retries=2, backoff_s=0.0)
    assert len(raised) == 3  # initial attempt + 2 retries
    assert ei.value is raised[-1]


def test_is_transient_walks_exception_chains():
    """jax re-raises device faults wrapped in tracing-layer exceptions:
    the transient signal (or a non-transient status code) must be found
    through __cause__/__context__ chains, and cycles must terminate."""
    from image_analogies_tpu.utils import failure

    class XlaRuntimeError(RuntimeError):
        pass

    def chained(inner):
        try:
            try:
                raise inner
            except Exception as e:
                raise RuntimeError("engine wrapper") from e
        except RuntimeError as outer:
            return outer

    assert failure._is_transient(chained(XlaRuntimeError("UNAVAILABLE: x")))
    assert failure._is_transient(chained(failure.InjectedFailure("synth")))
    # a non-transient status code stays a bug no matter the wrapping
    assert not failure._is_transient(
        chained(XlaRuntimeError("INVALID_ARGUMENT: bad shape")))
    assert not failure._is_transient(chained(ValueError("plain bug")))
    # self-referential chains terminate via the cycle guard
    loop = RuntimeError("loop")
    loop.__context__ = loop
    assert not failure._is_transient(loop)


def test_retry_wrapper_inert_when_injection_disabled(monkeypatch):
    """Disarmed injector + clean fn: the wrapper is a plain passthrough —
    one call, no metric or log activity on the success path."""
    from image_analogies_tpu.obs import metrics as obs_metrics
    from image_analogies_tpu.utils import failure

    assert failure._INJECT["n"] == 0

    def touched(*a, **k):
        raise AssertionError("metrics touched on the clean path")

    monkeypatch.setattr(obs_metrics, "inc", touched)
    calls = {"n": 0}

    def fn():
        calls["n"] += 1
        return 42

    assert failure.run_with_retry(fn, retries=3) == 42
    assert calls["n"] == 1


def test_backoff_delay_deterministic_jittered_capped():
    """Retry pacing: capped exponential with seeded jitter — same
    (seed, attempt) always sleeps the same, delays stay in
    [base/2, base), and the cap bounds the worst case."""
    from image_analogies_tpu.utils import failure

    kw = dict(backoff_s=0.5, backoff_cap_s=8.0)
    d1 = [failure.backoff_delay(a, jitter_seed=7, **kw)
          for a in range(1, 10)]
    d2 = [failure.backoff_delay(a, jitter_seed=7, **kw)
          for a in range(1, 10)]
    d3 = [failure.backoff_delay(a, jitter_seed=8, **kw)
          for a in range(1, 10)]
    assert d1 == d2          # deterministic per seed
    assert d1 != d3          # seeds de-correlate (thundering herd)
    assert 0.25 <= d1[0] < 0.5       # attempt 1: base 0.5, jitter [.5, 1)
    for a, d in enumerate(d1, start=1):
        base = min(0.5 * 2 ** (a - 1), 8.0)
        assert base / 2 <= d < base or d == pytest.approx(base)
    assert d1[-1] <= 8.0             # capped, not 0.5 * 2**8 = 128
    assert failure.backoff_delay(3, backoff_s=0.0) == 0.0


def test_retry_exhausted_counter_and_record(tmp_path):
    """Beyond-budget transients bump retry.exhausted (the reconciliation
    ledger's 'gave up' column) and log a retry_exhausted record."""
    from image_analogies_tpu.config import AnalogyParams
    from image_analogies_tpu.obs import trace as obs_trace
    from image_analogies_tpu.utils import failure

    log = str(tmp_path / "run.jsonl")
    params = AnalogyParams(backend="cpu", metrics=True, log_path=log)
    failure.inject_failures(5)
    with obs_trace.run_scope(params) as ctx:
        with pytest.raises(failure.InjectedFailure):
            failure.run_with_retry(lambda: "never", retries=1,
                                   backoff_s=0.0, log_path=log)
        counters = dict(ctx.registry.snapshot()["counters"])
    assert counters["retry.exhausted"] == 1
    assert counters["level_retry"] == 1  # the one absorbed retry
    recs = [json.loads(l) for l in open(log) if l.strip()]
    assert any(r.get("event") == "retry_exhausted" for r in recs)


def test_watchdog_times_out_wedged_dispatch():
    """A wedged dispatch surfaces as WatchdogTimeout well before the
    wedge resolves — and the timeout classifies TRANSIENT, so the level
    retry wrapper is its recovery path."""
    import time

    from image_analogies_tpu.utils import failure

    t0 = time.monotonic()
    with pytest.raises(failure.WatchdogTimeout):
        failure.run_with_watchdog(lambda: time.sleep(1.0), 0.05)
    assert time.monotonic() - t0 < 0.9  # surfaced early, not after the wedge
    assert failure._is_transient(failure.WatchdogTimeout("wedged"))


def test_watchdog_retry_recovers_wedge():
    """watchdog + retry composed (the engine's dispatch wrapping): first
    attempt wedges past the deadline, second completes; callers see the
    clean result."""
    import time

    from image_analogies_tpu.utils import failure

    calls = {"n": 0}

    def body():
        calls["n"] += 1
        if calls["n"] == 1:
            time.sleep(0.6)
        return "recovered"

    def dispatch():
        return failure.run_with_watchdog(body, 0.05)

    assert failure.run_with_retry(dispatch, retries=2,
                                  backoff_s=0.0) == "recovered"
    assert calls["n"] == 2


def test_watchdog_zero_timeout_runs_inline():
    from image_analogies_tpu.utils import failure

    ident = []
    import threading

    def body():
        ident.append(threading.current_thread())
        return 7

    assert failure.run_with_watchdog(body, 0.0) == 7
    assert ident == [threading.main_thread()]  # no helper thread spawned


def test_ssim_properties(rng):
    x = rng.uniform(0, 1, (32, 32))
    assert ssim(x, x) == pytest.approx(1.0, abs=1e-9)
    noisy = np.clip(x + 0.2 * rng.standard_normal(x.shape), 0, 1)
    v = ssim(x, noisy)
    assert 0.0 < v < 0.95
    assert ssim(x, noisy) > ssim(x, 1.0 - x)
    with pytest.raises(ValueError):
        ssim(x, x[:16])


def test_devcache_content_keyed(rng):
    """Upload memoization must key on CONTENT: identical bytes reuse the
    buffer, a mutated array gets a fresh one (never a stale hit)."""
    import jax.numpy as jnp

    from image_analogies_tpu.utils import devcache

    devcache.clear()
    a = np.asarray(rng.standard_normal((256, 256)), np.float32)
    d1 = devcache.device_put_cached(a, jnp.float32)
    d2 = devcache.device_put_cached(a.copy(), jnp.float32)  # same bytes
    assert d1 is d2
    a2 = a.copy()
    a2[0, 0] += 1.0
    d3 = devcache.device_put_cached(a2, jnp.float32)
    assert d3 is not d1
    np.testing.assert_array_equal(np.asarray(d3), a2)
    # tiny arrays bypass the cache entirely (hashing gains nothing)
    t = devcache.device_put_cached(np.zeros((4,), np.float32), jnp.float32)
    np.testing.assert_array_equal(np.asarray(t), np.zeros((4,)))
    devcache.clear()
