"""Color ops (SURVEY.md §4.2: YIQ round-trip, luminance remap statistics)."""

import numpy as np

from image_analogies_tpu.ops import color


def test_yiq_roundtrip(rng):
    rgb = rng.uniform(0, 1, (16, 17, 3)).astype(np.float32)
    back = color.yiq2rgb(color.rgb2yiq(rgb))
    np.testing.assert_allclose(back, rgb, atol=1e-5)


def test_luminance_of_gray_is_identity(rng):
    g = rng.uniform(0, 1, (8, 9)).astype(np.float32)
    np.testing.assert_allclose(color.luminance(g), g)


def test_luminance_weights():
    # Pure white -> Y == 1; pure green has the largest Y coefficient.
    white = np.ones((2, 2, 3), np.float32)
    np.testing.assert_allclose(color.luminance(white), 1.0, atol=1e-6)
    chans = [color.luminance(np.eye(3, dtype=np.float32)[None, c][None])
             for c in range(3)]
    ys = [float(c[0, 0]) for c in chans]
    assert ys[1] > ys[0] > ys[2]  # G > R > B


def test_remap_luminance_matches_stats(rng):
    ya = rng.uniform(0, 1, (32, 32)).astype(np.float32)
    yb = (rng.uniform(0, 1, (24, 40)) * 0.5 + 0.3).astype(np.float32)
    out = color.remap_luminance(ya, yb)
    assert abs(out.mean() - yb.mean()) < 1e-4
    assert abs(out.std() - yb.std()) < 1e-4


def test_remap_constant_source(rng):
    ya = np.full((8, 8), 0.4, np.float32)
    yb = rng.uniform(0, 1, (8, 8)).astype(np.float32)
    out = color.remap_luminance(ya, yb)
    np.testing.assert_allclose(out, yb.mean(), atol=1e-6)


def test_remap_pair_single_transform(rng):
    """A and A' must get the SAME affine transform — remapping each to B
    independently would exactly cancel an affine filter A -> A'."""
    ya = rng.uniform(0, 1, (16, 16)).astype(np.float32)
    yap = (0.5 * ya + 0.2).astype(np.float32)  # affine "filter"
    yb = (rng.uniform(0, 1, (16, 16)) * 0.7 + 0.1).astype(np.float32)
    ra, rap = color.remap_pair(ya, yap, yb)
    # A's stats now match B's...
    assert abs(ra.mean() - yb.mean()) < 1e-4
    # ...and the filter relationship survives: rap = 0.5*ra + const
    diff = rap - 0.5 * ra
    assert diff.std() < 1e-5
    # the filter is NOT cancelled: remapped planes still differ
    assert np.abs(ra - rap).max() > 1e-3


def test_as_float_uint8():
    u = np.array([[0, 255]], np.uint8)
    np.testing.assert_allclose(color.as_float(u), [[0.0, 1.0]])
