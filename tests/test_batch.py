"""Batched B-axis engine (ISSUE 10): batch/engine.py + the vmapped
lanes runner in backends/tpu.py, and its serve wiring.

Acceptance invariants locked here:

- every batched member is BIT-IDENTICAL to its sequential singleton run
  — on both lanes-runner strategies, and for same-bucket mixed shapes
  under query-side bucketing (tune/buckets.py);
- query padding is honest by construction: adversarially poisoning the
  padded rows of EVERY query-side leaf cannot change one output bit
  (the scan's row loop never reads them);
- incompatible batches refuse with a reasoned
  ``batch.fallback_sequential.<reason>`` counter, and the serve worker
  falls back to the sequential per-member loop — nothing is lost, the
  claimed futures still resolve;
- members whose degrade plans diverge never reach the engine
  (serve-side ``degrade_divergence`` refusal);
- k lanes share ONE compiled lanes program per level: compile records
  count levels, not k x levels, and a second same-shape launch compiles
  nothing;
- the serve selftest engages the engine end-to-end: engine launches <
  completed requests, under the selftest's own bit-identity gate.
"""

import dataclasses
import os
import time
from concurrent.futures import Future

import numpy as np
import pytest

from image_analogies_tpu.batch import BatchIncompatible, \
    create_image_analogy_batch
from image_analogies_tpu.chaos import drills
from image_analogies_tpu.config import AnalogyParams
from image_analogies_tpu.models.analogy import create_image_analogy
from image_analogies_tpu.obs import metrics as obs_metrics
from image_analogies_tpu.obs import trace as obs_trace


def _params(**kw):
    kw.setdefault("backend", "tpu")
    kw.setdefault("strategy", "batched")
    kw.setdefault("levels", 2)
    kw.setdefault("patch_size", 3)
    kw.setdefault("coarse_patch_size", 3)
    kw.setdefault("remap_luminance", False)
    kw.setdefault("metrics", True)
    return AnalogyParams(**kw)


def _load(k, shapes, seed=7):
    """One exemplar pair + k targets with the given per-member shapes."""
    rng = np.random.RandomState(seed)
    h, w = shapes[0]
    a = rng.rand(h, w).astype(np.float32)
    ap = rng.rand(h, w).astype(np.float32)
    targets = [rng.rand(hh, ww).astype(np.float32)
               for hh, ww in (shapes * k)[:k]]
    return a, ap, targets


def _counters(params, fn):
    """Run ``fn`` inside an obs scope; returns (result-or-exc, counters)."""
    with obs_trace.run_scope(params):
        try:
            out = fn()
        except Exception as exc:  # noqa: BLE001 - returned for inspection
            out = exc
        snap = obs_metrics.snapshot() or {}
    return out, snap.get("counters", {})


# ---------------------------------------------------------------------------
# bit-identity: the non-negotiable invariant
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("strategy", ["batched", "wavefront"])
def test_batched_bit_identical_to_sequential(strategy):
    params = _params(strategy=strategy)
    a, ap, targets = _load(3, [(16, 16)])
    results = create_image_analogy_batch(a, ap, targets, params)
    assert len(results) == 3
    for b, res in zip(targets, results):
        assert not isinstance(res, Exception)
        ref = create_image_analogy(a, ap, b, params)
        assert np.array_equal(np.asarray(res.bp), np.asarray(ref.bp))
        assert np.array_equal(np.asarray(res.bp_y), np.asarray(ref.bp_y))


def test_bucketed_mixed_shapes_bit_identical():
    """Same query bucket, DIFFERENT real row counts: bucketing is what
    admits them to one program, and each member must still match its own
    singleton bit for bit."""
    params = _params(shape_buckets=True)
    a, ap, _ = _load(1, [(20, 20)])
    rng = np.random.RandomState(11)
    targets = [rng.rand(20, 20).astype(np.float32),
               rng.rand(22, 20).astype(np.float32),
               rng.rand(21, 20).astype(np.float32)]
    results = create_image_analogy_batch(a, ap, targets, params)
    for b, res in zip(targets, results):
        assert not isinstance(res, Exception)
        assert res.bp.shape[:2] == b.shape  # cropped to the REAL shape
        ref = create_image_analogy(a, ap, b, params)
        assert np.array_equal(np.asarray(res.bp), np.asarray(ref.bp))


# ---------------------------------------------------------------------------
# padding honesty: adversarial pad contents
# ---------------------------------------------------------------------------

def test_query_padding_is_honest_under_adversarial_pad():
    """Poison the padded rows of every query-side leaf with garbage; if
    the scan ever read a pad row, some bit of the output would move.
    None may."""
    import jax.numpy as jnp

    from image_analogies_tpu.backends import get_backend
    from image_analogies_tpu.backends.base import LevelJob
    from image_analogies_tpu.ops.features import spec_for_level

    params = _params(levels=1, shape_buckets=True)
    rng = np.random.RandomState(5)
    a = rng.rand(12, 12).astype(np.float32)
    ap = rng.rand(12, 12).astype(np.float32)
    b = rng.rand(12, 12).astype(np.float32)
    backend = get_backend(params)
    job = LevelJob(level=0, spec=spec_for_level(params, 0, 1, 1),
                   kappa_mult=params.kappa_factor(0) ** 2,
                   a_src=a, a_filt=ap, b_src=b)
    db = backend.build_features(job)
    n = 12 * 12
    assert db.static_q.shape[0] > n  # bucketed: pad rows exist
    bp0, s0, _ = backend.synthesize_level(db, job)

    sq = np.asarray(db.static_q).copy()
    sq[n:] = 1e9  # any read would swing every distance it touches
    fi = np.asarray(db.flat_idx).copy()
    fi[n:] = 3  # in-range garbage: a read would gather a REAL pixel
    vd = np.asarray(db.valid).copy()
    vd[n:] = 1.0  # pad rows claim every neighbor is valid
    wr = np.asarray(db.written).copy()
    wr[n:] = 1.0  # ...and already written
    poisoned = dataclasses.replace(
        db, static_q=jnp.asarray(sq), flat_idx=jnp.asarray(fi),
        valid=jnp.asarray(vd), written=jnp.asarray(wr))
    bp1, s1, _ = backend.synthesize_level(poisoned, job)
    assert np.array_equal(np.asarray(bp0), np.asarray(bp1))
    assert np.array_equal(np.asarray(s0), np.asarray(s1))


# ---------------------------------------------------------------------------
# refusals: reasoned counters, nothing silently wrong
# ---------------------------------------------------------------------------

def test_mixed_bucket_refuses_with_counter():
    params = _params(levels=1, shape_buckets=True)
    rng = np.random.RandomState(3)
    a = rng.rand(16, 16).astype(np.float32)
    ap = rng.rand(16, 16).astype(np.float32)
    targets = [rng.rand(16, 16).astype(np.float32),   # 256 -> bucket 256
               rng.rand(40, 16).astype(np.float32)]   # 640 -> bucket 768
    out, counters = _counters(
        params, lambda: create_image_analogy_batch(a, ap, targets, params))
    assert isinstance(out, BatchIncompatible)
    assert out.reason == "mixed_bucket"
    assert counters.get("batch.fallback_sequential.mixed_bucket", 0) >= 1


def test_wavefront_mixed_shapes_refuse():
    """The wavefront scan's packed carry + diag schedule are program
    structure — lanes must agree on shape exactly."""
    params = _params(strategy="wavefront")
    rng = np.random.RandomState(3)
    a = rng.rand(16, 16).astype(np.float32)
    ap = rng.rand(16, 16).astype(np.float32)
    targets = [rng.rand(16, 16).astype(np.float32),
               rng.rand(20, 20).astype(np.float32)]
    out, counters = _counters(
        params, lambda: create_image_analogy_batch(a, ap, targets, params))
    assert isinstance(out, BatchIncompatible)
    assert out.reason == "shape_mismatch"
    assert counters.get("batch.fallback_sequential.shape_mismatch", 0) >= 1


def test_pad_waste_ceiling_refuses_then_env_admits(monkeypatch):
    """(17, 16) pads 272 -> 512 rows = 47% finest-level waste: past the
    default 25% ceiling the batch refuses; raising IA_BATCH_PAD_WASTE
    admits it AND the admitted run stays bit-identical."""
    params = _params(levels=1, shape_buckets=True)
    a, ap, targets = _load(2, [(17, 16)], seed=9)
    out, counters = _counters(
        params, lambda: create_image_analogy_batch(a, ap, targets, params))
    assert isinstance(out, BatchIncompatible)
    assert out.reason == "pad_waste"
    assert counters.get("batch.fallback_sequential.pad_waste", 0) >= 1

    monkeypatch.setenv("IA_BATCH_PAD_WASTE", "60")
    results = create_image_analogy_batch(a, ap, targets, params)
    for b, res in zip(targets, results):
        assert not isinstance(res, Exception)
        ref = create_image_analogy(a, ap, b, params)
        assert np.array_equal(np.asarray(res.bp), np.asarray(ref.bp))


# ---------------------------------------------------------------------------
# serve-layer fallback: refusals and degrade divergence resolve everything
# ---------------------------------------------------------------------------

def _serve_batch(params, k=3, size=(16, 16), deadline_s=None, seed=21):
    from image_analogies_tpu.serve import batcher
    from image_analogies_tpu.serve.types import Request

    rng = np.random.RandomState(seed)
    h, w = size
    a = rng.rand(h, w).astype(np.float32)
    ap = rng.rand(h, w).astype(np.float32)
    reqs = []
    for i in range(k):
        b = rng.rand(h, w).astype(np.float32)
        reqs.append(Request(
            request_id=i, a=a, ap=ap, b=b, params=params,
            key=batcher.batch_key(a, ap, b, params), future=Future(),
            deadline=(None if deadline_s is None
                      else time.monotonic() + deadline_s)))
    return reqs


def _pool(params, **cfg_kw):
    from image_analogies_tpu.serve.queue import AdmissionQueue
    from image_analogies_tpu.serve.types import ServeConfig
    from image_analogies_tpu.serve.worker import WorkerPool

    cfg = ServeConfig(params=params, workers=1, **cfg_kw)
    return WorkerPool(cfg, AdmissionQueue(16))


def test_engine_refusal_falls_back_to_sequential_dispatch():
    """remap_luminance couples the A/A' DB to each member's B stats, so
    distinct random targets refuse the batch (remap_divergence) — and
    the worker's sequential fallback must still resolve every claimed
    future, bit-identically."""
    params = _params(levels=1, remap_luminance=True)
    pool = _pool(params)
    reqs = _serve_batch(params)
    with obs_trace.run_scope(params):
        pool._run_batch(reqs)
        snap = obs_metrics.snapshot() or {}
    counters = snap.get("counters", {})
    assert counters.get(
        "batch.fallback_sequential.remap_divergence", 0) >= 1
    assert counters.get("batch.launches", 0) == 0
    for req in reqs:
        resp = req.future.result(timeout=60)
        ref = create_image_analogy(req.a, req.ap, req.b, params)
        assert np.array_equal(np.asarray(resp.bp), np.asarray(ref.bp))


def test_degrade_divergence_refuses_before_the_engine():
    """A poisoned cost model makes every deadlined plan non-"run": the
    batch must refuse on the serve side (degrade_divergence) without
    claiming futures or touching the engine."""
    params = _params()
    pool = _pool(params)
    # one observation at a catastrophic rate: any deadline now forces
    # the degrade/timeout ladder
    pool._cost.observe(1.0, 50.0)
    reqs = _serve_batch(params, deadline_s=10.0)
    with obs_trace.run_scope(params):
        handled = pool._dispatch_batch(reqs)
        snap = obs_metrics.snapshot() or {}
    counters = snap.get("counters", {})
    assert handled is False
    assert counters.get(
        "batch.fallback_sequential.degrade_divergence", 0) >= 1
    assert counters.get("batch.launches", 0) == 0
    # refused before the claim: the sequential loop owns these futures
    for req in reqs:
        assert not req.future.done()
        assert req.future.set_running_or_notify_cancel()


# ---------------------------------------------------------------------------
# one compiled program per level, shared by every lane and launch
# ---------------------------------------------------------------------------

def test_one_lanes_program_per_level(tmp_path):
    from image_analogies_tpu.obs.report import load_records

    log = str(tmp_path / "run.jsonl")
    params = _params(log_path=log)
    a, ap, targets = _load(3, [(18, 18)], seed=13)  # shapes unique to
    #    this test: the lanes-program cache is process-global

    def lanes_compiles():
        return [r for r in load_records(log)
                if r.get("event") == "compile"
                and r.get("name") == "tpu.run_lanes"]

    results = create_image_analogy_batch(a, ap, targets, params)
    assert all(not isinstance(r, Exception) for r in results)
    # 3 lanes, 2 levels: one compile per LEVEL shape, not per lane
    assert len(lanes_compiles()) == params.levels

    rng = np.random.RandomState(17)
    again = [rng.rand(18, 18).astype(np.float32) for _ in range(3)]
    results = create_image_analogy_batch(a, ap, again, params)
    assert all(not isinstance(r, Exception) for r in results)
    # a second same-shape launch compiles NOTHING new
    assert len(lanes_compiles()) == params.levels


# ---------------------------------------------------------------------------
# serve selftest end-to-end
# ---------------------------------------------------------------------------

def test_serve_selftest_batches_and_stays_bit_identical():
    from image_analogies_tpu.serve import loadgen
    from image_analogies_tpu.serve.types import ServeConfig

    cfg = ServeConfig(params=_params(levels=1), queue_depth=64,
                      batch_window_ms=25.0, max_batch=4, workers=1,
                      drain_timeout_s=60.0)
    summary = loadgen.selftest(cfg, 6, seed=0, shapes=((16, 16),))
    assert summary["errors"] == 0 and summary["rejected"] == 0
    assert summary["bit_identical"] is True
    ledger = summary["batch_engine"]
    # the lane axis compresses launches: strictly fewer engine launches
    # than completed requests (ISSUE 10 acceptance)
    assert ledger["launches"] >= 1
    assert ledger["completed"] == 6
    assert ledger["completed"] > ledger["launches"]
    assert ledger["lane_faults"] == 0
