"""Elastic-fleet control plane (ISSUE 19): declarative autoscaling +
per-tenant QoS (serve/control.py, serve/policy.py).

Locked here:

- ControlPolicy / QosPolicy round-trip to plain JSON, reject unknown
  fields, and validate their numeric invariants (a policy is a checked-
  in artifact, so a typo must fail loudly at load time);
- reconcile hysteresis: pressure must HOLD for ``scale_up_windows``
  consecutive passes (a mid-range pass resets both counters), and each
  direction honors its own cooldown;
- the full breathe cycle against a real inproc fleet: queue pressure
  -> spawn + ring join, calm -> the emptiest worker drains, ring-
  leaves, and retires, with every verdict in the ``control.*`` counters
  and the decision plane;
- scale-down NEVER strands work: a worker with queued requests,
  inflight dispatches, an unreplayed journal, or router-pending futures
  is not retireable, and a retire that races admitted traffic aborts
  and fully restores membership;
- scale-up warm path: with the exemplar catalog active, the joining
  worker's home styles are pre-staged, so its first home-style request
  is tier hits — zero cold builds;
- TenantQuota token buckets are deterministic under an injected clock,
  and the observed-cost-share penalty scales refill down;
- weighted-fair queue pop: stride scheduling across tenants with
  priority-class weights, aging promotion trumping fairness;
- flash-crowd arrival schedules are seed-deterministic and actually
  compress arrivals into the surge window;
- `ia fleet --autoscale --selftest` + `ia serve --flash-crowd` CLI
  smoke.
"""

import dataclasses
import json
from concurrent.futures import Future

import numpy as np
import pytest

from image_analogies_tpu.chaos import drills
from image_analogies_tpu.obs import metrics as obs_metrics
from image_analogies_tpu.serve.control import ControlPlane
from image_analogies_tpu.serve.fleet import Fleet
from image_analogies_tpu.serve.policy import (ControlPolicy, QosPolicy,
                                              TenantQuota)
from image_analogies_tpu.serve.types import FleetConfig, Request

# ------------------------------------------------------------- policy


def test_control_policy_json_roundtrip(tmp_path):
    pol = ControlPolicy(min_workers=2, max_workers=5, queue_high=3.0,
                        queue_low=0.25, scale_up_windows=3)
    assert ControlPolicy.from_json(pol.to_json()) == pol
    path = tmp_path / "policy.json"
    path.write_text(json.dumps(pol.to_json()))
    assert ControlPolicy.load(str(path)) == pol
    with pytest.raises(ValueError):
        ControlPolicy.from_json({"min_workers": 1, "warp_factor": 9})
    with pytest.raises(ValueError):
        ControlPolicy(min_workers=0)
    with pytest.raises(ValueError):
        ControlPolicy(min_workers=3, max_workers=2)
    with pytest.raises(ValueError):
        ControlPolicy(queue_low=4.0, queue_high=4.0)
    with pytest.raises(ValueError):
        ControlPolicy(scale_up_windows=0)


def test_qos_policy_json_roundtrip():
    qos = QosPolicy(quota_rps=2.0, quota_burst=4.0, share_cap=0.3)
    assert QosPolicy.from_json(qos.to_json()) == qos
    with pytest.raises(ValueError):
        QosPolicy.from_json({"quota_rps": 1.0, "free_lunch": True})
    with pytest.raises(ValueError):
        QosPolicy(quota_rps=-1.0)
    with pytest.raises(ValueError):
        QosPolicy(share_cap=0.0)
    with pytest.raises(ValueError):
        QosPolicy(quota_burst=0.5)


# ---------------------------------------------------------- hysteresis


class _FakeFleet:
    """Just enough fleet for reconcile passes that never act: the
    size reads and nothing else (min == max pins both directions)."""

    def __init__(self, n=1):
        self.workers = {f"w{i}": object() for i in range(n)}


def _health(depth=0.0, ok=True, recovering=False, burn=0.0):
    return {"ok": ok, "recovering": recovering, "queue_depth": depth,
            "slo": {"burn_rate_fast": burn}, "breakers": {}}


def test_reconcile_hysteresis_counters():
    """Pressure must hold for ``scale_up_windows`` consecutive passes;
    a mid-range pass (neither over queue_high nor under queue_low)
    resets BOTH hysteresis counters, so flapping load never scales."""
    pol = ControlPolicy(min_workers=1, max_workers=1, queue_high=2.0,
                        queue_low=0.5, scale_up_windows=2)
    now = [0.0]
    cp = ControlPlane(_FakeFleet(1), pol, clock=lambda: now[0])
    busy = {"w0": _health(depth=5)}
    mid = {"w0": _health(depth=1)}      # between low and high
    calm = {"w0": _health(depth=0)}

    assert cp.reconcile(busy) is None and cp._over == 1
    assert cp.reconcile(mid) is None
    assert cp._over == 0 and cp._idle == 0   # mid-range resets both
    assert cp.reconcile(calm) is None and cp._idle == 1
    assert cp.reconcile(busy) is None
    assert cp._over == 1 and cp._idle == 0
    # min == max: even held pressure/calm can never change the fleet
    for _ in range(10):
        assert cp.reconcile(busy) is None
    assert len(cp.fleet.workers) == 1


def _fleet_cfg(tmp_path=None, size=1, **kw):
    scfg = drills.serve_config(workers=1, max_batch=4,
                               batch_window_ms=20.0)
    return FleetConfig(
        serve=scfg, size=size, vnodes=16,
        journal_root=str(tmp_path / "journals") if tmp_path else None,
        health_interval_s=0.05, death_checks=2,
        backoff_s=0.01, backoff_cap_s=0.05, **kw)


def test_reconcile_scales_fleet_up_and_down():
    """The breathe cycle on a real inproc fleet, clock injected:
    held queue pressure spawns + ring-joins w1, held calm retires it
    (highest index first), and the scale-up cooldown blocks a second
    spawn until the clock moves past it.  Verdicts land in the
    ``control.*`` counters and the event deque."""
    pol = ControlPolicy(min_workers=1, max_workers=2, queue_high=2.0,
                        queue_low=0.5, scale_up_windows=2,
                        scale_down_windows=2, scale_up_cooldown_s=10.0,
                        scale_down_cooldown_s=0.0)
    now = [0.0]
    with Fleet(_fleet_cfg()) as fl:
        cp = ControlPlane(fl, pol, clock=lambda: now[0])
        busy = {"w0": _health(depth=5)}
        assert cp.reconcile(busy) is None          # window 1/2
        ev = cp.reconcile(busy)                    # window 2/2 -> spawn
        assert ev and ev["verdict"] == "scale_up" and ev["worker"] == "w1"
        assert ev["cause"] == "queue_pressure"
        assert set(fl.workers) == {"w0", "w1"}
        assert "w1" in fl.router.ring.members()

        # at max_workers: held pressure changes nothing
        both_busy = {w: _health(depth=5) for w in ("w0", "w1")}
        assert cp.reconcile(both_busy) is None
        assert len(fl.workers) == 2

        # held calm: the emptiest retireable worker goes, highest
        # index first, and the ring restores to w0 alone
        both_calm = {w: _health(depth=0) for w in ("w0", "w1")}
        assert cp.reconcile(both_calm) is None     # window 1/2
        ev = cp.reconcile(both_calm)
        assert ev and ev["verdict"] == "scale_down" and ev["worker"] == "w1"
        assert set(fl.workers) == {"w0"}
        assert fl.router.ring.members() == ["w0"]
        # at min_workers: held calm changes nothing
        calm0 = {"w0": _health(depth=0)}
        for _ in range(4):
            assert cp.reconcile(calm0) is None
        assert set(fl.workers) == {"w0"}

        # scale-up cooldown: pressure holds but the clock hasn't moved
        assert cp.reconcile(busy) is None
        assert cp.reconcile(busy) is None          # windows met, cooled
        assert len(fl.workers) == 1
        now[0] = 11.0                              # past the cooldown
        ev = cp.reconcile(busy)
        assert ev and ev["verdict"] == "scale_up"
        assert set(fl.workers) == {"w0", "w1"}

        snap = (obs_metrics.snapshot() or {}).get("counters") or {}
        assert snap.get("control.scale_up") == 2
        assert snap.get("control.scale_down") == 1
        # decision-plane mirror: every verdict funnels one decision
        assert snap.get("serve.decision.scale_up") == 2
        events = fl.control.status()  # the fleet's own plane is static
        assert events["autoscale"] is False
        assert [e["verdict"] for e in cp.events] == [
            "scale_up", "scale_down", "scale_up"]


def test_scale_down_never_strands_work(monkeypatch):
    """The satellite lock: a worker holding queued requests, inflight
    dispatches, an unreplayed journal entry, or router-pending futures
    is NOT retireable — reconcile stays armed rather than retiring it —
    and a retire that races admitted traffic aborts and restores ring
    membership + the gate."""
    pol = ControlPolicy(min_workers=1, max_workers=2, queue_high=2.0,
                        queue_low=0.5, scale_down_windows=1,
                        scale_down_cooldown_s=0.0)
    with Fleet(_fleet_cfg(size=2)) as fl:
        cp = ControlPlane(fl, pol, clock=lambda: 0.0)

        assert cp._retireable("w1", _health(depth=0)) is True
        assert cp._retireable("w1", None) is False
        assert cp._retireable("w1", _health(depth=3)) is False
        assert cp._retireable("w1", _health(recovering=True)) is False
        inflight = dict(_health(), inflight=1)
        assert cp._retireable("w1", inflight) is False
        unreplayed = dict(_health(),
                          journal={"admitted": 3, "done": 2, "deduped": 0,
                                   "rejected": 0, "poisoned": 0})
        assert cp._retireable("w1", unreplayed) is False
        settled = dict(_health(),
                       journal={"admitted": 3, "done": 2, "deduped": 1,
                                "rejected": 0, "poisoned": 0})
        assert cp._retireable("w1", settled) is True
        monkeypatch.setattr(fl.router, "pending_for", lambda wid: True)
        assert cp._retireable("w1", _health()) is False
        monkeypatch.undo()

        # every worker unsafe -> reconcile returns None, nobody retired
        stuck = {w: dict(_health(), inflight=1) for w in fl.workers}
        assert cp.reconcile(stuck) is None
        assert set(fl.workers) == {"w0", "w1"}

        # raced retire: health looked clean at pick time, but by the
        # gate-and-recheck the worker holds queued work -> abort,
        # membership and gate fully restored
        monkeypatch.setattr(fl.workers["w1"], "health",
                            lambda: dict(_health(depth=2), accepting=True))
        ev = cp.scale_down("w1", "idle")
        assert ev is None
        assert "w1" in fl.workers
        assert "w1" in fl.router.ring.members()
        assert fl._gates.get("w1") is None
        assert [e["verdict"] for e in cp.events] == ["scale_down_abort"]


# ------------------------------------------------------- warm scale-up


def test_scale_up_warms_joining_worker(tmp_path):
    """ISSUE acceptance: with the exemplar catalog active, scale-up
    pre-stages the joining worker's home styles (ring-placement-aware
    ``warm_for_fleet``), so the first request for a style homed on the
    joiner is pure tier hits — zero cold feature builds after the
    join."""
    from image_analogies_tpu.catalog import build as catalog_build
    from image_analogies_tpu.catalog import tiers
    from image_analogies_tpu.serve.router import Ring

    params = drills.catalog_params(str(tmp_path), levels=1)
    scfg = dataclasses.replace(
        drills.serve_config(workers=1, max_batch=4, batch_window_ms=20.0),
        params=params)
    fcfg = FleetConfig(serve=scfg, size=1, vnodes=16,
                       health_interval_s=0.05, death_checks=2,
                       backoff_s=0.01, backoff_cap_s=0.05)

    # pick an exemplar whose PREFETCH home in the post-join ring is the
    # joiner: warm_for_fleet(only_worker="w1") stages exactly these
    ring = Ring(vnodes=16)
    ring.add("w0")
    ring.add("w1")
    chosen = None
    for seed in range(64):
        rng = np.random.RandomState(seed)
        a, ap, b = (rng.rand(12, 12).astype(np.float32) for _ in range(3))
        if ring.successors(tiers.style_key(a, ap))[0] == "w1":
            chosen = (a, ap, b)
            break
    assert chosen is not None
    a, ap, b = chosen
    baseline = drills.run_image(a, ap, b, params)

    catalog_build.build_style(a, ap, params, root_dir=str(tmp_path),
                              target=b)
    tiers.clear()                     # fresh process: disk only
    tiers.configure(str(tmp_path))
    try:
        with Fleet(fcfg) as fl:
            assert list(fl.workers) == ["w0"]
            ev = fl.control.scale_up("test_join")
            assert ev["verdict"] == "scale_up" and ev["worker"] == "w1"
            before = dict((obs_metrics.snapshot() or {})
                          .get("counters") or {})
            res = fl.submit(a, ap, b).result(timeout=120)
            after = dict((obs_metrics.snapshot() or {})
                         .get("counters") or {})
    finally:
        tiers.clear()
        tiers.configure(None)

    assert np.array_equal(np.asarray(res.bp), baseline)
    delta = {k: after.get(k, 0) - before.get(k, 0)
             for k in set(after) | set(before)
             if k.startswith("catalog.")}
    # the join pre-staged the style: the request hits warm tiers and
    # never rebuilds features
    assert delta.get("catalog.builds", 0) == 0, delta
    hits = (delta.get("catalog.hbm.hits", 0)
            + delta.get("catalog.host.hits", 0))
    assert hits >= 1, delta


# ------------------------------------------------------------- quotas


def test_tenant_quota_deterministic_clock():
    now = [0.0]
    q = TenantQuota(QosPolicy(quota_rps=1.0, quota_burst=2.0),
                    clock=lambda: now[0])
    assert q.try_admit("s0") and q.try_admit("s0")   # burst
    assert not q.try_admit("s0")                     # bucket empty
    now[0] = 1.0
    assert q.try_admit("s0")                         # 1 token refilled
    assert not q.try_admit("s0")
    assert q.throttled == 2
    snap = q.snapshot()
    assert snap["throttled"] == 2 and "s0" in snap["tenants"]
    # quota_rps=0 disables quotas entirely
    off = TenantQuota(QosPolicy(quota_rps=0.0), clock=lambda: now[0])
    assert all(off.try_admit("s0") for _ in range(100))


def test_tenant_quota_cost_share_penalty():
    """A tenant over ``share_cap`` of observed dispatch cost has its
    refill scaled by share_cap/share — the viral style throttles harder
    as it gets hotter; everyone else refills at full rate."""
    doc = {"tenants": [{"tenant": "hot", "cost_share": 1.0},
                       {"tenant": "cold", "cost_share": 0.1}]}
    now = [0.0]
    q = TenantQuota(QosPolicy(quota_rps=1.0, quota_burst=1.0,
                              share_cap=0.5, share_refresh_s=0.001),
                    shares_fn=lambda: doc, clock=lambda: now[0])
    assert q.try_admit("hot") and q.try_admit("cold")  # burst drained
    assert q.effective_rps("hot") == pytest.approx(0.5)
    assert q.effective_rps("cold") == pytest.approx(1.0)
    now[0] = 1.0
    assert q.try_admit("cold")       # full refill: 1 token in 1 s
    assert not q.try_admit("hot")    # penalized: only 0.5 tokens
    now[0] = 2.0
    assert q.try_admit("hot")        # 0.5 + 0.5 across two seconds


# ------------------------------------------------------ weighted fair


def _req(rid, tenant, priority=2, t_submit=None):
    z = np.zeros((2, 2), np.float32)
    kw = {} if t_submit is None else {"t_submit": t_submit}
    return Request(request_id=rid, a=z, ap=z, b=z,
                   params=drills.image_params(levels=1, retries=0),
                   key=("2x2", tenant), future=Future(),
                   priority=priority, **kw)


def test_weighted_fair_pop_interleaves_tenants():
    """Stride scheduling: a tenant's pass advances by 1/priority per
    pick, so an interactive (weight 4) tenant gets picked repeatedly
    before a background (weight 1) tenant's next turn — a thousand-
    waiter viral style still only gets its fair share of leaders."""
    from image_analogies_tpu.serve.queue import AdmissionQueue

    q = AdmissionQueue(depth=32, qos=QosPolicy(weighted_fair=True))
    for i in range(6):
        q.submit(_req(i, "viral", priority=1))
    for i in range(6, 8):
        q.submit(_req(i, "nice", priority=4))
    order = [q.pop_batch(1, 0.0)[0] for _ in range(8)]
    tenants = [str(r.key[-1]) for r in order]
    # first pick goes to the earliest arrival (both passes at floor),
    # then the interactive tenant's cheap strides pull BOTH its
    # requests ahead of viral's five remaining waiters
    assert tenants[:3] == ["viral", "nice", "nice"]
    assert tenants[3:] == ["viral"] * 5
    q.close()


def test_weighted_fair_aging_trumps_fairness():
    """Anti-starvation: a waiter older than the age bound leads no
    matter whose stride turn it is — fairness may reorder, never
    starve."""
    import time as _time

    from image_analogies_tpu.serve.queue import AdmissionQueue

    q = AdmissionQueue(depth=8, qos=QosPolicy(weighted_fair=True))
    q.submit(_req(0, "a", priority=1))
    assert str(q.pop_batch(1, 0.0)[0].key[-1]) == "a"   # a's pass -> 1.0
    # fairness would now prefer "b" (pass floor) — but a's next waiter
    # has aged past the bound (default 5 s), so it leads anyway
    q.submit(_req(1, "a", priority=1,
                  t_submit=_time.monotonic() - 10.0))
    q.submit(_req(2, "b", priority=4))
    assert q.pop_batch(1, 0.0)[0].request_id == 1
    assert q.pop_batch(1, 0.0)[0].request_id == 2
    q.close()


# -------------------------------------------------------- flash crowd


def test_arrival_schedule_deterministic_and_surging():
    from image_analogies_tpu.serve import loadgen

    kw = dict(t0=0.2, duration=0.5, mult=20.0, base_rps=40.0)
    s1 = loadgen.arrival_schedule(50, seed=3, **kw)
    s2 = loadgen.arrival_schedule(50, seed=3, **kw)
    assert s1 == s2                        # one seed, one schedule
    assert s1 != loadgen.arrival_schedule(50, seed=4, **kw)
    assert len(s1) == 50
    assert all(b >= a for a, b in zip(s1, s1[1:]))   # non-decreasing
    # the surge compresses arrivals: the window holds far more than
    # its share under the base rate
    inside = sum(1 for t in s1 if 0.2 <= t < 0.7)
    flat = loadgen.arrival_schedule(50, seed=3, t0=0.2, duration=0.5,
                                    mult=1.0, base_rps=40.0)
    inside_flat = sum(1 for t in flat if 0.2 <= t < 0.7)
    assert inside > 1.5 * max(inside_flat, 1)
    assert s1[-1] < flat[-1]       # the surge compresses the whole run

    assert loadgen.parse_flash_crowd("0.5, 2.0, 8") == {
        "t0": 0.5, "duration": 2.0, "mult": 8.0}
    for bad in ("", "1,2", "a,b,c", "-1,1,2", "0,0,2", "0,1,0.5"):
        with pytest.raises(ValueError):
            loadgen.parse_flash_crowd(bad)


# ---------------------------------------------------------- CLI smoke


def test_fleet_autoscale_cli_selftest(capsys):
    """`ia fleet --autoscale --selftest`: the fleet starts at the
    policy floor, the summary carries the control-plane section, and
    bit-identity still gates."""
    from image_analogies_tpu.cli import main

    rc = main(["fleet", "--selftest", "3", "--size", "2", "--autoscale",
               "--max-batch", "3", "--batch-window-ms", "20",
               "--levels", "1", "--backend", "cpu"])
    captured = capsys.readouterr()
    assert rc == 0, captured.err
    summary = json.loads(captured.err.strip().splitlines()[-1])
    assert summary["errors"] == 0 and summary["bit_identical"] is True
    ctl = summary["control"]
    assert ctl["autoscale"] is True
    assert ctl["policy"]["max_workers"] == 2
    assert "autoscale" in captured.out


def test_serve_flash_crowd_cli_selftest(capsys):
    """`ia serve --flash-crowd T0,DUR,MULT`: the paced selftest passes
    and records the surge shape in its summary."""
    from image_analogies_tpu.cli import main

    rc = main(["serve", "--selftest", "3", "--workers", "1",
               "--flash-crowd", "0.05,0.2,5", "--levels", "1",
               "--backend", "cpu"])
    captured = capsys.readouterr()
    assert rc == 0, captured.err
    summary = json.loads(captured.err.strip().splitlines()[-1])
    assert summary["bit_identical"] is True
    assert summary["flash_crowd"] == {"t0": 0.05, "duration": 0.2,
                                      "mult": 5.0}
