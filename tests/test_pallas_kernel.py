"""Direct Pallas argmin-kernel tests via interpret mode (SURVEY.md §4.3,
round-1 VERDICT item 4 / ADVICE medium).

`pallas_argmin_l2` only dispatches on real TPUs, so without these tests the
kernel's masking/tie-break/scratch logic would be exercised by nothing in CI.
``interpret=True`` runs the same kernel body through the Pallas interpreter
on CPU; `xla_argmin_l2` (plain jnp, HIGHEST precision) is the reference.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from image_analogies_tpu.ops.pallas_match import (
    pallas_argmin_l2,
    pallas_argmin_l2_prepadded,
    xla_argmin_l2,
)

HIGHEST = jax.lax.Precision.HIGHEST


def _mk(m, f, n, seed):
    rng = np.random.default_rng(seed)
    q = rng.standard_normal((m, f)).astype(np.float32)
    db = rng.standard_normal((n, f)).astype(np.float32)
    return jnp.asarray(q), jnp.asarray(db), jnp.sum(jnp.asarray(db) ** 2, 1)


@pytest.mark.parametrize("m,f,n,tile", [
    (7, 68, 500, 512),     # N < tile (single partial tile)
    (8, 68, 512, 512),     # exact tile fit
    (13, 68, 1300, 512),   # N not a multiple of tile, M odd
    (4, 136, 700, 256),    # F > 128 (RGB label features, padded to 256)
    (1, 20, 3, 512),       # degenerate tiny shapes
    (32, 68, 2048, 256),   # multi-tile grid (8 tiles)
])
def test_kernel_matches_xla(m, f, n, tile):
    q, db, dbn = _mk(m, f, n, seed=n + m)
    ref_i, ref_d = xla_argmin_l2(q, db, dbn)
    idx, d = pallas_argmin_l2(q, db, dbn, tile_n=tile, interpret=True,
                              precision=HIGHEST)
    np.testing.assert_array_equal(np.asarray(idx), np.asarray(ref_i))
    np.testing.assert_allclose(np.asarray(d), np.asarray(ref_d),
                               rtol=1e-5, atol=1e-5)


def test_padding_rows_never_win():
    # all real DB rows are FAR from the queries; if +inf masking of the
    # padded rows (zeros — which would be closest) regressed, they would win
    q, db, dbn = _mk(5, 68, 700, seed=0)
    db = db + 100.0
    dbn = jnp.sum(db * db, axis=1)
    idx, d = pallas_argmin_l2(q, db, dbn, tile_n=512, interpret=True,
                              precision=HIGHEST)
    assert int(jnp.max(idx)) < 700
    assert float(jnp.min(d)) > 1000.0


@pytest.mark.parametrize("dup_pair", [(3, 250), (0, 699), (511, 512)])
def test_duplicate_row_tiebreak_lowest_index(dup_pair):
    # a duplicated best row must resolve to the LOWEST index, including when
    # the duplicates land in different grid tiles (511 vs 512 at tile 512)
    lo, hi = dup_pair
    q, db, dbn = _mk(4, 68, 700, seed=9)
    best = q[0] * 1.0  # row equal to query 0 -> distance 0, the global min
    db = db.at[lo].set(best).at[hi].set(best)
    dbn = jnp.sum(db * db, axis=1)
    idx, d = pallas_argmin_l2(q, db, dbn, tile_n=512, interpret=True,
                              precision=HIGHEST)
    assert int(idx[0]) == lo
    np.testing.assert_allclose(float(d[0]), 0.0, atol=1e-4)


def test_prepadded_matches_plain():
    m, f, n, tile = 6, 68, 900, 512
    q, db, dbn = _mk(m, f, n, seed=4)
    ref_i, ref_d = pallas_argmin_l2(q, db, dbn, tile_n=tile, interpret=True,
                                    precision=HIGHEST)
    # pad exactly the way backends/tpu.py does per level
    fp = max((f + 127) // 128 * 128, 128)
    mp = (m + 7) // 8 * 8
    npad = (n + tile - 1) // tile * tile
    qp = jnp.zeros((mp, fp), jnp.float32).at[:m, :f].set(q)
    dbp = jnp.zeros((npad, fp), jnp.float32).at[:n, :f].set(db)
    dbnp = jnp.full((1, npad), jnp.inf, jnp.float32).at[0, :n].set(dbn)
    idx, score = pallas_argmin_l2_prepadded(qp, dbp, dbnp, tile_n=tile,
                                            interpret=True,
                                            precision=HIGHEST)
    qn = jnp.sum(q * q, axis=1)
    np.testing.assert_array_equal(np.asarray(idx[:m]), np.asarray(ref_i))
    np.testing.assert_allclose(np.asarray(score[:m] + qn), np.asarray(ref_d),
                               rtol=1e-5, atol=1e-5)


def test_bf16_mode_winner_within_tolerance():
    # bf16 mode trades exact picks for bandwidth; its contract (used by the
    # approximate batched strategy only) is that the winner's TRUE distance
    # is within bf16 noise of the true minimum
    m, f, n = 9, 68, 1500
    q, db, dbn = _mk(m, f, n, seed=11)
    ref_i, ref_d = xla_argmin_l2(q, db, dbn)
    idx, _ = pallas_argmin_l2(q, db, dbn, tile_n=512, interpret=True,
                              bf16=True)
    true_d = jnp.sum((db[idx] - q) ** 2, axis=1)
    # |d_pick - d_min| bounded by the bf16 quantization of the dot products
    scale = jnp.abs(ref_d) + jnp.sum(jnp.abs(db[idx] * q), axis=1)
    assert np.all(np.asarray(true_d) <= np.asarray(ref_d + 0.03 * scale))


def test_default_precision_is_argmin_grade_on_cpu():
    # on the interpreter there are no bf16 MXU passes: DEFAULT == HIGHEST.
    # This locks the kernel's plumbing of the precision static arg.
    q, db, dbn = _mk(5, 68, 600, seed=2)
    i1, _ = pallas_argmin_l2(q, db, dbn, tile_n=512, interpret=True,
                             precision=jax.lax.Precision.DEFAULT)
    i2, _ = pallas_argmin_l2(q, db, dbn, tile_n=512, interpret=True,
                             precision=HIGHEST)
    np.testing.assert_array_equal(np.asarray(i1), np.asarray(i2))
