"""Direct Pallas argmin-kernel tests via interpret mode (SURVEY.md §4.3,
round-1 VERDICT item 4 / ADVICE medium).

`pallas_argmin_l2` only dispatches on real TPUs, so without these tests the
kernel's masking/tie-break/scratch logic would be exercised by nothing in CI.
``interpret=True`` runs the same kernel body through the Pallas interpreter
on CPU; `xla_argmin_l2` (plain jnp, HIGHEST precision) is the reference.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from image_analogies_tpu.ops.pallas_match import (
    pallas_argmin_l2,
    pallas_argmin_l2_prepadded,
    xla_argmin_l2,
)

HIGHEST = jax.lax.Precision.HIGHEST


def _mk(m, f, n, seed):
    rng = np.random.default_rng(seed)
    q = rng.standard_normal((m, f)).astype(np.float32)
    db = rng.standard_normal((n, f)).astype(np.float32)
    return jnp.asarray(q), jnp.asarray(db), jnp.sum(jnp.asarray(db) ** 2, 1)


@pytest.mark.parametrize("m,f,n,tile", [
    (7, 68, 500, 512),     # N < tile (single partial tile)
    (8, 68, 512, 512),     # exact tile fit
    (13, 68, 1300, 512),   # N not a multiple of tile, M odd
    (4, 136, 700, 256),    # F > 128 (RGB label features, padded to 256)
    (1, 20, 3, 512),       # degenerate tiny shapes
    (32, 68, 2048, 256),   # multi-tile grid (8 tiles)
])
def test_kernel_matches_xla(m, f, n, tile):
    q, db, dbn = _mk(m, f, n, seed=n + m)
    ref_i, ref_d = xla_argmin_l2(q, db, dbn)
    idx, d = pallas_argmin_l2(q, db, dbn, tile_n=tile, interpret=True,
                              precision=HIGHEST)
    np.testing.assert_array_equal(np.asarray(idx), np.asarray(ref_i))
    np.testing.assert_allclose(np.asarray(d), np.asarray(ref_d),
                               rtol=1e-5, atol=1e-5)


def test_padding_rows_never_win():
    # all real DB rows are FAR from the queries; if +inf masking of the
    # padded rows (zeros — which would be closest) regressed, they would win
    q, db, dbn = _mk(5, 68, 700, seed=0)
    db = db + 100.0
    dbn = jnp.sum(db * db, axis=1)
    idx, d = pallas_argmin_l2(q, db, dbn, tile_n=512, interpret=True,
                              precision=HIGHEST)
    assert int(jnp.max(idx)) < 700
    assert float(jnp.min(d)) > 1000.0


@pytest.mark.parametrize("dup_pair", [(3, 250), (0, 699), (511, 512)])
def test_duplicate_row_tiebreak_lowest_index(dup_pair):
    # a duplicated best row must resolve to the LOWEST index, including when
    # the duplicates land in different grid tiles (511 vs 512 at tile 512)
    lo, hi = dup_pair
    q, db, dbn = _mk(4, 68, 700, seed=9)
    best = q[0] * 1.0  # row equal to query 0 -> distance 0, the global min
    db = db.at[lo].set(best).at[hi].set(best)
    dbn = jnp.sum(db * db, axis=1)
    idx, d = pallas_argmin_l2(q, db, dbn, tile_n=512, interpret=True,
                              precision=HIGHEST)
    assert int(idx[0]) == lo
    np.testing.assert_allclose(float(d[0]), 0.0, atol=1e-4)


def test_prepadded_matches_plain():
    m, f, n, tile = 6, 68, 900, 512
    q, db, dbn = _mk(m, f, n, seed=4)
    ref_i, ref_d = pallas_argmin_l2(q, db, dbn, tile_n=tile, interpret=True,
                                    precision=HIGHEST)
    # pad exactly the way backends/tpu.py does per level
    fp = max((f + 127) // 128 * 128, 128)
    mp = (m + 7) // 8 * 8
    npad = (n + tile - 1) // tile * tile
    qp = jnp.zeros((mp, fp), jnp.float32).at[:m, :f].set(q)
    dbp = jnp.zeros((npad, fp), jnp.float32).at[:n, :f].set(db)
    dbnp = jnp.full((1, npad), jnp.inf, jnp.float32).at[0, :n].set(dbn)
    idx, score = pallas_argmin_l2_prepadded(qp, dbp, dbnp, tile_n=tile,
                                            interpret=True,
                                            precision=HIGHEST)
    qn = jnp.sum(q * q, axis=1)
    np.testing.assert_array_equal(np.asarray(idx[:m]), np.asarray(ref_i))
    np.testing.assert_allclose(np.asarray(score[:m] + qn), np.asarray(ref_d),
                               rtol=1e-5, atol=1e-5)


def test_bf16_mode_winner_within_tolerance():
    # bf16 mode trades exact picks for bandwidth; its contract (used by the
    # approximate batched strategy only) is that the winner's TRUE distance
    # is within bf16 noise of the true minimum
    m, f, n = 9, 68, 1500
    q, db, dbn = _mk(m, f, n, seed=11)
    ref_i, ref_d = xla_argmin_l2(q, db, dbn)
    idx, _ = pallas_argmin_l2(q, db, dbn, tile_n=512, interpret=True,
                              bf16=True)
    true_d = jnp.sum((db[idx] - q) ** 2, axis=1)
    # |d_pick - d_min| bounded by the bf16 quantization of the dot products
    scale = jnp.abs(ref_d) + jnp.sum(jnp.abs(db[idx] * q), axis=1)
    assert np.all(np.asarray(true_d) <= np.asarray(ref_d + 0.03 * scale))


def test_default_precision_is_argmin_grade_on_cpu():
    # on the interpreter there are no bf16 MXU passes: DEFAULT == HIGHEST.
    # This locks the kernel's plumbing of the precision static arg.
    q, db, dbn = _mk(5, 68, 600, seed=2)
    i1, _ = pallas_argmin_l2(q, db, dbn, tile_n=512, interpret=True,
                             precision=jax.lax.Precision.DEFAULT)
    i2, _ = pallas_argmin_l2(q, db, dbn, tile_n=512, interpret=True,
                             precision=HIGHEST)
    np.testing.assert_array_equal(np.asarray(i1), np.asarray(i2))


# ------------------------------------------------------- top-2 (two-pass)


def _np_top2(q, dbp, dbn_row):
    """NumPy reference: top-2 (score, index) pairs, (val, idx) lexicographic
    — scores exactly as the kernel computes them (fp32 dot on the
    interpreter)."""
    scores = dbn_row[None, :] - 2.0 * (
        np.asarray(q, np.float32) @ np.asarray(dbp, np.float32).T)
    order = np.lexsort((np.arange(scores.shape[1])[None, :].repeat(
        scores.shape[0], 0), scores), axis=1)
    i1, i2 = order[:, 0], order[:, 1]
    rows = np.arange(scores.shape[0])
    return i1, scores[rows, i1], i2, scores[rows, i2]


def _pad_for_kernel(q, db, dbn, tile, dtype=np.float32):
    m, f = q.shape
    n = db.shape[0]
    fp = max((f + 127) // 128 * 128, 128)
    mp = (m + 15) // 16 * 16
    npad = (n + tile - 1) // tile * tile
    qp = jnp.zeros((mp, fp), dtype).at[:m, :f].set(q.astype(dtype))
    dbp = jnp.zeros((npad, fp), dtype).at[:n, :f].set(db.astype(dtype))
    dbnp = jnp.full((1, npad), jnp.inf, jnp.float32).at[0, :n].set(dbn)
    return qp, dbp, dbnp


@pytest.mark.parametrize("m,f,n,tile", [
    (7, 68, 500, 512),    # single partial tile
    (13, 68, 1300, 512),  # multi-tile, M odd
    (32, 68, 2048, 256),  # 8 tiles: cross-tile merge exercised hard
    (1, 20, 3, 512),      # degenerate tiny shapes
])
def test_top2_kernel_matches_numpy(m, f, n, tile):
    from image_analogies_tpu.ops.pallas_match import (
        pallas_argmin2_l2_prepadded,
    )

    q, db, dbn = _mk(m, f, n, seed=3 * n + m)
    qp, dbp, dbnp = _pad_for_kernel(np.asarray(q), np.asarray(db),
                                    np.asarray(dbn), tile)
    # HIGHEST: on a real chip the interpreter's dots run on the TPU at
    # DEFAULT (bf16) otherwise, and the NumPy fp32 reference diverges
    i1, v1, i2, v2 = pallas_argmin2_l2_prepadded(qp, dbp, dbnp, tile_n=tile,
                                                 interpret=True,
                                                 precision=HIGHEST)
    # reference over the PADDED db (padding rows scored +inf via dbn)
    ref = _np_top2(np.asarray(qp), np.asarray(dbp),
                   np.asarray(dbnp)[0])
    np.testing.assert_array_equal(np.asarray(i1)[:m], ref[0][:m])
    np.testing.assert_array_equal(np.asarray(i2)[:m], ref[2][:m])
    # on a real chip (IA_TEST_PLATFORM=axon) the interpreter's dots run on
    # the TPU, where HIGHEST carries ~2^-24-relative-to-SCALE error (scores
    # are differences of O(||q||^2+||db||^2) terms) — scale-relative
    # tolerance there; the CPU interpreter computes true fp32 and keeps the
    # tight bound
    if jax.default_backend() == "cpu":
        tol = dict(rtol=1e-5, atol=1e-5)
    else:
        scale = float(np.abs(ref[1][:m]).max()
                      + np.abs(ref[3][:m]).max()) + 1.0
        tol = dict(atol=3e-6 * scale)
    np.testing.assert_allclose(np.asarray(v1)[:m], ref[1][:m], **tol)
    np.testing.assert_allclose(np.asarray(v2)[:m], ref[3][:m], **tol)


@pytest.mark.parametrize("trip", [(3, 250, 251), (0, 511, 512), (5, 6, 7)])
def test_top2_exact_ties_stay_lowest_index(trip):
    # THREE identical best rows: top-2 must be the two LOWEST indices, in
    # order — including across a tile boundary (511, 512) — so the two-pass
    # scheme's fp32 re-score inherits the lowest-index tie convention
    from image_analogies_tpu.ops.pallas_match import (
        pallas_argmin2_l2_prepadded,
    )

    a, b, c = trip
    q, db, dbn = _mk(4, 68, 700, seed=21)
    best = q[0] * 1.0
    db = db.at[a].set(best).at[b].set(best).at[c].set(best)
    dbn = jnp.sum(db * db, axis=1)
    qp, dbp, dbnp = _pad_for_kernel(np.asarray(q), np.asarray(db),
                                    np.asarray(dbn), 512)
    i1, _, i2, _ = pallas_argmin2_l2_prepadded(qp, dbp, dbnp, tile_n=512,
                                               precision=HIGHEST,
                                               interpret=True)
    assert int(i1[0]) == a
    assert int(i2[0]) == b


def test_top2_single_row_db_second_invalid():
    from image_analogies_tpu.ops.pallas_match import (
        prepadded_argmin2_queries,
    )

    q, db, dbn = _mk(3, 20, 1, seed=5)
    fp = 128
    dbp = jnp.zeros((512, fp), jnp.float32).at[:1, :20].set(db)
    dbnp = jnp.full((1, 512), jnp.inf, jnp.float32).at[0, :1].set(dbn)
    # interpret path: call the jit entry through its wrapper on CPU
    import functools
    from image_analogies_tpu.ops import pallas_match as pm

    i1, v1, i2, v2 = pm.pallas_argmin2_l2_prepadded(
        jnp.zeros((8, fp), jnp.float32).at[:3, :20].set(q), dbp, dbnp,
        tile_n=512, interpret=True)
    assert np.all(np.asarray(i1)[:3] == 0)
    # only one real row: the second candidate must be a padding row (+inf)
    assert not np.any(np.isfinite(np.asarray(v2)[:3]))


def test_two_pass_anchor_equals_exact_anchor_semantics():
    # the full two-pass contract, interpreter-level: top-2 picks + fp32
    # re-score + (val, idx) lexicographic selection == exact fp32 argmin
    # (on the interpreter the scan pass is fp32, so the candidate always
    # contains the true argmin; this locks the selection/re-score plumbing)
    from image_analogies_tpu.ops.pallas_match import (
        pallas_argmin2_l2_prepadded,
        xla_argmin_l2,
    )

    m, f, n, tile = 16, 68, 1500, 512
    q, db, dbn = _mk(m, f, n, seed=33)
    ref_i, ref_d = xla_argmin_l2(q, db, dbn)
    qp, dbp, dbnp = _pad_for_kernel(np.asarray(q), np.asarray(db),
                                    np.asarray(dbn), tile)
    i1, _, i2, v2 = pallas_argmin2_l2_prepadded(qp, dbp, dbnp, tile_n=tile,
                                                interpret=True,
                                                precision=HIGHEST)
    i1, i2, v2 = (np.asarray(x)[:m] for x in (i1, i2, v2))
    i2c = np.minimum(i2, n - 1)
    d1 = np.sum((np.asarray(db)[i1] - np.asarray(q)) ** 2, axis=1)
    d2 = np.where(np.isfinite(v2),
                  np.sum((np.asarray(db)[i2c] - np.asarray(q)) ** 2, axis=1),
                  np.inf)
    use2 = (d2 < d1) | ((d2 == d1) & (i2 < i1))
    pick = np.where(use2, i2, i1)
    np.testing.assert_array_equal(pick, np.asarray(ref_i))


# ------------------------------- round-3: per-tile champions + packed scan


def test_bf16_split_is_exact_and_fold_proof():
    # The split must reconstruct x EXACTLY through (hi + lo) / (d1+d2+r2)
    # and the parts must be bf16-representable — this is what makes the
    # multi-pass schemes immune to --xla_allow_excess_precision folding
    # (the dtype-round-trip split collapsed to a single pass, measured
    # round 3; see bf16_split2's docstring).
    from image_analogies_tpu.ops.pallas_match import bf16_split2, bf16_split3

    rng = np.random.default_rng(5)
    x = jnp.asarray(rng.standard_normal((64, 68)).astype(np.float32) * 3)
    hi, lo = jax.jit(bf16_split2)(x)
    np.testing.assert_array_equal(np.asarray(hi) + np.asarray(lo),
                                  np.asarray(x))
    # hi is exactly bf16-representable (bf16 round-trip is the identity)
    np.testing.assert_array_equal(
        np.asarray(hi), np.asarray(hi.astype(jnp.bfloat16).astype(
            jnp.float32)))
    d1, d2, r2 = jax.jit(bf16_split3)(x)
    np.testing.assert_array_equal(
        np.asarray(d1) + np.asarray(d2) + np.asarray(r2), np.asarray(x))
    assert float(jnp.max(jnp.abs(r2))) <= 2.0 ** -14 * float(
        jnp.max(jnp.abs(x)))


@pytest.mark.parametrize("m,n,tile", [(13, 1300, 512), (8, 512, 128)])
def test_pertile_champions_match_numpy(m, n, tile):
    # per-tile (max, argmax) of s2 = q.db - ||db||^2/2 against a NumPy
    # reference, including lowest-index-first in-tile ties
    from image_analogies_tpu.ops.pallas_match import (
        _round_up,
        pertile_champions_queries,
    )

    f = 68
    rng = np.random.default_rng(11)
    q = rng.standard_normal((m, f)).astype(np.float32)
    db = rng.standard_normal((n, f)).astype(np.float32)
    db[5] = db[2]  # duplicate rows -> in-tile tie
    q[0] = db[2]
    fp = 128
    npad = _round_up(n, tile)
    dbp = jnp.zeros((npad, fp), jnp.float32).at[:n, :f].set(db)
    dbnh = jnp.full((1, npad), jnp.inf, jnp.float32).at[0, :n].set(
        0.5 * (db ** 2).sum(1))
    vals, idx = pertile_champions_queries(
        jnp.asarray(q), dbp, dbnh, tile_n=tile,
        precision=HIGHEST, interpret=True)
    vals, idx = np.asarray(vals), np.asarray(idx)
    ntiles = npad // tile
    assert vals.shape == (m, ntiles) and idx.shape == (m, ntiles)
    for t in range(ntiles):
        sl = slice(t * tile, min((t + 1) * tile, n))
        if sl.start >= n:
            assert not np.isfinite(vals[:, t]).any()
            continue
        s2 = q @ db[sl].T - 0.5 * (db[sl] ** 2).sum(1)[None, :]
        np.testing.assert_allclose(s2.max(1), vals[:, t], atol=1e-4)
        np.testing.assert_array_equal(s2.argmax(1) + t * tile, idx[:, t])
    # duplicate-tie: q[0] hits rows 2 and 5 (same tile at tile=512);
    # first occurrence must win
    if tile >= 8:
        assert idx[0, 0] == 2


def test_packed3_reproduces_sixpass_product_set():
    # the 3-pass packed scan's scores must match the explicit 6-product
    # NumPy sum (q1d1 + q1d2 + q2d1 + q1d3 + q2d2 + q3d1) and resolve
    # exact-hit queries to the lowest duplicate index after champion argmax
    from image_analogies_tpu.ops.pallas_match import (
        bf16_split3,
        packed3_champions,
    )

    rng = np.random.default_rng(7)
    n, L, m, tile, npad, pk = 700, 55, 17, 128, 1024, 128
    x = rng.standard_normal((n, L)).astype(np.float32)
    x[300] = x[100]
    q = rng.standard_normal((m, L)).astype(np.float32)
    q[3] = x[100]

    def np_split3(a):
        d1, d2, r2 = (np.asarray(v) for v in bf16_split3(jnp.asarray(a)))
        return (d1, d2,
                np.asarray(jnp.asarray(r2, jnp.bfloat16), np.float32))

    d1, d2, d3 = np_split3(x)
    q1, q2, q3 = np_split3(q)

    def pack(left, right):
        w = jnp.zeros((npad, pk), jnp.bfloat16)
        return w.at[:n, :L].set(jnp.asarray(left, jnp.bfloat16)).at[
            :n, L:2 * L].set(jnp.asarray(right, jnp.bfloat16))

    nrm = (x ** 2).sum(1)
    dbnh = jnp.full((1, npad), jnp.inf, jnp.float32).at[0, :n].set(0.5 * nrm)
    vals, idx = packed3_champions(
        jnp.asarray(q1, jnp.bfloat16), jnp.asarray(q2, jnp.bfloat16),
        jnp.asarray(q3, jnp.bfloat16), pack(d1, d2), pack(d3, d1), dbnh,
        tile_n=tile, interpret=True)
    vals, idx = np.asarray(vals), np.asarray(idx)
    dots = (q1 @ d1.T + q1 @ d2.T + q2 @ d1.T
            + q1 @ d3.T + q2 @ d2.T + q3 @ d1.T)
    s2 = dots - 0.5 * nrm[None, :]
    for t in range(npad // tile):
        sl = slice(t * tile, min((t + 1) * tile, n))
        if sl.start >= n:
            continue
        np.testing.assert_allclose(s2[:, sl].max(1), vals[:, t], atol=2e-5)
    # champion selection: exact-hit duplicate pair resolves lowest-index
    pick = idx[np.arange(m), vals.argmax(1)]
    assert pick[3] == 100
    # fp32-grade accuracy: the product set tracks the f64 exact scores
    exact = (q.astype(np.float64) @ x.astype(np.float64).T
             - 0.5 * nrm.astype(np.float64)[None, :])
    assert np.abs(s2 - exact).max() < 2e-5


def test_exact_hi2_level_build_and_anchor_shapes():
    # end-to-end level build in packed mode on the CPU interpreter is not
    # possible (pallas only dispatches on TPU), but the pad geometry +
    # live-column bookkeeping must hold for any spec; lock the invariants
    # the anchor relies on: 2L <= packed width, live mask matches the
    # causal structure, the scan tile divides every realizable npad.
    from image_analogies_tpu.tune import resolve as tune
    from image_analogies_tpu.tune.geometry import default_tile_rows
    from image_analogies_tpu.ops.features import spec_for_level
    from image_analogies_tpu.config import AnalogyParams

    # (3, 7) gives spec.total=309 -> fp=384, the config whose un-rounded
    # 2730-row build tile used to leave npads with no power-of-2 divisor
    # above 2 (review round 3) — tile_rows now rounds to multiples of 256
    for src_channels, patch in ((1, 5), (3, 5), (1, 7), (3, 7)):
        spec = spec_for_level(AnalogyParams(patch_size=patch), 0, 3,
                              src_channels)
        live = spec.query_live_mask()
        l = int(live.sum())
        # non-causal fine-filt positions: all but the (p^2-1)/2 causal ones
        dead = spec.fine_n - (spec.fine_n - 1) // 2
        assert l == spec.total - dead
        pk = max((2 * l + 127) // 128 * 128, 128)
        assert 2 * l <= pk
        assert default_tile_rows(spec.total) % 256 == 0
        # every realizable npad (multiple of the build pad tile, which the
        # backend rounds to multiples of 256) is divisible by the scan tile
        for na in (130, 4096, 6784, 65536, 262144, 1048576):
            pad_tile = min(tune.tile_rows(spec.total),
                           max((na + 255) // 256 * 256, 256))
            npad = (na + pad_tile - 1) // pad_tile * pad_tile
            tile = tune.scan_tile(npad, pk)
            assert npad % tile == 0, (na, npad, tile)
            assert tile >= 128  # the halving loop may stop one below 256


def test_packed2_reproduces_fourterm_product_set():
    # the 2-pass packed scan (auto's large-level default) must match the
    # explicit 4-product NumPy sum q1d1 + q1d2 + q2d1 + q1d3 with
    # W1=[d1|d2], W2=[d1|d3] — the lane arrangement differs from packed3's
    # W2=[d3|d1], exactly the asymmetry this test pins down
    from image_analogies_tpu.ops.pallas_match import (
        bf16_split3,
        packed2_champions,
    )

    rng = np.random.default_rng(9)
    n, L, m, tile, npad, pk = 700, 55, 17, 128, 1024, 128
    x = rng.standard_normal((n, L)).astype(np.float32)
    x[300] = x[100]
    q = rng.standard_normal((m, L)).astype(np.float32)
    q[3] = x[100]

    def np_split3(a):
        d1, d2, r2 = (np.asarray(v) for v in bf16_split3(jnp.asarray(a)))
        return (d1, d2,
                np.asarray(jnp.asarray(r2, jnp.bfloat16), np.float32))

    d1, d2, d3 = np_split3(x)
    q1, q2, _ = np_split3(q)

    def pack(left, right):
        w = jnp.zeros((npad, pk), jnp.bfloat16)
        return w.at[:n, :L].set(jnp.asarray(left, jnp.bfloat16)).at[
            :n, L:2 * L].set(jnp.asarray(right, jnp.bfloat16))

    nrm = (x ** 2).sum(1)
    dbnh = jnp.full((1, npad), jnp.inf, jnp.float32).at[0, :n].set(0.5 * nrm)
    vals, idx = packed2_champions(
        jnp.asarray(q1, jnp.bfloat16), jnp.asarray(q2, jnp.bfloat16),
        pack(d1, d2), pack(d1, d3), dbnh, tile_n=tile, interpret=True)
    vals, idx = np.asarray(vals), np.asarray(idx)
    dots = q1 @ d1.T + q1 @ d2.T + q2 @ d1.T + q1 @ d3.T
    s2 = dots - 0.5 * nrm[None, :]
    for t in range(npad // tile):
        sl = slice(t * tile, min((t + 1) * tile, n))
        if sl.start >= n:
            continue
        np.testing.assert_allclose(s2[:, sl].max(1), vals[:, t], atol=2e-5)
        np.testing.assert_array_equal(s2[:, sl].argmax(1) + t * tile,
                                      idx[:, t])
    # exact-hit duplicate pair resolves lowest-index after champion argmax
    pick = idx[np.arange(m), vals.argmax(1)]
    assert pick[3] == 100


# ------------------------- round-4: champion-in-kernel + 1-stream variants


def test_packed_best_matches_champion_select():
    """`packed2_best` (champion folded into kernel scratch) must reproduce
    the shipping per-tile-champions + XLA-select pipeline exactly,
    including lowest-index ties; `packed1w_best` must compute exactly its
    documented single-stream product set q1.d1 + q1.d2 + q2.d1."""
    import jax.numpy as jnp

    from image_analogies_tpu.ops.pallas_match import (
        _round_up,
        bf16_split3,
        packed1w_best,
        packed2_best,
        packed2_champions,
    )

    rng = np.random.default_rng(0)
    m, l, n, tile = 13, 55, 1024, 256
    kp = _round_up(2 * l, 128)
    x = rng.standard_normal((n, l)).astype(np.float32) * 0.1
    x[5] = x[3]  # exact duplicate rows: ties must stay lowest-index
    q = rng.standard_normal((m, l)).astype(np.float32) * 0.1
    q[2] = x[3]
    d1, d2, d3 = bf16_split3(jnp.asarray(x))

    def pack(a, b):
        z = jnp.zeros((n, kp), jnp.bfloat16)
        return (z.at[:, :l].set(a.astype(jnp.bfloat16))
                .at[:, l:2 * l].set(b.astype(jnp.bfloat16)))

    w1, w2 = pack(d1, d2), pack(d1, d3)
    nrm = jnp.sum(jnp.asarray(x) ** 2, axis=1)
    dbnh = (0.5 * nrm)[None, :]
    g1, g2, _ = bf16_split3(jnp.asarray(q))
    q1 = g1.astype(jnp.bfloat16)
    q2 = g2.astype(jnp.bfloat16)

    vals, idx = packed2_champions(q1, q2, w1, w2, dbnh, tile_n=tile,
                                  interpret=True)
    k = jnp.argmax(vals, axis=1)
    ref_i = np.asarray(jnp.take_along_axis(idx, k[:, None], axis=1)[:, 0])
    ref_v = np.asarray(jnp.take_along_axis(vals, k[:, None], axis=1)[:, 0])

    bi, bv = packed2_best(q1, q2, w1, w2, dbnh, tile_n=tile, interpret=True)
    np.testing.assert_array_equal(np.asarray(bi), ref_i)
    np.testing.assert_array_equal(np.asarray(bv), ref_v)

    d1f, d2f = np.asarray(d1, np.float32), np.asarray(d2, np.float32)
    q1f, q2f = np.asarray(q1, np.float32), np.asarray(q2, np.float32)
    s_ref = (q1f @ d1f.T + q1f @ d2f.T + q2f @ d1f.T
             - np.asarray(0.5 * nrm)[None, :])
    wi, wv = packed1w_best(q1, q2, w1, dbnh, tile_n=tile, interpret=True)
    np.testing.assert_array_equal(np.asarray(wi), np.argmax(s_ref, axis=1))
    np.testing.assert_allclose(np.asarray(wv), s_ref.max(axis=1), rtol=1e-6,
                               atol=1e-6)

    # packed2wn_best — the SHIPPING exact_hi2_2p scan: full 2p product
    # set with norms riding W1's lanes; picks must equal the
    # exact-norm-subtract reference (duplicate rows included: identical
    # norm lanes keep exact ties lowest-index), scores within the
    # documented ~2^-24-relative norm-lane band
    from image_analogies_tpu.ops.pallas_match import (
        add_norm_lanes,
        packed2wn_best,
    )

    d3f = np.asarray(d3, np.float32)
    s2p = s_ref + q1f @ d3f.T
    w1n = add_norm_lanes(w1, 0.5 * nrm, l)
    ni, nv = packed2wn_best(q1, q2, w1n, w2, tile_n=tile, interpret=True)
    np.testing.assert_array_equal(np.asarray(ni), np.argmax(s2p, axis=1))
    np.testing.assert_allclose(np.asarray(nv), s2p.max(axis=1), rtol=0,
                               atol=5e-7)
    assert ni[2] == 3  # duplicate-row tie stays lowest-index

    # packed2k_best — the SHIPPING K-wide single-array form of the same
    # product set (one MXU dot per tile, d1 laid down twice): identical
    # picks, scores within the norm-lane band
    from image_analogies_tpu.ops.pallas_match import packed2k_best

    o2 = 2 * l + 3
    kp2 = 256
    wkk = jnp.zeros((n, kp2), jnp.bfloat16)
    wkk = (wkk.at[:, :l].set(d1.astype(jnp.bfloat16))
           .at[:, l:2 * l].set(d2.astype(jnp.bfloat16)))
    wkk = add_norm_lanes(wkk, 0.5 * nrm, l)
    wkk = (wkk.at[:, o2:o2 + l].set(d1.astype(jnp.bfloat16))
           .at[:, o2 + l:o2 + 2 * l].set(d3.astype(jnp.bfloat16)))
    ki, kv = packed2k_best(q1, q2, wkk, tile_n=tile, interpret=True)
    np.testing.assert_array_equal(np.asarray(ki), np.argmax(s2p, axis=1))
    np.testing.assert_allclose(np.asarray(kv), s2p.max(axis=1), rtol=0,
                               atol=5e-7)
    assert ki[2] == 3
