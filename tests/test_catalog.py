"""catalog/ — content-addressed exemplar catalog with tiered resolution.

Tier-1 invariants locked here:

- bit-identity at EVERY tier: a request served from the resident
  ("HBM") tier, the host-RAM tier, or a sealed disk artifact produces
  exactly the bytes of a cold build (each tier asserted separately);
- the second request for a cataloged style skips the feature build
  entirely — proven by counters (``catalog.builds`` absent,
  ``catalog.hbm.hits`` == levels), not by timing;
- damage never poisons a load: a flipped byte or a torn tail
  quarantines the entry as ``.corrupt`` and the request rebuilds
  bit-identically (same contract — and the same assertion shapes — as
  tests/test_journal.py's segment-damage tests);
- prefetch is ring-placement-aware: ``warm_for_fleet`` consults
  ``Router.home_for_style`` and stages styles into host RAM, and a real
  fleet join pre-stages a cataloged style before traffic;
- ``ia bench``'s ``cold_start_ms`` methodology holds at toy scale and
  its trajectory gate has the legacy no-floor path;
- catalog/ is host-side only: no module-scope jax, no jit (grep lock,
  same regexes as serve's).
"""

import json
import os
import re

import numpy as np
import pytest

import bench
from image_analogies_tpu import cli
from image_analogies_tpu.catalog import build as catalog_build
from image_analogies_tpu.catalog import store as catalog_store
from image_analogies_tpu.catalog import tiers
from image_analogies_tpu.chaos import inject
from image_analogies_tpu.config import AnalogyParams
from image_analogies_tpu.models.analogy import create_image_analogy
from image_analogies_tpu.obs import trace as obs_trace
from image_analogies_tpu.utils.imageio import save_image


@pytest.fixture(autouse=True)
def _clean_catalog_state():
    """Memory tiers are module-global by design (cross-request warmth);
    tests must never leak entries or a configured root into the suite."""
    tiers.clear()
    tiers.configure(None)
    yield
    tiers.clear()
    tiers.configure(None)
    inject.disarm()


def _inputs(size=20, seed=7):
    rng = np.random.RandomState(seed)
    return (rng.rand(size, size).astype(np.float32),
            rng.rand(size, size).astype(np.float32),
            rng.rand(size, size).astype(np.float32))


def _params(catalog_dir=None, levels=2):
    return AnalogyParams(backend="cpu", levels=levels, patch_size=3,
                         coarse_patch_size=3, catalog_dir=catalog_dir,
                         metrics=True)


def _run(a, ap, b, p):
    """One synthesis; returns (bp plane, catalog.* counter dict)."""
    with obs_trace.run_scope(p) as ctx:
        out = np.asarray(create_image_analogy(a, ap, b, p).bp)
    counters = ctx.registry.snapshot()["counters"]
    return out, {k: v for k, v in counters.items()
                 if k.startswith("catalog.")}


# ------------------------------------------------- tiered bit-identity


def test_every_tier_serves_bit_identical(tmp_path):
    """The acceptance property: resident hit, host hit, and disk load
    each produce exactly the cold build's bytes — asserted tier by tier
    by surgically draining the tiers between requests."""
    a, ap, b = _inputs()
    ref = np.asarray(create_image_analogy(a, ap, b, _params()).bp)

    p = _params(catalog_dir=str(tmp_path))
    # cold: every tier misses, the request builds + seals
    out, c = _run(a, ap, b, p)
    assert np.array_equal(out, ref)
    assert c["catalog.builds"] == 2
    assert c["catalog.disk.misses"] == 2
    assert c["catalog.disk.write_bytes"] > 0

    # resident ("HBM") tier hit
    out, c = _run(a, ap, b, p)
    assert np.array_equal(out, ref)
    assert c == {"catalog.hbm.hits": 2}

    # host tier hit: drain ONLY the resident tier
    with tiers._LOCK:
        tiers._resident.clear()
    out, c = _run(a, ap, b, p)
    assert np.array_equal(out, ref)
    assert c["catalog.host.hits"] == 2
    assert "catalog.builds" not in c and "catalog.disk.hits" not in c

    # disk tier: drop both memory tiers (a fresh process)
    tiers.clear()
    out, c = _run(a, ap, b, p)
    assert np.array_equal(out, ref)
    assert c["catalog.disk.hits"] == 2
    assert c["catalog.disk.read_bytes"] > 0
    assert "catalog.builds" not in c


def test_second_request_skips_feature_build(tmp_path):
    """ISSUE acceptance: the second request for a cataloged style skips
    the feature build entirely, and the skip is visible in counters (the
    CPU backend is constructed fresh per request, so its private memo
    cannot be what served this)."""
    a, ap, b = _inputs()
    p = _params(catalog_dir=str(tmp_path))
    _, c1 = _run(a, ap, b, p)
    assert c1["catalog.builds"] == 2
    _, c2 = _run(a, ap, b, p)
    assert "catalog.builds" not in c2
    assert c2["catalog.hbm.hits"] == 2


def test_prebuilt_style_serves_without_any_build(tmp_path):
    """`ia catalog build`'s engine path: build_style seals entries whose
    keys MATCH what live requests resolve — the very first request of a
    fresh process is pure disk hits, zero builds."""
    a, ap, b = _inputs()
    p = _params(catalog_dir=str(tmp_path))
    ref = np.asarray(create_image_analogy(a, ap, b, _params()).bp)

    rep = catalog_build.build_style(a, ap, p, root_dir=str(tmp_path),
                                    target=b)
    assert rep["levels"] == 2 and len(rep["entries"]) == 2
    tiers.clear()  # fresh process: nothing in memory, artifacts on disk

    out, c = _run(a, ap, b, p)
    assert np.array_equal(out, ref)
    assert "catalog.builds" not in c
    assert c["catalog.disk.hits"] == 2


def test_video_clip_shares_anchor_frame_entries(tmp_path):
    """build_style's remap-anchor contract: entries built against
    target=frame0 resolve for the frame-0 request (same post-remap A
    planes); bit-identity holds regardless."""
    a, ap, b = _inputs()
    p = _params(catalog_dir=str(tmp_path))
    catalog_build.build_style(a, ap, p, root_dir=str(tmp_path), target=b)
    style = tiers.style_key(a, ap)
    keys_built = {k for k, _ in
                  catalog_store.list_entries(str(tmp_path), style)}
    tiers.clear()
    _, c = _run(a, ap, b, p)
    assert c.get("catalog.disk.hits") == 2  # every level resolved
    # a DIFFERENT target (different luminance stats) must NOT silently
    # reuse the anchored entries: remap changes A's bytes, so the keys
    # differ and the request builds its own
    b2 = _inputs(seed=23)[2]
    tiers.clear()
    _, c2 = _run(a, ap, b2, p)
    assert c2["catalog.builds"] == 2
    keys_after = {k for k, _ in
                  catalog_store.list_entries(str(tmp_path), style)}
    assert keys_built < keys_after  # new entries, old ones untouched


# ------------------------------------------------- damage + quarantine
# (same .corrupt contract — and the same assertion shapes — as the
# journal's torn-tail / flipped-byte tests)


def _seal_one_style(tmp_path):
    a, ap, b = _inputs()
    p = _params(catalog_dir=str(tmp_path))
    ref = np.asarray(create_image_analogy(a, ap, b, _params()).bp)
    _run(a, ap, b, p)
    style = tiers.style_key(a, ap)
    entries = catalog_store.list_entries(str(tmp_path), style)
    assert len(entries) == 2
    return a, ap, b, p, ref, style, entries


def test_flipped_byte_quarantines_and_rebuilds_bit_identical(tmp_path):
    a, ap, b, p, ref, style, entries = _seal_one_style(tmp_path)
    victim = catalog_store.entry_path(str(tmp_path), style, entries[0][0])
    blob = bytearray(open(victim, "rb").read())
    blob[len(blob) // 2] ^= 0xFF  # flip one payload byte
    with open(victim, "wb") as f:
        f.write(blob)

    tiers.clear()  # force the disk path
    out, c = _run(a, ap, b, p)
    assert np.array_equal(out, ref)                  # rebuilt, not served
    assert c["catalog.quarantined"] == 1
    assert os.path.exists(victim + ".corrupt")       # evidence kept
    assert c["catalog.builds"] == 1                  # only the victim
    assert c["catalog.disk.hits"] == 1               # the intact sibling
    # the rebuild resealed a fresh artifact in the victim's place
    assert os.path.exists(victim)


def test_torn_tail_quarantines_and_rebuilds_bit_identical(tmp_path):
    a, ap, b, p, ref, style, entries = _seal_one_style(tmp_path)
    victim = catalog_store.entry_path(str(tmp_path), style, entries[1][0])
    whole = open(victim, "rb").read()
    with open(victim, "wb") as f:
        f.write(whole[: len(whole) // 2])  # torn mid-write

    tiers.clear()
    out, c = _run(a, ap, b, p)
    assert np.array_equal(out, ref)
    assert c["catalog.quarantined"] == 1
    assert os.path.exists(victim + ".corrupt")
    assert c["catalog.builds"] == 1
    assert os.path.exists(victim)


def test_gc_prunes_litter_and_budget(tmp_path):
    a, ap, b, p, _ref, style, entries = _seal_one_style(tmp_path)
    d = catalog_store.style_dir(str(tmp_path), style)
    open(os.path.join(d, "torn.tmp.npz"), "wb").close()
    open(os.path.join(d, "old.npz.corrupt"), "wb").close()

    rep = catalog_store.gc(str(tmp_path))  # default: tmp litter only
    assert rep["removed_entries"] == 1
    assert os.path.exists(os.path.join(d, "old.npz.corrupt"))

    rep = catalog_store.gc(str(tmp_path), keep=[style], max_bytes=0,
                           purge_corrupt=True)
    # keep exempts the style's sealed entries; corrupt evidence purged
    assert not os.path.exists(os.path.join(d, "old.npz.corrupt"))
    assert len(catalog_store.list_entries(str(tmp_path), style)) == 2

    rep = catalog_store.gc(str(tmp_path), max_bytes=0)
    assert rep["removed_styles"] == [style]
    assert catalog_store.list_styles(str(tmp_path)) == []


# ------------------------------------------------- prefetch placement


class _RingRouter:
    """Stub with the one method warm_for_fleet consults."""

    def __init__(self, home):
        self._home = home
        self.asked = []

    def home_for_style(self, style):
        self.asked.append(style)
        return self._home


def test_warm_for_fleet_places_by_ring(tmp_path):
    a, ap, b = _inputs()
    p = _params(catalog_dir=str(tmp_path))
    catalog_build.build_style(a, ap, p, root_dir=str(tmp_path), target=b)
    style = tiers.style_key(a, ap)
    tiers.clear()

    router = _RingRouter("w1")
    rep = tiers.warm_for_fleet(router, root_dir=str(tmp_path))
    assert router.asked == [style]
    assert rep["placements"] == {style: "w1"}
    assert rep["styles"] == 1 and rep["entries"] == 2 and rep["bytes"] > 0
    assert tiers.snapshot()["host_entries"] == 2

    # only_worker (the multi-host shape): a host that does not own the
    # style stages nothing
    tiers.clear()
    rep = tiers.warm_for_fleet(_RingRouter("w1"), root_dir=str(tmp_path),
                               only_worker="w0")
    assert rep["styles"] == 0
    assert tiers.snapshot()["host_entries"] == 0


def test_fleet_join_prestages_cataloged_styles(tmp_path, monkeypatch):
    """A real fleet start pre-stages every cataloged style into host RAM
    before traffic arrives (serve/fleet.py's join hook)."""
    from image_analogies_tpu.chaos import drills
    from image_analogies_tpu.serve.fleet import Fleet
    from image_analogies_tpu.serve.types import FleetConfig

    a, ap, b = _inputs()
    p = _params(catalog_dir=str(tmp_path))
    catalog_build.build_style(a, ap, p, root_dir=str(tmp_path), target=b)
    tiers.clear()
    monkeypatch.setenv("IA_CATALOG_DIR", str(tmp_path))

    cfg = FleetConfig(serve=drills.serve_config(workers=1), size=2)
    with Fleet(cfg):
        snap = tiers.snapshot()
        assert snap["host_entries"] == 2
        assert snap["host_bytes"] > 0


def test_host_tier_budget_evicts_lru(monkeypatch):
    monkeypatch.setenv("IA_CATALOG_HOST_BYTES", "4096")
    with obs_trace.run_scope(AnalogyParams(metrics=True)) as ctx:
        for i in range(4):  # 4 x ~2 KiB entries > 4 KiB budget
            db = np.full((16, 32), float(i), np.float32)
            aff = np.zeros(16, np.float32)
            tiers.record_build("style", f"key{i}", db, aff)
        snap = ctx.registry.snapshot()
    c, g = snap["counters"], snap["gauges"]
    assert c["catalog.host.evictions"] >= 1
    assert c["catalog.host.evicted_bytes"] >= 2048
    assert g["catalog.host.bytes"] == tiers.snapshot()["host_bytes"]
    assert g["catalog.host.bytes"] <= 4096


def test_chaos_eviction_falls_through_bit_identical(tmp_path):
    """The devcache.tier drill's core, inline: an armed plan evicts the
    key mid-request on every resolution; output stays bit-identical and
    the evictions reconcile against disk-hit recoveries."""
    from image_analogies_tpu.chaos.plan import ChaosPlan, SiteRule

    a, ap, b = _inputs()
    p = _params(catalog_dir=str(tmp_path))
    ref = np.asarray(create_image_analogy(a, ap, b, _params()).bp)
    _run(a, ap, b, p)  # populate every tier

    plan = ChaosPlan(seed=3, sites=(
        ("devcache.tier", SiteRule(kind="corrupt", schedule=(0, 1))),))
    with inject.plan_scope(plan):
        out, c = _run(a, ap, b, p)
    assert np.array_equal(out, ref)
    assert c["catalog.chaos_evictions"] == 2
    assert c["catalog.disk.hits"] == 2  # both evictions recovered on disk


# ------------------------------------------------- config + checkpoint


def test_catalog_config_precedence(monkeypatch, tmp_path):
    from image_analogies_tpu.tune import warmup as tune_warmup

    assert not tiers.active()
    tune_warmup.apply_runtime_config(
        AnalogyParams(catalog_dir=str(tmp_path), catalog_host_bytes=123))
    assert tiers.root() == str(tmp_path)
    assert tiers.host_budget() == 123
    # env beats the configured values, read at call time
    monkeypatch.setenv("IA_CATALOG_DIR", "/elsewhere")
    monkeypatch.setenv("IA_CATALOG_HOST_BYTES", "456")
    assert tiers.root() == "/elsewhere"
    assert tiers.host_budget() == 456
    monkeypatch.delenv("IA_CATALOG_DIR")
    monkeypatch.delenv("IA_CATALOG_HOST_BYTES")
    # a catalog-free run clears the previous run's configuration
    tune_warmup.apply_runtime_config(AnalogyParams())
    assert not tiers.active()
    assert tiers.host_budget() == tiers._DEFAULT_HOST_BYTES
    with pytest.raises(ValueError):
        AnalogyParams(catalog_host_bytes=0)


def test_catalog_knobs_do_not_split_run_digest(tmp_path):
    """Catalog tiers are bit-identical by construction, so the checkpoint
    run digest must not change when they are configured — resumability
    survives flipping the catalog on."""
    from image_analogies_tpu.utils import checkpoint as ckpt

    base = AnalogyParams(backend="cpu")
    tiered = AnalogyParams(backend="cpu", catalog_dir=str(tmp_path),
                           catalog_host_bytes=1 << 20)
    shapes = ((20, 20), (20, 20))
    assert (ckpt.run_digest(base, *shapes)
            == ckpt.run_digest(tiered, *shapes))


# ------------------------------------------------- cold-start metric


def test_bench_cold_start_toy_scale():
    out = bench.measure_cold_start(size=20, levels=2)
    assert out["bit_identical"]
    assert out["cold_start_ms"] > 0
    assert out["cold_start_ms"] == out["warm_first_ms"]
    assert not tiers.active()  # the measurement cleans up after itself


def test_bench_check_gates_cold_start_with_no_floor_path(tmp_path):
    """Satellite 6: cold_start_ms rides `ia bench --check`.  A floored
    archive gates regressions; legacy archives (pre-catalog rounds)
    record the number without gating."""
    floored = {"points": [
        {"value": 6.0, "metric_key": "1024x1024", "cold_start_ms": 100.0,
         "round": 1, "file": "BENCH_r01.json", "source": "parsed"}]}
    ok = bench.check_regression(floored, fresh_value=6.0,
                                fresh_key="1024x1024", fresh_cold=105.0)
    assert ok["ok"] and ok["cold_start_floor"] == 100.0
    bad = bench.check_regression(floored, fresh_value=6.0,
                                 fresh_key="1024x1024", fresh_cold=500.0)
    assert not bad["ok"]
    assert any("cold_start_ms" in s for s in bad["problems"])

    legacy = {"points": [
        {"value": 6.0, "metric_key": "1024x1024",
         "round": 1, "file": "BENCH_r01.json", "source": "parsed"}]}
    rec = bench.check_regression(legacy, fresh_value=6.0,
                                 fresh_key="1024x1024", fresh_cold=500.0)
    assert rec["ok"]
    assert rec["cold_start_ms"] == 500.0
    assert rec["cold_start_floor"] is None

    # the headline extractor carries the rider out of an archive doc
    head = bench.extract_headline(
        {"parsed": {"value": 6.0, "metric": "1024x1024 wall",
                    "cold_start_ms": 42.0}})
    assert head["cold_start_ms"] == 42.0


# ------------------------------------------------- CLI + report


def test_catalog_cli_roundtrip(tmp_path, capsys):
    a, ap, b = _inputs()
    for name, img in (("a", a), ("ap", ap), ("b", b)):
        save_image(str(tmp_path / f"{name}.png"), img)
    root = str(tmp_path / "cat")

    assert cli.main(["catalog", "build", "--a", str(tmp_path / "a.png"),
                     "--ap", str(tmp_path / "ap.png"),
                     "--b", str(tmp_path / "b.png"),
                     "--dir", root, "--levels", "2",
                     "--patch-size", "3", "--coarse-patch-size", "3"]) == 0
    rep = json.loads(capsys.readouterr().out)
    assert rep["levels"] == 2 and len(rep["entries"]) == 2

    assert cli.main(["catalog", "inspect", root, "--json"]) == 0
    info = json.loads(capsys.readouterr().out)
    assert info["entries"] == 2 and info["corrupt"] == 0

    tiers.clear()
    assert cli.main(["catalog", "warm", root]) == 0
    warm = json.loads(capsys.readouterr().out)
    assert warm["entries"] == 2
    assert tiers.snapshot()["host_entries"] == 2

    assert cli.main(["catalog", "gc", root, "--max-bytes", "0"]) == 0
    gc = json.loads(capsys.readouterr().out)
    assert gc["removed_entries"] == 2
    assert cli.main(["catalog", "inspect", root, "--json"]) == 0
    assert json.loads(capsys.readouterr().out)["entries"] == 0


def test_report_renders_catalog_section(tmp_path):
    from image_analogies_tpu.obs import report as obs_report

    a, ap, b = _inputs()
    log = str(tmp_path / "run.jsonl")
    p = _params(catalog_dir=str(tmp_path / "cat")).replace(log_path=log)
    create_image_analogy(a, ap, b, p)
    tiers.clear()
    create_image_analogy(a, ap, b, p)  # disk-hit run rides the same log

    text = obs_report.report(log)
    assert "catalog:" in text
    assert "disk tier" in text and "cold builds" in text
    doc = json.loads(obs_report.report_json(log))
    cats = [r["catalog"] for r in doc["runs"] if r.get("catalog")]
    assert cats
    assert sum(c["builds"] for c in cats) == 2
    assert sum(c["disk"]["hits"] for c in cats) == 2


# ------------------------------------------------------- grep lock


def test_catalog_never_touches_jax():
    """catalog/ is a host-side store exactly like serve/: all device
    work stays behind the engine entry points — same lock, same
    regexes as test_serve's."""
    import image_analogies_tpu.catalog as catalog_pkg

    root = os.path.dirname(catalog_pkg.__file__)
    forbidden = re.compile(r"\bjax\.jit\s*\(|\bpjit\s*\(|\bjax\.pmap\s*\(")
    toplevel_jax = re.compile(r"^(import jax|from jax)", re.MULTILINE)
    scanned = set()
    for name in sorted(os.listdir(root)):
        if not name.endswith(".py"):
            continue
        scanned.add(name)
        with open(os.path.join(root, name)) as f:
            src = f.read()
        assert not forbidden.findall(src), f"catalog/{name} calls jit/pjit"
        assert not toplevel_jax.findall(src), (
            f"catalog/{name} imports jax at module scope")
    assert {"__init__.py", "store.py", "tiers.py", "build.py"} <= scanned
