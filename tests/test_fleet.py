"""Fleet resilience plane (ISSUE 9): consistent-hash router, health-gated
workers, dead-worker journal handoff (serve/router.py, serve/fleet.py).

Locked here:

- ring determinism (sha256 positions, never hash()) and the rebalance
  property: adding/removing a worker only remaps keys whose home WAS
  that worker — every untouched key keeps its home;
- router affinity: batch-compatible requests land on one home worker,
  and routed responses stay bit-identical to direct engine runs through
  BOTH wire codecs (IAF2 binary and JSON fallback);
- spillover re-submit bit-identity: the same idempotency key answered
  once on each of two workers (home gated between submissions) yields
  identical bytes, with the spill visible in router.spills;
- non-chaos kill -> health-loop replacement: generation bump, journal
  handed to the replacement (lock pid / fresh segment in /healthz),
  recovery stats reconciled, resubmission deduped from the journal;
- `ia fleet --selftest` CLI smoke riding the obs pipeline (report
  "fleet:" section, trace router instants).

The chaos-armed fleet kill-restart drill itself rides the per-kind
tier-1 parametrization in test_chaos.py (kind="fleet_death").
"""

import json
import os
import time

import numpy as np
import pytest

from image_analogies_tpu.chaos import drills
from image_analogies_tpu.obs import metrics as obs_metrics
from image_analogies_tpu.serve.fleet import Fleet
from image_analogies_tpu.serve.router import Ring, _point
from image_analogies_tpu.serve.types import FleetConfig

# ------------------------------------------------------------------ ring


def test_ring_positions_deterministic():
    """Two independently-built rings agree on every key's successor walk
    (sha256 positions are process- and PYTHONHASHSEED-independent)."""
    r1, r2 = Ring(vnodes=16), Ring(vnodes=16)
    for r in (r1, r2):
        for wid in ("w0", "w1", "w2"):
            r.add(wid)
    for key in ("a/b/c", "digest/1024/1024/beef", "x" * 64):
        assert r1.successors(key) == r2.successors(key)
    assert r1.members() == ["w0", "w1", "w2"]
    # positions come from sha256, so they are stable across releases too
    assert _point("w0#0") == int.from_bytes(
        __import__("hashlib").sha256(b"w0#0").digest()[:8], "big")


def test_ring_rebalance_keeps_untouched_keys():
    """Join: a new worker only steals keys (they move TO it, never
    between old workers).  Leave: removing it restores every stolen key
    to its original home."""
    ring = Ring(vnodes=32)
    for i in range(4):
        ring.add(f"w{i}")
    keys = [f"key-{i}" for i in range(200)]
    before = {k: ring.successors(k)[0] for k in keys}

    ring.add("w4")
    after_join = {k: ring.successors(k)[0] for k in keys}
    moved = [k for k in keys if after_join[k] != before[k]]
    assert moved, "w4 took no keys — vnode count too low to matter"
    assert all(after_join[k] == "w4" for k in moved), (
        "a key moved between OLD workers on join")

    ring.remove("w4")
    assert {k: ring.successors(k)[0] for k in keys} == before


def test_home_for_style_ring_affinity():
    """Style-grain placement (catalog prefetch) walks the SAME ring as
    request routing: empty ring -> None; otherwise the style's home is
    its first ring successor, and membership changes move prefetch
    placement exactly the way they move traffic (join steals styles TO
    the joiner only; leave restores them)."""
    from image_analogies_tpu.serve.router import Router

    router = Router(None, vnodes=32)
    assert router.home_for_style("deadbeef0123") is None

    for i in range(4):
        router.ring.add(f"w{i}")
    styles = [f"{i:012x}" for i in range(50)]
    before = {s: router.home_for_style(s) for s in styles}
    assert all(before[s] == router.ring.successors(s)[0] for s in styles)

    router.ring.add("w4")
    after = {s: router.home_for_style(s) for s in styles}
    moved = [s for s in styles if after[s] != before[s]]
    assert moved and all(after[s] == "w4" for s in moved)
    router.ring.remove("w4")
    assert {s: router.home_for_style(s) for s in styles} == before


def test_fleet_config_validation():
    cfg = drills.serve_config()
    with pytest.raises(ValueError):
        FleetConfig(serve=cfg, size=0)
    with pytest.raises(ValueError):
        FleetConfig(serve=cfg, wire="msgpack")
    with pytest.raises(ValueError):
        FleetConfig(serve=cfg, spill_queue_frac=0.0)
    with pytest.raises(ValueError):
        FleetConfig(serve=cfg, backoff_s=0.5, backoff_cap_s=0.1)
    with pytest.raises(ValueError):
        FleetConfig(serve=cfg, transport="carrier_pigeon")


def test_judge_liveness_vs_readiness():
    """The death verdict is gated on LIVENESS only: a worker mid-replay
    reports ready=False / recovering=True and must be neither declared
    dead nor advisorily gated — spilling keys whose replay is about to
    answer them would double-compute work the journal already holds."""

    class H:
        def __init__(self, doc):
            self._doc = doc

        def health(self):
            if isinstance(self._doc, Exception):
                raise self._doc
            return self._doc

    fl = Fleet.__new__(Fleet)  # _judge only touches self.cfg
    fl.cfg = _fleet_cfg()
    alive = {"ok": True, "accepting": True, "ready": True,
             "recovering": False, "workers": {"alive": 1},
             "breakers": {}, "queue_depth": 0}
    assert fl._judge(H(alive)) is None
    recovering = dict(alive, ok=False, ready=False, recovering=True)
    assert fl._judge(H(recovering)) is None
    assert fl._judge(H(dict(alive, accepting=False))) == "dead"
    assert fl._judge(H(RuntimeError("unreachable"))) == "dead"
    tripped = dict(alive, breakers={"cpu": "open"})
    assert fl._judge(H(tripped)) == "breaker_open"


# ------------------------------------------------------ routed serving


def _fleet_cfg(tmp_path=None, wire="auto", **kw):
    scfg = drills.serve_config(workers=1, max_batch=4,
                               batch_window_ms=20.0)
    return FleetConfig(
        serve=scfg, size=2, vnodes=16, wire=wire,
        journal_root=str(tmp_path / "journals") if tmp_path else None,
        health_interval_s=0.05, death_checks=2,
        backoff_s=0.01, backoff_cap_s=0.05, **kw)


def _routed_counts():
    snap = obs_metrics.snapshot() or {}
    return {k.split("router.routed.", 1)[1]: int(v)
            for k, v in (snap.get("counters") or {}).items()
            if k.startswith("router.routed.")}


@pytest.mark.parametrize("wire", ["binary", "json"])
def test_router_affinity_and_bit_identity(wire):
    """Batch-compatible requests (one shared exemplar -> one batch key)
    all land on ONE home worker, and every routed response — through
    either wire codec — is bit-identical to a direct engine run."""
    fcfg = _fleet_cfg(wire=wire)
    load = drills.make_serve_load(4)
    baseline = {it["index"]: drills.run_image(
        it["a"], it["ap"], it["b"], fcfg.serve.params) for it in load}
    with Fleet(fcfg) as fl:
        futs = {it["index"]: fl.submit(it["a"], it["ap"], it["b"])
                for it in load}
        resp = {i: f.result(timeout=120) for i, f in futs.items()}
        routed = _routed_counts()
    # one home worker took everything (consistent-hash affinity)
    assert sorted(routed.values()) == [4], routed
    for i, r in resp.items():
        assert np.array_equal(np.asarray(r.bp), baseline[i])


def test_spillover_resubmit_bit_identity(tmp_path):
    """The same idempotency key answered once on EACH of two workers
    (home gated between submissions) returns identical bytes: the
    successor computes fresh in its own journal, so exactly-once holds
    per journal and bit-identity holds across the fleet."""
    fcfg = _fleet_cfg(tmp_path)
    item = drills.make_serve_load(1)[0]
    with Fleet(fcfg) as fl:
        r1 = fl.submit(item["a"], item["ap"], item["b"],
                       idempotency_key="spill-me").result(timeout=120)
        (home,) = _routed_counts().keys()
        fl.gate_worker(home, "test_spill")
        try:
            r2 = fl.submit(item["a"], item["ap"], item["b"],
                           idempotency_key="spill-me").result(timeout=120)
            routed = _routed_counts()
            snap = obs_metrics.snapshot() or {}
            counters = snap.get("counters") or {}
        finally:
            fl.ungate_worker(home)
    assert len(routed) == 2 and all(v == 1 for v in routed.values()), (
        "the gated resubmission did not land on the other worker")
    assert counters.get("router.spills", 0) >= 1
    # both workers journaled their own copy; neither deduped the other's
    assert counters.get("serve.journal.admitted", 0) == 2
    assert counters.get("serve.journal.done", 0) == 2
    assert counters.get("serve.journal.deduped", 0) == 0
    assert np.array_equal(np.asarray(r1.bp), np.asarray(r2.bp))


def test_kill_triggers_handoff_and_dedupe(tmp_path):
    """Non-chaos worker death: the health loop detects the dead worker,
    hands its journal directory to a replacement (same wid, bumped
    generation, fresh segment, this process's lock pid), and a
    resubmission under the original key dedupes against the recovered
    journal instead of recomputing."""
    fcfg = _fleet_cfg(tmp_path)
    load = drills.make_serve_load(2)
    with Fleet(fcfg) as fl:
        futs = {it["index"]: fl.submit(
            it["a"], it["ap"], it["b"],
            idempotency_key=f"handoff-{it['index']}") for it in load}
        resp = {i: f.result(timeout=120) for i, f in futs.items()}
        (home,) = _routed_counts().keys()
        gen0 = fl.workers[home].generation

        fl.workers[home].server.kill()
        deadline = time.monotonic() + 30.0
        while not fl.handoffs and time.monotonic() < deadline:
            time.sleep(0.02)
        assert len(fl.handoffs) == 1, "health loop never replaced the worker"
        ho = fl.handoffs[0]
        assert ho["worker"] == home and ho["generation"] == gen0 + 1
        # both requests were already done: handoff replays nothing,
        # preserves both done records
        assert ho["recovered"]["entries"] == 2
        assert ho["recovered"]["done"] == 2
        assert ho["recovered"]["replayed"] == 0

        health = fl.health()
        wh = health["workers"][home]
        assert wh["ok"] is True and wh["generation"] == gen0 + 1
        # satellite: /healthz journal section reports lock owner + segment
        assert wh["journal"]["lock_pid"] == os.getpid()
        assert wh["journal"]["segment"] == 2  # incarnation 2's segment
        assert health["handoffs"] == 1

        again = fl.submit(load[0]["a"], load[0]["ap"], load[0]["b"],
                          idempotency_key="handoff-0").result(timeout=120)
        snap = obs_metrics.snapshot() or {}
        deduped = (snap.get("counters") or {}).get(
            "serve.journal.deduped", 0)
    assert deduped == 1
    assert again.request_id == resp[0].request_id  # the recorded response
    assert np.array_equal(np.asarray(again.bp), np.asarray(resp[0].bp))


# ------------------------------------------- fleet observability (PR 11)


def test_fleet_healthz_obs_identity_and_federated_metrics(tmp_path):
    """PR 11: each worker's /healthz entry names its ObsScope id and the
    last health-loop scrape age; fleet /metrics is the federated view
    (merged + worker-labeled, byte-consistent sums) and ``?worker=``
    selects one worker's ISOLATED exposition."""
    import re
    import urllib.error
    import urllib.request

    from image_analogies_tpu.serve.http import serve_fleet_http

    fcfg = _fleet_cfg(tmp_path)
    load = drills.make_serve_load(3)
    with Fleet(fcfg) as fl:
        futs = [fl.submit(it["a"], it["ap"], it["b"]) for it in load]
        for f in futs:
            f.result(timeout=120)
        time.sleep(4 * fcfg.health_interval_s)  # let the scrape loop run

        health = fl.health()
        assert health["transport"] == "inproc"
        for wid, wh in health["workers"].items():
            obs = wh["obs"]
            assert obs["scope"] == f"{wid}.g0"
            assert obs["last_scrape_age_s"] >= 0.0
            assert "stale_scope" not in obs
            # liveness/readiness split: every worker entry carries the
            # schema the health daemon and operators key on (an
            # in-process worker shares the router's pid)
            assert wh["ready"] is True and wh["recovering"] is False
            assert wh["pid"] == os.getpid()

        merged = fl.metrics_text()
        solo = fl.metrics_text("w0")
        assert fl.metrics_text("w9") is None
        # isolated view: no worker labels, just w0's own registry
        assert 'worker=' not in solo
        # federated view: merged sample + one labeled sample per worker
        # that admitted anything, summing exactly to the merged value
        sample = re.compile(
            r'^ia_serve_accepted_total(?:\{worker="(w\d)"\})? (\S+)$',
            re.MULTILINE)
        pairs = sample.findall(merged)
        total = sum(float(v) for wid, v in pairs if not wid)
        labeled = {wid: float(v) for wid, v in pairs if wid}
        assert total == 3.0 and sum(labeled.values()) == total
        # the fleet scope's own families ride along unlabeled
        assert "ia_router_routed" in merged

        # same bytes over HTTP, plus the 404 contract for unknown wids
        httpd = serve_fleet_http(fl, port=0)
        import threading
        t = threading.Thread(target=httpd.serve_forever, daemon=True)
        t.start()
        try:
            base = "http://127.0.0.1:{}".format(httpd.server_address[1])
            with urllib.request.urlopen(base + "/metrics") as r:
                assert 'worker="w0"' in r.read().decode()
            with urllib.request.urlopen(base + "/metrics?worker=w0") as r:
                body = r.read().decode()
            assert "ia_serve_accepted_total" in body and "worker=" not in body
            try:
                urllib.request.urlopen(base + "/metrics?worker=nope")
                raise AssertionError("unknown worker did not 404")
            except urllib.error.HTTPError as exc:
                assert exc.code == 404
                assert json.loads(exc.read())["error"] == "unknown_worker"
            with urllib.request.urlopen(base + "/healthz") as r:
                hz = json.loads(r.read())
            assert hz["workers"]["w0"]["obs"]["scope"] == "w0.g0"
        finally:
            httpd.shutdown()
            httpd.server_close()


# --------------------------------------------------------- CLI smoke


def test_fleet_cli_selftest_report_and_trace(tmp_path, capsys):
    """`ia fleet --selftest` routes the synthetic load, gates on
    bit-identity, and its run log renders the fleet section in
    `ia report` and router instants in `ia trace`."""
    from image_analogies_tpu.cli import main
    from image_analogies_tpu.obs import export as obs_export

    log = str(tmp_path / "fleet.jsonl")
    rc = main(["fleet", "--selftest", "3", "--size", "2",
               "--max-batch", "3", "--batch-window-ms", "50",
               "--levels", "2", "--backend", "cpu", "--log-path", log])
    captured = capsys.readouterr()
    assert rc == 0, captured.err
    assert "fleet selftest: 3 requests over 2 workers" in captured.out
    assert "bit-identical to singleton dispatch: True" in captured.out
    summary = json.loads(captured.err.strip().splitlines()[-1])
    assert summary["errors"] == 0 and summary["bit_identical"] is True
    assert sum(summary["routed"].values()) == 3
    assert summary["codecs"].get("iaf2", 0) == 3  # auto negotiates binary

    rc = main(["report", log])
    assert rc == 0
    rep = capsys.readouterr().out
    assert "fleet:" in rep and "routing" in rep

    out = str(tmp_path / "trace.json")
    rc = main(["trace", log, "-o", out])
    assert rc == 0
    capsys.readouterr()
    trace = json.load(open(out))
    routes = [e for e in trace["traceEvents"]
              if e["ph"] == "i" and e["name"].startswith("route ")]
    assert len(routes) == 3  # one routing instant per request
    # PR 14: routed records carry the router-minted trace id, so each
    # request's hop chain re-homes onto its own per-trace track
    assert all(e["tid"] >= obs_export.TRACE_TID_BASE for e in routes)
