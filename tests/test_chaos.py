"""chaos/ — seeded fault-injection plane + resilience drills.

Tier-1 invariants locked here:

- one canonical drill per fault kind passes end-to-end: bit-identical
  recovery, no lost or hung request, and the injection counters
  reconcile against the recovery counters they caused;
- same seed ⇒ same fault schedule (determinism is the whole point of a
  *seeded* fault plane: a failing drill must replay);
- disarmed sites are provably inert — no metric, no record, no
  directive, just `None` (the obs/ off-path contract);
- chaos/ never imports jax at module scope and never calls jit/pjit
  (grep lock) — plans must arm on any host, device or not;
- plans round-trip through JSON, and the `ia chaos` CLI wires the
  whole thing together.
"""

import json
import os
import re

import pytest

from image_analogies_tpu import chaos
from image_analogies_tpu.chaos import inject, runner
from image_analogies_tpu.chaos.plan import ChaosPlan, SiteRule

# ------------------------------------------------- drills (per kind)


@pytest.mark.parametrize("kind", runner.DRILL_KINDS)
def test_drill_recovers_per_fault_kind(kind):
    """The seeded smoke `ia chaos --selftest` runs in CI: one canonical
    plan per drill kind (every raw fault kind plus the composite fleet
    kill-restart), each asserting full recovery."""
    report = runner.run_drill(runner.plan_for_kind(kind, seed=0))
    assert report["ok"], report["problems"]
    assert report["injected"] >= 1
    assert report["identical"] is True


def test_drill_kinds_cover_fault_kinds():
    """DRILL_KINDS is FAULT_KINDS plus the composite fleet drill — a new
    fault kind automatically gains a tier-1 drill."""
    assert set(chaos.FAULT_KINDS) <= set(runner.DRILL_KINDS)
    assert "fleet_death" in runner.DRILL_KINDS


def test_same_seed_same_schedule():
    det = runner.check_determinism(seed=3)
    assert det["ok"], det["problems"]
    assert det["injected"] > 0


# ------------------------------------------------- the injection plane


def test_disarmed_site_is_inert(monkeypatch):
    """Disarmed = production: a site visit must not touch metrics, the
    run log, locks' state, or return a directive."""
    from image_analogies_tpu.obs import metrics as obs_metrics
    from image_analogies_tpu.obs import trace as obs_trace

    assert not chaos.armed()

    def touched(*a, **k):
        raise AssertionError("chaos site touched obs while disarmed")

    monkeypatch.setattr(obs_metrics, "inc", touched)
    monkeypatch.setattr(obs_trace, "emit_record", touched)
    assert chaos.site("level.dispatch", level=0) is None
    assert chaos.site("ckpt.save") is None
    assert chaos.snapshot() == {}
    assert chaos.injected_total() == 0
    assert chaos.plan_seed() is None


def test_max_faults_caps_probabilistic_rule():
    plan = ChaosPlan(seed=1, sites=(
        ("level.dispatch", SiteRule(kind="latency", p=1.0, latency_ms=0.0,
                                    max_faults=2)),))
    with inject.plan_scope(plan):
        for _ in range(10):
            inject.site("level.dispatch")
        snap = inject.snapshot()
    assert snap["level.dispatch"] == {"visits": 10, "injected": 2}


def test_unplanned_site_passes_through():
    plan = ChaosPlan(seed=1, sites=(
        ("ckpt.save", SiteRule(kind="corrupt", schedule=(0,))),))
    with inject.plan_scope(plan):
        assert inject.site("level.dispatch") is None  # no rule -> no-op
        assert inject.site("ckpt.save") == "corrupt"  # directive returned
        assert inject.site("ckpt.save") is None       # schedule spent


def test_plan_scope_disarms_even_on_error():
    plan = runner.plan_for_kind("transient")
    with pytest.raises(RuntimeError):
        with inject.plan_scope(plan):
            assert chaos.armed()
            raise RuntimeError("drill body died")
    assert not chaos.armed()
    assert chaos.plan_seed() is None


# ------------------------------------------------------ plan format


def test_plan_json_roundtrip():
    plan = ChaosPlan(seed=42, name="rt", sites=(
        ("level.dispatch", SiteRule(kind="transient", p=0.5, max_faults=2)),
        ("ckpt.save", SiteRule(kind="corrupt", schedule=(0, 3))),
        ("serve.dispatch", SiteRule(kind="latency", latency_ms=10.0,
                                    hang=True, schedule=(1,))),
    ))
    assert ChaosPlan.from_json(json.dumps(plan.to_dict())) == plan


def test_plan_validation():
    with pytest.raises(ValueError):
        SiteRule(kind="meteor")
    with pytest.raises(ValueError):
        SiteRule(kind="transient", p=1.5)
    with pytest.raises(ValueError):
        ChaosPlan.from_dict({"sites": {"x": {"p": 0.5}}})  # no kind
    with pytest.raises(ValueError):
        ChaosPlan.from_dict([])  # not an object
    # lognormal latency spec: both percentiles or neither, and ordered
    with pytest.raises(ValueError):
        SiteRule(kind="latency", latency_p50_ms=10.0)
    with pytest.raises(ValueError):
        SiteRule(kind="latency", latency_p99_ms=10.0)
    with pytest.raises(ValueError):
        SiteRule(kind="latency", latency_p50_ms=10.0, latency_p99_ms=5.0)
    with pytest.raises(ValueError):
        SiteRule(kind="latency", latency_p50_ms=-1.0, latency_p99_ms=5.0)


def test_plan_json_roundtrip_lognormal_latency():
    plan = ChaosPlan(seed=3, sites=(
        ("level.dispatch", SiteRule(kind="latency", p=1.0,
                                    latency_p50_ms=2.0,
                                    latency_p99_ms=20.0)),))
    again = ChaosPlan.from_json(json.dumps(plan.to_dict()))
    assert again == plan
    # inert zero defaults stay out of the serialized form
    flat = json.dumps(ChaosPlan(seed=3, sites=(
        ("x", SiteRule(kind="latency")),)).to_dict())
    assert "latency_p50_ms" not in flat


def test_lognormal_latency_draws_are_plan_deterministic():
    """Same (seed, site) -> same tail-latency draws; the p50/p99 spec
    shapes them (median near p50, spread reaching toward p99)."""
    rule = SiteRule(kind="latency", p=1.0, latency_p50_ms=5.0,
                    latency_p99_ms=50.0)
    plan = ChaosPlan(seed=11, sites=(("level.dispatch", rule),))

    def draws(n=64):
        inject.arm(plan)
        try:
            return [inject._latency_s("level.dispatch", rule)
                    for _ in range(n)]
        finally:
            inject.disarm()

    first, second = draws(), draws()
    assert first == second                      # replayable tail
    assert all(d > 0 for d in first)
    med = sorted(first)[len(first) // 2]
    assert 0.001 < med < 0.025                  # median ~5ms, not 50ms
    assert max(first) > med * 2                 # a tail actually exists
    # a different seed reshuffles the draws
    inject.arm(ChaosPlan(seed=12, sites=(("level.dispatch", rule),)))
    try:
        other = [inject._latency_s("level.dispatch", rule)
                 for _ in range(64)]
    finally:
        inject.disarm()
    assert other != first


def test_fixed_latency_rule_ignores_lognormal_path():
    rule = SiteRule(kind="latency", p=1.0, latency_ms=7.0)
    plan = ChaosPlan(seed=11, sites=(("level.dispatch", rule),))
    inject.arm(plan)
    try:
        assert inject._latency_s("level.dispatch", rule) == 0.007
    finally:
        inject.disarm()


# ------------------------------------------------------- telemetry


def test_chaos_telemetry_in_report_and_trace(tmp_path):
    """An injection under an observed run surfaces in `ia report`'s
    chaos section and on the trace's chaos track."""
    from image_analogies_tpu.config import AnalogyParams
    from image_analogies_tpu.obs import export as obs_export
    from image_analogies_tpu.obs import report as obs_report
    from image_analogies_tpu.obs import trace as obs_trace

    log = str(tmp_path / "run.jsonl")
    params = AnalogyParams(backend="cpu", metrics=True, log_path=log)
    plan = ChaosPlan(seed=0, sites=(
        ("level.dispatch", SiteRule(kind="latency", p=1.0,
                                    latency_ms=0.0)),))
    with obs_trace.run_scope(params):
        with inject.plan_scope(plan):
            inject.site("level.dispatch", level=0)

    an = obs_report.analyze(obs_report.load_records(log))
    assert an["chaos"] is not None
    assert an["chaos"]["injected"] == 1
    assert an["chaos"]["by_site"] == {"level.dispatch": 1}
    assert an["chaos"]["by_kind"] == {"latency": 1}
    assert "chaos:" in obs_report.report(log)

    out = str(tmp_path / "trace.json")
    obs_export.export_trace(log, out)
    trace = json.load(open(out))
    hits = [e for e in trace["traceEvents"]
            if e.get("tid") == obs_export.CHAOS_TID and e["ph"] == "i"]
    assert [e["name"] for e in hits] == ["inject latency @level.dispatch"]


# ------------------------------------------------------------- CLI


def test_cli_chaos_selftest_smoke(capsys):
    from image_analogies_tpu.cli import main

    rc = main(["chaos", "--selftest", "--kinds", "transient", "--seed", "1"])
    out = capsys.readouterr().out
    assert rc == 0, out
    assert "PASS" in out and "determinism" in out


def test_cli_chaos_plan_file(tmp_path, capsys):
    from image_analogies_tpu.cli import main

    path = str(tmp_path / "plan.json")
    with open(path, "w") as f:
        json.dump(runner.plan_for_kind("oom", seed=2).to_dict(), f)
    rc = main(["chaos", "--plan", path])
    out = capsys.readouterr().out
    assert rc == 0, out
    assert "PASS" in out


def test_cli_chaos_requires_plan_or_selftest(capsys):
    from image_analogies_tpu.cli import main

    assert main(["chaos"]) == 2
    assert "pass --plan FILE or --selftest" in capsys.readouterr().err


# ------------------------------------------------------- grep locks


def test_chaos_package_is_jax_free():
    """chaos/ must arm (and stay zero-cost disarmed) on any host: no
    module-scope jax import anywhere, no direct jit/pjit calls ever.
    Engine work in drills goes through lazy engine imports."""
    import image_analogies_tpu.chaos as chaos_pkg

    root = os.path.dirname(chaos_pkg.__file__)
    forbidden = re.compile(r"\bjax\.jit\s*\(|\bpjit\s*\(|\bjax\.pmap\s*\(")
    toplevel_jax = re.compile(r"^(import jax|from jax)", re.MULTILINE)
    for name in sorted(os.listdir(root)):
        if not name.endswith(".py"):
            continue
        with open(os.path.join(root, name)) as f:
            src = f.read()
        assert not forbidden.findall(src), f"chaos/{name} calls jit/pjit"
        assert not toplevel_jax.findall(src), (
            f"chaos/{name} imports jax at module scope")
