"""TPU-vs-CPU backend equivalence (SURVEY.md §4.3-4.4).

Feature matrices must agree to fp32 tolerance; best_match distances must
agree (argmin ties may differ — compare distances, not indices); end-to-end
outputs must reach SSIM parity.
"""

import numpy as np
import pytest

from image_analogies_tpu.backends.base import LevelJob
from image_analogies_tpu.backends.cpu import CpuMatcher
from image_analogies_tpu.backends.tpu import TpuMatcher
from image_analogies_tpu.config import AnalogyParams
from image_analogies_tpu.models.analogy import create_image_analogy
from image_analogies_tpu.ops.features import spec_for_level
from image_analogies_tpu.utils.ssim import ssim
from tests.conftest import make_pair


def _job(a, ap, b, params, level=0, levels=1):
    spec = spec_for_level(params, level, levels, 1)
    return LevelJob(level=level, spec=spec,
                    kappa_mult=params.kappa_factor(level) ** 2,
                    a_src=a, a_filt=ap, b_src=b)


def test_db_features_match(rng):
    a, ap, b = make_pair(12, 13)
    p = AnalogyParams(levels=1)
    cpu, tpu = CpuMatcher(p), TpuMatcher(p.replace(backend="tpu"))
    job = _job(a, ap, b, p)
    db_c = cpu.build_features(job)
    db_t = tpu.build_features(job)
    np.testing.assert_allclose(np.asarray(db_t.db), db_c.db, atol=1e-5)
    np.testing.assert_allclose(np.asarray(db_t.static_q), db_c.static_q,
                               atol=1e-5)


def test_best_match_distance_parity(rng):
    a, ap, b = make_pair(10, 11, seed=5)
    p = AnalogyParams(levels=1)
    cpu = CpuMatcher(p)
    tpu = TpuMatcher(p.replace(backend="tpu"))
    job = _job(a, ap, b, p)
    db_c = cpu.build_features(job)
    db_t = tpu.build_features(job)
    n = b.size
    # mid-synthesis state: first 40 pixels "synthesized"
    bp = np.zeros(n, np.float32)
    s = np.zeros(n, np.int32)
    bp[:40] = db_c.a_filt_flat[:40]
    s[:40] = np.arange(40)
    for q in [0, 1, 17, 39, 40, 41, 87]:
        pc, dc, cc = cpu.best_match(db_c, job, q, bp, s)
        pt, dt, ct = tpu.best_match(db_t, job, q, bp, s)
        assert dt == pytest.approx(dc, abs=1e-3), q
        if pc != pt:  # tie: distances must agree tightly
            assert dt == pytest.approx(dc, abs=1e-3)


def test_end_to_end_ssim_parity_exact(rng):
    """The exact strategy reproduces the oracle's decisions pixel-for-pixel
    (SSIM ~ 1.0).  This is THE parity proof (BASELINE.json:2); approximate
    strategies are validated by quality invariants below, because on
    ambiguous inputs any candidate divergence cascades into a different but
    equally-valid synthesis (SURVEY.md §7 hard part 2)."""
    a, ap, b = make_pair(24, 24, seed=2)
    p_cpu = AnalogyParams(levels=2, kappa=3.0, backend="cpu")
    r_cpu = create_image_analogy(a, ap, b, p_cpu)
    r_tpu = create_image_analogy(
        a, ap, b, p_cpu.replace(backend="tpu", strategy="exact"))
    sv = ssim(r_cpu.bp_y, r_tpu.bp_y, data_range=1.0)
    assert sv >= 0.99, f"SSIM {sv}"


@pytest.mark.parametrize("strategy", ["rowwise", "batched"])
def test_fast_strategies_self_analogy_quality(strategy, rng):
    """Quality invariant that does not depend on tie-breaking: with B == A
    the ideal output is A' and the source map the identity.  The fast
    strategies must recover it (they do >= 95% in practice)."""
    a, ap, _ = make_pair(24, 24, seed=4)
    p = AnalogyParams(levels=2, kappa=2.0, backend="tpu", strategy=strategy)
    r = create_image_analogy(a, ap, a.copy(), p)
    sv = ssim(r.bp_y, np.asarray(ap), data_range=1.0)
    ident = (r.source_map.reshape(-1) == np.arange(a.size)).mean()
    assert sv >= 0.9, f"self-analogy SSIM {sv}"
    assert ident >= 0.8, f"identity source-map fraction {ident}"


def test_batched_quality_not_worse_than_oracle(rng):
    """On the posterize task, batched output must track the 'ideal' filtered
    B at least as well as the oracle does (it typically does better)."""
    a, ap, b = make_pair(24, 24, seed=2)
    ideal = np.round(np.asarray(b) * 5) / 5.0
    p_cpu = AnalogyParams(levels=2, kappa=3.0, backend="cpu")
    r_cpu = create_image_analogy(a, ap, b, p_cpu)
    r_bat = create_image_analogy(
        a, ap, b, p_cpu.replace(backend="tpu", strategy="batched"))
    mae_cpu = np.abs(r_cpu.bp_y - ideal).mean()
    mae_bat = np.abs(r_bat.bp_y - ideal).mean()
    assert mae_bat <= mae_cpu * 1.25, (mae_bat, mae_cpu)


def test_exact_strategy_matches_oracle_picks(rng):
    """On tie-free random data the exact strategy should reproduce the
    oracle's source map almost everywhere."""
    a, ap, b = make_pair(16, 16, seed=9)
    p = AnalogyParams(levels=1, kappa=2.0)
    r_cpu = create_image_analogy(a, ap, b, p)
    r_tpu = create_image_analogy(
        a, ap, b, p.replace(backend="tpu", strategy="exact"))
    agree = (r_cpu.source_map == r_tpu.source_map).mean()
    assert agree > 0.9, f"source map agreement {agree}"


def test_device_gather_maps_match_numpy():
    """The device-computed gather maps must equal the NumPy spec twin."""
    from image_analogies_tpu.backends.tpu import _gather_maps_device
    from image_analogies_tpu.ops.features import fine_gather_maps

    for (h, w, p) in [(7, 9, 5), (4, 5, 3), (16, 16, 7)]:
        flat_np, valid_np, written_np = fine_gather_maps(h, w, p)
        flat_d, valid_d, written_d = _gather_maps_device(h, w, p)
        np.testing.assert_array_equal(np.asarray(flat_d), flat_np)
        np.testing.assert_array_equal(np.asarray(valid_d), valid_np)
        np.testing.assert_array_equal(np.asarray(written_d), written_np)


def test_single_level_texture_by_numbers_tpu(rng):
    """BASELINE config 1 shape: single-scale, source_rgb, on the TPU path."""
    r = np.random.default_rng(0)
    lab_a = np.zeros((16, 16, 3), np.float32)
    lab_a[:, :8, 0] = 1.0
    lab_a[:, 8:, 1] = 1.0
    tex = np.stack([0.2 + 0.05 * r.standard_normal((16, 16))] * 3,
                   -1).clip(0, 1).astype(np.float32)
    tex[:, 8:] = (0.8 + 0.05 * r.standard_normal((16, 8, 1))).clip(0, 1)
    lab_b = np.zeros((16, 16, 3), np.float32)
    lab_b[:8, :, 0] = 1.0
    lab_b[8:, :, 1] = 1.0
    p = AnalogyParams(levels=1, kappa=1.0, remap_luminance=False,
                      color_mode="source_rgb", backend="tpu",
                      strategy="exact")
    res = create_image_analogy(lab_a, tex, lab_b, p)
    assert res.bp.shape == (16, 16, 3)
    assert res.bp[:8].mean() < 0.5 < res.bp[8:].mean()


def test_auto_match_mode_crossover():
    """match_mode="auto" must resolve exact_hi2_2p at/above the measured
    DB-size crossover and exact_hi below it, and the resolution must agree
    with `packed_scan_eligible` — the steering predicate the mesh paths
    share (round-3 ADVICE: the auto branch needs a committed test)."""
    from image_analogies_tpu.backends.tpu import (
        _PACKED_CROSSOVER_ROWS,
        TpuMatcher,
        packed_scan_eligible,
    )

    r = np.random.default_rng(3)
    b = r.random((16, 16), dtype=np.float32)
    p = AnalogyParams(levels=1, backend="tpu", strategy="wavefront",
                      match_mode="auto")
    # 256*512 = 131072 sits exactly ON the crossover (>= packs);
    # 255*512 = 130560 sits below it
    for (h, w), want in [((256, 512), "exact_hi2_2p"),
                         ((255, 512), "exact_hi")]:
        assert (h * w >= _PACKED_CROSSOVER_ROWS) == (want == "exact_hi2_2p")
        a = r.random((h, w), dtype=np.float32)
        ap = r.random((h, w), dtype=np.float32)
        db = TpuMatcher(p).build_features(_job(a, ap, b, p))
        assert db.match_mode == want, (h, w)
        assert packed_scan_eligible("auto", h * w) == (want == "exact_hi2_2p")


def test_experimental_match_modes_gated(monkeypatch):
    """Non-parity A/B probe modes must not be selectable from the
    production config surface (round-3 VERDICT item 7)."""
    from image_analogies_tpu.config import EXPERIMENTAL_MATCH_MODES

    monkeypatch.delenv("IA_EXPERIMENTAL", raising=False)
    for mode in EXPERIMENTAL_MATCH_MODES:
        with pytest.raises(ValueError, match="IA_EXPERIMENTAL"):
            AnalogyParams(match_mode=mode)
    # explicit falsey spellings keep the gate CLOSED
    for off in ("0", "false", "no"):
        monkeypatch.setenv("IA_EXPERIMENTAL", off)
        with pytest.raises(ValueError, match="IA_EXPERIMENTAL"):
            AnalogyParams(match_mode="two_pass")
    monkeypatch.setenv("IA_EXPERIMENTAL", "1")
    assert AnalogyParams(match_mode="two_pass").match_mode == "two_pass"


def test_experimental_match_modes_hidden_from_cli(monkeypatch):
    """--match-mode lists only parity modes unless IA_EXPERIMENTAL=1."""
    from image_analogies_tpu.cli import build_parser

    monkeypatch.delenv("IA_EXPERIMENTAL", raising=False)
    with pytest.raises(SystemExit):
        build_parser().parse_args(
            ["run", "--ap", "x.png", "--out", "y.png",
             "--match-mode", "scan_rescue"])
    ok = build_parser().parse_args(
        ["run", "--ap", "x.png", "--out", "y.png",
         "--match-mode", "exact_hi2_2p"])
    assert ok.match_mode == "exact_hi2_2p"
    monkeypatch.setenv("IA_EXPERIMENTAL", "1")
    gated = build_parser().parse_args(
        ["run", "--ap", "x.png", "--out", "y.png",
         "--match-mode", "scan_rescue"])
    assert gated.match_mode == "scan_rescue"
