"""Worker process for the REAL two-process jax.distributed smoke test
(round-3 VERDICT item 5; SURVEY.md §5.8).

Run by tests/test_sharded.py::test_two_process_distributed_smoke as TWO
localhost subprocesses:

    python tests/distributed_worker.py <port> <process_id>

Each process owns ONE CPU device; `initialize_distributed` performs the
actual coordination-service handshake (un-mocked), after which
`jax.devices()` spans both processes and the db_shards=2 mesh lays the
exemplar DB across them — one shard per PROCESS, so the min+argmin
all-reduce and psum row-gathers of parallel/step.py cross a real process
boundary via gloo CPU collectives.  Process 0 also synthesizes the serial
(db_shards=1, local-device) result and asserts the sharded output matches
it exactly; success prints DISTRIBUTED_SMOKE_OK.
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main() -> int:
    port, pid = sys.argv[1], int(sys.argv[2])
    os.environ["JAX_PLATFORMS"] = "cpu"
    os.environ.pop("XLA_FLAGS", None)  # exactly one local device

    import jax

    jax.config.update("jax_platforms", "cpu")

    from image_analogies_tpu.parallel.distributed import (
        initialize_distributed,
    )

    try:
        jax.config.update("jax_cpu_collectives_implementation", "gloo")
        assert initialize_distributed(f"127.0.0.1:{port}", 2, pid)
    except (AttributeError, RuntimeError, ValueError) as e:
        # environment lacks the distributed runtime / gloo collectives —
        # the PRECISE sentinel test_sharded.py skips on (anything past
        # this point is a real failure and must FAIL the test)
        print(f"DISTRIBUTED_SMOKE_UNSUPPORTED: {e}", flush=True)
        return 0
    assert jax.process_count() == 2, jax.process_count()
    assert jax.device_count() == 2, jax.devices()
    assert jax.local_device_count() == 1

    import numpy as np

    from image_analogies_tpu.config import AnalogyParams
    from image_analogies_tpu.models.analogy import create_image_analogy

    rng = np.random.default_rng(11)
    a = rng.uniform(0, 1, (24, 24)).astype(np.float32)
    ap = (np.round(a * 5) / 5).astype(np.float32)
    b = rng.uniform(0, 1, (24, 24)).astype(np.float32)
    base = dict(levels=2, kappa=2.0, strategy="wavefront", backend="tpu")

    sharded = create_image_analogy(a, ap, b,
                                   AnalogyParams(db_shards=2, **base))
    if pid == 0:
        solo = create_image_analogy(a, ap, b, AnalogyParams(**base))
        np.testing.assert_array_equal(solo.source_map, sharded.source_map)
        np.testing.assert_allclose(solo.bp_y, sharded.bp_y, atol=1e-6)
    print("DISTRIBUTED_SMOKE_OK", flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
