"""tune/ subsystem: geometry resolution, store, buckets, autotuner.

Locks the PR-3 acceptance invariants:

- empty store + no env  ->  bit-for-bit legacy geometry (defaults);
- env vars are read at CALL time and win over the store;
- corrupt stores warn once and fall back — never crash;
- champion picks are tile-geometry invariant (the property the autotuner
  verifies before persisting);
- no kernel call site reads the legacy constants directly (grep lock);
- shape bucketing reuses jit programs across exemplar sizes without
  changing outputs.
"""

import json
import os
import re

import numpy as np
import pytest

from image_analogies_tpu.config import AnalogyParams
from image_analogies_tpu.obs import metrics as obs_metrics
from image_analogies_tpu.obs import trace as obs_trace
from image_analogies_tpu.tune import autotune, buckets, geometry
from image_analogies_tpu.tune import resolve as tune
from image_analogies_tpu.tune import store as tune_store


@pytest.fixture(autouse=True)
def _clean_tune_env(monkeypatch, tmp_path):
    """Isolate every test from developer stores and env overrides."""
    for var in ("IA_TILE_ROWS", "IA_PACKED_TILE", "IA_PACKED_VMEM",
                "IA_WAVEFRONT_ROWS", "IA_SHAPE_BUCKETS",
                "IA_DEVCACHE_BYTES", "IA_ANN_TOP_M", "IA_ANN_PROJ_DIMS"):
        monkeypatch.delenv(var, raising=False)
    monkeypatch.setenv("IA_TUNE_STORE", str(tmp_path / "no_store.json"))
    tune_store.invalidate_cache()
    tune.reset_provenance()
    yield
    tune_store.invalidate_cache()
    tune.reset_provenance()


# ------------------------------------------------------------ defaults


def test_defaults_match_legacy_constants():
    # the exact values the deleted backend constants produced
    assert tune.tile_rows(128) == 8192
    assert tune.tile_rows(253) == 4096  # north-star F (1ch, 5x5): fp=256
    assert tune.tile_rows(309) == 2560  # 3ch 7x7: fp=384
    assert tune.packed_vmem_limit() == 110 * 2 ** 20
    cfg = tune.resolve(strategy="wavefront", dtype="packed2", fp=256)
    assert cfg.packed_tile_cap == 16384
    assert all(o == "default" for _, o in cfg.origin)
    # scan_tile with no cap reproduces the legacy tile_rows//2 cap chain
    assert tune.scan_tile(8192, 256) == geometry.scan_tile_rows(
        8192, geometry.default_tile_rows(256) // 2)


def test_default_tile_rows_invariants():
    for f in (1, 64, 128, 253, 309, 512, 1000):
        t = geometry.default_tile_rows(f)
        assert t % 256 == 0 and t >= 512


# ------------------------------------------------------------ env layer


def test_env_override_read_at_call_time(monkeypatch):
    base = tune.tile_rows(128)
    assert base == 8192
    # flipped AFTER import/first use — the legacy module-level read
    # would have ignored this
    monkeypatch.setenv("IA_TILE_ROWS", "1024")
    assert tune.tile_rows(128) == 1024
    cfg = tune.resolve(strategy="wavefront", dtype="f32", fp=128)
    assert cfg.origin_of("tile_rows") == "env"
    monkeypatch.delenv("IA_TILE_ROWS")
    assert tune.tile_rows(128) == 8192


def test_env_invalid_value_ignored(monkeypatch):
    monkeypatch.setenv("IA_PACKED_TILE", "not-a-number")
    cfg = tune.resolve(strategy="wavefront", dtype="packed2", fp=256)
    assert cfg.packed_tile_cap == 16384  # default, not a crash
    assert cfg.origin_of("packed_tile_cap") == "default"
    monkeypatch.setenv("IA_PACKED_TILE", "-5")
    cfg = tune.resolve(strategy="wavefront", dtype="packed2", fp=256)
    assert cfg.packed_tile_cap == 16384


def test_wavefront_max_rows_resolves_and_clamps(monkeypatch, tmp_path):
    """The last geometry constant: default is the f32-exactness ceiling,
    env/store may only LOWER it, and the wavefront guard consumes the
    resolved value (not a module constant — grep lock below)."""
    assert tune.wavefront_max_rows() == geometry.DEFAULT_WAVEFRONT_MAX_ROWS
    assert geometry.DEFAULT_WAVEFRONT_MAX_ROWS == 1 << 24
    monkeypatch.setenv("IA_WAVEFRONT_ROWS", "4096")
    cfg = tune.resolve(strategy="wavefront", dtype="f32", fp=128)
    assert cfg.wavefront_max_rows == 4096
    assert cfg.origin_of("wavefront_max_rows") == "env"
    # a value above the ceiling clamps (correctness bound, not a knob
    # you can raise): origin still records where it came from
    monkeypatch.setenv("IA_WAVEFRONT_ROWS", str(1 << 30))
    cfg = tune.resolve(strategy="wavefront", dtype="f32", fp=128)
    assert cfg.wavefront_max_rows == geometry.WAVEFRONT_MAX_ROWS_CEILING
    monkeypatch.delenv("IA_WAVEFRONT_ROWS")
    # store entries flow through the same chain
    path = str(tmp_path / "s.json")
    key = tune.make_key(tune.device_kind(), "wavefront", "f32", 128, "*")
    tune_store.save_entries({key: {"wavefront_max_rows": 1 << 20}}, path)
    monkeypatch.setenv("IA_TUNE_STORE", path)
    assert tune.wavefront_max_rows() == 1 << 20


def test_wavefront_guard_uses_resolved_bound(monkeypatch):
    """Lowering the bound below a small exemplar makes the wavefront
    build refuse it — proof the guard reads tune/, not a constant."""
    a = np.tile(np.linspace(0, 1, 24, dtype=np.float32), (24, 1))
    params = AnalogyParams(levels=1, backend="tpu", strategy="wavefront")
    from image_analogies_tpu.backends.tpu import TpuMatcher
    from image_analogies_tpu.backends.base import LevelJob
    from image_analogies_tpu.ops.features import spec_for_level
    spec = spec_for_level(params, level=0, levels=1, src_channels=1)
    job = LevelJob(level=0, spec=spec, kappa_mult=1.0, a_src=a, a_filt=a,
                   b_src=a)
    monkeypatch.setenv("IA_WAVEFRONT_ROWS", "64")
    with pytest.raises(ValueError, match="wavefront strategy caps"):
        m = TpuMatcher(params)
        db = m.build_features(job)
        m.synthesize_level(db, job)


def test_env_beats_store(monkeypatch, tmp_path):
    path = str(tmp_path / "s.json")
    key = tune.make_key(tune.device_kind(), "wavefront", "f32", 128, "*")
    tune_store.save_entries({key: {"tile_rows": 2048}}, path)
    monkeypatch.setenv("IA_TUNE_STORE", path)
    assert tune.tile_rows(128) == 2048
    monkeypatch.setenv("IA_TILE_ROWS", "512")
    assert tune.tile_rows(128) == 512


# --------------------------------------------------------------- store


def test_store_roundtrip_exact_and_wildcard(monkeypatch, tmp_path):
    path = str(tmp_path / "s.json")
    dev = tune.device_kind()
    exact_key = tune.make_key(dev, "wavefront", "f32", 128,
                              buckets.bucket_rows(5000))
    wild_key = tune.make_key(dev, "wavefront", "f32", 128, "*")
    tune_store.save_entries(
        {exact_key: {"tile_rows": 1024, "source": "test"},
         wild_key: {"tile_rows": 2048, "packed_vmem_limit": 64 << 20}},
        path)
    monkeypatch.setenv("IA_TUNE_STORE", path)

    cfg = tune.resolve(strategy="wavefront", dtype="f32", fp=128,
                       n_rows=5000)
    assert cfg.tile_rows == 1024
    assert cfg.origin_of("tile_rows") == "store"
    # knob missing from the exact entry falls through to the wildcard
    assert cfg.packed_vmem_limit == 64 << 20
    assert cfg.origin_of("packed_vmem_limit") == "store_wildcard"

    # a bucket with no exact entry uses the wildcard
    cfg2 = tune.resolve(strategy="wavefront", dtype="f32", fp=128,
                        n_rows=300)
    assert cfg2.tile_rows == 2048
    assert cfg2.origin_of("tile_rows") == "store_wildcard"

    # round-trip: what save wrote, load returns
    assert tune_store.load_entries(path)[exact_key]["tile_rows"] == 1024


def test_store_schema_validation():
    assert tune_store.validate_entry({"tile_rows": 512, "note": "x"})
    assert not tune_store.validate_entry({"tile_rows": 0})
    assert not tune_store.validate_entry({"tile_rows": -4})
    assert not tune_store.validate_entry({"tile_rows": True})
    assert not tune_store.validate_entry({"tile_rows": "512"})
    assert not tune_store.validate_entry(["tile_rows"])
    with pytest.raises(ValueError):
        tune_store.save_entries({"k": {"tile_rows": "junk"}})


def test_corrupt_store_warns_and_falls_back(monkeypatch, tmp_path):
    log = str(tmp_path / "run.jsonl")
    path = str(tmp_path / "corrupt.json")
    with open(path, "w") as f:
        f.write("{ not json !!")
    monkeypatch.setenv("IA_TUNE_STORE", path)
    p = AnalogyParams(metrics=True, log_path=log)
    with obs_trace.run_scope(p):
        # never a crash; resolution falls back to defaults
        assert tune.tile_rows(128) == 8192
    recs = [json.loads(l) for l in open(log) if l.strip()]
    errs = [r for r in recs if r.get("event") == "tune_store_error"]
    assert len(errs) == 1 and errs[0]["severity"] == "warning"
    assert errs[0]["path"] == path

    # wrong version and bad entries also degrade to empty, once per path
    for blob in ('{"version": 99, "entries": {}}',
                 '{"version": 1, "entries": {"k": {"tile_rows": -1}}}',
                 '{"version": 1}', '[1,2]'):
        p2 = str(tmp_path / f"bad_{abs(hash(blob))}.json")
        with open(p2, "w") as f:
            f.write(blob)
        assert tune_store.load_entries(p2) == {}


def test_store_merge_new_keys_win(tmp_path):
    path = str(tmp_path / "m.json")
    tune_store.save_entries({"a": {"tile_rows": 512},
                             "b": {"tile_rows": 1024}}, path)
    tune_store.merge_entries({"b": {"tile_rows": 2048},
                              "c": {"tile_rows": 256}}, path)
    e = tune_store.load_entries(path)
    assert e["a"]["tile_rows"] == 512
    assert e["b"]["tile_rows"] == 2048
    assert e["c"]["tile_rows"] == 256


# ------------------------------------------------------------ override


def test_override_context_nests_and_restores():
    with tune.override(tile_rows=512):
        assert tune.tile_rows(128) == 512
        with tune.override(packed_tile_cap=4096):
            cfg = tune.resolve(strategy="wavefront", dtype="packed2",
                               fp=256)
            assert cfg.tile_rows == 512
            assert cfg.packed_tile_cap == 4096
            assert cfg.origin_of("tile_rows") == "override"
        assert tune.tile_rows(128) == 512
    assert tune.tile_rows(128) == 8192
    with pytest.raises(ValueError):
        with tune.override(bogus_knob=1):
            pass


# ------------------------------------------------------------- buckets


def test_bucket_rows_properties():
    assert [buckets.bucket_rows(n) for n in
            (1, 100, 256, 300, 700, 1100, 1936, 2500)] == \
        [256, 256, 256, 512, 768, 1536, 2048, 3072]
    for n in range(1, 5000, 37):
        b = buckets.bucket_rows(n)
        assert b >= n
        assert b % 256 == 0
        p2 = b & (-b)  # largest power-of-two divisor
        assert p2 >= 256  # kernels need a pow2-friendly tile divisor
        assert buckets.bucket_rows(b) == b  # idempotent
        assert b <= 2 * n or n <= 256  # bounded padding waste


def test_buckets_enabled_env_wins(monkeypatch):
    p_on = AnalogyParams(shape_buckets=True)
    p_off = AnalogyParams(shape_buckets=False)
    assert buckets.buckets_enabled(p_on)
    assert not buckets.buckets_enabled(p_off)
    monkeypatch.setenv("IA_SHAPE_BUCKETS", "1")
    assert buckets.buckets_enabled(p_off)
    monkeypatch.setenv("IA_SHAPE_BUCKETS", "off")
    assert not buckets.buckets_enabled(p_on)


# --------------------------------------------------- packaged tables


def test_packaged_table_for_known_device_class(monkeypatch):
    """Satellite: known TPU classes resolve shipped per-class geometry
    (origin "packaged"); unknown devices keep the computed defaults."""
    monkeypatch.setattr(tune, "device_kind", lambda: "TPU v5e")
    cfg = tune.resolve(strategy="wavefront", dtype="bf16", fp=256,
                       n_rows=500)
    assert cfg.tile_rows == 2048
    assert cfg.origin_of("tile_rows") == "packaged"
    assert cfg.packed_tile_cap == 8192
    assert cfg.origin_of("packed_tile_cap") == "packaged"

    monkeypatch.setattr(tune, "device_kind", lambda: "cpu")
    cfg2 = tune.resolve(strategy="wavefront", dtype="bf16", fp=256)
    assert all(o == "default" for _, o in cfg2.origin)


def test_v4_packaged_row_matches_legacy_defaults(monkeypatch):
    """The v4 table is the reference sweep: values equal the legacy
    constants, only the provenance label changes."""
    monkeypatch.setattr(tune, "device_kind", lambda: "TPU v4")
    cfg = tune.resolve(strategy="wavefront", dtype="packed2", fp=256)
    assert cfg.packed_tile_cap == geometry.DEFAULT_PACKED_TILE_CAP
    assert cfg.packed_vmem_limit == geometry.DEFAULT_PACKED_VMEM_LIMIT
    assert cfg.origin_of("packed_tile_cap") == "packaged"
    # tile_rows has no v4 row -> still the computed default
    assert cfg.origin_of("tile_rows") == "default"


def test_store_beats_packaged_and_counters(monkeypatch, tmp_path):
    """Precedence: a locally measured store entry shadows the shipped
    class value; counters distinguish the two origins."""
    monkeypatch.setattr(tune, "device_kind", lambda: "TPU v5p")
    p = AnalogyParams(metrics=True)
    with obs_trace.run_scope(p):
        cfg = tune.resolve(strategy="wavefront", dtype="bf16", fp=256)
        snap = obs_metrics.snapshot()
    assert cfg.tile_rows == 8192  # v5p wavefront|bf16 packaged row
    assert snap["counters"]["tune.packaged"] == 1
    assert "tune.fallbacks" not in snap["counters"]

    path = str(tmp_path / "measured.json")
    key = tune.make_key("TPU v5p", "wavefront", "bf16", 256, "*")
    tune_store.save_entries({key: {"tile_rows": 1234}}, path)
    monkeypatch.setenv("IA_TUNE_STORE", path)
    tune_store.invalidate_cache()
    with obs_trace.run_scope(p):
        cfg2 = tune.resolve(strategy="wavefront", dtype="bf16", fp=256)
        snap2 = obs_metrics.snapshot()
    assert cfg2.tile_rows == 1234
    assert cfg2.origin_of("tile_rows") == "store_wildcard"
    # un-measured knobs still fall through to the packaged class row
    assert cfg2.origin_of("packed_tile_cap") == "packaged"
    assert snap2["counters"]["tune.store_hits"] == 1


def test_device_class_mapping():
    from image_analogies_tpu.tune import tables

    assert tables.device_class("TPU v4") == "v4"
    assert tables.device_class("TPU v5e") == "v5e"
    assert tables.device_class("TPU v5 lite") == "v5e"
    assert tables.device_class("TPU v5p") == "v5p"
    assert tables.device_class("cpu") is None
    assert tables.device_class("") is None
    assert tables.lookup("cpu", "wavefront", "f32") == {}


# ------------------------------------------------------ pin scope


def test_pin_scope_single_consult_per_key(monkeypatch, tmp_path):
    """Inside pin_scope a key resolves once; repeats return the pinned
    config with no store consult and no counter/record activity."""
    p = AnalogyParams(metrics=True)
    with obs_trace.run_scope(p):
        with tune.pin_scope():
            first = tune.tile_rows(128, n_rows=500)
            again = tune.tile_rows(128, n_rows=500)
            tune.tile_rows(512, n_rows=500)  # distinct key -> consult
            snap = obs_metrics.snapshot()
    assert first == again
    assert snap["counters"]["tune.fallbacks"] == 2  # not 3

    # reentrant: an inner scope joins the outer pin cache
    with obs_trace.run_scope(p):
        with tune.pin_scope():
            tune.tile_rows(128, n_rows=500)
            with tune.pin_scope():
                tune.tile_rows(128, n_rows=500)
            snap2 = obs_metrics.snapshot()
    assert snap2["counters"]["tune.fallbacks"] == 1


# ----------------------------------------------------------- grep lock


def test_no_call_site_reads_legacy_geometry_constants():
    """Acceptance: ALL kernel geometry flows through tune/ resolution —
    no consumer module mentions the deleted constants/helpers."""
    import image_analogies_tpu
    root = os.path.dirname(image_analogies_tpu.__file__)
    consumers = [os.path.join(root, "backends", "tpu.py"),
                 os.path.join(root, "parallel", "step.py"),
                 os.path.join(root, "models", "video.py"),
                 os.path.join(root, "ops", "pallas_match.py")]
    legacy = re.compile(
        r"\b_tile_rows\b|\b_scan_tile\b|\b_packed_tile_cap\b"
        r"|_PACKED_TILE_CAP|_PACKED_VMEM_LIMIT|_ARGMIN_TILE"
        r"|_WAVEFRONT_MAX_ROWS|DEFAULT_ANN_TOP_M|DEFAULT_ANN_PROJ_DIMS")
    for path in consumers:
        with open(path) as f:
            src = f.read()
        hits = legacy.findall(src)
        assert not hits, f"{path} still reads legacy geometry: {hits}"


# ------------------------------------------- tile-geometry invariance


def test_argmin_champion_invariant_across_tiles():
    """Parity satellite: bit-identical source picks across >=3 tile
    geometries (CPU interpret-mode Pallas)."""
    from image_analogies_tpu.ops.pallas_match import (
        pallas_argmin_l2_prepadded,
    )
    import jax.numpy as jnp

    rng = np.random.RandomState(3)
    npad, fp, m = 1536, 128, 16
    dbp = jnp.asarray(rng.randn(npad, fp).astype(np.float32))
    dbn = jnp.sum(dbp * dbp, axis=1)[None, :]
    q = jnp.asarray(rng.randn(m, fp).astype(np.float32))
    picks, vals = [], []
    for tile in (256, 512, 768):
        idx, val = pallas_argmin_l2_prepadded(q, dbp, dbn, tile_n=tile,
                                              interpret=True)
        picks.append(np.asarray(idx))
        vals.append(np.asarray(val))
    for p, v in zip(picks[1:], vals[1:]):
        np.testing.assert_array_equal(picks[0], p)
        np.testing.assert_array_equal(vals[0], v)


def test_packed_champion_invariant_across_tiles():
    from image_analogies_tpu.ops.pallas_match import packed2k_best
    import jax.numpy as jnp

    rng = np.random.RandomState(4)
    npad, l, m = 2048, 63, 16
    kp = 256  # 4l+3 = 255 <= 256
    wk = jnp.asarray(rng.randn(npad, kp).astype(np.float32), jnp.bfloat16)
    q1 = jnp.asarray(rng.randn(m, l).astype(np.float32), jnp.bfloat16)
    q2 = jnp.asarray(rng.randn(m, l).astype(np.float32), jnp.bfloat16)
    picks = []
    for tile in (256, 512, 1024, 2048):
        idx, _ = packed2k_best(q1, q2, wk, tile_n=tile, interpret=True)
        picks.append(np.asarray(idx))
    for p in picks[1:]:
        np.testing.assert_array_equal(picks[0], p)


def test_snap_tile_to_divisor():
    assert tune.snap_tile_to_divisor(512, 2048) == 512
    assert tune.snap_tile_to_divisor(1000, 1536) == 768
    assert tune.snap_tile_to_divisor(8192, 1536) == 1536
    assert tune.snap_tile_to_divisor(255, 1536) == 192
    assert tune.snap_tile_to_divisor(1, 777) == 1
    for npad in (256, 1536, 2048, 6784):
        for t in (1, 100, 255, 256, 700, 10 ** 6):
            s = tune.snap_tile_to_divisor(t, npad)
            assert npad % s == 0 and 1 <= s <= min(t, npad)


# ------------------------------------------------------------ autotune


def test_autotune_dry_run_cli(capsys):
    """Tier-1 smoke (satellite f): the plan prints without device work."""
    from image_analogies_tpu import cli

    rc = cli.main(["tune", "--dry-run", "--rows", "4096", "--m", "64"])
    assert rc == 0
    plan = json.loads(capsys.readouterr().out)
    assert {s["knob"] for s in plan["sweeps"]} == {"packed_tile_cap",
                                                   "tile_rows"}
    for s in plan["sweeps"]:
        assert s["candidates"] and s["store_key"].endswith("|b*")
        npad = s["shape"]["npad"]
        assert all(npad % c == 0 for c in s["candidates"])


def test_autotune_dry_run_ann_knob(capsys):
    """`ia tune --knob ann` plans the two-stage slab sweep — and stays
    OUT of the default plan above (a full-synthesis sweep is not the
    casual kernel-geometry pass)."""
    from image_analogies_tpu import cli

    rc = cli.main(["tune", "--dry-run", "--knob", "ann"])
    assert rc == 0
    plan = json.loads(capsys.readouterr().out)
    (sweep,) = plan["sweeps"]
    assert sweep["knob"] == "ann_top_m"
    assert sweep["kernel"] == "two_stage"
    assert tuple(sweep["candidates"]) == autotune.ANN_TOP_M_CANDIDATES
    assert sweep["store_key"].endswith("|b*")


def test_autotune_rejects_bad_candidates():
    with pytest.raises(ValueError):
        autotune.build_plan(knob="packed_tile", candidates=(300,))
    with pytest.raises(ValueError):
        autotune.build_plan(knob="argmin_tile", candidates=(100,))
    with pytest.raises(ValueError):
        autotune.build_plan(knob="nonsense")


def test_autotune_run_plan_persists_verified(tmp_path):
    """Interpret-mode sweep end-to-end: verify + persist + resolution
    picks the winner up."""
    import jax

    jax.devices()  # settle device_kind before the plan keys are built
    path = str(tmp_path / "tuned.json")
    plan = autotune.build_plan(knob="argmin_tile", rows=1024, m=16,
                               reps=1, candidates=(256, 512),
                               store=path)
    res = autotune.run_plan(plan, interpret=True)
    assert res["all_verified"]
    assert res["persisted"] == path
    entries = tune_store.load_entries(path)
    (key, entry), = entries.items()
    assert key.endswith("|b*")
    assert entry["tile_rows"] in (256, 512)
    assert entry["source"] == "ia tune"
    # the resolution layer now serves the measured winner
    os.environ["IA_TUNE_STORE"] = path  # autouse fixture restores
    tune_store.invalidate_cache()
    cfg = tune.resolve(strategy="wavefront", dtype="f32", fp=256,
                       n_rows=1024)
    assert cfg.tile_rows == entry["tile_rows"]
    assert cfg.origin_of("tile_rows") == "store_wildcard"


@pytest.mark.slow
def test_autotune_full_sweep_live(tmp_path):
    """The full default grid on the live backend (interpret off on TPU,
    on elsewhere) — the `ia tune` production path."""
    import jax

    path = str(tmp_path / "tuned.json")
    plan = autotune.build_plan(rows=65536, m=256, reps=2, store=path)
    res = autotune.run_plan(plan,
                            interpret=jax.default_backend() != "tpu")
    assert res["all_verified"]
    entries = tune_store.load_entries(path)
    assert entries
    for entry in entries.values():
        assert tune_store.validate_entry(entry)


# -------------------------------------------------- provenance + obs


def test_resolution_counters_and_records(monkeypatch, tmp_path):
    path = str(tmp_path / "s.json")
    dev = tune.device_kind()
    tune_store.save_entries(
        {tune.make_key(dev, "wavefront", "f32", 128, "*"):
         {"tile_rows": 1024}}, path)
    monkeypatch.setenv("IA_TUNE_STORE", path)
    log = str(tmp_path / "run.jsonl")
    p = AnalogyParams(metrics=True, log_path=log)
    with obs_trace.run_scope(p):
        tune.tile_rows(128, n_rows=500)   # store hit
        tune.tile_rows(512, n_rows=500)   # fallback (no entry for f512)
        snap = obs_metrics.snapshot()
    c = snap["counters"]
    assert c["tune.store_hits"] == 1
    assert c["tune.fallbacks"] == 1
    recs = [json.loads(l) for l in open(log) if l.strip()]
    resolved = [r for r in recs if r.get("event") == "tune_resolved"]
    assert len(resolved) == 2  # once per fresh store_key
    by_key = {r["key"]: r for r in resolved}
    hit = by_key[tune.make_key(dev, "wavefront", "f32", 128,
                               buckets.bucket_rows(500))]
    assert hit["tile_rows"] == 1024
    assert hit["origin"]["tile_rows"] == "store_wildcard"

    prov = tune.provenance_snapshot()
    assert set(prov) == set(by_key)
    tune.reset_provenance()
    assert tune.provenance_snapshot() == {}


def test_report_renders_tune_section(tmp_path):
    from image_analogies_tpu.obs import report as obs_report

    log = str(tmp_path / "run.jsonl")
    p = AnalogyParams(metrics=True, log_path=log)
    with obs_trace.run_scope(p, manifest_extra=tune.manifest_info()):
        tune.tile_rows(128, n_rows=500)
    an = obs_report.analyze(obs_report.load_records(log))
    assert an["tune"] is not None
    assert an["tune"]["fallbacks"] == 1
    assert an["tune"]["configs"] and "key" in an["tune"]["configs"][0]
    assert an["manifest"]["tune_entries"] == 0
    text = obs_report.render(an)
    assert "tune:" in text and "resolutions" in text


def test_manifest_info(tmp_path, monkeypatch):
    path = str(tmp_path / "s.json")
    tune_store.save_entries({"a": {"tile_rows": 512}}, path)
    monkeypatch.setenv("IA_TUNE_STORE", path)
    info = tune.manifest_info()
    assert info == {"tune_store": path, "tune_entries": 1}


# ------------------------------------------------- shape bucket engine


def _mini_pair(n, seed=0):
    rng = np.random.RandomState(seed)
    a = rng.rand(n, n).astype(np.float32)
    ap = np.clip(a + 0.1 * rng.rand(n, n).astype(np.float32), 0, 1)
    return a, ap


def test_bucketed_output_parity():
    """Acceptance: bucketing changes program signatures, never outputs."""
    from image_analogies_tpu.models.analogy import create_image_analogy

    a, ap = _mini_pair(24)
    b = np.random.RandomState(5).rand(20, 20).astype(np.float32)
    p = AnalogyParams(backend="tpu", levels=2)
    r_off = create_image_analogy(a, ap, b, p)
    r_on = create_image_analogy(a, ap, b, p.replace(shape_buckets=True))
    np.testing.assert_array_equal(r_off.bp, r_on.bp)
    np.testing.assert_array_equal(r_off.source_map, r_on.source_map)


def test_shape_buckets_reuse_programs_across_exemplar_sizes(tmp_path):
    """Acceptance: with bucketing, a second run at a DIFFERENT exemplar
    size (same buckets) recompiles only the per-size prepare programs —
    every runner program is a cache hit; with bucketing off the same
    pair recompiles everything.  Asserted from the obs engine log."""
    from image_analogies_tpu.models.analogy import create_image_analogy

    levels = 3
    b = np.random.RandomState(7).rand(32, 32).astype(np.float32)

    def compile_stats(n, shape_buckets):
        log = str(tmp_path / f"run_{n}_{shape_buckets}.jsonl")
        p = AnalogyParams(backend="tpu", levels=levels, metrics=True,
                          log_path=log, shape_buckets=shape_buckets)
        a, ap = _mini_pair(n)
        with obs_trace.run_scope(p):
            create_image_analogy(a, ap, b, p)
            snap = obs_metrics.snapshot()
        c = snap["counters"]
        recs = [json.loads(l) for l in open(log) if l.strip()]
        # the shim emits one record per actual compile (hits emit none)
        n_compile_events = sum(r.get("event") == "compile" for r in recs)
        return (int(c.get("compile.count", 0)),
                int(c.get("compile.cache_hits", 0)), n_compile_events)

    # 40^2 and 44^2 exemplars: per-level row counts 1600/400/100 and
    # 1936/484/121 land in the same buckets (2048/512/256)
    first = compile_stats(40, True)
    second = compile_stats(44, True)
    off_first = compile_stats(41, False)
    off_second = compile_stats(45, False)

    # bucketed second run: only the prepare program per level recompiles
    # (its input planes carry the raw exemplar shape); every runner
    # program is reused
    assert second[0] <= levels < first[0]
    assert second[1] >= levels  # cache hits for the reused runners
    # bucketing off: a new exemplar size recompiles everything
    assert off_second[0] == off_first[0] > levels
    # the engine-log compile events agree with the counters
    assert second[2] == second[0]
    assert off_second[2] == off_second[0]


# ------------------------------------------------------------ devcache


def test_devcache_budget_and_gauge_honest(monkeypatch):
    from image_analogies_tpu.utils import devcache

    devcache.clear()
    devcache.set_max_bytes(600 * 1024)
    try:
        p = AnalogyParams(metrics=True)
        with obs_trace.run_scope(p):
            rng = np.random.RandomState(11)
            for i in range(3):  # 3 x 256 KiB > 600 KiB -> evict oldest
                devcache.device_put_cached(
                    rng.rand(256, 256).astype(np.float32))
            snap = obs_metrics.snapshot()
            assert snap["counters"]["devcache.evictions"] >= 1
            gauge = snap["gauges"]["devcache.bytes"]
            assert gauge == devcache._bytes
            assert gauge <= 600 * 1024
            # per-entry byte accounting at eviction: every uploaded byte
            # is either still resident (the gauge) or was counted out
            # through devcache.evicted_bytes — exact identity, not >=
            assert snap["counters"]["devcache.evicted_bytes"] >= 256 * 1024
            assert (gauge + snap["counters"]["devcache.evicted_bytes"]
                    == snap["counters"]["devcache.upload_bytes"])
            devcache.clear()
            assert obs_metrics.snapshot()["gauges"]["devcache.bytes"] == 0
        # env beats the configured budget, read at call time
        monkeypatch.setenv("IA_DEVCACHE_BYTES", "12345")
        assert devcache.max_bytes() == 12345
        monkeypatch.delenv("IA_DEVCACHE_BYTES")
        assert devcache.max_bytes() == 600 * 1024
    finally:
        devcache.set_max_bytes(None)
        devcache.clear()
    assert devcache.max_bytes() == devcache._DEFAULT_MAX_BYTES


def test_params_devcache_budget_applied():
    from image_analogies_tpu.tune import warmup as tune_warmup
    from image_analogies_tpu.utils import devcache

    try:
        p = AnalogyParams(devcache_max_bytes=7 << 20)
        tune_warmup.apply_runtime_config(p)
        assert devcache.max_bytes() == 7 << 20
    finally:
        devcache.set_max_bytes(None)
    with pytest.raises(ValueError):
        AnalogyParams(devcache_max_bytes=0)


# ------------------------------------------------------ warmup + cache


def test_compile_cache_config(tmp_path, monkeypatch):
    import jax

    from image_analogies_tpu.tune import warmup as tune_warmup

    assert tune_warmup.compile_cache_dir(AnalogyParams()) is None
    p = AnalogyParams(compile_cache_dir=str(tmp_path / "cc"))
    assert tune_warmup.compile_cache_dir(p) == str(tmp_path / "cc")
    monkeypatch.setenv("IA_COMPILE_CACHE_DIR", str(tmp_path / "env_cc"))
    assert tune_warmup.compile_cache_dir(p) == str(tmp_path / "env_cc")
    monkeypatch.delenv("IA_COMPILE_CACHE_DIR")

    prev = jax.config.jax_compilation_cache_dir
    try:
        d = tune_warmup.maybe_enable_compile_cache(p)
        assert d == str(tmp_path / "cc")
        assert jax.config.jax_compilation_cache_dir == d
    finally:
        jax.config.update("jax_compilation_cache_dir", prev)


def test_warmup_smoke():
    from image_analogies_tpu.tune import warmup as tune_warmup

    p = AnalogyParams(backend="tpu", levels=1)
    res = tune_warmup.warmup(p, 16, 16)
    assert res["height"] == 16 and res["levels"] == 1
    assert res["compile_count"] >= 1
    assert res["compile_cache_dir"] is None


def test_cli_warmup_smoke(capsys):
    from image_analogies_tpu import cli

    rc = cli.main(["warmup", "--size", "16x16", "--levels", "1"])
    assert rc == 0
    out = json.loads(capsys.readouterr().out)
    # programs may already be warm from an earlier in-process warmup —
    # compiled or reused, the signatures must have been visited
    assert out["compile_count"] + out["compile_cache_hits"] >= 1
