"""Native C++ brute-force matcher (native/match.cpp) and its NumPy fallback."""

import numpy as np
import pytest

from image_analogies_tpu.backends import native_match as nm


def _oracle(db, qs):
    sc = ((db[None, :, :] - qs[:, None, :]) ** 2).sum(-1)
    return sc.argmin(1), sc.min(1)


def test_numpy_fallback_matches_oracle(rng, monkeypatch):
    monkeypatch.setattr(nm, "_LIB", None)
    monkeypatch.setattr(nm, "_TRIED", True)
    db = rng.standard_normal((500, 23)).astype(np.float32)
    qs = rng.standard_normal((17, 23)).astype(np.float32)
    idx, dist = nm.brute_argmin_batch(db, qs)
    ri, rd = _oracle(db, qs)
    np.testing.assert_array_equal(idx, ri)
    np.testing.assert_allclose(dist, rd, atol=1e-3)


@pytest.mark.skipif(not nm.have_native(), reason="libia_match.so not built")
def test_native_matches_oracle(rng):
    db = rng.standard_normal((1000, 40)).astype(np.float32)
    qs = rng.standard_normal((29, 40)).astype(np.float32)
    idx, dist = nm.brute_argmin_batch(db, qs)
    ri, rd = _oracle(db, qs)
    np.testing.assert_array_equal(idx, ri)
    np.testing.assert_allclose(dist, rd, atol=1e-3)


@pytest.mark.skipif(not nm.have_native(), reason="libia_match.so not built")
def test_native_tie_break_lowest_index(rng):
    row = rng.standard_normal(8).astype(np.float32)
    db = np.tile(row, (32, 1))
    idx, _ = nm.brute_argmin_batch(db, row[None, :] + 0.01)
    assert idx[0] == 0
