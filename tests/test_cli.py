"""CLI surface (SURVEY.md §2 P1): arg parsing, modes, eval, error paths."""

import json
import os

import numpy as np
import pytest

from image_analogies_tpu.cli import build_parser, main
from image_analogies_tpu.utils.imageio import load_image, save_image
from tests.conftest import make_pair


@pytest.fixture
def assets(tmp_path):
    a, ap, b = make_pair(16, 16, seed=1)
    paths = {}
    for name, img in [("a", a), ("ap", ap), ("b", b)]:
        p = str(tmp_path / f"{name}.png")
        save_image(p, img)
        paths[name] = p
    return paths, tmp_path


def test_run_filter(assets, capsys):
    paths, tmp = assets
    out = str(tmp / "out.png")
    rc = main(["run", "--mode", "filter", "--a", paths["a"], "--ap",
               paths["ap"], "--b", paths["b"], "--out", out,
               "--levels", "1", "--backend", "cpu", "--kappa", "2"])
    assert rc == 0 and os.path.exists(out)
    img = load_image(out)
    assert img.shape[:2] == (16, 16)


def test_run_texture_synthesis(assets):
    paths, tmp = assets
    out = str(tmp / "tex.png")
    rc = main(["run", "--mode", "texture_synthesis", "--ap", paths["ap"],
               "--out", out, "--out-shape", "12x12", "--levels", "1",
               "--backend", "cpu"])
    assert rc == 0
    assert load_image(out).shape[:2] == (12, 12)


def test_run_missing_b_errors(assets):
    paths, _ = assets
    with pytest.raises(SystemExit):
        main(["run", "--mode", "filter", "--ap", paths["ap"],
              "--out", "/tmp/x.png"])


def test_eval(assets, capsys):
    paths, _ = assets
    rc = main(["eval", "--a", paths["a"], "--b", paths["a"]])
    assert rc == 0
    out = json.loads(capsys.readouterr().out)
    assert out["ssim"] == pytest.approx(1.0, abs=1e-6)


def test_video_cli(assets, capsys):
    paths, tmp = assets
    outdir = str(tmp / "vid")
    rc = main(["video", "--a", paths["a"], "--ap", paths["ap"],
               "--frames", paths["b"], paths["b"], "--out-dir", outdir,
               "--levels", "1", "--backend", "cpu"])
    assert rc == 0
    assert sorted(os.listdir(outdir)) == ["frame_0000.png", "frame_0001.png"]


def test_engine_flags_map_to_params(assets):
    parser = build_parser()
    args = parser.parse_args(
        ["run", "--ap", "x", "--out", "y", "--no-ann", "--no-remap",
         "--kappa", "7", "--db-shards", "4", "--strategy", "batched",
         "--refine-passes", "5"])
    from image_analogies_tpu.cli import _params_from_args
    from image_analogies_tpu.config import PRESETS

    p = _params_from_args(args, PRESETS["oil_filter"])
    assert p.kappa == 7 and not p.use_ann and not p.remap_luminance
    assert p.db_shards == 4 and p.strategy == "batched"
    assert p.refine_passes == 5


def test_sweep_cli(assets, capsys):
    paths, tmp = assets
    outdir = str(tmp / "sweep")
    rc = main(["sweep", "--mode", "filter", "--a", paths["a"], "--ap",
               paths["ap"], "--b", paths["b"], "--kappas", "0,5",
               "--out-dir", outdir, "--ref", paths["b"],
               "--levels", "1", "--backend", "cpu"])
    assert rc == 0
    recs = [json.loads(l) for l in capsys.readouterr().out.splitlines()]
    assert [r["kappa"] for r in recs] == [0.0, 5.0]
    for r in recs:
        assert os.path.exists(r["out"]) and 0.0 <= r["ssim_vs_ref"] <= 1.0


def test_seeded_texture_cli(assets):
    paths, tmp = assets
    o1, o2 = str(tmp / "t1.png"), str(tmp / "t2.png")
    for out, seed in ((o1, "3"), (o2, "4")):
        rc = main(["run", "--mode", "texture_synthesis", "--ap", paths["ap"],
                   "--out", out, "--out-shape", "12x12", "--levels", "1",
                   "--backend", "cpu", "--seed", seed])
        assert rc == 0
    assert (load_image(o1) != load_image(o2)).any()


def test_refine_passes_reaches_batched_scan(assets):
    # refine_passes is a static TpuLevelDB field: 0 passes must still run
    a, ap, b = make_pair(14, 14, seed=2)
    from image_analogies_tpu.config import AnalogyParams
    from image_analogies_tpu.models.analogy import create_image_analogy

    r0 = create_image_analogy(a, ap, b, AnalogyParams(
        levels=1, backend="tpu", strategy="batched", refine_passes=0))
    r3 = create_image_analogy(a, ap, b, AnalogyParams(
        levels=1, backend="tpu", strategy="batched", refine_passes=3))
    assert r0.bp_y.shape == r3.bp_y.shape == (14, 14)


def test_no_level_sync_flag_maps():
    args = build_parser().parse_args(
        ["run", "--ap", "x.png", "--out", "y.png", "--no-level-sync"])
    from image_analogies_tpu.cli import _params_from_args
    from image_analogies_tpu.config import PRESETS

    p = _params_from_args(args, PRESETS["oil_filter"])
    assert p.level_sync is False
    # default stays synced (per-level stats measure real device time)
    args2 = build_parser().parse_args(
        ["run", "--ap", "x.png", "--out", "y.png"])
    assert _params_from_args(args2, PRESETS["oil_filter"]).level_sync is True
