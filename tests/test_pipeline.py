"""Async pipelined engine (PR 8): overlap, donation, bf16 gate, wire.

Tier-1 invariants locked here:

- the pipelined + donating engine is BIT-IDENTICAL to the sequential
  engine (same bp/bp_y/source_map bytes) on the oracle-parity
  strategies — pipelining is cache warming and donation is memory
  reuse; neither may touch results;
- donation safety under §5.3: with level_retries armed the driver
  refuses donation, keeps host copies, and recovers bit-identically
  from an injected level.dispatch transient;
- pipeline accounting: AnalogyResult.timing + pipeline.* gauges and
  counters, and the `ia report` pipeline section that renders them;
- bf16_scoring is opt-in, off by default, validated at config time,
  and gated behind the oracle-parity probe audit;
- AnalogyResult.source_map performs exactly ONE device transfer no
  matter how often it is read;
- serve/wire.py: the length-prefixed raw-f32 frame round-trips, rejects
  malformed frames, and both directions of the HTTP content
  negotiation work end-to-end (JSON stays the default).
"""

import json
import threading
import urllib.request

import numpy as np
import pytest

from image_analogies_tpu.chaos import inject
from image_analogies_tpu.chaos.plan import ChaosPlan, SiteRule
from image_analogies_tpu.config import AnalogyParams
from image_analogies_tpu.models.analogy import (
    AnalogyResult,
    create_image_analogy,
)
from image_analogies_tpu.obs import trace as obs_trace
from image_analogies_tpu.serve import wire
from tests.conftest import make_pair


def _params(**kw):
    kw.setdefault("levels", 2)
    kw.setdefault("backend", "tpu")
    kw.setdefault("strategy", "wavefront")
    return AnalogyParams(**kw)


# ------------------------------------------------- bit-identity


@pytest.mark.parametrize("strategy", ["wavefront", "batched"])
def test_pipelined_donating_engine_bit_identical(strategy):
    """pipeline=True + donate_buffers=True (forcing both code paths on
    the CPU jax backend, where donate_argnums is a no-op warning) must
    produce byte-identical planes to the sequential lock-step engine."""
    a, ap, b = make_pair(20, 22, seed=5)
    seq = create_image_analogy(a, ap, b, _params(
        strategy=strategy, level_sync=True, pipeline=False,
        donate_buffers=False))
    pipe = create_image_analogy(a, ap, b, _params(
        strategy=strategy, level_sync=False, pipeline=True,
        donate_buffers=True))
    np.testing.assert_array_equal(np.asarray(seq.bp_y),
                                  np.asarray(pipe.bp_y))
    np.testing.assert_array_equal(np.asarray(seq.bp), np.asarray(pipe.bp))
    np.testing.assert_array_equal(seq.source_map, pipe.source_map)


def test_pipeline_timing_accounting():
    """The driver reports host_gap_ms always, and the overlap fields +
    pipeline.* gauges/counters when the pipeline ran."""
    a, ap, b = make_pair(20, 22, seed=6)
    with obs_trace.run_scope(AnalogyParams(metrics=True)) as ctx:
        res = create_image_analogy(a, ap, b, _params(
            levels=3, level_sync=False, pipeline=True))
    assert "host_gap_ms" in res.timing
    assert res.timing["prepped_levels"] == 2  # levels-1 lookaheads
    assert res.timing["prep_ms"] >= 0.0
    assert res.timing["host_hidden_ms"] >= 0.0
    snap = ctx.registry.snapshot()
    assert "pipeline.host_gap_ms" in snap["gauges"]
    assert "pipeline.host_hidden_ms" in snap["gauges"]
    assert snap["counters"]["pipeline.levels_prepped"] == 2


def test_sequential_run_still_records_host_gap():
    a, ap, b = make_pair(16, 16, seed=7)
    res = create_image_analogy(a, ap, b, _params(
        level_sync=True, pipeline=False))
    assert res.timing["host_gap_ms"] >= 0.0
    assert "prep_ms" not in res.timing  # pipeline off -> no overlap rows


def test_retries_disable_pipeline_and_donation():
    """level_retries > 0 must force both features off (the §5.3 fault
    envelope): pipeline_active() says so, and the engine recovers
    bit-identically from an injected level.dispatch transient even when
    the caller asked for donation."""
    p = _params(level_retries=1, pipeline=True, donate_buffers=True,
                level_sync=False)
    assert p.pipeline_active() is False

    a, ap, b = make_pair(20, 22, seed=8)
    clean = create_image_analogy(a, ap, b, _params())
    plan = ChaosPlan(seed=0, name="donate-retry", sites=(
        ("level.dispatch", SiteRule(kind="transient", schedule=(1,))),))
    with inject.plan_scope(plan):
        faulted = create_image_analogy(a, ap, b, p)
        snap = inject.snapshot()
    assert snap["level.dispatch"]["injected"] == 1
    np.testing.assert_array_equal(np.asarray(clean.bp_y),
                                  np.asarray(faulted.bp_y))
    np.testing.assert_array_equal(clean.source_map, faulted.source_map)
    assert "donated_levels" not in faulted.timing


# ------------------------------------------------- report section


def test_report_renders_pipeline_section():
    from image_analogies_tpu.obs import report

    records = [{"event": "run_end", "metrics": {
        "counters": {"pipeline.levels_prepped": 4,
                     "pipeline.donated_levels": 4},
        "gauges": {"pipeline.host_gap_ms": 12.5,
                   "pipeline.prep_ms": 30.0,
                   "pipeline.wait_ms": 2.0,
                   "pipeline.host_hidden_ms": 28.0}}}]
    an = report.analyze(records)
    assert an["pipeline"]["host_gap_ms"] == 12.5
    assert an["pipeline"]["hidden_fraction"] == pytest.approx(28.0 / 30.0)
    text = report.render(an)
    assert "pipeline:" in text
    assert "hidden under" in text
    assert "4 levels donated" in text
    # pipeline.* counters must not leak into the generic counter dump
    assert "pipeline.levels_prepped" not in text


def test_bench_check_gates_host_gap(tmp_path):
    """`ia bench --check` fails a fresh result whose host_gap_ms
    regressed past threshold even when wall-clock held."""
    import importlib.util
    import os

    spec = importlib.util.spec_from_file_location(
        "ia_bench_t", os.path.join(os.path.dirname(__file__), "..",
                                   "bench.py"))
    bench = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(bench)

    def point(rnd, value, gap):
        doc = {"parsed": {"value": value, "metric": "1024x1024 wall",
                          "host_gap_ms": gap}}
        (tmp_path / f"BENCH_r{rnd:02d}.json").write_text(json.dumps(doc))

    point(1, 5.0, 100.0)
    point(2, 5.0, 40.0)
    traj = bench.load_trajectory(str(tmp_path))
    assert [p["host_gap_ms"] for p in traj["points"]] == [100.0, 40.0]
    ok = bench.check_regression(traj, fresh_value=5.0, fresh_gap=41.0)
    assert ok["ok"] and ok["host_gap_floor"] == 40.0
    bad = bench.check_regression(traj, fresh_value=5.0, fresh_gap=90.0)
    assert not bad["ok"]
    assert any("host_gap_ms regressed" in p for p in bad["problems"])
    # archives without the field still gate wall-clock alone
    (tmp_path / "BENCH_r01.json").write_text(
        json.dumps({"parsed": {"value": 5.0, "metric": "1024x1024 w"}}))
    traj = bench.load_trajectory(str(tmp_path))
    legacy = bench.check_regression(traj, fresh_value=5.0)
    assert legacy["ok"] and "host_gap_ms" not in legacy


# ------------------------------------------------- bf16 gate


def test_bf16_scoring_config_validation():
    with pytest.raises(ValueError, match="bf16_scoring"):
        AnalogyParams(backend="cpu", bf16_scoring=True)
    with pytest.raises(ValueError, match="bf16_scoring"):
        AnalogyParams(backend="tpu", strategy="batched",
                      bf16_scoring=True)
    assert AnalogyParams().bf16_scoring is False  # off by default


def test_bf16_gate_probe_allows_on_parity(monkeypatch):
    """On this (CPU jax) backend the bf16 pad plane never materializes,
    so the probe's bf16 run IS the exact scan — the audit comes back
    clean and the gate opens; results equal the exact engine's."""
    from image_analogies_tpu.backends import tpu as tpu_backend

    tpu_backend.reset_bf16_gate()
    a, ap, b = make_pair(20, 22, seed=9)
    exact = create_image_analogy(a, ap, b, _params())
    fast = create_image_analogy(a, ap, b, _params(bf16_scoring=True))
    np.testing.assert_array_equal(np.asarray(exact.bp_y),
                                  np.asarray(fast.bp_y))
    assert tpu_backend._bf16_gate_allows(_params(bf16_scoring=True))


def test_bf16_gate_refuses_unexplained_mismatch(monkeypatch):
    """An audit with unexplained mismatches must auto-disable the mode
    process-wide (cached verdict) without failing the synthesis."""
    from image_analogies_tpu.backends import tpu as tpu_backend

    tpu_backend.reset_bf16_gate()
    monkeypatch.setattr(
        tpu_backend, "_bf16_probe_verdict",
        lambda params: {"ok": False, "mismatches": 3, "unexplained": 3,
                        "first_divergence_is_tie": False})
    p = _params(bf16_scoring=True)
    assert tpu_backend._bf16_gate_allows(p) is False
    assert tpu_backend._bf16_gate_allows(p) is False  # cached, no re-probe
    a, ap, b = make_pair(16, 16, seed=10)
    res = create_image_analogy(a, ap, b, p)  # silently exact
    exact = create_image_analogy(a, ap, b, _params())
    np.testing.assert_array_equal(np.asarray(exact.bp_y),
                                  np.asarray(res.bp_y))
    tpu_backend.reset_bf16_gate()


# ------------------------------------------------- source_map transfers


def test_source_map_fetches_exactly_once():
    class CountingPlane:
        def __init__(self, arr):
            self.arr = arr
            self.transfers = 0

        def __array__(self, dtype=None, copy=None):
            self.transfers += 1
            return np.asarray(self.arr, dtype or np.int32)

    plane = CountingPlane(np.arange(16, dtype=np.int32).reshape(4, 4))
    res = AnalogyResult(bp=np.zeros((4, 4)), bp_y=np.zeros((4, 4)),
                        source_map_raw=plane)
    first = res.source_map
    for _ in range(5):
        np.testing.assert_array_equal(res.source_map, first)
    assert plane.transfers == 1


# ------------------------------------------------- wire format


def test_wire_roundtrip_shapes():
    arrays = [np.random.default_rng(0).random((5, 7)).astype(np.float32),
              np.zeros((3,), np.float32),
              np.arange(24, dtype=np.float32).reshape(2, 3, 4)]
    out = wire.decode_planes(wire.encode_planes(arrays))
    assert len(out) == 3
    for x, y in zip(arrays, out):
        assert y.dtype == np.float32
        np.testing.assert_array_equal(x, y)
        assert y.flags.writeable


def test_wire_rejects_malformed_frames():
    good = wire.encode_planes([np.ones((2, 2), np.float32)])
    with pytest.raises(wire.WireError, match="magic"):
        wire.decode_planes(b"NOPE" + good[4:])
    with pytest.raises(wire.WireError, match="truncated"):
        wire.decode_planes(good[:-3])
    with pytest.raises(wire.WireError, match="trailing"):
        wire.decode_planes(good + b"\x00")
    with pytest.raises(wire.WireError, match="too many arrays"):
        wire.encode_planes([np.zeros(1, np.float32)]
                           * (wire.MAX_ARRAYS + 1))
    hostile = wire.MAGIC + np.array([1, 2, 1 << 20, 1 << 20],
                                    "<u4").tobytes()
    with pytest.raises(wire.WireError, match="exceeds"):
        wire.decode_planes(hostile)


def test_http_binary_negotiation():
    """POST a binary frame (planes in body, deadline/idem in headers),
    Accept binary back; then mix the directions; JSON default intact."""
    from image_analogies_tpu.serve import ServeConfig, Server
    from image_analogies_tpu.serve.http import serve_http

    a, ap, b = make_pair(10, 10, seed=30)
    cfg = ServeConfig(params=AnalogyParams(levels=2, backend="cpu"),
                      workers=1, max_batch=1, batch_window_ms=0.0,
                      default_deadline_s=60.0)
    with Server(cfg) as srv:
        httpd = serve_http(srv, 0)
        t = threading.Thread(target=httpd.serve_forever, daemon=True)
        t.start()
        try:
            url = f"http://127.0.0.1:{httpd.server_address[1]}/v1/analogy"
            frame = wire.encode_planes([a, ap, b])

            # binary in, binary out
            req = urllib.request.Request(url, data=frame, headers={
                "Content-Type": wire.CONTENT_TYPE,
                "Accept": wire.CONTENT_TYPE,
                "X-IA-Deadline-Ms": "60000",
                "X-IA-Idempotency-Key": "wire-test-1"})
            with urllib.request.urlopen(req) as r:
                assert r.headers["Content-Type"] == wire.CONTENT_TYPE
                assert r.headers["X-IA-Status"] == "ok"
                assert r.headers["X-IA-Request"]
                timings = json.loads(r.headers["X-IA-Timings"])
                bp_bin = wire.decode_planes(r.read())[0]
            assert set(timings) == {"queue_ms", "dispatch_ms", "total_ms"}

            # JSON in, JSON out (the default) agrees bit-for-bit
            body = json.dumps({"a": a.tolist(), "ap": ap.tolist(),
                               "b": b.tolist()}).encode()
            req = urllib.request.Request(url, data=body, headers={
                "Content-Type": "application/json"})
            with urllib.request.urlopen(req) as r:
                assert r.headers["Content-Type"] == "application/json"
                bp_json = np.asarray(json.load(r)["bp"], np.float32)
            np.testing.assert_array_equal(bp_bin, bp_json)

            # binary in, JSON out (no Accept header)
            req = urllib.request.Request(url, data=frame, headers={
                "Content-Type": wire.CONTENT_TYPE})
            with urllib.request.urlopen(req) as r:
                bp_mixed = np.asarray(json.load(r)["bp"], np.float32)
            np.testing.assert_array_equal(bp_bin, bp_mixed)

            # malformed binary -> 400, JSON error body
            req = urllib.request.Request(url, data=b"IAF2garbage",
                                         headers={"Content-Type":
                                                  wire.CONTENT_TYPE})
            with pytest.raises(urllib.error.HTTPError) as err:
                urllib.request.urlopen(req)
            assert err.value.code == 400
            assert json.load(err.value)["error"] == "bad_request"
        finally:
            httpd.shutdown()
