"""serve/ subsystem (ISSUE 4): admission control, micro-batching,
deadlines, graceful degradation, failure retry, telemetry.

Acceptance invariants locked here:

- queue-full submits get Rejected("queue_full") immediately — no hang,
  no unbounded queue growth;
- an already-expired deadline is cancelled BEFORE dispatch
  (DeadlineExceeded), an unmeetable-but-live deadline yields a valid
  response flagged as degraded;
- an injected transient device failure retries inside the server and the
  client never observes an error;
- batched responses are bit-identical to singleton engine dispatch;
- serving telemetry flows end-to-end: serve_request records + spans in
  the run log, a "serving" section in `ia report`, a serve track in
  `ia trace` output;
- serve/ never calls jit/pjit/pmap directly (grep lock) — all device
  work goes through the engine entry point.
"""

import json
import os
import re
import threading
import time

import numpy as np
import pytest

from image_analogies_tpu.config import AnalogyParams
from image_analogies_tpu.models.analogy import create_image_analogy
from image_analogies_tpu.serve import (
    DeadlineExceeded,
    Rejected,
    Server,
    ServeConfig,
)
from image_analogies_tpu.serve.worker import WorkerPool
from tests.conftest import make_pair


@pytest.fixture(autouse=True)
def _disarm_fault_injector():
    yield
    from image_analogies_tpu.utils import failure

    failure.inject_failures(0)


def _params(**kw):
    kw.setdefault("levels", 2)
    kw.setdefault("backend", "cpu")
    return AnalogyParams(**kw)


def _cfg(params=None, **kw):
    return ServeConfig(params=params or _params(), **kw)


def _wait_until(pred, timeout=10.0):
    end = time.monotonic() + timeout
    while time.monotonic() < end:
        if pred():
            return True
        time.sleep(0.005)
    return False


def _gate_workers(monkeypatch):
    """Block every worker batch until the returned event is set — makes
    queue-occupancy tests deterministic."""
    gate = threading.Event()
    orig = WorkerPool._run_batch

    def gated(self, batch):
        gate.wait(30)
        orig(self, batch)

    monkeypatch.setattr(WorkerPool, "_run_batch", gated)
    return gate


# ------------------------------------------------ admission control


def test_queue_full_rejected_immediately(monkeypatch):
    gate = _gate_workers(monkeypatch)
    cfg = _cfg(queue_depth=2, workers=1, max_batch=1, batch_window_ms=0.0)
    a, ap, b = make_pair(10, 10, seed=1)
    with Server(cfg) as srv:
        first = srv.submit(a, ap, b)
        # the single worker pops the leader and blocks on the gate...
        assert _wait_until(lambda: srv.queue_depth == 0)
        queued = [srv.submit(a, ap, b) for _ in range(2)]  # ...queue fills
        t0 = time.monotonic()
        with pytest.raises(Rejected) as ei:
            srv.submit(a, ap, b)
        assert ei.value.reason == "queue_full"
        assert time.monotonic() - t0 < 1.0  # immediate, not a blocked wait
        gate.set()
        for fut in [first] + queued:
            assert fut.result(timeout=60).bp is not None


def test_submit_after_shutdown_rejected():
    cfg = _cfg(workers=1)
    srv = Server(cfg).start()
    srv.shutdown()
    a, ap, b = make_pair(8, 8, seed=2)
    with pytest.raises(Rejected) as ei:
        srv.submit(a, ap, b)
    assert ei.value.reason == "shutting_down"


def test_shutdown_without_drain_fails_queued(monkeypatch):
    gate = _gate_workers(monkeypatch)
    cfg = _cfg(queue_depth=8, workers=1, max_batch=1, batch_window_ms=0.0)
    a, ap, b = make_pair(10, 10, seed=3)
    srv = Server(cfg).start()
    inflight = srv.submit(a, ap, b)
    assert _wait_until(lambda: srv.queue_depth == 0)
    queued = srv.submit(a, ap, b)
    threading.Timer(0.2, gate.set).start()
    srv.shutdown(drain=False)
    with pytest.raises(Rejected) as ei:
        queued.result(timeout=1)
    assert ei.value.reason == "shutting_down"
    # the in-flight request still completes normally during drain
    assert inflight.result(timeout=60).bp is not None


# --------------------------------------- micro-batching + bit-identity


def test_batch_coalesces_and_matches_singleton_dispatch():
    """Same-exemplar burst coalesces into one batch; every response is
    bit-identical to a direct engine call for the same request."""
    params = _params()
    a, ap, _ = make_pair(14, 14, seed=4)
    rng = np.random.default_rng(4)
    targets = [rng.random((14, 14), dtype=np.float32).astype(np.float32)
               for _ in range(3)]
    singleton = [create_image_analogy(a, ap, b, params).bp for b in targets]

    # max_batch == burst size: the window closes the moment the batch is
    # complete, so a generous window costs nothing and removes timing luck.
    cfg = _cfg(params=params, workers=1, max_batch=3,
               batch_window_ms=2000.0)
    with Server(cfg) as srv:
        futs = [srv.submit(a, ap, b) for b in targets]
        resps = [f.result(timeout=120) for f in futs]
    assert [r.batch_size for r in resps] == [3, 3, 3]
    assert all(r.status == "ok" and r.degraded is None for r in resps)
    for resp, ref in zip(resps, singleton):
        np.testing.assert_array_equal(resp.bp, ref)


def test_incompatible_params_do_not_share_a_batch():
    params = _params()
    a, ap, b = make_pair(12, 12, seed=5)
    cfg = _cfg(params=params, workers=1, max_batch=4, batch_window_ms=500.0)
    with Server(cfg) as srv:
        f1 = srv.submit(a, ap, b)
        f2 = srv.submit(a, ap, b, params=params.replace(kappa=9.0))
        r1, r2 = f1.result(timeout=120), f2.result(timeout=120)
    # different params digest -> different batch keys -> singleton batches
    assert r1.batch_size == 1 and r2.batch_size == 1


# --------------------------------------------- deadlines + degradation


def test_expired_deadline_cancelled_before_dispatch():
    cfg = _cfg(workers=1)
    a, ap, b = make_pair(12, 12, seed=6)
    with Server(cfg) as srv:
        fut = srv.submit(a, ap, b, deadline_s=0.0)  # expired at submit
        with pytest.raises(DeadlineExceeded) as ei:
            fut.result(timeout=60)
    assert ei.value.request_id == 1


def test_unmeetable_deadline_degrades_but_serves():
    """With a measured cost model that says full fidelity cannot meet the
    deadline, the request is served at reduced fidelity and flagged —
    never silently dropped."""
    params = _params(levels=2, patch_size=5)
    a, ap, b = make_pair(16, 16, seed=7)
    cfg = _cfg(params=params, workers=1, max_batch=1, batch_window_ms=0.0)
    with Server(cfg) as srv:
        # seed the EWMA at 1e-3 s/unit: full fidelity (16*16*2*25 units)
        # estimates 12.8s against a 5s deadline, the 3x3 ladder rungs fit
        srv.cost_model.observe(1000.0, 1.0)
        resp = srv.request(a, ap, b, deadline_s=5.0, timeout=120)
    assert resp.status == "degraded"
    assert resp.degraded is not None
    assert resp.degraded["patch_size"] == 3
    assert resp.degraded["levels"] <= params.levels
    assert resp.bp.shape == b.shape
    assert np.isfinite(np.asarray(resp.bp)).all()


def test_no_degrade_config_runs_full_fidelity():
    params = _params(levels=2)
    a, ap, b = make_pair(12, 12, seed=8)
    cfg = _cfg(params=params, workers=1, degrade=False)
    with Server(cfg) as srv:
        srv.cost_model.observe(1000.0, 1.0)  # same pessimistic model
        resp = srv.request(a, ap, b, deadline_s=5.0, timeout=120)
    assert resp.status == "ok" and resp.degraded is None
    np.testing.assert_array_equal(
        resp.bp, create_image_analogy(a, ap, b, params).bp)


# ------------------------------------------------ failure injection


def test_injected_transient_failure_retried_transparently(tmp_path):
    """SURVEY.md §5.3 in the serving path: the worker's retry wrapper
    absorbs a transient fault; the client sees a clean, correct result."""
    from image_analogies_tpu.utils import failure

    log = str(tmp_path / "serve.jsonl")
    params = _params(log_path=log)
    a, ap, b = make_pair(12, 12, seed=9)
    clean = create_image_analogy(a, ap, b, _params())
    cfg = _cfg(params=params, workers=1, request_retries=2)
    with Server(cfg) as srv:
        failure.inject_failures(1)  # the first wrapped dispatch dies
        resp = srv.request(a, ap, b, timeout=120)
    assert resp.status == "ok"
    np.testing.assert_array_equal(resp.bp_y, clean.bp_y)
    recs = [json.loads(l) for l in open(log) if l.strip()]
    retries = [r for r in recs if r.get("event") == "level_retry"
               and r.get("scope") == "serve"]
    assert len(retries) == 1 and retries[0]["error"] == "InjectedFailure"
    errors = [r for r in recs if r.get("event") == "serve_request"
              and r.get("status") == "error"]
    assert not errors


# --------------------------------------------------- selftest smoke


def test_selftest_smoke_zero_drops_bit_identical():
    """Fast tier-1 slice of `ia serve --selftest`: every request admitted
    and completed, outputs bit-identical to the sequential baseline."""
    from image_analogies_tpu.serve import loadgen

    cfg = _cfg(workers=2, max_batch=4, batch_window_ms=25.0)
    summary = loadgen.selftest(cfg, 4, seed=0,
                               shapes=((12, 12), (14, 14)))
    assert summary["rejected"] == 0
    assert summary["errors"] == 0 and summary["timeouts"] == 0
    assert summary["completed"] == 4 and summary["degraded"] == 0
    assert summary["bit_identical"] is True
    assert sum(int(v) for v in summary["batch_size_hist"].values()) == 4


# ------------------------------------------- telemetry end-to-end


def test_cli_selftest_report_and_trace(tmp_path, capsys):
    """`ia serve --selftest` writes a run log whose serving telemetry
    survives the whole obs pipeline: `ia report` renders the serving
    section, `ia trace` exports serve-track events."""
    from image_analogies_tpu.cli import main
    from image_analogies_tpu.obs import export as obs_export

    log = str(tmp_path / "serve.jsonl")
    rc = main(["serve", "--selftest", "3", "--workers", "1",
               "--max-batch", "3", "--batch-window-ms", "50",
               "--levels", "2", "--backend", "cpu", "--log-path", log])
    captured = capsys.readouterr()
    assert rc == 0, captured.err
    assert "selftest: 3 requests" in captured.out
    assert "bit-identical to singleton dispatch: True" in captured.out

    rc = main(["report", log])
    assert rc == 0
    rep = capsys.readouterr().out
    assert "serving:" in rep
    assert "admission" in rep and "p50" in rep

    out = str(tmp_path / "trace.json")
    rc = main(["trace", log, "-o", out])
    assert rc == 0
    capsys.readouterr()
    trace = json.load(open(out))
    serve_events = [e for e in trace["traceEvents"]
                    if e.get("tid") == obs_export.SERVE_TID]
    reqs = [e for e in serve_events
            if e["ph"] == "X" and e["name"].startswith("req ")]
    assert len(reqs) == 3  # one interval per served request
    names = {e.get("args", {}).get("name") for e in trace["traceEvents"]
             if e["ph"] == "M"}
    assert "serve" in names  # the serve track is labeled


def test_server_scope_counters_in_report(tmp_path):
    """Server lifetime = one obs run: run_end carries the admission and
    outcome counters `ia report --json` aggregates."""
    from image_analogies_tpu.obs import report as obs_report

    log = str(tmp_path / "run.jsonl")
    params = _params(log_path=log)
    a, ap, b = make_pair(12, 12, seed=10)
    cfg = _cfg(params=params, workers=1)
    with Server(cfg) as srv:
        srv.request(a, ap, b, timeout=120)
        with pytest.raises(DeadlineExceeded):
            srv.request(a, ap, b, deadline_s=0.0, timeout=60)
    an = obs_report.analyze(obs_report.load_records(log))
    srv_info = an["serve"]
    assert srv_info is not None
    assert srv_info["accepted"] == 2 and srv_info["rejected"] == 0
    assert srv_info["completed"] == 1 and srv_info["timeouts"] == 1
    assert srv_info["p50_ms"] > 0


# --------------------------------------- deadline-aware (EDF) ordering


def _mk_req(rid, key, deadline=None, age_s=0.0):
    from concurrent.futures import Future

    from image_analogies_tpu.serve.types import Request

    req = Request(request_id=rid, a=None, ap=None, b=None, params=None,
                  key=(key,), future=Future())
    req.t_submit -= age_s
    if deadline is not None:
        req.deadline = req.t_submit + age_s + deadline
    return req


def test_edf_pop_order_tight_deadlines_first():
    """Distinct-key waiters pop earliest-deadline-first; undeadlined
    traffic sorts last (but see the aging test: never starves)."""
    from image_analogies_tpu.serve.queue import AdmissionQueue

    q = AdmissionQueue(8, deadline_ordering=True, age_bound_s=60.0)
    q.submit(_mk_req(1, "a"))                  # no deadline
    q.submit(_mk_req(2, "b", deadline=9.0))    # slack
    q.submit(_mk_req(3, "c", deadline=0.5))    # tight
    order = [q.pop_batch(1, 0.0)[0].request_id for _ in range(3)]
    assert order == [3, 2, 1]


def test_fifo_when_deadline_ordering_off():
    from image_analogies_tpu.serve.queue import AdmissionQueue

    q = AdmissionQueue(8, deadline_ordering=False)
    q.submit(_mk_req(1, "a"))
    q.submit(_mk_req(2, "b", deadline=0.5))
    order = [q.pop_batch(1, 0.0)[0].request_id for _ in range(2)]
    assert order == [1, 2]


def test_aging_bound_prevents_starvation():
    """Once the oldest waiter has queued past the bound it leads no
    matter what — EDF can reorder by at most age_bound_s."""
    from image_analogies_tpu.serve.queue import AdmissionQueue

    q = AdmissionQueue(8, deadline_ordering=True, age_bound_s=5.0)
    q.submit(_mk_req(1, "a", age_s=10.0))      # undeadlined, aged out
    q.submit(_mk_req(2, "b", deadline=0.1))    # tight deadline
    assert q.pop_batch(1, 0.0)[0].request_id == 1  # promoted past EDF
    assert q.pop_batch(1, 0.0)[0].request_id == 2


def test_loadgen_mixed_deadline_load_accounts_for_everything():
    """The EDF satellite's load shape: tight-deadline traffic interleaved
    with undeadlined bulk.  Every request resolves to exactly one
    outcome and full-fidelity outputs stay bit-identical."""
    from image_analogies_tpu.serve import loadgen

    cfg = _cfg(workers=2, max_batch=2, batch_window_ms=5.0)
    summary = loadgen.selftest(cfg, 4, seed=1,
                               deadline_ms=(10_000, None),
                               shapes=((12, 12),))
    assert summary["errors"] == 0
    resolved = (summary["completed"] + summary["degraded"]
                + summary["timeouts"] + summary["rejected"])
    assert resolved == 4
    assert summary["bit_identical"] is True


# ------------------------------------------------- circuit breaker


def test_breaker_state_machine_with_fake_clock():
    from image_analogies_tpu.serve.breaker import CircuitBreaker

    now = {"t": 0.0}
    br = CircuitBreaker(threshold=2, cooldown_s=10.0,
                        clock=lambda: now["t"])
    assert br.state == "closed" and br.allow()
    br.record_failure()
    assert br.state == "closed"        # 1 < threshold
    br.record_success()
    br.record_failure()
    assert br.state == "closed"        # success reset the streak
    br.record_failure()
    br.record_failure()
    assert br.state == "open"          # 2 consecutive -> tripped
    assert not br.allow()              # fast fail inside cooldown
    now["t"] = 11.0
    assert br.allow()                  # half-open: the ONE probe slot
    assert not br.allow()              # second caller: still fast fail
    br.record_failure()                # probe failed
    assert br.state == "open"          # fresh cooldown
    now["t"] = 22.0
    assert br.allow()
    br.record_success()                # probe succeeded
    assert br.state == "closed" and br.allow()


def test_breaker_threshold_zero_disabled():
    from image_analogies_tpu.serve.breaker import CircuitBreaker

    br = CircuitBreaker(threshold=0, cooldown_s=1.0)
    for _ in range(50):
        br.record_failure()
    assert br.state == "closed" and br.allow()


def test_breaker_trips_server_and_recovers():
    """End-to-end: consecutive dispatch failures trip the breaker, later
    submits are shed at ADMISSION with Rejected("breaker_open") — one hop
    before the queue, no retry burn — and a successful probe after the
    cooldown closes it again."""
    from image_analogies_tpu.utils import failure

    a, ap, b = make_pair(10, 10, seed=20)
    cfg = _cfg(workers=1, max_batch=1, batch_window_ms=0.0,
               request_retries=0, breaker_threshold=2,
               breaker_cooldown_s=30.0)
    with Server(cfg) as srv:
        failure.inject_failures(2)
        for _ in range(2):  # two consecutive dispatch failures
            with pytest.raises(failure.InjectedFailure):
                srv.request(a, ap, b, timeout=60)
        assert srv._pool.breaker.state == "open"
        t0 = time.monotonic()
        with pytest.raises(Rejected) as ei:
            srv.request(a, ap, b, timeout=60)
        assert ei.value.reason == "breaker_open"
        assert time.monotonic() - t0 < 5.0  # shed at submit, no dispatch
        assert srv.queue_depth == 0         # never entered the queue
        # elapse the cooldown without sleeping 30s (white-box nudge)
        srv._pool.breaker._opened_at -= 60.0
        resp = srv.request(a, ap, b, timeout=120)  # the half-open probe
        assert resp.status == "ok"
        assert srv._pool.breaker.state == "closed"


def test_breaker_circuit_open_still_reachable_at_dispatch():
    """An ACCEPTED request whose breaker trips between admission and
    dispatch still gets the dispatch-layer Rejected("circuit_open") —
    admission shedding did not remove the inner containment layer."""
    a, ap, b = make_pair(10, 10, seed=22)
    cfg = _cfg(workers=1, max_batch=1, batch_window_ms=0.0,
               request_retries=0, breaker_threshold=1,
               breaker_cooldown_s=300.0)
    srv = Server(cfg)
    # Gate the worker loop so the request sits in the queue while we
    # trip the breaker underneath it.
    gate = threading.Event()
    orig_pop = srv._queue.pop_batch

    def gated_pop(*a_, **kw):
        batch = orig_pop(*a_, **kw)
        gate.wait(timeout=30)
        return batch

    srv._queue.pop_batch = gated_pop
    with srv:
        fut = srv.submit(a, ap, b)       # admitted while closed
        srv._pool.breaker.record_failure()  # threshold=1 -> open
        assert srv._pool.breaker.state == "open"
        gate.set()                        # worker proceeds to dispatch
        with pytest.raises(Rejected) as ei:
            fut.result(timeout=60)
        assert ei.value.reason == "circuit_open"


# ----------------------------------------------- crash containment


def test_worker_crash_requeue_exhausted_rejects():
    """crash_requeues=0: a crashed batch fails its members with
    Rejected("worker_crash") — resolved, never lost — and the worker
    thread survives to serve the next request."""
    from image_analogies_tpu.chaos import inject
    from image_analogies_tpu.chaos.plan import ChaosPlan, SiteRule

    a, ap, b = make_pair(10, 10, seed=21)
    cfg = _cfg(workers=1, max_batch=1, batch_window_ms=0.0,
               crash_requeues=0, breaker_threshold=0)
    plan = ChaosPlan(seed=0, sites=(
        ("serve.dispatch", SiteRule(kind="crash", schedule=(0,))),))
    with Server(cfg) as srv:
        with inject.plan_scope(plan):
            with pytest.raises(Rejected) as ei:
                srv.request(a, ap, b, timeout=60)
            assert ei.value.reason == "worker_crash"
            # the thread survived: the next request dispatches normally
            assert srv.request(a, ap, b, timeout=120).status == "ok"


# ----------------------------------------------- cost-model priors


def test_cost_prior_store_roundtrip(tmp_path, monkeypatch):
    """cost_persist: a server's learned rate lands in the tune store and
    seeds the NEXT server's degrade estimates (provenance "store")."""
    from image_analogies_tpu.tune import store as tune_store

    monkeypatch.setenv("IA_TUNE_STORE", str(tmp_path / "tune.json"))
    params = _params(levels=1)
    a, ap, b = make_pair(10, 10, seed=22)

    srv = Server(_cfg(params=params, workers=1, cost_persist=True)).start()
    assert srv.cost_prior_source == "default"  # cpu: no store, no table
    srv.request(a, ap, b, timeout=120)         # one REAL observation
    learned = srv.cost_model.rate
    srv.shutdown()

    entry = tune_store.load_entries().get("serve_cost|cpu|any")
    assert entry is not None and entry["cost_rate"] == pytest.approx(learned)

    srv2 = Server(_cfg(params=params, workers=1)).start()
    try:
        assert srv2.cost_prior_source == "store"
        assert srv2.cost_model.rate == pytest.approx(learned)
        assert srv2.cost_model.samples == 1      # seeded counts as history
        assert srv2.cost_model.real_samples == 0  # ...but not as evidence
    finally:
        srv2.shutdown()


def test_cost_persist_off_by_default(tmp_path, monkeypatch):
    monkeypatch.setenv("IA_TUNE_STORE", str(tmp_path / "tune.json"))
    params = _params(levels=1)
    a, ap, b = make_pair(10, 10, seed=23)
    with Server(_cfg(params=params, workers=1)) as srv:
        srv.request(a, ap, b, timeout=120)
    assert not os.path.exists(str(tmp_path / "tune.json"))


def test_cost_prior_packaged_table(tmp_path, monkeypatch):
    from image_analogies_tpu.serve import degrade as serve_degrade
    from image_analogies_tpu.tune import tables as tune_tables

    monkeypatch.setenv("IA_TUNE_STORE", str(tmp_path / "empty.json"))
    monkeypatch.setitem(tune_tables.COST_RATES, "cpu|any", 5e-9)
    rate, src = serve_degrade.load_prior(_params())
    assert src == "packaged" and rate == 5e-9


def test_seeded_cost_model_blends_first_sample():
    """A store/packaged prior is a real past measurement: the first
    observation BLENDS into it; only the hardwired default is replaced
    wholesale on first contact."""
    from image_analogies_tpu.serve.degrade import CostModel

    seeded = CostModel(1e-3, seeded=True)
    seeded.observe(1.0, 2e-3)  # sample rate 2e-3
    assert 1e-3 < seeded.rate < 2e-3  # EWMA blend, not replacement

    fresh = CostModel()  # optimistic default, unseeded
    fresh.observe(1.0, 2e-3)
    assert fresh.rate == pytest.approx(2e-3)  # replaced outright


# ------------------------------------------------------- grep locks


def test_serve_never_calls_jit_directly():
    """serve/ is a host-side scheduler: all device work goes through the
    engine entry point (which owns jit/sharding), and no serve module
    imports jax at module scope — `import serve` must stay cheap."""
    import image_analogies_tpu.serve as serve_pkg

    root = os.path.dirname(serve_pkg.__file__)
    # call syntax, so prose mentions in docstrings don't trip the lock
    forbidden = re.compile(r"\bjax\.jit\s*\(|\bpjit\s*\(|\bjax\.pmap\s*\(")
    toplevel_jax = re.compile(r"^(import jax|from jax)", re.MULTILINE)
    scanned = set()
    for name in sorted(os.listdir(root)):
        if not name.endswith(".py"):
            continue
        scanned.add(name)
        with open(os.path.join(root, name)) as f:
            src = f.read()
        assert not forbidden.findall(src), f"serve/{name} calls jit/pjit"
        assert not toplevel_jax.findall(src), (
            f"serve/{name} imports jax at module scope")
    # the fleet plane must stay under this lock — a rename that moves
    # router/fleet out of serve/ must move the jax-free guarantee with
    # it; transport/worker_main are the subprocess spawn path, where a
    # module-scope jax import would bill every child ~seconds before
    # the readiness handshake even starts; control/policy are the
    # elastic control plane, which runs inside the health daemon and
    # the admission path
    assert {"router.py", "fleet.py", "transport.py", "worker_main.py",
            "control.py", "policy.py"} <= scanned
