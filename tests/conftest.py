"""Test env: force an 8-device virtual CPU mesh BEFORE jax import
(SURVEY.md §4.5 — the TPU-world analogue of testing multi-node without a
cluster).  Sharded-argmin/pmin logic is exercised on this mesh."""

import os

# Force CPU (the box's sitecustomize registers the axon TPU plugin and sets
# jax_platforms programmatically, overriding the env var — so override the
# config after import, before any device is touched).  Set
# IA_TEST_PLATFORM=axon to run the suite against the real chip instead.
_platform = os.environ.get("IA_TEST_PLATFORM", "cpu")
os.environ["JAX_PLATFORMS"] = _platform
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8").strip()

import jax

jax.config.update("jax_platforms", _platform)
if _platform == "cpu":
    try:
        jax.config.update("jax_num_cpu_devices", 8)
    except AttributeError:
        # older jax: the XLA_FLAGS host-platform count above already
        # provides the 8-device virtual mesh
        pass

import numpy as np
import pytest


@pytest.fixture(autouse=True)
def _disarm_fault_planes():
    """Fault injection is process-global (utils.failure's counter injector
    AND the chaos plan): reset both after every test so a failing test can
    never leak armed synthetic faults into unrelated tests."""
    yield
    from image_analogies_tpu import chaos
    from image_analogies_tpu.utils import failure

    failure.inject_failures(0)
    chaos.disarm()


@pytest.fixture(autouse=True)
def _reap_worker_children():
    """SIGKILL any subprocess fleet worker a test left behind.

    The subprocess transport keeps a live-children registry
    (serve.transport._LIVE); a test that fails mid-fleet would otherwise
    orphan real OS processes that outlive the whole pytest run.  Checked
    via sys.modules so tests that never import the transport pay
    nothing."""
    import sys as _sys

    yield
    mod = _sys.modules.get("image_analogies_tpu.serve.transport")
    if mod is not None:
        mod.reap_orphans()


@pytest.fixture
def rng():
    return np.random.default_rng(1234)


def make_pair(h=20, w=22, seed=0, channels=0):
    """Synthetic (A, A', B) triple: A' is a deterministic filter of A."""
    r = np.random.default_rng(seed)
    yy, xx = np.meshgrid(np.linspace(0, 1, h), np.linspace(0, 1, w),
                         indexing="ij")
    a = (0.6 * yy + 0.4 * xx + 0.08 * r.standard_normal((h, w))).clip(0, 1)
    ap = np.round(a * 5) / 5.0
    b = (0.3 * yy**2 + 0.7 * xx + 0.08 * r.standard_normal((h, w))).clip(0, 1)
    if channels:
        a = np.stack([a] * channels, -1) * r.uniform(0.5, 1.0, channels)
        b = np.stack([b] * channels, -1) * r.uniform(0.5, 1.0, channels)
    return a.astype(np.float32), ap.astype(np.float32), b.astype(np.float32)
