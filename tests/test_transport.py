"""Subprocess worker transport (serve/transport.py, serve/worker_main.py).

Locked here:

- crash-loop supervisor semantics: rapid deaths (uptime under the
  window) escalate a capped jittered respawn backoff and gate the slot
  at the threshold; a slow death resets the streak; threshold 0
  disables the gate;
- ServeConfig/AnalogyParams JSON codec roundtrip (the spawn handshake's
  stdin document survives a real json encode/decode);
- `ia fleet --transport` flag parses and rejects unknown transports;
- REAL advisory-lock semantics against foreign pids: a live child's
  journal lock refuses a second opener (JournalLocked), a SIGKILLed
  child's lock is swept by the next opener — the exact handoff path the
  fleet drill rides;
- `ia fleet --selftest` methodology over the subprocess transport:
  routed children answer bit-identical to the sequential baseline
  through the IAF2 HTTP hop.

Every test runs under a hard SIGALRM budget and the conftest
_reap_worker_children fixture SIGKILLs anything left behind — a wedged
child must fail ONE test loudly, never hang the suite.

The chaos-armed SIGKILL handoff drill itself (exactly-once, lock sweep,
segment advance, spill) rides the per-kind tier-1 parametrization in
test_chaos.py (kind="fleet_death_subprocess").
"""

import json
import os
import signal

import pytest

from image_analogies_tpu.chaos import drills
from image_analogies_tpu.serve import journal as serve_journal
from image_analogies_tpu.serve import transport as serve_transport
from image_analogies_tpu.serve.types import FleetConfig


@pytest.fixture(autouse=True)
def _hard_timeout():
    """Per-test wall-clock ceiling for everything in this module: a lost
    readiness handshake or a wedged child raises here (and the orphan
    reaper cleans up) instead of eating the tier-1 budget."""

    def _boom(signum, frame):  # noqa: ARG001 - signal API
        serve_transport.reap_orphans()
        raise TimeoutError("transport test exceeded its 180 s budget")

    old = signal.signal(signal.SIGALRM, _boom)
    signal.alarm(180)
    yield
    signal.alarm(0)
    signal.signal(signal.SIGALRM, old)


def test_crash_loop_supervisor_semantics():
    sup = serve_transport.CrashLoopSupervisor(
        window_s=1.0, threshold=3, backoff_s=0.05, backoff_cap_s=0.4)
    # rapid death: backoff armed, no gate yet
    v = sup.on_death("w0", uptime_s=0.1)
    assert v["rapid"] == 1 and not v["gate"]
    assert 0.0 < v["delay_s"] <= 0.4
    v = sup.on_death("w0", uptime_s=0.2)
    assert v["rapid"] == 2 and not v["gate"]
    # third rapid death in a row: gate, and no pointless delay
    v = sup.on_death("w0", uptime_s=0.0)
    assert v["rapid"] == 3 and v["gate"] and v["delay_s"] == 0.0
    # a slow death (lived past the window) resets the streak
    sup.reset("w0")
    v = sup.on_death("w0", uptime_s=5.0)
    assert v == {"rapid": 0, "delay_s": 0.0, "gate": False}
    # per-wid isolation, and the same wid always jitters the same
    d1 = sup.on_death("w1", uptime_s=0.0)["delay_s"]
    sup.reset("w1")
    assert sup.on_death("w1", uptime_s=0.0)["delay_s"] == d1
    # threshold 0 disables the gate entirely (respawn forever)
    sup0 = serve_transport.CrashLoopSupervisor(
        window_s=1.0, threshold=0, backoff_s=0.05, backoff_cap_s=0.4)
    for _ in range(5):
        assert not sup0.on_death("w0", uptime_s=0.0)["gate"]


def test_config_json_roundtrip():
    """The spawn handshake ships ServeConfig as JSON on the child's
    stdin: a real encode/decode roundtrip must reproduce the dataclass
    exactly (tuples re-tupled, params rebuilt)."""
    import dataclasses

    cfg = drills.serve_config(workers=2, max_batch=3,
                              journal_dir="/tmp/jdir")
    cfg = dataclasses.replace(cfg, warmup_sizes=((8, 8), (16, 16)))
    doc = json.loads(json.dumps(serve_transport.config_to_json(cfg)))
    assert serve_transport.config_from_json(doc) == cfg
    p = cfg.params
    pdoc = json.loads(json.dumps(serve_transport.params_to_json(p)))
    assert serve_transport.params_from_json(pdoc) == p


def test_cli_fleet_transport_flag():
    from image_analogies_tpu import cli

    args = cli.build_parser().parse_args(
        ["fleet", "--selftest", "2", "--transport", "subprocess"])
    assert args.transport == "subprocess"
    assert cli.build_parser().parse_args(["fleet"]).transport == "inproc"
    with pytest.raises(SystemExit):
        cli.build_parser().parse_args(["fleet", "--transport", "smoke"])
    with pytest.raises(ValueError):
        serve_transport.make_transport("smoke")


def test_live_lock_refuses_and_dead_lock_sweeps(tmp_path):
    """Advisory-lock truth against REAL foreign pids: while the child
    lives, its journal lock refuses this process (JournalLocked, the
    single-writer invariant); after SIGKILL the same lock is stale and
    the next open() sweeps it — the handoff path's first step."""
    jdir = str(tmp_path / "w0")
    cfg = drills.serve_config(workers=1, max_batch=2,
                              batch_window_ms=5.0, journal_dir=jdir)
    handle = serve_transport.SubprocessTransport().spawn(
        "w0", 0, cfg, "iaf2", spawn_timeout_s=120.0)
    try:
        assert handle.pid != os.getpid()
        h = handle.health()
        # the lock holds the CHILD's pid — a real foreign owner, visible
        # through the worker's own /healthz
        assert h["ok"] and h["journal"]["lock_pid"] == handle.pid
        with pytest.raises(serve_journal.JournalLocked) as exc:
            serve_journal.RequestJournal(jdir).open()
        assert exc.value.pid == handle.pid
    finally:
        handle.kill()
    # owner is a corpse now: open() sweeps the stale lock and takes over
    j = serve_journal.RequestJournal(jdir).open()
    try:
        assert j.info()["lock_pid"] == os.getpid()
    finally:
        j.close()


def test_bench_handoff_recovery_toy_scale():
    """`ia bench`'s ``handoff_recovery_ms`` methodology at toy scale:
    SIGKILL the home subprocess worker mid-request, and the headline
    times kill -> the replacement (same journal dir, foreign lock
    swept) resolving the stranded future bit-identically."""
    import bench

    out = bench.measure_handoff_recovery(size=16, levels=1)
    assert out["bit_identical"]
    assert out["handoff_recovery_ms"] > 0
    assert out["replacement_pid"] not in (out["victim_pid"], os.getpid())
    assert out["replacement_generation"] == 1
    assert out["stale_lock_swept"] >= 1


def test_bench_check_gates_handoff_with_no_floor_path():
    """handoff_recovery_ms rides `ia bench --check`: a floored archive
    gates regressions; legacy archives (pre-subprocess-transport
    rounds) record the number without gating."""
    import bench

    floored = {"points": [
        {"value": 6.0, "metric_key": "1024x1024",
         "handoff_recovery_ms": 4000.0,
         "round": 1, "file": "BENCH_r01.json", "source": "parsed"}]}
    ok = bench.check_regression(floored, fresh_value=6.0,
                                fresh_key="1024x1024",
                                fresh_handoff=4100.0)
    assert ok["ok"] and ok["handoff_recovery_floor"] == 4000.0
    bad = bench.check_regression(floored, fresh_value=6.0,
                                 fresh_key="1024x1024",
                                 fresh_handoff=9000.0)
    assert not bad["ok"]
    assert any("handoff_recovery_ms" in s for s in bad["problems"])

    legacy = {"points": [
        {"value": 6.0, "metric_key": "1024x1024",
         "round": 1, "file": "BENCH_r01.json", "source": "parsed"}]}
    rec = bench.check_regression(legacy, fresh_value=6.0,
                                 fresh_key="1024x1024",
                                 fresh_handoff=9000.0)
    assert rec["ok"]
    assert rec["handoff_recovery_ms"] == 9000.0
    assert rec["handoff_recovery_floor"] is None

    # the headline extractor carries the rider out of an archive doc
    head = bench.extract_headline(
        {"parsed": {"value": 6.0, "metric": "1024x1024 wall",
                    "handoff_recovery_ms": 1234.0}})
    assert head["handoff_recovery_ms"] == 1234.0


def test_subprocess_fleet_selftest_bit_identity(tmp_path):
    """`ia fleet --selftest` methodology over --transport subprocess:
    requests routed to real child processes over the IAF2 HTTP hop come
    back bit-identical to the sequential in-process baseline."""
    from image_analogies_tpu.obs import trace as obs_trace
    from image_analogies_tpu.serve import loadgen

    fcfg = FleetConfig(
        serve=drills.serve_config(workers=1, max_batch=4,
                                  batch_window_ms=20.0),
        size=2, vnodes=16, transport="subprocess",
        journal_root=str(tmp_path / "journals"),
        health_interval_s=0.1, death_checks=2,
        backoff_s=0.01, backoff_cap_s=0.05)
    with obs_trace.run_scope(fcfg.serve.params):
        summary = loadgen.fleet_selftest(fcfg, 3, seed=3)
    assert summary["transport"] == "subprocess"
    assert summary["errors"] == 0 and summary["rejected"] == 0
    assert summary["bit_identical"] is True
    assert summary["codecs"].get("iaf2", 0) >= 3
