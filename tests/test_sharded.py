"""Sharded patch-DB argmin on the 8-device virtual CPU mesh (SURVEY.md §4.5).

Exercises the `lax.pmin`+index all-reduce logic without a pod: conftest forces
XLA_FLAGS=--xla_force_host_platform_device_count=8.
"""

import os
import sys

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from image_analogies_tpu.config import AnalogyParams
from image_analogies_tpu.models.analogy import create_image_analogy
from image_analogies_tpu.ops.pallas_match import xla_argmin_l2
from image_analogies_tpu.parallel.mesh import make_mesh
from image_analogies_tpu.parallel.sharded_match import (
    make_sharded_argmin,
    shard_level_db,
)
from image_analogies_tpu.utils.ssim import ssim
from tests.conftest import make_pair


def test_mesh_shape():
    assert jax.device_count() == 8, "conftest must provide 8 virtual devices"
    mesh = make_mesh(db_shards=4, data_shards=2)
    assert mesh.shape == {"data": 2, "db": 4}
    with pytest.raises(ValueError):
        make_mesh(db_shards=16)


@pytest.mark.parametrize("shards", [1, 2, 4, 8])
@pytest.mark.parametrize("n", [64, 100])  # 100: padding rows in play
def test_sharded_argmin_matches_single_device(shards, n, rng):
    f, m = 40, 16
    db = jnp.asarray(rng.standard_normal((n, f)), jnp.float32)
    dbn = jnp.sum(db * db, axis=1)
    q = jnp.asarray(rng.standard_normal((m, f)), jnp.float32)

    ref_idx, ref_d = xla_argmin_l2(q, db, dbn)

    mesh = make_mesh(db_shards=shards)
    db_sh, dbn_sh, _ = shard_level_db(db, dbn, jnp.zeros((n,)), mesh)
    fn = make_sharded_argmin(mesh, force_xla=True)
    idx, d = fn(q, db_sh, dbn_sh)

    np.testing.assert_allclose(np.asarray(d), np.asarray(ref_d), atol=1e-3)
    # indices agree except on fp ties; where they differ, distances must tie
    ii, ri = np.asarray(idx), np.asarray(ref_idx)
    diff = ii != ri
    if diff.any():
        np.testing.assert_allclose(np.asarray(d)[diff],
                                   np.asarray(ref_d)[diff], atol=1e-3)


def test_sharded_argmin_tie_break_lowest_index(rng):
    """Duplicate rows across shards: the LOWEST global index must win,
    matching the single-chip kernel's tie-break."""
    f = 8
    row = rng.standard_normal(f).astype(np.float32)
    db = np.tile(row, (16, 1)).astype(np.float32)  # all rows identical
    dbn = jnp.sum(jnp.asarray(db) ** 2, axis=1)
    q = jnp.asarray(row[None, :] + 0.01)
    mesh = make_mesh(db_shards=4)
    db_sh, dbn_sh, _ = shard_level_db(jnp.asarray(db), dbn,
                                      jnp.zeros((16,)), mesh)
    fn = make_sharded_argmin(mesh, force_xla=True)
    idx, _ = fn(q, db_sh, dbn_sh)
    assert int(idx[0]) == 0


def test_sharded_build_drops_per_chip_db_copies(rng):
    """The honest sharded-memory story (round-1 VERDICT weak item 3): with
    db_shards > 1, the per-chip full-DB arrays must be 1-row placeholders —
    rows are read only through the sharded arrays + psum lookups."""
    from image_analogies_tpu.backends.base import LevelJob
    from image_analogies_tpu.backends.tpu import TpuMatcher
    from image_analogies_tpu.ops.features import spec_for_level

    a, ap, b = make_pair(24, 24, seed=1)
    params = AnalogyParams(levels=1, backend="tpu", strategy="wavefront",
                           db_shards=4)
    from image_analogies_tpu.ops import color

    spec = spec_for_level(params, 0, 1, 1)
    job = LevelJob(level=0, spec=spec, kappa_mult=4.0,
                   a_src=color.luminance(a), a_filt=color.luminance(ap),
                   b_src=color.luminance(b))
    db = TpuMatcher(params).build_features(job)
    assert db.mesh is not None and db.mesh.shape["db"] == 4
    for name in ("db", "db_rowsafe"):
        assert getattr(db, name).shape[0] == 1, name  # placeholder, not Na
    assert db.a_filt_flat.shape[0] == 1
    assert db.db_sharded is not None and db.afilt_sharded is not None
    assert db.db_sharded.shape[0] >= 24 * 24
    # and the level still synthesizes correctly through the mesh step
    bp, s, st = TpuMatcher(params).synthesize_level(db, job)
    assert bp.shape == (24, 24) and s.max() < 24 * 24


def test_end_to_end_sharded_matches_unsharded(rng):
    """db_shards=4 on the virtual mesh must reproduce the single-device
    batched output exactly (same candidates, same tie-breaks)."""
    a, ap, b = make_pair(20, 20, seed=7)
    p1 = AnalogyParams(levels=2, kappa=2.0, backend="tpu",
                       strategy="batched", db_shards=1)
    p4 = p1.replace(db_shards=4)
    r1 = create_image_analogy(a, ap, b, p1)
    r4 = create_image_analogy(a, ap, b, p4)
    sv = ssim(r1.bp_y, r4.bp_y, data_range=1.0)
    assert sv >= 0.99, f"sharded-vs-unsharded SSIM {sv}"
    agree = (r1.source_map == r4.source_map).mean()
    assert agree >= 0.95, f"source-map agreement {agree}"


def test_distributed_initialize_noop_and_plumbing(monkeypatch):
    """SURVEY.md §5.8: single-process runs skip jax.distributed entirely;
    configured runs pass coordinates through (initialize itself is mocked —
    a real multi-host handshake needs actual hosts)."""
    from image_analogies_tpu.parallel import distributed

    monkeypatch.delenv("JAX_COORDINATOR_ADDRESS", raising=False)
    monkeypatch.delenv("JAX_NUM_PROCESSES", raising=False)
    assert distributed.initialize_distributed() is False  # no-op path

    calls = {}
    monkeypatch.setattr(jax.distributed, "initialize",
                        lambda **kw: calls.update(kw))
    assert distributed.initialize_distributed("h0:1234", 2, 1) is True
    assert calls == {"coordinator_address": "h0:1234",
                     "num_processes": 2, "process_id": 1}

    calls.clear()
    monkeypatch.setenv("JAX_COORDINATOR_ADDRESS", "h9:99")
    monkeypatch.setenv("JAX_NUM_PROCESSES", "4")
    monkeypatch.setenv("JAX_PROCESS_ID", "3")
    assert distributed.initialize_distributed() is True
    assert calls["num_processes"] == 4 and calls["process_id"] == 3


@pytest.mark.parametrize("shards", [2, 4, 8])
def test_ring_argmin_matches_allreduce(shards, rng):
    """Ring-rotating query tiles (SURVEY.md §5.7's ring-attention analogue)
    must produce exactly the all-reduce variant's picks, including the
    lowest-global-index tie-break."""
    from image_analogies_tpu.parallel.sharded_match import make_ring_argmin

    n, f, m = 96, 40, 16  # m divides every shard count
    db = jnp.asarray(rng.standard_normal((n, f)), jnp.float32)
    q = jnp.asarray(rng.standard_normal((m, f)), jnp.float32)
    # plant cross-shard duplicates of query 0 -> exact tie, lowest must win
    db = db.at[5].set(q[0]).at[n - 3].set(q[0])
    dbn = jnp.sum(db * db, axis=1)

    mesh = make_mesh(db_shards=shards)
    db_sh, dbn_sh, _ = shard_level_db(db, dbn, jnp.zeros((n,)), mesh)
    ref = make_sharded_argmin(mesh, force_xla=True)
    ring = make_ring_argmin(mesh, force_xla=True)
    ri, rd = ref(q, db_sh, dbn_sh)
    gi, gd = ring(q, db_sh, dbn_sh)
    np.testing.assert_array_equal(np.asarray(gi), np.asarray(ri))
    np.testing.assert_allclose(np.asarray(gd), np.asarray(rd), atol=1e-4)
    assert int(gi[0]) == 5  # tie broken to the lowest global index


@pytest.mark.slow
def test_two_process_distributed_smoke():
    """Round-3 VERDICT item 5: exercise parallel/distributed.py UN-MOCKED.

    Two localhost CPU processes (one device each) perform the real
    jax.distributed coordination handshake, lay the db_shards=2 mesh
    across the PROCESS boundary, and run a tiny wavefront analogy whose
    collectives (min+argmin all-reduce, psum row-gathers) ride gloo;
    process 0 asserts the sharded output equals the serial one bit-exactly
    (tests/distributed_worker.py)."""
    import socket
    import subprocess

    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
    worker = os.path.join(os.path.dirname(__file__),
                          "distributed_worker.py")
    env = {k: v for k, v in os.environ.items()
           if k not in ("JAX_PLATFORMS", "XLA_FLAGS")}
    procs = [subprocess.Popen(
        [sys.executable, worker, str(port), str(i)],
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
        env=env) for i in range(2)]
    outs = []
    try:
        for p in procs:
            out, _ = p.communicate(timeout=600)
            outs.append(out)
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
    for i, (p, out) in enumerate(zip(procs, outs)):
        # ONLY the worker's explicit init-failure sentinel skips; a crash
        # whose traceback merely mentions gloo is a real regression in the
        # collectives path and must fail (review round 3)
        if "DISTRIBUTED_SMOKE_UNSUPPORTED" in out:
            pytest.skip(f"distributed runtime unavailable: {out[-400:]}")
        assert p.returncode == 0, f"worker {i} failed:\n{out[-4000:]}"
        assert "DISTRIBUTED_SMOKE_OK" in out, out[-4000:]


def test_packed_champion_allreduce_matches_global(rng):
    """The packed sharded scan's cross-shard resolution must reproduce the
    single-array packed champion pick, including lowest-GLOBAL-index ties
    for duplicate rows planted in DIFFERENT shards (the invariant the
    real-TPU mesh wavefront now rides — interpret-mode kernel inside the
    virtual shard_map)."""
    from jax.sharding import PartitionSpec as P

    from image_analogies_tpu.ops.pallas_match import (
        add_norm_lanes,
        bf16_split3,
        packed2_champions,
    )
    from image_analogies_tpu.parallel.mesh import shard_map
    from image_analogies_tpu.parallel.sharded_match import (
        packed_champion_allreduce,
    )

    n, L, m, shards, tile = 512, 55, 16, 4, 128
    x = rng.standard_normal((n, L)).astype(np.float32)
    q = rng.standard_normal((m, L)).astype(np.float32)
    # duplicates across shard boundaries: rows 70 (shard 0) and 400
    # (shard 3) equal query 0 -> exact tie, global-lowest 70 must win
    x[70] = q[0]
    x[400] = q[0]

    shift = np.zeros((L,), np.float32)
    shift[:] = x.mean(0)
    xc = jnp.asarray(x - shift[None, :])
    d1, d2, r2 = bf16_split3(xc)
    d1, d2 = d1.astype(jnp.bfloat16), d2.astype(jnp.bfloat16)
    d3 = r2.astype(jnp.bfloat16)
    kp = 128

    def pack(left, right):
        return jnp.zeros((n, kp), jnp.bfloat16).at[:, :L].set(left).at[
            :, L:2 * L].set(right)

    w1, w2 = pack(d1, d2), pack(d1, d3)
    dbnh = 0.5 * jnp.sum(xc * xc, axis=1)
    qc = jnp.asarray(q - shift[None, :])
    g1, g2, _ = bf16_split3(qc)
    q1, q2 = g1.astype(jnp.bfloat16), g2.astype(jnp.bfloat16)

    # global reference: single packed2 call over the whole array
    vals, idx = packed2_champions(q1, q2, w1, w2, dbnh[None, :],
                                  tile_n=tile, interpret=True)
    k = jnp.argmax(vals, axis=1)
    ref = np.asarray(jnp.take_along_axis(idx, k[:, None], 1)[:, 0])
    assert ref[0] == 70  # the planted tie resolves to the lowest index

    mesh = make_mesh(db_shards=shards)
    sharded = shard_map(
        lambda qq1, qq2, wks: packed_champion_allreduce(
            qq1, qq2, wks, "db", tile_n=tile, interpret=True),
        mesh=mesh,
        in_specs=(P(), P(), P("db", None)),
        out_specs=(P(), P()),
        check_rep=False,
    )
    # round 4: the allreduce consumes the K-wide single-array layout
    # [d1|d2|norm lanes|d1|d3] (the same one packed2k_best scans)
    o2 = 2 * L + 3
    kp2 = 256
    wk = jnp.zeros((n, kp2), jnp.bfloat16)
    wk = wk.at[:, :L].set(d1).at[:, L:2 * L].set(d2)
    wk = add_norm_lanes(wk, dbnh, L)
    wk = wk.at[:, o2:o2 + L].set(d1).at[:, o2 + L:o2 + 2 * L].set(d3)
    gi, gv = jax.jit(sharded)(q1, q2, wk)
    np.testing.assert_array_equal(np.asarray(gi), ref)


@pytest.mark.parametrize("fused", [False, True])
def test_packed_mesh_level_matches_solo_interpret(rng, fused):
    """End-to-end coverage of the PRODUCTION packed mesh wavefront (the
    real-TPU scan) on CI hardware: the packed kernel runs through the
    Pallas interpreter inside the virtual db_shards=4 shard_map, driven by
    the same build_sharded_db(packed=True) the TPU path uses, and the
    level output must bit-match the solo CPU wavefront (the interpreter's
    scan is fp32, so picks are exact).  ``fused`` additionally routes the
    coherence/re-score/A'-value reads through the round-5 sharded
    [live | dead norm | A'] psum gather (the production real-TPU form);
    its live-split scoring reorders fp sums, so the tie-aware check below
    adjudicates any divergence."""
    import dataclasses

    from image_analogies_tpu.backends.base import LevelJob
    from image_analogies_tpu.backends.tpu import (
        _prepare_query_arrays,
        build_sharded_db,
        make_level_template,
    )
    from image_analogies_tpu.ops import color
    from image_analogies_tpu.ops.features import spec_for_level
    from image_analogies_tpu.parallel.step import multichip_level_step

    from image_analogies_tpu.models.analogy import _prep_planes

    a, ap, b = make_pair(24, 24, seed=21)
    params = AnalogyParams(levels=1, kappa=3.0, backend="tpu",
                           strategy="wavefront")
    solo = create_image_analogy(a, ap, b, params)

    # the same remapped planes the solo run synthesized from
    a_src, b_src, a_filt, _, _ = _prep_planes(a, ap, b, params)
    spec = spec_for_level(params, 0, 1, 1)
    job = LevelJob(level=0, spec=spec,
                   kappa_mult=params.kappa_factor(0) ** 2,
                   a_src=a_src, a_filt=a_filt, b_src=b_src)
    mesh = make_mesh(db_shards=4)
    to_j = lambda x: None if x is None else jnp.asarray(x, jnp.float32)
    template = make_level_template(params, job, "wavefront")
    dbp, dbnp, afp, wk, shift, dbl = build_sharded_db(
        spec, to_j(job.a_src), to_j(job.a_filt), None, None, None,
        template.rowsafe, mesh, True, 1, packed=True)
    template = dataclasses.replace(template, feat_mean=shift)
    static_q = _prepare_query_arrays(spec, to_j(job.b_src), None, None,
                                     None)
    bp, s, _ = multichip_level_step(
        mesh, static_q[None], dbp, dbnp, afp, template, job.kappa_mult,
        force_xla=True, wk_shard=wk,
        packed_interpret=True, dbl_shard=dbl if fused else None)
    s_mesh = np.asarray(s[0]).reshape(24, 24)
    # the packed score formula rounds differently than the solo XLA score
    # (qc.dbc - ||dbc||^2/2 vs ||db||^2 - 2 q.db), so near-tied rows of this
    # posterized data may legally resolve to different picks, which then
    # cascade; the check is tie-aware: the FIRST scan-order divergence must
    # be a genuine fp-band tie of the anchor decision (everything after is
    # its deterministic consequence — the same argument utils/parity.py
    # makes for oracle parity)
    mism = np.nonzero(s_mesh.reshape(-1) != solo.source_map.reshape(-1))[0]
    if mism.size:
        from image_analogies_tpu.ops.features import build_features_np

        db_rows = build_features_np(spec, a_src, a_filt, None, None)
        # scan-order-first mismatch (wavefront order: t = j + 3*i)
        ii, jj = mism // 24, mism % 24
        q0 = mism[np.argmin(jj + 3 * ii)]
        p_mesh = int(s_mesh.reshape(-1)[q0])
        p_solo = int(solo.source_map.reshape(-1)[q0])
        # both runs saw the same context at the first divergence: re-score
        # both picks against the solo run's query vector
        from image_analogies_tpu.ops.features import fine_gather_maps

        flat_idx, _, written = fine_gather_maps(24, 24, spec.fine_size)
        fsl = spec.fine_filt_slice
        qv = build_features_np(spec, b_src, None, None, None)[q0].copy()
        qv[fsl] = (solo.bp_y.reshape(-1)[flat_idx[q0]] * written[q0]
                   * spec.sqrt_weights()[fsl])
        d = ((db_rows[[p_mesh, p_solo]].astype(np.float64)
              - qv.astype(np.float64)) ** 2).sum(1)
        scale = (qv.astype(np.float64) ** 2).sum() + max(
            (db_rows[p_mesh].astype(np.float64) ** 2).sum(),
            (db_rows[p_solo].astype(np.float64) ** 2).sum())
        assert abs(d[0] - d[1]) <= 2e-6 * scale, (
            f"first divergence at {q0} is not a tie: {d}")
