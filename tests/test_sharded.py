"""Sharded patch-DB argmin on the 8-device virtual CPU mesh (SURVEY.md §4.5).

Exercises the `lax.pmin`+index all-reduce logic without a pod: conftest forces
XLA_FLAGS=--xla_force_host_platform_device_count=8.
"""

import os
import sys

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from image_analogies_tpu.config import AnalogyParams
from image_analogies_tpu.models.analogy import create_image_analogy
from image_analogies_tpu.ops.pallas_match import xla_argmin_l2
from image_analogies_tpu.parallel.mesh import make_mesh
from image_analogies_tpu.parallel.sharded_match import (
    make_sharded_argmin,
    shard_level_db,
)
from image_analogies_tpu.utils.ssim import ssim
from tests.conftest import make_pair


def test_mesh_shape():
    assert jax.device_count() == 8, "conftest must provide 8 virtual devices"
    mesh = make_mesh(db_shards=4, data_shards=2)
    assert mesh.shape == {"data": 2, "db": 4}
    with pytest.raises(ValueError):
        make_mesh(db_shards=16)


@pytest.mark.parametrize("shards", [1, 2, 4, 8])
@pytest.mark.parametrize("n", [64, 100])  # 100: padding rows in play
def test_sharded_argmin_matches_single_device(shards, n, rng):
    f, m = 40, 16
    db = jnp.asarray(rng.standard_normal((n, f)), jnp.float32)
    dbn = jnp.sum(db * db, axis=1)
    q = jnp.asarray(rng.standard_normal((m, f)), jnp.float32)

    ref_idx, ref_d = xla_argmin_l2(q, db, dbn)

    mesh = make_mesh(db_shards=shards)
    db_sh, dbn_sh, _ = shard_level_db(db, dbn, jnp.zeros((n,)), mesh)
    fn = make_sharded_argmin(mesh, force_xla=True)
    idx, d = fn(q, db_sh, dbn_sh)

    np.testing.assert_allclose(np.asarray(d), np.asarray(ref_d), atol=1e-3)
    # indices agree except on fp ties; where they differ, distances must tie
    ii, ri = np.asarray(idx), np.asarray(ref_idx)
    diff = ii != ri
    if diff.any():
        np.testing.assert_allclose(np.asarray(d)[diff],
                                   np.asarray(ref_d)[diff], atol=1e-3)


def test_sharded_argmin_tie_break_lowest_index(rng):
    """Duplicate rows across shards: the LOWEST global index must win,
    matching the single-chip kernel's tie-break."""
    f = 8
    row = rng.standard_normal(f).astype(np.float32)
    db = np.tile(row, (16, 1)).astype(np.float32)  # all rows identical
    dbn = jnp.sum(jnp.asarray(db) ** 2, axis=1)
    q = jnp.asarray(row[None, :] + 0.01)
    mesh = make_mesh(db_shards=4)
    db_sh, dbn_sh, _ = shard_level_db(jnp.asarray(db), dbn,
                                      jnp.zeros((16,)), mesh)
    fn = make_sharded_argmin(mesh, force_xla=True)
    idx, _ = fn(q, db_sh, dbn_sh)
    assert int(idx[0]) == 0


def test_sharded_build_drops_per_chip_db_copies(rng):
    """The honest sharded-memory story (round-1 VERDICT weak item 3): with
    db_shards > 1, the per-chip full-DB arrays must be 1-row placeholders —
    rows are read only through the sharded arrays + psum lookups."""
    from image_analogies_tpu.backends.base import LevelJob
    from image_analogies_tpu.backends.tpu import TpuMatcher
    from image_analogies_tpu.ops.features import spec_for_level

    a, ap, b = make_pair(24, 24, seed=1)
    params = AnalogyParams(levels=1, backend="tpu", strategy="wavefront",
                           db_shards=4)
    from image_analogies_tpu.ops import color

    spec = spec_for_level(params, 0, 1, 1)
    job = LevelJob(level=0, spec=spec, kappa_mult=4.0,
                   a_src=color.luminance(a), a_filt=color.luminance(ap),
                   b_src=color.luminance(b))
    db = TpuMatcher(params).build_features(job)
    assert db.mesh is not None and db.mesh.shape["db"] == 4
    for name in ("db", "db_rowsafe"):
        assert getattr(db, name).shape[0] == 1, name  # placeholder, not Na
    assert db.a_filt_flat.shape[0] == 1
    assert db.db_sharded is not None and db.afilt_sharded is not None
    assert db.db_sharded.shape[0] >= 24 * 24
    # and the level still synthesizes correctly through the mesh step
    bp, s, st = TpuMatcher(params).synthesize_level(db, job)
    assert bp.shape == (24, 24) and s.max() < 24 * 24


def test_end_to_end_sharded_matches_unsharded(rng):
    """db_shards=4 on the virtual mesh must reproduce the single-device
    batched output exactly (same candidates, same tie-breaks)."""
    a, ap, b = make_pair(20, 20, seed=7)
    p1 = AnalogyParams(levels=2, kappa=2.0, backend="tpu",
                       strategy="batched", db_shards=1)
    p4 = p1.replace(db_shards=4)
    r1 = create_image_analogy(a, ap, b, p1)
    r4 = create_image_analogy(a, ap, b, p4)
    sv = ssim(r1.bp_y, r4.bp_y, data_range=1.0)
    assert sv >= 0.99, f"sharded-vs-unsharded SSIM {sv}"
    agree = (r1.source_map == r4.source_map).mean()
    assert agree >= 0.95, f"source-map agreement {agree}"


def test_distributed_initialize_noop_and_plumbing(monkeypatch):
    """SURVEY.md §5.8: single-process runs skip jax.distributed entirely;
    configured runs pass coordinates through (initialize itself is mocked —
    a real multi-host handshake needs actual hosts)."""
    from image_analogies_tpu.parallel import distributed

    monkeypatch.delenv("JAX_COORDINATOR_ADDRESS", raising=False)
    monkeypatch.delenv("JAX_NUM_PROCESSES", raising=False)
    assert distributed.initialize_distributed() is False  # no-op path

    calls = {}
    monkeypatch.setattr(jax.distributed, "initialize",
                        lambda **kw: calls.update(kw))
    assert distributed.initialize_distributed("h0:1234", 2, 1) is True
    assert calls == {"coordinator_address": "h0:1234",
                     "num_processes": 2, "process_id": 1}

    calls.clear()
    monkeypatch.setenv("JAX_COORDINATOR_ADDRESS", "h9:99")
    monkeypatch.setenv("JAX_NUM_PROCESSES", "4")
    monkeypatch.setenv("JAX_PROCESS_ID", "3")
    assert distributed.initialize_distributed() is True
    assert calls["num_processes"] == 4 and calls["process_id"] == 3


@pytest.mark.parametrize("shards", [2, 4, 8])
def test_ring_argmin_matches_allreduce(shards, rng):
    """Ring-rotating query tiles (SURVEY.md §5.7's ring-attention analogue)
    must produce exactly the all-reduce variant's picks, including the
    lowest-global-index tie-break."""
    from image_analogies_tpu.parallel.sharded_match import make_ring_argmin

    n, f, m = 96, 40, 16  # m divides every shard count
    db = jnp.asarray(rng.standard_normal((n, f)), jnp.float32)
    q = jnp.asarray(rng.standard_normal((m, f)), jnp.float32)
    # plant cross-shard duplicates of query 0 -> exact tie, lowest must win
    db = db.at[5].set(q[0]).at[n - 3].set(q[0])
    dbn = jnp.sum(db * db, axis=1)

    mesh = make_mesh(db_shards=shards)
    db_sh, dbn_sh, _ = shard_level_db(db, dbn, jnp.zeros((n,)), mesh)
    ref = make_sharded_argmin(mesh, force_xla=True)
    ring = make_ring_argmin(mesh, force_xla=True)
    ri, rd = ref(q, db_sh, dbn_sh)
    gi, gd = ring(q, db_sh, dbn_sh)
    np.testing.assert_array_equal(np.asarray(gi), np.asarray(ri))
    np.testing.assert_allclose(np.asarray(gd), np.asarray(rd), atol=1e-4)
    assert int(gi[0]) == 5  # tie broken to the lowest global index


@pytest.mark.slow
def test_two_process_distributed_smoke():
    """Round-3 VERDICT item 5: exercise parallel/distributed.py UN-MOCKED.

    Two localhost CPU processes (one device each) perform the real
    jax.distributed coordination handshake, lay the db_shards=2 mesh
    across the PROCESS boundary, and run a tiny wavefront analogy whose
    collectives (min+argmin all-reduce, psum row-gathers) ride gloo;
    process 0 asserts the sharded output equals the serial one bit-exactly
    (tests/distributed_worker.py)."""
    import socket
    import subprocess

    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
    worker = os.path.join(os.path.dirname(__file__),
                          "distributed_worker.py")
    env = {k: v for k, v in os.environ.items()
           if k not in ("JAX_PLATFORMS", "XLA_FLAGS")}
    procs = [subprocess.Popen(
        [sys.executable, worker, str(port), str(i)],
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
        env=env) for i in range(2)]
    outs = []
    try:
        for p in procs:
            out, _ = p.communicate(timeout=600)
            outs.append(out)
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
    for i, (p, out) in enumerate(zip(procs, outs)):
        # ONLY the worker's explicit init-failure sentinel skips; a crash
        # whose traceback merely mentions gloo is a real regression in the
        # collectives path and must fail (review round 3)
        if "DISTRIBUTED_SMOKE_UNSUPPORTED" in out:
            pytest.skip(f"distributed runtime unavailable: {out[-400:]}")
        assert p.returncode == 0, f"worker {i} failed:\n{out[-4000:]}"
        assert "DISTRIBUTED_SMOKE_OK" in out, out[-4000:]
