"""obs/live + obs/slo (ISSUE 6): streaming telemetry plane.

Locked here:

- golden Prometheus text exposition — stable metric names, HELP/TYPE
  pairs, cumulative base-2 histogram buckets; no NaN/Inf ever emitted,
  including empty and single-sample histograms;
- the disabled snapshot path allocates nothing (tracemalloc-asserted)
  and never touches a registry (monkeypatch-proven, chaos pattern);
- serve front end: GET /metrics is valid exposition carrying
  serve.queue_depth + serve.breaker.state + a bucketed histogram, and
  GET /healthz reports breaker state + worker liveness — including
  while the breaker is OPEN under a chaos serve.dispatch drill;
- request-id propagation: every span/record of one served request
  carries the same id, and the trace export chains admit -> dispatch;
- SLO burn-rate math (fake clock), slo.* gauges, `ia report` section;
- `ia bench --check` sentry: real trajectory passes, injected
  regression fails, `--dry-run` smoke rides tier-1;
- grep locks: obs/live.py + obs/slo.py have no module-scope jax.
"""

import json
import os
import re
import tracemalloc
import urllib.request

import pytest

from image_analogies_tpu.config import AnalogyParams
from image_analogies_tpu.obs import live as obs_live
from image_analogies_tpu.obs import metrics as obs_metrics
from image_analogies_tpu.obs import trace as obs_trace
from image_analogies_tpu.obs.slo import SloTracker
from tests.conftest import make_pair


def _params(**kw):
    kw.setdefault("levels", 2)
    kw.setdefault("backend", "cpu")
    return AnalogyParams(**kw)


# ------------------------------------------------ exposition rendering


def test_prometheus_golden_exposition():
    """Byte-exact golden: names sanitized under the ia_ prefix, one
    HELP/TYPE pair per metric (HELP carries the dotted registry name),
    counters get _total, histogram buckets are cumulative with 2^k
    edges + +Inf + _sum + _count, sections and names sorted."""
    reg = obs_metrics.MetricsRegistry()
    reg.inc("serve.accepted", 3)
    reg.inc("compile.count", 1)
    reg.set_gauge("serve.queue_depth", 2)
    reg.set_gauge("serve.breaker.state.cpu", 0)
    reg.observe("serve.latency_ms", 0.5)   # k=0 bucket (le=1)
    reg.observe("serve.latency_ms", 3.0)   # k=2 bucket (le=4)
    reg.observe("serve.latency_ms", 3.5)
    golden = "\n".join([
        "# HELP ia_compile_count_total counter compile.count",
        "# TYPE ia_compile_count_total counter",
        "ia_compile_count_total 1",
        "# HELP ia_serve_accepted_total counter serve.accepted",
        "# TYPE ia_serve_accepted_total counter",
        "ia_serve_accepted_total 3",
        "# HELP ia_serve_breaker_state_cpu gauge serve.breaker.state.cpu",
        "# TYPE ia_serve_breaker_state_cpu gauge",
        "ia_serve_breaker_state_cpu 0",
        "# HELP ia_serve_queue_depth gauge serve.queue_depth",
        "# TYPE ia_serve_queue_depth gauge",
        "ia_serve_queue_depth 2",
        "# HELP ia_serve_latency_ms histogram serve.latency_ms",
        "# TYPE ia_serve_latency_ms histogram",
        'ia_serve_latency_ms_bucket{le="1"} 1',
        'ia_serve_latency_ms_bucket{le="4"} 3',
        'ia_serve_latency_ms_bucket{le="+Inf"} 3',
        "ia_serve_latency_ms_sum 7",
        "ia_serve_latency_ms_count 3",
        # the tail-quantile sketch rides next to the base-2 histogram on
        # latency series, under its own _q summary family; the quantile
        # values are DDSketch bucket midpoints (exact goldens: relative
        # error <= 0.01 of 3.0 and 3.5, deterministic by construction)
        "# HELP ia_serve_latency_ms_q quantile sketch serve.latency_ms "
        "(relative error 0.01)",
        "# TYPE ia_serve_latency_ms_q summary",
        'ia_serve_latency_ms_q{quantile="0.5"} 2.9742334234767016',
        'ia_serve_latency_ms_q{quantile="0.9"} 3.4903138713917436',
        'ia_serve_latency_ms_q{quantile="0.99"} 3.4903138713917436',
        'ia_serve_latency_ms_q{quantile="0.999"} 3.4903138713917436',
        'ia_serve_latency_ms_q{quantile="0.9999"} 3.4903138713917436',
        "ia_serve_latency_ms_q_sum 7",
        "ia_serve_latency_ms_q_count 3",
    ]) + "\n"
    assert obs_live.render_prometheus(reg.snapshot()) == golden


def test_prometheus_empty_and_single_sample_histograms():
    """Satellite: histogram export is well-defined on empty and
    single-sample histograms — no exception, no NaN, cumulative buckets
    still monotone."""
    h = obs_metrics.Histogram()
    assert h.percentile(50) == 0.0          # empty: defined, not NaN
    assert h.percentile(99) == 0.0
    assert h.cumulative_buckets() == []
    empty_summary = h.summary()
    assert empty_summary["count"] == 0

    h.observe(7.0)                          # single sample
    assert h.percentile(0) == 7.0           # clamped to observed max
    assert h.percentile(50) == 7.0
    assert h.percentile(100) == 7.0
    assert h.cumulative_buckets() == [(8.0, 1)]

    reg = obs_metrics.MetricsRegistry()
    reg.observe("one.sample", 7.0)
    snap = reg.snapshot()
    snap["histograms"]["empty.hist"] = empty_summary
    text = obs_live.render_prometheus(snap)
    assert "nan" not in text.lower() and "inf " not in text.lower()
    assert 'ia_empty_hist_bucket{le="+Inf"} 0' in text
    assert "ia_empty_hist_count 0" in text
    assert 'ia_one_sample_bucket{le="8"} 1' in text


def test_prometheus_name_sanitization_and_none_snapshot():
    assert obs_live.prom_name("serve.breaker.state.cpu") == \
        "ia_serve_breaker_state_cpu"
    assert obs_live.prom_name("hbm.peak_bytes.d0") == "ia_hbm_peak_bytes_d0"
    # None snapshot (obs disabled) renders a comment, not an error
    text = obs_live.render_prometheus(None)
    assert text.startswith("#") and text.endswith("\n")
    # every emitted metric name is exposition-legal
    reg = obs_metrics.MetricsRegistry()
    reg.inc("weird-name.with:chars!")
    legal = re.compile(r"^[a-zA-Z_:][a-zA-Z0-9_:]*(_total)?$")
    for line in obs_live.render_prometheus(reg.snapshot()).splitlines():
        if line.startswith("#"):
            continue
        assert legal.match(line.split("{")[0].split(" ")[0])


# ------------------------------------------------ disabled path cost


def test_disabled_snapshot_path_allocates_nothing(monkeypatch):
    """Acceptance: with obs disabled, the snapshot path is one global
    read returning None — zero allocations attributable to obs/, and
    the registry is provably never touched (chaos disarm pattern:
    poison the expensive call and prove it unreached)."""
    assert obs_metrics.registry() is None

    # monkeypatch-proven inert: if the disabled path ever reached a
    # registry snapshot it would raise
    monkeypatch.setattr(obs_metrics.MetricsRegistry, "snapshot",
                        lambda self: (_ for _ in ()).throw(
                            AssertionError("registry touched while off")))
    assert obs_live.snapshot_or_none() is None

    tracemalloc.start()
    try:
        for _ in range(1000):
            obs_live.snapshot_or_none()
        snap = tracemalloc.take_snapshot()
    finally:
        tracemalloc.stop()
    obs_allocs = [t for t in snap.traces
                  if any("image_analogies_tpu/obs/" in fr.filename
                         for fr in t.traceback)]
    # Same steady-state budget as the other disarmed-plane locks: the
    # interpreter's frame free list can attribute ~100 B of realloc to
    # the call site depending on what ran earlier in the process, so an
    # exact-zero assertion is flaky across test orderings.  The
    # monkeypatch poison above is the real "never touched" proof.
    assert len(obs_allocs) <= 8
    assert sum(t.size for t in obs_allocs) <= 1024


# ------------------------------------------------ exposition server


def test_live_http_server_metrics_and_healthz():
    httpd = obs_live.start_http_server(
        0,
        snapshot_fn=lambda: {"counters": {"x.y": 1}, "gauges": {},
                             "histograms": {}},
        health_fn=lambda: {"ok": True, "who": "test"})
    try:
        port = httpd.server_address[1]
        with urllib.request.urlopen(
                f"http://127.0.0.1:{port}/metrics") as r:
            assert r.headers["Content-Type"] == obs_live.CONTENT_TYPE
            assert "ia_x_y_total 1" in r.read().decode()
        with urllib.request.urlopen(
                f"http://127.0.0.1:{port}/healthz") as r:
            assert json.load(r) == {"ok": True, "who": "test"}
        bad = urllib.request.Request(f"http://127.0.0.1:{port}/nope")
        with pytest.raises(urllib.error.HTTPError):
            urllib.request.urlopen(bad)
    finally:
        obs_live.stop_http_server(httpd)


# ------------------------------------------------ serve front end


def _serve_cfg(**kw):
    from image_analogies_tpu.serve import ServeConfig

    kw.setdefault("params", _params())
    kw.setdefault("workers", 1)
    kw.setdefault("max_batch", 1)
    kw.setdefault("batch_window_ms", 0.0)
    return ServeConfig(**kw)


def test_serve_http_metrics_and_healthz_schema():
    """Acceptance: during a served run, GET /metrics is valid Prometheus
    exposition carrying serve.queue_depth, serve.breaker.state, and a
    bucketed histogram; GET /healthz reports breaker + worker liveness
    + SLO."""
    import threading

    from image_analogies_tpu.serve import Server
    from image_analogies_tpu.serve.http import serve_http

    a, ap, b = make_pair(10, 10, seed=30)
    with Server(_serve_cfg(default_deadline_s=60.0)) as srv:
        assert srv.request(a, ap, b, timeout=120).status == "ok"
        httpd = serve_http(srv, 0)
        t = threading.Thread(target=httpd.serve_forever, daemon=True)
        t.start()
        try:
            port = httpd.server_address[1]
            with urllib.request.urlopen(
                    f"http://127.0.0.1:{port}/metrics") as r:
                assert r.headers["Content-Type"] == obs_live.CONTENT_TYPE
                text = r.read().decode()
            with urllib.request.urlopen(
                    f"http://127.0.0.1:{port}/healthz") as r:
                hz = json.load(r)
        finally:
            httpd.shutdown()
    # exposition content (dotted names ride in HELP lines)
    assert "serve.queue_depth" in text
    assert "serve.breaker.state" in text
    assert re.search(r'_bucket\{le="[^"]+"\} \d+', text)
    assert "nan" not in text.lower()
    # healthz schema
    assert hz["ok"] is True and hz["accepting"] is True
    assert hz["queue_depth"] == 0 and hz["inflight"] == 0
    assert hz["breakers"] == {"cpu": "closed"}
    assert hz["workers"]["total"] == 1 and hz["workers"]["alive"] == 1
    assert all(hz["workers"]["threads"].values())
    assert hz["uptime_s"] >= 0
    assert hz["slo"]["target"] == pytest.approx(0.99)
    assert hz["slo"]["deadlined"] == 1 and hz["slo"]["violations"] == 0
    assert {"devcache_bytes", "hbm_peak_bytes"} <= set(hz)


def test_healthz_breaker_open_under_chaos_dispatch_drill():
    """Satellite: /healthz + /metrics show the breaker OPEN while a
    chaos serve.dispatch drill is mid-flight — the exact brownout view
    an operator (or the future router) routes around."""
    from image_analogies_tpu.chaos import inject
    from image_analogies_tpu.chaos.plan import ChaosPlan, SiteRule
    from image_analogies_tpu.serve import Rejected, Server

    a, ap, b = make_pair(10, 10, seed=31)
    cfg = _serve_cfg(request_retries=0, breaker_threshold=1,
                     breaker_cooldown_s=300.0, crash_requeues=0)
    plan = ChaosPlan(seed=0, sites=(
        ("serve.dispatch", SiteRule(kind="crash", schedule=(0,))),))
    with Server(cfg) as srv:
        with inject.plan_scope(plan):
            # drill batch 0 crashes at the dispatch site; containment
            # resolves it as worker_crash
            with pytest.raises(Rejected) as ei:
                srv.request(a, ap, b, timeout=60)
            assert ei.value.reason == "worker_crash"
            # now trip the breaker (threshold=1) and read health while
            # the drill plan is still armed
            srv._pool.breaker.record_failure()
            hz = srv.health()
            assert hz["breakers"] == {"cpu": "open"}
            assert hz["workers"]["alive"] == 1  # crash was contained
            srv.refresh_gauges()
            text = obs_live.render_prometheus(obs_live.snapshot_or_none())
            assert "ia_serve_breaker_state_cpu 2" in text  # open=2
            # admission sheds one hop early while open
            with pytest.raises(Rejected) as ei2:
                srv.submit(a, ap, b)
            assert ei2.value.reason == "breaker_open"


# ------------------------------------------------ request-id chain


def test_request_id_propagates_through_all_spans(tmp_path):
    """Acceptance: every span/record of one served request — admit,
    queue, dispatch, the engine's own level spans — carries the same
    request id, and the trace export chains them on the serve track."""
    from image_analogies_tpu.obs import export as obs_export
    from image_analogies_tpu.serve import Server

    log = str(tmp_path / "req.jsonl")
    a, ap, b = make_pair(10, 10, seed=32)
    cfg = _serve_cfg(params=_params(log_path=log))
    with Server(cfg) as srv:
        assert srv.request(a, ap, b, timeout=120).status == "ok"

    recs = [json.loads(line) for line in open(log)]
    chain = [r for r in recs if r.get("request") == 1]
    events = {r.get("event") for r in chain}
    assert "serve_admit" in events          # admission hop
    assert "serve_request" in events        # completion record
    span_names = {r.get("name") for r in chain if r.get("event") == "span"}
    assert "serve_dispatch" in span_names   # dispatch hop
    assert "level" in span_names            # ENGINE spans inherit the id
    # no other request id appears in the chain
    assert {r.get("request") for r in chain} == {1}

    out = str(tmp_path / "trace.json")
    obs_export.export_trace(log, out)
    tr = json.load(open(out))
    serve_track = [e for e in tr["traceEvents"]
                   if e.get("tid") == obs_export.SERVE_TID
                   and e.get("ph") != "M"]
    names = [e["name"] for e in serve_track]
    assert "admit r1" in names              # instant at admission
    assert any(n.startswith("req 1 ") for n in names)  # lifetime interval
    assert "serve_dispatch" in names


def test_request_context_nests_and_restores():
    with obs_trace.run_scope(_params(metrics=True)):
        assert obs_trace.context_attrs() is None
        with obs_trace.request_context(request=7):
            assert obs_trace.context_attrs() == {"request": 7}
            with obs_trace.request_context(hop="inner"):
                assert obs_trace.context_attrs() == {"request": 7,
                                                     "hop": "inner"}
            assert obs_trace.context_attrs() == {"request": 7}
        assert obs_trace.context_attrs() is None


# ------------------------------------------------ SLO tracking


def test_slo_burn_rate_math_fake_clock():
    now = {"t": 1000.0}
    slo = SloTracker(target=0.9, fast_window_s=10.0, slow_window_s=100.0,
                     clock=lambda: now["t"])
    # 10 outcomes in the fast window, 2 violations: violation rate 0.2,
    # budget 0.1 -> fast burn 2.0
    for i in range(10):
        slo.record(i not in (3, 7))
    s = slo.snapshot()
    assert s["deadlined"] == 10 and s["violations"] == 2
    assert s["burn_rate_fast"] == pytest.approx(2.0)
    assert s["burn_rate_slow"] == pytest.approx(2.0)
    assert s["attainment"] == pytest.approx(0.8)
    # advance past the fast window: fast burn decays to the new traffic,
    # slow window still remembers
    now["t"] += 50.0
    for _ in range(10):
        slo.record(True)
    s = slo.snapshot()
    assert s["burn_rate_fast"] == 0.0
    assert s["burn_rate_slow"] == pytest.approx((2 / 20) / 0.1)
    # advance past the slow window: everything pruned
    now["t"] += 200.0
    assert slo.snapshot()["burn_rate_slow"] == 0.0
    assert slo.snapshot()["attainment"] == 1.0  # no data -> not burning


def test_slo_validation_and_gauges_and_report(tmp_path):
    with pytest.raises(ValueError):
        SloTracker(target=1.0)
    with pytest.raises(ValueError):
        SloTracker(target=0.99, fast_window_s=60.0, slow_window_s=1.0)

    log = str(tmp_path / "slo.jsonl")
    with obs_trace.run_scope(_params(metrics=True, log_path=log)):
        slo = SloTracker(target=0.95)
        slo.record(True)
        slo.record(False)
        snap = obs_metrics.snapshot()
    assert snap["counters"]["slo.deadlined"] == 2
    assert snap["counters"]["slo.violations"] == 1
    assert snap["gauges"]["slo.target"] == pytest.approx(0.95)
    assert snap["gauges"]["slo.burn_rate.fast"] == pytest.approx(10.0)
    assert snap["gauges"]["slo.attainment"] == pytest.approx(0.5)

    from image_analogies_tpu.obs import report as obs_report

    an = json.loads(obs_report.report_json(log))["runs"][0]
    assert an["slo"]["deadlined"] == 2 and an["slo"]["violations"] == 1
    assert an["slo"]["target"] == pytest.approx(0.95)
    assert an["slo"]["attainment"] == pytest.approx(0.5)
    assert "slo:" in obs_report.report(log)


def test_serve_records_slo_outcomes():
    """Worker path feeds the tracker: met deadlines count, undeadlined
    traffic does not."""
    from image_analogies_tpu.serve import Server

    a, ap, b = make_pair(10, 10, seed=33)
    with Server(_serve_cfg()) as srv:
        assert srv.request(a, ap, b, timeout=120).status == "ok"  # no dl
        assert srv.request(a, ap, b, deadline_s=60.0,
                           timeout=120).status == "ok"
        s = srv.slo.snapshot()
    assert s["deadlined"] == 1          # only the deadlined request
    assert s["violations"] == 0


# ------------------------------------------------ ia metrics CLI


def test_cli_metrics_renders_log_snapshot(tmp_path, capsys):
    from image_analogies_tpu import cli

    log = str(tmp_path / "run.jsonl")
    with obs_trace.run_scope(_params(metrics=True, log_path=log)):
        obs_metrics.inc("serve.accepted", 4)
        obs_metrics.observe("serve.latency_ms", 12.0)
    assert cli.main(["metrics", log]) == 0
    out = capsys.readouterr().out
    assert "ia_serve_accepted_total 4" in out
    assert 'ia_serve_latency_ms_bucket{le="+Inf"} 1' in out
    # missing log -> usage error, no traceback
    assert cli.main(["metrics", str(tmp_path / "absent.jsonl")]) == 2


def test_metrics_sidecar_server_rereads_log(tmp_path):
    log = str(tmp_path / "run.jsonl")
    with obs_trace.run_scope(_params(metrics=True, log_path=log)):
        obs_metrics.inc("runs.count", 1)
    httpd = obs_live.start_http_server(
        0, snapshot_fn=lambda: obs_live.snapshot_from_log(log),
        health_fn=lambda: obs_live.health_from_log(log))
    try:
        port = httpd.server_address[1]
        url = f"http://127.0.0.1:{port}"
        text = urllib.request.urlopen(f"{url}/metrics").read().decode()
        assert "ia_runs_count_total 1" in text
        hz = json.load(urllib.request.urlopen(f"{url}/healthz"))
        assert hz["runs"] == 1 and hz["last_run_complete"] is True
        # a second run appends to the log; the next scrape sees it
        with obs_trace.run_scope(_params(metrics=True, log_path=log)):
            obs_metrics.inc("runs.count", 2)
        text = urllib.request.urlopen(f"{url}/metrics").read().decode()
        assert "ia_runs_count_total 2" in text
        assert json.load(urllib.request.urlopen(
            f"{url}/healthz"))["runs"] == 2
    finally:
        obs_live.stop_http_server(httpd)


# ------------------------------------------------ bench sentry


def test_bench_check_real_trajectory_passes_and_injected_fails(capsys):
    """Acceptance + tier-1 smoke: the sentry parses every BENCH_r*.json
    in the repo (no problems), passes the real trajectory, and fails an
    injected synthetic regression."""
    from image_analogies_tpu import cli

    assert cli.main(["bench", "--check", "--dry-run"]) == 0
    verdict = json.loads(capsys.readouterr().out)
    assert verdict["ok"] is True
    assert verdict["problems"] == []    # the archive formats still parse
    assert verdict["points"] >= 5

    # injected regression: way past the floor -> exit 1
    bad = (verdict.get("floor") or verdict["candidate"]) * 10
    assert cli.main(["bench", "--check", "--value", str(bad)]) == 1
    assert json.loads(capsys.readouterr().out)["ok"] is False


def test_bench_sentry_groups_by_metric_key(tmp_path):
    """r01 measured 256^2, later rounds 1024^2 — points only gate
    against same-metric history (a config switch is not a regression)."""
    import importlib.util

    spec = importlib.util.spec_from_file_location(
        "bench_probe", os.path.join(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))), "bench.py"))
    bench = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(bench)

    def doc(value, metric):
        return {"parsed": {"value": value, "metric": metric}, "tail": ""}

    (tmp_path / "BENCH_r01.json").write_text(
        json.dumps(doc(1.0, "256x256 oil config")))
    (tmp_path / "BENCH_r02.json").write_text(
        json.dumps(doc(20.0, "1024x1024 north star")))
    (tmp_path / "BENCH_r03.json").write_text(
        json.dumps(doc(15.0, "1024x1024 north star")))
    traj = bench.load_trajectory(str(tmp_path))
    # latest (15.0, 1024^2) gates only vs 20.0, never vs r01's 1.0
    verdict = bench.check_regression(traj)
    assert verdict["ok"] is True and verdict["floor"] == 20.0
    # truncated-tail regex fallback still yields a point
    (tmp_path / "BENCH_r04.json").write_text(json.dumps({
        "parsed": None,
        "tail": 'garbage {"north_star_1024_seed7": {"tpu_s": 14.5, '}))
    traj = bench.load_trajectory(str(tmp_path))
    assert traj["points"][-1] == {"value": 14.5, "metric_key": "1024x1024",
                                  "source": "tail_regex", "round": 4,
                                  "file": "BENCH_r04.json"}
    # fresh value gates against the min of same-metric points
    assert bench.check_regression(traj, fresh_value=30.0)["ok"] is False
    assert bench.check_regression(traj, fresh_value=14.0)["ok"] is True


def test_bench_check_empty_trajectory_is_no_floor_pass(tmp_path, capsys):
    """A fresh value whose metric has no archived floor (new metric, or
    an empty archive) passes explicitly as 'no floor, recorded only'
    instead of crashing or gating against an unrelated metric's floor;
    the dry-run path (nothing to check at all) still fails."""
    import importlib.util

    from image_analogies_tpu import cli

    spec = importlib.util.spec_from_file_location(
        "bench_probe2", os.path.join(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))), "bench.py"))
    bench = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(bench)

    # empty archive + fresh value: explicit recorded-only pass
    traj = bench.load_trajectory(str(tmp_path))
    verdict = bench.check_regression(traj, fresh_value=5.0)
    assert verdict["ok"] is True
    assert verdict["reason"] == "no_floor_recorded_only"
    assert verdict["no_floor"] == 1

    # empty archive WITHOUT a fresh value: still an explicit failure
    assert bench.check_regression(traj)["ok"] is False
    assert bench.check_regression(traj)["reason"] == "no_trajectory_points"

    # archive exists, but the fresh value names a BRAND-NEW metric:
    # no-floor pass under its own key, never the other metric's floor
    (tmp_path / "BENCH_r01.json").write_text(json.dumps(
        {"parsed": {"value": 1.0, "metric": "1024x1024 north star"},
         "tail": ""}))
    traj = bench.load_trajectory(str(tmp_path))
    verdict = bench.check_regression(traj, fresh_value=500.0,
                                     fresh_key="fleet_selftest_s")
    assert verdict["ok"] is True
    assert verdict["reason"] == "no_floor_recorded_only"
    assert verdict["metric_key"] == "fleet_selftest_s"
    # ... while a MATCHING fresh_key still gates against the floor
    verdict = bench.check_regression(traj, fresh_value=500.0,
                                     fresh_key="1024x1024")
    assert verdict["ok"] is False and verdict["floor"] == 1.0

    # CLI plumbing: --metric-key rides --value end to end
    rc = cli.main(["bench", "--check", "--value", "5.0",
                   "--metric-key", "brand_new_metric",
                   "--dir", str(tmp_path)])
    assert rc == 0
    out = json.loads(capsys.readouterr().out)
    assert out["reason"] == "no_floor_recorded_only"
    assert out["metric_key"] == "brand_new_metric"


# ------------------------------------------------ grep locks


def test_live_and_slo_modules_are_jax_free():
    """Satellite lock: the telemetry plane must import (and serve
    scrapes) on any host without pulling jax — no module-scope jax
    import, no direct jit/pjit/pmap calls."""
    import image_analogies_tpu.obs as obs_pkg

    root = os.path.dirname(obs_pkg.__file__)
    forbidden = re.compile(r"\bjax\.jit\s*\(|\bpjit\s*\(|\bjax\.pmap\s*\(")
    toplevel_jax = re.compile(r"^(import jax|from jax)", re.MULTILINE)
    for name in ("live.py", "slo.py", "metrics.py", "fleet.py",
                 "recorder.py", "timeline.py", "ledger.py", "tenants.py",
                 "archive.py", "quantiles.py", "ceilings.py"):
        with open(os.path.join(root, name)) as f:
            src = f.read()
        assert not forbidden.findall(src), f"obs/{name} calls jit/pjit"
        assert not toplevel_jax.findall(src), (
            f"obs/{name} imports jax at module scope")


def test_registry_resolution_is_scoped_only():
    """Grep lock (PR 11 satellite): the legacy global-install surface is
    gone — no module outside obs/metrics.py may reference a module-level
    ``_REGISTRY`` or call a ``_install``-style hook.  Every call site
    resolves metrics through the ambient ObsScope, so per-worker
    isolation cannot be silently bypassed by a new global."""
    import image_analogies_tpu as pkg

    root = os.path.dirname(pkg.__file__)
    forbidden = re.compile(r"_REGISTRY\b|\b_install\s*\(|\b_uninstall\s*\(")
    scanned = set()
    for dirpath, dirnames, filenames in os.walk(root):
        dirnames[:] = [d for d in dirnames if d != "__pycache__"]
        for name in sorted(filenames):
            if not name.endswith(".py"):
                continue
            rel = os.path.relpath(os.path.join(dirpath, name), root)
            if rel == os.path.join("obs", "metrics.py"):
                continue
            scanned.add(rel)
            with open(os.path.join(dirpath, name)) as f:
                src = f.read()
            assert not forbidden.findall(src), (
                f"{rel} references the deleted global-registry install "
                "path; resolve through obs.metrics scopes instead")
    # the scan must actually have covered the obs + serve planes — a
    # package move must carry this lock with it
    assert {os.path.join("obs", "trace.py"),
            os.path.join("obs", "live.py"),
            os.path.join("obs", "recorder.py"),
            os.path.join("obs", "fleet.py"),
            os.path.join("serve", "fleet.py"),
            os.path.join("serve", "worker.py"),
            "cli.py"} <= scanned


# ------------------------------------------------ fleet federation (PR 11)


def _two_worker_snapshots():
    r0, r1 = obs_metrics.MetricsRegistry(), obs_metrics.MetricsRegistry()
    r0.inc("serve.admitted", 3)
    r1.inc("serve.admitted", 5)
    r0.inc("only.w0", 2)
    r0.set_gauge("serve.queue_depth", 1)
    r1.set_gauge("serve.queue_depth", 4)
    r0.set_gauge("hbm.peak_bytes.d0", 100)
    r1.set_gauge("hbm.peak_bytes.d0", 700)
    for v in (0.5, 3.0):
        r0.observe("serve.latency_ms", v)
    for v in (3.5, 9.0):
        r1.observe("serve.latency_ms", v)
    return {"w0": r0.snapshot(), "w1": r1.snapshot()}


def test_render_fleet_labeled_series_sum_byte_consistent():
    """Acceptance: every per-worker-labeled sample is byte-identical to
    the worker's own isolated exposition, and labeled counter samples
    sum exactly to the merged unlabeled sample."""
    from image_analogies_tpu.obs import fleet as obs_fleet

    by_worker = _two_worker_snapshots()
    text = obs_fleet.render_fleet(by_worker)

    # merged roll-up values
    assert "ia_serve_admitted_total 8" in text
    assert 'ia_serve_admitted_total{worker="w0"} 3' in text
    assert 'ia_serve_admitted_total{worker="w1"} 5' in text
    # a family only one worker has still merges (missing worker omitted)
    assert "ia_only_w0_total 2" in text
    assert 'ia_only_w0_total{worker="w1"}' not in text
    # plain gauges sum; peak watermarks take the max
    assert "ia_serve_queue_depth 5" in text
    assert "ia_hbm_peak_bytes_d0 700" in text
    # histograms merge bucketwise: counts add, cumulative stays monotone
    assert "ia_serve_latency_ms_count 4" in text
    assert 'ia_serve_latency_ms_bucket{le="4",worker="w0"} 2' in text

    # byte-consistency: each labeled sample equals the worker's own
    # render of the same family (same formatter, same value bytes)
    sample = re.compile(r'^(\S+)\{worker="(w\d)"\} (\S+)$', re.MULTILINE)
    solo = {wid: obs_live.render_prometheus(snap)
            for wid, snap in by_worker.items()}
    labeled = sample.findall(text)
    assert labeled, "no worker-labeled samples rendered"
    for pn, wid, value in labeled:
        assert f"{pn} {value}\n" in solo[wid], (
            f"{pn}{{worker={wid}}}={value} differs from {wid}'s own "
            "exposition")
    # and labeled counters sum to the merged sample exactly
    merged_admitted = re.search(r"^ia_serve_admitted_total (\S+)$", text,
                                re.MULTILINE).group(1)
    parts = [float(v) for pn, _w, v in labeled
             if pn == "ia_serve_admitted_total"]
    assert float(merged_admitted) == sum(parts) == 8.0


def test_snapshot_from_exposition_roundtrip():
    """Transport-agnostic federation: a worker's /metrics text recovers
    into a snapshot whose counters/gauges are lossless and whose
    histograms rebuild the base-2 buckets from the cumulative samples."""
    from image_analogies_tpu.obs import fleet as obs_fleet

    reg = obs_metrics.MetricsRegistry()
    reg.inc("serve.admitted", 7)
    reg.inc("router.wire_bytes", 4096)
    reg.set_gauge("serve.queue_depth", 3)
    reg.set_gauge("slo.burn_rate.fast", 2.5)
    for v in (0.5, 3.0, 3.5, 100.0):
        reg.observe("serve.latency_ms", v)
    snap = reg.snapshot()
    text = obs_live.render_prometheus(snap)

    back = obs_fleet.snapshot_from_exposition(text)
    assert back["counters"] == {"serve.admitted": 7,
                                "router.wire_bytes": 4096}
    assert back["gauges"] == {"serve.queue_depth": 3,
                              "slo.burn_rate.fast": 2.5}
    h = back["histograms"]["serve.latency_ms"]
    assert h["count"] == 4
    assert h["sum"] == pytest.approx(107.0)
    assert h["buckets"] == snap["histograms"]["serve.latency_ms"]["buckets"]
    # merging a scraped snapshot == merging the in-process snapshot
    merged = obs_fleet.merge_snapshots({"w0": snap, "w1": back})
    assert merged["counters"]["serve.admitted"] == 14
    assert merged["histograms"]["serve.latency_ms"]["count"] == 8
    # worker-labeled lines in an already-federated view are skipped
    fed = obs_fleet.render_fleet({"w0": snap})
    refed = obs_fleet.snapshot_from_exposition(fed)
    assert refed["counters"]["serve.admitted"] == 7


def test_bench_check_gates_obs_overhead(tmp_path, capsys):
    """PR 11 satellite: obs_overhead_pct rides the bench trajectory —
    extract_headline propagates it and check_regression gates it in
    absolute percentage points."""
    import importlib.util

    spec = importlib.util.spec_from_file_location(
        "ia_bench_obs_test", os.path.join(
            os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
            "bench.py"))
    bench = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(bench)

    doc = {"parsed": {"value": 7.5, "metric": "1024x1024 north star",
                      "obs_overhead_pct": 3.2, "host_gap_ms": 1.0}}
    head = bench.extract_headline(doc)
    assert head["obs_overhead_pct"] == 3.2

    trajectory = {"points": [
        {"value": 7.0, "metric_key": "1024x1024", "round": 1,
         "file": "BENCH_r01.json", "obs_overhead_pct": 2.0},
        {"value": 7.2, "metric_key": "1024x1024", "round": 2,
         "file": "BENCH_r02.json", "obs_overhead_pct": 4.0},
    ], "problems": []}
    ok = bench.check_regression(trajectory, fresh_value=7.1,
                                fresh_obs=5.0, threshold_pct=20.0)
    assert ok["ok"] and ok["obs_overhead_pct"] == 5.0
    assert ok["obs_overhead_floor"] == 2.0
    assert ok["obs_overhead_delta_pts"] == 3.0
    bad = bench.check_regression(trajectory, fresh_value=7.1,
                                 fresh_obs=30.0, threshold_pct=20.0)
    assert not bad["ok"]
    assert any("obs_overhead_pct" in p for p in bad["problems"])
    # archive self-check path reads the latest point's own overhead
    latest = bench.check_regression(trajectory, threshold_pct=20.0)
    assert latest["obs_overhead_pct"] == 4.0
    assert latest["obs_overhead_floor"] == 2.0
