"""Application modes and video (SURVEY.md §3.4-3.5, BASELINE configs 1-5)."""

import numpy as np
import pytest

from image_analogies_tpu.config import PRESETS, AnalogyParams
from image_analogies_tpu.models import modes
from image_analogies_tpu.models.video import video_analogy
from image_analogies_tpu.ops.features import spec_for_level
from tests.conftest import make_pair


@pytest.fixture
def small():
    return make_pair(16, 16, seed=11)


def _params(**kw):
    kw.setdefault("levels", 1)
    kw.setdefault("backend", "cpu")
    return AnalogyParams(**kw)


def test_artistic_filter(small):
    a, ap, b = small
    res = modes.artistic_filter(a, ap, b, _params(levels=2))
    assert res.bp.shape == b.shape


def test_texture_by_numbers_rgb_labels(rng):
    lab_a = np.zeros((16, 16, 3), np.float32)
    lab_a[:, :8, 0] = 1
    lab_a[:, 8:, 2] = 1
    tex = rng.uniform(0, 1, (16, 16, 3)).astype(np.float32)
    lab_b = lab_a[:, ::-1].copy()
    res = modes.texture_by_numbers(
        lab_a, tex, lab_b, PRESETS["texture_by_numbers"].replace(levels=1))
    assert res.bp.shape == (16, 16, 3)


def test_super_resolution(small):
    a, ap, _ = small
    res = modes.super_resolution(ap, ap, _params(patch_size=5, levels=1))
    assert res.bp.shape == ap.shape[:2]


def test_texture_synthesis_ignores_src(rng):
    tex = rng.uniform(0, 1, (16, 16)).astype(np.float32)
    res = modes.texture_synthesis(
        tex, (12, 14), PRESETS["texture_synthesis"].replace(levels=1))
    assert res.bp.shape == (12, 14)
    # every output pixel is copied verbatim from the exemplar
    assert np.isin(res.bp.ravel(), tex.ravel()).all()


def test_texture_synthesis_seed_varies_output(rng):
    """ADVICE round-1: a seed must yield varied textures from one exemplar;
    the same seed must reproduce, and pixels still come from the exemplar."""
    tex = rng.uniform(0, 1, (16, 16)).astype(np.float32)
    p = PRESETS["texture_synthesis"].replace(levels=1)
    r1 = modes.texture_synthesis(tex, (12, 12), p, seed=1)
    r1b = modes.texture_synthesis(tex, (12, 12), p, seed=1)
    r2 = modes.texture_synthesis(tex, (12, 12), p, seed=2)
    np.testing.assert_array_equal(r1.bp, r1b.bp)
    assert (r1.bp != r2.bp).any()
    assert np.isin(r1.bp.ravel(), tex.ravel()).all()


def test_source_rgb_remap_preserves_pair_relation(rng):
    """ADVICE round-1: in source_rgb mode with grayscale planes and
    remap_luminance=True, A and A' must receive the SAME affine transform
    (an affine filter A -> A' is preserved)."""
    from image_analogies_tpu.models.analogy import _prep_planes
    from image_analogies_tpu.config import AnalogyParams

    a = rng.uniform(0.2, 0.6, (12, 12)).astype(np.float32)
    ap = (0.5 * a + 0.2).astype(np.float32)  # affine filter
    b = rng.uniform(0, 1, (12, 12)).astype(np.float32)
    p = AnalogyParams(color_mode="source_rgb", remap_luminance=True)
    a_src, b_src, a_filt, _, _ = _prep_planes(a, ap, b, p)
    # the affine relation A' = 0.5 A + const must survive the remap
    resid = a_filt - 0.5 * a_src
    assert np.std(resid) < 1e-5, np.std(resid)


def test_video_two_phase_and_sequential(small):
    a, ap, _ = small
    r = np.random.default_rng(0)
    frames = [np.clip(a + 0.02 * t + 0.01 * r.standard_normal(a.shape), 0, 1)
              .astype(np.float32) for t in range(3)]
    p = _params(temporal_weight=1.0)
    res2 = video_analogy(a, ap, frames, p, scheme="two_phase")
    assert len(res2.frames) == 3
    phases = {s["phase"] for s in res2.stats}
    assert phases == {"phase1", "phase2"}
    res_seq = video_analogy(a, ap, frames, p, scheme="sequential")
    assert len(res_seq.frames) == 3
    with pytest.raises(ValueError):
        video_analogy(a, ap, frames, p, scheme="bogus")


def test_video_temporal_term_increases_frame_coherence(small):
    """With a strong temporal term, consecutive output frames of a static
    scene must be closer than without it."""
    a, ap, _ = small
    r = np.random.default_rng(1)
    frames = [np.clip(a + 0.04 * r.standard_normal(a.shape), 0, 1)
              .astype(np.float32) for _ in range(2)]
    p0 = _params(temporal_weight=0.0)
    pt = _params(temporal_weight=8.0)
    r0 = video_analogy(a, ap, frames, p0, scheme="sequential")
    rt = video_analogy(a, ap, frames, pt, scheme="sequential")
    d0 = np.abs(r0.frames_y[1] - r0.frames_y[0]).mean()
    dt = np.abs(rt.frames_y[1] - rt.frames_y[0]).mean()
    assert dt <= d0 + 1e-6, (dt, d0)


def test_video_clip_pins_tune_geometry_once(small, monkeypatch, tmp_path):
    """Satellite: a clip resolves its kernel geometry ONCE up front and
    pins it — provenance counters record exactly one consult per clip,
    so frame batches inside the clip can never diverge mid-run."""
    from image_analogies_tpu.obs import metrics as obs_metrics
    from image_analogies_tpu.obs import trace as obs_trace
    from image_analogies_tpu.tune import resolve as tune
    from image_analogies_tpu.tune import store as tune_store

    for var in ("IA_TILE_ROWS", "IA_PACKED_TILE", "IA_PACKED_VMEM"):
        monkeypatch.delenv(var, raising=False)
    monkeypatch.setenv("IA_TUNE_STORE", str(tmp_path / "no_store.json"))
    tune_store.invalidate_cache()
    tune.reset_provenance()

    a, ap, _ = small
    r = np.random.default_rng(2)
    frames = [np.clip(a + 0.02 * r.standard_normal(a.shape), 0, 1)
              .astype(np.float32) for _ in range(3)]
    p = _params(levels=2, temporal_weight=1.0, metrics=True)
    # outer scope joins video_analogy's own run reentrantly, so the
    # counters stay readable after each clip returns
    with obs_trace.run_scope(p):
        video_analogy(a, ap, frames, p, scheme="sequential")
        snap1 = obs_metrics.snapshot()
        video_analogy(a, ap, frames, p, scheme="sequential")
        snap2 = obs_metrics.snapshot()
    # one consult for clip 1, one more for clip 2: pinning is per-clip,
    # not a process-global memo that would mask store updates
    assert snap1["counters"]["tune.fallbacks"] == 1
    assert snap2["counters"]["tune.fallbacks"] == 2
    assert "tune.store_hits" not in snap1["counters"]


def test_video_flicker_metric(small):
    a, ap, _ = small
    r = np.random.default_rng(0)
    frames = [np.clip(a + 0.01 * r.standard_normal(a.shape), 0, 1)
              .astype(np.float32) for _ in range(3)]
    res = video_analogy(a, ap, frames, _params(temporal_weight=1.0))
    f = res.flicker()
    assert len(f) == 2 and all(-1.0 <= x <= 1.0 for x in f)


def test_temporal_spec_only_with_prev_frame():
    p = AnalogyParams(temporal_weight=1.0)
    s_on = spec_for_level(p, 0, 1, 1, temporal=True)
    s_off = spec_for_level(p, 0, 1, 1, temporal=False)
    assert s_on.temporal_n > 0 and s_off.temporal_n == 0
