"""Pyramid: shapes, kernel weights, NumPy/JAX twin agreement (SURVEY.md §4.2-4.3)."""

import numpy as np

from image_analogies_tpu.ops import pyramid


def test_shapes_odd_even():
    img = np.zeros((21, 34), np.float32)
    pyr = pyramid.build_pyramid_np(img, 3)
    assert [p.shape for p in pyr] == [(21, 34), (11, 17), (6, 9)]


def test_blur_preserves_constant():
    img = np.full((10, 12), 0.7, np.float32)
    np.testing.assert_allclose(pyramid.blur_np(img), 0.7, atol=1e-6)


def test_blur_kernel_weights():
    # Impulse response at the center of a large image = outer([1,4,6,4,1])/256.
    img = np.zeros((11, 11), np.float32)
    img[5, 5] = 1.0
    out = pyramid.blur_np(img)
    k = np.array([1, 4, 6, 4, 1], np.float32) / 16.0
    expect = np.outer(k, k)
    np.testing.assert_allclose(out[3:8, 3:8], expect, atol=1e-6)
    assert out[:3].sum() == 0 and out[8:].sum() == 0


def test_jax_matches_numpy(rng):
    img = rng.uniform(0, 1, (17, 23)).astype(np.float32)
    for np_lvl, jx_lvl in zip(pyramid.build_pyramid_np(img, 3),
                              pyramid.build_pyramid_jax(img, 3)):
        np.testing.assert_allclose(np.asarray(jx_lvl), np_lvl, atol=1e-6)


def test_jax_matches_numpy_multichannel(rng):
    img = rng.uniform(0, 1, (12, 14, 3)).astype(np.float32)
    np.testing.assert_allclose(
        np.asarray(pyramid.blur_jax(img)), pyramid.blur_np(img), atol=1e-6)


def test_num_feasible_levels():
    assert pyramid.num_feasible_levels((256, 256), 5, 5) == 5
    assert pyramid.num_feasible_levels((8, 8), 5, 5) == 1
    assert pyramid.num_feasible_levels((16, 16), 5, 5) == 2
    assert pyramid.num_feasible_levels((256, 256), 1, 5) == 1
