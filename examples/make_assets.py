"""Generate synthetic example assets (SURVEY.md §2 P10).

The reference ships sample A/A'/B triples (oil-paint filter, textures, label
maps, blur/sharp pairs).  This box has no network, so we synthesize
procedurally-generated equivalents covering every BASELINE.json config:

    python examples/make_assets.py [--out examples/assets] [--size 256]

Writes:
    filter_{a,ap,b}.png          oil-paint-ish posterize+smooth filter pair
    tbn_{labels_a,texture,labels_b}.png   texture-by-numbers triple
    sr_{sharp,low}.png           super-resolution pair
    texture.png                  texture-synthesis exemplar
    video_f{0..3}.png            four B frames with a moving feature
"""

from __future__ import annotations

import argparse
import os
import sys

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from image_analogies_tpu.ops.pyramid import blur_np
from image_analogies_tpu.utils.imageio import save_image


def _perlin_ish(h, w, rng, octaves=4):
    """Multi-octave value noise — a cheap natural-image stand-in."""
    out = np.zeros((h, w), np.float64)
    for o in range(octaves):
        step = max(2, min(h, w) >> (o + 1))
        gh, gw = h // step + 2, w // step + 2
        g = rng.standard_normal((gh, gw))
        ii = np.arange(h) / step
        jj = np.arange(w) / step
        i0 = ii.astype(int)
        j0 = jj.astype(int)
        fi = (ii - i0)[:, None]
        fj = (jj - j0)[None, :]
        v = (g[i0][:, j0] * (1 - fi) * (1 - fj)
             + g[i0 + 1][:, j0] * fi * (1 - fj)
             + g[i0][:, j0 + 1] * (1 - fi) * fj
             + g[i0 + 1][:, j0 + 1] * fi * fj)
        out += v * (0.6 ** o)
    out -= out.min()
    return (out / max(out.max(), 1e-9)).astype(np.float32)


def make_structured(h, seed: int = 7):
    """Canonical structured A/A'/B triple (perlin A, oil-filtered A', perlin
    B) used by bench.py, the cached 1024^2 oracle, and the experiments — ONE
    generator so cached oracle outputs can never silently diverge from the
    inputs being scored (bench.py also hashes the inputs)."""
    import numpy as np

    rng = np.random.default_rng(seed)
    a = _perlin_ish(h, h, rng)
    ap = _oil_filter(a)
    b = _perlin_ish(h, h, rng)
    return a, ap, b


def _oil_filter(img):
    """The 'A -> A'' training filter: smoothing + posterization (an
    oil-paint look, same family as the reference's example filters)."""
    x = blur_np(blur_np(img))
    return (np.round(x * 6) / 6.0).astype(np.float32)


def _texture(h, w, rng, kind):
    if kind == "stripes":
        base = 0.5 + 0.35 * np.sin(
            np.arange(w)[None, :] * 0.55 + 3.0 * _perlin_ish(h, w, rng, 2))
    elif kind == "spots":
        base = _perlin_ish(h, w, rng, 2)
        base = (base > 0.55).astype(np.float32) * 0.6 + 0.2
        base = blur_np(base)
    else:
        base = _perlin_ish(h, w, rng)
    return (base + 0.05 * rng.standard_normal((h, w))).clip(0, 1).astype(
        np.float32)


def make_all(out_dir: str, size: int = 256, seed: int = 0) -> None:
    os.makedirs(out_dir, exist_ok=True)
    rng = np.random.default_rng(seed)
    h = w = size

    # 1. artistic filter pair + target (BASELINE configs 2/4)
    a = _perlin_ish(h, w, rng)
    ap = _oil_filter(a)
    b = _perlin_ish(h, w, rng)
    save_image(f"{out_dir}/filter_a.png", a)
    save_image(f"{out_dir}/filter_ap.png", ap)
    save_image(f"{out_dir}/filter_b.png", b)

    # 2. texture-by-numbers (BASELINE config 1): 2-region label maps
    lab_a = np.zeros((h, w, 3), np.float32)
    split = _perlin_ish(h, w, rng, 2) > 0.5
    lab_a[..., 0] = split
    lab_a[..., 1] = ~split
    tex = np.where(split, _texture(h, w, rng, "stripes"),
                   _texture(h, w, rng, "spots")).astype(np.float32)
    lab_b = np.zeros((h, w, 3), np.float32)
    split_b = _perlin_ish(h, w, np.random.default_rng(seed + 7), 2) > 0.45
    lab_b[..., 0] = split_b
    lab_b[..., 1] = ~split_b
    save_image(f"{out_dir}/tbn_labels_a.png", lab_a)
    save_image(f"{out_dir}/tbn_texture.png", tex)
    save_image(f"{out_dir}/tbn_labels_b.png", lab_b)

    # 3. super-resolution pair (BASELINE config 3)
    sharp = _texture(h, w, rng, "stripes")
    low = blur_np(blur_np(_texture(h, w, np.random.default_rng(seed + 3),
                                   "stripes")))
    save_image(f"{out_dir}/sr_sharp.png", sharp)
    save_image(f"{out_dir}/sr_low.png", low)

    # 4. texture-synthesis exemplar
    save_image(f"{out_dir}/texture.png", _texture(h, w, rng, "spots"))

    # 5. video frames (BASELINE config 5): drifting blob over noise
    base = _perlin_ish(h, w, rng)
    yy, xx = np.meshgrid(np.arange(h), np.arange(w), indexing="ij")
    for t in range(4):
        cx = w * (0.3 + 0.1 * t)
        blob = np.exp(-((yy - h * 0.5) ** 2 + (xx - cx) ** 2)
                      / (2 * (0.08 * h) ** 2))
        frame = (0.7 * base + 0.5 * blob).clip(0, 1).astype(np.float32)
        save_image(f"{out_dir}/video_f{t}.png", frame)


if __name__ == "__main__":
    ap = argparse.ArgumentParser()
    ap.add_argument("--out", default=os.path.join(
        os.path.dirname(__file__), "assets"))
    ap.add_argument("--size", type=int, default=256)
    ap.add_argument("--seed", type=int, default=0)
    args = ap.parse_args()
    make_all(args.out, args.size, args.seed)
    print(args.out)
