"""Generate the committed miniature golden gallery (round-1 VERDICT item 5).

Runs all five BASELINE.json eval configs end-to-end at miniature sizes on
the TPU backend (wavefront strategy — the oracle-parity path) and writes
inputs + outputs as small PNGs to ``examples/golden/``.  The gallery is
checked into git, so output regressions show up as image diffs, and
``tests/test_golden.py`` asserts every config still reproduces its golden
within SSIM tolerance AND tracks the CPU oracle.

    JAX_PLATFORMS=cpu python examples/make_golden.py [--out examples/golden]
"""

from __future__ import annotations

import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np


def golden_configs(assets: dict):
    """The five BASELINE.json:7-12 configs at miniature golden sizes.

    Each entry: (name, callable(backend) -> dict of output plane(s)).
    `assets` maps asset name -> float image.
    """
    from image_analogies_tpu.config import PRESETS
    from image_analogies_tpu.models import modes
    from image_analogies_tpu.models.video import video_analogy

    def tbn(backend):
        res = modes.texture_by_numbers(
            assets["tbn_labels_a"], assets["tbn_texture"],
            assets["tbn_labels_b"],
            PRESETS["texture_by_numbers"].replace(backend=backend))
        return {"out": res.bp}

    def oil(backend):
        res = modes.artistic_filter(
            assets["filter_a"], assets["filter_ap"], assets["filter_b"],
            PRESETS["oil_filter"].replace(backend=backend))
        return {"out": res.bp}

    def superres(backend):
        res = modes.super_resolution(
            assets["sr_sharp"], assets["sr_low"],
            PRESETS["super_resolution"].replace(backend=backend))
        return {"out": res.bp}

    def npr(backend):
        res = modes.artistic_filter(
            assets["filter_a"], assets["filter_ap"], assets["filter_b"],
            PRESETS["npr_1024"].replace(backend=backend))
        return {"out": res.bp}

    def video(backend):
        # Note: on these miniature assets the committed goldens for frames 1
        # and 2 are byte-identical.  That is the algorithm, not a regen
        # artifact: with temporal_weight=1.0 the phase-2 synthesis of both
        # frames converges onto the same attractor (the CPU oracle produces
        # bit-equal SOURCE MAPS for the two frames despite inputs differing
        # by up to 0.33), verified round 3 against backend="cpu".
        res = video_analogy(
            assets["video_filter_a"], assets["video_filter_ap"],
            [assets[f"video_f{t}"] for t in range(3)],
            PRESETS["video"].replace(backend=backend, levels=2),
            scheme="two_phase")
        return {f"f{t}": res.frames[t] for t in range(3)}

    return [
        ("tbn", tbn),          # config 1: texture-by-numbers, single-scale
        ("oil", oil),          # config 2: oil filter, 3-level, kappa=5
        ("superres", superres),  # config 3: super-res, 7x7 patches
        ("npr", npr),          # config 4: NPR, 5-level pyramid
        ("video", video),      # config 5: batched video B-frames
    ]


def make_assets_small(size_main: int = 64, size_video: int = 32,
                      seed: int = 0) -> dict:
    """Miniature versions of examples/make_assets.py's asset set, generated
    deterministically in-memory (the gallery commits the rendered PNGs)."""
    import tempfile

    from examples.make_assets import make_all
    from image_analogies_tpu.utils.imageio import load_image

    assets = {}
    with tempfile.TemporaryDirectory() as d:
        make_all(d, size=size_main, seed=seed)
        for name in ("filter_a", "filter_ap", "filter_b", "tbn_labels_a",
                     "tbn_texture", "tbn_labels_b", "sr_sharp", "sr_low",
                     "texture"):
            assets[name] = load_image(os.path.join(d, f"{name}.png"))
    with tempfile.TemporaryDirectory() as d:
        make_all(d, size=size_video, seed=seed)
        for t in range(4):
            assets[f"video_f{t}"] = load_image(
                os.path.join(d, f"video_f{t}.png"))
        # video A/A' pair at the video size
        assets["video_filter_a"] = load_image(os.path.join(d, "filter_a.png"))
        assets["video_filter_ap"] = load_image(
            os.path.join(d, "filter_ap.png"))
    return assets


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--out", default=os.path.join(
        os.path.dirname(os.path.abspath(__file__)), "golden"))
    args = ap.parse_args()

    from image_analogies_tpu.utils.imageio import save_image

    assets = make_assets_small()
    os.makedirs(args.out, exist_ok=True)
    for name, img in assets.items():
        save_image(os.path.join(args.out, f"in_{name}.png"), img)

    for name, fn in golden_configs(assets):
        outs = fn("tpu")
        for key, img in outs.items():
            save_image(os.path.join(args.out, f"golden_{name}_{key}.png"),
                       np.asarray(img))
        print(f"golden {name}: {sorted(outs)}")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
