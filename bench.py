"""Benchmark harness — prints ONE JSON line for the driver.

Substantiates every clause of the north star (BASELINE.json:5): wall-clock
for 1024^2 B' / 5-level pyramid, speedup >= 50x over the NumPy/cKDTree CPU
oracle, AT SSIM PARITY — measured on the `wavefront` strategy, whose
anti-diagonal schedule reproduces the oracle's algorithm exactly
(backends/tpu.py), so the speedup and the parity are finally proven on the
SAME strategy (round-1 VERDICT item 1).

Inputs are structured perlin-like fields (natural-image statistics), not
white noise: on noise the synthesis task is ambiguous everywhere and any
quality metric is meaningless (round-1 VERDICT item 6).

Two configs run:

- north star: 1024^2 B', 5 levels, kappa=5.  The CPU oracle takes 1840.6 s
  here, so it was measured ONCE (experiments/oracle_1024.py) and its
  wall-clock + output plane are cached in bench_cache/ — SSIM is computed
  live against the cached oracle output.
- oil filter (BASELINE config 2): 256^2, 3 levels, kappa=5.  The oracle runs
  LIVE (~25 s on structured inputs) so every bench invocation re-validates
  an end-to-end oracle-vs-TPU number with nothing cached.

Output fields: value/vs_baseline describe the north-star config;
`ssim_vs_oracle` + `value_match` are its parity evidence; `configs` carries
both configs' full numbers.

On parity statistics: `value_match` (fraction of output pixels EXACTLY
bit-equal to the oracle's, np.equal) is the honest parity metric at scale.  `source_map_mismatch`
overcounts: posterized flat regions contain thousands of IDENTICAL A'
patches, the oracle's cKDTree breaks those exact ties in traversal order
(not lowest-index), and ~99% of "mismatched" picks copy an identical A'
value anyway (measured at 1024^2: 37.8% pick mismatch but 99.65% bit-equal
output, MAE 9e-4, SSIM 0.989).
"""

from __future__ import annotations

import json
import os
import sys
import time

import numpy as np

_HERE = os.path.dirname(os.path.abspath(__file__))
sys.path.insert(0, _HERE)


def make_structured(h: int, seed: int = 7):
    """Canonical structured inputs — examples/make_assets.py owns the
    generator; this thin alias keeps the historic bench import path."""
    from examples.make_assets import make_structured as gen

    return gen(h, seed)


def input_digest(a, ap, b) -> str:
    import hashlib

    h = hashlib.sha256()
    for x in (a, ap, b):
        h.update(np.ascontiguousarray(x, np.float32).tobytes())
    return h.hexdigest()[:16]


def _run_tpu(a, ap, b, params):
    from image_analogies_tpu.models.analogy import create_image_analogy

    create_image_analogy(a, ap, b, params)  # compile warm-up
    t0 = time.perf_counter()
    res = create_image_analogy(a, ap, b, params)
    return res, time.perf_counter() - t0


def main() -> int:
    import jax

    from image_analogies_tpu.config import AnalogyParams
    from image_analogies_tpu.models.analogy import create_image_analogy
    from image_analogies_tpu.utils.ssim import ssim

    dev = jax.devices()[0].device_kind
    configs = {}

    # ---- config 2 (oil filter, 256^2, 3 levels): LIVE oracle ----
    a, ap, b = make_structured(256)
    p = AnalogyParams(levels=3, kappa=5.0, backend="tpu",
                      strategy="wavefront")
    res_tpu, tpu_s = _run_tpu(a, ap, b, p)
    t0 = time.perf_counter()
    res_cpu = create_image_analogy(a, ap, b, p.replace(backend="cpu"))
    cpu_s = time.perf_counter() - t0
    diff = np.abs(res_tpu.bp_y - res_cpu.bp_y)
    match = float((res_tpu.bp_y == res_cpu.bp_y).mean())
    configs["oil_256"] = {
        "tpu_s": round(tpu_s, 3),
        "cpu_oracle_s": round(cpu_s, 1),
        "speedup": round(cpu_s / tpu_s, 1),
        "ssim_vs_oracle": round(ssim(res_tpu.bp_y, res_cpu.bp_y), 4),
        "value_match": round(match, 4),
        "output_mae": round(float(diff.mean()), 6),
        "source_map_mismatch": round(float(
            (res_tpu.source_map != res_cpu.source_map).mean()), 6),
        "oracle": "live",
    }

    # ---- north star (1024^2, 5 levels): cached oracle ----
    cache = os.path.join(_HERE, "bench_cache")
    with open(os.path.join(cache, "oracle_1024.json")) as f:
        ocfg = json.load(f)
    oz = np.load(os.path.join(
        cache, f"oracle_1024_seed{ocfg['config']['seed']}.npz"))
    a, ap, b = make_structured(ocfg["config"]["size"],
                               ocfg["config"]["seed"])
    if "input_digest" in ocfg:
        got = input_digest(a, ap, b)
        if got != ocfg["input_digest"]:
            raise SystemExit(
                f"bench inputs drifted from the cached oracle's "
                f"({got} != {ocfg['input_digest']}): re-run "
                "experiments/oracle_1024.py before benching")
    p = AnalogyParams(levels=ocfg["config"]["levels"],
                      kappa=ocfg["config"]["kappa"], backend="tpu",
                      strategy="wavefront")
    res_ns, ns_s = _run_tpu(a, ap, b, p)
    oracle_s = float(ocfg["wall_s"])
    ns_ssim = ssim(res_ns.bp_y, oz["bp_y"])
    ns_diff = np.abs(res_ns.bp_y - oz["bp_y"])
    ns_match = float((res_ns.bp_y == oz["bp_y"]).mean())
    configs["north_star_1024"] = {
        "tpu_s": round(ns_s, 3),
        "cpu_oracle_s": oracle_s,
        "speedup": round(oracle_s / ns_s, 1),
        "ssim_vs_oracle": round(ns_ssim, 4),
        "value_match": round(ns_match, 4),
        "output_mae": round(float(ns_diff.mean()), 6),
        "source_map_mismatch": round(float(
            (res_ns.source_map != oz["source_map"]).mean()), 6),
        "oracle": "cached (experiments/oracle_1024.py)",
    }

    print(json.dumps({
        "metric": "1024x1024 B' synthesis wall-clock, 5-level pyramid, "
                  "kappa=5 (north-star config), wavefront oracle-parity "
                  f"strategy on {dev}",
        "value": round(ns_s, 3),
        "unit": "s",
        "vs_baseline": round(oracle_s / ns_s, 1),
        "ssim_vs_oracle": round(ns_ssim, 4),
        "value_match": round(ns_match, 4),
        "configs": configs,
    }))
    print(f"# parity strategy=wavefront; configs={json.dumps(configs)}",
          file=sys.stderr)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
