"""Benchmark harness — prints ONE JSON line for the driver.

Measures the classic A:A'::B:B' filter config (BASELINE.json config 2 shape:
256x256, 3-level pyramid, kappa=5) end-to-end on the TPU backend (batched
strategy, Pallas fused argmin) and on the reference-equivalent NumPy/cKDTree
CPU oracle, on this machine.

    metric      : config + hardware
    value       : TPU wall-clock (warm, compile excluded), seconds
    vs_baseline : CPU-oracle wall-clock / TPU wall-clock  (the ">= 50x the
                  NumPy/cKDTree path" axis of BASELINE.json:5; >1 = faster)
"""

from __future__ import annotations

import json
import sys
import time

import numpy as np


def make_inputs(h: int, seed: int = 0):
    rng = np.random.default_rng(seed)
    yy, xx = np.meshgrid(np.linspace(0, 1, h), np.linspace(0, 1, h),
                         indexing="ij")
    base = 0.5 * yy + 0.5 * xx
    a = (base + 0.08 * rng.standard_normal((h, h))).clip(0, 1).astype(
        np.float32)
    ap = (np.round(a * 6) / 6).astype(np.float32)
    b = (0.35 * yy ** 2 + 0.65 * xx
         + 0.08 * rng.standard_normal((h, h))).clip(0, 1).astype(np.float32)
    return a, ap, b


def main() -> int:
    import jax

    from image_analogies_tpu.config import AnalogyParams
    from image_analogies_tpu.models.analogy import create_image_analogy

    size = 256
    levels = 3
    kappa = 5.0
    a, ap, b = make_inputs(size)

    p_tpu = AnalogyParams(levels=levels, kappa=kappa, backend="tpu",
                          strategy="batched")
    # warm-up: compile every level's scan once
    create_image_analogy(a, ap, b, p_tpu)
    t0 = time.perf_counter()
    res_tpu = create_image_analogy(a, ap, b, p_tpu)
    tpu_s = time.perf_counter() - t0

    p_cpu = AnalogyParams(levels=levels, kappa=kappa, backend="cpu")
    t0 = time.perf_counter()
    create_image_analogy(a, ap, b, p_cpu)
    cpu_s = time.perf_counter() - t0

    dev = jax.devices()[0].device_kind
    print(json.dumps({
        "metric": f"{size}x{size} B' synthesis wall-clock, {levels}-level "
                  f"pyramid, kappa={kappa} (oil-filter config) on {dev}",
        "value": round(tpu_s, 3),
        "unit": "s",
        "vs_baseline": round(cpu_s / tpu_s, 2),
    }))
    print(f"# cpu_oracle={cpu_s:.2f}s tpu={tpu_s:.2f}s "
          f"levels={[s['ms'] for s in res_tpu.stats]}", file=sys.stderr)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
