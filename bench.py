"""Benchmark harness — prints ONE JSON line for the driver.

Substantiates every clause of the north star (BASELINE.json:5): wall-clock
for 1024^2 B' / 5-level pyramid, speedup >= 50x over the NumPy/cKDTree CPU
oracle, AT SSIM PARITY — measured on the `wavefront` strategy, whose
anti-diagonal schedule reproduces the oracle's algorithm exactly
(backends/tpu.py), so the speedup and the parity are finally proven on the
SAME strategy (round-1 VERDICT item 1).

Inputs are structured perlin-like fields (natural-image statistics), not
white noise: on noise the synthesis task is ambiguous everywhere and any
quality metric is meaningless (round-1 VERDICT item 6).

All five BASELINE.json:7-12 eval configs run (round-4 VERDICT item 6):

- north star / artistic NPR (config 4): 1024^2 B', 5 levels, kappa=5.
  The CPU oracle takes 1432-3246 s here, so it was measured once per seed
  (experiments/oracle_1024.py) and its wall-clock + output planes are
  cached in bench_cache/ — SSIM/tie-audit run live against the cache.
- oil filter (config 2): 256^2, 3 levels, kappa=5.  The oracle runs LIVE
  (~25 s on structured inputs) so every bench invocation re-validates an
  end-to-end oracle-vs-TPU number with nothing cached, tie-audit included.
- texture-by-numbers (config 1): 256^2 labels->texture, single-scale.
- super-resolution (config 3): 192^2, 7x7 patches, kappa in {0.5, 2, 5}
  (BASELINE pins patches + sweep, not size; the 256^2 oracle alone blew a
  25-minute budget).
- batched video (config 5): 3 x 256^2 B-frames, 2 levels, temporal term,
  two_phase (the frame-sharded mesh form is validated by dryrun_multichip;
  the 4-frame 3-level point is committed in
  bench_cache/bench_full_r05_builder.json).

The last three run LIVE oracles at native sizes (min-of-N on the TPU
side; ONE oracle draw each — their multi-minute oracles are the bench's
budget ceiling, and the oil config's min-of-2 already anchors the
live-oracle floor methodology).  IA_BENCH_CONFIGS=name[,name...] restricts the oracle configs
during development (the north star always runs — it carries the headline
JSON); the driver's plain invocation runs everything.

Output fields: value/vs_baseline describe the north-star config;
`ssim_vs_oracle` + `value_match` are its parity evidence; `configs` carries
both configs' full numbers.

On parity statistics: `value_match` (fraction of output pixels EXACTLY
bit-equal to the oracle's, np.equal) is the honest parity metric at scale.  `source_map_mismatch`
overcounts: posterized flat regions contain thousands of IDENTICAL A'
patches, the oracle's cKDTree breaks those exact ties in traversal order
(not lowest-index), and ~99% of "mismatched" picks copy an identical A'
value anyway (measured at 1024^2: 37.8% pick mismatch but 99.65% bit-equal
output, MAE 9e-4, SSIM 0.989).
"""

from __future__ import annotations

import json
import os
import re
import sys
import time

import numpy as np

# --- regression sentry (`ia bench --check`) ---------------------------------
#
# The BENCH_r0N.json archive the driver keeps per round is a wall-clock
# trajectory; these helpers turn it into a gate: parse each round's
# headline number, group by metric (r01 measured the 256^2 oil config,
# r02+ the 1024^2 north star — they must never be compared), and fail
# when a fresh number regresses more than a threshold past the best
# (lowest) same-metric point.  Everything here is jax-free and runs in
# milliseconds — `ia bench --check --dry-run` rides in tier-1 so the
# parsing of the archive formats can never silently rot.

# r03-r05 have parsed=null and a head-truncated tail that cuts off the
# headline "value" field; the north-star per-config block survives, so
# this regex recovers the wall-clock from the raw text.
_NORTH_STAR_RE = re.compile(
    r'"north_star_1024_seed7"\s*:\s*\{\s*"tpu_s"\s*:\s*([0-9.eE+-]+)')
_BENCH_FILE_RE = re.compile(r"^BENCH_r(\d+)\.json$")


def _metric_key(metric: str) -> str:
    """Comparable-config key of a headline metric string: its first
    token ("1024x1024", "256x256") — rounds measuring different configs
    must not gate each other."""
    parts = str(metric).split()
    return parts[0] if parts else "unknown"


def extract_headline(doc: dict):
    """Headline wall-clock of one BENCH_r0N.json driver doc, or None.

    Tries, in order: the driver's ``parsed`` dict; the last full JSON
    line in ``tail`` carrying a ``value`` field; a regex over the raw
    tail for the north-star per-config block (survives the driver's
    head-truncation of long tails).
    """
    def _head(obj, source):
        out = {"value": float(obj["value"]),
               "metric_key": _metric_key(obj.get("metric", "")),
               "source": source}
        # host-gap trajectory (PR 8): rounds that measured the pipelined
        # engine carry the inter-level host time; older archives don't —
        # the sentry gates it only where both sides have one
        if obj.get("host_gap_ms") is not None:
            out["host_gap_ms"] = float(obj["host_gap_ms"])
        # obs-overhead trajectory (PR 11): instrumented vs metrics=False
        # wall-clock at 256^2 — the scoped-observability fast path is a
        # perf promise, so its cost rides the same archive
        if obj.get("obs_overhead_pct") is not None:
            out["obs_overhead_pct"] = float(obj["obs_overhead_pct"])
        # catalog cold-start trajectory (PR 12): first-request wall with
        # a warm exemplar catalog at 256^2 — the tiered catalog is a
        # cold-start promise, so its number rides the same archive
        if obj.get("cold_start_ms") is not None:
            out["cold_start_ms"] = float(obj["cold_start_ms"])
        # exemplar-scaling trajectory (PR 13): two-stage ANN wall-clock
        # ratio at 16x the exemplar rows — the sub-linear matcher is a
        # scaling promise, so its ratio rides the same archive
        if obj.get("exemplar_scale_ratio") is not None:
            out["exemplar_scale_ratio"] = float(
                obj["exemplar_scale_ratio"])
        # timeline trajectory (PR 14): armed temporal plane (windowed
        # store + background sampler) vs disarmed at 256^2 — the
        # always-on cockpit only stays always-on if this stays small
        if obj.get("timeline_overhead_pct") is not None:
            out["timeline_overhead_pct"] = float(
                obj["timeline_overhead_pct"])
        # handoff trajectory (PR 15): SIGKILL -> the replacement
        # subprocess worker answering the stranded request on the SAME
        # journal dir at 64^2 — the fleet's failover promise in ms
        if obj.get("handoff_recovery_ms") is not None:
            out["handoff_recovery_ms"] = float(
                obj["handoff_recovery_ms"])
        # ledger trajectory (PR 16): armed tenant metering plane (cost
        # vectors + space-saving heavy hitters) vs disarmed at 256^2 —
        # per-request attribution only stays always-on if this stays
        # small
        if obj.get("ledger_overhead_pct") is not None:
            out["ledger_overhead_pct"] = float(
                obj["ledger_overhead_pct"])
        # archive trajectory (PR 17): armed durable telemetry archive
        # (sealed append-only segments) vs the bare armed timeline at
        # 256^2 — the flight recorder only stays always-on if this
        # stays small; sketch_p999_rel_err rides ungated (the sketch
        # selftest raises on dishonesty before a number is printed)
        if obj.get("archive_overhead_pct") is not None:
            out["archive_overhead_pct"] = float(
                obj["archive_overhead_pct"])
        if obj.get("sketch_p999_rel_err") is not None:
            out["sketch_p999_rel_err"] = float(
                obj["sketch_p999_rel_err"])
        # elastic-fleet trajectory (PR 19): burst overruns the policy's
        # pressure threshold -> the control plane's reconcile spawns,
        # ring-joins, and warm-stages a worker; the headline is
        # pressure-onset -> joined worker ready, in ms
        if obj.get("scale_up_ms") is not None:
            out["scale_up_ms"] = float(obj["scale_up_ms"])
        # soak trajectory (PR 20): full seeded trace against an
        # autoscaling fleet with chaos armed throughout — the DDSketch
        # p99.9 of answered latency plus the loss count (submits that
        # neither answered nor shed cleanly; the gate is zero)
        if obj.get("soak_p999_ms") is not None:
            out["soak_p999_ms"] = float(obj["soak_p999_ms"])
        if obj.get("soak_loss") is not None:
            out["soak_loss"] = int(obj["soak_loss"])
        return out

    parsed = doc.get("parsed")
    if isinstance(parsed, dict) and "value" in parsed:
        return _head(parsed, "parsed")
    tail = doc.get("tail") or ""
    for line in reversed(tail.splitlines()):
        line = line.strip()
        if not (line.startswith("{") and '"value"' in line):
            continue
        try:
            obj = json.loads(line)
        except ValueError:
            continue
        if isinstance(obj, dict) and "value" in obj:
            return _head(obj, "tail_json")
    m = _NORTH_STAR_RE.search(tail)
    if m:
        return {"value": float(m.group(1)),
                "metric_key": "1024x1024",
                "source": "tail_regex"}
    return None


def load_trajectory(bench_dir: str = ".") -> dict:
    """Parse every BENCH_r*.json in ``bench_dir`` into an ordered list of
    trajectory points; unparseable files land in ``problems`` rather
    than raising (the sentry must degrade loudly, not crash)."""
    rounds = []
    for fname in os.listdir(bench_dir):
        m = _BENCH_FILE_RE.match(fname)
        if m:
            rounds.append((int(m.group(1)), fname))
    points, problems = [], []
    for rnd, fname in sorted(rounds):
        path = os.path.join(bench_dir, fname)
        try:
            with open(path) as f:
                doc = json.load(f)
        except (OSError, ValueError) as exc:
            problems.append(f"{fname}: unreadable ({exc})")
            continue
        head = extract_headline(doc)
        if head is None:
            problems.append(f"{fname}: no headline value found")
            continue
        head.update({"round": rnd, "file": fname})
        points.append(head)
    return {"points": points, "problems": problems}


def check_regression(trajectory: dict, fresh_value=None,
                     threshold_pct: float = 20.0,
                     fresh_gap=None, fresh_key=None,
                     fresh_obs=None, fresh_cold=None,
                     fresh_scale=None, fresh_timeline=None,
                     fresh_handoff=None, fresh_ledger=None,
                     fresh_archive=None, fresh_scaleup=None,
                     fresh_soak_p999=None, fresh_soak_loss=None) -> dict:
    """Gate a wall-clock number against the trajectory floor.

    With ``fresh_value`` (a just-measured number), it is compared against
    the best (minimum) same-metric point of the whole archive.  Without
    one (dry-run / archive self-check), the LATEST archived point is
    checked against the best of the points before it.  ``ok`` is False
    when the candidate exceeds the floor by more than ``threshold_pct``
    percent.

    ``fresh_key`` names the metric a ``fresh_value`` belongs to (default:
    the latest archived point's key, the historic behavior).  A fresh
    value whose metric has NO archived points — a brand-new metric, or an
    empty archive — is NOT a crash and NOT a gate: it passes explicitly
    as ``reason: "no_floor_recorded_only"`` (with a
    ``bench.check.no_floor`` counter when an obs registry is live), so
    the first measurement of a new metric can ride the same CI command
    that later gates it.

    ``host_gap_ms`` (the pipelined engine's inter-level host time)
    rides the same gate wherever BOTH the candidate and at least one
    comparison point carry it — the pipeline's whole point is keeping
    that number near zero, so a silent regression there must fail the
    sentry even when total wall-clock absorbs it.  ``fresh_gap`` pairs
    with ``fresh_value``; archive points carry theirs from
    ``extract_headline``.

    ``obs_overhead_pct`` (wall-clock cost of live observability at
    256^2, instrumented vs ``metrics=False`` — PR 11's scoped fast
    path) rides the same pattern via ``fresh_obs``.  The number is
    already a percentage, so its gate is ABSOLUTE: more than
    ``threshold_pct`` percentage POINTS over the floor fails (a
    relative gate on a near-zero floor would flap on noise).

    ``cold_start_ms`` (first-request wall-clock with a warm exemplar
    catalog at 256^2 — PR 12's tiered catalog) rides via
    ``fresh_cold``, gated relatively like ``host_gap_ms``.  Archives
    from rounds before the catalog existed carry no floor, so the
    first measured point records without gating (the same
    legacy-archive posture as every other rider).

    ``exemplar_scale_ratio`` (two-stage ANN wall-clock at 16x the
    exemplar rows over 1x — PR 13's sub-linear matcher) rides via
    ``fresh_scale`` with TWO gates: the relative archive-floor gate of
    every other rider (no floor on legacy archives ⇒ recorded only),
    plus an ABSOLUTE sub-linearity gate — a ratio of 8x or more means
    16x the rows cost at least half of linear and the prefilter has
    stopped paying for itself, which fails regardless of what the
    archive says (``exemplar_scale_not_sublinear``).

    ``timeline_overhead_pct`` (armed temporal plane — windowed store +
    background sampler — vs disarmed at 256^2, PR 14) rides via
    ``fresh_timeline`` with the same ABSOLUTE percentage-points gate
    as ``obs_overhead_pct``; archives from rounds before the timeline
    existed carry no floor, so the first point records without gating.

    ``handoff_recovery_ms`` (SIGKILL a subprocess fleet worker
    mid-request -> its replacement answering on the same journal dir at
    64^2 — PR 15's failover promise) rides via ``fresh_handoff``, gated
    relatively like ``cold_start_ms``.  Archives from rounds before the
    subprocess transport existed carry no floor, so the first measured
    point records without gating.

    ``ledger_overhead_pct`` (armed tenant metering plane — cost vectors
    + space-saving heavy hitters — vs disarmed at 256^2, PR 16) rides
    via ``fresh_ledger`` with the same ABSOLUTE percentage-points gate
    as ``timeline_overhead_pct``; archives from rounds before the
    ledger existed carry no floor, so the first point records without
    gating.

    ``archive_overhead_pct`` (armed durable telemetry archive — sealed
    append-only segments fed by the timeline sampler — vs the bare
    armed timeline at 256^2, PR 17) rides via ``fresh_archive`` with
    the same ABSOLUTE percentage-points gate; archives from rounds
    before the flight recorder existed carry no floor, so the first
    point records without gating.

    ``scale_up_ms`` (a burst overruns the control policy's pressure
    threshold -> the reconcile loop spawns, ring-joins, and warm-stages
    a worker; headline = pressure onset -> joined worker ready — PR
    19's elastic-fleet promise) rides via ``fresh_scaleup``, gated
    relatively like ``handoff_recovery_ms``.  Archives from rounds
    before the control plane existed carry no floor, so the first
    measured point records without gating.

    ``soak_p999_ms`` / ``soak_loss`` (the full seeded soak's DDSketch
    p99.9 answered latency and its zero-loss accounting residue — PR
    20's duration-emergent promises) ride via ``fresh_soak_p999`` /
    ``fresh_soak_loss``.  The p99.9 gates relatively like
    ``handoff_recovery_ms`` (legacy archives record only); the loss is
    an ABSOLUTE gate needing no archive — ANY lost request fails
    (``soak_lost_requests``), because the soak gate already passed
    before the number was printed and a nonzero here means the archive
    was fed by a run that should have refused.
    """
    points = trajectory.get("points") or []
    problems = list(trajectory.get("problems", []))
    if not points and fresh_value is None:
        return {"ok": False, "reason": "no_trajectory_points",
                "problems": problems}
    if fresh_value is not None:
        key = fresh_key or (points[-1]["metric_key"] if points
                            else "unknown")
        same = [p for p in points if p["metric_key"] == key]
        if not same:
            try:
                from image_analogies_tpu.obs import metrics as _obs_m
                _obs_m.inc("bench.check.no_floor")
            except Exception:
                pass
            return {"ok": True, "reason": "no_floor_recorded_only",
                    "metric_key": key, "candidate": float(fresh_value),
                    "candidate_source": "fresh", "no_floor": 1,
                    "points": len(points), "problems": problems}
        candidate, cand_src = float(fresh_value), "fresh"
        cand_gap = fresh_gap
        cand_obs = fresh_obs
        cand_cold = fresh_cold
        cand_scale = fresh_scale
        cand_timeline = fresh_timeline
        cand_handoff = fresh_handoff
        cand_ledger = fresh_ledger
        cand_archive = fresh_archive
        cand_scaleup = fresh_scaleup
        cand_soak_p999 = fresh_soak_p999
        cand_soak_loss = fresh_soak_loss
        prior = same
        floor = min(p["value"] for p in same)
    else:
        latest = points[-1]
        key = latest["metric_key"]
        same = [p for p in points if p["metric_key"] == key]
        candidate, cand_src = latest["value"], latest["file"]
        cand_gap = latest.get("host_gap_ms")
        cand_obs = latest.get("obs_overhead_pct")
        cand_cold = latest.get("cold_start_ms")
        cand_scale = latest.get("exemplar_scale_ratio")
        cand_timeline = latest.get("timeline_overhead_pct")
        cand_handoff = latest.get("handoff_recovery_ms")
        cand_ledger = latest.get("ledger_overhead_pct")
        cand_archive = latest.get("archive_overhead_pct")
        cand_scaleup = latest.get("scale_up_ms")
        cand_soak_p999 = latest.get("soak_p999_ms")
        cand_soak_loss = latest.get("soak_loss")
        prior = same[:-1]
        if not prior:
            return {"ok": True, "reason": "single_point",
                    "metric_key": key, "candidate": candidate,
                    "candidate_source": cand_src,
                    "points": len(points),
                    "problems": problems}
        floor = min(p["value"] for p in prior)
    regression_pct = (candidate - floor) / floor * 100.0
    out = {
        "ok": regression_pct <= threshold_pct,
        "metric_key": key,
        "candidate": candidate,
        "candidate_source": cand_src,
        "floor": floor,
        "regression_pct": round(regression_pct, 2),
        "threshold_pct": threshold_pct,
        "points": len(points),
        "problems": problems,
    }
    prior_gaps = [p["host_gap_ms"] for p in prior
                  if p.get("host_gap_ms") is not None]
    if cand_gap is not None and prior_gaps:
        gap_floor = min(prior_gaps)
        # floor can legitimately be ~0 on a fully-hidden run: gate on an
        # absolute 1 ms slack there instead of exploding the percentage
        gap_reg = ((float(cand_gap) - gap_floor)
                   / max(gap_floor, 1.0) * 100.0)
        out["host_gap_ms"] = float(cand_gap)
        out["host_gap_floor"] = gap_floor
        out["host_gap_regression_pct"] = round(gap_reg, 2)
        if gap_reg > threshold_pct:
            out["ok"] = False
            problems.append(
                f"host_gap_ms regressed {gap_reg:.1f}% past the "
                f"{gap_floor:.1f} ms floor (candidate {cand_gap:.1f} ms)")
    prior_obs = [p["obs_overhead_pct"] for p in prior
                 if p.get("obs_overhead_pct") is not None]
    if cand_obs is not None and prior_obs:
        obs_floor = min(prior_obs)
        obs_delta = float(cand_obs) - obs_floor
        out["obs_overhead_pct"] = float(cand_obs)
        out["obs_overhead_floor"] = obs_floor
        out["obs_overhead_delta_pts"] = round(obs_delta, 2)
        if obs_delta > threshold_pct:
            out["ok"] = False
            problems.append(
                f"obs_overhead_pct grew {obs_delta:.1f} points past the "
                f"{obs_floor:.1f}% floor (candidate {cand_obs:.1f}%)")
    prior_colds = [p["cold_start_ms"] for p in prior
                   if p.get("cold_start_ms") is not None]
    if cand_cold is not None and prior_colds:
        cold_floor = min(prior_colds)
        cold_reg = ((float(cand_cold) - cold_floor)
                    / max(cold_floor, 1.0) * 100.0)
        out["cold_start_ms"] = float(cand_cold)
        out["cold_start_floor"] = cold_floor
        out["cold_start_regression_pct"] = round(cold_reg, 2)
        if cold_reg > threshold_pct:
            out["ok"] = False
            problems.append(
                f"cold_start_ms regressed {cold_reg:.1f}% past the "
                f"{cold_floor:.1f} ms floor (candidate {cand_cold:.1f} ms)")
    elif cand_cold is not None:
        # legacy archives (pre-catalog rounds) carry no floor: record
        # the point without gating, same posture as no_floor_recorded_only
        out["cold_start_ms"] = float(cand_cold)
        out["cold_start_floor"] = None
    if cand_scale is not None:
        out["exemplar_scale_ratio"] = float(cand_scale)
        # absolute sub-linearity promise: needs no archive floor
        if float(cand_scale) >= 8.0:
            out["ok"] = False
            problems.append(
                f"exemplar_scale_not_sublinear: 16x the exemplar rows "
                f"cost {float(cand_scale):.1f}x wall-clock (>= 8x)")
        prior_ratios = [p["exemplar_scale_ratio"] for p in prior
                        if p.get("exemplar_scale_ratio") is not None]
        if prior_ratios:
            ratio_floor = min(prior_ratios)
            ratio_reg = ((float(cand_scale) - ratio_floor)
                         / max(ratio_floor, 1.0) * 100.0)
            out["exemplar_scale_floor"] = ratio_floor
            out["exemplar_scale_regression_pct"] = round(ratio_reg, 2)
            if ratio_reg > threshold_pct:
                out["ok"] = False
                problems.append(
                    f"exemplar_scale_ratio regressed {ratio_reg:.1f}% "
                    f"past the {ratio_floor:.2f}x floor (candidate "
                    f"{float(cand_scale):.2f}x)")
        else:
            # legacy archives (pre-ANN rounds) carry no floor: the
            # relative gate records only; the absolute gate above ran
            out["exemplar_scale_floor"] = None
    prior_timelines = [p["timeline_overhead_pct"] for p in prior
                       if p.get("timeline_overhead_pct") is not None]
    if cand_timeline is not None and prior_timelines:
        tl_floor = min(prior_timelines)
        # already a percentage — gate in absolute points, like the obs
        # overhead above (a relative gate on a near-zero floor flaps)
        tl_delta = float(cand_timeline) - tl_floor
        out["timeline_overhead_pct"] = float(cand_timeline)
        out["timeline_overhead_floor"] = tl_floor
        out["timeline_overhead_delta_pts"] = round(tl_delta, 2)
        if tl_delta > threshold_pct:
            out["ok"] = False
            problems.append(
                f"timeline_overhead_pct grew {tl_delta:.1f} points past "
                f"the {tl_floor:.1f}% floor "
                f"(candidate {cand_timeline:.1f}%)")
    elif cand_timeline is not None:
        # legacy archives (pre-timeline rounds) carry no floor: record
        # the point without gating, same posture as cold_start_ms
        out["timeline_overhead_pct"] = float(cand_timeline)
        out["timeline_overhead_floor"] = None
    prior_handoffs = [p["handoff_recovery_ms"] for p in prior
                      if p.get("handoff_recovery_ms") is not None]
    if cand_handoff is not None and prior_handoffs:
        ho_floor = min(prior_handoffs)
        ho_reg = ((float(cand_handoff) - ho_floor)
                  / max(ho_floor, 1.0) * 100.0)
        out["handoff_recovery_ms"] = float(cand_handoff)
        out["handoff_recovery_floor"] = ho_floor
        out["handoff_recovery_regression_pct"] = round(ho_reg, 2)
        if ho_reg > threshold_pct:
            out["ok"] = False
            problems.append(
                f"handoff_recovery_ms regressed {ho_reg:.1f}% past the "
                f"{ho_floor:.1f} ms floor "
                f"(candidate {cand_handoff:.1f} ms)")
    elif cand_handoff is not None:
        # legacy archives (pre-subprocess-transport rounds) carry no
        # floor: record the point without gating, same posture as
        # cold_start_ms
        out["handoff_recovery_ms"] = float(cand_handoff)
        out["handoff_recovery_floor"] = None
    prior_ledgers = [p["ledger_overhead_pct"] for p in prior
                     if p.get("ledger_overhead_pct") is not None]
    if cand_ledger is not None and prior_ledgers:
        lg_floor = min(prior_ledgers)
        # already a percentage — absolute points, like the timeline gate
        lg_delta = float(cand_ledger) - lg_floor
        out["ledger_overhead_pct"] = float(cand_ledger)
        out["ledger_overhead_floor"] = lg_floor
        out["ledger_overhead_delta_pts"] = round(lg_delta, 2)
        if lg_delta > threshold_pct:
            out["ok"] = False
            problems.append(
                f"ledger_overhead_pct grew {lg_delta:.1f} points past "
                f"the {lg_floor:.1f}% floor "
                f"(candidate {cand_ledger:.1f}%)")
    elif cand_ledger is not None:
        # legacy archives (pre-ledger rounds) carry no floor: record
        # the point without gating, same posture as timeline_overhead
        out["ledger_overhead_pct"] = float(cand_ledger)
        out["ledger_overhead_floor"] = None
    prior_archives = [p["archive_overhead_pct"] for p in prior
                      if p.get("archive_overhead_pct") is not None]
    if cand_archive is not None and prior_archives:
        av_floor = min(prior_archives)
        # already a percentage — absolute points, like the timeline gate
        av_delta = float(cand_archive) - av_floor
        out["archive_overhead_pct"] = float(cand_archive)
        out["archive_overhead_floor"] = av_floor
        out["archive_overhead_delta_pts"] = round(av_delta, 2)
        if av_delta > threshold_pct:
            out["ok"] = False
            problems.append(
                f"archive_overhead_pct grew {av_delta:.1f} points past "
                f"the {av_floor:.1f}% floor "
                f"(candidate {cand_archive:.1f}%)")
    elif cand_archive is not None:
        # legacy archives (pre-flight-recorder rounds) carry no floor:
        # record the point without gating, same posture as the others
        out["archive_overhead_pct"] = float(cand_archive)
        out["archive_overhead_floor"] = None
    prior_scaleups = [p["scale_up_ms"] for p in prior
                      if p.get("scale_up_ms") is not None]
    if cand_scaleup is not None and prior_scaleups:
        su_floor = min(prior_scaleups)
        su_reg = ((float(cand_scaleup) - su_floor)
                  / max(su_floor, 1.0) * 100.0)
        out["scale_up_ms"] = float(cand_scaleup)
        out["scale_up_floor"] = su_floor
        out["scale_up_regression_pct"] = round(su_reg, 2)
        if su_reg > threshold_pct:
            out["ok"] = False
            problems.append(
                f"scale_up_ms regressed {su_reg:.1f}% past the "
                f"{su_floor:.1f} ms floor "
                f"(candidate {cand_scaleup:.1f} ms)")
    elif cand_scaleup is not None:
        # legacy archives (pre-control-plane rounds) carry no floor:
        # record the point without gating, same posture as
        # handoff_recovery_ms
        out["scale_up_ms"] = float(cand_scaleup)
        out["scale_up_floor"] = None
    prior_soaks = [p["soak_p999_ms"] for p in prior
                   if p.get("soak_p999_ms") is not None]
    if cand_soak_p999 is not None and prior_soaks:
        sp_floor = min(prior_soaks)
        sp_reg = ((float(cand_soak_p999) - sp_floor)
                  / max(sp_floor, 1.0) * 100.0)
        out["soak_p999_ms"] = float(cand_soak_p999)
        out["soak_p999_floor"] = sp_floor
        out["soak_p999_regression_pct"] = round(sp_reg, 2)
        if sp_reg > threshold_pct:
            out["ok"] = False
            problems.append(
                f"soak_p999_ms regressed {sp_reg:.1f}% past the "
                f"{sp_floor:.1f} ms floor "
                f"(candidate {cand_soak_p999:.1f} ms)")
    elif cand_soak_p999 is not None:
        # legacy archives (pre-soak rounds) carry no floor: record the
        # point without gating, same posture as scale_up_ms
        out["soak_p999_ms"] = float(cand_soak_p999)
        out["soak_p999_floor"] = None
    if cand_soak_loss is not None:
        out["soak_loss"] = int(cand_soak_loss)
        # absolute zero-loss promise: needs no archive floor — the soak
        # gate refuses to print a headline off a lossy run, so a
        # nonzero archived loss is itself the regression
        if int(cand_soak_loss) > 0:
            out["ok"] = False
            problems.append(
                f"soak_lost_requests: {int(cand_soak_loss)} submitted "
                "request(s) neither answered nor shed cleanly")
    return out

_HERE = os.path.dirname(os.path.abspath(__file__))
sys.path.insert(0, _HERE)


def make_structured(h: int, seed: int = 7):
    """Canonical structured inputs — examples/make_assets.py owns the
    generator; this thin alias keeps the historic bench import path."""
    from examples.make_assets import make_structured as gen

    return gen(h, seed)


def input_digest(a, ap, b) -> str:
    import hashlib

    h = hashlib.sha256()
    for x in (a, ap, b):
        h.update(np.ascontiguousarray(x, np.float32).tobytes())
    return h.hexdigest()[:16]


# metrics snapshot of the most recent _timed scope (IA_BENCH_OBS=1 only):
# _obs_fields() folds it into the per-config result dict
_OBS_LAST = None
# resolved kernel-geometry provenance of the most recent _timed scope
# (tune/resolve.py); rides every per-config dict so a bench number is
# never separated from the geometry it measured
_TUNE_LAST = None


def _timed(fn, reps=3):
    """Warm once (compile), time ``reps`` runs, return
    (last result, min, median) — the ONE timing methodology every config
    uses.  The PJRT tunnel on this box shows +-35% run-to-run wall-clock
    variance on IDENTICAL compiled programs (measured round 3: 7.5 s and
    11.3 s for the same north-star binary within the hour), so a single
    draw measures the infrastructure's mood, not the program.  The
    MINIMUM (the schedulable floor, same provenance rule as the cached
    oracle numbers — experiments/oracle_1024.py) is the headline; the
    MEDIAN rides along so the draw spread is visible (round-3 VERDICT
    item 4).

    IA_BENCH_OBS=1 opens an obs run scope around warm-up + reps (the
    engine's internal run_scope joins it) and stashes the metrics
    snapshot for `_obs_fields` — compile accounting and peak HBM ride
    the bench JSON.  Off by default: the obs-active shims add per-call
    program-key work, and the headline timings must not carry it."""
    global _OBS_LAST, _TUNE_LAST
    _OBS_LAST = None
    import contextlib

    from image_analogies_tpu.tune import resolve as tune_resolve

    scope = contextlib.nullcontext(None)
    if os.environ.get("IA_BENCH_OBS"):
        from image_analogies_tpu.config import AnalogyParams
        from image_analogies_tpu.obs import trace as obs_trace

        scope = obs_trace.run_scope(AnalogyParams(metrics=True))
    tune_resolve.reset_provenance()
    with scope as ctx:
        fn()  # compile warm-up
        times = []
        for _ in range(reps):
            t0 = time.perf_counter()
            res = fn()
            times.append(time.perf_counter() - t0)
        if ctx is not None:
            _OBS_LAST = ctx.registry.snapshot()
    _TUNE_LAST = tune_resolve.provenance_snapshot()
    return res, min(times), float(np.median(times))


def _obs_fields():
    """Per-config obs + tune fold: compile.count/ms/cache_hits and peak
    HBM per device from the most recent `_timed` scope (IA_BENCH_OBS=1;
    empty when obs was off), plus the resolved kernel-geometry configs
    and their store-hit/fallback origins (always on — host-side dicts,
    free)."""
    out = {}
    if _TUNE_LAST:
        from image_analogies_tpu.tune import resolve as tune_resolve
        cfgs = sorted(_TUNE_LAST.values(), key=lambda c: c["key"])
        origins = sorted({o for c in cfgs for o in c["origin"].values()})
        out["tune"] = {**tune_resolve.manifest_info(),
                       "origins": origins, "configs": cfgs}
    if _OBS_LAST is None:
        return out
    c = _OBS_LAST.get("counters", {})
    g = _OBS_LAST.get("gauges", {})
    obs = {
        "compile_count": int(c.get("compile.count", 0)),
        "compile_cache_hits": int(c.get("compile.cache_hits", 0)),
        "compile_ms": round(float(c.get("compile.ms", 0.0)), 1),
    }
    hbm = {k.split("hbm.peak_bytes.", 1)[1]: int(v)
           for k, v in g.items() if k.startswith("hbm.peak_bytes.")}
    if hbm:
        obs["peak_hbm_bytes"] = dict(sorted(hbm.items()))
    out["obs"] = obs
    return out


def _measure_obs_overhead(a, ap, b, p, reps=3):
    """Wall-clock cost of live observability at one 256^2 synthesis:
    min-of-``reps`` with a metrics-bearing run scope active (every
    engine call site resolves + writes through the ambient ObsScope)
    vs min-of-``reps`` with ``metrics=False`` (the scoped fast path —
    one module-bool check per call).  Returns the headline
    ``obs_overhead_pct`` plus both raw floors; gated by ``ia bench
    --check`` in percentage points (see check_regression)."""
    from image_analogies_tpu.models.analogy import create_image_analogy
    from image_analogies_tpu.obs import trace as obs_trace

    p_off = p.replace(metrics=False, log_path=None)
    p_on = p.replace(metrics=True, log_path=None)
    create_image_analogy(a, ap, b, p_off)  # shared compile warm-up
    off = on = float("inf")
    for _ in range(reps):
        t0 = time.perf_counter()
        create_image_analogy(a, ap, b, p_off)
        off = min(off, time.perf_counter() - t0)
    for _ in range(reps):
        t0 = time.perf_counter()
        with obs_trace.run_scope(p_on):
            create_image_analogy(a, ap, b, p_on)
        on = min(on, time.perf_counter() - t0)
    return {
        "obs_overhead_pct": round((on - off) / off * 100.0, 2),
        "instrumented_s": round(on, 3),
        "disabled_s": round(off, 3),
        "reps": reps,
    }


def _measure_timeline_overhead(a, ap, b, p, reps=3):
    """Wall-clock cost of the ARMED temporal plane at one 256^2
    synthesis.  Both arms carry a metrics-bearing run scope — the obs
    cost itself is already gated by ``obs_overhead_pct``; this isolates
    what the timeline adds ON TOP: an armed process :class:`Timeline`
    with a live background sampler folding registry snapshots into
    windows mid-synthesis.  Headline ``timeline_overhead_pct`` rides
    the archive and ``ia bench --check`` gates it in percentage points
    (legacy archives carry no floor, so the first point records only).
    """
    from image_analogies_tpu.models.analogy import create_image_analogy
    from image_analogies_tpu.obs import timeline as obs_timeline
    from image_analogies_tpu.obs import trace as obs_trace

    p_on = p.replace(metrics=True, log_path=None)
    create_image_analogy(a, ap, b, p_on)  # shared compile warm-up
    disarmed = armed = float("inf")
    for _ in range(reps):
        t0 = time.perf_counter()
        with obs_trace.run_scope(p_on):
            create_image_analogy(a, ap, b, p_on)
        disarmed = min(disarmed, time.perf_counter() - t0)
    for _ in range(reps):
        tl = obs_timeline.arm()
        # an aggressive sampler interval keeps the armed arm honest:
        # several snapshot folds land inside every synthesis
        tl.start_sampler(interval_s=0.05)
        try:
            t0 = time.perf_counter()
            with obs_trace.run_scope(p_on):
                create_image_analogy(a, ap, b, p_on)
            armed = min(armed, time.perf_counter() - t0)
        finally:
            obs_timeline.disarm()
    return {
        "timeline_overhead_pct": round(
            (armed - disarmed) / disarmed * 100.0, 2),
        "armed_s": round(armed, 3),
        "disarmed_s": round(disarmed, 3),
        "reps": reps,
    }


def _measure_ledger_overhead(a, ap, b, p, reps=3):
    """Wall-clock cost of the ARMED tenant metering plane at one 256^2
    served request.  The ledger lives on the serve dispatch path (cost
    vectors + space-saving tenant tracking per completion), so both
    arms go through a real :class:`Server` — ``cfg.ledger`` is the only
    difference.  Headline ``ledger_overhead_pct`` rides the archive and
    ``ia bench --check`` gates it in percentage points (legacy archives
    carry no floor, so the first point records only)."""
    from image_analogies_tpu.serve.server import Server
    from image_analogies_tpu.serve.types import ServeConfig

    p_srv = p.replace(metrics=False, log_path=None)
    best = {}
    for armed in (False, True):
        cfg = ServeConfig(params=p_srv, workers=1, ledger=armed,
                          cost_persist=False)
        t_best = float("inf")
        with Server(cfg) as srv:
            srv.submit(a, ap, b).result(timeout=600)  # compile warm-up
            for _ in range(reps):
                t0 = time.perf_counter()
                srv.submit(a, ap, b).result(timeout=600)
                t_best = min(t_best, time.perf_counter() - t0)
        best[armed] = t_best
    return {
        "ledger_overhead_pct": round(
            (best[True] - best[False]) / best[False] * 100.0, 2),
        "armed_s": round(best[True], 3),
        "disarmed_s": round(best[False], 3),
        "reps": reps,
    }


def _measure_archive_overhead(a, ap, b, p, reps=3):
    """Wall-clock cost of the ARMED durable telemetry archive at one
    256^2 synthesis.  Both arms run an armed timeline with a live
    background sampler (that cost is already gated by
    ``timeline_overhead_pct``); this isolates what the archive adds ON
    TOP: the timeline feeder sealing closed windows, anomaly hints and
    tenant snapshots into append-only segments mid-synthesis (the
    sample throttle is zeroed so every sampler tick writes).  Headline
    ``archive_overhead_pct`` rides the archive and ``ia bench --check``
    gates it in percentage points (legacy archives carry no floor, so
    the first point records only)."""
    import tempfile

    from image_analogies_tpu.models.analogy import create_image_analogy
    from image_analogies_tpu.obs import archive as obs_archive
    from image_analogies_tpu.obs import timeline as obs_timeline
    from image_analogies_tpu.obs import trace as obs_trace

    p_on = p.replace(metrics=True, log_path=None)
    create_image_analogy(a, ap, b, p_on)  # shared compile warm-up
    best = {}
    with tempfile.TemporaryDirectory() as d:
        for armed in (False, True):
            t_best = float("inf")
            for rep in range(reps):
                tl = obs_timeline.arm()
                if armed:
                    obs_archive.arm(root=os.path.join(d, str(rep)),
                                    sample_interval_s=0.0)
                tl.start_sampler(interval_s=0.05)
                try:
                    t0 = time.perf_counter()
                    with obs_trace.run_scope(p_on):
                        create_image_analogy(a, ap, b, p_on)
                    t_best = min(t_best, time.perf_counter() - t0)
                finally:
                    if armed:
                        obs_archive.disarm()
                    obs_timeline.disarm()
            best[armed] = t_best
    return {
        "archive_overhead_pct": round(
            (best[True] - best[False]) / best[False] * 100.0, 2),
        "armed_s": round(best[True], 3),
        "disarmed_s": round(best[False], 3),
        "reps": reps,
    }


def measure_cold_start(size=256, levels=3, seed=7):
    """Catalog cold-start point (`ia bench`'s ``cold_start_ms``).

    Two first-requests for the same style on the CPU oracle path (the
    backend that consults the catalog): COLD — empty catalog, the
    request builds + seals every level's features in-line; WARM — the
    memory tiers are dropped (a fresh process joining the fleet) but the
    sealed disk entries survive, so the request resolves through disk
    and skips every feature build.  The headline ``cold_start_ms`` is
    the catalog-WARM first-request wall-clock — the number the tiered
    catalog exists to keep low — and the run refuses to report one
    whose output drifted from the cold build (``bit_identical`` gates).

    ``size``/``levels`` are parameters so tier-1 can run the identical
    methodology at toy scale; the bench runs the 256^2 oil geometry.
    """
    import tempfile

    from image_analogies_tpu.catalog import tiers as catalog_tiers
    from image_analogies_tpu.config import AnalogyParams
    from image_analogies_tpu.models.analogy import create_image_analogy

    a, ap, b = make_structured(size, seed)
    catalog_tiers.clear()
    try:
        with tempfile.TemporaryDirectory() as d:
            p = AnalogyParams(levels=levels, kappa=5.0, backend="cpu",
                              catalog_dir=d)
            t0 = time.perf_counter()
            res_cold = create_image_analogy(a, ap, b, p)
            cold_ms = (time.perf_counter() - t0) * 1e3
            # fresh-process shape: memory tiers dropped, disk retained
            catalog_tiers.clear()
            t0 = time.perf_counter()
            res_warm = create_image_analogy(a, ap, b, p)
            warm_ms = (time.perf_counter() - t0) * 1e3
    finally:
        catalog_tiers.clear()
        catalog_tiers.configure(None)
    return {
        "cold_start_ms": round(warm_ms, 1),
        "cold_first_ms": round(cold_ms, 1),
        "warm_first_ms": round(warm_ms, 1),
        "saved_ms": round(cold_ms - warm_ms, 1),
        "bit_identical": bool(np.array_equal(np.asarray(res_cold.bp),
                                             np.asarray(res_warm.bp))),
        "size": size,
        "levels": levels,
    }


def measure_handoff_recovery(size=64, levels=2, seed=7):
    """Fleet handoff-recovery point (`ia bench`'s ``handoff_recovery_ms``).

    A 2-worker SUBPROCESS fleet (each worker a real OS process on its
    own loopback port — serve/transport.py): one request warms the home
    worker and lands a ``done`` journal record, a second request for the
    same exemplar is admitted mid-batch-window, then the home child is
    SIGKILLed.  The headline is kill -> the REPLACEMENT process (spawned
    on the SAME journal dir, foreign stale lock swept, incomplete entry
    replayed) resolving the stranded future — jax import, journal
    recovery, and the replayed synthesis all inside the measured
    window, because that IS what failover costs.  The run refuses to
    report a number whose replayed answer drifted from a direct engine
    run (``bit_identical`` gates).

    ``size``/``levels`` are parameters so tier-1 can run the identical
    methodology at toy scale; the bench runs 64^2.
    """
    import signal
    import tempfile

    from image_analogies_tpu.config import AnalogyParams
    from image_analogies_tpu.models.analogy import create_image_analogy
    from image_analogies_tpu.serve.fleet import Fleet
    from image_analogies_tpu.serve.types import FleetConfig, ServeConfig

    a, ap, b = make_structured(size, seed)
    # second target on the SAME exemplar: identical batch key -> same
    # home worker as the warm request
    b2 = np.ascontiguousarray(b[::-1])
    params = AnalogyParams(levels=levels, kappa=5.0, backend="cpu")
    baseline = np.asarray(create_image_analogy(a, ap, b2, params).bp)

    with tempfile.TemporaryDirectory() as tmp:
        scfg = ServeConfig(params=params, queue_depth=8,
                           batch_window_ms=2000.0, max_batch=2,
                           workers=1, cost_persist=False,
                           journal_fsync=False)
        fcfg = FleetConfig(serve=scfg, size=2, vnodes=16,
                           journal_root=os.path.join(tmp, "journals"),
                           transport="subprocess",
                           health_interval_s=0.05, death_checks=2,
                           backoff_s=0.01, backoff_cap_s=0.05)
        with Fleet(fcfg) as fl:
            # warm the home: computes, journals done, pins which worker
            # owns the exemplar's batch key
            fl.submit(a, ap, b, idempotency_key="bench-handoff-warm"
                      ).result(timeout=600)
            workers = fl.health()["workers"]
            home = next(w for w, info in sorted(workers.items())
                        if (info.get("journal") or {}).get("done", 0))
            victim_pid = workers[home]["pid"]
            fut = fl.submit(a, ap, b2,
                            idempotency_key="bench-handoff-victim")
            # wait until the victim request is journaled (admitted: the
            # entry the replacement must replay), then kill
            end = time.monotonic() + 60.0
            while time.monotonic() < end:
                j = (fl.health()["workers"].get(home, {})
                     .get("journal") or {})
                if j.get("admitted", 0) >= 2:
                    break
                time.sleep(0.01)
            t0 = time.perf_counter()
            os.kill(victim_pid, signal.SIGKILL)
            res = fut.result(timeout=600)
            recovery_ms = (time.perf_counter() - t0) * 1e3
            post = fl.health()["workers"].get(home, {})
    return {
        "handoff_recovery_ms": round(recovery_ms, 1),
        "victim_pid": victim_pid,
        "replacement_pid": post.get("pid"),
        "replacement_generation": post.get("generation"),
        "stale_lock_swept": int((post.get("journal") or {})
                                .get("stale_lock_swept", 0)),
        "bit_identical": bool(np.array_equal(np.asarray(res.bp),
                                             baseline)),
        "size": size,
        "levels": levels,
    }


def measure_scale_up(size=48, levels=1, seed=7, burst=8):
    """Elastic scale-up point (`ia bench`'s ``scale_up_ms``).

    An inproc fleet floored at ONE worker under a declarative
    ControlPolicy (max 2, single pressure window, tight reconcile
    cadence): a burst of distinct-style requests overruns
    ``queue_high``, the control plane's reconcile loop spawns a second
    worker, joins it to the ring, and (with a catalog armed) warm-stages
    its share.  The headline is burst-admit -> the joined worker
    reporting ready in fleet health — detection latency, spawn, and
    ring join all inside the measured window, because that IS what an
    elastic scale-up costs.  The run refuses to report a number whose
    burst answers drifted from direct engine runs (``bit_identical``
    gates), and fails loudly if the control plane never scaled.

    ``size``/``levels``/``burst`` are parameters so tier-1 can run the
    identical methodology at toy scale; the bench runs 48^2 x 8.
    """
    from image_analogies_tpu.config import AnalogyParams
    from image_analogies_tpu.models.analogy import create_image_analogy
    from image_analogies_tpu.serve.fleet import Fleet
    from image_analogies_tpu.serve.policy import ControlPolicy
    from image_analogies_tpu.serve.types import FleetConfig, ServeConfig

    a, ap, b = make_structured(size, seed)
    # one exemplar pair per request: distinct styles = distinct batch
    # keys, so the consistent-hash ring actually spreads the burst over
    # the grown fleet instead of pinning it to one home worker
    styles = [(np.ascontiguousarray(np.roll(a, i + 1, axis=0)),
               np.ascontiguousarray(np.roll(ap, i + 1, axis=0)))
              for i in range(burst)]
    params = AnalogyParams(levels=levels, kappa=5.0, backend="cpu")
    baselines = [np.asarray(create_image_analogy(ai, api, b, params).bp)
                 for ai, api in styles]

    scfg = ServeConfig(params=params, queue_depth=64,
                       batch_window_ms=4.0, max_batch=2, workers=1,
                       cost_persist=False)
    policy = ControlPolicy(min_workers=1, max_workers=2,
                           queue_high=1.0, queue_low=0.1,
                           scale_up_windows=1, scale_down_windows=1000,
                           scale_up_cooldown_s=0.05,
                           scale_down_cooldown_s=600.0)
    fcfg = FleetConfig(serve=scfg, size=2, vnodes=16, policy=policy,
                       health_interval_s=0.05)
    with Fleet(fcfg) as fl:
        # warm compile on the floor worker so the measured window is
        # control-plane cost, not first-ever jit of the burst shape
        fl.submit(a, ap, b, idempotency_key="bench-scaleup-warm"
                  ).result(timeout=600)
        t0 = time.perf_counter()
        futs = [fl.submit(ai, api, b,
                          idempotency_key=f"bench-scaleup-{i}")
                for i, (ai, api) in enumerate(styles)]
        scale_ms = None
        end = time.monotonic() + 120.0
        while time.monotonic() < end:
            h = fl.health()
            ready = sum(1 for w in h["workers"].values()
                        if w.get("ok") and w.get("ready"))
            if h["size"] >= 2 and ready >= 2:
                scale_ms = (time.perf_counter() - t0) * 1e3
                break
            time.sleep(0.002)
        results = [np.asarray(f.result(timeout=600).bp) for f in futs]
        status = fl.health()["control"]
    if scale_ms is None:
        raise SystemExit("control plane never scaled up under burst — "
                         "refusing to record scale_up_ms")
    return {
        "scale_up_ms": round(scale_ms, 1),
        "burst": burst,
        "last_verdict": (status.get("last_verdict") or {}).get("verdict"),
        "control_events": status.get("events"),
        "bit_identical": all(
            np.array_equal(r, bl) for r, bl in zip(results, baselines)),
        "size": size,
        "levels": levels,
    }


def measure_soak():
    """Full-profile soak point (`ia bench`'s ``soak_p999_ms`` /
    ``soak_loss``).

    Replays the canonical full ``TraceSpec`` (240 requests, diurnal +
    two flash crowds, mixed session kinds) against an autoscaling
    inproc fleet with the default chaos plan armed throughout —
    periodic worker kills, catalog tier evictions, a torn archive
    segment, injected hop latency.  The end-of-run invariant gate
    (zero-loss accounting, audit bit-identity, journal bounds, chaos
    reconciliation, ...) must be GREEN before a number is recorded: a
    red gate refuses via SystemExit, naming the failing verdicts, so
    the archive only ever carries headlines from runs that survived
    their own chaos.
    """
    from image_analogies_tpu.soak import driver as soak_driver
    from image_analogies_tpu.soak import trace as soak_trace

    res = soak_driver.run(soak_trace.full_spec())
    if not res["ok"]:
        failing = [v["name"] for v in res["verdicts"] if not v["ok"]]
        raise SystemExit(
            "soak gate failed (%s) — refusing to record soak_p999_ms"
            % ", ".join(failing))
    facts = res["facts"]
    return {
        "soak_p999_ms": res["p999_ms"],
        "soak_loss": res["loss"],
        "requests": facts["submitted"],
        "answered": facts["answered"],
        "kills": len(facts["kills"]),
        "handoffs": len(facts["handoffs"]),
        "injected": sum(st.get("injected", 0)
                        for st in facts["sites"].values()),
        "wall_s": facts["wall_s"],
    }


def measure_exemplar_scaling(size=64, levels=2, seed=7,
                             scales=(1, 4, 16), reps=2):
    """Exemplar-DB scaling point (`ia bench --exemplar-scale`).

    Times the SAME synthesis request against exemplar DBs of 1x/4x/16x
    the rows with the two-stage ANN matcher armed — the configuration
    ISSUE 13's sub-linear promise is about.  The geometry isolates the
    scaled variable: B (the query load) is a full ``size``^2 plane and
    stays FIXED across scales, while the base exemplar is a half-height
    ``size/2 x size`` crop tiled vertically — so the 1x point already
    carries the full per-query work (coherence, slab re-score, scan
    machinery) and the only thing growing 16x is the DB the prefilter
    ranks.  Reports seconds and s-per-Mrow per scale plus the headline
    ``exemplar_scale_ratio`` = t(max scale) / t(1x); `ia bench --check`
    gates that ratio both against the archive floor and absolutely (16x
    the rows must cost under 8x the wall-clock, or the matcher has
    degraded to linear).

    Runs under ``ann_gate_bypass`` — the parity gate's audit probe is a
    correctness mechanism measured elsewhere (the tie-audit); paying it
    inside a timing loop would charge the matcher for the audit.

    ``size``/``levels``/``scales`` are parameters so tier-1 can run the
    identical methodology at toy scale; the bench default is 64^2 with
    a 2-level pyramid (the largest scale already tiles the exemplar to
    512 x 64 — bigger bases cross the multi-GB feature-DB line this
    box's tunnel cannot stream at 16x).
    """
    from image_analogies_tpu.backends import tpu as _tpu
    from image_analogies_tpu.config import AnalogyParams
    from image_analogies_tpu.models.analogy import create_image_analogy

    a, ap, b = make_structured(size, seed)
    p = AnalogyParams(levels=levels, kappa=5.0, backend="tpu",
                      strategy="wavefront", ann_prefilter=True)
    base_h = max(size // 2, 4 * p.patch_size)
    a, ap = a[:base_h], ap[:base_h]
    points = []
    with _tpu.ann_gate_bypass():
        for s in scales:
            at = np.tile(a, (int(s), 1))
            apt = np.tile(ap, (int(s), 1))
            run = lambda: create_image_analogy(at, apt, b, p)
            run()  # compile warmup outside timing (per-scale shapes)
            best = float("inf")
            for _ in range(max(int(reps), 1)):
                t0 = time.perf_counter()
                run()
                best = min(best, time.perf_counter() - t0)
            rows = ((at.shape[0] - p.patch_size + 1)
                    * (at.shape[1] - p.patch_size + 1))
            points.append({"scale": int(s), "rows": int(rows),
                           "wall_s": round(best, 3),
                           "s_per_mrow": round(best / (rows / 1e6), 4)})
    ratio = points[-1]["wall_s"] / max(points[0]["wall_s"], 1e-9)
    return {
        "exemplar_scale_ratio": round(ratio, 2),
        "max_scale": int(scales[-1]),
        "points": points,
        "size": size,
        "levels": levels,
    }


def _min_cpu(fn, reps=2):
    """Live-oracle floor: min wall-clock over ``reps`` CPU draws (round-3
    review: a single slow CPU draw against a best-of-N TPU time would
    inflate the speedup)."""
    best_s, best = float("inf"), None
    for _ in range(reps):
        t0 = time.perf_counter()
        out = fn()
        dt = time.perf_counter() - t0
        if dt < best_s:
            best_s, best = dt, out
    return best, best_s


def _run_tpu(a, ap, b, params, keep_levels=False, reps=3):
    """`_timed` over the library entry.  ``keep_levels`` (the tie-audit's
    per-level plane capture) is INSTRUMENTATION, not synthesis: on this
    box's ~9 MB/s tunnel its extra plane fetches cost ~0.5 s/run, so the
    timed reps run without it and one final UNTIMED run captures the
    audit planes — the synthesis is deterministic, so they are the same
    planes the timed runs computed."""
    from image_analogies_tpu.models.analogy import create_image_analogy

    res, t_min, t_med = _timed(
        lambda: create_image_analogy(a, ap, b, params), reps)
    timing = dict(getattr(res, "timing", None) or {})
    if keep_levels:
        res = create_image_analogy(a, ap, b, params, keep_levels=True)
        # report the TIMED reps' pipeline accounting, not the untimed
        # instrumentation run's (keep_levels disables donation)
        res.timing = timing
    return res, t_min, t_med


def bench_batched(k: int, size: int = 256, levels: int = 2,
                  reps: int = 3) -> int:
    """`ia bench --batch K`: batched B-axis engine throughput point.

    Synthesizes K same-shape B' planes twice — sequentially (K singleton
    engine runs, the bit-identity reference) and through
    batch/engine.py's single vmapped launch — and prints ONE JSON line
    whose headline ``value`` is the batched MARGINAL per-lane wall-clock
    (batched seconds / K, min-of-reps).  Lower is better, so the number
    rides the same `ia bench --check` trajectory gate as the north star:
    the metric string leads with the ``batched_qps`` key, giving the
    sentry a distinct metric family (a batched point never gates against
    a 1024^2 singleton point).  Raw lanes-per-second rides along as
    ``qps``.

    The run refuses to report a throughput win that broke correctness:
    ``bit_identical`` compares every batched member against its
    sequential singleton, and a False fails the command (exit 1) —
    a fast wrong engine must not record a trajectory point.
    """
    from image_analogies_tpu.batch.engine import create_image_analogy_batch
    from image_analogies_tpu.config import AnalogyParams
    from image_analogies_tpu.models.analogy import create_image_analogy

    import jax

    dev = jax.devices()[0].device_kind
    a, ap, _ = make_structured(size)
    # distinct targets per lane: identical B planes would let a broken
    # lane-broadcast masquerade as a working batch
    targets = [make_structured(size, 11 + i)[2] for i in range(k)]
    # batched strategy (the throughput path); remap off — per-member
    # luminance remap diverges the shared A/A' DB and the engine refuses
    p = AnalogyParams(levels=levels, kappa=5.0, backend="tpu",
                      strategy="batched", level_sync=False,
                      remap_luminance=False)

    seq_res, seq_s, seq_med = _timed(
        lambda: [create_image_analogy(a, ap, b, p) for b in targets], reps)
    bat_res, bat_s, bat_med = _timed(
        lambda: create_image_analogy_batch(a, ap, targets, p), reps)

    errors = [r for r in bat_res if isinstance(r, Exception)]
    identical = not errors and all(
        np.array_equal(np.asarray(s.bp), np.asarray(r.bp))
        for s, r in zip(seq_res, bat_res))
    print(json.dumps({
        "metric": f"batched_qps marginal per-lane wall-clock, "
                  f"k={k} x {size}^2 B', {levels}-level pyramid, "
                  f"batched strategy on {dev}",
        "value": round(bat_s / k, 4),
        "value_median": round(bat_med / k, 4),
        "unit": "s/lane",
        "qps": round(k / bat_s, 3),
        "k": k,
        "batched_s": round(bat_s, 3),
        "sequential_s": round(seq_s, 3),
        "sequential_s_median": round(seq_med, 3),
        "batch_speedup": round(seq_s / bat_s, 2),
        "bit_identical": bool(identical),
        "lane_errors": len(errors),
        **_obs_fields(),
    }), flush=True)
    return 0 if identical else 1


def main() -> int:
    import jax

    from image_analogies_tpu.config import AnalogyParams
    from image_analogies_tpu.models.analogy import create_image_analogy
    from image_analogies_tpu.utils.ssim import ssim

    dev = jax.devices()[0].device_kind
    configs = {}

    from image_analogies_tpu.utils.parity import (
        audit_source_map_mismatches,
    )

    def _parity_fields(res, o_bp, o_smap):
        diff = np.abs(res.bp_y - o_bp)
        return {
            "ssim_vs_oracle": round(ssim(res.bp_y, o_bp), 4),
            "value_match": round(float((res.bp_y == o_bp).mean()), 4),
            "output_mae": round(float(diff.mean()), 6),
            "source_map_mismatch": round(float(
                (res.source_map != o_smap).mean()), 6),
        }

    def _audit_fields(a, ap, b, p, res, oracle_levels):
        """Tie-audit (utils/parity.py): mechanically classify every
        mismatched pick; `mismatch_explained_by_ties` target is 1.0."""
        audit = audit_source_map_mismatches(a, ap, b, p, res.levels,
                                            oracle_levels)
        return {
            "mismatch_explained_by_ties":
                audit["mismatch_explained_by_ties"],
            "mismatch_classes": {
                k: audit[k] for k in ("mismatches", "ctx_diverged",
                                      "tie_exact", "tie_fp",
                                      "kappa_boundary", "unexplained")},
            "first_divergence_is_tie": audit["first_divergence_is_tie"],
        }

    # IA_BENCH_CONFIGS can name a comma-set of the oracle configs to run
    # during development (the north star always runs — it carries the
    # headline JSON); the driver's plain invocation runs everything.
    only = os.environ.get("IA_BENCH_CONFIGS")
    only = set(only.split(",")) if only else None

    def want(name):
        return only is None or name in only

    # ---- config 2 (oil filter, 256^2, 3 levels): LIVE oracle ----
    a, ap, b = make_structured(256)
    p = AnalogyParams(levels=3, kappa=5.0, backend="tpu",
                      strategy="wavefront", level_sync=False)
    if want("oil_256"):
        res_tpu, tpu_s, tpu_s_med = _run_tpu(a, ap, b, p, keep_levels=True)
        # the live oracle gets the same min-of-N floor treatment as the
        # TPU side (review round 3: a single slow CPU draw against a
        # best-of-3 TPU time would inflate the speedup)
        cpu_s = float("inf")
        for _ in range(2):
            t0 = time.perf_counter()
            res_cpu = create_image_analogy(a, ap, b,
                                           p.replace(backend="cpu"),
                                           keep_levels=True)
            cpu_s = min(cpu_s, time.perf_counter() - t0)
        configs["oil_256"] = {
            "tpu_s": round(tpu_s, 3),
            "tpu_s_median": round(tpu_s_med, 3),
            "cpu_oracle_s": round(cpu_s, 1),
            "speedup": round(cpu_s / tpu_s, 1),
            **_parity_fields(res_tpu, res_cpu.bp_y, res_cpu.source_map),
            **_audit_fields(a, ap, b, p, res_tpu, res_cpu.levels),
            "oracle": "live",
            **_obs_fields(),
        }

    # ---- obs overhead (PR 11): scoped-observability cost at 256^2 —
    # measured on the oil config's inputs (already built above) so the
    # number tracks a real synthesis, not a microbenchmark
    obs_overhead = _measure_obs_overhead(a, ap, b, p)
    configs["obs_overhead_256"] = obs_overhead

    # ---- timeline overhead (PR 14): armed temporal plane (windowed
    # store + background sampler) vs disarmed, both under a live run
    # scope — what `ia top`'s always-on cockpit costs at 256^2
    timeline_overhead = _measure_timeline_overhead(a, ap, b, p)
    configs["timeline_overhead_256"] = timeline_overhead

    # ---- ledger overhead (PR 16): armed tenant metering plane (cost
    # vectors + heavy-hitter tracking) vs disarmed through a real
    # Server — what per-request attribution costs at 256^2
    ledger_overhead = _measure_ledger_overhead(a, ap, b, p)
    configs["ledger_overhead_256"] = ledger_overhead

    # ---- archive overhead (PR 17): armed durable telemetry archive
    # (sealed append-only segments fed by the timeline sampler) vs the
    # same armed timeline without it — what the flight recorder costs
    archive_overhead = _measure_archive_overhead(a, ap, b, p)
    configs["archive_overhead_256"] = archive_overhead

    # ---- tail-quantile honesty (PR 17): the DDSketch selftest at 10^6
    # lognormal samples, whole-stream vs split-and-merged; it RAISES if
    # p99/p999/p9999 drift past the stated relative error, so a bench
    # that prints a number is itself the proof the sketch is honest
    from image_analogies_tpu.obs import quantiles as obs_quantiles
    sketch_honesty = obs_quantiles.selftest(n=1_000_000)
    configs["sketch_honesty_1e6"] = sketch_honesty

    # ---- catalog cold start (PR 12): first-request wall at 256^2 with
    # a warm exemplar catalog vs an empty one, on the CPU path the
    # catalog serves; bit-identity between the two runs gates the number
    cold_start = measure_cold_start()
    configs["cold_start_256"] = cold_start
    if not cold_start["bit_identical"]:
        raise SystemExit("catalog-warm first request drifted from the "
                         "cold build — refusing to record cold_start_ms")

    # ---- exemplar scaling (PR 13): two-stage ANN wall-clock at 1x/4x/
    # 16x the exemplar rows; the headline ratio rides the archive and
    # `--check` gates it (relative floor + absolute sub-linearity)
    exemplar_scale = measure_exemplar_scaling()
    configs["exemplar_scale_64"] = exemplar_scale

    # ---- fleet handoff recovery (PR 15): SIGKILL a subprocess worker
    # mid-request; the headline is kill -> the replacement answering on
    # the SAME journal dir at 64^2 (spawn + lock sweep + replay, the
    # full failover cost); bit-identity of the replayed answer gates
    handoff = measure_handoff_recovery()
    configs["handoff_recovery_64"] = handoff
    if not handoff["bit_identical"]:
        raise SystemExit("replayed handoff answer drifted from a direct "
                         "engine run — refusing to record "
                         "handoff_recovery_ms")

    # ---- elastic scale-up (PR 19): a burst overruns the declarative
    # policy's pressure threshold; the headline is burst-admit -> the
    # control plane's spawned worker joined and ready; bit-identity of
    # every burst answer gates
    scale_up = measure_scale_up()
    configs["scale_up_48"] = scale_up
    if not scale_up["bit_identical"]:
        raise SystemExit("burst answers under autoscale drifted from "
                         "direct engine runs — refusing to record "
                         "scale_up_ms")

    # ---- soak (PR 20): the full seeded trace against an autoscaling
    # fleet with chaos armed throughout; measure_soak refuses via
    # SystemExit on a red invariant gate, so the recorded p99.9/loss
    # always come from a run that survived its own chaos
    soak = measure_soak()
    configs["soak_240"] = soak

    # ---- configs 1/3/5 (BASELINE.json:7-12): texture-by-numbers,
    # super-res kappa sweep, batched video — live oracles at native sizes
    # (round-4 VERDICT item 6: the driver artifact must substantiate all
    # five eval configs, not just oil + north star).
    def _plane(res):
        return res.bp if getattr(res, "bp", None) is not None \
            and np.asarray(res.bp).ndim == 3 else res.bp_y

    def _pair_fields(res_t, res_c, t_min, t_med, cpu_s):
        pt, pc = np.asarray(_plane(res_t)), np.asarray(_plane(res_c))
        return {
            "tpu_s": round(t_min, 3),
            "tpu_s_median": round(t_med, 3),
            "cpu_oracle_s": round(cpu_s, 1),
            "speedup": round(cpu_s / t_min, 1),
            "ssim_vs_oracle": round(ssim(pt, pc), 4),
            "value_match": round(float((pt == pc).mean()), 4),
            "output_mae": round(float(np.abs(pt - pc).mean()), 6),
            "oracle": "live",
            **_obs_fields(),
        }

    if want("tbn_256") or want("superres_192") or want("video_256"):
        import tempfile

        from examples.make_assets import make_all
        from image_analogies_tpu.config import PRESETS
        from image_analogies_tpu.utils.imageio import load_image

        assets = {}
        # asset building gated per SELECTED config: each make_all draws
        # the full asset family (pyramid blurs + PNG encodes, seconds per
        # size), so a --configs subset must not pay for sizes or asset
        # groups only unselected configs read
        names_256 = ()
        if want("tbn_256"):
            names_256 += ("tbn_labels_a", "tbn_texture", "tbn_labels_b")
        if want("video_256"):
            names_256 += tuple(f"video_f{t}" for t in range(3)) + (
                "filter_a", "filter_ap")
        if names_256:
            with tempfile.TemporaryDirectory() as d:
                make_all(d, size=256, seed=7)
                for name in names_256:
                    assets[name] = load_image(
                        os.path.join(d, f"{name}.png"))
        if want("superres_192"):
            with tempfile.TemporaryDirectory() as d:
                # super-res runs at 192^2: BASELINE.json:10 pins patches
                # (7x7) and the kappa sweep but no size, and the 256^2
                # cKDTree oracle on 147-dim rows alone blew a 25-minute
                # bench budget (measured round 5) — 192^2 keeps the leg a
                # few minutes
                make_all(d, size=192, seed=7)
                for name in ("sr_sharp", "sr_low"):
                    assets[name] = load_image(
                        os.path.join(d, f"{name}.png"))

    if want("tbn_256"):
        # config 1: texture-by-numbers 256^2, single-scale, 5x5 patches
        p = PRESETS["texture_by_numbers"].replace(backend="tpu")
        args_t = (assets["tbn_labels_a"], assets["tbn_texture"],
                  assets["tbn_labels_b"])
        res_t, t_min, t_med = _timed(
            lambda: create_image_analogy(*args_t, p))
        res_c, cpu_s = _min_cpu(
            lambda: create_image_analogy(*args_t,
                                         p.replace(backend="cpu")),
            reps=1)  # (one ~40 s draw; see the module docstring's
        #                live-oracle budget note)
        configs["tbn_256"] = _pair_fields(res_t, res_c, t_min, t_med,
                                          cpu_s)

    if want("superres_192"):
        # config 3: super-resolution analogy, 7x7 patches, kappa sweep
        from image_analogies_tpu.models.modes import blur_for_superres

        sharp, low = assets["sr_sharp"], assets["sr_low"]
        blurred = blur_for_superres(sharp)
        sweep = {}
        for kappa in (0.5, 2.0, 5.0):
            p = PRESETS["super_resolution"].replace(backend="tpu",
                                                    kappa=kappa)
            args_s = (blurred, sharp, low)
            res_t, t_min, t_med = _timed(
                lambda: create_image_analogy(*args_s, p))
            # reps=1: three kappa legs already give the sweep three
            # independent oracle draws of the same geometry
            res_c, cpu_s = _min_cpu(
                lambda: create_image_analogy(*args_s,
                                             p.replace(backend="cpu")),
                reps=1)
            sweep[f"kappa_{kappa}"] = _pair_fields(
                res_t, res_c, t_min, t_med, cpu_s)
        configs["superres_192"] = sweep

    if want("video_256"):
        # config 5: batched video B-frames, temporal term, two_phase (the
        # frame-parallel scheme data_shards>1 shards over the mesh; one
        # chip here, so the sharded path is covered by dryrun_multichip).
        # 3 frames x 2 levels keeps the leg's LIVE oracle within the
        # driver's bench budget (4 x 3-level measured 4.08 s TPU vs a
        # 324.7 s oracle = 80x — committed in
        # bench_cache/bench_full_r05_builder.json); levels=2 matches the
        # golden video config.
        from image_analogies_tpu.models.video import video_analogy

        frames = [assets[f"video_f{t}"] for t in range(3)]
        p = PRESETS["video"].replace(backend="tpu", levels=2)
        res_t, t_min, t_med = _timed(
            lambda: video_analogy(assets["filter_a"], assets["filter_ap"],
                                  frames, p, scheme="two_phase"))
        res_c, cpu_s = _min_cpu(
            lambda: video_analogy(assets["filter_a"], assets["filter_ap"],
                                  frames, p.replace(backend="cpu"),
                                  scheme="two_phase"), reps=1)
        # (reps=1: the two-phase video oracle is the priciest CPU run in
        # the bench — a second draw would double multi-minute wall for a
        # floor the other configs already establish)
        ft = [np.asarray(f, np.float32) for f in res_t.frames]
        fc = [np.asarray(f, np.float32) for f in res_c.frames]
        configs["video_256"] = {
            "tpu_s": round(t_min, 3),
            "tpu_s_median": round(t_med, 3),
            "cpu_oracle_s": round(cpu_s, 1),
            "speedup": round(cpu_s / t_min, 1),
            "frames": len(ft),
            "ssim_vs_oracle_min": round(
                min(ssim(t, c) for t, c in zip(ft, fc)), 4),
            "value_match_mean": round(float(np.mean(
                [(t == c).mean() for t, c in zip(ft, fc)])), 4),
            "oracle": "live",
            **_obs_fields(),
        }

    # ---- north star (1024^2, 5 levels): every cached oracle seed ----
    # seed 7 is the historic headline; additional seeds (13) make the
    # at-scale parity claim n>=2 (round-2 VERDICT weak item 2).  The TPU
    # run is re-timed per seed (same compiled program, different inputs).
    cache = os.path.join(_HERE, "bench_cache")
    import glob as _glob

    seed_jsons = _glob.glob(os.path.join(cache, "oracle_1024_seed*.json"))
    if not seed_jsons:  # legacy single-seed cache layout (seed 7)
        legacy = os.path.join(cache, "oracle_1024.json")
        if not os.path.exists(legacy):
            raise SystemExit("no cached 1024^2 oracle; run "
                             "experiments/oracle_1024.py first")
        seed_jsons = [legacy]
    ocfgs = []
    for sj in seed_jsons:
        with open(sj) as f:
            ocfgs.append(json.load(f))
    # deterministic order: historic seed 7 is the headline, then by seed
    ocfgs.sort(key=lambda c: (c["config"]["seed"] != 7,
                              c["config"]["seed"]))
    ns_headline = None
    for ocfg in ocfgs:
        seed = ocfg["config"]["seed"]
        oz = np.load(os.path.join(cache, f"oracle_1024_seed{seed}.npz"))
        a, ap, b = make_structured(ocfg["config"]["size"], seed)
        if "input_digest" in ocfg:
            got = input_digest(a, ap, b)
            if got != ocfg["input_digest"]:
                raise SystemExit(
                    f"bench inputs drifted from cached oracle seed {seed} "
                    f"({got} != {ocfg['input_digest']}): re-run "
                    "experiments/oracle_1024.py before benching")
        p = AnalogyParams(levels=ocfg["config"]["levels"],
                          kappa=ocfg["config"]["kappa"], backend="tpu",
                          strategy="wavefront", level_sync=False)
        # min-of-5 on the headline config: the tunnel's run-to-run
        # variance (±35% under load, a few percent on a quiet box — see
        # _run_tpu's docstring) makes a deeper rep pool cheap insurance
        # for the reported floor; five ~6.5 s reps cost little
        res_ns, ns_s, ns_s_med = _run_tpu(a, ap, b, p, keep_levels=True,
                                          reps=5)
        oracle_s = float(ocfg["wall_s"])
        timing = getattr(res_ns, "timing", None) or {}
        rec = {
            "tpu_s": round(ns_s, 3),
            "tpu_s_median": round(ns_s_med, 3),
            "cpu_oracle_s": oracle_s,
            "speedup": round(oracle_s / ns_s, 1),
            # inter-level host time of the last timed rep — the number
            # the async pipeline exists to hide (gated by `ia bench
            # --check` against the archive floor)
            "host_gap_ms": round(float(timing.get("host_gap_ms", 0.0)), 1),
            **_parity_fields(res_ns, oz["bp_y"], oz["source_map"]),
            "oracle": f"cached seed {seed} (experiments/oracle_1024.py)",
            **_obs_fields(),
        }
        if "s_l0" in oz.files:  # level planes present -> full tie-audit
            n_lv = ocfg["config"]["levels"]
            o_levels = [(oz[f"bp_l{i}"], oz[f"s_l{i}"])
                        for i in range(n_lv)]
            rec.update(_audit_fields(a, ap, b, p, res_ns, o_levels))
        configs[f"north_star_1024_seed{seed}"] = rec
        if ns_headline is None:
            ns_headline = (ns_s, ns_s_med, oracle_s, rec)
    ns_s, ns_s_med, oracle_s, ns_rec = ns_headline
    ns_ssim = ns_rec["ssim_vs_oracle"]
    ns_match = ns_rec["value_match"]

    # The JSON below is bench.py's ONLY output on either stream: rounds
    # 3/4 printed a parity note to stderr AFTER the JSON and the driver's
    # capture (which appends captured stderr after stdout) recorded
    # "parsed": null every round (round-4 VERDICT weak item 2).  The note
    # carried nothing the JSON's `configs` doesn't; emitting nothing else
    # keeps the JSON parseable under every capture model (last-line,
    # whole-stdout, merged-fd).
    print(json.dumps({
        "metric": "1024x1024 B' synthesis wall-clock, 5-level pyramid, "
                  "kappa=5 (north-star config), wavefront oracle-parity "
                  f"strategy on {dev}",
        "value": round(ns_s, 3),
        "value_median": round(ns_s_med, 3),
        "unit": "s",
        "host_gap_ms": ns_rec["host_gap_ms"],
        "obs_overhead_pct": obs_overhead["obs_overhead_pct"],
        "cold_start_ms": cold_start["cold_start_ms"],
        "exemplar_scale_ratio": exemplar_scale["exemplar_scale_ratio"],
        "timeline_overhead_pct":
            timeline_overhead["timeline_overhead_pct"],
        "handoff_recovery_ms": handoff["handoff_recovery_ms"],
        "scale_up_ms": scale_up["scale_up_ms"],
        "soak_p999_ms": soak["soak_p999_ms"],
        "soak_loss": soak["soak_loss"],
        "ledger_overhead_pct": ledger_overhead["ledger_overhead_pct"],
        "archive_overhead_pct":
            archive_overhead["archive_overhead_pct"],
        "sketch_p999_rel_err": sketch_honesty["p999_rel_err"],
        "vs_baseline": round(oracle_s / ns_s, 1),
        "ssim_vs_oracle": round(ns_ssim, 4),
        "value_match": round(ns_match, 4),
        "configs": configs,
    }), flush=True)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
