"""Batched B-axis synthesis engine (ROADMAP direction 4).

The serial driver (`models.analogy`) runs one B plane per coarse-to-fine
loop: every request re-walks the level loop, re-enqueues one device
program per level, and pays the full launch overhead alone.  For serve
workloads the common case is k small same-shape targets against ONE
exemplar pair — the A/A' feature DB, the level schedule, and the
compiled programs are all shared; only the query planes differ.  This
engine stacks those k query planes on a leading lane axis and runs the
EXACT singleton scan vmapped over lanes (`backends.tpu._run_lanes`):
one compiled program, one devcache upload of the A/A' DB, one driver
loop, k results.

Correctness contract — the non-negotiable invariant every test gates:
each batched member is **bit-identical** to its sequential singleton
run.  That holds because nothing about a lane's computation changes:
per-lane `build_features` runs the identical jitted prep program on the
identical inputs (so `static_q` is bitwise the singleton's), the A/A'
arrays are preflighted bitwise-equal across members, and `jax.vmap`
adds a batch dimension without reassociating the per-lane arithmetic.
Anything that WOULD diverge refuses the batch instead
(:class:`BatchIncompatible`), and the caller falls back to the
sequential path — refusal reasons ride the
``batch.fallback_sequential.<reason>`` counter so operators can see why
batching isn't engaging:

  level_retries     §5.3 retries rebuild one member's level; a shared
                    launch cannot re-run one lane
  sharded           data_shards > 1 composes with the mesh wavefront,
                    not the lane axis
  cpu_backend       params.backend == "cpu" is the NumPy oracle — not
                    vmappable (backend "tpu" under JAX_PLATFORMS=cpu IS
                    supported; the XLA programs compile anywhere)
  unsupported       strategy/feature outside the lanes runner
                    (exact/rowwise probes, checkpoints, profiling)
  shape_mismatch    members disagree on shape where sharing needs
                    equality (wavefront lanes, unbucketed batched)
  mixed_bucket      bucketed members land in different query buckets at
                    some level
  remap_divergence  remap_luminance couples the A/A' DB to each
                    member's B stats and the members' stats differ
  pad_waste         a member's finest-level query pad exceeds the tuned
                    ceiling (tune.resolve.batch_pad_waste_pct) — dead
                    padded rows cost real FLOPs in every scan row
  degrade_divergence (serve-layer) members' degrade plans differ; the
                    worker refuses before calling the engine

Query-side bucketing (tune/buckets.py) is what lets same-bucket members
with DIFFERENT real row counts share the one program: each lane's scan
bound rides its own traced ``dims_b`` leaf, padded query rows are never
read (padding honesty is by construction — the row loop bound is the
real hb), and results are cropped back to each member's real shape on
exit.  The pad-waste ceiling keeps the shared-program win from losing
to dead-row compute on pathological just-past-a-bucket-edge shapes.

Lane-fault isolation: a chaos/device fault in ONE lane's host-side
dispatch (`engine.batch` site, `build_features`) marks that member
failed and duplicates a live lane's query plane in its slot — k stays
shape-stable so the compiled program is reused — and the other k-1
members complete bit-identically.  The engine returns a mixed list
(AnalogyResult | Exception per member) so callers re-dispatch only the
failed members.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Sequence

import numpy as np

from image_analogies_tpu import chaos
from image_analogies_tpu.backends import get_backend
from image_analogies_tpu.backends.base import LevelJob
from image_analogies_tpu.config import AnalogyParams
from image_analogies_tpu.models.analogy import (AnalogyResult,
                                                _finalize_stats,
                                                _prep_planes,
                                                create_image_analogy)
from image_analogies_tpu.obs import device as obs_device
from image_analogies_tpu.obs import metrics as obs_metrics
from image_analogies_tpu.obs import trace as obs_trace
from image_analogies_tpu.ops import color
from image_analogies_tpu.ops.features import spec_for_level
from image_analogies_tpu.ops.pyramid import build_pyramid_np, num_feasible_levels
from image_analogies_tpu.tune import buckets as tune_buckets
from image_analogies_tpu.utils import logging as ialog


class BatchIncompatible(RuntimeError):
    """This batch cannot share one device program; run members
    sequentially.  ``reason`` is the counter label (see module
    docstring for the vocabulary)."""

    def __init__(self, reason: str, detail: str = ""):
        self.reason = reason
        super().__init__(f"batch incompatible ({reason})"
                         + (f": {detail}" if detail else ""))


def _refuse(reason: str, detail: str = "") -> None:
    obs_metrics.inc(f"batch.fallback_sequential.{reason}")
    raise BatchIncompatible(reason, detail)


def create_image_analogy_batch(
    a: np.ndarray,
    ap: np.ndarray,
    targets: Sequence[np.ndarray],
    params: AnalogyParams = AnalogyParams(),
    backend=None,
) -> List[Any]:
    """Synthesize B'_i for every B_i in ``targets`` against one (A, A')
    pair, sharing one compiled program and one driver loop per level.

    Returns a list the length of ``targets`` holding AnalogyResult for
    members that completed and the Exception for members whose lane
    faulted (per-lane isolation; see module docstring).  Raises
    :class:`BatchIncompatible` when the batch as a whole cannot take
    the shared path — callers fall back to sequential singletons.
    """
    targets = list(targets)
    if not targets:
        return []
    if len(targets) == 1:
        # A 1-batch IS the sequential path; delegating keeps the jit
        # cache warm for real singletons instead of tracing a k=1 twin.
        try:
            return [create_image_analogy(a, ap, targets[0], params,
                                         backend=backend)]
        except Exception as e:  # uniform per-member fault contract
            return [e]

    from image_analogies_tpu.tune import resolve as tune_resolve
    from image_analogies_tpu.tune import warmup as tune_warmup

    tune_warmup.apply_runtime_config(params)
    with obs_trace.run_scope(params,
                             manifest_extra=tune_resolve.manifest_info()):
        with tune_resolve.pin_scope():
            return _run_batch(a, ap, targets, params, backend)


def _effective_strategy(params: AnalogyParams) -> str:
    # mirrors TpuMatcher.build_features: auto resolves to wavefront
    return "wavefront" if params.strategy == "auto" else params.strategy


def _preflight(a, ap, targets, params):
    """Refuse anything that would break single-program sharing or the
    bit-identity contract.  Returns the per-member prepped planes."""
    if params.level_retries > 0:
        _refuse("level_retries", "per-member level retries cannot re-run "
                "one lane of a shared launch")
    if params.data_shards > 1:
        _refuse("sharded", "data_shards composes with the mesh wavefront, "
                "not the lane axis")
    if params.backend != "tpu":
        _refuse("cpu_backend", "the NumPy oracle backend is not vmappable")
    strategy = _effective_strategy(params)
    if strategy not in ("wavefront", "batched"):
        _refuse("unsupported", f"strategy {strategy!r} has no lanes runner")
    if (params.checkpoint_dir or params.save_levels_dir
            or params.profile_dir or params.resume_from_level is not None):
        _refuse("unsupported", "checkpoint/save-levels/profile runs need "
                "the sequential driver")

    preps = []
    try:
        for b in targets:
            preps.append(_prep_planes(a, ap, b, params))
    except ValueError as e:
        _refuse("shape_mismatch", str(e))
    # remap_luminance couples the A/A' DB to each member's B stats
    # (Hertzmann §3.4): lanes share lane 0's DB, so every member must
    # have prepped bitwise-identical A planes.  Compared unconditionally
    # — any A-side divergence, whatever its cause, breaks sharing.
    a0_src, _, a0_filt = preps[0][0], preps[0][1], preps[0][2]
    for p in preps[1:]:
        if not (np.array_equal(a0_src, p[0]) and np.array_equal(a0_filt,
                                                                p[2])):
            _refuse("remap_divergence", "members' luminance stats remap "
                    "the A/A' DB differently; batch with "
                    "remap_luminance=False or identical-stats targets")
    return preps, strategy


def _check_level_shapes(b_pyrs, strategy, params, levels):
    """Per-level shape compatibility across members; returns the finest
    -level max pad-waste fraction (0.0 when unbucketed)."""
    bucketed = (strategy == "batched"
                and tune_buckets.buckets_enabled(params))
    waste = 0.0
    for level in range(levels):
        shapes = [p[level].shape[:2] for p in b_pyrs]
        if not bucketed:
            if any(sh != shapes[0] for sh in shapes[1:]):
                _refuse("shape_mismatch",
                        f"level {level} B shapes {shapes} must be "
                        "identical for the "
                        + ("wavefront" if strategy == "wavefront"
                           else "unbucketed") + " lanes runner")
            continue
        if any(sh[1] != shapes[0][1] for sh in shapes[1:]):
            # wb is the dynamic_slice width in the row-query gather — a
            # STATIC program constant that bucketing cannot absorb.
            _refuse("shape_mismatch",
                    f"level {level} B widths {[sh[1] for sh in shapes]} "
                    "must be identical (wb is static)")
        bks = [tune_buckets.bucket_rows(h * w) for h, w in shapes]
        if any(bk != bks[0] for bk in bks[1:]):
            _refuse("mixed_bucket",
                    f"level {level} query buckets {bks} diverge")
        if level == 0:
            # Waste gate at the FINEST level only: level sizes shrink
            # geometrically, so the finest level dominates the dead-row
            # FLOPs the ceiling protects against.
            waste = max(tune_buckets.pad_waste_frac(h * w, bks[0])
                        for h, w in shapes)
    return waste


def _finalize_lane(bp_dev, s_dev, stats, params, ap_rgb, b_yiq):
    """Per-lane tail of the sequential driver: fetch the deferred device
    scalars fused with the finest plane, then reconstruct color exactly
    as `models.analogy._create_image_analogy` does (same ops, same
    order — the fetch moves bits, it never computes)."""
    need_s_host = params.color_mode == "source_rgb"
    dev = [(st, k) for st in stats for k in ("_n_coh", "_n_ref")
           if k in st and not isinstance(st[k], (int, float, np.number))]
    if dev:
        import jax
        import jax.numpy as jnp

        with obs_trace.span("fetch"):
            bundle = (jnp.stack([st[k] for st, k in dev]), bp_dev) + (
                (s_dev,) if need_s_host else ())
            got = jax.device_get(bundle)
        vals, bp_fetched = got[0], got[1]
        for (st, k), v in zip(dev, vals):
            st[k] = float(v)
        bp_y = np.asarray(bp_fetched, np.float32)
        s_raw = np.asarray(got[2], np.int32) if need_s_host else s_dev
        obs_metrics.inc("fetch.bytes", int(vals.nbytes) + int(bp_y.nbytes))
    else:
        bp_y = np.asarray(bp_dev, np.float32)
        s_raw = np.asarray(s_dev, np.int32) if need_s_host else s_dev
    for st in stats:
        _finalize_stats(st)
        ialog.emit(st, params.log_path)
    if obs_metrics._ACTIVE:
        for st in stats:
            cr, px = st.get("coherence_ratio"), st.get("pixels", 0)
            if cr is not None and px:
                obs_metrics.inc("kappa.coherence_px", cr * px)
                obs_metrics.inc("kappa.total_px", px)
    if params.color_mode == "source_rgb":
        ap_flat = ap_rgb.reshape(-1, ap_rgb.shape[-1]) if ap_rgb.ndim == 3 \
            else ap_rgb.reshape(-1)
        out = ap_flat[np.asarray(s_raw, np.int32).reshape(-1)].reshape(
            bp_y.shape + (() if ap_rgb.ndim == 2 else (ap_rgb.shape[-1],)))
    elif b_yiq is not None:
        out = color.yiq2rgb(
            np.stack([bp_y, b_yiq[..., 1], b_yiq[..., 2]], axis=-1))
    else:
        out = np.clip(bp_y, 0.0, 1.0)
    return AnalogyResult(bp=out, bp_y=bp_y, source_map_raw=s_raw,
                         stats=stats, levels=None, timing={})


def _run_batch(a, ap, targets, params, backend) -> List[Any]:
    preps, strategy = _preflight(a, ap, targets, params)
    k = len(targets)
    backend = backend or get_backend(params)
    if not hasattr(backend, "synthesize_level_lanes"):
        _refuse("unsupported",
                f"backend {type(backend).__name__} has no lanes runner")

    # A-side planes are bitwise-equal across members (preflighted), so
    # member 0's pyramids serve every lane; query pyramids are per-lane.
    a_src, _, a_filt, ap_rgb, _ = preps[0]
    min_shapes = [(min(a_src.shape[0], p[1].shape[0]),
                   min(a_src.shape[1], p[1].shape[1])) for p in preps]
    levels_per = [num_feasible_levels(ms, params.levels, params.patch_size)
                  for ms in min_shapes]
    if any(lv != levels_per[0] for lv in levels_per[1:]):
        _refuse("shape_mismatch",
                f"members disagree on feasible levels {levels_per}")
    levels = levels_per[0]

    a_src_pyr = build_pyramid_np(a_src, levels)
    a_filt_pyr = build_pyramid_np(a_filt, levels)
    b_pyrs = [build_pyramid_np(p[1], levels) for p in preps]
    src_channels = 1 if a_src.ndim == 2 else a_src.shape[-1]

    waste = _check_level_shapes(b_pyrs, strategy, params, levels)
    if waste > 0.0:
        from image_analogies_tpu.tune import resolve as tune_resolve

        h0, w0 = b_pyrs[0][0].shape[:2]
        ceiling = tune_resolve.batch_pad_waste_pct(
            strategy=strategy, n_rows=h0 * w0) / 100.0
        if waste > ceiling:
            _refuse("pad_waste",
                    f"finest-level pad waste {waste:.0%} exceeds the "
                    f"tuned ceiling {ceiling:.0%} (IA_BATCH_PAD_WASTE)")
    obs_metrics.inc("batch.launches")
    obs_metrics.inc("batch.lanes", k)
    obs_metrics.set_gauge("batch.pad_waste_frac", waste)

    failed: List[Optional[Exception]] = [None] * k
    bp_pyr = [[None] * levels for _ in range(k)]
    s_pyr = [[None] * levels for _ in range(k)]
    stats: List[List[Dict[str, Any]]] = [[] for _ in range(k)]

    for level in range(levels - 1, -1, -1):  # coarsest -> finest
        with obs_trace.span("batch_level", level=level, lanes=k):
            spec = spec_for_level(params, level, levels, src_channels)
            jobs: List[Optional[LevelJob]] = [None] * k
            dbs: List[Any] = [None] * k
            for i in range(k):
                if failed[i] is not None:
                    continue
                job = LevelJob(
                    level=level,
                    spec=spec,
                    kappa_mult=params.kappa_factor(level) ** 2,
                    a_src=a_src_pyr[level],
                    a_filt=a_filt_pyr[level],
                    b_src=b_pyrs[i][level],
                    a_src_coarse=(a_src_pyr[level + 1]
                                  if level + 1 < levels else None),
                    a_filt_coarse=(a_filt_pyr[level + 1]
                                   if level + 1 < levels else None),
                    b_src_coarse=(b_pyrs[i][level + 1]
                                  if level + 1 < levels else None),
                    b_filt_coarse=(bp_pyr[i][level + 1]
                                   if level + 1 < levels else None),
                    # lanes>0 read lane 0's DB buffers; donation would
                    # free them under the other lanes' feet
                    donate=False,
                )
                try:
                    # per-lane fault boundary: the chaos site and the
                    # host-side feature dispatch are where one lane can
                    # die without taking the launch down
                    chaos.site("engine.batch", lane=i, level=level)
                    dbs[i] = backend.build_features(job)
                    jobs[i] = job
                except Exception as e:
                    failed[i] = e
                    obs_metrics.inc("batch.lane_faults")
            live = [i for i in range(k) if failed[i] is None]
            if not live:
                break
            # dead lanes duplicate a live lane's query plane: k stays
            # shape-stable so the compiled program is reused, and the
            # duplicate lane's results are simply never read
            ref = live[0]
            run_dbs = [dbs[i] if dbs[i] is not None else dbs[ref]
                       for i in range(k)]
            run_jobs = [jobs[i] if jobs[i] is not None else jobs[ref]
                        for i in range(k)]
            try:
                outs = backend.synthesize_level_lanes(run_dbs, run_jobs)
            except Exception as e:
                # whole-launch fault: every live member failed together
                for i in live:
                    failed[i] = e
                break
            for i in live:
                bp, s, st = outs[i]
                bp_pyr[i][level], s_pyr[i][level] = bp, s
                stats[i].append(st)
            obs_device.record_hbm(level, params.log_path)

    results: List[Any] = [None] * k
    for i in range(k):
        if failed[i] is not None:
            results[i] = failed[i]
            continue
        results[i] = _finalize_lane(bp_pyr[i][0], s_pyr[i][0], stats[i],
                                    params, ap_rgb, preps[i][4])
        results[i].timing["lanes"] = float(k)
    return results
