"""Batched B-axis engine: one device program synthesizes k B' planes.

`create_image_analogy_batch` stacks k same-bucket targets on a leading
lane axis and drives the existing level programs through a vmapped twin
(`backends.tpu._run_lanes`), sharing ONE compiled program, one devcache
upload of the A/A' DB, and one coarse-to-fine driver loop per launch.
Every batched member is bit-identical to its sequential singleton run;
incompatible batches raise `BatchIncompatible` so callers (serve/) fall
back to the sequential path with the reason on a counter label.
"""

from image_analogies_tpu.batch.engine import (BatchIncompatible,
                                              create_image_analogy_batch)

__all__ = ["BatchIncompatible", "create_image_analogy_batch"]
