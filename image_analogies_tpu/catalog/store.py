"""Disk tier: sha256-sealed per-level feature artifacts.

Layout: ``<root>/<style>/<entry_key>.npz`` where ``style`` is the serve
batcher's exemplar sha1 and ``entry_key`` is the feature-content digest
(``tiers.feature_key``).  One artifact holds one stored
``build_features_np`` output — the (Na, F) feature DB and the flat A'
luminance — sealed by the checkpoint discipline (utils/checkpoint.py):
the checksum lives INSIDE the npz, integrity is checked before anything
is trusted, writes are tmp + ``os.replace`` atomic, and damaged entries
are quarantined as ``<entry>.npz.corrupt`` (``catalog.quarantined`` /
``catalog_quarantined``) so a rotten artifact costs at most a rebuild.
"""

from __future__ import annotations

import hashlib
import os
import zipfile
from typing import Dict, List, Optional, Tuple

import numpy as np

from image_analogies_tpu.obs import metrics as obs_metrics
from image_analogies_tpu.utils import checkpoint as ckpt


def style_dir(root: str, style: str) -> str:
    return os.path.join(root, style)


def entry_path(root: str, style: str, key: str) -> str:
    return os.path.join(root, style, f"{key}.npz")


def _entry_checksum(db: np.ndarray, a_filt_flat: np.ndarray,
                    key: str) -> str:
    """sha256 seal over both payload arrays (shape + dtype + bytes) AND
    the entry key: rot landing on the stored key field reads as damage,
    not as a different entry (same reasoning as checkpoint's seal)."""
    h = hashlib.sha256()
    for arr in (np.ascontiguousarray(db), np.ascontiguousarray(a_filt_flat)):
        h.update(repr((arr.shape, str(arr.dtype))).encode())
        h.update(arr.tobytes())
    h.update(key.encode())
    return h.hexdigest()[:32]


def save_entry(root: str, style: str, key: str, db: np.ndarray,
               a_filt_flat: np.ndarray) -> str:
    path = entry_path(root, style, key)
    os.makedirs(os.path.dirname(path), exist_ok=True)
    tmp = path + ".tmp.npz"
    np.savez(tmp, db=db, a_filt_flat=a_filt_flat, key=key,
             checksum=_entry_checksum(db, a_filt_flat, key))
    os.replace(tmp, path)
    obs_metrics.inc("catalog.disk.write_bytes", os.path.getsize(path))
    return path


def load_entry(root: str, style: str, key: str
               ) -> Optional[Tuple[np.ndarray, np.ndarray]]:
    """Returns (db, a_filt_flat) or None when missing or damaged.

    Damage (unreadable container, missing arrays, seal mismatch, stored
    key disagreeing with the filename's) quarantines the file as
    ``.corrupt`` and returns None — the caller falls through to a full
    rebuild, which is bit-identical by construction."""
    path = entry_path(root, style, key)
    if not os.path.exists(path):
        return None
    try:
        with np.load(path) as z:
            stored_key = str(z["key"])
            want = str(z["checksum"])
            got = _entry_checksum(z["db"], z["a_filt_flat"], stored_key)
            if want != got:
                raise ValueError(
                    f"catalog entry checksum mismatch at {path}")
            if stored_key != key:
                raise ValueError(
                    f"catalog entry key mismatch at {path}: "
                    f"stored {stored_key!r}")
            db = z["db"].astype(np.float32)
            a_filt_flat = z["a_filt_flat"].astype(np.float32)
    except (zipfile.BadZipFile, OSError, ValueError, KeyError, EOFError):
        ckpt.quarantine(path, counter="catalog.quarantined",
                        event="catalog_quarantined")
        return None
    return db, a_filt_flat


def list_styles(root: str) -> List[str]:
    """Style directories only: ``_``-prefixed siblings (the sealed ANN
    bases under ``_ann/``) are derived state, not styles."""
    if not root or not os.path.isdir(root):
        return []
    return sorted(d for d in os.listdir(root)
                  if os.path.isdir(os.path.join(root, d))
                  and not d.startswith("_"))


def list_entries(root: str, style: str) -> List[Tuple[str, int]]:
    """(entry_key, nbytes) pairs for one style, sorted by key."""
    d = style_dir(root, style)
    if not os.path.isdir(d):
        return []
    out = []
    for fn in sorted(os.listdir(d)):
        if fn.endswith(".npz") and not fn.endswith(".tmp.npz"):
            out.append((fn[:-4], os.path.getsize(os.path.join(d, fn))))
    return out


def stats(root: str) -> Dict[str, object]:
    """Catalog inventory for ``ia catalog inspect``."""
    styles = {}
    total_bytes = 0
    total_entries = 0
    corrupt = 0
    for style in list_styles(root):
        entries = list_entries(root, style)
        nbytes = sum(sz for _, sz in entries)
        d = style_dir(root, style)
        corrupt += sum(1 for fn in os.listdir(d) if fn.endswith(".corrupt"))
        styles[style] = {"entries": len(entries), "bytes": nbytes}
        total_bytes += nbytes
        total_entries += len(entries)
    return {"root": root, "styles": styles, "entries": total_entries,
            "bytes": total_bytes, "corrupt": corrupt}


def gc(root: str, *, keep: Optional[List[str]] = None,
       max_bytes: Optional[int] = None,
       purge_corrupt: bool = False) -> Dict[str, object]:
    """Prune the disk tier.

    ``keep`` exempts listed styles entirely; with ``max_bytes`` set the
    non-exempt entries are dropped oldest-mtime-first until the catalog
    fits.  Torn ``.tmp.npz`` leftovers always go; quarantined
    ``.corrupt`` files are evidence and only go with ``purge_corrupt``.
    """
    keep_set = set(keep or ())
    removed_entries = 0
    freed = 0
    candidates = []  # (mtime, path, size, style)
    for style in list_styles(root):
        d = style_dir(root, style)
        for fn in os.listdir(d):
            path = os.path.join(d, fn)
            if fn.endswith(".tmp.npz") or (
                    purge_corrupt and fn.endswith(".corrupt")):
                freed += os.path.getsize(path)
                os.remove(path)
                removed_entries += 1
            elif fn.endswith(".npz") and style not in keep_set:
                st = os.stat(path)
                candidates.append((st.st_mtime, path, st.st_size, style))
    if max_bytes is not None:
        total = sum(sz for _, _, sz, _ in candidates) + sum(
            sz for style in keep_set for _, sz in list_entries(root, style))
        for _, path, sz, _ in sorted(candidates):
            if total <= max_bytes:
                break
            os.remove(path)
            total -= sz
            freed += sz
            removed_entries += 1
    removed_styles = []
    for style in list_styles(root):
        d = style_dir(root, style)
        if not os.listdir(d):
            os.rmdir(d)
            removed_styles.append(style)
    obs_metrics.inc("catalog.gc_removed", removed_entries)
    return {"removed_entries": removed_entries,
            "removed_styles": removed_styles, "freed_bytes": freed}
