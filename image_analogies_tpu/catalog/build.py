"""Ahead-of-time catalog builds (`ia catalog build`).

Precompute one style's per-level feature pyramid and persist it as
sealed artifacts BEFORE traffic arrives, mirroring the driver's own prep
exactly (same ``_prep_planes`` → ``build_pyramid_np`` →
``spec_for_level`` → ``build_features_np`` chain), so the keys — and the
bytes — match what a request would have built.

Luminance-remap caveat (Hertzmann §3.4): with ``remap_luminance`` on,
the A planes are affinely remapped to the TARGET's luminance stats, so
an AOT build needs a ``target`` anchor to produce the entries requests
will actually resolve (video clips anchor every frame on frame 0, so
one build with ``target=frame0`` covers the whole clip).  Without a
target the style's own A plane anchors the remap — an exact identity
transform — which matches requests whose target shares A's stats, or
any config with the remap off.
"""

from __future__ import annotations

import time
from typing import Any, Dict, Optional

import numpy as np

from image_analogies_tpu.catalog import tiers
from image_analogies_tpu.obs import metrics as obs_metrics


def build_style(a, ap, params=None, *, root_dir: Optional[str] = None,
                target=None) -> Dict[str, Any]:
    """Build + persist every level of one style's feature pyramid.

    Returns {style, levels, entries: [{level, key, rows, ms}]}.  Engine
    and ops imports stay lazy so the catalog package imports on any
    host (and `build` itself never touches jax — these are the host
    NumPy builds)."""
    from image_analogies_tpu.config import AnalogyParams
    from image_analogies_tpu.models.analogy import _prep_planes
    from image_analogies_tpu.ops.features import (build_features_np,
                                                  spec_for_level)
    from image_analogies_tpu.ops.pyramid import (build_pyramid_np,
                                                 num_feasible_levels)

    params = params or AnalogyParams()
    a = np.asarray(a)
    ap = np.asarray(ap)
    style = tiers.style_key(a, ap)
    b = np.asarray(target) if target is not None else a
    a_src, b_src, a_filt, _, _ = _prep_planes(a, ap, b, params)
    min_shape = (min(a_src.shape[0], b_src.shape[0]),
                 min(a_src.shape[1], b_src.shape[1]))
    levels = num_feasible_levels(min_shape, params.levels, params.patch_size)
    a_src_pyr = build_pyramid_np(a_src, levels)
    a_filt_pyr = build_pyramid_np(a_filt, levels)
    src_channels = 1 if a_src.ndim == 2 else a_src.shape[-1]

    entries = []
    for level in range(levels - 1, -1, -1):
        spec = spec_for_level(params, level, levels, src_channels,
                              temporal=False)
        a_src_coarse = a_src_pyr[level + 1] if level + 1 < levels else None
        a_filt_coarse = a_filt_pyr[level + 1] if level + 1 < levels else None
        key = tiers.feature_key(spec, a_src_pyr[level], a_filt_pyr[level],
                                a_src_coarse, a_filt_coarse, None)
        t0 = time.perf_counter()
        db = build_features_np(spec, a_src_pyr[level], a_filt_pyr[level],
                               a_src_coarse, a_filt_coarse,
                               temporal_fine=None)
        ms = (time.perf_counter() - t0) * 1e3
        aff = np.asarray(a_filt_pyr[level], np.float32).reshape(-1)
        tiers.record_build(style, key, db, aff, build_ms=ms,
                           root_dir=root_dir)
        entry = {"level": level, "key": key,
                 "rows": int(db.shape[0]), "ms": ms}
        # Derived ANN state rides the build (ISSUE 13): seal the PCA
        # basis for this level's feature DB next to the entry so a
        # request with ann_prefilter on never pays the eigendecomposition
        # on the serving path.  numpy-only like the features themselves.
        r = root_dir or tiers.root()
        if r:
            from image_analogies_tpu.catalog import ann as _ann
            from image_analogies_tpu.tune import resolve as _tune_resolve

            mean, proj = _ann.build_projection(
                db, _tune_resolve.ann_proj_dims())
            _ann.save_artifact(r, key, mean, proj)
            obs_metrics.inc("ann.artifacts_built")
            entry["ann_dims"] = int(proj.shape[1])
        entries.append(entry)
    return {"style": style, "levels": levels, "entries": entries}
