"""Sealed ANN projection artifacts: the catalog-derived PCA bases.

The two-stage matcher's prefilter (ops/pallas_match.py) ranks DB rows in
a low-dimensional PCA subspace; the basis for one feature DB is DERIVED
state — recomputable from the stored feature bytes at any time — so it
lives beside the catalog entries under the same seal discipline
(store.py): checksum inside the npz, tmp + ``os.replace`` atomic writes,
damage quarantined as ``.corrupt`` (``ann.quarantined`` /
``ann_quarantined``) with the caller falling back to the bit-identical
exact path and rebuilding.

Layout is a flat ``<root>/_ann/<entry_key>.npz`` (no style directory:
the TPU backend resolves projections from the feature content key alone,
and one feature DB has exactly one deterministic basis regardless of
which style produced it).  The ``_ann`` prefix keeps these out of
``store.list_styles``'s style enumeration.

NumPy-only on purpose — the catalog package must import (and build
artifacts) on hosts with no accelerator stack at all.
"""

from __future__ import annotations

import hashlib
import os
import zipfile
from typing import Optional, Tuple

import numpy as np

from image_analogies_tpu.obs import metrics as obs_metrics
from image_analogies_tpu.utils import checkpoint as ckpt

ANN_DIR = "_ann"


def artifact_path(root: str, key: str) -> str:
    return os.path.join(root, ANN_DIR, f"{key}.npz")


def build_projection(db: np.ndarray, dims: int
                     ) -> Tuple[np.ndarray, np.ndarray]:
    """Deterministic PCA basis for one (N, F) feature DB.

    Returns ``(mean (F,), proj (F, Kp))`` with Kp = min(dims, F, N): the
    top-Kp eigenvectors of the centered covariance, eigh-based (symmetric
    F x F — cheap: F is ~30-250) so the result is reproducible across
    runs, with each column sign-normalized (largest-|.|. component made
    positive) to kill the residual sign ambiguity.  float64 accumulation,
    float32 out — rebuilding from the same bytes reproduces the same
    artifact bit-for-bit."""
    x = np.asarray(db, np.float64)
    n, f = x.shape
    kp = max(1, min(int(dims), f, n))
    mean = x.mean(axis=0)
    xc = x - mean[None, :]
    cov = xc.T @ xc
    _, vecs = np.linalg.eigh(cov)  # ascending eigenvalues
    proj = vecs[:, ::-1][:, :kp]
    flip = np.sign(proj[np.argmax(np.abs(proj), axis=0),
                        np.arange(kp)])
    flip = np.where(flip == 0, 1.0, flip)
    return (mean.astype(np.float32),
            (proj * flip[None, :]).astype(np.float32))


def _artifact_checksum(mean: np.ndarray, proj: np.ndarray,
                       key: str) -> str:
    """Same seal construction as store._entry_checksum: shape + dtype +
    bytes of both arrays AND the entry key, so rot on the stored key
    field reads as damage rather than as a different entry."""
    h = hashlib.sha256()
    for arr in (np.ascontiguousarray(mean), np.ascontiguousarray(proj)):
        h.update(repr((arr.shape, str(arr.dtype))).encode())
        h.update(arr.tobytes())
    h.update(key.encode())
    return h.hexdigest()[:32]


def save_artifact(root: str, key: str, mean: np.ndarray,
                  proj: np.ndarray) -> str:
    mean = np.asarray(mean, np.float32)
    proj = np.asarray(proj, np.float32)
    path = artifact_path(root, key)
    os.makedirs(os.path.dirname(path), exist_ok=True)
    tmp = path + ".tmp.npz"
    np.savez(tmp, mean=mean, proj=proj, key=key,
             checksum=_artifact_checksum(mean, proj, key))
    os.replace(tmp, path)
    obs_metrics.inc("ann.artifact_write_bytes", os.path.getsize(path))
    return path


def load_artifact(root: str, key: str
                  ) -> Optional[Tuple[np.ndarray, np.ndarray]]:
    """Returns (mean, proj) or None when missing or damaged.

    Damage (unreadable container, missing arrays, seal mismatch, stored
    key disagreeing with the filename's) quarantines the file as
    ``.corrupt`` (``ann.quarantined``) and returns None — the caller
    runs this request on the exact path (bit-identical by construction)
    and rebuilds the artifact from the feature bytes."""
    path = artifact_path(root, key)
    if not os.path.exists(path):
        return None
    try:
        with np.load(path) as z:
            stored_key = str(z["key"])
            want = str(z["checksum"])
            got = _artifact_checksum(z["mean"], z["proj"], stored_key)
            if want != got:
                raise ValueError(
                    f"ann artifact checksum mismatch at {path}")
            if stored_key != key:
                raise ValueError(
                    f"ann artifact key mismatch at {path}: "
                    f"stored {stored_key!r}")
            mean = z["mean"].astype(np.float32)
            proj = z["proj"].astype(np.float32)
    except (zipfile.BadZipFile, OSError, ValueError, KeyError, EOFError):
        ckpt.quarantine(path, counter="ann.quarantined",
                        event="ann_quarantined")
        return None
    return mean, proj


def damage_artifact(path: str, seed: int = 0) -> None:
    """Chaos helper (``match.prefilter`` corrupt directive): flip one
    byte of the sealed artifact in place, deterministically from
    ``seed``, so the next load fails its seal and quarantines."""
    if not os.path.exists(path):
        return
    size = os.path.getsize(path)
    if size == 0:
        return
    pos = int(np.random.RandomState(seed).randint(0, size))
    with open(path, "r+b") as f:
        f.seek(pos)
        b = f.read(1)
        f.seek(pos)
        f.write(bytes([b[0] ^ 0xFF]))
    obs_metrics.inc("ann.chaos_corruptions")
