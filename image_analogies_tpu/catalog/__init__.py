"""catalog/ — content-addressed multi-tenant exemplar catalog (ROADMAP item 4).

The Image Analogies engine treats the A/A' exemplar as a fixed ambient
input, but at catalog scale ("millions of users", thousands of styles) a
cold style pays the full per-level feature-pyramid build inside the
request path.  This package makes any style warm-by-construction:

- ``store``  — disk tier: per-style directories of sha256-sealed ``.npz``
  feature artifacts (checkpoint-style seal/quarantine: damaged entries go
  ``.corrupt``, never poison a load);
- ``tiers``  — the memory tiers and the tier-by-tier resolution a request
  walks: resident ("HBM") hit → host-RAM hit → disk load → full build,
  every path returning bit-identical features to a cold build (an entry
  IS a stored ``build_features_np`` output);
- ``build``  — ahead-of-time ``ia catalog build``: precompute and persist
  a style's per-level feature pyramid before traffic arrives.

Keying: a style is the SAME exemplar sha1 the serve batcher/router
already use (``serve.batcher.exemplar_digest``); one entry below it is a
content digest over (per-level FeatureSpec, post-prep A-side planes) —
with luminance remap on, the A planes depend on the target's stats, so
the sub-key captures exactly what the features were built from.

Like serve/ and chaos/, this package never imports jax at module scope
and never compiles device programs (grep-locked): device work stays behind the
backend boundary; the TPU backend's HBM residency is the devcache, which
the resident tier fronts.
"""

from image_analogies_tpu.catalog import build, store, tiers  # noqa: F401
