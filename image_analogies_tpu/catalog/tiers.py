"""Memory tiers + tier-by-tier resolution of exemplar features.

A request resolves a level's A-side features through the tier stack:

    resident ("HBM") hit → host-RAM hit → disk load → full build

- **resident tier** — a small count-capped LRU of consumer-ready
  :class:`Entry` handles (feature DB + flat A' luminance + a consumer
  scratch slot the CPU backend parks its KD-tree in).  On the TPU
  backend the actual HBM residency is the devcache (utils/devcache.py),
  which this tier fronts: a resident hit means the request-path feature
  build is skipped entirely.
- **host tier** — a byte-bounded LRU of decoded arrays between the
  resident tier and disk; ``ia catalog warm`` / fleet join pre-stage a
  worker's styles here before traffic arrives.
- **disk tier** — the sealed artifacts (store.py).

Every path returns the SAME bytes: an entry is a stored
``build_features_np`` output, so bit-identity to a cold build holds by
construction at every tier — a miss anywhere only costs time.

Chaos: the ``devcache.tier`` site fires at the top of every resolution;
its ``"corrupt"`` directive is applied as a mid-request eviction of the
key from BOTH memory tiers (counted in ``catalog.chaos_evictions``), so
the drill proves the fall-through recomputes bit-identically.

Configuration mirrors devcache: env ``IA_CATALOG_DIR`` /
``IA_CATALOG_HOST_BYTES`` win over the per-run ``AnalogyParams`` knobs
(``catalog_dir`` / ``catalog_host_bytes``, wired by
``tune.warmup.apply_runtime_config``).  Tiers are process-local and
survive across runs — that is the point: the second request for a
cataloged style finds warm tiers no matter which engine instance serves
it.
"""

from __future__ import annotations

import hashlib
import os
import threading
from collections import OrderedDict
from dataclasses import dataclass, field
from typing import Any, Dict, Optional, Tuple

import numpy as np

from image_analogies_tpu import chaos
from image_analogies_tpu.catalog import store
from image_analogies_tpu.obs import metrics as obs_metrics
from image_analogies_tpu.obs import trace as obs_trace

_RESIDENT_CAP = 32  # consumer-ready handles (per-level, so ~6 styles deep)
_DEFAULT_HOST_BYTES = 256 << 20

_LOCK = threading.Lock()
_resident: "OrderedDict[str, Entry]" = OrderedDict()
_host: "OrderedDict[str, Tuple[np.ndarray, np.ndarray, int]]" = OrderedDict()
_host_bytes = 0
_configured_root: Optional[str] = None
_configured_host: Optional[int] = None


@dataclass
class Entry:
    """A consumer-ready catalog entry (resident-tier handle)."""

    db: np.ndarray  # (Na, F) stored build_features_np output
    a_filt_flat: np.ndarray  # (Na,) flat A' luminance
    # Consumer scratch keyed by the consumer (the CPU backend parks its
    # cKDTree here so a resident hit skips index construction too).
    # Derived state only — never feeds the stored bytes.
    state: Dict[str, Any] = field(default_factory=dict)

    @property
    def nbytes(self) -> int:
        return int(self.db.nbytes) + int(self.a_filt_flat.nbytes)


@dataclass
class CatalogRef:
    """One level's catalog resolution, attached to LevelJob.a_features.

    ``entry`` is the tier hit (None = every tier missed); the backend
    that then builds cold calls :meth:`record` so every tier above
    fills and the next request skips the build."""

    style: str
    key: str
    entry: Optional[Entry]

    def record(self, db: np.ndarray, a_filt_flat: np.ndarray, *,
               build_ms: float = 0.0) -> Entry:
        self.entry = record_build(self.style, self.key, db, a_filt_flat,
                                  build_ms=build_ms)
        return self.entry


# ------------------------------------------------------------------
# configuration


def root() -> Optional[str]:
    """Effective catalog root: env IA_CATALOG_DIR > configured > None.
    Read at call time so operators can flip it on a live process."""
    env = os.environ.get("IA_CATALOG_DIR", "").strip()
    if env:
        return env
    return _configured_root


def host_budget() -> int:
    env = os.environ.get("IA_CATALOG_HOST_BYTES", "").strip()
    if env:
        try:
            n = int(env)
            if n > 0:
                return n
        except ValueError:
            pass
    if _configured_host:
        return _configured_host
    return _DEFAULT_HOST_BYTES


def configure(root_dir: Optional[str] = None,
              host_bytes: Optional[int] = None) -> None:
    """Per-run wiring (AnalogyParams.catalog_dir / catalog_host_bytes
    plumb here); None clears the configured value.  Env still wins.
    The tiers themselves are NOT dropped — warmth survives runs."""
    global _configured_root, _configured_host
    _configured_root = root_dir or None
    _configured_host = int(host_bytes) if host_bytes else None


def active() -> bool:
    """Catalog consultation is root-gated: no disk tier, no catalog."""
    return root() is not None


# ------------------------------------------------------------------
# keys


def style_key(a, ap) -> str:
    """The style identity: the SAME exemplar sha1 the serve batcher and
    router key on, so `ia catalog warm` and ring placement agree with
    where the traffic for this style actually lands."""
    from image_analogies_tpu.serve.batcher import exemplar_digest

    return exemplar_digest(np.asarray(a), np.asarray(ap))


def feature_key(spec, a_src, a_filt, a_src_coarse=None, a_filt_coarse=None,
                a_temporal=None) -> str:
    """Content digest of everything one level's A-side build consumes.

    The POST-prep planes go in (with luminance remap on they depend on
    the target's stats — Hertzmann §3.4), so a catalog entry can only
    resolve for a request that would have built the same bytes."""
    h = hashlib.sha1()
    h.update(repr(spec).encode())
    for arr in (a_src, a_filt, a_src_coarse, a_filt_coarse, a_temporal):
        if arr is None:
            h.update(b"-")
        else:
            x = np.ascontiguousarray(np.asarray(arr))
            h.update(str((x.shape, x.dtype)).encode())
            h.update(x.tobytes())
    return h.hexdigest()[:24]


def lookup(style: str, job) -> CatalogRef:
    """Resolve one LevelJob's A-side through the tiers (driver entry)."""
    key = feature_key(job.spec, job.a_src, job.a_filt, job.a_src_coarse,
                      job.a_filt_coarse, job.a_temporal)
    return CatalogRef(style, key, resolve(style, key, level=job.level))


# ------------------------------------------------------------------
# tier plumbing


def _gauges() -> None:
    obs_metrics.set_gauge("catalog.host.bytes", _host_bytes)
    obs_metrics.set_gauge("catalog.hbm.entries", len(_resident))


def _insert_resident(key: str, ent: Entry) -> None:
    evicted = 0
    with _LOCK:
        _resident[key] = ent
        _resident.move_to_end(key)
        while len(_resident) > _RESIDENT_CAP:
            _resident.popitem(last=False)
            evicted += 1
    for _ in range(evicted):
        obs_metrics.inc("catalog.hbm.evictions")
    _gauges()


def _insert_host(key: str, db: np.ndarray, aff: np.ndarray) -> None:
    global _host_bytes
    n = int(db.nbytes) + int(aff.nbytes)
    budget = host_budget()
    evicted = []
    with _LOCK:
        old = _host.pop(key, None)
        if old is not None:
            _host_bytes -= old[2]
        _host[key] = (db, aff, n)
        _host_bytes += n
        # keep at least the newest entry even when it alone exceeds the
        # budget (evicting it would thrash every request)
        while _host_bytes > budget and len(_host) > 1:
            _, (_, _, en) = _host.popitem(last=False)
            _host_bytes -= en
            evicted.append(en)
    for en in evicted:
        obs_metrics.inc("catalog.host.evictions")
        obs_metrics.inc("catalog.host.evicted_bytes", en)
    _gauges()


def evict(key: str) -> bool:
    """Drop ``key`` from BOTH memory tiers (chaos directive / operator).
    Disk entries stay — the next resolution falls through to them."""
    global _host_bytes
    hit = False
    with _LOCK:
        if _resident.pop(key, None) is not None:
            hit = True
        h = _host.pop(key, None)
        if h is not None:
            hit = True
            _host_bytes -= h[2]
    _gauges()
    return hit


def clear() -> None:
    """Drop all memory tiers (tests / operator reset).  Disk untouched."""
    global _host_bytes
    with _LOCK:
        _resident.clear()
        _host.clear()
        _host_bytes = 0
    _gauges()


def snapshot() -> Dict[str, Any]:
    with _LOCK:
        return {"root": root(), "resident_entries": len(_resident),
                "host_entries": len(_host), "host_bytes": _host_bytes,
                "host_budget": host_budget()}


# ------------------------------------------------------------------
# resolution


def resolve(style: str, key: str, *, level: int = -1) -> Optional[Entry]:
    """Tier-by-tier resolution; None means every tier missed and the
    caller builds cold (then records through :meth:`CatalogRef.record`).
    """
    directive = chaos.site("devcache.tier", style=style, level=level)
    if directive == "corrupt":
        # the "corrupt" directive doubles as the mid-request tier
        # eviction order: drop the key from both memory tiers NOW, so
        # the resolution below must recover through disk or a rebuild
        evict(key)
        obs_metrics.inc("catalog.chaos_evictions")
    with _LOCK:
        ent = _resident.get(key)
        if ent is not None:
            _resident.move_to_end(key)
    if ent is not None:
        obs_metrics.inc("catalog.hbm.hits")
        return ent
    obs_metrics.inc("catalog.hbm.misses")
    with _LOCK:
        hot = _host.get(key)
        if hot is not None:
            _host.move_to_end(key)
    if hot is not None:
        obs_metrics.inc("catalog.host.hits")
        ent = Entry(db=hot[0], a_filt_flat=hot[1])
        _insert_resident(key, ent)
        return ent
    obs_metrics.inc("catalog.host.misses")
    r = root()
    if r:
        got = store.load_entry(r, style, key)
        if got is not None:
            db, aff = got
            obs_metrics.inc("catalog.disk.hits")
            obs_metrics.inc("catalog.disk.read_bytes",
                            int(db.nbytes) + int(aff.nbytes))
            ent = Entry(db=db, a_filt_flat=aff)
            _insert_host(key, db, aff)
            _insert_resident(key, ent)
            return ent
    obs_metrics.inc("catalog.disk.misses")
    return None


def record_build(style: str, key: str, db: np.ndarray,
                 a_filt_flat: np.ndarray, *, build_ms: float = 0.0,
                 root_dir: Optional[str] = None) -> Entry:
    """Record a cold build: fill every tier (and persist a sealed
    artifact when a disk root is configured) so the NEXT resolution of
    this key is a hit.  ``build_ms`` feeds the cold-start histogram."""
    db = np.asarray(db, np.float32)
    aff = np.asarray(a_filt_flat, np.float32)
    ent = Entry(db=db, a_filt_flat=aff)
    _insert_host(key, db, aff)
    _insert_resident(key, ent)
    obs_metrics.inc("catalog.builds")
    obs_metrics.observe("catalog.cold_start_ms", build_ms)
    r = root_dir or root()
    if r:
        store.save_entry(r, style, key, db, aff)
    return ent


# ------------------------------------------------------------------
# prefetch / warm


def warm(style: str, *, root_dir: Optional[str] = None) -> Dict[str, int]:
    """Pre-stage one style's disk entries into the host tier (the `ia
    catalog warm` / fleet-join path).  Returns {entries, bytes} newly
    staged; already-warm entries are skipped."""
    r = root_dir or root()
    out = {"entries": 0, "bytes": 0}
    if not r:
        return out
    for key, _sz in store.list_entries(r, style):
        with _LOCK:
            present = key in _host or key in _resident
        if present:
            continue
        got = store.load_entry(r, style, key)
        if got is None:
            continue
        db, aff = got
        _insert_host(key, db, aff)
        out["entries"] += 1
        out["bytes"] += int(db.nbytes) + int(aff.nbytes)
        obs_metrics.inc("catalog.warmed")
    return out


def warm_for_fleet(router, *, root_dir: Optional[str] = None,
                   only_worker: Optional[str] = None) -> Dict[str, Any]:
    """Ring-placement-aware pre-staging (fleet join / `ia catalog warm`):
    for every cataloged style, ask the router which worker owns it
    (``home_for_style``) and stage its entries into host RAM.  In a
    single-process fleet all workers share one host tier, so everything
    warms; ``only_worker`` restricts to one worker's home styles (the
    multi-host shape, where each host stages only what it owns)."""
    r = root_dir or root()
    report: Dict[str, Any] = {"styles": 0, "entries": 0, "bytes": 0,
                              "placements": {}}
    if not r:
        return report
    for style in store.list_styles(r):
        home = getattr(router, "home_for_style", None)
        wid = home(style) if home is not None else None
        if only_worker is not None and wid != only_worker:
            continue
        got = warm(style, root_dir=r)
        report["styles"] += 1
        report["entries"] += got["entries"]
        report["bytes"] += got["bytes"]
        report["placements"][style] = wid
        obs_metrics.inc("catalog.prefetch.styles")
        obs_metrics.inc("catalog.prefetch.bytes", got["bytes"])
        obs_trace.emit_record({"event": "catalog_prefetch", "style": style,
                               "worker": wid or "",
                               "entries": got["entries"],
                               "bytes": got["bytes"]})
    return report
