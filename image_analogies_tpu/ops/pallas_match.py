"""Pallas TPU kernel: fused L2 distance + argmin over the patch database.

This is the framework's hot-path kernel (BASELINE.json:5: "the per-pixel
best-match ... runs as a Pallas kernel with the patch DB resident in HBM").
For a block of query feature vectors Q (M,F) and the DB (N,F) it computes

    idx[m]  = argmin_n ||db[n] - q[m]||^2      (ties -> lowest n)
    dist[m] = min_n    ||db[n] - q[m]||^2

without ever materializing the (M,N) distance matrix in HBM: the DB is tiled
(TILE_N, F) through VMEM by the Pallas pipeline (double-buffered DMA), each
tile's scores are one MXU matmul, and a running (min, argmin) lives in VMEM
scratch across the sequential TPU grid.

Distances use the matmul trick  ||db-q||^2 = ||db||^2 - 2 db.q + ||q||^2 with
fp32 accumulation; the ||q||^2 term is added outside the loop (it does not
affect the argmin).
"""

from __future__ import annotations

import functools
from typing import Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

_F32 = jnp.float32


def _round_up(x: int, m: int) -> int:
    return (x + m - 1) // m * m


def _argmin_kernel(q_ref, db_ref, dbn_ref, idx_out, val_out,
                   best_val, best_idx, *, tile_n: int, n_total: int,
                   precision):
    """One grid step: score one DB tile against all queries, fold into the
    running (min, argmin) scratch; write outputs on the last tile."""
    t = pl.program_id(0)

    @pl.when(t == 0)
    def _init():
        best_val[:] = jnp.full_like(best_val, jnp.inf)
        best_idx[:] = jnp.zeros_like(best_idx)

    # scores[m, n] = dbn[n] - 2 * q[m] . db[n]   (M, TILE_N) on the MXU.
    # Precision matters for fp32 inputs: the TPU MXU multiplies in bf16
    # passes, and the DEFAULT single pass gives ~1e-3 score error — enough to
    # flip argmin picks vs an exact fp32 re-score.  The wavefront (oracle
    # parity) strategy therefore runs this kernel at HIGHEST (3 bf16 passes,
    # fp32-grade scores, ~2x wall-clock); the approximate batched strategy
    # keeps the fast DEFAULT pass.  bf16 inputs are unaffected either way:
    # their single pass IS the operands' full precision.
    scores = dbn_ref[:] - 2.0 * jax.lax.dot_general(
        q_ref[:], db_ref[:],
        dimension_numbers=(((1,), (1,)), ((), ())),
        preferred_element_type=_F32,
        precision=precision,
    )
    # mask DB padding rows (global index >= n_total)
    col = jax.lax.broadcasted_iota(jnp.int32, scores.shape, 1)
    gidx = col + t * tile_n
    scores = jnp.where(gidx < n_total, scores, jnp.inf)

    part_val = jnp.min(scores, axis=1, keepdims=True)  # (M, 1)
    part_arg = jnp.argmin(scores, axis=1).astype(jnp.int32)[:, None]
    part_idx = part_arg + t * tile_n

    improve = part_val < best_val[:]  # strict: earlier tile wins ties
    best_idx[:] = jnp.where(improve, part_idx, best_idx[:])
    best_val[:] = jnp.where(improve, part_val, best_val[:])

    @pl.when(t == pl.num_programs(0) - 1)
    def _flush():
        idx_out[:] = best_idx[:]
        val_out[:] = best_val[:]


@functools.partial(jax.jit, static_argnames=("tile_n", "interpret", "bf16",
                                             "precision"))
def pallas_argmin_l2(
    queries: jax.Array,  # (M, F) fp32
    db: jax.Array,  # (N, F) fp32 or bf16
    db_sqnorm: jax.Array,  # (N,) fp32
    *,
    tile_n: int = 512,
    interpret: bool = False,
    bf16: bool = False,
    precision=jax.lax.Precision.DEFAULT,
) -> Tuple[jax.Array, jax.Array]:
    """Fused argmin kernel.  Returns (idx (M,) int32, sqdist (M,) fp32).

    Shapes are padded to TPU tiles internally (F -> mult of 128, M -> mult of
    8, N -> mult of tile_n); padded DB rows can never win (masked to +inf),
    padded query rows are discarded.

    With ``bf16=True`` the dot-product inputs are bfloat16 (fp32 MXU
    accumulation) — ~2-4x faster and the memory-bandwidth-friendly mode for
    HBM-resident DBs.  Candidate selection tolerates the quantization; callers
    that need exact distances re-score the winner in fp32 (the TPU backend's
    batched strategy does).
    """
    m, f = queries.shape
    n = db.shape[0]
    comp = jnp.bfloat16 if bf16 else _F32
    fp = _round_up(max(f, 128), 128)
    mp = _round_up(max(m, 8), 16 if bf16 else 8)
    npad = _round_up(n, tile_n)

    q = jnp.zeros((mp, fp), comp).at[:m, :f].set(queries.astype(comp))
    dbp = jnp.zeros((npad, fp), comp).at[:n, :f].set(db.astype(comp))
    dbn = jnp.full((1, npad), jnp.inf, _F32).at[0, :n].set(db_sqnorm)

    idx, val = pallas_argmin_l2_prepadded(q, dbp, dbn, tile_n=tile_n,
                                          interpret=interpret,
                                          precision=precision)
    qn = jnp.sum(queries * queries, axis=1)
    dist = jnp.maximum(val[:m] + qn, 0.0)
    return idx[:m], dist


@functools.partial(jax.jit, static_argnames=("tile_n", "interpret",
                                             "precision"))
def pallas_argmin_l2_prepadded(
    q: jax.Array,  # (Mp, Fp) already tile-aligned
    dbp: jax.Array,  # (Npad, Fp) already tile-aligned (zero feature padding)
    dbn: jax.Array,  # (1, Npad) squared norms, +inf on padding rows
    *,
    tile_n: int = 2048,
    interpret: bool = False,
    precision=jax.lax.Precision.DEFAULT,
) -> Tuple[jax.Array, jax.Array]:
    """Padding-free kernel entry for hot loops: callers pre-pad ONCE per
    level (backends/tpu.py) so the per-row scan doesn't re-copy the DB.

    Returns (idx (Mp,) int32, min_score (Mp,) = dist - ||q||^2)."""
    mp, fp = q.shape
    npad = dbp.shape[0]
    tile_n = min(tile_n, npad)
    assert npad % tile_n == 0, (npad, tile_n)

    grid = npad // tile_n
    kernel = functools.partial(_argmin_kernel, tile_n=tile_n, n_total=npad,
                               precision=precision)
    idx, val = pl.pallas_call(
        kernel,
        grid=(grid,),
        in_specs=[
            pl.BlockSpec((mp, fp), lambda t: (0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((tile_n, fp), lambda t: (t, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, tile_n), lambda t: (0, t),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=[
            pl.BlockSpec((mp, 1), lambda t: (0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((mp, 1), lambda t: (0, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((mp, 1), jnp.int32),
            jax.ShapeDtypeStruct((mp, 1), _F32),
        ],
        scratch_shapes=[
            pltpu.VMEM((mp, 1), _F32),
            pltpu.VMEM((mp, 1), jnp.int32),
        ],
        cost_estimate=pl.CostEstimate(
            flops=2 * mp * fp * npad,
            bytes_accessed=npad * fp * 4 + mp * fp * 4 + mp * 8,
            transcendentals=0,
        ),
        interpret=interpret,
    )(q, dbp, dbn)
    return idx[:, 0], val[:, 0]


def prepadded_argmin_queries(queries, dbp, dbn, *, tile_n: int,
                             precision=jax.lax.Precision.DEFAULT):
    """The one padding/score-recovery contract for `pallas_argmin_l2_prepadded`
    callers holding RAW (M, F) queries against an already tile/lane-aligned
    DB: lane-pad + 8-row-align the queries, run the kernel, and recover the
    true squared distance d = max(score + ||q||^2, 0).

    ``dbn`` is the (1, Npad) norm row (+inf on padding rows).  Returns
    (idx (M,), d (M,))."""
    m, f = queries.shape
    fp = dbp.shape[1]
    mp = _round_up(max(m, 8), 8)
    qp = jnp.zeros((mp, fp), _F32).at[:m, :f].set(queries)
    idx, score = pallas_argmin_l2_prepadded(
        qp, dbp, dbn, tile_n=min(tile_n, dbp.shape[0]), precision=precision)
    qn = jnp.sum(queries * queries, axis=1)
    return idx[:m], jnp.maximum(score[:m] + qn, 0.0)


def xla_argmin_l2(queries: jax.Array, db: jax.Array,
                  db_sqnorm: jax.Array) -> Tuple[jax.Array, jax.Array]:
    """XLA reference/fallback (materializes (M,N) — fine for small DBs and
    for non-TPU platforms in tests)."""
    scores = db_sqnorm[None, :] - 2.0 * jnp.dot(
        queries, db.T, preferred_element_type=_F32,
        precision=jax.lax.Precision.HIGHEST)
    idx = jnp.argmin(scores, axis=1).astype(jnp.int32)
    qn = jnp.sum(queries * queries, axis=1)
    d = jnp.take_along_axis(scores, idx[:, None], axis=1)[:, 0]
    return idx, jnp.maximum(d + qn, 0.0)


def argmin_l2(queries, db, db_sqnorm, *, force_xla: bool = False,
              precision=jax.lax.Precision.DEFAULT):
    """Dispatch: Pallas on TPU, XLA elsewhere.  ``precision`` governs the
    Pallas kernel's MXU passes (parity callers pass HIGHEST); the XLA
    fallback always scores at HIGHEST — it exists for CPU platforms where
    fp32 is native and exactness is the point."""
    if force_xla or jax.default_backend() != "tpu":
        return xla_argmin_l2(queries, db, db_sqnorm)
    return pallas_argmin_l2(queries, db, db_sqnorm, precision=precision)
