"""Pallas TPU kernel: fused L2 distance + argmin over the patch database.

This is the framework's hot-path kernel (BASELINE.json:5: "the per-pixel
best-match ... runs as a Pallas kernel with the patch DB resident in HBM").
For a block of query feature vectors Q (M,F) and the DB (N,F) it computes

    idx[m]  = argmin_n ||db[n] - q[m]||^2      (ties -> lowest n)
    dist[m] = min_n    ||db[n] - q[m]||^2

without ever materializing the (M,N) distance matrix in HBM: the DB is tiled
(TILE_N, F) through VMEM by the Pallas pipeline (double-buffered DMA), each
tile's scores are one MXU matmul, and a running (min, argmin) lives in VMEM
scratch across the sequential TPU grid.

Distances use the matmul trick  ||db-q||^2 = ||db||^2 - 2 db.q + ||q||^2 with
fp32 accumulation; the ||q||^2 term is added outside the loop (it does not
affect the argmin).
"""

from __future__ import annotations

import functools
from typing import Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

_F32 = jnp.float32


def _round_up(x: int, m: int) -> int:
    return (x + m - 1) // m * m


def _snap_tile(tile_n: int, npad: int) -> int:
    """Largest divisor of `npad` that is <= `tile_n` (identity for a
    tile that already divides), so tuner-swept tile candidates can
    never trip the grid divisibility requirement."""
    tile_n = max(min(int(tile_n), npad), 1)
    if npad % tile_n == 0:
        return tile_n
    for t in range(tile_n, 0, -1):
        if npad % t == 0:
            return t
    return 1


def _argmin_kernel(q_ref, db_ref, dbn_ref, idx_out, val_out,
                   best_val, best_idx, *, tile_n: int, n_total: int,
                   precision):
    """One grid step: score one DB tile against all queries, fold into the
    running (min, argmin) scratch; write outputs on the last tile."""
    t = pl.program_id(0)

    @pl.when(t == 0)
    def _init():
        best_val[:] = jnp.full_like(best_val, jnp.inf)
        best_idx[:] = jnp.zeros_like(best_idx)

    # scores[m, n] = dbn[n] - 2 * q[m] . db[n]   (M, TILE_N) on the MXU.
    # Precision matters for fp32 inputs: the TPU MXU multiplies in bf16
    # passes, and the DEFAULT single pass gives ~1e-3 score error — enough to
    # flip argmin picks vs an exact fp32 re-score.  The wavefront (oracle
    # parity) strategy therefore runs this kernel at HIGHEST (bf16_6x: six
    # bf16 passes, fp32-grade ~7e-7 score resolution, measured ~3.5x
    # wall-clock); the approximate batched strategy keeps the fast DEFAULT
    # pass.  bf16 inputs are unaffected either way: their single pass IS the
    # operands' full precision.
    scores = dbn_ref[:] - 2.0 * jax.lax.dot_general(
        q_ref[:], db_ref[:],
        dimension_numbers=(((1,), (1,)), ((), ())),
        preferred_element_type=_F32,
        precision=precision,
    )
    # mask DB padding rows (global index >= n_total)
    col = jax.lax.broadcasted_iota(jnp.int32, scores.shape, 1)
    gidx = col + t * tile_n
    scores = jnp.where(gidx < n_total, scores, jnp.inf)

    part_val = jnp.min(scores, axis=1, keepdims=True)  # (M, 1)
    part_arg = jnp.argmin(scores, axis=1).astype(jnp.int32)[:, None]
    part_idx = part_arg + t * tile_n

    improve = part_val < best_val[:]  # strict: earlier tile wins ties
    best_idx[:] = jnp.where(improve, part_idx, best_idx[:])
    best_val[:] = jnp.where(improve, part_val, best_val[:])

    @pl.when(t == pl.num_programs(0) - 1)
    def _flush():
        idx_out[:] = best_idx[:]
        val_out[:] = best_val[:]


def bf16_split2(x: jax.Array) -> Tuple[jax.Array, jax.Array]:
    """Exact hi/lo bf16 decomposition of fp32 ``x`` that XLA cannot fold.

    The naive ``hi = x.astype(bf16); lo = x - hi.astype(f32)`` is UNSAFE
    under ``--xla_allow_excess_precision=true`` (set by this environment's
    TPU compile service): XLA may delete the downcast/upcast pair, turning
    ``lo`` into exact zero and silently degrading every split-based
    multi-pass scheme to a single bf16 pass (measured round 3: the packed
    scans all collapsed to 1-pass accuracy).  Masking the low 16 mantissa
    bits instead produces the TRUNCATED bf16 (bf16 is by definition the
    top 16 bits of an f32), the subtraction ``x - hi`` is then exact, and
    bitwise ops are opaque to the precision folder."""
    u = jax.lax.bitcast_convert_type(x, jnp.uint32)
    hi = jax.lax.bitcast_convert_type(u & np.uint32(0xFFFF0000), _F32)
    return hi, x - hi


def bf16_split3(x: jax.Array):
    """(d1, d2, r2): x = d1 + d2 + r2 with d1/d2 exactly bf16-representable
    fp32 (top-16-bit truncations) and |r2| <= 2^-16 |x|; see bf16_split2."""
    d1, r1 = bf16_split2(x)
    d2, r2 = bf16_split2(r1)
    return d1, d2, r2


def _lex_lt(va, ia, vb, ib):
    """Lexicographic (value, index) less-than — the one ordering every argmin
    path uses, so 'lowest index wins ties' holds bit-for-bit everywhere."""
    return (va < vb) | ((va == vb) & (ia < ib))


_IDX_INF = 2**31 - 1  # init index: loses every (val, idx) tie


def _argmin2_kernel(q_ref, db_ref, dbn_ref, i1_out, v1_out, i2_out, v2_out,
                    b1v, b1i, b2v, b2i, *, tile_n: int, n_total: int,
                    precision, q_split: bool):
    """Top-2 variant of `_argmin_kernel`: track the two best (val, idx) pairs
    per query across tiles, ordered lexicographically by (val, idx).

    This is the scan pass of the TWO-PASS exact-match scheme
    (backends/tpu.py `make_anchor_fn`): a fast MXU scan over the
    bf16-resident DB produces two candidates per query; the caller
    re-scores both in exact fp32 and takes the (val, idx)-min — so a scan
    rank-1/rank-2 inversion never changes the final pick.

    With ``q_split`` the query block is (2M, F): rows [0, M) hold the bf16
    HI halves and rows [M, 2M) the LO residuals of the fp32 queries
    (q = qh + ql, ||ql|| <= 2^-9 ||q||), and the tile's score uses
    qh.db + ql.db — TWO MXU passes that eliminate the query-side
    truncation entirely, leaving only the DB-side 2^-9.  Combined with
    feature centering on the host side (backends/tpu.py — distances are
    shift-invariant but the bf16 absolute error scales with |q|.|d|, which
    centering shrinks ~10x for these all-positive features), the scan
    misranks only inside a ~1e-5-wide band, where the top-2 fp32 re-score
    recovers the winner.  Still one bf16 HBM stream (half of fp32) and 2
    passes vs HIGHEST's 3.
    """
    t = pl.program_id(0)

    @pl.when(t == 0)
    def _init():
        b1v[:] = jnp.full_like(b1v, jnp.inf)
        b2v[:] = jnp.full_like(b2v, jnp.inf)
        b1i[:] = jnp.full_like(b1i, _IDX_INF)
        b2i[:] = jnp.full_like(b2i, _IDX_INF)

    dots = jax.lax.dot_general(
        q_ref[:], db_ref[:],
        dimension_numbers=(((1,), (1,)), ((), ())),
        preferred_element_type=_F32,
        precision=precision,
    )
    if q_split:  # (2M, TILE_N): hi rows + lo rows, fp32 accumulation
        m = dots.shape[0] // 2
        dots = dots[:m] + dots[m:]
    scores = dbn_ref[:] - 2.0 * dots
    col = jax.lax.broadcasted_iota(jnp.int32, scores.shape, 1)
    gidx = col + t * tile_n
    scores = jnp.where(gidx < n_total, scores, jnp.inf)

    # in-tile top-2: min, then min with the argmin's position masked out
    # (argmin returns the FIRST occurrence, so ties stay lowest-index)
    t1v = jnp.min(scores, axis=1, keepdims=True)
    t1a = jnp.argmin(scores, axis=1).astype(jnp.int32)[:, None]
    masked = jnp.where(col == t1a, jnp.inf, scores)
    t2v = jnp.min(masked, axis=1, keepdims=True)
    t2a = jnp.argmin(masked, axis=1).astype(jnp.int32)[:, None]
    t1i = t1a + t * tile_n
    t2i = t2a + t * tile_n

    # merge sorted pairs (g1<g2, t1<t2; all (val,idx) keys distinct):
    # new1 = min(g1, t1); new2 = min(max(g1, t1)'s list head, other's 2nd)
    g_first = _lex_lt(b1v[:], b1i[:], t1v, t1i)
    n1v = jnp.where(g_first, b1v[:], t1v)
    n1i = jnp.where(g_first, b1i[:], t1i)
    # candidates for 2nd place: the loser of the firsts, and the winner's 2nd
    lv = jnp.where(g_first, t1v, b1v[:])
    li = jnp.where(g_first, t1i, b1i[:])
    wv = jnp.where(g_first, b2v[:], t2v)
    wi = jnp.where(g_first, b2i[:], t2i)
    l_second = _lex_lt(lv, li, wv, wi)
    b1v[:], b1i[:] = n1v, n1i
    b2v[:] = jnp.where(l_second, lv, wv)
    b2i[:] = jnp.where(l_second, li, wi)

    @pl.when(t == pl.num_programs(0) - 1)
    def _flush():
        i1_out[:] = b1i[:]
        v1_out[:] = b1v[:]
        i2_out[:] = b2i[:]
        v2_out[:] = b2v[:]


@functools.partial(jax.jit, static_argnames=("tile_n", "interpret",
                                             "precision", "q_split"))
def pallas_argmin2_l2_prepadded(
    q: jax.Array,  # (Mp, Fp) tile-aligned, fp32 or bf16
    dbp: jax.Array,  # (Npad, Fp) tile-aligned (zero feature padding)
    dbn: jax.Array,  # (1, Npad) fp32 squared norms, +inf on padding rows
    *,
    tile_n: int = 2048,
    interpret: bool = False,
    precision=jax.lax.Precision.DEFAULT,
    q_split: bool = False,
) -> Tuple[jax.Array, jax.Array, jax.Array, jax.Array]:
    """Top-2 kernel entry.  Returns (i1, s1, i2, s2) per query, (val, idx)
    lexicographic order, scores = dist - ||q||^2 like the top-1 entry.

    With a bf16 `dbp` the MXU pass reads half the HBM bytes and DEFAULT
    precision is the operands' full precision — the fast scan of the
    two-pass exact scheme.  ``q_split`` feeds the kernel the hi/lo bf16
    decomposition of fp32 queries (see `_argmin2_kernel`), removing the
    query-side truncation error for one extra MXU pass."""
    mp, fp = q.shape
    npad = dbp.shape[0]
    tile_n = _snap_tile(tile_n, npad)
    if q_split:
        hi, lo = bf16_split2(q.astype(_F32))  # XLA-folding-safe split
        q = jnp.concatenate([hi.astype(jnp.bfloat16),
                             lo.astype(jnp.bfloat16)], axis=0)  # (2Mp, Fp)
    elif q.dtype != dbp.dtype:
        q = q.astype(dbp.dtype)
    qm = q.shape[0]

    grid = npad // tile_n
    kernel = functools.partial(_argmin2_kernel, tile_n=tile_n, n_total=npad,
                               precision=precision, q_split=q_split)
    outs = pl.pallas_call(
        kernel,
        grid=(grid,),
        in_specs=[
            pl.BlockSpec((qm, fp), lambda t: (0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((tile_n, fp), lambda t: (t, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, tile_n), lambda t: (0, t),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=[pl.BlockSpec((mp, 1), lambda t: (0, 0),
                                memory_space=pltpu.VMEM)] * 4,
        out_shape=[
            jax.ShapeDtypeStruct((mp, 1), jnp.int32),
            jax.ShapeDtypeStruct((mp, 1), _F32),
            jax.ShapeDtypeStruct((mp, 1), jnp.int32),
            jax.ShapeDtypeStruct((mp, 1), _F32),
        ],
        scratch_shapes=[
            pltpu.VMEM((mp, 1), _F32),
            pltpu.VMEM((mp, 1), jnp.int32),
            pltpu.VMEM((mp, 1), _F32),
            pltpu.VMEM((mp, 1), jnp.int32),
        ],
        cost_estimate=pl.CostEstimate(
            flops=2 * mp * fp * npad,
            bytes_accessed=npad * fp * dbp.dtype.itemsize
            + mp * fp * q.dtype.itemsize + mp * 16,
            transcendentals=0,
        ),
        interpret=interpret,
    )(q, dbp, dbn)
    i1, v1, i2, v2 = outs
    return i1[:, 0], v1[:, 0], i2[:, 0], v2[:, 0]


def prepadded_argmin2_queries(queries, dbp, dbn, *, tile_n: int,
                              precision=jax.lax.Precision.DEFAULT,
                              q_split: bool = False):
    """Top-2 twin of `prepadded_argmin_queries` for RAW (M, F) fp32 queries:
    pad, run the top-2 kernel, return (i1, i2, valid2) — scores are NOT
    returned because two-pass callers re-score both candidates in exact
    fp32 anyway.  `valid2` is False where no second distinct row exists
    (DB of one row)."""
    m, f = queries.shape
    fp = dbp.shape[1]
    mp = _round_up(max(m, 8), 16 if dbp.dtype == jnp.bfloat16 else 8)
    qp = jnp.zeros((mp, fp), queries.dtype).at[:m, :f].set(queries)
    i1, _, i2, v2 = pallas_argmin2_l2_prepadded(
        qp, dbp, dbn, tile_n=min(tile_n, dbp.shape[0]), precision=precision,
        q_split=q_split)
    return i1[:m], i2[:m], jnp.isfinite(v2[:m])


def _pertile_kernel(q_ref, db_ref, dbnh_ref, val_out, idx_out, *,
                    precision, fold2: bool):
    """Per-tile champion kernel — the VPU-minimal scan pass.

    The top-1/top-2 kernels spend more time in VPU reductions than in MXU
    passes (measured: top-1 HIGHEST 5.2 ms vs a 1.34 ms 3-pass MXU roofline
    at M=344, Na=1M — experiments/step_cost_probe.py): iota masking, the
    running-scratch merge, and argmin cascades all cost full passes over the
    (M, tile_n) scores.  This kernel strips the per-element work to the
    minimum:

        s2[m, n] = q[m] . db[n] - 0.5 ||db[n]||^2     (one fused sub)
        val[m]   = max_n s2                           (bigger s2 = smaller
        idx[m]   = argmax_n s2  (+ tile offset)        L2 distance)

    and writes each tile's champion straight to its own output column — no
    cross-tile scratch, no merge, no padding mask (padding rows carry
    ``dbnh = +inf`` so s2 = -inf loses every max).  Cross-tile selection,
    re-scoring, and tie-breaking happen OUTSIDE in XLA (backends/tpu.py
    `make_anchor_fn`): take the top-T tile champions by scan score, re-score
    those rows in exact fp32, pick the (distance, index)-lexicographic min.

    In-tile ties: ``jnp.argmax`` returns the first occurrence, so bf16-equal
    scores (identical rows quantize identically) keep lowest-index-first.
    """
    t = pl.program_id(0)
    dots = jax.lax.dot_general(
        q_ref[:], db_ref[:],
        dimension_numbers=(((1,), (1,)), ((), ())),
        preferred_element_type=_F32,
        precision=precision,
    )
    if fold2:  # (2M, TILE_N): two row-blocks per query, dots summed in fp32
        m = dots.shape[0] // 2
        dots = dots[:m] + dots[m:]
    s2 = dots - dbnh_ref[:]
    # the (ntiles, M) outputs stay VMEM-resident across the sequential grid;
    # each tile stores its champion ROW at its own (dynamic) sublane offset
    # — Mosaic supports dynamic sublane stores but not dynamic LANE-column
    # stores, hence the tile-major layout (callers transpose, it's tiny)
    val_out[pl.dslice(t, 1), :] = jnp.max(s2, axis=1)[None, :]
    idx_out[pl.dslice(t, 1), :] = (
        jnp.argmax(s2, axis=1).astype(jnp.int32)[None, :]
        + t * s2.shape[1])


@functools.partial(jax.jit, static_argnames=("tile_n", "interpret",
                                             "precision", "q_split"))
def pallas_pertile_champions(
    q: jax.Array,  # (Mp, Fp) tile-aligned, fp32 or bf16
    dbp: jax.Array,  # (Npad, Fp) tile-aligned (zero feature padding)
    dbnh: jax.Array,  # (1, Npad) fp32 HALF squared norms, +inf on padding
    *,
    tile_n: int,
    interpret: bool = False,
    precision=jax.lax.Precision.DEFAULT,
    q_split: bool = False,
) -> Tuple[jax.Array, jax.Array]:
    """Per-tile champion entry: returns (vals (ntiles, Mp) fp32 scan scores
    s2 = q.db - ||db||^2/2 [bigger = closer], idx (ntiles, Mp) int32 global
    row of each tile's best) in TILE-MAJOR layout (see `_pertile_kernel` on
    why).  See `pertile_champions_queries` for the (M, ntiles) wrapper."""
    npad = dbp.shape[0]
    tile_n = _snap_tile(tile_n, npad)
    if q_split:
        hi, lo = bf16_split2(q.astype(_F32))  # XLA-folding-safe split
        q = jnp.concatenate([hi.astype(jnp.bfloat16),
                             lo.astype(jnp.bfloat16)], axis=0)  # (2Mp, Fp)
    elif q.dtype != dbp.dtype:
        q = q.astype(dbp.dtype)
    qm, fp = q.shape
    mp = qm // 2 if q_split else qm

    grid = npad // tile_n
    kernel = functools.partial(_pertile_kernel, precision=precision,
                               fold2=q_split)
    vals, idx = pl.pallas_call(
        kernel,
        grid=(grid,),
        in_specs=[
            pl.BlockSpec((qm, fp), lambda t: (0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((tile_n, fp), lambda t: (t, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, tile_n), lambda t: (0, t),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=[
            pl.BlockSpec((grid, mp), lambda t: (0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((grid, mp), lambda t: (0, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((grid, mp), _F32),
            jax.ShapeDtypeStruct((grid, mp), jnp.int32),
        ],
        cost_estimate=pl.CostEstimate(
            flops=2 * qm * fp * npad,
            bytes_accessed=npad * fp * dbp.dtype.itemsize
            + qm * fp * q.dtype.itemsize + mp * grid * 8,
            transcendentals=0,
        ),
        interpret=interpret,
    )(q, dbp, dbnh)
    return vals, idx


def pertile_champions_queries(queries, dbp, dbnh, *, tile_n: int,
                              precision=jax.lax.Precision.DEFAULT,
                              q_split: bool = False,
                              interpret: bool = False):
    """Raw-query wrapper for `pallas_pertile_champions`: lane-pad + row-align
    the (M, F) fp32 queries, run the kernel, return (vals (M, ntiles),
    idx (M, ntiles)).  Scores are scan-space (q.db - ||db||^2/2, BIGGER =
    closer); callers re-score candidates in exact fp32 anyway."""
    m, f = queries.shape
    fp = dbp.shape[1]
    mp = _round_up(max(m, 8), 16 if dbp.dtype == jnp.bfloat16 else 8)
    qp = jnp.zeros((mp, fp), queries.dtype).at[:m, :f].set(queries)
    vals, idx = pallas_pertile_champions(
        qp, dbp, dbnh, tile_n=min(tile_n, dbp.shape[0]), precision=precision,
        q_split=q_split, interpret=interpret)
    return vals.T[:m], idx.T[:m]


def _packed_kernel(qa_ref, qb_ref, w1_ref, w2_ref, dbnh_ref, val_out,
                   idx_out, *, fold_a: bool):
    """Per-tile champion kernel for the packed fp32-grade scans.

    ``qa_ref`` row-blocks dot against W1 and ``qb_ref`` against W2; with
    ``fold_a`` qa is (2M, K) and its two row-blocks are summed.  The two
    lane packings served (backends/tpu.py make_anchor_fn):

    - 3-pass (exact_hi2): qa = [[q1|q1]; [q2|q2]] . W1=[d1|d2],
      qb = [q1|q3] . W2=[d3|d1] — sums to  q1.d1 + (q1.d2 + q2.d1) +
      (q1.d3 + q2.d2 + q3.d1), exactly the bf16_6x (jax HIGHEST) product
      set; dropped terms carry coefficients <= 2^-24.
    - 2-pass (exact_hi2_2p): qa = [q1|q1] . W1=[d1|d2],
      qb = [q2|q1] . W2=[d1|d3] — the same set minus its two smallest
      members (q2.d2, q3.d1, both ~2^-16 coefficient); with live-dim
      centering the dropped mass is ~1e-6 absolute on real features,
      inside the tie-audit's fp-resolution band (BENCH_r03).

    K=128 passes over bf16 streams instead of HIGHEST's six fp32-stream
    passes, because only the L ~ 55 query-LIVE dims are packed (see
    FeatureSpec.query_live_mask); dead dims reach scores exactly via the
    precomputed half-norm term."""
    t = pl.program_id(0)
    dots_a = jax.lax.dot_general(
        qa_ref[:], w1_ref[:],
        dimension_numbers=(((1,), (1,)), ((), ())),
        preferred_element_type=_F32)
    dots_b = jax.lax.dot_general(
        qb_ref[:], w2_ref[:],
        dimension_numbers=(((1,), (1,)), ((), ())),
        preferred_element_type=_F32)
    if fold_a:
        m = dots_a.shape[0] // 2
        dots_a = dots_a[:m] + dots_a[m:]
    s2 = dots_a + dots_b - dbnh_ref[:]
    val_out[pl.dslice(t, 1), :] = jnp.max(s2, axis=1)[None, :]
    idx_out[pl.dslice(t, 1), :] = (
        jnp.argmax(s2, axis=1).astype(jnp.int32)[None, :]
        + t * s2.shape[1])


@functools.partial(jax.jit, static_argnames=("tile_n", "fold_a", "interpret"))
def pallas_packed_champions(
    qa: jax.Array,  # (Mp or 2Mp, Kp) bf16 row-blocks against W1
    qb: jax.Array,  # (Mp, Kp) bf16 row-block against W2
    w1: jax.Array,  # (Npad, Kp) bf16
    w2: jax.Array,  # (Npad, Kp) bf16
    dbnh: jax.Array,  # (1, Npad) fp32 half norms, +inf on padding
    *,
    tile_n: int,
    fold_a: bool,
    interpret: bool = False,
) -> Tuple[jax.Array, jax.Array]:
    """Entry for `_packed_kernel`; returns tile-major (ntiles, Mp) pairs."""
    mp, kp = qb.shape
    npad = w1.shape[0]
    tile_n = _snap_tile(tile_n, npad)
    assert qa.shape == ((2 * mp if fold_a else mp), kp), (qa.shape, qb.shape)
    qm = qa.shape[0]
    grid = npad // tile_n
    passes = (2 if fold_a else 1) + 1
    vals, idx = pl.pallas_call(
        functools.partial(_packed_kernel, fold_a=fold_a),
        grid=(grid,),
        in_specs=[
            pl.BlockSpec((qm, kp), lambda t: (0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((mp, kp), lambda t: (0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((tile_n, kp), lambda t: (t, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((tile_n, kp), lambda t: (t, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, tile_n), lambda t: (0, t),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=[
            pl.BlockSpec((grid, mp), lambda t: (0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((grid, mp), lambda t: (0, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((grid, mp), _F32),
            jax.ShapeDtypeStruct((grid, mp), jnp.int32),
        ],
        cost_estimate=pl.CostEstimate(
            flops=2 * passes * mp * kp * npad,
            bytes_accessed=2 * npad * kp * 2 + (qm + mp) * kp * 2
            + mp * grid * 8,
            transcendentals=0,
        ),
        interpret=interpret,
    )(qa, qb, w1, w2, dbnh)
    return vals, idx


def _packed_best_kernel(qa_ref, qb_ref, w1_ref, w2_ref, dbnh_ref, idx_out,
                        val_out, best_val, best_idx, *, tile_n: int,
                        fold_a: bool, one_stream: bool,
                        norm_in_w: bool = False):
    """Running-champion variant of `_packed_kernel`: the same packed MXU
    product sets, but the cross-tile champion is folded into VMEM scratch
    inside the kernel (strict > on the scan score keeps ties lowest-index,
    matching `jnp.argmax`-then-first-occurrence semantics of the per-tile
    variant), so the kernel emits the FINAL (idx, val) per query — no
    (ntiles, M) projection table, no XLA champion select over ~128-256
    tiles after it (round-4 fusion work, VERDICT item 1).

    ``one_stream``: read only W1 and score qa against it (qb_ref/w2_ref
    are ignored 1-row stubs) — the single-weight-stream product set
    q1.d1 + q1.d2 + q2.d1 via row-blocks [q1|q1], [q2|0] against
    W = [d1|d2], HALF the HBM bytes of the two-stream scan.

    ``norm_in_w``: the -||d||^2/2 term rides INSIDE W as three extra
    bf16-split lanes (multiplied by constant-1 query lanes, accumulating
    in the MXU's fp32 accumulator to ~2^-24 relative — the same class as
    the dots' own fp32 rounding), so the kernel skips the dbnh stream AND
    the (M, tile) subtract pass; dbnh_ref is a (1, 1) stub.  Padding rows
    carry ~-3e38 norm lanes and lose every max."""
    t = pl.program_id(0)

    @pl.when(t == 0)
    def _init():
        best_val[:] = jnp.full_like(best_val, -jnp.inf)
        best_idx[:] = jnp.zeros_like(best_idx)

    dots = jax.lax.dot_general(
        qa_ref[:], w1_ref[:],
        dimension_numbers=(((1,), (1,)), ((), ())),
        preferred_element_type=_F32)
    if fold_a:
        m = dots.shape[0] // 2
        dots = dots[:m] + dots[m:]
    if not one_stream:
        dots = dots + jax.lax.dot_general(
            qb_ref[:], w2_ref[:],
            dimension_numbers=(((1,), (1,)), ((), ())),
            preferred_element_type=_F32)
    s2 = dots if norm_in_w else dots - dbnh_ref[:]
    part_val = jnp.max(s2, axis=1, keepdims=True)
    part_idx = (jnp.argmax(s2, axis=1).astype(jnp.int32)[:, None]
                + t * s2.shape[1])
    improve = part_val > best_val[:]  # strict: earlier tile wins ties
    best_idx[:] = jnp.where(improve, part_idx, best_idx[:])
    best_val[:] = jnp.where(improve, part_val, best_val[:])

    @pl.when(t == pl.num_programs(0) - 1)
    def _flush():
        idx_out[:] = best_idx[:]
        val_out[:] = best_val[:]


@functools.partial(jax.jit, static_argnames=("tile_n", "fold_a",
                                             "one_stream", "norm_in_w",
                                             "interpret", "vmem_limit"))
def pallas_packed_best(
    qa: jax.Array,  # (Mp or 2Mp, Kp) bf16 row-blocks against W1
    qb: jax.Array,  # (Mp, Kp) bf16 against W2 (1-row stub if one_stream)
    w1: jax.Array,  # (Npad, Kp) bf16
    w2: jax.Array,  # (Npad, Kp) bf16 (1-row stub if one_stream)
    dbnh: jax.Array,  # (1, Npad) fp32 half norms, +inf on padding
    #                   ((1, 1) stub if norm_in_w)
    *,
    tile_n: int,
    fold_a: bool,
    one_stream: bool = False,
    norm_in_w: bool = False,
    interpret: bool = False,
    vmem_limit: int = 0,  # bytes; 0 keeps the platform's scoped default
) -> Tuple[jax.Array, jax.Array]:
    """Entry for `_packed_best_kernel`; returns (idx (Mp,), val (Mp,)) —
    the global scan champion per query, ties lowest-index."""
    npad, kp = w1.shape
    tile_n = _snap_tile(tile_n, npad)
    qm, mp = qa.shape[0], (qa.shape[0] // 2 if fold_a else qa.shape[0])
    grid = npad // tile_n
    qb_spec = (pl.BlockSpec((qb.shape[0], qb.shape[1]), lambda t: (0, 0),
                            memory_space=pltpu.VMEM))
    w2_spec = (pl.BlockSpec((1, kp), lambda t: (0, 0),
                            memory_space=pltpu.VMEM) if one_stream else
               pl.BlockSpec((tile_n, kp), lambda t: (t, 0),
                            memory_space=pltpu.VMEM))
    dbnh_spec = (pl.BlockSpec((1, 1), lambda t: (0, 0),
                              memory_space=pltpu.VMEM) if norm_in_w else
                 pl.BlockSpec((1, tile_n), lambda t: (0, t),
                              memory_space=pltpu.VMEM))
    passes = (2 if fold_a else 1) + (0 if one_stream else 1)
    streams = 1 if one_stream else 2
    idx, val = pl.pallas_call(
        functools.partial(_packed_best_kernel, tile_n=tile_n, fold_a=fold_a,
                          one_stream=one_stream, norm_in_w=norm_in_w),
        grid=(grid,),
        in_specs=[
            pl.BlockSpec((qm, kp), lambda t: (0, 0),
                         memory_space=pltpu.VMEM),
            qb_spec,
            pl.BlockSpec((tile_n, kp), lambda t: (t, 0),
                         memory_space=pltpu.VMEM),
            w2_spec,
            dbnh_spec,
        ],
        out_specs=[pl.BlockSpec((mp, 1), lambda t: (0, 0),
                                memory_space=pltpu.VMEM)] * 2,
        out_shape=[
            jax.ShapeDtypeStruct((mp, 1), jnp.int32),
            jax.ShapeDtypeStruct((mp, 1), _F32),
        ],
        scratch_shapes=[
            pltpu.VMEM((mp, 1), _F32),
            pltpu.VMEM((mp, 1), jnp.int32),
        ],
        cost_estimate=pl.CostEstimate(
            flops=2 * passes * mp * kp * npad,
            bytes_accessed=streams * npad * kp * 2 + (qm + qb.shape[0]) * kp * 2
            + mp * 8,
            transcendentals=0,
        ),
        interpret=interpret,
        **({"compiler_params": pltpu.CompilerParams(
            vmem_limit_bytes=vmem_limit)} if vmem_limit else {}),
    )(qa, qb, w1, w2, dbnh)
    return idx[:, 0], val[:, 0]


def _pack_rows(left, right, m, l, kp):
    z = jnp.zeros((m, kp), jnp.bfloat16)
    return z.at[:, :l].set(left).at[:, l:2 * l].set(right)


def packed2_best(q1, q2, w1, w2, dbnh, *, tile_n: int,
                 interpret: bool = False):
    """Champion-in-kernel twin of `packed2_champions` (same 2-pass product
    set q1.d1 + q1.d2 + q2.d1 + q1.d3): returns the FINAL (idx (M,),
    val (M,)) global scan champion — no (M, ntiles) projection table."""
    m, l = q1.shape
    kp = w1.shape[1]
    mp = _round_up(max(m, 8), 16)
    pad = lambda x: jnp.zeros((mp, l), jnp.bfloat16).at[:m].set(x)
    q1, q2 = pad(q1), pad(q2)
    idx, val = pallas_packed_best(
        _pack_rows(q1, q1, mp, l, kp), _pack_rows(q2, q1, mp, l, kp),
        w1, w2, dbnh, tile_n=min(tile_n, w1.shape[0]), fold_a=False,
        interpret=interpret)
    return idx[:m], val[:m]


def packed1w_best(q1, q2, w1, dbnh, *, tile_n: int,
                  interpret: bool = False):
    """Single-weight-stream champion scan: product set
    q1.d1 + q1.d2 + q2.d1 over ONE packed array W1 = [d1|d2] via folded
    row-blocks [q1|q1] and [q2|0] — half the HBM bytes of the two-stream
    scans (the one dropped ~2^-16 term vs packed2 is q1.d3; parity
    adjudicated by the tie-audit before this mode is ever steered to).
    Returns (idx (M,), val (M,))."""
    m, l = q1.shape
    kp = w1.shape[1]
    mp = _round_up(max(m, 8), 16)
    pad = lambda x: jnp.zeros((mp, l), jnp.bfloat16).at[:m].set(x)
    q1, q2 = pad(q1), pad(q2)
    qa = jnp.concatenate([_pack_rows(q1, q1, mp, l, kp),
                          _pack_rows(q2, jnp.zeros_like(q2), mp, l, kp)],
                         axis=0)
    stub16 = jnp.zeros((1, kp), jnp.bfloat16)
    idx, val = pallas_packed_best(
        qa, stub16, w1, stub16, dbnh, tile_n=min(tile_n, w1.shape[0]),
        fold_a=True, one_stream=True, interpret=interpret)
    return idx[:m], val[:m]


# score assigned to padding rows by the norm-in-W scheme: far below any
# real score, finite (an inf lane would split to hi=-inf, lo=NaN and the
# NaN would poison the max)
_PAD_SCORE = -3.0e38


def add_norm_lanes(w1, dbnh_row, l: int):
    """Fold -||d||^2/2 into W as three bf16-split lanes at [2l, 2l+3).

    Multiplied by constant-1.0 query lanes, the three products accumulate
    in the MXU's fp32 accumulator to the exact half-norm up to ~2^-24
    relative — the same resolution class as the fp32 accumulation of the
    ~l feature products themselves, so scan scores keep fp32-grade
    resolution with NO per-element norm subtract in the kernel (and no
    (1, Npad) dbnh stream).  Identical DB rows get identical lanes, so
    exact ties still break lowest-index.  Padding rows (+inf dbnh) become
    finite `_PAD_SCORE` lanes and lose every max.

    ``w1`` is (Npad, Kp) bf16 with lanes [0, 2l) in use; requires
    2l + 3 <= Kp (callers check — see tpu.py packed steering)."""
    npad, kp = w1.shape
    assert 2 * l + 3 <= kp, (l, kp)
    neg = jnp.where(jnp.isfinite(dbnh_row), -dbnh_row.astype(_F32),
                    _PAD_SCORE)
    n1, n2, n3 = bf16_split3(neg)
    lanes = jnp.stack([x.astype(jnp.bfloat16) for x in (n1, n2, n3)],
                      axis=1)  # (Npad, 3)
    return jax.lax.dynamic_update_slice(w1, lanes, (0, 2 * l))


def norm_query_rows(q1, q2, mp: int, l: int, kp: int):
    """The qa row-blocks of the norm-in-W single-stream scan: rows [0, mp)
    = [q1|q1|1,1,1] (products q1.d1 + q1.d2 + norm), rows [mp, 2mp) =
    [q2|0|0] (product q2.d1), folded by the kernel."""
    pad = lambda x: jnp.zeros((mp, l), jnp.bfloat16).at[:q1.shape[0]].set(x)
    q1p, q2p = pad(q1), pad(q2)
    row_a = _pack_rows(q1p, q1p, mp, l, kp)
    ones = jnp.ones((mp, 3), jnp.bfloat16)
    row_a = jax.lax.dynamic_update_slice(row_a, ones, (0, 2 * l))
    row_b = _pack_rows(q2p, jnp.zeros_like(q2p), mp, l, kp)
    return jnp.concatenate([row_a, row_b], axis=0)


def packed2k_best(q1, q2, wk, *, tile_n: int, interpret: bool = False,
                  vmem_limit: int = 0):
    """The shipping exact_hi2_2p scan (round-4 final form): the FULL
    2-pass product set q1.d1 + q1.d2 + q2.d1 + q1.d3 - ||d||^2/2 computed
    by ONE wide dot_general per tile against a single (Npad, Kp~256)
    weight array

        wk = [ d1 | d2 | n1 n2 n3 | d1 | d3 | 0pad ]   (4L + 3 lanes)

    with the matching query row [ q1 | q1 | 1 1 1 | q2 | q1 | 0 ].  Same
    HBM bytes as the two-array layout (d1 is duplicated so q1 AND q2 can
    meet it), but the cross-block accumulation now happens INSIDE the
    MXU's fp32 accumulator (K = 256 is two systolic passes into one
    output) — no VPU add pass, no dbnh subtract pass, champion in kernel
    scratch: the per-element VPU work is down to max + argmax, the
    measured bound of the scan (experiments/step_decompose_probe.py).
    Norm lanes are bf16-split to ~2^-24 relative (`add_norm_lanes`
    rationale); padding rows carry `_PAD_SCORE` lanes and lose every max.
    Returns (idx (M,), val (M,))."""
    m, l = q1.shape
    kp = wk.shape[1]
    o2 = 2 * l + 3
    assert o2 + 2 * l <= kp, (l, kp)
    mp = _round_up(max(m, 8), 16)
    pad = lambda x: jnp.zeros((mp, l), jnp.bfloat16).at[:m].set(x)
    q1p, q2p = pad(q1), pad(q2)
    qa = jnp.zeros((mp, kp), jnp.bfloat16)
    qa = jax.lax.dynamic_update_slice(qa, q1p, (0, 0))
    qa = jax.lax.dynamic_update_slice(qa, q1p, (0, l))
    qa = jax.lax.dynamic_update_slice(
        qa, jnp.ones((mp, 3), jnp.bfloat16), (0, 2 * l))
    qa = jax.lax.dynamic_update_slice(qa, q2p, (0, o2))
    qa = jax.lax.dynamic_update_slice(qa, q1p, (0, o2 + l))
    stub16 = jnp.zeros((1, kp), jnp.bfloat16)
    stub_n = jnp.zeros((1, 1), _F32)
    idx, val = pallas_packed_best(
        qa, stub16, wk, stub16, stub_n, tile_n=min(tile_n, wk.shape[0]),
        fold_a=False, one_stream=True, norm_in_w=True, interpret=interpret,
        vmem_limit=vmem_limit)
    return idx[:m], val[:m]


def packed2wn_best(q1, q2, w1n, w2, *, tile_n: int,
                   interpret: bool = False):
    """Two-array intermediate of the round-4 fusion work — SUPERSEDED in
    production by `packed2k_best` (the K-wide single-array form two
    functions down); kept with its test as the stepping stone that
    validated the two fusions separately.  Computes the FULL 2-pass
    product set q1.d1 + q1.d2 + q2.d1 + q1.d3 (unchanged — the
    single-stream variant that dropped q1.d3 FAILED the 256^2 tie-audit:
    explained 0.999873, first divergence not a tie), with two round-4
    fusions that preserve it:

    - champion folded into kernel scratch (no (M, ntiles) projection
      table, no XLA select), and
    - the -||d||^2/2 term riding W1's lanes [2L, 2L+3) as bf16-split
      products against constant-1 query lanes (`add_norm_lanes`) — a
      ~2^-24-relative perturbation, the same class as the fp32
      accumulation of the dots themselves, which the tie-audit explains
      as fp-band ties — killing the (1, Npad) dbnh stream and the
      per-element subtract pass.

    ``w1n`` = [d1|d2|norm lanes], ``w2`` = [d1|d3|0].  Row-blocks
    [q1|q1|1]. W1 and [q2|q1|0]. W2.  Returns (idx (M,), val (M,))."""
    m, l = q1.shape
    kp = w1n.shape[1]
    mp = _round_up(max(m, 8), 16)
    pad = lambda x: jnp.zeros((mp, l), jnp.bfloat16).at[:m].set(x)
    q1, q2 = pad(q1), pad(q2)
    qa = jax.lax.dynamic_update_slice(  # [q1|q1|1,1,1]
        _pack_rows(q1, q1, mp, l, kp),
        jnp.ones((mp, 3), jnp.bfloat16), (0, 2 * l))
    qb = _pack_rows(q2, q1, mp, l, kp)  # [q2|q1|0,0,0]
    stub_n = jnp.zeros((1, 1), _F32)
    idx, val = pallas_packed_best(
        qa, qb, w1n, w2, stub_n, tile_n=min(tile_n, w1n.shape[0]),
        fold_a=False, norm_in_w=True, interpret=interpret)
    return idx[:m], val[:m]


def packed1wn_best(q1, q2, w1n, *, tile_n: int, interpret: bool = False):
    """Single-stream, norm-in-W champion scan (the round-4 fusion
    candidate): ONE (Npad, Kp) bf16 weight stream carrying [d1|d2|norm
    lanes] (see `add_norm_lanes`), folded query row-blocks
    [q1|q1|1], [q2|0|0], champion resolved in kernel scratch.  Product
    set q1.d1 + q1.d2 + q2.d1 - ||d||^2/2: vs the shipping exact_hi2_2p
    this drops only the ~2^-16-coefficient q1.d3 term (parity adjudicated
    by the tie-audit before steering ever selects it).  Returns
    (idx (M,), val (M,))."""
    m, l = q1.shape
    kp = w1n.shape[1]
    mp = _round_up(max(m, 8), 16)
    qa = norm_query_rows(q1, q2, mp, l, kp)
    stub16 = jnp.zeros((1, kp), jnp.bfloat16)
    stub_n = jnp.zeros((1, 1), _F32)
    idx, val = pallas_packed_best(
        qa, stub16, w1n, stub16, stub_n, tile_n=min(tile_n, w1n.shape[0]),
        fold_a=True, one_stream=True, norm_in_w=True, interpret=interpret)
    return idx[:m], val[:m]


def packed3_best(q1, q2, q3, w1, w2, dbnh, *, tile_n: int,
                 interpret: bool = False):
    """Champion-in-kernel twin of `packed3_champions` (the full bf16_6x
    product set of exact_hi2): returns (idx (M,), val (M,))."""
    m, l = q1.shape
    kp = w1.shape[1]
    mp = _round_up(max(m, 8), 16)
    pad = lambda x: jnp.zeros((mp, l), jnp.bfloat16).at[:m].set(x)
    q1, q2, q3 = pad(q1), pad(q2), pad(q3)
    qa = jnp.concatenate([_pack_rows(q1, q1, mp, l, kp),
                          _pack_rows(q2, q2, mp, l, kp)], axis=0)
    idx, val = pallas_packed_best(
        qa, _pack_rows(q1, q3, mp, l, kp), w1, w2, dbnh,
        tile_n=min(tile_n, w1.shape[0]), fold_a=True, interpret=interpret)
    return idx[:m], val[:m]


def packed2_champions(q1, q2, w1, w2, dbnh, *, tile_n: int,
                      interpret: bool = False):
    """Raw wrapper for the 2-pass packed scan: ``q1``/``q2`` are the (M, L)
    bf16 hi/mid query splits on LIVE dims; W1 = [d1|d2], W2 = [d1|d3].
    Returns (vals (M, ntiles), idx (M, ntiles))."""
    m, l = q1.shape
    kp = w1.shape[1]
    mp = _round_up(max(m, 8), 16)
    pad = lambda x: jnp.zeros((mp, l), jnp.bfloat16).at[:m].set(x)
    q1, q2 = pad(q1), pad(q2)
    vals, idx = pallas_packed_champions(
        _pack_rows(q1, q1, mp, l, kp), _pack_rows(q2, q1, mp, l, kp),
        w1, w2, dbnh, tile_n=min(tile_n, w1.shape[0]), fold_a=False,
        interpret=interpret)
    return vals.T[:m], idx.T[:m]


def packed3_champions(q1, q2, q3, w1, w2, dbnh, *, tile_n: int,
                      interpret: bool = False):
    """Raw wrapper for the 3-pass packed scan: ``q1``/``q2``/``q3`` are the
    (M, L) bf16 hi/mid/lo query splits on LIVE dims (q = q1+q2+q3 to
    ~2^-24); W1 = [d1|d2], W2 = [d3|d1].  Returns (vals (M, ntiles),
    idx (M, ntiles))."""
    m, l = q1.shape
    kp = w1.shape[1]
    mp = _round_up(max(m, 8), 16)
    pad = lambda x: jnp.zeros((mp, l), jnp.bfloat16).at[:m].set(x)
    q1, q2, q3 = pad(q1), pad(q2), pad(q3)
    qa = jnp.concatenate([_pack_rows(q1, q1, mp, l, kp),
                          _pack_rows(q2, q2, mp, l, kp)], axis=0)
    vals, idx = pallas_packed_champions(
        qa, _pack_rows(q1, q3, mp, l, kp), w1, w2, dbnh,
        tile_n=min(tile_n, w1.shape[0]), fold_a=True, interpret=interpret)
    return vals.T[:m], idx.T[:m]


@functools.partial(jax.jit, static_argnames=("tile_n", "interpret", "bf16",
                                             "precision"))
def pallas_argmin_l2(
    queries: jax.Array,  # (M, F) fp32
    db: jax.Array,  # (N, F) fp32 or bf16
    db_sqnorm: jax.Array,  # (N,) fp32
    *,
    tile_n: int = 512,
    interpret: bool = False,
    bf16: bool = False,
    precision=jax.lax.Precision.DEFAULT,
) -> Tuple[jax.Array, jax.Array]:
    """Fused argmin kernel.  Returns (idx (M,) int32, sqdist (M,) fp32).

    Shapes are padded to TPU tiles internally (F -> mult of 128, M -> mult of
    8, N -> mult of tile_n); padded DB rows can never win (masked to +inf),
    padded query rows are discarded.

    With ``bf16=True`` the dot-product inputs are bfloat16 (fp32 MXU
    accumulation) — ~2-4x faster and the memory-bandwidth-friendly mode for
    HBM-resident DBs.  Candidate selection tolerates the quantization; callers
    that need exact distances re-score the winner in fp32 (the TPU backend's
    batched strategy does).
    """
    m, f = queries.shape
    n = db.shape[0]
    comp = jnp.bfloat16 if bf16 else _F32
    fp = _round_up(max(f, 128), 128)
    mp = _round_up(max(m, 8), 16 if bf16 else 8)
    npad = _round_up(n, tile_n)

    q = jnp.zeros((mp, fp), comp).at[:m, :f].set(queries.astype(comp))
    dbp = jnp.zeros((npad, fp), comp).at[:n, :f].set(db.astype(comp))
    dbn = jnp.full((1, npad), jnp.inf, _F32).at[0, :n].set(db_sqnorm)

    idx, val = pallas_argmin_l2_prepadded(q, dbp, dbn, tile_n=tile_n,
                                          interpret=interpret,
                                          precision=precision)
    qn = jnp.sum(queries * queries, axis=1)
    dist = jnp.maximum(val[:m] + qn, 0.0)
    return idx[:m], dist


@functools.partial(jax.jit, static_argnames=("tile_n", "interpret",
                                             "precision"))
def pallas_argmin_l2_prepadded(
    q: jax.Array,  # (Mp, Fp) already tile-aligned
    dbp: jax.Array,  # (Npad, Fp) already tile-aligned (zero feature padding)
    dbn: jax.Array,  # (1, Npad) squared norms, +inf on padding rows
    *,
    tile_n: int = 2048,
    interpret: bool = False,
    precision=jax.lax.Precision.DEFAULT,
) -> Tuple[jax.Array, jax.Array]:
    """Padding-free kernel entry for hot loops: callers pre-pad ONCE per
    level (backends/tpu.py) so the per-row scan doesn't re-copy the DB.

    Returns (idx (Mp,) int32, min_score (Mp,) = dist - ||q||^2)."""
    mp, fp = q.shape
    npad = dbp.shape[0]
    tile_n = _snap_tile(tile_n, npad)

    grid = npad // tile_n
    kernel = functools.partial(_argmin_kernel, tile_n=tile_n, n_total=npad,
                               precision=precision)
    idx, val = pl.pallas_call(
        kernel,
        grid=(grid,),
        in_specs=[
            pl.BlockSpec((mp, fp), lambda t: (0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((tile_n, fp), lambda t: (t, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, tile_n), lambda t: (0, t),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=[
            pl.BlockSpec((mp, 1), lambda t: (0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((mp, 1), lambda t: (0, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((mp, 1), jnp.int32),
            jax.ShapeDtypeStruct((mp, 1), _F32),
        ],
        scratch_shapes=[
            pltpu.VMEM((mp, 1), _F32),
            pltpu.VMEM((mp, 1), jnp.int32),
        ],
        cost_estimate=pl.CostEstimate(
            flops=2 * mp * fp * npad,
            bytes_accessed=npad * fp * 4 + mp * fp * 4 + mp * 8,
            transcendentals=0,
        ),
        interpret=interpret,
    )(q, dbp, dbn)
    return idx[:, 0], val[:, 0]


def prepadded_argmin_queries(queries, dbp, dbn, *, tile_n: int,
                             precision=jax.lax.Precision.DEFAULT):
    """The one padding/score-recovery contract for `pallas_argmin_l2_prepadded`
    callers holding RAW (M, F) queries against an already tile/lane-aligned
    DB: lane-pad + 8-row-align the queries, run the kernel, and recover the
    true squared distance d = max(score + ||q||^2, 0).

    ``dbn`` is the (1, Npad) norm row (+inf on padding rows).  Returns
    (idx (M,), d (M,))."""
    m, f = queries.shape
    fp = dbp.shape[1]
    mp = _round_up(max(m, 8), 8)
    qp = jnp.zeros((mp, fp), _F32).at[:m, :f].set(queries)
    idx, score = pallas_argmin_l2_prepadded(
        qp, dbp, dbn, tile_n=min(tile_n, dbp.shape[0]), precision=precision)
    qn = jnp.sum(queries * queries, axis=1)
    return idx[:m], jnp.maximum(score[:m] + qn, 0.0)


def xla_argmin_l2(queries: jax.Array, db: jax.Array,
                  db_sqnorm: jax.Array) -> Tuple[jax.Array, jax.Array]:
    """XLA reference/fallback (materializes (M,N) — fine for small DBs and
    for non-TPU platforms in tests)."""
    scores = db_sqnorm[None, :] - 2.0 * jnp.dot(
        queries, db.T, preferred_element_type=_F32,
        precision=jax.lax.Precision.HIGHEST)
    idx = jnp.argmin(scores, axis=1).astype(jnp.int32)
    qn = jnp.sum(queries * queries, axis=1)
    d = jnp.take_along_axis(scores, idx[:, None], axis=1)[:, 0]
    return idx, jnp.maximum(d + qn, 0.0)


def argmin_l2(queries, db, db_sqnorm, *, force_xla: bool = False,
              precision=jax.lax.Precision.DEFAULT):
    """Dispatch: Pallas on TPU, XLA elsewhere.  ``precision`` governs the
    Pallas kernel's MXU passes (parity callers pass HIGHEST); the XLA
    fallback always scores at HIGHEST — it exists for CPU platforms where
    fp32 is native and exactness is the point."""
    if force_xla or jax.default_backend() != "tpu":
        return xla_argmin_l2(queries, db, db_sqnorm)
    return pallas_argmin_l2(queries, db, db_sqnorm, precision=precision)


# ----------------------------------------------------------------------
# Two-stage ANN matcher (sub-linear candidate search, ROADMAP item 3).
#
# Stage 1 scores every DB row in a Kp-dim PCA subspace (Kp << F, so the
# prefilter matmul is ~F/Kp cheaper than an exact scan) and keeps the
# top-m candidates per query; stage 2 gathers that (M, m) slab and
# re-scores it with the SAME exact-fp32 distance the one-stage matcher
# uses.  Both stages are plain jnp on purpose: the slab shapes (m is 64
# by default) are far below the Pallas tiling quanta, XLA fuses the
# gather + re-score fine, and the same program runs on the CPU tier-1
# platform where the Pallas kernels are unavailable.


def ann_topm_candidates(queries, proj, mean, dbp, dbp_halfnorm, n_valid,
                        top_m: int):
    """Stage 1: the top-``top_m`` candidate rows per query, by projected
    distance.

    ``proj`` is the (F, Kp) catalog-sealed PCA basis, ``mean`` the (F,)
    feature column mean it was centered on, ``dbp`` the pre-projected
    (Npad, Kp) DB and ``dbp_halfnorm`` its (Npad,) half squared norms.
    Scoring uses  -0.5*||dbp_n - qp||^2 = qp.dbp_n - 0.5||dbp_n||^2 +
    const  so one (M, Npad) matmul ranks all rows (bigger = closer); the
    query norm constant cannot change the per-query ordering and is
    dropped.  Rows at or past ``n_valid`` (shape-bucket padding — which
    projects to FINITE scores, zero rows are near the feature mean) are
    masked to -inf before the top-k; ``n_valid`` may be a traced scalar.
    Returns (M, m) int32 candidate indices clamped into [0, n_valid) so
    a gather through them never reads a padding row."""
    m_sel = max(1, min(int(top_m), dbp.shape[0]))
    qp = jnp.dot(queries - mean[None, :queries.shape[1]], proj,
                 preferred_element_type=_F32)
    scores = jnp.dot(qp, dbp.T, preferred_element_type=_F32) \
        - dbp_halfnorm[None, :]
    row = jax.lax.broadcasted_iota(jnp.int32, scores.shape, 1)
    scores = jnp.where(row < n_valid, scores, -jnp.inf)
    _, cand = jax.lax.top_k(scores, m_sel)
    return jnp.minimum(cand, n_valid - 1).astype(jnp.int32)


def ann_rescore_slab(queries, db, cand, n_valid):
    """Stage 2: exact-fp32 re-score of the candidate slab.

    Gathers ``db[cand]`` ((M, m, F)) and computes true squared
    distances directly (no matmul trick — the slab is tiny and the
    difference form is exactly the one-stage scorer's d >= 0 contract).
    The winner uses the one-stage tie rule: among candidates at the
    minimum distance, the LOWEST DB index wins — a min over indices
    masked to the tie set, which also collapses the duplicate indices
    the stage-1 clamp can produce.  Returns (idx (M,) int32, d (M,))."""
    cf = db[cand]
    diff = cf - queries[:, None, :]
    d = jnp.sum(diff * diff, axis=-1)
    bv = jnp.min(d, axis=1)
    bi = jnp.min(jnp.where(d <= bv[:, None], cand, n_valid), axis=1)
    return bi.astype(jnp.int32), bv
