"""Color-space ops: RGB<->YIQ and luminance remapping.

Reference parity (SURVEY.md §2 P3): synthesis runs on luminance (Y of YIQ)
only; B's IQ chroma is carried into B' (Hertzmann §3.4).  Luminance remapping
linearly matches A's Y statistics to B's so training pairs with different
exposure still transfer.

These run once per image on the host, so they are NumPy; `ops.pyramid` and
everything after live on the device.
"""

from __future__ import annotations

import numpy as np

# NTSC YIQ matrix (the classic one used by matplotlib/skimage and the
# reference family of implementations).
_RGB2YIQ = np.array(
    [[0.299, 0.587, 0.114],
     [0.59590059, -0.27455667, -0.32134392],
     [0.21153661, -0.52273617, 0.31119955]],
    dtype=np.float64,
)
_YIQ2RGB = np.linalg.inv(_RGB2YIQ)


def as_float(img: np.ndarray) -> np.ndarray:
    """uint8 [0,255] or float -> float32 in [0,1] (H,W) or (H,W,C)."""
    img = np.asarray(img)
    if img.dtype == np.uint8:
        return img.astype(np.float32) / 255.0
    return img.astype(np.float32)


def rgb2yiq(rgb: np.ndarray) -> np.ndarray:
    """(H,W,3) float RGB in [0,1] -> (H,W,3) YIQ."""
    return (rgb.astype(np.float64) @ _RGB2YIQ.T).astype(np.float32)


def yiq2rgb(yiq: np.ndarray) -> np.ndarray:
    """(H,W,3) YIQ -> (H,W,3) RGB, clipped to [0,1]."""
    rgb = yiq.astype(np.float64) @ _YIQ2RGB.T
    return np.clip(rgb, 0.0, 1.0).astype(np.float32)


def luminance(img: np.ndarray) -> np.ndarray:
    """(H,W) or (H,W,3) -> (H,W) float32 luminance."""
    img = as_float(img)
    if img.ndim == 2:
        return img
    if img.shape[-1] == 1:
        return img[..., 0]
    return rgb2yiq(img[..., :3])[..., 0]


def remap_luminance(y_a: np.ndarray, y_b: np.ndarray) -> np.ndarray:
    """Linearly remap A's luminance to B's statistics (Hertzmann §3.4):

        Y(p) <- (sigma_B / sigma_A) * (Y(p) - mu_A) + mu_B
    """
    out, _ = remap_pair(y_a, None, y_b)
    return out


def remap_pair(y_a: np.ndarray, y_ap: np.ndarray | None,
               y_b: np.ndarray) -> tuple:
    """Remap A's luminance to B's statistics and apply the SAME affine
    transform to A' (Hertzmann §3.4).

    One transform — computed from (mu_A, sigma_A) vs (mu_B, sigma_B) — must be
    applied to both planes: remapping A' with its own statistics would exactly
    cancel any affine filter A -> A' and destroy the analogy signal.

    Returns (remapped_A, remapped_A_or_None).
    """
    ya64 = y_a.astype(np.float64)
    yb64 = y_b.astype(np.float64)
    mu_a, sigma_a = float(ya64.mean()), float(ya64.std())
    mu_b, sigma_b = float(yb64.mean()), float(yb64.std())
    if sigma_a < 1e-8:
        scale, shift = 0.0, mu_b
    else:
        scale = sigma_b / sigma_a
        shift = mu_b - scale * mu_a
    out_a = (scale * y_a + shift).astype(np.float32)
    out_ap = None if y_ap is None else (scale * y_ap + shift).astype(np.float32)
    return out_a, out_ap
