"""Array ops: color, pyramid, features, distances, Pallas kernels."""
