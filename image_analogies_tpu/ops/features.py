"""Neighborhood feature vectors — the shared semantic spec (SURVEY.md §2 P5).

A feature vector for pixel q at pyramid level l concatenates, in this fixed
order (Hertzmann §3.1: two-level concatenated neighborhoods):

    [ fine_src | fine_filt | coarse_src | coarse_filt | temporal ]

- ``fine_src``:    PxP window of the unfiltered plane (A or B) at level l,
                   per channel (C_s channels; channel-major blocks).
- ``fine_filt``:   PxP window of the filtered plane (A' or B') at level l,
                   **causally masked**: only offsets strictly before the
                   center in raster order (di<0, or di==0 and dj<0) — the
                   already-synthesized half (Hertzmann §3.1-3.2).  The DB side
                   (A') is masked identically so distances compare
                   like-with-like.
- ``coarse_src``:  CxC window of the unfiltered plane at level l+1, centered
                   at (i//2, j//2).
- ``coarse_filt``: CxC window of the filtered plane at level l+1, FULL window
                   (the coarser level is fully synthesized before level l
                   starts).  Absent at the coarsest level.
- ``temporal``:    (video mode only) PxP full window of the previous output
                   frame's B' (query side) / of A' (DB side) — the
                   temporal-coherence term (BASELINE.json:12).

All blocks are scaled elementwise by sqrt(w) where w are per-block-normalized
Gaussian weights (Hertzmann §3.1), so plain squared-L2 on features equals the
weighted patch distance.  Edge handling is edge-replicate (clamp) everywhere;
both backends share these exact functions' semantics and are tested for
bitwise-level agreement (SURVEY.md §4.3).
"""

from __future__ import annotations

import functools
from dataclasses import dataclass, field
from typing import List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np


def window_offsets(p: int) -> np.ndarray:
    """(p*p, 2) int32 offsets (di, dj), row-major di-then-dj."""
    r = p // 2
    return np.array(
        [(di, dj) for di in range(-r, r + 1) for dj in range(-r, r + 1)],
        dtype=np.int32,
    )


def causal_mask(p: int) -> np.ndarray:
    """(p*p,) float32; 1.0 for offsets strictly before center in raster order."""
    off = window_offsets(p)
    m = (off[:, 0] < 0) | ((off[:, 0] == 0) & (off[:, 1] < 0))
    return m.astype(np.float32)


def gaussian_window(p: int) -> np.ndarray:
    """(p*p,) float32 Gaussian weights over the window, normalized to sum 1.

    sigma = p/3 — fixed here once; both backends inherit it.
    """
    if p == 1:
        return np.ones((1,), dtype=np.float32)
    off = window_offsets(p).astype(np.float64)
    sigma = p / 3.0
    w = np.exp(-(off[:, 0] ** 2 + off[:, 1] ** 2) / (2.0 * sigma**2))
    return (w / w.sum()).astype(np.float32)


@dataclass(frozen=True)
class FeatureSpec:
    """Layout + weights of the feature space at one pyramid level."""

    fine_size: int  # P
    coarse_size: int  # C
    has_coarse: bool
    src_channels: int  # C_s
    src_weight: float = 1.0
    gaussian: bool = True
    temporal_weight: float = 0.0  # > 0 enables the temporal block

    @property
    def fine_n(self) -> int:
        return self.fine_size * self.fine_size

    @property
    def coarse_n(self) -> int:
        return self.coarse_size * self.coarse_size if self.has_coarse else 0

    @property
    def temporal_n(self) -> int:
        return self.fine_n if self.temporal_weight > 0 else 0

    # Block boundaries, in order.
    @property
    def block_sizes(self) -> List[int]:
        return [
            self.fine_n * self.src_channels,  # fine_src
            self.fine_n,  # fine_filt (causal)
            self.coarse_n * self.src_channels,  # coarse_src
            self.coarse_n,  # coarse_filt
            self.temporal_n,  # temporal
        ]

    @property
    def total(self) -> int:
        return int(sum(self.block_sizes))

    def slices(self) -> List[slice]:
        out, start = [], 0
        for s in self.block_sizes:
            out.append(slice(start, start + s))
            start += s
        return out

    @property
    def fine_filt_slice(self) -> slice:
        return self.slices()[1]

    def _window_w(self, p: int) -> np.ndarray:
        return gaussian_window(p) if self.gaussian else (
            np.full((p * p,), 1.0 / (p * p), dtype=np.float32))

    def weight_vector(self) -> np.ndarray:
        """(F,) per-element weights w (pre-sqrt)."""
        wf = self._window_w(self.fine_size)
        parts = [np.tile(wf, self.src_channels)
                 * (self.src_weight / max(self.src_channels, 1)),
                 wf.copy()]
        if self.has_coarse:
            wc = self._window_w(self.coarse_size)
            parts.append(np.tile(wc, self.src_channels)
                         * (self.src_weight / max(self.src_channels, 1)))
            parts.append(wc.copy())
        else:
            parts.append(np.zeros((0,), np.float32))
            parts.append(np.zeros((0,), np.float32))
        if self.temporal_weight > 0:
            parts.append(wf * self.temporal_weight)
        else:
            parts.append(np.zeros((0,), np.float32))
        return np.concatenate(parts).astype(np.float32)

    def sqrt_weights(self) -> np.ndarray:
        return np.sqrt(self.weight_vector()).astype(np.float32)

    def fine_causal(self) -> np.ndarray:
        """(fine_n,) float32 causal mask for the fine_filt block."""
        return causal_mask(self.fine_size)

    def query_live_mask(self) -> np.ndarray:
        """(F,) bool: dims that can be NONZERO in a query vector.

        Query vectors zero the non-causal half of the fine_filt block by
        construction (`written` masks to causal positions that were already
        synthesized); every other block is fully live (static B features,
        coarse B' windows, the temporal block).  The TPU backend's packed
        scan kernel streams only live dims — dead dims reach the score
        solely through the precomputed ||db||^2 term, EXACTLY (q is zero
        there), so dropping them from the dot loses nothing."""
        live = np.ones((self.total,), bool)
        live[self.fine_filt_slice] = causal_mask(self.fine_size) > 0
        return live


def spec_for_level(params, level: int, levels: int, src_channels: int,
                   temporal: bool = False) -> FeatureSpec:
    """FeatureSpec at `level` (0 = finest) of an `levels`-deep pyramid."""
    return FeatureSpec(
        fine_size=params.patch_size,
        coarse_size=params.coarse_patch_size,
        has_coarse=(level < levels - 1),
        src_channels=src_channels,
        src_weight=params.src_weight,
        gaussian=params.gaussian_weights,
        temporal_weight=params.temporal_weight if temporal else 0.0,
    )


# ---------------------------------------------------------------- NumPy twin


def extract_patches_np(img: np.ndarray, p: int) -> np.ndarray:
    """(H,W) -> (H*W, p*p) edge-replicated windows, offset order = window_offsets."""
    h, w = img.shape
    r = p // 2
    x = np.pad(img, r, mode="edge")
    cols = [x[di : di + h, dj : dj + w] for di in range(p) for dj in range(p)]
    return np.stack(cols, axis=-1).reshape(h * w, p * p).astype(np.float32)


def coarse_index_map_np(h: int, w: int, hc: int, wc: int) -> np.ndarray:
    """(H*W,) flat index into the coarse grid for each fine pixel: (i//2, j//2)."""
    ii, jj = np.meshgrid(np.arange(h), np.arange(w), indexing="ij")
    ic = np.minimum(ii // 2, hc - 1)
    jc = np.minimum(jj // 2, wc - 1)
    return (ic * wc + jc).reshape(-1).astype(np.int32)


def _as_channels(img: Optional[np.ndarray]) -> np.ndarray:
    if img.ndim == 2:
        return img[..., None]
    return img


def build_features_np(
    spec: FeatureSpec,
    src_fine: np.ndarray,  # (H,W) or (H,W,C_s)
    filt_fine: Optional[np.ndarray],  # (H,W) or None (query static part)
    src_coarse: Optional[np.ndarray],
    filt_coarse: Optional[np.ndarray],
    temporal_fine: Optional[np.ndarray] = None,
) -> np.ndarray:
    """(H*W, F) feature matrix.  fine_filt is always causally masked; pass
    filt_fine=None to leave that block zero (the per-pixel dynamic part)."""
    sf = _as_channels(np.asarray(src_fine, np.float32))
    h, w, cs = sf.shape
    assert cs == spec.src_channels, (cs, spec.src_channels)
    sw = spec.sqrt_weights()
    sl = spec.slices()
    out = np.zeros((h * w, spec.total), dtype=np.float32)

    for c in range(cs):
        blk = extract_patches_np(sf[..., c], spec.fine_size)
        s = sl[0].start + c * spec.fine_n
        out[:, s : s + spec.fine_n] = blk
    if filt_fine is not None:
        blk = extract_patches_np(np.asarray(filt_fine, np.float32),
                                 spec.fine_size)
        out[:, sl[1]] = blk * spec.fine_causal()[None, :]
    if spec.has_coarse:
        sc = _as_channels(np.asarray(src_coarse, np.float32))
        hc, wc, _ = sc.shape
        cmap = coarse_index_map_np(h, w, hc, wc)
        for c in range(cs):
            blk = extract_patches_np(sc[..., c], spec.coarse_size)[cmap]
            s = sl[2].start + c * spec.coarse_n
            out[:, s : s + spec.coarse_n] = blk
        blk = extract_patches_np(np.asarray(filt_coarse, np.float32),
                                 spec.coarse_size)[cmap]
        out[:, sl[3]] = blk
    if spec.temporal_n:
        tp = np.zeros((h, w), np.float32) if temporal_fine is None else (
            np.asarray(temporal_fine, np.float32))
        out[:, sl[4]] = extract_patches_np(tp, spec.fine_size)
    return out * sw[None, :]


# Per-pixel gather machinery for the scan loops (both backends) -------------


def fine_gather_maps(h: int, w: int, p: int):
    """Static per-level index maps for the evolving fine_filt gathers.

    Returns (flat_idx, valid, written) where
      flat_idx: (H*W, p*p) int32 — clipped flat indices into the (H,W) plane,
                per pixel, offset order = window_offsets.
      valid:    (H*W, p*p) float32 — 1.0 where the UNclipped neighbor is
                in-bounds AND causal (used for coherence-candidate validity).
      written:  (H*W, p*p) float32 — 1.0 where the offset is causal AND the
                CLIPPED index points at a pixel synthesized before q
                (flat < q).  The query-side B' gather uses this mask so border
                queries never read unwritten zeros as if they were data: a
                clamped read of an already-written pixel keeps its real value
                (mirroring the DB side's edge-replicate), while clamped reads
                landing at or after q contribute zero.  For interior pixels
                written == causal.
    """
    off = window_offsets(p)  # (n,2)
    ii, jj = np.meshgrid(np.arange(h), np.arange(w), indexing="ij")
    qi = ii.reshape(-1, 1) + off[None, :, 0]  # (H*W, n) unclipped
    qj = jj.reshape(-1, 1) + off[None, :, 1]
    inb = (qi >= 0) & (qi < h) & (qj >= 0) & (qj < w)
    ci = np.clip(qi, 0, h - 1)
    cj = np.clip(qj, 0, w - 1)
    flat = (ci * w + cj).astype(np.int32)
    causal = causal_mask(p)[None, :] > 0
    valid = (inb & causal).astype(np.float32)
    q = (ii * w + jj).reshape(-1, 1)
    written = (causal & (flat < q)).astype(np.float32)
    return flat, valid, written


# ------------------------------------------------------------------ JAX twin


def extract_patches_jax(img: jax.Array, p: int) -> jax.Array:
    """JAX mirror of `extract_patches_np` — static shifted slices, XLA fuses."""
    h, w = img.shape
    r = p // 2
    x = jnp.pad(img, r, mode="edge")
    cols = [
        jax.lax.dynamic_slice(x, (di, dj), (h, w))
        for di in range(p)
        for dj in range(p)
    ]
    return jnp.stack(cols, axis=-1).reshape(h * w, p * p).astype(jnp.float32)


@functools.lru_cache(maxsize=64)
def _clip_window_idx(h: int, w: int, p: int) -> np.ndarray:
    """(H*W, p*p) int32 flat indices of edge-clamped windows (= edge pad)."""
    off = window_offsets(p)
    ii, jj = np.meshgrid(np.arange(h), np.arange(w), indexing="ij")
    ci = np.clip(ii.reshape(-1, 1) + off[None, :, 0], 0, h - 1)
    cj = np.clip(jj.reshape(-1, 1) + off[None, :, 1], 0, w - 1)
    return (ci * w + cj).astype(np.int32)


def extract_patches_jax_gather(img: jax.Array, p: int) -> jax.Array:
    """Bit-identical twin of `extract_patches_jax` built as ONE clip-index
    gather instead of pad+shifted slices.  Exists for programs compiled with
    row-sharded `out_shardings` (the direct-sharded DB builders): XLA's SPMD
    partitioner miscompiles the edge-pad concatenate chain when the per-shard
    row count is not a multiple of the image width — every output element
    comes back exactly doubled (observed on the CPU backend at 10x10/4
    shards; jax 0.4.37).  A gather carries no halo arithmetic for the
    partitioner to get wrong, and returns the same values bit-for-bit."""
    h, w = img.shape
    idx = jnp.asarray(_clip_window_idx(h, w, p))
    return img.reshape(-1)[idx].astype(jnp.float32)


def build_features_jax(
    spec: FeatureSpec,
    src_fine: jax.Array,
    filt_fine: Optional[jax.Array],
    src_coarse: Optional[jax.Array],
    filt_coarse: Optional[jax.Array],
    temporal_fine: Optional[jax.Array] = None,
    edge_gather: bool = False,
) -> jax.Array:
    """JAX mirror of `build_features_np` (same layout, weights, masks).

    ``edge_gather`` swaps every window extraction to the clip-index gather
    twin — REQUIRED when this build is compiled with row-sharded
    out_shardings (see `extract_patches_jax_gather`); values are
    bit-identical either way."""
    patches = extract_patches_jax_gather if edge_gather else \
        extract_patches_jax
    sf = src_fine if src_fine.ndim == 3 else src_fine[..., None]
    h, w, cs = sf.shape
    sw = jnp.asarray(spec.sqrt_weights())
    parts = []
    for c in range(cs):
        parts.append(patches(sf[..., c], spec.fine_size))
    if filt_fine is not None:
        blk = patches(filt_fine, spec.fine_size)
        parts.append(blk * jnp.asarray(spec.fine_causal())[None, :])
    else:
        parts.append(jnp.zeros((h * w, spec.fine_n), jnp.float32))
    if spec.has_coarse:
        sc = src_coarse if src_coarse.ndim == 3 else src_coarse[..., None]
        hc, wc, _ = sc.shape
        cmap = jnp.asarray(coarse_index_map_np(h, w, hc, wc))
        for c in range(cs):
            parts.append(
                patches(sc[..., c], spec.coarse_size)[cmap])
        parts.append(
            patches(filt_coarse, spec.coarse_size)[cmap])
    if spec.temporal_n:
        tp = (jnp.zeros((h, w), jnp.float32) if temporal_fine is None
              else temporal_fine)
        parts.append(patches(tp, spec.fine_size))
    return jnp.concatenate(parts, axis=1) * sw[None, :]
