"""Gaussian pyramids (SURVEY.md §2 P4 / N2).

The reference builds pyramids with OpenCV/SciPy native blur kernels; the
TPU-native equivalent is a separable 5-tap binomial stencil expressed as an XLA
convolution (`lax.conv_general_dilated`) so it tiles onto the VPU/MXU — no
host round-trips (BASELINE.json:5 "Gaussian-pyramid build ... jax.vmap'd
stencils").

The NumPy twin is the semantic spec: both paths use the SAME kernel
([1,4,6,4,1]/16, separable), edge-replicate padding, and even-pixel
decimation, so backend-equivalence tests can require exact agreement.

Pyramid list convention: index 0 = finest (full resolution), index L-1 =
coarsest.  Synthesis iterates coarsest -> finest.
"""

from __future__ import annotations

from functools import partial
from typing import List

import jax
import jax.numpy as jnp
import numpy as np

# 5-tap binomial approximation of a Gaussian, the classic pyrDown kernel.
KERNEL_1D = np.array([1.0, 4.0, 6.0, 4.0, 1.0], dtype=np.float32) / 16.0


def min_level_size(patch_size: int) -> int:
    """Smallest usable level edge: at least one full patch."""
    return max(patch_size, 4)


def num_feasible_levels(shape, levels: int, patch_size: int) -> int:
    """Clamp requested depth so the coarsest level stays >= one patch."""
    h, w = shape[:2]
    n = 1
    while (
        n < levels
        and (h + 1) // 2 >= min_level_size(patch_size)
        and (w + 1) // 2 >= min_level_size(patch_size)
    ):
        h, w = (h + 1) // 2, (w + 1) // 2
        n += 1
    return n


# ---------------------------------------------------------------- NumPy twin


def blur_np(img: np.ndarray) -> np.ndarray:
    """Separable [1,4,6,4,1]/16 blur with edge-replicate padding, (H,W[,C])."""
    k = KERNEL_1D
    pad = [(2, 2), (0, 0)] + ([(0, 0)] if img.ndim == 3 else [])
    x = np.pad(img, pad, mode="edge")
    x = sum(k[i] * x[i : i + img.shape[0]] for i in range(5))
    pad = [(0, 0), (2, 2)] + ([(0, 0)] if img.ndim == 3 else [])
    x = np.pad(x, pad, mode="edge")
    x = sum(k[i] * x[:, i : i + img.shape[1]] for i in range(5))
    return x.astype(np.float32)


def downsample_np(img: np.ndarray) -> np.ndarray:
    return blur_np(img)[::2, ::2]


def build_pyramid_np(img: np.ndarray, levels: int) -> List[np.ndarray]:
    """[finest, ..., coarsest], length `levels`."""
    pyr = [np.asarray(img, dtype=np.float32)]
    for _ in range(levels - 1):
        pyr.append(downsample_np(pyr[-1]))
    return pyr


# ------------------------------------------------------------------ JAX twin


@jax.jit
def blur_jax(img: jax.Array) -> jax.Array:
    """Same stencil as `blur_np`, as an XLA conv on the device.

    Accepts (H,W) or (H,W,C); channels are independent (feature-grouped conv).
    """
    squeeze = img.ndim == 2
    if squeeze:
        img = img[..., None]
    h, w, c = img.shape
    x = jnp.pad(img, ((2, 2), (2, 2), (0, 0)), mode="edge")
    x = x.transpose(2, 0, 1)[None]  # NCHW
    k = jnp.asarray(KERNEL_1D)
    kern2d = jnp.outer(k, k)[None, None]  # (1,1,5,5)
    kern = jnp.tile(kern2d, (c, 1, 1, 1))  # (C,1,5,5) depthwise
    y = jax.lax.conv_general_dilated(
        x, kern, window_strides=(1, 1), padding="VALID",
        feature_group_count=c,
        dimension_numbers=("NCHW", "OIHW", "NCHW"),
        # fp32 accumulate: default precision is reduced on TPU and breaks
        # bitwise-level parity with the NumPy twin (SURVEY.md §7 hard part 2).
        precision=jax.lax.Precision.HIGHEST,
    )
    y = y[0].transpose(1, 2, 0)
    return y[..., 0] if squeeze else y


def downsample_jax(img: jax.Array) -> jax.Array:
    return blur_jax(img)[::2, ::2]


def build_pyramid_jax(img: jax.Array, levels: int) -> List[jax.Array]:
    """[finest, ..., coarsest], length `levels`.

    Shapes shrink per level, so this stays a Python-level list (each level is
    its own jitted conv; the per-level shapes are static).
    """
    pyr = [jnp.asarray(img, dtype=jnp.float32)]
    for _ in range(levels - 1):
        pyr.append(downsample_jax(pyr[-1]))
    return pyr
