"""Warmup + runtime wiring: persistent compile cache, AOT pre-compile.

``maybe_enable_compile_cache`` points JAX's persistent compilation cache
at ``AnalogyParams.compile_cache_dir`` (env ``IA_COMPILE_CACHE_DIR``
overrides) so program compiles survive process restarts — the natural
partner of shape-bucketing, which collapses the set of signatures worth
caching.  ``warmup`` runs one tiny-but-real synthesis at a target
resolution so every jit signature for that shape class is compiled (and,
with the cache enabled, persisted) before serving traffic; ``ia warmup``
is its CLI face.  ``apply_runtime_config`` is the one call the engine
makes per run to apply both this and the devcache budget.
"""

from __future__ import annotations

import os
from typing import Any, Dict, Optional

_CACHE_DIRS_APPLIED: set = set()


def compile_cache_dir(params: Any = None) -> Optional[str]:
    env = os.environ.get("IA_COMPILE_CACHE_DIR", "").strip()
    if env:
        return env
    return getattr(params, "compile_cache_dir", None)


def maybe_enable_compile_cache(params: Any = None) -> Optional[str]:
    """Idempotently enable JAX's persistent compilation cache when
    configured; returns the dir in effect (None = disabled)."""
    d = compile_cache_dir(params)
    if not d or d in _CACHE_DIRS_APPLIED:
        return d
    import jax

    jax.config.update("jax_compilation_cache_dir", d)
    # Cache even fast compiles: warmup exists to make serving compiles
    # zero, not just the slow ones.  Knob names vary across jax
    # versions; best-effort.
    for knob, val in (("jax_persistent_cache_min_compile_time_secs", 0.0),
                      ("jax_persistent_cache_min_entry_size_bytes", -1)):
        try:
            jax.config.update(knob, val)
        except Exception:
            pass
    _CACHE_DIRS_APPLIED.add(d)
    return d


def apply_runtime_config(params: Any = None) -> None:
    """Per-run runtime wiring: compile cache + devcache byte budget +
    exemplar-catalog root/budget."""
    maybe_enable_compile_cache(params)
    from image_analogies_tpu.utils import devcache

    mb = getattr(params, "devcache_max_bytes", None)
    if mb:
        devcache.set_max_bytes(int(mb))
    # Catalog wiring is unconditional so each run's params decide
    # activation (None clears a previous run's root); env IA_CATALOG_DIR
    # still wins inside catalog.tiers.root() — the fleet-operator path.
    # The tiers themselves persist across runs (that is the warmth).
    from image_analogies_tpu.catalog import tiers as catalog_tiers

    catalog_tiers.configure(
        root_dir=getattr(params, "catalog_dir", None),
        host_bytes=getattr(params, "catalog_host_bytes", None))


def warmup(params: Any, height: int, width: int, *,
           exemplar_height: Optional[int] = None,
           exemplar_width: Optional[int] = None,
           seed: int = 0) -> Dict[str, Any]:
    """AOT-compile the jit signatures for a target B resolution by
    running one real synthesis on synthetic planes.  With shape
    bucketing on, any image whose per-level row counts land in the same
    buckets then reuses these programs; with the persistent compile
    cache configured, later PROCESSES skip the XLA compiles too.

    Returns the compile counters of the warmup run."""
    import numpy as np

    from image_analogies_tpu.models.analogy import create_image_analogy
    from image_analogies_tpu.obs import metrics as _metrics
    from image_analogies_tpu.obs import trace as _trace

    eh = exemplar_height or height
    ew = exemplar_width or width
    rng = np.random.RandomState(seed)
    a = rng.rand(eh, ew).astype(np.float32)
    ap = rng.rand(eh, ew).astype(np.float32)
    b = rng.rand(height, width).astype(np.float32)
    wp = params.replace(metrics=True, checkpoint_dir=None,
                        resume_from_level=None, save_levels_dir=None)
    with _trace.run_scope(wp):
        create_image_analogy(a, ap, b, wp)
        snap = _metrics.snapshot() or {}
    counters = snap.get("counters", {})
    return {"height": height, "width": width,
        "exemplar": [eh, ew],
        "levels": wp.levels,
        "compile_count": counters.get("compile.count", 0),
        "compile_ms": counters.get("compile.ms", 0),
        "compile_cache_hits": counters.get("compile.cache_hits", 0),
        "compile_cache_dir": compile_cache_dir(wp)}


def warmup_buckets(params: Any, sizes, *, seed: int = 0):
    """AOT-precompile a set of (height, width) target sizes — the serve/
    lifecycle runs this over its configured bucket set before accepting
    traffic.  Returns one ``warmup`` summary per size."""
    return [warmup(params, int(h), int(w), seed=seed) for (h, w) in sizes]
