"""Measured autotuner: sweep kernel geometries, verify, persist.

``ia tune`` builds a sweep plan (:func:`build_plan`) and runs it on the
live device (:func:`run_plan`): for each knob a synthetic workload with
the production kernel entry points, min-of-k wall timing bracketed by
``obs.trace.span`` records, and — before anything is persisted — a
bit-identical champion check across ALL candidates (the cross-tile
strict-improve fold makes the argmin pick independent of tile geometry;
the tuner enforces that invariant rather than assuming it, so a kernel
regression can never be laundered into the store as a "fast" winner).

Winners land in the tune store under the bucket-wildcard key for the
swept (device, strategy, dtype, F) so one measurement covers every row
count of that shape class.  ``--dry-run`` prints the plan JSON and never
touches the device.
"""

from __future__ import annotations

import time
from typing import Any, Dict, List, Optional, Sequence

import numpy as np

from image_analogies_tpu.obs import trace as _trace
from image_analogies_tpu.tune import geometry as _geometry
from image_analogies_tpu.tune import resolve as _resolve
from image_analogies_tpu.tune import store as _store

# Candidate tile caps for the packed anchor scan (the round-5 hand-sweep
# grid, now measured per device class instead of frozen).
PACKED_TILE_CANDIDATES = (4096, 8192, 16384, 32768)

# Candidate slab sizes for the two-stage ANN matcher (`ia tune --knob
# ann`).  NOT part of the "all" sweep: an ANN sweep runs full synthesis
# pairs + tie audits (minutes, and it probes the parity gate), while
# "all" is the cheap kernel-geometry sweep operators run casually.
ANN_TOP_M_CANDIDATES = (16, 32, 64, 128)


def _argmin_candidates(fp: int) -> List[int]:
    base = _geometry.default_tile_rows(fp)
    return sorted({max(base // 2, 256), base, base * 2})


def build_plan(*, knob: str = "all", rows: int = 262144, f: int = 253,
               m: int = 1024, reps: int = 5,
               candidates: Optional[Sequence[int]] = None,
               store: Optional[str] = None) -> Dict[str, Any]:
    """The sweep plan: everything ``run_plan`` will do, as data.  ``f``
    is the raw feature width (lane-padded per kernel); ``rows`` the
    synthetic DB size (padded so every candidate tiles it evenly)."""
    device = _resolve.device_kind()
    sweeps: List[Dict[str, Any]] = []
    if knob in ("packed_tile", "all"):
        cands = sorted(set(int(c) for c in (candidates or
                                            PACKED_TILE_CANDIDATES)))
        if any(c < 256 or c & (c - 1) for c in cands):
            raise ValueError(
                f"packed_tile candidates must be powers of two >= 256, "
                f"got {cands}")
        # packed2k layout needs 4l+3 <= kp; l=63 fills kp=256 exactly.
        l = 63
        kp = _geometry.round_up(4 * l + 3, 128)
        npad = _geometry.round_up(rows, max(cands))
        sweeps.append({
            "knob": "packed_tile_cap",
            "kernel": "packed2k_best",
            "store_key": _resolve.make_key(device, "wavefront", "packed2",
                                           kp, "*"),
            "candidates": cands,
            "shape": {"npad": npad, "kp": kp, "l": l, "m": m},
        })
    if knob in ("argmin_tile", "all"):
        fp = max(_geometry.round_up(f, 128), 128)
        cands = sorted(set(int(c) for c in (candidates or
                                            _argmin_candidates(fp))))
        if any(c < 256 or c % 256 for c in cands):
            raise ValueError(
                f"argmin_tile candidates must be multiples of 256, "
                f"got {cands}")
        lcm = int(np.lcm.reduce(np.asarray(cands, np.int64)))
        npad = _geometry.round_up(rows, lcm)
        sweeps.append({
            "knob": "tile_rows",
            "kernel": "prepadded_argmin",
            "store_key": _resolve.make_key(device, "wavefront", "f32",
                                           fp, "*"),
            "candidates": cands,
            "shape": {"npad": npad, "fp": fp, "m": m},
        })
    if knob == "ann":
        cands = sorted(set(int(c) for c in (candidates or
                                            ANN_TOP_M_CANDIDATES)))
        if any(c < 1 for c in cands):
            raise ValueError(
                f"ann candidates must be positive slab sizes, got {cands}")
        sweeps.append({
            "knob": "ann_top_m",
            "kernel": "two_stage",
            # the canonical ANN key: slab size is a candidate COUNT, not
            # a tile shape, so every call site resolves it at the
            # wrapper defaults (wavefront|f32|f128) and one wildcard row
            # covers both strategies and every feature width
            "store_key": _resolve.make_key(device, "wavefront", "f32",
                                           128, "*"),
            "candidates": cands,
            "shape": {"size": 32, "levels": 2},
        })
    if not sweeps:
        raise ValueError(f"unknown tune knob {knob!r}")
    return {"device_kind": device, "reps": int(reps),
            "store": _store.store_path(store), "sweeps": sweeps}


def _time_call(fn, reps: int, **attrs) -> float:
    """min-of-k wall ms; one warmup call (compile) then k timed reps,
    each fully synchronized, each bracketed by a tune.candidate span."""
    import jax

    jax.block_until_ready(fn())  # warmup/compile outside timing
    best = float("inf")
    for _ in range(max(reps, 1)):
        with _trace.span("tune.candidate", **attrs):
            t0 = time.perf_counter()
            jax.block_until_ready(fn())
            ms = (time.perf_counter() - t0) * 1e3
        best = min(best, ms)
    return best


def _run_ann_sweep(sweep: Dict[str, Any], reps: int) -> Dict[str, Any]:
    """Sweep ann_top_m with FULL two-stage syntheses, one per candidate,
    each audited against an exact run.  Persistence criterion (ISSUE 13):
    only candidates whose audited first divergence is a tie (and whose
    mismatches are fully explained) may become the champion — a fast slab
    that loses parity is reported but never stored."""
    from image_analogies_tpu.backends import tpu as _tpu
    from image_analogies_tpu.models.analogy import create_image_analogy
    from image_analogies_tpu.utils.parity import audit_source_map_mismatches

    shape = sweep["shape"]
    a, ap, b = _tpu._bf16_probe_pair(shape["size"])
    base = _tpu._probe_base_params(levels=shape["levels"],
                                   strategy="wavefront")
    exact = create_image_analogy(a, ap, b, base, keep_levels=True)
    results: List[Dict[str, Any]] = []
    for cand in sweep["candidates"]:
        with _resolve.override(ann_top_m=cand), _tpu.ann_gate_bypass():
            ann_params = base.replace(ann_prefilter=True)
            run = lambda: create_image_analogy(a, ap, b, ann_params,
                                               keep_levels=True)
            res = run()  # warmup/compile outside timing
            best = float("inf")
            for _ in range(max(reps, 1)):
                with _trace.span("tune.candidate", knob="ann_top_m",
                                 candidate=cand):
                    t0 = time.perf_counter()
                    res = run()
                    best = min(best, (time.perf_counter() - t0) * 1e3)
        audit = audit_source_map_mismatches(a, ap, b, base, res.levels,
                                            exact.levels)
        tie_ok = (audit["unexplained"] == 0
                  and audit["first_divergence_is_tie"] is not False)
        results.append({"candidate": cand, "ms": round(best, 3),
                        "tie_ok": tie_ok,
                        "explained": audit["mismatch_explained_by_ties"]})
    clean = [r for r in results if r["tie_ok"]]
    best = min(clean, key=lambda r: r["ms"]) if clean else None
    return {"knob": sweep["knob"], "store_key": sweep["store_key"],
            "results": results, "verified": bool(clean),
            "winner": best["candidate"] if best else None,
            "winner_ms": best["ms"] if best else None}


def _run_sweep(sweep: Dict[str, Any], reps: int,
               interpret: bool) -> Dict[str, Any]:
    import jax.numpy as jnp

    from image_analogies_tpu.ops.pallas_match import (
        pallas_argmin_l2_prepadded,
        packed2k_best,
    )

    if sweep["kernel"] == "two_stage":
        return _run_ann_sweep(sweep, reps)

    rng = np.random.RandomState(0)
    shape = sweep["shape"]
    results: List[Dict[str, Any]] = []
    picks: List[np.ndarray] = []
    if sweep["kernel"] == "packed2k_best":
        npad, kp, l, m = (shape["npad"], shape["kp"], shape["l"],
                          shape["m"])
        wk = jnp.asarray(rng.randn(npad, kp).astype(np.float32),
                         jnp.bfloat16)
        q1 = jnp.asarray(rng.randn(m, l).astype(np.float32), jnp.bfloat16)
        q2 = jnp.asarray(rng.randn(m, l).astype(np.float32), jnp.bfloat16)
        for cand in sweep["candidates"]:
            tile = _resolve.snap_tile_to_divisor(cand, npad)
            call = lambda t=tile: packed2k_best(q1, q2, wk, tile_n=t,
                                                interpret=interpret)
            ms = _time_call(call, reps, knob=sweep["knob"], candidate=cand)
            idx, val = call()
            picks.append(np.asarray(idx))
            results.append({"candidate": cand, "tile_n": tile,
                            "ms": round(ms, 3)})
    else:
        npad, fp, m = shape["npad"], shape["fp"], shape["m"]
        dbp = jnp.asarray(rng.randn(npad, fp).astype(np.float32))
        dbn = (jnp.sum(dbp * dbp, axis=1))[None, :]
        q = jnp.asarray(rng.randn(max(m, 8), fp).astype(np.float32))
        for cand in sweep["candidates"]:
            tile = _resolve.snap_tile_to_divisor(cand, npad)
            call = lambda t=tile: pallas_argmin_l2_prepadded(
                q, dbp, dbn, tile_n=t, interpret=interpret)
            ms = _time_call(call, reps, knob=sweep["knob"], candidate=cand)
            idx, val = call()
            picks.append(np.asarray(idx))
            results.append({"candidate": cand, "tile_n": tile,
                            "ms": round(ms, 3)})

    verified = all(np.array_equal(picks[0], p) for p in picks[1:])
    best = min(results, key=lambda r: r["ms"])
    return {"knob": sweep["knob"], "store_key": sweep["store_key"],
            "results": results, "verified": verified,
            "winner": best["candidate"], "winner_ms": best["ms"]}


def run_plan(plan: Dict[str, Any], *, interpret: bool = False,
             persist: bool = True) -> Dict[str, Any]:
    """Execute a plan from :func:`build_plan`.  Champion picks must be
    bit-identical across every candidate of a sweep or that sweep's
    winner is NOT persisted (reported with ``verified: false``)."""
    out: List[Dict[str, Any]] = []
    winners: Dict[str, Dict[str, Any]] = {}
    for sweep in plan["sweeps"]:
        res = _run_sweep(sweep, plan["reps"], interpret)
        out.append(res)
        if res["verified"] and persist:
            entry = dict(winners.get(res["store_key"], {}))
            entry[res["knob"]] = int(res["winner"])
            entry["source"] = "ia tune"
            entry[f"{res['knob']}_ms"] = res["winner_ms"]
            winners[res["store_key"]] = entry
    saved = None
    if winners and persist:
        saved = _store.merge_entries(winners, plan["store"])
    return {"device_kind": plan["device_kind"], "sweeps": out,
            "persisted": saved,
            "all_verified": all(r["verified"] for r in out)}
