"""Default kernel-geometry math (the pre-tune constants, verbatim).

Every number here is the hard-coded value the engine shipped with before
the tune/ subsystem existed — the round-5 hand-sweep winners from
``backends/tpu.py``.  ``resolve.py`` consults the persistent store and
the environment first and falls back to these functions, so an empty
store reproduces the legacy geometry bit-for-bit.

This module is PURE: no jax, no env reads, no store I/O — just the
arithmetic that turns (padded feature width, row count, VMEM budget)
into tile shapes.  That purity is what makes the defaults testable
against the legacy constants and reusable by the autotuner's sweep-plan
builder without touching a device.
"""

from __future__ import annotations

# Target score-matrix footprint of one Pallas argmin grid step, in
# elements: tile_n rows x 128-lane feature panels (legacy _ARGMIN_TILE).
ARGMIN_TILE = 8192

# Packed anchor-scan knobs (legacy _PACKED_TILE_CAP / _PACKED_VMEM_LIMIT,
# round-5 measured: 4096->5.745s ... 16384->5.084s ... 32768->5.284s).
DEFAULT_PACKED_TILE_CAP = 16384
DEFAULT_PACKED_VMEM_LIMIT = 110 * 2 ** 20

# Wavefront host-scheduling bound (legacy _WAVEFRONT_MAX_ROWS): the scan
# carry stores source-map indices as exact f32 values, so the A row count
# must stay below 2^24 (the f32 integer-exactness limit).  4096x4096
# exemplars fit; anything larger must shard.  Tunable only DOWN from the
# correctness ceiling (a host with a slow schedule builder may cap rows
# earlier); resolve.py clamps any larger configured value back to this.
WAVEFRONT_MAX_ROWS_CEILING = 1 << 24
DEFAULT_WAVEFRONT_MAX_ROWS = WAVEFRONT_MAX_ROWS_CEILING

# Batched B-axis engine waste ceiling, in percent: a lane whose query
# rows must pad by more than this fraction of its bucket refuses the
# batched path and falls back to sequential (the padded rows are dead
# FLOPs in every scan row, so past ~1/4 the "shared program" win loses
# to the wasted compute).  Worst-case bucket pad is ~33% (just past a
# 3*2^k midpoint), so 25 admits most bucket residents while refusing
# the pathological just-past-a-bucket-edge shapes.
DEFAULT_BATCH_PAD_WASTE = 25

# Two-stage ANN matcher knobs (ROADMAP item 3): the prefilter selects a
# top-m candidate slab per query from PCA-projected distances, then the
# exact-f32 scorer re-scores only the slab.  64 keeps recall high enough
# that divergences from exact stay inside the tie-audit's resolution
# band at probe sizes while still pruning >90% of large DBs; 32
# projection dims capture essentially all variance of the ~30-200-wide
# patch feature vectors (texture features are low-rank).
DEFAULT_ANN_TOP_M = 64
DEFAULT_ANN_PROJ_DIMS = 32


def round_up(n: int, m: int) -> int:
    return -(-n // m) * m


def default_tile_rows(f: int) -> int:
    """Rows per Pallas argmin tile for feature width ``f`` (legacy
    ``_tile_rows``): scale inversely with the padded feature width so the
    per-tile score block stays near ARGMIN_TILE*128 elements, floored at
    512 and snapped down to a multiple of 256 (the kernel's row quantum).
    """
    fp = max(round_up(f, 128), 128)
    return max(512, ARGMIN_TILE * 128 // fp // 256 * 256)


def scan_tile_rows(npad: int, cap_rows: int) -> int:
    """Anchor-scan tile height for a DB padded to ``npad`` rows (legacy
    ``_scan_tile`` with the cap made explicit): the largest power of two
    that divides npad, bounded by ``cap_rows`` (snapped down to a power
    of two, floored at 256), then halved until the grid has >= 16 steps
    so short DBs still pipeline.
    """
    p2_npad = npad & (-npad)
    cap = max(cap_rows, 256)
    cap = 1 << (cap.bit_length() - 1)
    tile = min(cap, p2_npad, npad)
    while npad // tile < 16 and tile >= 256:
        tile //= 2
    return tile


def vmem_bounded_tile_cap(hb: int, wb: int, n_off: int,
                          tile_cap: int, vmem_limit: int) -> int:
    """Packed-scan tile cap bounded by the VMEM budget (legacy
    ``_packed_tile_cap`` with the two knobs passed in): estimate the
    plateau query-batch height from the B extent and the candidate
    window, then cap the DB tile so scratch + both streams fit in ~45%
    of ``vmem_limit``; never below 256, always a power of two, never
    above ``tile_cap``.
    """
    p5 = int(round(n_off ** 0.5))
    m_plateau = min(hb, -(-wb // (p5 // 2 + 1)))
    mp = max(round_up(max(m_plateau, 8), 16), 16)
    budget = int(0.45 * (vmem_limit or 64 * 2 ** 20))
    m_cap = max(budget // (mp * 4), 256)
    m_cap = 1 << (m_cap.bit_length() - 1)
    return min(tile_cap, m_cap)
