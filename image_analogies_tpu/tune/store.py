"""Persistent tune store: measured geometry winners, keyed per device.

One JSON file holds every tuned ``TuneConfig``:

    {
      "version": 1,
      "entries": {
        "TPU v4|wavefront|packed2|f256|b4096": {
          "tile_rows": 8192,
          "packed_tile_cap": 16384,
          "packed_vmem_limit": 115343360,
          "source": "ia tune",           # free-form provenance
          "measured_ms": 5.08            # optional, informational
        },
        ...
      }
    }

Path precedence: explicit argument > ``IA_TUNE_STORE`` env > the
repo-local default ``<repo>/.ia_tune.json``.  Loading is cached on
(path, mtime, size) so the resolution layer can consult the store on
every call without re-reading the file; a corrupt or invalid store emits
one ``tune_store_error`` warning record (when a run is active) and
resolves as empty — never a crash, never partial entries.
"""

from __future__ import annotations

import json
import os
import threading
from typing import Any, Dict, Optional, Tuple

from image_analogies_tpu.obs import trace as _trace
from image_analogies_tpu.utils import logging as _logging

SCHEMA_VERSION = 1

# Integer knobs an entry may carry; each must be a positive int when
# present.  Unknown keys are allowed (provenance annotations).
_KNOBS = ("tile_rows", "packed_tile_cap", "packed_vmem_limit",
          "wavefront_max_rows", "ann_top_m", "ann_proj_dims")

_LOCK = threading.Lock()
# path -> ((mtime_ns, size), entries)
_CACHE: Dict[str, Tuple[Tuple[int, int], Dict[str, Dict[str, Any]]]] = {}
_WARNED: set = set()  # paths whose corruption was already reported


def _repo_root() -> str:
    return os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))


def store_path(explicit: Optional[str] = None) -> str:
    if explicit:
        return explicit
    env = os.environ.get("IA_TUNE_STORE", "").strip()
    if env:
        return env
    return os.path.join(_repo_root(), ".ia_tune.json")


def invalidate_cache() -> None:
    with _LOCK:
        _CACHE.clear()
        _WARNED.clear()


def _warn(path: str, reason: str) -> None:
    """One tune_store_error warning per corrupt path per process; routed
    to the active run's log when there is one."""
    with _LOCK:
        if path in _WARNED:
            return
        _WARNED.add(path)
    ctx = _trace._CURRENT
    _logging.emit({"event": "tune_store_error", "severity": "warning",
                   "path": path, "reason": reason},
                  ctx.log_path if ctx is not None else None)


def validate_entry(entry: Any) -> bool:
    if not isinstance(entry, dict):
        return False
    for k in _KNOBS:
        if k in entry:
            v = entry[k]
            if not isinstance(v, int) or isinstance(v, bool) or v <= 0:
                return False
    return True


def _parse(raw: Any, path: str) -> Dict[str, Dict[str, Any]]:
    if not isinstance(raw, dict):
        _warn(path, "store root is not an object")
        return {}
    if raw.get("version") != SCHEMA_VERSION:
        _warn(path, f"unsupported store version {raw.get('version')!r}")
        return {}
    entries = raw.get("entries")
    if not isinstance(entries, dict):
        _warn(path, "store has no entries object")
        return {}
    out: Dict[str, Dict[str, Any]] = {}
    for key, entry in entries.items():
        if isinstance(key, str) and validate_entry(entry):
            out[key] = entry
        else:
            _warn(path, f"invalid entry for key {key!r}")
    return out


def load_entries(path: Optional[str] = None) -> Dict[str, Dict[str, Any]]:
    """Validated entries of the store at ``path`` (resolved via
    :func:`store_path`); ``{}`` for missing/corrupt stores."""
    path = store_path(path)
    try:
        st = os.stat(path)
    except OSError:
        return {}
    stamp = (st.st_mtime_ns, st.st_size)
    with _LOCK:
        cached = _CACHE.get(path)
        if cached is not None and cached[0] == stamp:
            return cached[1]
    try:
        with open(path) as f:
            raw = json.load(f)
    except (OSError, ValueError) as e:
        _warn(path, f"unreadable store: {e}")
        return {}
    entries = _parse(raw, path)
    with _LOCK:
        _CACHE[path] = (stamp, entries)
    return entries


def save_entries(entries: Dict[str, Dict[str, Any]],
                 path: Optional[str] = None) -> str:
    """Atomically write ``entries`` (replacing the whole store)."""
    path = store_path(path)
    for key, entry in entries.items():
        if not (isinstance(key, str) and validate_entry(entry)):
            raise ValueError(f"invalid tune entry for key {key!r}")
    blob = json.dumps({"version": SCHEMA_VERSION, "entries": entries},
                      indent=2, sort_keys=True)
    d = os.path.dirname(os.path.abspath(path))
    os.makedirs(d, exist_ok=True)
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        f.write(blob + "\n")
    os.replace(tmp, path)
    invalidate_cache()
    return path


def merge_entries(new: Dict[str, Dict[str, Any]],
                  path: Optional[str] = None) -> str:
    """Merge ``new`` into the store at ``path`` (new keys win)."""
    merged = dict(load_entries(path))
    merged.update(new)
    return save_entries(merged, path)
