"""Packaged per-device-class geometry tables (ROADMAP open item).

Measured winners shipped with the package, keyed by TPU device class, so
a fresh install on known hardware starts from class-appropriate geometry
instead of the generic computed defaults — `serve` warmup on a v5e pod
slice should not have to re-measure what every v5e measures.

Precedence note: the issue sketch placed packaged tables between env and
store, but a persistent-store entry is a winner measured on the
operator's *actual device and shapes* while a packaged value covers the
device *class* — letting the class table shadow local measurements would
make `ia tune` a no-op on any device with a packaged row.  So the chain
is:  override > env > store > **packaged** > computed default.

Entries mirror the store's partial-knob shape: per class, a ``"*"`` row
of device-wide constants (VMEM budgets are per-device facts, not
per-shape), optionally refined by ``"{strategy}|{dtype}"`` rows.  The v4
row matches :mod:`tune.geometry` by construction — v4 is where the
round-5 hand sweep that produced those defaults ran; the table makes the
provenance explicit ("packaged", not "default") without changing values.
"""

from __future__ import annotations

from typing import Any, Dict, Optional

_MIB = 2 ** 20

# wavefront_max_rows is a host-scheduling bound, not a kernel shape: the
# f32 index-packing ceiling (2^24, tune.geometry) is a per-architecture
# fact, so every class ships it explicitly — provenance reads "packaged"
# on known hardware instead of "default", and a future class with a
# different carry encoding can lower it here without code changes.
TABLES: Dict[str, Dict[str, Dict[str, int]]] = {
    "v4": {
        # Reference class: the legacy defaults WERE the v4 sweep winners.
        # ANN slab: v4's MXU amortizes the slab gather well — the default
        # 64-candidate slab holds.
        "*": {"packed_tile_cap": 16384, "packed_vmem_limit": 110 * _MIB,
              "wavefront_max_rows": 1 << 24, "batch_pad_waste_pct": 25,
              "ann_top_m": 64, "ann_proj_dims": 32},
    },
    "v5e": {
        # 128 MiB VMEM (see pallas guide) but a narrower core than v4:
        # leave more compiler headroom and keep scan tiles smaller.
        # Narrower core also means pad-row FLOPs hurt more, so the
        # batched engine's waste ceiling is tighter than on v4/v5p.
        # ANN slab: the narrow core pays more per re-scored candidate, so
        # the slab is half the v4 default (recall guarded by the gate).
        "*": {"packed_tile_cap": 8192, "packed_vmem_limit": 96 * _MIB,
              "wavefront_max_rows": 1 << 24, "batch_pad_waste_pct": 20,
              "ann_top_m": 32, "ann_proj_dims": 32},
        "wavefront|bf16": {"tile_rows": 2048},
    },
    "v5p": {
        # More VMEM headroom + HBM bandwidth: larger tiles amortize the
        # per-grid-step overhead better.  ANN slab: bandwidth to spare —
        # a wider slab buys recall at near-zero marginal cost.
        "*": {"packed_tile_cap": 32768, "packed_vmem_limit": 120 * _MIB,
              "wavefront_max_rows": 1 << 24, "batch_pad_waste_pct": 25,
              "ann_top_m": 128, "ann_proj_dims": 32},
        "wavefront|bf16": {"tile_rows": 8192},
    },
}


# Packaged serve cost-rate priors (seconds per pixel*level*patch^2 work
# unit — serve/degrade.py's EWMA), keyed "{backend}|{class}".  Same idea
# as the geometry tables: a fresh server on known hardware should start
# its deadline estimates from a class-appropriate rate, not the generic
# optimistic prior.  A store entry (this device's own measured rate)
# always wins over these.  No cpu row on purpose: host speed varies too
# much across machines for a packaged number to beat the default-then-
# learn path.
COST_RATES: Dict[str, float] = {
    "tpu|v4": 4.0e-9,
    "tpu|v5e": 8.0e-9,
    "tpu|v5p": 2.5e-9,
}


def device_class(kind: str) -> Optional[str]:
    """Map a jax ``device_kind`` string to a table class; None when the
    device has no packaged table (CPU, GPU, unknown TPUs)."""
    k = (kind or "").lower()
    if "v5p" in k:
        return "v5p"
    if "v5e" in k or "v5 lite" in k or "v5lite" in k:
        return "v5e"
    if "v4" in k:
        return "v4"
    return None


def lookup(kind: str, strategy: str, dtype: str) -> Dict[str, Any]:
    """Merged packaged knobs for one resolution key ({} = no table)."""
    cls = device_class(kind)
    if cls is None:
        return {}
    table = TABLES.get(cls, {})
    merged = dict(table.get("*", {}))
    merged.update(table.get(f"{strategy}|{dtype}", {}))
    return merged
