"""Shape buckets: canonical DB row counts for jit-program reuse.

The wavefront runner's program signature depends on the padded DB row
count, so every distinct A size used to compile a fresh program even
when the arrays could share one.  ``bucket_rows`` snaps a row count up
to a small canonical set — powers of two plus the 3*2^k midpoints whose
power-of-two divisor is still >= 256 (the Pallas row quantum):

    256, 512, 768, 1024, 1536, 2048, 3072, 4096, 6144, 8192, ...

Worst-case padding waste is just above a power of two (1025 -> 1536,
~1.5x); the geometric spacing keeps the bucket count logarithmic in the
largest supported image.  Every bucket is a multiple of 256 with a
power-of-two divisor >= 256, which is exactly what ``_scan_tile`` /
``pallas_argmin_l2_prepadded`` need for their divisibility contracts.

Bucketing is opt-in (``AnalogyParams.shape_buckets`` or
``IA_SHAPE_BUCKETS=1``): with it off, pad shapes — and therefore program
signatures and outputs — are bit-identical to the pre-tune engine.

The same bucket ladder also serves the QUERY side (batch/engine.py):
the batched scan core pads each B plane's ``static_q`` row count up to
``bucket_rows(hb*wb)`` so differently-sized targets share one lane
program.  Query padding is honest by construction — the scan's row loop
only ever reads rows ``< hb*wb`` — and :func:`pad_waste_frac` quantifies
the dead rows so the engine can refuse lanes past the tuned ceiling
(``tune.resolve.batch_pad_waste_pct``).
"""

from __future__ import annotations

import os
from typing import Any


def bucket_rows(n: int) -> int:
    """Smallest bucket >= n from {2^k} U {3*2^(k-2) : 2^(k-2) >= 256}."""
    if n <= 256:
        return 256
    k = (n - 1).bit_length()
    three = 3 << (k - 2)
    if three >= n and (three & -three) >= 256:
        return three
    return 1 << k


def pad_waste_frac(n: int, bucket: int = 0) -> float:
    """Fraction of a bucket that is padding for ``n`` real rows.  The
    batched engine compares this against the tuned waste ceiling before
    admitting a lane (dead padded rows cost real FLOPs in every scan
    row, unlike the A-side pad which only widens one argmin)."""
    bucket = bucket or bucket_rows(n)
    if bucket <= 0 or n >= bucket:
        return 0.0
    return (bucket - n) / float(bucket)


def buckets_enabled(params: Any = None) -> bool:
    """Call-time gate: IA_SHAPE_BUCKETS env (non-empty wins outright,
    falsey spellings disable) > ``params.shape_buckets`` > off."""
    env = os.environ.get("IA_SHAPE_BUCKETS", "").strip().lower()
    if env:
        return env not in ("0", "false", "no", "off")
    return bool(getattr(params, "shape_buckets", False))
