"""Shape buckets: canonical DB row counts for jit-program reuse.

The wavefront runner's program signature depends on the padded DB row
count, so every distinct A size used to compile a fresh program even
when the arrays could share one.  ``bucket_rows`` snaps a row count up
to a small canonical set — powers of two plus the 3*2^k midpoints whose
power-of-two divisor is still >= 256 (the Pallas row quantum):

    256, 512, 768, 1024, 1536, 2048, 3072, 4096, 6144, 8192, ...

Worst-case padding waste is just above a power of two (1025 -> 1536,
~1.5x); the geometric spacing keeps the bucket count logarithmic in the
largest supported image.  Every bucket is a multiple of 256 with a
power-of-two divisor >= 256, which is exactly what ``_scan_tile`` /
``pallas_argmin_l2_prepadded`` need for their divisibility contracts.

Bucketing is opt-in (``AnalogyParams.shape_buckets`` or
``IA_SHAPE_BUCKETS=1``): with it off, pad shapes — and therefore program
signatures and outputs — are bit-identical to the pre-tune engine.
"""

from __future__ import annotations

import os
from typing import Any


def bucket_rows(n: int) -> int:
    """Smallest bucket >= n from {2^k} U {3*2^(k-2) : 2^(k-2) >= 256}."""
    if n <= 256:
        return 256
    k = (n - 1).bit_length()
    three = 3 << (k - 2)
    if three >= n and (three & -three) >= 256:
        return three
    return 1 << k


def buckets_enabled(params: Any = None) -> bool:
    """Call-time gate: IA_SHAPE_BUCKETS env (non-empty wins outright,
    falsey spellings disable) > ``params.shape_buckets`` > off."""
    env = os.environ.get("IA_SHAPE_BUCKETS", "").strip().lower()
    if env:
        return env not in ("0", "false", "no", "off")
    return bool(getattr(params, "shape_buckets", False))
