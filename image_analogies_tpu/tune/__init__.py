"""Measured kernel-geometry tuning (ISSUE 3).

Three layers:

- :mod:`tune.geometry` — the legacy default math (pure, device-free);
- :mod:`tune.store` + :mod:`tune.resolve` — the persistent JSON store
  and the per-call-site resolution funnel every kernel-shape knob now
  flows through (override > env > store > defaults);
- :mod:`tune.buckets` — shape bucketing for jit-program reuse.

:mod:`tune.autotune` (the ``ia tune`` sweep) and :mod:`tune.warmup`
(``ia warmup`` + compile-cache wiring) are imported lazily by the CLI —
NOT re-exported here — so importing ``tune`` from the backends never
pulls in the model layer.
"""

from image_analogies_tpu.tune.buckets import bucket_rows, buckets_enabled
from image_analogies_tpu.tune.geometry import (
    ARGMIN_TILE,
    DEFAULT_PACKED_TILE_CAP,
    DEFAULT_PACKED_VMEM_LIMIT,
    DEFAULT_WAVEFRONT_MAX_ROWS,
    WAVEFRONT_MAX_ROWS_CEILING,
    default_tile_rows,
    scan_tile_rows,
    vmem_bounded_tile_cap,
)
# NB: the low-level `resolve()` entry point is deliberately NOT
# re-exported by name — it would shadow the `tune.resolve` submodule
# attribute and break `from image_analogies_tpu.tune import resolve`.
from image_analogies_tpu.tune.resolve import (
    TuneConfig,
    device_kind,
    make_key,
    manifest_info,
    override,
    packed_tile_cap,
    packed_vmem_limit,
    provenance_snapshot,
    reset_provenance,
    scan_tile,
    snap_tile_to_divisor,
    tile_rows,
    wavefront_max_rows,
)
from image_analogies_tpu.tune.store import (
    SCHEMA_VERSION,
    invalidate_cache,
    load_entries,
    merge_entries,
    save_entries,
    store_path,
)

__all__ = [
    "ARGMIN_TILE",
    "DEFAULT_PACKED_TILE_CAP",
    "DEFAULT_PACKED_VMEM_LIMIT",
    "DEFAULT_WAVEFRONT_MAX_ROWS",
    "WAVEFRONT_MAX_ROWS_CEILING",
    "SCHEMA_VERSION",
    "TuneConfig",
    "bucket_rows",
    "buckets_enabled",
    "default_tile_rows",
    "device_kind",
    "invalidate_cache",
    "load_entries",
    "make_key",
    "manifest_info",
    "merge_entries",
    "override",
    "packed_tile_cap",
    "packed_vmem_limit",
    "provenance_snapshot",
    "reset_provenance",
    "save_entries",
    "scan_tile",
    "scan_tile_rows",
    "snap_tile_to_divisor",
    "store_path",
    "tile_rows",
    "vmem_bounded_tile_cap",
    "wavefront_max_rows",
]
