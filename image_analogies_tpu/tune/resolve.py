"""Geometry resolution: one funnel for every kernel-shape knob.

Call sites that used to read ``_tile_rows`` / ``_PACKED_TILE_CAP`` /
``_PACKED_VMEM_LIMIT`` directly now ask this module, which resolves a
:class:`TuneConfig` keyed by ``(device_kind, strategy, dtype, padded-F,
shape-bucket)`` with per-knob precedence:

    tuner override (thread-local)  >  env var  >  store entry
        >  packaged device-class table (tune.tables)  >  default

- **override**: the autotuner brackets its timed candidates with
  :func:`override` so the swept value flows through the SAME call sites
  production uses.
- **env**: ``IA_TILE_ROWS`` / ``IA_PACKED_TILE`` / ``IA_PACKED_VMEM`` /
  ``IA_WAVEFRONT_ROWS`` / ``IA_BATCH_PAD_WASTE`` / ``IA_ANN_TOP_M`` /
  ``IA_ANN_PROJ_DIMS``, parsed at CALL time
  (the legacy module-import
  read silently ignored later changes); invalid values warn once and are
  ignored.
- **store**: :mod:`tune.store` entries — exact key first, then the
  bucket-wildcard key (``...|b*``) so one measured winner can cover all
  row counts of a device/strategy/dtype/F combination.
- **packaged**: :mod:`tune.tables` per-device-class winners shipped with
  the package (v4/v5e/v5p), so known hardware skips generic geometry
  without any local measurement.
- **default**: :mod:`tune.geometry`, the legacy constants — an empty
  store with no env reproduces the pre-tune engine bit-for-bit (packaged
  tables only exist for real TPU device classes, so CPU test runs and
  unknown devices still hit these defaults).

Resolution happens on the host at trace time, so the returned ints are
baked into jit programs exactly like the old constants were.  Every
resolution records its origin in a process-local provenance registry
(:func:`provenance_snapshot` — bench.py attaches it to each result dict,
the run manifest carries the store summary) and bumps
``tune.store_hits`` / ``tune.fallbacks`` / ``tune.env_overrides``
counters when a metrics run is active.
"""

from __future__ import annotations

import contextlib
import math
import os
import sys
import threading
from dataclasses import dataclass, field
from typing import Any, Dict, Optional, Tuple

from image_analogies_tpu.obs import metrics as _metrics
from image_analogies_tpu.obs import trace as _trace
from image_analogies_tpu.tune import buckets as _buckets
from image_analogies_tpu.tune import geometry as _geometry
from image_analogies_tpu.tune import store as _store
from image_analogies_tpu.tune import tables as _tables
from image_analogies_tpu.utils import logging as _logging

_ENV_VARS = {
    "tile_rows": "IA_TILE_ROWS",
    "packed_tile_cap": "IA_PACKED_TILE",
    "packed_vmem_limit": "IA_PACKED_VMEM",
    "wavefront_max_rows": "IA_WAVEFRONT_ROWS",
    "batch_pad_waste_pct": "IA_BATCH_PAD_WASTE",
    "ann_top_m": "IA_ANN_TOP_M",
    "ann_proj_dims": "IA_ANN_PROJ_DIMS",
}

_TLS = threading.local()  # .overrides: Dict[str, int] while tuner active
_LOCK = threading.Lock()
_PROV: Dict[str, Dict[str, Any]] = {}  # store_key -> provenance record
_ENV_WARNED: set = set()


@dataclass(frozen=True)
class TuneConfig:
    """One resolved geometry: the three knobs plus where each came from.

    ``origin`` maps knob -> one of ``override|env|store|store_wildcard|
    default`` (as a tuple of pairs so the config stays hashable).
    """

    key: str
    tile_rows: int
    packed_tile_cap: int
    packed_vmem_limit: int
    origin: Tuple[Tuple[str, str], ...] = field(default=())
    store_key: str = ""
    # Host-scheduling bound, not a kernel shape: the wavefront scan packs
    # source-map indices into exact f32, so values are clamped to the
    # 2^24 correctness ceiling (tune DOWN only; see tune.geometry).
    wavefront_max_rows: int = _geometry.DEFAULT_WAVEFRONT_MAX_ROWS
    # Batched engine admission knob, not a kernel shape: max query-row
    # pad waste (percent of the bucket) before a lane refuses batching.
    batch_pad_waste_pct: int = _geometry.DEFAULT_BATCH_PAD_WASTE
    # Two-stage ANN matcher knobs: candidate slab size per query and the
    # PCA projection rank the prefilter scores against.
    ann_top_m: int = _geometry.DEFAULT_ANN_TOP_M
    ann_proj_dims: int = _geometry.DEFAULT_ANN_PROJ_DIMS

    def origin_of(self, knob: str) -> str:
        return dict(self.origin).get(knob, "default")


def device_kind() -> str:
    """Device class for the store key WITHOUT forcing backend init (same
    peek as obs.trace._device_info); "any" when nothing is known yet —
    resolution must never be the thing that initializes a device."""
    jax = sys.modules.get("jax")
    if jax is None:
        return "any"
    try:
        backends = sys.modules.get("jax._src.xla_bridge")
        if backends is None or not getattr(backends, "_backends", None):
            return "any"
        devs = jax.devices()
        return devs[0].device_kind if devs else "any"
    except Exception:
        return "any"


def make_key(device: str, strategy: str, dtype: str, fp: int,
             bucket: int) -> str:
    return f"{device}|{strategy}|{dtype}|f{fp}|b{bucket}"


def _env_int(knob: str) -> Optional[int]:
    var = _ENV_VARS[knob]
    raw = os.environ.get(var, "").strip()
    if not raw:
        return None
    try:
        v = int(raw)
        if v <= 0:
            raise ValueError(raw)
        return v
    except ValueError:
        with _LOCK:
            seen = var in _ENV_WARNED
            _ENV_WARNED.add(var)
        if not seen:
            ctx = _trace._CURRENT
            _logging.emit(
                {"event": "tune_env_error", "severity": "warning",
                 "var": var, "value": raw},
                ctx.log_path if ctx is not None else None)
        return None


@contextlib.contextmanager
def pin_scope():
    """Pin geometry for a scope: the FIRST resolution of each key walks
    the full chain (store I/O, provenance counters/records); repeats
    inside the scope return the pinned config with no consult at all.

    models/video.py brackets each clip with this so a TuneConfig
    resolves once per clip instead of once per frame batch — frame
    timings become byte-comparable and the obs provenance counters
    record exactly one consult per distinct geometry per clip.  Reentrant
    (an inner scope joins the outer pin cache); thread-local, so serve/
    workers pinning concurrent requests never share state.
    """
    prev = getattr(_TLS, "pins", None)
    if prev is None:
        _TLS.pins = {}
    try:
        yield
    finally:
        _TLS.pins = prev


@contextlib.contextmanager
def override(**knobs: int):
    """Thread-locally pin knobs (the autotuner's sweep lever); nests."""
    bad = set(knobs) - set(_ENV_VARS)
    if bad:
        raise ValueError(f"unknown tune knobs {sorted(bad)}")
    prev = getattr(_TLS, "overrides", None)
    merged = dict(prev or {})
    merged.update(knobs)
    _TLS.overrides = merged
    try:
        yield
    finally:
        _TLS.overrides = prev


def _record(cfg: TuneConfig, fp: int, bucket: int) -> None:
    origins = dict(cfg.origin)
    any_store = any(o.startswith("store") for o in origins.values())
    any_packaged = any(o == "packaged" for o in origins.values())
    any_env = any(o == "env" for o in origins.values())
    with _LOCK:
        fresh = cfg.store_key not in _PROV
        if fresh:
            _PROV[cfg.store_key] = {
                "key": cfg.store_key,
                "tile_rows": cfg.tile_rows,
                "packed_tile_cap": cfg.packed_tile_cap,
                "packed_vmem_limit": cfg.packed_vmem_limit,
                "wavefront_max_rows": cfg.wavefront_max_rows,
                "batch_pad_waste_pct": cfg.batch_pad_waste_pct,
                "ann_top_m": cfg.ann_top_m,
                "ann_proj_dims": cfg.ann_proj_dims,
                "origin": origins,
            }
    if _metrics._ACTIVE:
        if any_store:
            _metrics.inc("tune.store_hits")
        elif any_packaged:
            _metrics.inc("tune.packaged")
        else:
            _metrics.inc("tune.fallbacks")
        if any_env:
            _metrics.inc("tune.env_overrides")
    if fresh:
        ctx = _trace._CURRENT
        if ctx is not None:
            _logging.emit({"event": "tune_resolved", "key": cfg.store_key,
                           "tile_rows": cfg.tile_rows,
                           "packed_tile_cap": cfg.packed_tile_cap,
                           "packed_vmem_limit": cfg.packed_vmem_limit,
                           "wavefront_max_rows": cfg.wavefront_max_rows,
                           "batch_pad_waste_pct": cfg.batch_pad_waste_pct,
                           "ann_top_m": cfg.ann_top_m,
                           "ann_proj_dims": cfg.ann_proj_dims,
                           "origin": origins, "fp": fp, "bucket": bucket},
                          ctx.log_path)


def provenance_snapshot() -> Dict[str, Dict[str, Any]]:
    with _LOCK:
        return {k: dict(v) for k, v in _PROV.items()}


def reset_provenance() -> None:
    with _LOCK:
        _PROV.clear()


def resolve(*, strategy: str, dtype: str, fp: int, n_rows: int = 0,
            store: Optional[str] = None) -> TuneConfig:
    """The TuneConfig for one call site.  ``fp`` is the padded feature
    width the kernel sees, ``n_rows`` the (padded) DB row count the
    shape bucket is derived from (0 = unknown -> wildcard bucket)."""
    fp = max(_geometry.round_up(max(int(fp), 1), 128), 128)
    bucket = _buckets.bucket_rows(int(n_rows)) if n_rows else 0
    dev = device_kind()
    key = make_key(dev, strategy, dtype, fp, bucket)
    wild = make_key(dev, strategy, dtype, fp, "*")

    overrides = getattr(_TLS, "overrides", None) or {}
    pins = getattr(_TLS, "pins", None)
    pin_key = (key, store, tuple(sorted(overrides.items())))
    if pins is not None:
        pinned = pins.get(pin_key)
        if pinned is not None:
            return pinned

    entries = _store.load_entries(store)
    exact = entries.get(key)
    wildcard = entries.get(wild)
    packaged = _tables.lookup(dev, strategy, dtype)

    defaults = {
        "tile_rows": _geometry.default_tile_rows(fp),
        "packed_tile_cap": _geometry.DEFAULT_PACKED_TILE_CAP,
        "packed_vmem_limit": _geometry.DEFAULT_PACKED_VMEM_LIMIT,
        "wavefront_max_rows": _geometry.DEFAULT_WAVEFRONT_MAX_ROWS,
        "batch_pad_waste_pct": _geometry.DEFAULT_BATCH_PAD_WASTE,
        "ann_top_m": _geometry.DEFAULT_ANN_TOP_M,
        "ann_proj_dims": _geometry.DEFAULT_ANN_PROJ_DIMS,
    }
    values: Dict[str, int] = {}
    origin: Dict[str, str] = {}
    for knob, dflt in defaults.items():
        if knob in overrides:
            values[knob], origin[knob] = int(overrides[knob]), "override"
            continue
        env = _env_int(knob)
        if env is not None:
            values[knob], origin[knob] = env, "env"
            continue
        if exact is not None and knob in exact:
            values[knob], origin[knob] = int(exact[knob]), "store"
            continue
        if wildcard is not None and knob in wildcard:
            values[knob] = int(wildcard[knob])
            origin[knob] = "store_wildcard"
            continue
        if knob in packaged:
            values[knob], origin[knob] = int(packaged[knob]), "packaged"
            continue
        values[knob], origin[knob] = dflt, "default"

    # wavefront_max_rows is a correctness ceiling, not a perf sweet spot:
    # a store/env value may only LOWER it (f32-exact index packing caps
    # the A row count at 2^24 no matter what anyone configures).
    values["wavefront_max_rows"] = min(
        values["wavefront_max_rows"], _geometry.WAVEFRONT_MAX_ROWS_CEILING)

    cfg = TuneConfig(key=key, store_key=key,
                     origin=tuple(sorted(origin.items())), **values)
    _record(cfg, fp, bucket)
    if pins is not None:
        pins[pin_key] = cfg
    return cfg


# ---------------------------------------------------------------------------
# Call-site conveniences: each maps one legacy helper onto a resolution.


def _norm_dtype(dtype: str) -> str:
    return {"float32": "f32", "bfloat16": "bf16"}.get(dtype, dtype)


def tile_rows(f: int, *, strategy: str = "wavefront", dtype: str = "f32",
              n_rows: int = 0, store: Optional[str] = None) -> int:
    """Argmin tile rows for feature width ``f`` (legacy ``_tile_rows``)."""
    cfg = resolve(strategy=strategy, dtype=_norm_dtype(dtype), fp=f,
                  n_rows=n_rows, store=store)
    return cfg.tile_rows


def packed_vmem_limit(*, strategy: str = "wavefront",
                      dtype: str = "packed2", fp: int = 128,
                      n_rows: int = 0, store: Optional[str] = None) -> int:
    cfg = resolve(strategy=strategy, dtype=_norm_dtype(dtype), fp=fp,
                  n_rows=n_rows, store=store)
    return cfg.packed_vmem_limit


def packed_tile_cap(hb: int, wb: int, n_off: int, *,
                    strategy: str = "wavefront", dtype: str = "packed2",
                    fp: int = 128, n_rows: int = 0,
                    store: Optional[str] = None) -> int:
    """VMEM-bounded packed-scan cap (legacy ``_packed_tile_cap``) with
    the two budget knobs resolved through the store/env chain."""
    cfg = resolve(strategy=strategy, dtype=_norm_dtype(dtype), fp=fp,
                  n_rows=n_rows, store=store)
    return _geometry.vmem_bounded_tile_cap(
        hb, wb, n_off, cfg.packed_tile_cap, cfg.packed_vmem_limit)


def wavefront_max_rows(*, strategy: str = "wavefront", dtype: str = "f32",
                       fp: int = 128, n_rows: int = 0,
                       store: Optional[str] = None) -> int:
    """A-row bound for the wavefront scan (legacy ``_WAVEFRONT_MAX_ROWS``):
    a host-scheduling knob, clamped by resolution to the f32-exactness
    ceiling (2^24) — store/env entries can only tighten it."""
    cfg = resolve(strategy=strategy, dtype=_norm_dtype(dtype), fp=fp,
                  n_rows=n_rows, store=store)
    return cfg.wavefront_max_rows


def batch_pad_waste_pct(*, strategy: str = "batched", dtype: str = "f32",
                        fp: int = 128, n_rows: int = 0,
                        store: Optional[str] = None) -> int:
    """Batched-engine pad-waste ceiling in percent (``IA_BATCH_PAD_WASTE``):
    a lane padding its query rows by more than this fraction of the
    bucket refuses the batched path (dead FLOPs beat program sharing)."""
    cfg = resolve(strategy=strategy, dtype=_norm_dtype(dtype), fp=fp,
                  n_rows=n_rows, store=store)
    return cfg.batch_pad_waste_pct


def ann_top_m(*, strategy: str = "wavefront", dtype: str = "f32",
              fp: int = 128, n_rows: int = 0,
              store: Optional[str] = None) -> int:
    """Candidate-slab size for the two-stage ANN matcher
    (``IA_ANN_TOP_M``): how many prefilter survivors the exact-f32
    re-score walks per query.  Never a hard-coded call-site constant —
    the grep-lock on slab geometry pins every consumer to this funnel."""
    cfg = resolve(strategy=strategy, dtype=_norm_dtype(dtype), fp=fp,
                  n_rows=n_rows, store=store)
    return cfg.ann_top_m


def ann_proj_dims(*, strategy: str = "wavefront", dtype: str = "f32",
                  fp: int = 128, n_rows: int = 0,
                  store: Optional[str] = None) -> int:
    """PCA projection rank the ANN prefilter scores against
    (``IA_ANN_PROJ_DIMS``); catalog/build.py resolves it when sealing
    projection artifacts so build-time and request-time agree."""
    cfg = resolve(strategy=strategy, dtype=_norm_dtype(dtype), fp=fp,
                  n_rows=n_rows, store=store)
    return cfg.ann_proj_dims


def scan_tile(npad: int, fp: int, cap_rows: int = 0, *,
              strategy: str = "wavefront", dtype: str = "bf16",
              store: Optional[str] = None) -> int:
    """Anchor-scan tile (legacy ``_scan_tile``): cap defaults to half the
    resolved tile_rows for ``fp``, exactly like the legacy default."""
    if not cap_rows:
        cap_rows = tile_rows(fp, strategy=strategy, dtype=dtype,
                             n_rows=npad, store=store) // 2
    return _geometry.scan_tile_rows(npad, cap_rows)


def snap_tile_to_divisor(tile: int, npad: int) -> int:
    """Largest value <= tile that divides npad (>=1): belt-and-braces so
    a store/env-supplied tile can never trip a kernel divisibility
    assert.  Resolved defaults already divide every legal npad."""
    tile = max(min(int(tile), int(npad)), 1)
    g = math.gcd(tile, npad)
    if g == tile:
        return tile
    # largest divisor of npad not exceeding tile
    best = 1
    d = 1
    while d * d <= npad:
        if npad % d == 0:
            if d <= tile:
                best = max(best, d)
            q = npad // d
            if q <= tile:
                best = max(best, q)
        d += 1
    return best


def manifest_info(store: Optional[str] = None) -> Dict[str, Any]:
    """Run-manifest extras: where the store lives and how warm it is."""
    path = _store.store_path(store)
    entries = _store.load_entries(path)
    return {"tune_store": path, "tune_entries": len(entries)}
