"""Sharded patch-DB argmin over the device mesh (BASELINE.json:5).

The A/A' feature database is sharded row-wise across the ``db`` mesh axis;
each chip computes a local (min-distance, argmin) over its shard with the
fused Pallas kernel, and the global winner is resolved with a min+argmin
all-reduce: `all_gather` the per-shard (dist, global-index) pairs (one pair
per query — tiny) and select the minimum, ties -> lowest global index, i.e.
bitwise the same ordering as the single-chip kernel.

This is the framework's answer to SURVEY.md §5.7: the scaling axis of Image
Analogies is exemplar-database size, and it scales with pod size.
"""

from __future__ import annotations

from typing import Callable

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from image_analogies_tpu.parallel.mesh import shard_map
from image_analogies_tpu.ops.pallas_match import (
    _round_up,
    argmin_l2,
    packed2k_best,
    prepadded_argmin_queries,
    xla_argmin_l2,
)


def _shard_score(queries, db_shard, dbn_shard, *, force_xla: bool,
                 precision, tile_n: int = 2048):
    """Score raw (M, F) queries against ONE `shard_level_db` shard
    (features 128-lane-aligned, +inf norm padding) — the single dispatch
    used by the all-reduce and ring variants: XLA off-TPU, the prepadded
    Pallas entry when the shard's rows are tile-aligned, and the
    self-padding kernel entry otherwise (correct, one extra copy)."""
    m, f = queries.shape
    rows, fp = db_shard.shape
    if force_xla or jax.default_backend() != "tpu":
        qf = jnp.zeros((m, fp), jnp.float32).at[:, :f].set(queries)
        return xla_argmin_l2(qf, db_shard, dbn_shard)
    if rows % min(tile_n, rows) == 0:
        return prepadded_argmin_queries(
            queries, db_shard, dbn_shard[None, :], tile_n=tile_n,
            precision=precision)
    qf = jnp.zeros((m, fp), jnp.float32).at[:, :f].set(queries)
    return argmin_l2(qf, db_shard, dbn_shard, precision=precision)


def local_argmin_allreduce(queries, db_shard, dbn_shard, axis: str,
                           force_xla: bool = False,
                           precision=jax.lax.Precision.DEFAULT,
                           prepadded: bool = False, tile_n: int = 2048):
    """Per-shard fused argmin + the min+argmin all-reduce, for use INSIDE a
    shard_map whose mesh has axis ``axis`` carrying the DB rows.

    Per-shard winners are (M,) scalars, so the all_gather is D x M tiny;
    ties resolve to the lowest shard, matching the single-chip lowest-index
    tie-break (the returned index is in the PADDED global row space).  This
    is the ONE copy of the tie-break invariant both the standalone sharded
    matcher and the multi-frame video step rely on for oracle parity.

    With ``prepadded=True`` the shard came from `shard_level_db` (features
    128-lane-aligned, +inf norm padding): queries are lane-padded once per
    call, and when the shard's rows are tile-aligned the Pallas kernel's
    prepadded entry runs with no per-step copy work (unaligned rows fall
    back to the self-padding kernel entry — correct, just one extra copy)."""
    if prepadded:
        idx, d = _shard_score(queries, db_shard, dbn_shard,
                              force_xla=force_xla, precision=precision,
                              tile_n=tile_n)
    else:
        idx, d = argmin_l2(queries, db_shard, dbn_shard, force_xla=force_xla,
                           precision=precision)
    gidx = idx + jax.lax.axis_index(axis) * db_shard.shape[0]
    alld = jax.lax.all_gather(d, axis)  # (D, M)
    alli = jax.lax.all_gather(gidx, axis)  # (D, M)
    k = jnp.argmin(alld, axis=0)
    d = jnp.take_along_axis(alld, k[None], axis=0)[0]
    i = jnp.take_along_axis(alli, k[None], axis=0)[0]
    return i.astype(jnp.int32), d


def packed_champion_allreduce(q1, q2, wk_shard, axis: str, tile_n: int,
                              interpret: bool = False,
                              vmem_limit: int = 0):
    """Sharded twin of the single-chip exact_hi2_2p anchor scan: each chip
    runs the K-wide packed champion kernel (`packed2k_best` — the SAME
    kernel and weight layout as the single-chip anchor) over ITS shard,
    then the global winner resolves with a max+argmax all-reduce over
    ``axis``.

    Scan scores are globally comparable (the live-dim centering shift is
    computed over the FULL DB before sharding, identical rows pack into
    identical bf16 lanes — including the norm lanes), so cross-shard
    exact ties gather equal values and `argmax`'s first-occurrence rule
    picks the lowest shard — whose per-shard champion already holds the
    lowest in-shard index (the kernel's running-scratch strict-improve
    rule, locked equal to the per-tile-champions pipeline by
    tests/test_pallas_kernel.py) — i.e. the lowest GLOBAL index, bitwise
    the same tie-break as the single-chip packed scan.  Returns
    (global idx (M,), scan val (M,)); callers re-score the winner in
    exact fp32 through their sharded row-gather (the kappa rule's d_app
    never comes from scan space)."""
    li_loc, lv = packed2k_best(q1, q2, wk_shard, tile_n=tile_n,
                               interpret=interpret, vmem_limit=vmem_limit)
    li = li_loc + jax.lax.axis_index(axis) * wk_shard.shape[0]
    allv = jax.lax.all_gather(lv, axis)  # (D, M)
    alli = jax.lax.all_gather(li, axis)
    k2 = jnp.argmax(allv, axis=0)
    i = jnp.take_along_axis(alli, k2[None], axis=0)[0]
    v = jnp.take_along_axis(allv, k2[None], axis=0)[0]
    return i.astype(jnp.int32), v


def sharded_pad_geometry(n: int, f: int, shards: int, tile: int = 1):
    """(npad, fp) for a sharded level DB: per-shard rows are a multiple of
    ``tile`` capped at the 128-aligned per-shard need, features pad to the
    128-lane boundary.  The ONE definition shared by `shard_level_db` and
    the sharded feature builder (backends/tpu.py) so their layouts can
    never diverge."""
    fp = max(_round_up(f, 128), 128)
    per_shard = -(-n // shards)
    tile = min(max(tile, 1), max(_round_up(per_shard, 128), 128))
    return shards * _round_up(per_shard, tile), fp


def shard_level_db(score_db: jax.Array, score_dbn: jax.Array,
                   a_filt_flat: jax.Array, mesh: Mesh, tile: int = 1,
                   axis: str = "db"):
    """Tile- and lane-aligned sharded layout of a level's scoring DB.

    Per-shard row count R is a multiple of ``tile`` so each shard's Pallas
    argmin can use the prepadded kernel entry with ZERO per-step copy work
    (round-1 ADVICE item 5: the sharded path re-padded the DB every scan
    row); features pad to the 128-lane MXU boundary; padding rows carry +inf
    norms and can never win.  The A' value plane shards alongside so the
    scan's output writes also read only sharded state.

    Returns (dbp (S*R, Fp), dbnp (S*R,), afiltp (S*R,)) laid out over
    ``axis``.  Global row index == padded array index; real rows come first.
    """
    shards = mesh.shape[axis]
    n, f = score_db.shape
    npad, fp = sharded_pad_geometry(n, f, shards, tile)
    dbp = jnp.zeros((npad, fp), score_db.dtype).at[:n, :f].set(score_db)
    dbnp = jnp.full((npad,), jnp.inf, jnp.float32).at[:n].set(score_dbn)
    afp = jnp.zeros((npad,), jnp.float32).at[:n].set(a_filt_flat)
    spec_db = NamedSharding(mesh, P(axis, None))
    spec_n = NamedSharding(mesh, P(axis))
    return (jax.device_put(dbp, spec_db), jax.device_put(dbnp, spec_n),
            jax.device_put(afp, spec_n))


def make_sharded_argmin(mesh: Mesh, axis: str = "db",
                        force_xla: bool = False,
                        precision=jax.lax.Precision.DEFAULT) -> Callable:
    """Returns argmin_fn(queries (M,F), db_sharded, dbn_sharded) -> (idx, d):
    the standalone sharded k-NN entry (SURVEY.md §2.3 T2) over a
    `shard_level_db` layout.

    Queries are replicated over `axis`; the DB stays sharded.  The returned
    global index refers to the PADDED row space (real rows come first so
    indices < n are unaffected).  ``precision`` reaches the per-shard Pallas
    kernel: the wavefront parity path passes HIGHEST so sharded picks equal
    the oracle's argmin.
    """

    def local(q, db_shard, dbn_shard):
        return local_argmin_allreduce(q, db_shard, dbn_shard, axis,
                                      force_xla=force_xla,
                                      precision=precision, prepadded=True)

    return shard_map(
        local, mesh=mesh,
        in_specs=(P(), P(axis, None), P(axis)),
        out_specs=(P(), P()),
        check_rep=False,
    )


def make_ring_argmin(mesh: Mesh, axis: str = "db",
                     force_xla: bool = False,
                     precision=jax.lax.Precision.DEFAULT) -> Callable:
    """Ring-parallel sharded k-NN: BOTH queries and DB shard over ``axis``
    (SURVEY.md §5.7's nearest analogue of ring attention).

    Each chip starts with its own query tile; over D hops the tiles rotate
    around the ring via `lax.ppermute`, scoring the RESIDENT DB shard at
    every hop and carrying the running (best distance, best global index)
    with them.  After D hops every tile has visited every shard and is back
    home.  Versus `make_sharded_argmin` (replicated queries + one
    all_gather), the ring keeps per-chip query memory at M/D and moves only
    tile-sized messages per hop — the right trade when the query batch
    itself is too large to replicate (the "long-context" axis).

    Ties break to the lowest GLOBAL row index — lexicographic (d, gidx)
    carry — exactly matching the single-chip kernel and the all-reduce
    variant (locked by tests/test_sharded.py).

    Returns argmin_fn(queries (M, F), db_sharded, dbn_sharded) -> (idx, d);
    M must divide by the axis size (pad queries if needed).
    """
    n_shards = mesh.shape[axis]

    def local(q_tile, db_shard, dbn_shard):
        rows = db_shard.shape[0]
        me = jax.lax.axis_index(axis)
        # tile starting on chip `me` was authored by chip `me`; after k hops
        # chip `me` holds the tile of chip (me - k) — it just scores it
        # against its resident shard, whose global row offset is me * rows.

        def hop(k, carry):
            q, best_d, best_i = carry
            idx, d = _shard_score(q, db_shard, dbn_shard,
                                  force_xla=force_xla, precision=precision)
            gidx = idx + me * rows
            better = (d < best_d) | ((d == best_d) & (gidx < best_i))
            best_d = jnp.where(better, d, best_d)
            best_i = jnp.where(better, gidx, best_i)
            # rotate tiles one step around the ring (carry travels along)
            perm = [(i, (i + 1) % n_shards) for i in range(n_shards)]
            q = jax.lax.ppermute(q, axis, perm)
            best_d = jax.lax.ppermute(best_d, axis, perm)
            best_i = jax.lax.ppermute(best_i, axis, perm)
            return q, best_d, best_i


        m = q_tile.shape[0]
        init = (q_tile, jnp.full((m,), jnp.inf, jnp.float32),
                jnp.full((m,), jnp.iinfo(jnp.int32).max, jnp.int32))
        # D hops: visit every shard once; the D-th ppermute returns each
        # tile (and its carried best) to its home chip
        _, best_d, best_i = jax.lax.fori_loop(0, n_shards, hop, init)
        return best_i.astype(jnp.int32), best_d

    return shard_map(
        local, mesh=mesh,
        in_specs=(P(axis, None), P(axis, None), P(axis)),
        out_specs=(P(axis), P(axis)),
        check_rep=False,
    )
