"""Sharded patch-DB argmin over the device mesh (BASELINE.json:5).

The A/A' feature database is sharded row-wise across the ``db`` mesh axis;
each chip computes a local (min-distance, argmin) over its shard with the
fused Pallas kernel, and the global winner is resolved with a min+argmin
all-reduce: `all_gather` the per-shard (dist, global-index) pairs (one pair
per query — tiny) and select the minimum, ties -> lowest global index, i.e.
bitwise the same ordering as the single-chip kernel.

This is the framework's answer to SURVEY.md §5.7: the scaling axis of Image
Analogies is exemplar-database size, and it scales with pod size.
"""

from __future__ import annotations

import functools
from typing import Callable, Tuple

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from image_analogies_tpu.parallel.mesh import shard_map
from image_analogies_tpu.ops.pallas_match import argmin_l2


def shard_db(db: jax.Array, db_sqnorm: jax.Array, mesh: Mesh,
             axis: str = "db") -> Tuple[jax.Array, jax.Array]:
    """Pad DB rows to a multiple of the axis size and lay them out sharded.

    Padding rows get +inf sqnorm so they can never win the argmin.
    """
    shards = mesh.shape[axis]
    n, f = db.shape
    npad = (n + shards - 1) // shards * shards
    dbp = jnp.zeros((npad, f), db.dtype).at[:n].set(db)
    dbnp = jnp.full((npad,), jnp.inf, jnp.float32).at[:n].set(db_sqnorm)
    spec_db = NamedSharding(mesh, P(axis, None))
    spec_n = NamedSharding(mesh, P(axis))
    return (jax.device_put(dbp, spec_db), jax.device_put(dbnp, spec_n))


def local_argmin_allreduce(queries, db_shard, dbn_shard, axis: str,
                           force_xla: bool = False,
                           precision=jax.lax.Precision.DEFAULT):
    """Per-shard fused argmin + the min+argmin all-reduce, for use INSIDE a
    shard_map whose mesh has axis ``axis`` carrying the DB rows.

    Per-shard winners are (M,) scalars, so the all_gather is D x M tiny;
    ties resolve to the lowest shard, matching the single-chip lowest-index
    tie-break (the returned index is in the PADDED global row space).  This
    is the ONE copy of the tie-break invariant both the standalone sharded
    matcher and the multi-frame video step rely on for oracle parity."""
    idx, d = argmin_l2(queries, db_shard, dbn_shard, force_xla=force_xla,
                       precision=precision)
    gidx = idx + jax.lax.axis_index(axis) * db_shard.shape[0]
    alld = jax.lax.all_gather(d, axis)  # (D, M)
    alli = jax.lax.all_gather(gidx, axis)  # (D, M)
    k = jnp.argmin(alld, axis=0)
    d = jnp.take_along_axis(alld, k[None], axis=0)[0]
    i = jnp.take_along_axis(alli, k[None], axis=0)[0]
    return i.astype(jnp.int32), d


def make_sharded_argmin(mesh: Mesh, axis: str = "db",
                        force_xla: bool = False,
                        precision=jax.lax.Precision.DEFAULT) -> Callable:
    """Returns argmin_fn(queries (M,F), db_sharded, dbn_sharded) -> (idx, d).

    Queries are replicated over `axis`; the DB stays sharded.  The returned
    global index refers to the PADDED row space (callers built it via
    `shard_db`, real rows come first so indices < n are unaffected).
    ``precision`` reaches the per-shard Pallas kernel: the wavefront parity
    path passes HIGHEST so sharded picks equal the oracle's argmin.
    """

    def local(q, db_shard, dbn_shard):
        return local_argmin_allreduce(q, db_shard, dbn_shard, axis,
                                      force_xla=force_xla,
                                      precision=precision)

    return shard_map(
        local, mesh=mesh,
        in_specs=(P(), P(axis, None), P(axis)),
        out_specs=(P(), P()),
        check_rep=False,
    )
