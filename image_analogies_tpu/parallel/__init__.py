"""Device-mesh parallelism: sharded patch-DB argmin, video frame sharding."""
