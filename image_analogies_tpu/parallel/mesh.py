"""Device-mesh helpers (SURVEY.md §5.8).

The framework's two parallel axes (SURVEY.md §2.3 T2/T3):

- ``db``:   the A/A' patch database sharded across chips — exemplar size
  scales with pod size (BASELINE.json:5).
- ``data``: batched video B-frames sharded across chips (BASELINE.json:12).

Collectives ride the ICI mesh via `shard_map` + XLA (`all_gather`/`pmin`);
multi-host DCN meshes come for free from `jax.make_mesh` device ordering.
"""

from __future__ import annotations

import functools
from typing import Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

try:  # jax >= 0.8: stable jax.shard_map (check_rep renamed check_vma)
    from jax import shard_map as _shard_map_new

    def shard_map(f, mesh, in_specs, out_specs, check_rep=False):
        return _shard_map_new(f, mesh=mesh, in_specs=in_specs,
                              out_specs=out_specs, check_vma=check_rep)
except ImportError:  # pragma: no cover - older jax
    from jax.experimental.shard_map import shard_map  # noqa: F401


def make_mesh(db_shards: int = 1, data_shards: int = 1,
              devices: Optional[Sequence] = None) -> Mesh:
    """A (data, db) mesh over the available devices.

    `db_shards * data_shards` must divide the device count; surplus devices
    are left unused (single-chip dev boxes just get a 1x1 mesh).

    Default-device meshes are CACHED per (db_shards, data_shards): callers
    throughout the run (per-level feature builds, video phases) then share
    ONE Mesh object, so jit caches keyed on mesh identity never depend on
    Mesh.__eq__ saving them (round-2 VERDICT weak item 5)."""
    if devices is None:
        return _default_mesh(db_shards, data_shards)
    return _build_mesh(db_shards, data_shards, tuple(devices))


@functools.lru_cache(maxsize=16)
def _default_mesh(db_shards: int, data_shards: int) -> Mesh:
    return _build_mesh(db_shards, data_shards, tuple(jax.devices()))


def _build_mesh(db_shards: int, data_shards: int,
                devices: Tuple) -> Mesh:
    devices = list(devices)
    need = db_shards * data_shards
    if need > len(devices):
        raise ValueError(
            f"mesh needs {need} devices (data={data_shards} x db={db_shards}) "
            f"but only {len(devices)} are available")
    dev = np.asarray(devices[:need]).reshape(data_shards, db_shards)
    return Mesh(dev, ("data", "db"))


def pad_to_shards(n: int, shards: int) -> int:
    """Rows the DB must be padded to so every shard gets an equal slice."""
    return (n + shards - 1) // shards * shards
