"""Device-mesh helpers (SURVEY.md §5.8).

The framework's two parallel axes (SURVEY.md §2.3 T2/T3):

- ``db``:   the A/A' patch database sharded across chips — exemplar size
  scales with pod size (BASELINE.json:5).
- ``data``: batched video B-frames sharded across chips (BASELINE.json:12).

Collectives ride the ICI mesh via `shard_map` + XLA (`all_gather`/`pmin`);
multi-host DCN meshes come for free from `jax.make_mesh` device ordering.
"""

from __future__ import annotations

from typing import Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

try:  # jax >= 0.8: stable jax.shard_map (check_rep renamed check_vma)
    from jax import shard_map as _shard_map_new

    def shard_map(f, mesh, in_specs, out_specs, check_rep=False):
        return _shard_map_new(f, mesh=mesh, in_specs=in_specs,
                              out_specs=out_specs, check_vma=check_rep)
except ImportError:  # pragma: no cover - older jax
    from jax.experimental.shard_map import shard_map  # noqa: F401


def make_mesh(db_shards: int = 1, data_shards: int = 1,
              devices: Optional[Sequence] = None) -> Mesh:
    """A (data, db) mesh over the available devices.

    `db_shards * data_shards` must divide the device count; surplus devices
    are left unused (single-chip dev boxes just get a 1x1 mesh).
    """
    devices = list(devices if devices is not None else jax.devices())
    need = db_shards * data_shards
    if need > len(devices):
        raise ValueError(
            f"mesh needs {need} devices (data={data_shards} x db={db_shards}) "
            f"but only {len(devices)} are available")
    dev = np.asarray(devices[:need]).reshape(data_shards, db_shards)
    return Mesh(dev, ("data", "db"))


def pad_to_shards(n: int, shards: int) -> int:
    """Rows the DB must be padded to so every shard gets an equal slice."""
    return (n + shards - 1) // shards * shards
