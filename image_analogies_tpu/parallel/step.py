"""The fused multi-chip execution step: video frames x sharded patch DB.

One `shard_map` over the full ('data', 'db') mesh runs the REAL batched level
scan (backends/tpu.py `batched_scan_core`) for a batch of B frames:

- frames shard over the ``data`` axis (BASELINE.json:12 — batched video
  B-frames sharded over chips);
- the A/A' patch DB shards row-wise over the ``db`` axis; each chip computes
  a local fused argmin and the global winner is resolved with the min+argmin
  all-reduce (all_gather of per-shard (dist, index) pairs over 'db');
- coherence gathers read a replicated copy of the (rowsafe-masked) DB — the
  argmin matmul, which dominates compute and HBM traffic, is what shards.

This is both the production multi-chip path and what `__graft_entry__.
dryrun_multichip` compiles on an N-device virtual mesh.
"""

from __future__ import annotations

import functools
from typing import Tuple

import numpy as np

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P
from jax.experimental.shard_map import shard_map

from image_analogies_tpu.backends.tpu import TpuLevelDB, batched_scan_core
from image_analogies_tpu.ops.pallas_match import argmin_l2


def multichip_level_step(
    mesh: Mesh,
    frame_static_q: jax.Array,  # (T, Nb, F) per-frame query-side features
    db_shard_src: jax.Array,  # (Npad, F) rowsafe-masked DB, to shard on 'db'
    dbn_shard_src: jax.Array,  # (Npad,) (+inf on padding rows)
    template: TpuLevelDB,  # single-frame LevelDB carrying shared arrays/meta
    kappa_mult: float,
    force_xla: bool = False,
) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """Jit+shard_map'd whole-level scan for T frames.  Returns
    (bp (T, Nb), s (T, Nb), counts (T, 2) [n_coherence, n_refined])."""
    t_total = frame_static_q.shape[0]
    data_shards = mesh.shape["data"]
    db_shards = mesh.shape["db"]
    if t_total % data_shards:
        raise ValueError(f"{t_total} frames not divisible by "
                         f"data={data_shards}")
    if db_shard_src.shape[0] % db_shards:
        raise ValueError("DB rows must be padded to a multiple of db shards "
                         "(use parallel.sharded_match.shard_db)")
    t_local = t_total // data_shards
    shard_rows = db_shard_src.shape[0] // db_shards

    def local_step(static_q_loc, db_loc, dbn_loc, tmpl: TpuLevelDB, km):
        def approx_fn(queries):
            idx, d = argmin_l2(queries, db_loc, dbn_loc, force_xla=force_xla)
            gidx = idx + jax.lax.axis_index("db") * shard_rows
            alld = jax.lax.all_gather(d, "db")
            alli = jax.lax.all_gather(gidx, "db")
            k = jnp.argmin(alld, axis=0)
            d = jnp.take_along_axis(alld, k[None], axis=0)[0]
            i = jnp.take_along_axis(alli, k[None], axis=0)[0]
            return i.astype(jnp.int32), d

        bps, ss, cohs = [], [], []
        for t in range(t_local):
            dbt = TpuLevelDB(
                **{**{f: getattr(tmpl, f) for f in tmpl.__dataclass_fields__},
                   "static_q": static_q_loc[t]})
            bp, s, n_coh = batched_scan_core(dbt, km, approx_fn)
            bps.append(bp)
            ss.append(s)
            cohs.append(n_coh)
        return (jnp.stack(bps), jnp.stack(ss), jnp.stack(cohs))

    stepped = shard_map(
        functools.partial(local_step),
        mesh=mesh,
        in_specs=(P("data", None, None), P("db", None), P("db"), P(), P()),
        out_specs=(P("data", None), P("data", None), P("data", None)),
        check_rep=False,
    )
    return jax.jit(stepped)(frame_static_q, db_shard_src, dbn_shard_src,
                            template, jnp.float32(kappa_mult))
