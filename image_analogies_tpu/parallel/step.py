"""The fused multi-chip execution step: video frames x sharded patch DB.

One `shard_map` over the full ('data', 'db') mesh runs the REAL level scan
(backends/tpu.py `batched_scan_core` / `wavefront_scan_core`) for a batch of
B frames:

- frames shard over the ``data`` axis (BASELINE.json:12 — batched video
  B-frames sharded over chips) and are `jax.vmap`'d within a chip, so local
  frames batch through one traced program instead of a Python-unrolled loop;
- the A/A' patch DB shards row-wise over the ``db`` axis; each chip computes
  a local fused argmin (prepadded Pallas entry — the shards are tile- and
  lane-aligned by `shard_level_db`, so no per-step copy work) and the global
  winner is resolved with the min+argmin all-reduce (all_gather of per-shard
  (dist, index) pairs over 'db');
- coherence gathers and the A'-value reads ALSO run against the sharded
  arrays: a row lookup gathers each chip's local hits and psum-combines them
  over 'db', so NO chip ever materializes the whole DB — exemplar memory
  truly scales with pod size (BASELINE.json:5).  The per-step psum payload
  is M x window x F (a few MB), riding ICI.

The shard_map'd step is built ONCE per (mesh, strategy, force_xla) and kept
in a module-level jit whose identity is stable, so repeated level calls with
equal shapes reuse the compiled program (round-1 VERDICT weak item 2).

This is the production multi-chip path: `models/video.py` dispatches here
whenever ``params.data_shards > 1``, and `__graft_entry__.dryrun_multichip`
exercises the same entry on a virtual N-device mesh.
"""

from __future__ import annotations

import functools
from typing import Tuple

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from image_analogies_tpu.backends.tpu import (
    TpuLevelDB,
    batched_scan_core,
    wavefront_scan_core,
)
from image_analogies_tpu import chaos
from image_analogies_tpu.obs import device as obs_device
from image_analogies_tpu.obs import metrics as obs_metrics
from image_analogies_tpu.ops.pallas_match import bf16_split3
from image_analogies_tpu.parallel.mesh import shard_map
from image_analogies_tpu.parallel.sharded_match import (
    local_argmin_allreduce,
    packed_champion_allreduce,
)
from image_analogies_tpu.tune import resolve as tune


@functools.lru_cache(maxsize=None)
def _cached_multichip_step(mesh: Mesh, strategy: str, force_xla: bool,
                           precision, packed: bool,
                           packed_interpret: bool = False,
                           fused_live: bool = False,
                           query_parallel: bool = False):
    """Build the shard_map'd multi-frame level step once per
    (mesh, strategy, force_xla, precision, packed, fused_live); jit
    caching then keys on shapes.  ``packed`` switches the wavefront
    anchor's scan from the HIGHEST merged kernel to the exact_hi2_2p
    packed champion kernel per shard (same parity class, ~2x fewer MXU
    passes) — real-TPU meshes only; the signature grows by the wk shard
    input.  ``fused_live`` (packed wavefront + a dblive shard — the
    round-5 gather diet) scores coherence through a psum-gather of the
    SHARDED [live | dead norm | A'] rows: the per-step ICI payload drops
    from M x window x F full rows to L+2 columns, the anchor re-score
    rides the same gather (deferred d_app), and the A'-value psum
    disappears (the value comes back as a gathered column)."""

    def local_step(static_q_loc, db_loc, dbn_loc, af_loc, wk_loc, dbl_loc,
                   tmpl: TpuLevelDB, km):
        rows = db_loc.shape[0]
        f = tmpl.static_q.shape[1]

        def approx_fn(queries):
            # shards come from shard_level_db (lane-padded); the allreduce
            # helper picks the prepadded Pallas entry when rows align.
            # Geometry resolves at trace time (host), like every site.
            return local_argmin_allreduce(
                queries, db_loc, dbn_loc, "db", force_xla=force_xla,
                precision=precision, prepadded=True,
                tile_n=tune.tile_rows(f, strategy=strategy,
                                      dtype=str(db_loc.dtype),
                                      n_rows=rows))

        def scan_fn(queries):
            # globally-reduced pick, no re-score (see anchor_fn)
            if packed:
                qc = (queries
                      - tmpl.feat_mean[None, :queries.shape[1]])
                g1, g2, _ = bf16_split3(qc[:, tmpl.live_idx])
                p, _ = packed_champion_allreduce(
                    g1.astype(jnp.bfloat16), g2.astype(jnp.bfloat16),
                    wk_loc, "db",
                    # the same VMEM-aware cap the single-chip anchor uses
                    # (the per-shard kernel builds the same (M, tile) f32
                    # score block, and M plateaus at B's diagonal width
                    # regardless of sharding)
                    tile_n=tune.scan_tile(
                        wk_loc.shape[0], wk_loc.shape[1],
                        strategy=strategy, dtype="packed2",
                        cap_rows=tune.packed_tile_cap(
                            tmpl.hb, tmpl.wb, int(tmpl.off.shape[0]),
                            strategy=strategy, dtype="packed2",
                            fp=wk_loc.shape[1],
                            n_rows=wk_loc.shape[0])),
                    interpret=packed_interpret,
                    vmem_limit=0 if packed_interpret
                    else tune.packed_vmem_limit(
                        strategy=strategy, dtype="packed2",
                        fp=wk_loc.shape[1], n_rows=wk_loc.shape[0]))
            else:
                p, _ = approx_fn(queries)
            return p

        def anchor_fn(queries):
            # wavefront anchor contract (see backends.tpu.make_anchor_fn):
            # globally-reduced pick + exact fp32 re-score — through the
            # full-row psum gather, or deferred into the coherence
            # block's live-row gather (fused_live) — the kappa rule's
            # d_app never comes from scan space on any path.
            p = scan_fn(queries)
            if fused_live:
                return p, None  # wavefront_scan_core re-scores via
                #                 live_gather (same rows, same formula)
            return p, jnp.sum((row_fn(p) - queries) ** 2, axis=1)

        def _local(idx):
            """(local offset, in-shard mask) for global row indices."""
            loc = idx - jax.lax.axis_index("db") * rows
            inb = (loc >= 0) & (loc < rows)
            return jnp.clip(loc, 0, rows - 1), inb

        def row_fn(idx):
            # psum-gather: each chip contributes its local hits; no chip
            # holds the whole DB (the honest sharded-memory story)
            loc, inb = _local(idx)
            vals = jnp.where(inb[..., None], db_loc[loc], 0.0)
            return jax.lax.psum(vals, "db")[..., :f]

        def live_gather(idx):
            # the round-5 diet: L+2 columns instead of full-F rows
            loc, inb = _local(idx)
            vals = jnp.where(inb[..., None], dbl_loc[loc], 0.0)
            return jax.lax.psum(vals, "db")

        def afilt_fn(idx):
            loc, inb = _local(idx)
            return jax.lax.psum(jnp.where(inb, af_loc[loc], 0.0), "db")

        def one_frame(static_q):
            dbt = TpuLevelDB(
                **{**{f: getattr(tmpl, f) for f in tmpl.__dataclass_fields__},
                   "static_q": static_q})
            if strategy == "wavefront":
                return wavefront_scan_core(
                    dbt, km, anchor_fn, row_fn, afilt_fn,
                    live_gather=live_gather if fused_live else None,
                    data_axis="data" if query_parallel else None,
                    data_axis_size=(mesh.shape["data"]
                                    if query_parallel else 1))
            bp, s, counts = batched_scan_core(dbt, km, approx_fn, row_fn,
                                              afilt_fn)
            return bp, s, counts[0]

        # local frames batch through vmap (pallas_call and the collectives
        # both have batching rules), not a Python-unrolled loop
        return jax.vmap(one_frame)(static_q_loc)

    if query_parallel:
        # ONE image over BOTH axes (round-5, SURVEY §5.7): the frame axis
        # (T=1) replicates over 'data' and each data row scores its slice
        # of every anti-diagonal (wavefront_scan_core data_axis) against
        # its 'db' DB shards; outputs are replicated-identical.
        in_q = P(None, None, None)
        out = (P(None, None), P(None, None), P(None))
    else:
        in_q = P("data", None, None)
        out = (P("data", None), P("data", None), P("data"))
    stepped = shard_map(
        local_step,
        mesh=mesh,
        in_specs=(in_q, P("db", None), P("db"), P("db"),
                  P("db", None), P("db", None), P(), P()),
        out_specs=out,
        check_rep=False,
    )
    # lru-cached, so ONE shim per (mesh, strategy, ...) — its program key
    # then separates shapes, mirroring jit's own dispatch cache
    return obs_device.instrument(jax.jit(stepped), "mesh.multichip_step")


def multichip_level_step(
    mesh: Mesh,
    frame_static_q: jax.Array,  # (T, Nb, F) per-frame query-side features
    db_shard_src: jax.Array,  # (Npad, Fp) scoring DB, sharded on 'db'
    dbn_shard_src: jax.Array,  # (Npad,) (+inf on padding rows)
    afilt_shard_src: jax.Array,  # (Npad,) A' values, sharded alongside
    template: TpuLevelDB,  # single-frame LevelDB carrying shared arrays/meta
    kappa_mult: float,
    force_xla: bool = False,
    wk_shard: jax.Array = None,  # K-wide packed-scan shard
    # (build_sharded_db with packed=True); None -> HIGHEST merged scan
    packed_interpret: bool = False,  # tests: packed scan via the Pallas
    # interpreter on CPU meshes (overrides the force_xla packed gate)
    dbl_shard: jax.Array = None,  # (Npad, L+2) [live|dead norm|A'] shard
    # (round-5 gather diet); None keeps the full-row psum gathers
) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """Whole-level scan for T frames on the ('data','db') mesh.  Returns
    (bp (T, Nb), s (T, Nb), n_coherence (T,)).

    The scoring DB must match the template's strategy (rowsafe-masked for
    batched, full for wavefront) and use the `shard_level_db` /
    `sharded_pad_geometry` layout — production callers build it DIRECTLY
    sharded via `backends.tpu.build_sharded_db` and construct the template
    with `backends.tpu.make_level_template` (the step reads DB rows and A'
    values only through the sharded inputs, so the template must carry
    placeholders, never full per-chip DB arrays)."""
    chaos.site("mesh.step", frames=int(frame_static_q.shape[0]))
    t_total = frame_static_q.shape[0]
    data_shards = mesh.shape["data"]
    db_shards = mesh.shape["db"]
    # ONE frame on a data>1 mesh = query-parallel wavefront (the image's
    # anti-diagonals split over 'data'; frames can't shard any further)
    query_parallel = (t_total == 1 and data_shards > 1
                      and template.strategy == "wavefront")
    if t_total % data_shards and not query_parallel:
        raise ValueError(f"{t_total} frames not divisible by "
                         f"data={data_shards}")
    if db_shard_src.shape[0] % db_shards:
        raise ValueError("DB rows must be padded to a multiple of db shards "
                         "(build via backends.tpu.build_sharded_db or "
                         "parallel.sharded_match.shard_level_db)")
    precision = (jax.lax.Precision.HIGHEST
                 if template.strategy == "wavefront"
                 else jax.lax.Precision.DEFAULT)
    packed = (wk_shard is not None and template.strategy == "wavefront"
              and (not force_xla or packed_interpret))
    fused_live = packed and dbl_shard is not None
    if not packed:
        # tiny placeholder shard keeps ONE shard_map signature; the
        # non-packed anchor never reads it
        wk_shard = jnp.zeros((db_shards, 1), jnp.bfloat16)
    if not fused_live:
        dbl_shard = jnp.zeros((db_shards, 1), jnp.float32)
    step = _cached_multichip_step(mesh, template.strategy, force_xla,
                                  precision, packed,
                                  packed and packed_interpret, fused_live,
                                  query_parallel)
    if obs_metrics._ACTIVE:
        # host-side ESTIMATE of the per-step psum-gather payload (the
        # logical rows every chip contributes to, per frame): the nf
        # coherence candidates + 1 anchor row per pixel, each gather
        # moving (L+2) f32 columns on the fused-live diet or full-F rows
        # (+ the separate afilt psum) otherwise.  Counted here, not in
        # the traced step — tracing must stay observability-free.
        nb = int(template.static_q.shape[0])
        nf = int(template.flat_idx.shape[1])
        width = (int(dbl_shard.shape[1]) if fused_live
                 else int(template.static_q.shape[1]) + 1)
        obs_metrics.inc("mesh.level_steps")
        obs_metrics.inc("mesh.psum_gather_bytes",
                        t_total * nb * (nf + 1) * width * 4)
    return step(frame_static_q, db_shard_src, dbn_shard_src,
                afilt_shard_src, wk_shard, dbl_shard, template,
                jnp.float32(kappa_mult))
