"""Multi-host (DCN) initialization (SURVEY.md §5.8).

Single-slice multi-chip runs need nothing from this module: `make_mesh`
over `jax.devices()` rides ICI.  For MULTI-HOST pods/slices, JAX requires
`jax.distributed.initialize` before any device access; this module wraps it
with environment autodetection so the same CLI works on one host or many:

    # host 0
    python -m image_analogies_tpu.cli run ... \\
        --coordinator h0:1234 --num-processes 2 --process-id 0
    # host 1: same command with --process-id 1

After initialization, `jax.devices()` spans every host's chips and
`make_mesh(db_shards=..., data_shards=...)` lays the ('data','db') mesh over
the global device list — jax orders devices so the fast ICI dimension maps
to contiguous mesh axes, and the min+argmin all-reduce / psum row lookups
(parallel/step.py) ride ICI within a slice and DCN across slices with no
further code changes (XLA inserts the hierarchical collectives).
"""

from __future__ import annotations

import os
from typing import Optional


def initialize_distributed(
    coordinator_address: Optional[str] = None,
    num_processes: Optional[int] = None,
    process_id: Optional[int] = None,
) -> bool:
    """Initialize JAX's multi-host runtime when configured; no-op otherwise.

    Order of precedence: explicit args > JAX_COORDINATOR_ADDRESS /
    JAX_NUM_PROCESSES / JAX_PROCESS_ID env vars > cloud autodetection
    (jax.distributed.initialize with no args works on TPU pods where the
    metadata server provides topology).  Returns True if initialization ran.

    Must be called BEFORE any jax device/array API touches the backend.
    Single-process runs (the common case, and every test in this repo)
    simply skip it.
    """
    coordinator_address = (coordinator_address
                           or os.environ.get("JAX_COORDINATOR_ADDRESS"))
    if num_processes is None and "JAX_NUM_PROCESSES" in os.environ:
        num_processes = int(os.environ["JAX_NUM_PROCESSES"])
    if process_id is None and "JAX_PROCESS_ID" in os.environ:
        process_id = int(os.environ["JAX_PROCESS_ID"])

    if coordinator_address is None and num_processes is None:
        if process_id is not None:
            raise ValueError(
                "process_id given without coordinator_address/num_processes "
                "— a partially-configured multi-host run would silently "
                "start standalone and hang the other hosts")
        return False  # single-process: nothing to do

    import jax

    jax.distributed.initialize(
        coordinator_address=coordinator_address,
        num_processes=num_processes,
        process_id=process_id,
    )
    return True
