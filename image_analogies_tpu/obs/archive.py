"""Durable telemetry archive: the witness plane for soak.

Every observability surface before this one is deliberately fixed-memory
and in-RAM — the timeline ring folds closed windows away after its
1s->10s->60s tiers, the cost ledger is a bounded deque, anomaly hints
live in a 64-entry ring.  A week-long soak needs a *witness*: what did
the fleet look like six hours ago, what did p99.9 do across the night,
which shed decision preceded the RSS knee.  This module streams those
documents to disk and reads them back offline.

On-disk shape (journal idiom throughout):

- Append-only raw segments ``archive-%06d.jsonl`` under one root.  Each
  line is one sealed record ``{"seal", "ts", "seq", "kind", "doc"}``
  where ``seal`` is sha256 over the canonical JSON of the rest
  (``sort_keys`` + compact separators, first 32 hex chars) — exactly
  serve/journal.py's per-line seal, so a torn tail or a flipped bit
  fails verification on read: the valid prefix is kept, the damaged
  file moves aside as ``<name>.corrupt`` (never deleted — evidence).
- Record kinds: ``timeline`` / ``tenants`` (full endpoint documents —
  the replay contract is that the LAST sealed doc is returned verbatim,
  so round-trip is bit-identical by construction), ``cost`` (per-request
  ledger vectors), ``decision``, ``anomaly``.
- Bounded disk: segments rotate at ``max_segment_bytes``; when the raw
  tier exceeds ``max_total_bytes`` (or a segment outlives ``max_age_s``)
  the oldest raw segment is FOLDED into the coarser summary tier
  (``summary-%06d.jsonl``, rewritten tmp+rename): one sealed line per
  folded segment carrying the span, per-kind counts, and the last
  timeline/tenants doc — so even after compaction eats every raw byte,
  ``replay`` still reconstructs the newest state and ``inspect`` still
  accounts for every record ever written.

The module-level plane mirrors obs/timeline.py: ``_ARMED`` is one bool
and every producer helper checks it first — the disarmed path allocates
nothing (tracemalloc-locked in tests).  Producers: the fleet health
daemon and the standalone timeline sampler call :func:`sample` per
tick (throttled here); obs/ledger.py streams ``decision`` records.
Consumers: ``ia archive inspect|replay|diff``, ``ia top
--from-archive``, and the ``/archive/stats`` endpoint.

Pure stdlib, jax-free (grep-locked in tests/test_obs_live.py): offline
readers and sidecars must import this without an accelerator runtime.
"""

from __future__ import annotations

import hashlib
import json
import os
import re
import threading
import time
from typing import Any, Callable, Dict, Iterator, List, Optional, Tuple

from image_analogies_tpu import chaos
from image_analogies_tpu.obs import metrics as _metrics
from image_analogies_tpu.obs import trace as _trace

_SEGMENT_FMT = "archive-%06d.jsonl"
_SEGMENT_RE = re.compile(r"^archive-(\d{6})\.jsonl$")
_SUMMARY_FMT = "summary-%06d.jsonl"
_SUMMARY_RE = re.compile(r"^summary-(\d{6})\.jsonl$")

DEFAULT_MAX_SEGMENT_BYTES = 1 << 20   # rotate raw segments at 1 MiB
DEFAULT_MAX_TOTAL_BYTES = 64 << 20    # raw tier cap before compaction
DEFAULT_MAX_AGE_S = 7 * 24 * 3600.0   # fold segments older than a week
DEFAULT_SAMPLE_INTERVAL_S = 5.0       # sample() throttle

# Doc kinds whose latest instance a summary line preserves, so replay
# survives total compaction of the raw tier.
_WITNESS_KINDS = ("timeline", "tenants")


def _seal(record: Dict[str, Any]) -> str:
    blob = json.dumps(record, sort_keys=True, separators=(",", ":"))
    return hashlib.sha256(blob.encode()).hexdigest()[:32]


def _quarantine(path: str) -> str:
    """Journal-style quarantine: damaged evidence moves aside, never
    deleted.  Same contract as utils/checkpoint.quarantine with this
    plane's telemetry names (local so the offline reader does not drag
    in the checkpoint module's numpy import)."""
    qpath = path + ".corrupt"
    os.replace(path, qpath)
    _metrics.inc("obs.archive.quarantined")
    _trace.emit_record({"event": "archive_quarantined", "path": path})
    return qpath


def _read_sealed_lines(path: str) -> Tuple[List[Dict[str, Any]], bool]:
    """Verified records of one segment file (valid prefix) plus a
    damaged flag.  First seal failure stops the scan: everything after
    an unverifiable line is untrusted."""
    records: List[Dict[str, Any]] = []
    try:
        with open(path, "rb") as f:
            lines = f.read().splitlines()
    except OSError:
        return records, False
    for raw in lines:
        if not raw.strip():
            continue
        try:
            # binary read: a flipped byte may not even be valid UTF-8
            rec = json.loads(raw.decode())
            seal = rec.pop("seal")
            if _seal(rec) != seal:
                return records, True
        except (ValueError, KeyError, TypeError, UnicodeDecodeError):
            return records, True
        records.append(rec)
    return records, False


class TelemetryArchive:
    """One archive root: sealed ring segments plus the summary tier.

    Thread-safe writer; readers (:meth:`read`, :meth:`replay`) operate
    on whatever is durable, so a separate process can inspect a live
    archive.  The clock is injectable for deterministic tests."""

    def __init__(self, root: str,
                 max_segment_bytes: int = DEFAULT_MAX_SEGMENT_BYTES,
                 max_total_bytes: int = DEFAULT_MAX_TOTAL_BYTES,
                 max_age_s: float = DEFAULT_MAX_AGE_S,
                 sample_interval_s: float = DEFAULT_SAMPLE_INTERVAL_S,
                 clock: Callable[[], float] = time.time):
        self.root = root
        self.max_segment_bytes = int(max_segment_bytes)
        self.max_total_bytes = int(max_total_bytes)
        self.max_age_s = float(max_age_s)
        self.sample_interval_s = float(sample_interval_s)
        self._clock = clock
        self._lock = threading.Lock()
        os.makedirs(root, exist_ok=True)
        # Writer always opens a fresh segment above every existing index
        # (raw or summary): single-writer per segment, like the journal.
        taken = [i for i, _ in self._indexed(_SEGMENT_RE)]
        taken += [i for i, _ in self._indexed(_SUMMARY_RE)]
        self._seg_index = (max(taken) + 1) if taken else 0
        self._seg_bytes = 0
        self._seq = 0
        self._appended = 0
        self._dropped = 0
        self._compactions = 0
        self._last_sample = 0.0
        self._last_anomaly: Tuple[float, str] = (-1.0, "")
        self._seen_costs = 0

    # ----------------------------------------------------------- paths
    def _indexed(self, pat: re.Pattern) -> List[Tuple[int, str]]:
        out: List[Tuple[int, str]] = []
        try:
            names = os.listdir(self.root)
        except OSError:
            return out
        for name in names:
            m = pat.match(name)
            if m:
                out.append((int(m.group(1)), os.path.join(self.root, name)))
        return sorted(out)

    def _seg_path(self) -> str:
        return os.path.join(self.root, _SEGMENT_FMT % self._seg_index)

    # ----------------------------------------------------------- write
    def append(self, kind: str, doc: Any,
               now: Optional[float] = None) -> bool:
        """Seal one record onto the current segment.  Returns False when
        the record was dropped (injected or real disk trouble) — the
        archive is a witness, never a request-path dependency, so write
        failures count (``obs.archive.append_errors``) and drop rather
        than raise."""
        if now is None:
            now = self._clock()
        try:
            directive = chaos.site("archive.append", kind=kind)
        except Exception:
            # raising fault kinds model disk-full / EIO on the write
            with self._lock:
                self._dropped += 1
            _metrics.inc("obs.archive.append_errors")
            return False
        with self._lock:
            rec = {"ts": round(now, 3), "seq": self._seq,
                   "kind": kind, "doc": doc}
            line = json.dumps({"seal": _seal(rec), **rec},
                              sort_keys=True, separators=(",", ":"))
            path = self._seg_path()
            try:
                with open(path, "a") as f:
                    f.write(line + "\n")
            except (OSError, ValueError):
                self._dropped += 1
                _metrics.inc("obs.archive.append_errors")
                return False
            self._seq += 1
            self._appended += 1
            self._seg_bytes += len(line) + 1
            _metrics.inc("obs.archive.appended")
            if directive == "corrupt":
                # damage lands AFTER a successful-looking write — the
                # torn-segment drill's realistic failure shape.
                from image_analogies_tpu.chaos import faults as _faults
                _faults.corrupt_file(path, seed=self._seq, n_flips=1)
            if self._seg_bytes >= self.max_segment_bytes:
                self._seg_index += 1
                self._seg_bytes = 0
            self._compact_locked(now)
        return True

    def _compact_locked(self, now: float) -> None:
        """Fold oldest closed raw segments into the summary tier until
        the raw tier fits ``max_total_bytes`` and nothing closed is
        older than ``max_age_s``.  The summary file is rewritten
        tmp+rename, so a crash mid-compaction leaves either the old
        summary or the new one — never a torn hybrid."""
        while True:
            segs = self._indexed(_SEGMENT_RE)
            closed = [(i, p) for i, p in segs if i < self._seg_index]
            if not closed:
                return
            total = 0
            for _i, p in segs:
                try:
                    total += os.path.getsize(p)
                except OSError:
                    pass
            oldest_i, oldest_p = closed[0]
            try:
                age = now - os.path.getmtime(oldest_p)
            except OSError:
                age = 0.0
            if total <= self.max_total_bytes and age <= self.max_age_s:
                return
            self._fold_locked(oldest_i, oldest_p)

    def _fold_locked(self, seg_i: int, seg_path: str) -> None:
        records, damaged = _read_sealed_lines(seg_path)
        summ_doc: Dict[str, Any] = {"segment": seg_i,
                                    "records": len(records),
                                    "kinds": {}, "last": {}}
        if records:
            summ_doc["span"] = [records[0].get("ts"),
                                records[-1].get("ts")]
        for rec in records:
            k = str(rec.get("kind"))
            summ_doc["kinds"][k] = summ_doc["kinds"].get(k, 0) + 1
            if k in _WITNESS_KINDS:
                summ_doc["last"][k] = rec.get("doc")
        srec = {"ts": round(self._clock(), 3), "kind": "summary",
                "doc": summ_doc}
        sline = json.dumps({"seal": _seal(srec), **srec},
                           sort_keys=True, separators=(",", ":"))
        spath = os.path.join(self.root, _SUMMARY_FMT % 0)
        tmp = spath + ".tmp"
        try:
            existing = ""
            if os.path.exists(spath):
                with open(spath) as f:
                    existing = f.read()
            with open(tmp, "w") as f:
                f.write(existing + sline + "\n")
                f.flush()
                os.fsync(f.fileno())
            os.replace(tmp, spath)
        except OSError:
            _metrics.inc("obs.archive.append_errors")
            return
        if damaged:
            _quarantine(seg_path)
        else:
            try:
                os.remove(seg_path)
            except OSError:
                pass
        self._compactions += 1
        _metrics.inc("obs.archive.compactions")

    # ------------------------------------------------------------ read
    def read(self) -> Iterator[Dict[str, Any]]:
        """Every verified record on disk, summaries first, then raw
        segments in index order.  Damaged files yield their valid
        prefix and are quarantined ``.corrupt`` in place."""
        for _i, path in self._indexed(_SUMMARY_RE):
            records, damaged = _read_sealed_lines(path)
            if damaged:
                _quarantine(path)
            for rec in records:
                yield rec
        for _i, path in self._indexed(_SEGMENT_RE):
            records, damaged = _read_sealed_lines(path)
            if damaged:
                _quarantine(path)
            for rec in records:
                yield rec

    def history(self, kind: str) -> List[Dict[str, Any]]:
        """All archived docs of one kind, oldest first (summary-folded
        segments contribute their preserved last doc)."""
        out: List[Dict[str, Any]] = []
        for rec in self.read():
            if rec.get("kind") == kind:
                out.append(rec.get("doc"))
            elif rec.get("kind") == "summary":
                last = (rec.get("doc") or {}).get("last") or {}
                if kind in last:
                    out.append(last[kind])
        return out

    def replay(self) -> Dict[str, Any]:
        """Reconstruct the latest ``/timeline`` + ``/tenants`` documents
        (verbatim — the round-trip contract) plus totals over
        everything the archive witnessed."""
        timeline_doc: Optional[Dict[str, Any]] = None
        tenants_doc: Optional[Dict[str, Any]] = None
        kinds: Dict[str, int] = {}
        decisions: List[Dict[str, Any]] = []
        anomalies: List[Dict[str, Any]] = []
        span: List[Optional[float]] = [None, None]
        for rec in self.read():
            kind = str(rec.get("kind"))
            ts = rec.get("ts")
            if isinstance(ts, (int, float)):
                span[0] = ts if span[0] is None else min(span[0], ts)
                span[1] = ts if span[1] is None else max(span[1], ts)
            if kind == "summary":
                doc = rec.get("doc") or {}
                for k, n in (doc.get("kinds") or {}).items():
                    kinds[k] = kinds.get(k, 0) + int(n)
                last = doc.get("last") or {}
                if "timeline" in last:
                    timeline_doc = last["timeline"]
                if "tenants" in last:
                    tenants_doc = last["tenants"]
                continue
            kinds[kind] = kinds.get(kind, 0) + 1
            if kind == "timeline":
                timeline_doc = rec.get("doc")
            elif kind == "tenants":
                tenants_doc = rec.get("doc")
            elif kind == "decision":
                decisions.append(rec.get("doc"))
            elif kind == "anomaly":
                anomalies.append(rec.get("doc"))
        return {"timeline": timeline_doc, "tenants": tenants_doc,
                "kinds": kinds, "decisions": decisions,
                "anomalies": anomalies, "span": span}

    def stats(self) -> Dict[str, Any]:
        """The ``/archive/stats`` document + the ceilings watchdog's
        archive-disk-usage series."""
        segs = self._indexed(_SEGMENT_RE)
        summs = self._indexed(_SUMMARY_RE)
        total = 0
        for _i, p in segs + summs:
            try:
                total += os.path.getsize(p)
            except OSError:
                pass
        quarantined = 0
        try:
            quarantined = sum(1 for n in os.listdir(self.root)
                              if n.endswith(".corrupt"))
        except OSError:
            pass
        with self._lock:
            return {"root": self.root, "segments": len(segs),
                    "summary_segments": len(summs), "bytes": total,
                    "appended": self._appended, "dropped": self._dropped,
                    "compactions": self._compactions,
                    "quarantined": quarantined,
                    "max_segment_bytes": self.max_segment_bytes,
                    "max_total_bytes": self.max_total_bytes}

    # --------------------------------------------------------- sampling
    def sample(self, now: Optional[float] = None,
               force: bool = False) -> bool:
        """One witness tick: seal the current ``/timeline`` and
        ``/tenants`` documents plus any new anomaly hints and ledger
        cost vectors.  Throttled to ``sample_interval_s`` so the fleet
        health loop / timeline sampler can call it every poll; returns
        True when a sample was taken."""
        from image_analogies_tpu.obs import ledger as _ledger
        from image_analogies_tpu.obs import timeline as _timeline

        if now is None:
            now = self._clock()
        with self._lock:
            if not force and now - self._last_sample < self.sample_interval_s:
                return False
            self._last_sample = now
        tl_doc = _timeline.snapshot_json()
        if tl_doc.get("armed"):
            self.append("timeline", tl_doc, now=now)
            for hint in tl_doc.get("anomalies") or []:
                key = (float(hint.get("window_start", 0.0)),
                       str(hint.get("series", "")))
                if key > self._last_anomaly:
                    self._last_anomaly = key
                    self.append("anomaly", hint, now=now)
        led = _ledger.current()
        if led is not None:
            tn_doc = _ledger.tenants_doc()
            self.append("tenants", tn_doc, now=now)
            recorded = int(tn_doc.get("recorded") or 0)
            fresh = recorded - self._seen_costs
            if fresh > 0:
                # best-effort: the deque bounds how far back we can see
                for vec in led.recent(fresh):
                    self.append("cost", vec, now=now)
                self._seen_costs = recorded
        return True


# --- archive diffing ---------------------------------------------------------

def diff_replays(a: Dict[str, Any], b: Dict[str, Any]) -> Dict[str, Any]:
    """Compare two :meth:`TelemetryArchive.replay` documents — the
    regression-hunting view behind ``ia archive diff``.  Pure function
    of the two docs so tests and the CLI share it."""
    out: Dict[str, Any] = {"kinds": {}, "series": {}, "tenants": {}}
    ka, kb = a.get("kinds") or {}, b.get("kinds") or {}
    for k in sorted(set(ka) | set(kb)):
        if ka.get(k, 0) != kb.get(k, 0):
            out["kinds"][k] = [ka.get(k, 0), kb.get(k, 0)]

    def last_points(doc: Optional[Dict[str, Any]]) -> Dict[str, Any]:
        pts: Dict[str, Any] = {}
        for name, ent in ((doc or {}).get("series") or {}).items():
            points = (ent or {}).get("points") or []
            if points:
                pts[name] = points[-1][1]
        return pts

    sa, sb = last_points(a.get("timeline")), last_points(b.get("timeline"))
    for name in sorted(set(sa) | set(sb)):
        va, vb = sa.get(name), sb.get(name)
        if va is None or vb is None:
            out["series"][name] = {"a": va, "b": vb}
        elif isinstance(va, dict) or isinstance(vb, dict):
            da = va if isinstance(va, dict) else {}
            db = vb if isinstance(vb, dict) else {}
            delta = {k: [da.get(k), db.get(k)]
                     for k in ("p50", "p95", "p99", "p999", "count")
                     if da.get(k) != db.get(k)
                     and (k in da or k in db)}
            if delta:
                out["series"][name] = delta
        elif va != vb:
            out["series"][name] = {"a": va, "b": vb}

    def tenant_rows(doc: Optional[Dict[str, Any]]) -> Dict[str, Any]:
        return {str(r.get("tenant")): r
                for r in ((doc or {}).get("tenants") or [])}

    ta, tb = tenant_rows(a.get("tenants")), tenant_rows(b.get("tenants"))
    for t in sorted(set(ta) | set(tb)):
        ra, rb = ta.get(t), tb.get(t)
        if ra is None or rb is None:
            out["tenants"][t] = {"a": "present" if ra else "absent",
                                 "b": "present" if rb else "absent"}
    out["empty"] = not (out["kinds"] or out["series"] or out["tenants"])
    return out


def render_diff(d: Dict[str, Any]) -> str:
    lines = ["ia archive diff"]
    if d.get("empty"):
        lines.append("  (no differences)")
    for k, (na, nb) in sorted((d.get("kinds") or {}).items()):
        lines.append(f"  records[{k}]: {na} -> {nb}")
    for name, delta in sorted((d.get("series") or {}).items()):
        lines.append(f"  series {name}: {json.dumps(delta, sort_keys=True)}")
    for t, delta in sorted((d.get("tenants") or {}).items()):
        lines.append(f"  tenant {t}: {delta.get('a')} -> {delta.get('b')}")
    return "\n".join(lines) + "\n"


# --- module-level armed plane ------------------------------------------------
#
# Mirrors obs/timeline.py: one bool, producer helpers check it first,
# the disarmed path allocates nothing (tracemalloc-locked in tests).

_ARMED = False
_ARM_LOCK = threading.Lock()
_ARM_COUNT = 0
_ARCHIVE: Optional[TelemetryArchive] = None


def arm(root: Optional[str] = None,
        archive: Optional[TelemetryArchive] = None,
        **kwargs: Any) -> TelemetryArchive:
    """Install (or join) the process archive.  Arming registers a
    timeline-sampler feeder so a standalone ``ia serve --http`` persists
    without extra wiring; the fleet health loop calls :func:`sample`
    itself."""
    from image_analogies_tpu.obs import timeline as _timeline

    global _ARMED, _ARM_COUNT, _ARCHIVE
    with _ARM_LOCK:
        if _ARCHIVE is None:
            if archive is not None:
                _ARCHIVE = archive
            else:
                if root is None:
                    raise ValueError("archive.arm() needs a root "
                                     "directory or an archive instance")
                _ARCHIVE = TelemetryArchive(root, **kwargs)
        _ARM_COUNT += 1
        _ARMED = True
        _timeline.register_feeder(_feed)
        return _ARCHIVE


def disarm() -> None:
    from image_analogies_tpu.obs import timeline as _timeline

    global _ARMED, _ARM_COUNT, _ARCHIVE
    with _ARM_LOCK:
        _ARM_COUNT = max(_ARM_COUNT - 1, 0)
        if _ARM_COUNT == 0:
            _ARCHIVE = None
            _ARMED = False
            _timeline.unregister_feeder(_feed)


def current() -> Optional[TelemetryArchive]:
    return _ARCHIVE if _ARMED else None


def record(kind: str, doc: Any) -> None:
    """Producer fast path: one bool check when disarmed."""
    if not _ARMED:
        return
    ar = _ARCHIVE
    if ar is not None:
        ar.append(kind, doc)


def sample(force: bool = False) -> None:
    if not _ARMED:
        return
    ar = _ARCHIVE
    if ar is not None:
        ar.sample(force=force)


def _feed() -> None:
    sample()


def stats_doc() -> Dict[str, Any]:
    """The ``/archive/stats`` endpoint body; disarmed shape mirrors the
    other planes."""
    ar = _ARCHIVE if _ARMED else None
    if ar is None:
        return {"armed": False, "segments": 0, "bytes": 0}
    return dict(ar.stats(), armed=True)
