"""Flight recorder: a bounded ring of recent records per ObsScope.

Every record stamped while a run is active (span exits, serve/journal
events, chaos injections — anything flowing through
``utils.logging.emit``) is also appended to the CURRENT scope's ring, so
each fleet worker carries its own last-N-records black box.  On the
death paths that historically left nothing behind — ``ProcessDeath`` in
the worker loop, a breaker tripping open, a watchdog timeout — the ring
is dumped as a SEALED JSON file (same integrity idea as
utils/checkpoint.py: a sha256 over the payload rides inside the file and
is verified on load, so a torn write or bit rot reads as damage, never
as a plausible-but-wrong flight log) into the scope's ``dump_dir``
(the worker's journal dir).  ``ia blackbox <dir>`` renders the last
seconds before the death.

Jax-free like the rest of the obs core; imports of obs.metrics stay
inside functions (metrics imports this module at module scope).
"""

from __future__ import annotations

import hashlib
import itertools
import json
import os
import threading
import time
from collections import deque
from typing import Any, Dict, List, Optional, Tuple

DEFAULT_CAPACITY = 256

_DUMP_SEQ = itertools.count(1)  # uniquifies same-millisecond dumps


class FlightRecorder:
    """Thread-safe bounded ring of record dicts (newest last).

    ``record`` keeps a reference, not a copy: callers
    (obs.trace._stamp) hand over the per-emit private dict that
    utils.logging already copied, so the ring costs one append — the
    recorder must stay cheap enough to run on every record of a live
    worker.  Evictions are counted in ``dropped`` so a dump says how
    much history fell off the back.
    """

    __slots__ = ("capacity", "_ring", "_lock", "dropped")

    def __init__(self, capacity: int = DEFAULT_CAPACITY):
        self.capacity = int(capacity)
        self._ring: deque = deque(maxlen=self.capacity)
        self._lock = threading.Lock()
        self.dropped = 0

    def record(self, rec: Dict[str, Any]) -> None:
        with self._lock:
            if len(self._ring) == self.capacity:
                self.dropped += 1
            self._ring.append(rec)

    def __len__(self) -> int:
        with self._lock:
            return len(self._ring)

    def snapshot(self) -> Tuple[List[Dict[str, Any]], int]:
        """(records oldest->newest, dropped count) — records are shallow
        copies so a dump serializes a stable view."""
        with self._lock:
            return [dict(r) for r in self._ring], self.dropped


# --- sealed dumps -----------------------------------------------------------

def _payload_checksum(payload: Dict[str, Any]) -> str:
    """sha256 over the canonical-JSON payload: the integrity seal stored
    INSIDE the dump, checked on load (checkpoint-style — partial writes
    and rot fail the seal rather than rendering a wrong flight log)."""
    blob = json.dumps(payload, sort_keys=True, default=str).encode()
    return hashlib.sha256(blob).hexdigest()[:32]


def dump(recorder: FlightRecorder, dump_dir: str, reason: str, *,
         scope_id: str = "", extra: Optional[Dict[str, Any]] = None) -> str:
    """Write the ring as a sealed ``blackbox-*.json`` into ``dump_dir``
    (atomic tmp+rename, like every other durable artifact here).
    Returns the dump path."""
    records, dropped = recorder.snapshot()
    payload: Dict[str, Any] = {
        "version": 1,
        "reason": str(reason),
        "scope": scope_id,
        "wall_ts": round(time.time(), 3),
        "dropped": dropped,
        "records": records,
    }
    if extra:
        payload["extra"] = extra
    doc = dict(payload)
    doc["checksum"] = _payload_checksum(payload)
    os.makedirs(dump_dir, exist_ok=True)
    fname = (f"blackbox-{int(time.time() * 1e3):013d}"
             f"-{next(_DUMP_SEQ):04d}-{_safe(reason)}.json")
    path = os.path.join(dump_dir, fname)
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(doc, f, sort_keys=True, default=str)
    os.replace(tmp, path)
    return path


def _safe(name: str) -> str:
    return "".join(c if c.isalnum() or c in "-_" else "_" for c in name)[:40]


def dump_current(reason: str,
                 extra: Optional[Dict[str, Any]] = None) -> Optional[str]:
    """Dump the CURRENT scope's ring, resolving thread-ambiently.

    This is the one-liner the death paths call (ProcessDeath handler,
    breaker trip, watchdog timeout).  It never raises — a failing dump
    must not turn a contained fault into a new crash — and it is a no-op
    when no scope is active, the scope has no recorder, or no
    ``dump_dir`` was assigned (non-journaled runs have nowhere durable
    to put a black box).  Successful dumps bump ``obs.blackbox.dumps``
    (+ a per-reason counter); failures bump ``obs.blackbox.dump_errors``.

    The calling thread's ambient ``request_context`` attrs (request id,
    trace id, batch key) are folded into the dump's ``context`` field —
    a crash dump that cannot say WHICH request it died on is half a
    black box.  Explicit ``extra`` keys win on collision.
    """
    from image_analogies_tpu.obs import metrics as _metrics

    try:
        scope = _metrics.current_scope()
        if scope is None or scope.recorder is None or not scope.dump_dir:
            return None
        from image_analogies_tpu.obs import trace as _trace_ctx

        ambient = _trace_ctx.context_attrs()
        if ambient:
            merged = dict(ambient)
            merged.update(extra or {})
            extra = merged
        path = dump(scope.recorder, scope.dump_dir, reason,
                    scope_id=scope.scope_id, extra=extra)
        _metrics.inc("obs.blackbox.dumps")
        _metrics.inc(f"obs.blackbox.dumps.{_safe(reason)}")
        # the dump itself is a fault-plane event: record it so the run
        # log (and the Perfetto chaos track) shows where a black box
        # was sealed
        from image_analogies_tpu.obs import trace as _trace

        _trace.emit_record({"event": "blackbox_dump", "reason": reason,
                            "scope": scope.scope_id,
                            "file": os.path.basename(path)})
        return path
    except Exception:
        try:
            _metrics.inc("obs.blackbox.dump_errors")
        except Exception:
            pass
        return None


# --- load / render (`ia blackbox`) ------------------------------------------

def list_dumps(dump_dir: str) -> List[str]:
    """Sorted ``blackbox-*.json`` paths under ``dump_dir`` (filename
    order == chronological: the name leads with the epoch-ms stamp)."""
    try:
        names = sorted(n for n in os.listdir(dump_dir)
                       if n.startswith("blackbox-") and n.endswith(".json"))
    except OSError:
        return []
    return [os.path.join(dump_dir, n) for n in names]


def load_dump(path: str) -> Dict[str, Any]:
    """Parse + seal-verify one dump.  Raises ``ValueError`` on a missing
    or failed seal — a damaged black box must be reported as damaged,
    never rendered as if it were the real pre-death history."""
    with open(path) as f:
        doc = json.load(f)
    if not isinstance(doc, dict) or "checksum" not in doc:
        raise ValueError(f"blackbox dump {path}: no integrity seal")
    want = doc.pop("checksum")
    got = _payload_checksum(doc)
    if want != got:
        raise ValueError(f"blackbox dump {path}: seal mismatch "
                         f"(want {want}, got {got})")
    return doc


def render_dump(doc: Dict[str, Any], *, last: int = 0) -> str:
    """Human-readable flight log: one line per record, timestamped
    relative to the final record (the moment of death).  ``last`` trims
    to the N newest records (0 = all)."""
    records = list(doc.get("records") or [])
    if last > 0:
        records = records[-last:]
    end_ts = None
    for rec in reversed(records):
        ts = rec.get("ts")
        if isinstance(ts, (int, float)):
            end_ts = float(ts)
            break
    lines = [
        f"blackbox: reason={doc.get('reason', '?')} "
        f"scope={doc.get('scope') or '(unscoped)'} "
        f"records={len(doc.get('records') or [])} "
        f"dropped={doc.get('dropped', 0)}"
    ]
    for rec in records:
        ts = rec.get("ts")
        if end_ts is not None and isinstance(ts, (int, float)):
            stamp = f"{float(ts) - end_ts:+9.3f}s"
        else:
            stamp = " " * 10
        ev = rec.get("event") or rec.get("name") or "record"
        detail = {k: v for k, v in sorted(rec.items())
                  if k not in ("ts", "event") and not isinstance(v, dict)}
        body = " ".join(f"{k}={v}" for k, v in detail.items())
        lines.append(f"  {stamp} {ev} {body}".rstrip())
    return "\n".join(lines) + "\n"
