"""Run-scoped span tracing.

``run_scope(params, ...)`` opens a run: it mints a ``run_id``, installs
a per-run :class:`~image_analogies_tpu.obs.metrics.ObsScope` (registry +
flight recorder) as the PROCESS-DEFAULT scope — threads with their own
pushed scope (fleet workers) keep theirs; everyone else resolves here —
registers a record stamper with ``utils.logging`` (every JSONL record
written while the run is active gains ``run_id`` + a monotonically
increasing ``seq``), and emits a ``run_manifest`` record (config hash,
backend, mesh shape, device kind, git rev).  On exit it emits a
``run_end`` record carrying the metrics snapshot.

``span(name, **attrs)`` is a context manager producing one
``{"event": "span", "name": ..., "wall_ms": ..., "depth": ...,
"parent": ...}`` record per exit.  Spans nest via a thread-local stack.

The whole module is inert unless a run is active: ``run_scope`` with
``params.metrics`` false and no ``log_path`` yields a no-op scope, and
``span`` then returns a singleton no-op context manager — no record,
no allocation, no clock read — so the disabled engine path stays at
bench speed.  ``run_scope`` is reentrant: a nested call (video's
per-frame ``create_image_analogy``) joins the enclosing run instead of
minting a second ``run_id``.
"""

from __future__ import annotations

import contextlib
import hashlib
import json
import subprocess
import sys
import threading
import time
import uuid
from typing import Any, Dict, Optional

from image_analogies_tpu.obs import metrics as _metrics
from image_analogies_tpu.utils import logging as _logging


class RunContext:
    """State of one observed run (one engine invocation or one clip)."""

    __slots__ = ("run_id", "log_path", "scope", "registry", "seq",
                 "_seq_lock", "depth", "owner_thread", "_joined_threads")

    def __init__(self, run_id: str, log_path: Optional[str],
                 scope: _metrics.ObsScope):
        self.run_id = run_id
        self.log_path = log_path
        self.scope = scope
        self.registry = scope.registry
        self.seq = 0
        self._seq_lock = threading.Lock()
        self.depth = 0  # run_scope reentrancy count
        self.owner_thread = threading.get_ident()
        self._joined_threads: set = set()  # foreign threads already warned

    def next_seq(self) -> int:
        with self._seq_lock:
            s = self.seq
            self.seq += 1
            return s


_CURRENT: Optional[RunContext] = None
_SPANS = threading.local()  # per-thread span stack
_REQ_CTX = threading.local()  # per-thread ambient request attrs


def current_run_id() -> Optional[str]:
    return _CURRENT.run_id if _CURRENT is not None else None


def _stamp(record: Dict[str, Any]) -> None:
    ctx = _CURRENT
    if ctx is not None:
        record.setdefault("run_id", ctx.run_id)
        record.setdefault("seq", ctx.next_seq())
        # Feed the CURRENT scope's flight recorder (thread-ambient: a
        # fleet worker's records land in ITS ring, not the run's), so
        # every scope carries its own last-seconds black box.  The
        # record is emit()'s private copy — a reference is safe.
        scope = _metrics.current_scope() or ctx.scope
        rec = scope.recorder
        if rec is not None:
            rec.record(record)


# Registered once at import: utils.logging calls it on every emit; it is
# a no-op dict check while no run is active.
_logging.set_record_stamper(_stamp)


@contextlib.contextmanager
def request_context(**attrs: Any):
    """Ambient trace attributes for the current thread.

    Every span exit and :func:`emit_record` inside the scope inherits
    ``attrs`` (explicit span attrs win).  This is how a serve request's
    ``request`` id flows from admission through queue → batcher → worker
    → engine dispatch without threading a parameter through every layer:
    the worker wraps the per-request path once and all nested records —
    including the engine's own ``level``/``fetch`` spans — carry the id,
    so ``ia trace`` can render one request's critical path end to end.

    Nests: an inner scope overlays the outer and restores it on exit.
    Zero-cost when unused: span/emit paths read one thread-local slot.
    """
    prev = getattr(_REQ_CTX, "attrs", None)
    merged = dict(prev) if prev else {}
    merged.update(attrs)
    _REQ_CTX.attrs = merged
    try:
        yield
    finally:
        _REQ_CTX.attrs = prev


def context_attrs() -> Optional[Dict[str, Any]]:
    """The current thread's ambient request attrs (or None)."""
    return getattr(_REQ_CTX, "attrs", None)


# --- cross-process trace context ---------------------------------------------
#
# The ambient request_context keys that must SURVIVE a process boundary
# (HTTP hop via the X-IA-Trace header, router->worker hop via the IAF2
# trace-context frame).  "trace" is the end-to-end trace id shared by
# every span of one client request; "parent_span" names the hop that
# forwarded it; "origin_request" pins the id the client saw at admission
# even when a downstream layer re-mints its own request id.

TRACE_HEADER = "X-IA-Trace"
TRACE_KEYS = ("trace", "parent_span", "origin_request")
_TOKEN_OK = frozenset(
    "ABCDEFGHIJKLMNOPQRSTUVWXYZabcdefghijklmnopqrstuvwxyz0123456789_-")


def mint_trace_id() -> str:
    return uuid.uuid4().hex[:16]


def _token_ok(part: str) -> bool:
    return 0 < len(part) <= 64 and all(c in _TOKEN_OK for c in part)


def parse_trace_header(value: Optional[str]) -> Optional[Dict[str, str]]:
    """Parse an ``X-IA-Trace`` header: ``trace/parent_span/request``
    (``-`` marks an absent field).  Returns the context dict or None for
    anything malformed — a bad header degrades to a fresh trace, never
    an error."""
    if not value:
        return None
    parts = value.strip().split("/")
    if len(parts) != 3 or not all(_token_ok(p) for p in parts):
        return None
    ctx: Dict[str, str] = {}
    for key, part in zip(TRACE_KEYS, parts):
        if part != "-":
            ctx[key] = part
    return ctx if "trace" in ctx else None


def format_trace_header(ctx: Optional[Dict[str, Any]] = None
                        ) -> Optional[str]:
    """Render a trace context (default: the ambient one) as the
    ``X-IA-Trace`` header value, or None when there is no trace."""
    if ctx is None:
        ctx = capture_trace()
    if not ctx or "trace" not in ctx:
        return None
    parts = []
    for key in TRACE_KEYS:
        part = str(ctx.get(key, "") or "-")
        parts.append(part if _token_ok(part) else "-")
    return "/".join(parts)


def capture_trace() -> Optional[Dict[str, str]]:
    """The portable subset of the ambient request attrs — what a hop
    serializes before handing the request to another registry/process.
    None when the calling thread carries no trace."""
    ambient = getattr(_REQ_CTX, "attrs", None)
    if not ambient or "trace" not in ambient:
        return None
    return {k: str(ambient[k]) for k in TRACE_KEYS if ambient.get(k)}


@contextlib.contextmanager
def ensure_trace(parent_span: Optional[str] = None, **extra: Any):
    """Guarantee the block runs under a trace: adopt the thread's
    ambient trace id if one is set, else mint one.  ``parent_span``
    (and any extra attrs) overlay the context either way, so records
    emitted inside name the hop that owns them."""
    ambient = getattr(_REQ_CTX, "attrs", None)
    attrs: Dict[str, Any] = dict(extra)
    if not ambient or not ambient.get("trace"):
        attrs["trace"] = mint_trace_id()
    if parent_span is not None:
        attrs["parent_span"] = parent_span
    with request_context(**attrs):
        yield


_UNSET = object()
_GIT_REV: Any = _UNSET


def _git_rev() -> Optional[str]:
    global _GIT_REV
    if _GIT_REV is _UNSET:
        try:
            _GIT_REV = subprocess.run(
                ["git", "rev-parse", "--short", "HEAD"],
                capture_output=True, text=True, timeout=5,
                check=True).stdout.strip() or None
        except Exception:
            _GIT_REV = None
    return _GIT_REV


def _device_info() -> Dict[str, Any]:
    """Backend/device facts WITHOUT forcing jax (or device) init: only
    report what an already-imported, already-initialized jax knows."""
    jax = sys.modules.get("jax")
    if jax is None:
        return {}
    try:
        # jax.devices() would initialize the backend; only peek if the
        # runtime already has one (local_devices after init is cheap).
        backends = sys.modules.get("jax._src.xla_bridge")
        if backends is None or not getattr(backends, "_backends", None):
            return {"jax_version": getattr(jax, "__version__", None)}
        devs = jax.devices()
        return {
            "jax_version": getattr(jax, "__version__", None),
            "device_kind": devs[0].device_kind if devs else None,
            "device_count": len(devs),
            "platform": devs[0].platform if devs else None,
        }
    except Exception:
        return {"jax_version": getattr(jax, "__version__", None)}


def config_digest(params: Any) -> str:
    """Stable short hash of the full params dataclass (every field —
    unlike checkpoint.run_digest, which excludes aux knobs: the manifest
    should distinguish runs that differ in ANY knob)."""
    try:
        import dataclasses
        d = dataclasses.asdict(params)
    except TypeError:
        d = dict(getattr(params, "__dict__", {"repr": repr(params)}))
    blob = json.dumps(d, sort_keys=True, default=str).encode()
    return hashlib.sha1(blob).hexdigest()[:12]


def build_manifest(params: Any = None,
                   extra: Optional[Dict[str, Any]] = None) -> Dict[str, Any]:
    man: Dict[str, Any] = {"event": "run_manifest"}
    if params is not None:
        man["config_hash"] = config_digest(params)
        man["backend"] = getattr(params, "backend", None)
        man["strategy"] = getattr(params, "strategy", None)
        man["mesh"] = [getattr(params, "data_shards", 1),
                       getattr(params, "db_shards", 1)]
        man["levels"] = getattr(params, "levels", None)
        man["metrics"] = bool(getattr(params, "metrics", False))
    rev = _git_rev()
    if rev:
        man["git_rev"] = rev
    man.update(_device_info())
    if extra:
        man.update(extra)
    return man


@contextlib.contextmanager
def run_scope(params: Any = None, log_path: Optional[str] = None,
              manifest_extra: Optional[Dict[str, Any]] = None):
    """Open an observed run, or join the active one (reentrant).

    Inert (yields None, zero side effects) unless the params ask for
    observability: ``params.metrics`` truthy or a log path is set.
    """
    global _CURRENT
    if log_path is None and params is not None:
        log_path = getattr(params, "log_path", None)
    want = bool(getattr(params, "metrics", False) or log_path)

    ctx = _CURRENT
    if ctx is not None:
        # Reentrant join: video's per-frame engine calls ride the clip's
        # run — one run_id, one registry, one manifest.  _CURRENT is a
        # plain module global, so a SECOND THREAD entering run_scope also
        # lands here and silently shares the first thread's run_id: make
        # the share visible with one run_join warning per foreign thread.
        tid = threading.get_ident()
        if tid != ctx.owner_thread and tid not in ctx._joined_threads:
            ctx._joined_threads.add(tid)
            _logging.emit({"event": "run_join", "severity": "warning",
                           "owner_thread": ctx.owner_thread,
                           "joined_thread": tid}, ctx.log_path)
        ctx.depth += 1
        try:
            yield ctx
        finally:
            ctx.depth -= 1
        return
    if not want:
        yield None
        return

    run_id = uuid.uuid4().hex[:16]
    scope = _metrics.ObsScope(scope_id=f"run:{run_id}")
    ctx = RunContext(run_id, log_path, scope)
    _CURRENT = ctx
    # The run's scope is the PROCESS default: every thread without its
    # own pushed scope (engine, tests, HTTP handlers) resolves to it —
    # the historic single-registry behavior, now one scope among many.
    _metrics.install_process_scope(scope)
    # One append handle per log path for the whole run (the hot level
    # loop streams a record per level/frame); flushed + closed with the
    # run so `run_end` is durable the moment the scope exits.
    _logging.begin_handle_cache()
    try:
        _logging.emit(build_manifest(params, manifest_extra), log_path)
        yield ctx
    finally:
        # run_end goes out while the stamper is still active so it
        # carries the run_id like every other record of the run.
        snap = ctx.registry.snapshot()
        _logging.emit({"event": "run_end", "metrics": snap}, log_path)
        _logging.end_handle_cache()
        _metrics.uninstall_process_scope(scope)
        _CURRENT = None


class _NoopSpan:
    """Singleton no-op context manager for the disabled path: ``span()``
    with no active run costs one global read + one attribute call and
    allocates nothing."""

    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


_NOOP = _NoopSpan()


class _Span:
    __slots__ = ("name", "attrs", "t0", "ctx")

    def __init__(self, name: str, attrs: Dict[str, Any], ctx: RunContext):
        self.name = name
        self.attrs = attrs
        self.ctx = ctx
        self.t0 = 0.0

    def __enter__(self):
        stack = getattr(_SPANS, "stack", None)
        if stack is None:
            stack = _SPANS.stack = []
        stack.append(self)
        self.t0 = time.perf_counter()
        return self

    def __exit__(self, *exc):
        wall_ms = (time.perf_counter() - self.t0) * 1e3
        stack = _SPANS.stack
        stack.pop()
        rec: Dict[str, Any] = {
            "event": "span",
            "name": self.name,
            "wall_ms": round(wall_ms, 3),
            "depth": len(stack),
        }
        if stack:
            rec["parent"] = stack[-1].name
        if exc and exc[0] is not None:
            rec["error"] = getattr(exc[0], "__name__", str(exc[0]))
        rec.update(self.attrs)
        ambient = getattr(_REQ_CTX, "attrs", None)
        if ambient:
            for k, v in ambient.items():
                rec.setdefault(k, v)
        _logging.emit(rec, self.ctx.log_path)
        return False


def span(name: str, **attrs: Any):
    """Wall-clock span; no-op singleton when no run is active."""
    ctx = _CURRENT
    if ctx is None:
        return _NOOP
    return _Span(name, attrs, ctx)


def emit_record(record: Dict[str, Any]) -> None:
    """Emit a structured record into the active run's log.  With no run
    active it still mirrors to stdlib logging (utils.logging.emit), just
    without a JSONL destination — callers never need to branch."""
    ctx = _CURRENT
    ambient = getattr(_REQ_CTX, "attrs", None)
    if ambient:
        for k, v in ambient.items():
            record.setdefault(k, v)
    _logging.emit(record, ctx.log_path if ctx is not None else None)


def current_span_attrs() -> Optional[Dict[str, Any]]:
    """Merged attrs of this thread's open spans (innermost wins) — lets
    out-of-band records (obs.device compile events) attribute themselves
    to the enclosing level/phase.  None when no span is open."""
    stack = getattr(_SPANS, "stack", None)
    if not stack:
        return None
    merged: Dict[str, Any] = {}
    for sp in stack:
        merged.update(sp.attrs)
    return merged
